"""The north-star campaign, end to end (VERDICT r4 missing #4).

BASELINE.json metric verbatim: *wall-clock to AVF ±1% CI* per
(workload, structure) — every workload × its SimPoint representatives ×
every O3 fault structure {regfile, rob, iq, lsq, fu, latch}, each window
run through ``parallel.campaign.run_until_ci`` (batched accumulation
until the 95% Wilson interval half-width ≤ 0.01) on the current chip.

Per (workload, structure) the artifact reports: per-SimPoint AVF + CI +
trials + seconds, the SimPoint-weighted AVF (the reference's
population-weighted metric, ``src/cpu/simple/probes/simpoint.hh:82``),
and the summed wall-clock.  The grand total is the headline: wall-clock
to ±1% CI across all structures × all workloads × SimPoints on one chip.

``--fleet`` re-runs the same sweep as ONE interleaved multi-tenant fleet
(``shrewd_tpu/service/``): every (workload, SimPoint, structure)
campaign becomes a tenant on one shared mesh, batches interleaved
through the pipelined engine under a global dispatch-depth budget.  The
reference's ``multisim`` answer to this sweep is process-per-config;
here one resident process serves all campaigns.  With ``--also-serial``
both arms run back-to-back at the same scale and the measured speedup
lands in ``--bench-out`` (BENCH_r07.json).

Usage: python tools/northstar.py [--k 3] [--interval 4000] [--out FILE]
       python tools/northstar.py --fleet --also-serial
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORKLOADS = ["workloads/sort.c", "workloads/intmm.c", "workloads/divmix.c",
             "workloads/bytehash.c", "workloads/memops.c",
             "workloads/ptrchase.c", "workloads/rotmix.c",
             "workloads/strmix.c"]
STRUCTURES = ["regfile", "rob", "iq", "lsq", "fu", "latch"]


def _build_windows(a) -> dict:
    """Ingest phase, shared by both arms: {workload: [(trace, meta)]}."""
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.simpoint import simpoint_windows

    out = {}
    for wl in a.workloads:
        paths = hd.build_tools(wl)
        windows, _sps, _profile = simpoint_windows(
            paths, interval=a.interval, k=a.k, seed=a.seed)
        out[wl] = windows
        print(f"{wl}: {len(windows)} SimPoint windows", file=sys.stderr,
              flush=True)
    return out


def _run_serial(a, windows_by_wl: dict) -> dict:
    """The serial sweep: one ``run_until_ci`` campaign at a time (the
    reference's posture — campaigns queue behind each other)."""
    import jax

    from shrewd_tpu.models.minor import MinorConfig
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign, run_until_ci
    from shrewd_tpu.parallel.mesh import make_mesh

    dev = jax.devices()[0]
    mesh = make_mesh(jax.devices()[:1])       # one chip — the metric's unit
    grand_t0 = time.time()
    doc = {"metric": "wall-clock to AVF ±1% CI (95%), one chip",
           "platform": dev.platform,
           "halfwidth_target": a.halfwidth,
           "simpoint_interval_macro_ops": a.interval,
           "k_per_workload": a.k,
           "max_trials": a.max_trials,
           "batch": a.batch,
           "workloads": {}}
    grand_trials = 0
    for wl, windows in windows_by_wl.items():
        t_wl = time.time()
        row = {"n_simpoints": len(windows), "structures": {}}
        kernels = [(TrialKernel(trace, O3Config(), MinorConfig()), meta)
                   for trace, meta in windows]
        for structure in a.structures:
            t_s = time.time()
            weighted = 0.0
            s_trials = 0
            sp_rows = []
            converged_all = True
            for kernel, meta in kernels:
                camp = ShardedCampaign(kernel, mesh, structure)
                res = run_until_ci(
                    camp, seed=a.seed,
                    simpoint_id=meta["simpoint_interval"],
                    structure_id=STRUCTURES.index(structure),
                    batch_size=a.batch, target_halfwidth=a.halfwidth,
                    max_trials=a.max_trials)
                weighted += meta["simpoint_weight"] * res.avf
                s_trials += res.trials
                converged_all &= res.converged
                sp_rows.append({
                    "interval": meta["simpoint_interval"],
                    "weight": round(meta["simpoint_weight"], 4),
                    "avf": round(res.avf, 4),
                    "ci95": [round(res.avf_interval.lo, 4),
                             round(res.avf_interval.hi, 4)],
                    "trials": res.trials,
                    "trials_per_sec": round(res.trials_per_second, 1),
                })
            row["structures"][structure] = {
                "weighted_avf": round(weighted, 4),
                "trials": s_trials,
                "wall_clock_s": round(time.time() - t_s, 1),
                "converged": converged_all,
                "simpoints": sp_rows,
            }
            grand_trials += s_trials
            print(f"{wl} {structure}: weighted AVF {weighted:.4f} "
                  f"({s_trials} trials, "
                  f"{row['structures'][structure]['wall_clock_s']}s)",
                  file=sys.stderr, flush=True)
        row["wall_clock_s"] = round(time.time() - t_wl, 1)
        doc["workloads"][wl] = row
    doc["total_wall_clock_s"] = round(time.time() - grand_t0, 1)
    doc["total_trials"] = grand_trials
    doc["campaigns"] = sum(len(r["structures"]) * r["n_simpoints"]
                           for r in doc["workloads"].values())
    return doc


def _run_fleet(a, windows_by_wl: dict) -> dict:
    """The same sweep as ONE interleaved fleet: each (workload, SimPoint,
    structure) campaign is a tenant; one mesh, one resident scheduler,
    batches interleaved through the pipelined engine.  Windows are
    spilled to .npz once so every tenant's plan round-trips (the service
    contract: a tenant is reproducible from its spec document)."""
    import jax

    from shrewd_tpu.campaign.plan import CampaignPlan, TraceFileSpec
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.service import CampaignScheduler, TenantSpec
    from shrewd_tpu.trace import format as tf

    dev = jax.devices()[0]
    mesh = make_mesh(jax.devices()[:1])       # the same one-chip unit
    spool = tempfile.mkdtemp(prefix="northstar_fleet_")
    tenants = []          # (name, wl, structure, weight)
    for wl, windows in windows_by_wl.items():
        base = os.path.splitext(os.path.basename(wl))[0]
        for trace, meta in windows:
            npz = os.path.join(
                spool, f"{base}_sp{meta['simpoint_interval']}.npz")
            if not os.path.exists(npz):
                tf.save(npz, trace, meta)
            for structure in a.structures:
                sp_name = f"{base}.sp{meta['simpoint_interval']}"
                # the fleet's batch may be SMALLER than the serial arm's:
                # the interval machinery decouples stopping granularity
                # (one batch) from device-call granularity (sync_every
                # batches accumulated in one jitted scan), so the fleet
                # checks convergence at fleet_batch-granularity while
                # keeping the serial posture's per-call device efficiency
                # — the over-sampling a coarse serial batch pays on
                # small campaigns is the fleet's structural win.
                # min_trials floors at one full interval so the first
                # convergence check matches the serial arm's (its first
                # check is at one serial batch = one fleet interval).
                fb = a.fleet_batch or a.batch
                plan = CampaignPlan(
                    simpoints=[TraceFileSpec(name=sp_name, path=npz)],
                    structures=[structure], batch_size=fb,
                    target_halfwidth=a.halfwidth,
                    max_trials=a.max_trials, seed=a.seed,
                    min_trials=max(1000, fb * a.sync_every))
                # parity with the serial arm's BARE run_until_ci loop:
                # no canaries/audit/invariants and no watchdog in either
                # arm (the integrity and resilience layers have their own
                # benchmarks) — interleaving + interval accumulation is
                # the variable under measurement, nothing else
                plan.integrity.canary_trials = 0
                plan.integrity.audit_rate = 0.0
                plan.integrity.invariants = False
                plan.resilience.backoff_base = 0.0
                plan.resilience.dispatch_timeout = 0.0
                plan.pipeline.sync_every = a.sync_every
                tenants.append((f"{base}.sp{meta['simpoint_interval']}"
                                f".{structure}", plan,
                                meta["simpoint_weight"], wl, structure))
    sched = CampaignScheduler(outdir=None, mesh=mesh,
                              depth_budget=a.depth_budget)
    for name, plan, _w, _wl, _s in tenants:
        sched.admit(TenantSpec(name=name, plan=plan.to_dict()))
    t0 = time.time()
    rc = sched.run()
    fleet_s = time.time() - t0
    doc = {"metric": "wall-clock to AVF ±1% CI (95%), one chip, "
                     "interleaved multi-tenant fleet",
           "platform": dev.platform,
           "halfwidth_target": a.halfwidth,
           "simpoint_interval_macro_ops": a.interval,
           "k_per_workload": a.k,
           "max_trials": a.max_trials,
           "batch": a.fleet_batch or a.batch,
           "serial_arm_batch": a.batch,
           "sync_every": a.sync_every,
           "depth_budget": a.depth_budget,
           "policy": "fair",
           "rc": rc,
           "tenants": len(tenants),
           "fleet_ticks": sched.ticks,
           "fairness_index": round(sched.fairness_index(), 4),
           "workloads": {}}
    grand_trials = 0
    for name, _plan, weight, wl, structure in tenants:
        t = sched.tenants[name]
        row = doc["workloads"].setdefault(
            wl, {"structures": {}})["structures"].setdefault(
            structure, {"weighted_avf": 0.0, "trials": 0,
                        "converged": True, "tenants": []})
        summary = list((t.results or {}).values())
        avf = summary[0]["avf"] if summary else 0.0
        conv = summary[0]["converged"] if summary else False
        row["weighted_avf"] = round(row["weighted_avf"]
                                    + weight * (avf or 0.0), 4)
        row["trials"] += t.trials
        row["converged"] = bool(row["converged"] and conv)
        row["tenants"].append({
            "tenant": name, "avf": round(avf or 0.0, 4),
            "trials": t.trials, "ticks": t.ticks,
            "status": t.status})
        grand_trials += t.trials
    doc["total_wall_clock_s"] = round(fleet_s, 1)
    doc["total_trials"] = grand_trials
    doc["campaigns"] = len(tenants)
    from shrewd_tpu.parallel import exec_cache
    doc["exec_cache"] = exec_cache.cache().stats()
    return doc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workloads", nargs="*", default=WORKLOADS)
    ap.add_argument("--structures", nargs="*", default=STRUCTURES)
    ap.add_argument("--k", type=int, default=3, help="SimPoints/workload")
    ap.add_argument("--interval", type=int, default=4000)
    ap.add_argument("--halfwidth", type=float, default=0.01)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--max-trials", type=int, default=200_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=str(REPO / "NORTHSTAR_r05.json"))
    ap.add_argument("--fleet", action="store_true",
                    help="run the sweep as ONE interleaved multi-tenant "
                         "fleet (shrewd_tpu/service/) instead of the "
                         "serial campaign-after-campaign loop")
    ap.add_argument("--also-serial", action="store_true",
                    help="[fleet] run the serial sweep too (same scale, "
                         "same process) and record the measured speedup")
    ap.add_argument("--sync-every", type=int, default=4,
                    help="[fleet] batches per device sync interval")
    ap.add_argument("--fleet-batch", type=int, default=0,
                    help="[fleet] per-tenant batch size (default: --batch)."
                         "  A smaller fleet batch with sync-every scan "
                         "accumulation keeps the serial arm's per-device-"
                         "call trial count while stopping at finer "
                         "granularity — less over-sampling per campaign")
    ap.add_argument("--depth-budget", type=int, default=4,
                    help="[fleet] global dispatch-depth budget")
    ap.add_argument("--fleet-out",
                    default=str(REPO / "NORTHSTAR_FLEET_r07.json"))
    ap.add_argument("--bench-out", default=str(REPO / "BENCH_r07.json"))
    ap.add_argument("--serial-baseline",
                    default=str(REPO / "NORTHSTAR_r05.json"),
                    help="[fleet] serial artifact to compare against when "
                         "--also-serial is not given (scales must match "
                         "for the comparison to mean anything)")
    a = ap.parse_args()

    windows_by_wl = _build_windows(a)

    if not a.fleet:
        doc = _run_serial(a, windows_by_wl)
        with open(a.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(json.dumps({"total_wall_clock_s": doc["total_wall_clock_s"],
                          "total_trials": doc["total_trials"],
                          "campaigns": doc["campaigns"],
                          "platform": doc["platform"]}))
        return 0

    serial_doc = None
    if a.also_serial:
        serial_doc = _run_serial(a, windows_by_wl)
        # cold-start parity: both arms must pay their own compiles — the
        # process-wide executable cache and XLA's jit caches would
        # otherwise hand the second arm the first arm's warm steps
        import jax

        from shrewd_tpu.parallel import exec_cache
        exec_cache.cache().clear()
        exec_cache.clear_kernels()
        jax.clear_caches()
    fleet_doc = _run_fleet(a, windows_by_wl)
    if serial_doc is not None:
        serial_s = serial_doc["total_wall_clock_s"]
        serial_src = "measured (--also-serial, same scale/process)"
        serial_trials = serial_doc["total_trials"]
    else:
        with open(a.serial_baseline) as f:
            base = json.load(f)
        serial_s = base["total_wall_clock_s"]
        serial_src = a.serial_baseline
        serial_trials = base.get("total_trials")
    fleet_doc["serial_wall_clock_s"] = serial_s
    fleet_doc["serial_source"] = serial_src
    fleet_doc["speedup_vs_serial"] = round(
        serial_s / max(fleet_doc["total_wall_clock_s"], 1e-9), 3)
    with open(a.fleet_out, "w") as f:
        json.dump(fleet_doc, f, indent=1)
        f.write("\n")
    bench = {
        "benchmark": "NORTHSTAR sweep: interleaved multi-tenant fleet "
                     "vs serial campaign-after-campaign",
        "platform": fleet_doc["platform"],
        "campaigns": fleet_doc["campaigns"],
        "config": {"workloads": a.workloads, "structures": a.structures,
                   "k": a.k, "interval": a.interval,
                   "halfwidth": a.halfwidth, "batch": a.batch,
                   "fleet_batch": a.fleet_batch or a.batch,
                   "max_trials": a.max_trials,
                   "sync_every": a.sync_every,
                   "depth_budget": a.depth_budget},
        "serial_wall_clock_s": serial_s,
        "serial_source": serial_src,
        "serial_trials": serial_trials,
        "fleet_wall_clock_s": fleet_doc["total_wall_clock_s"],
        "fleet_trials": fleet_doc["total_trials"],
        "speedup": fleet_doc["speedup_vs_serial"],
        "fairness_index": fleet_doc["fairness_index"],
        "exec_cache": fleet_doc["exec_cache"],
    }
    with open(a.bench_out, "w") as f:
        json.dump(bench, f, indent=1)
        f.write("\n")
    print(json.dumps({"serial_s": serial_s,
                      "fleet_s": fleet_doc["total_wall_clock_s"],
                      "speedup": fleet_doc["speedup_vs_serial"],
                      "campaigns": fleet_doc["campaigns"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
