// Shared ptrace plumbing for the native-trace tools.
//
// These tools are the framework's analog of the reference's NativeTrace /
// statetrace machinery (reference src/cpu/nativetrace.{hh,cc} and
// util/statetrace): instead of diffing a simulated CPU against a live host
// process, we *capture* a live host process's dynamic instruction stream as
// ground truth (tools/nativetrace.cc) and drive real-hardware fault-injection
// campaigns against it (tools/hostsfi.cc).  The host CPU plays the role of
// the golden oracle that gem5's serial C++ path plays in BASELINE configs[0].
#ifndef SHREWD_PTRACE_COMMON_H
#define SHREWD_PTRACE_COMMON_H

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <signal.h>
#include <sys/personality.h>
#include <sys/ptrace.h>
#include <sys/types.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

// Canonical register order: x86-64 instruction-encoding order (the ModRM
// register numbering objdump's operand names map onto), then rip, eflags.
// The lifter (shrewd_tpu/ingest/lift.py) and the SFI harness index registers
// by this table; keep all three in sync.
static const int kNumGPR = 16;
static const int kRegsPerStep = 18;  // 16 GPRs + rip + eflags
// SHTRACE3: each step additionally records the 16 xmm registers' low 32
// bits (the scalar-SSE f32 lanes the FP lift verifies against), packed
// two lanes per u64 — xmm[2k] in the low half, xmm[2k+1] in the high.
static const int kXmmWords = 8;

static inline void regs_to_canonical(const struct user_regs_struct &r,
                                     uint64_t out[kRegsPerStep]) {
  out[0] = r.rax;  out[1] = r.rcx;  out[2] = r.rdx;  out[3] = r.rbx;
  out[4] = r.rsp;  out[5] = r.rbp;  out[6] = r.rsi;  out[7] = r.rdi;
  out[8] = r.r8;   out[9] = r.r9;   out[10] = r.r10; out[11] = r.r11;
  out[12] = r.r12; out[13] = r.r13; out[14] = r.r14; out[15] = r.r15;
  out[16] = r.rip;
  out[17] = r.eflags;
}

static inline void xmm_lo_to_canonical(const struct user_fpregs_struct &fp,
                                       uint64_t out[kXmmWords]) {
  for (int k = 0; k < 8; k++) {
    uint64_t lo = fp.xmm_space[4 * (2 * k)];
    uint64_t hi = fp.xmm_space[4 * (2 * k + 1)];
    out[k] = lo | (hi << 32);
  }
}

static inline void canonical_set(struct user_regs_struct &r, int idx,
                                 uint64_t val) {
  switch (idx) {
    case 0: r.rax = val; break;   case 1: r.rcx = val; break;
    case 2: r.rdx = val; break;   case 3: r.rbx = val; break;
    case 4: r.rsp = val; break;   case 5: r.rbp = val; break;
    case 6: r.rsi = val; break;   case 7: r.rdi = val; break;
    case 8: r.r8 = val; break;    case 9: r.r9 = val; break;
    case 10: r.r10 = val; break;  case 11: r.r11 = val; break;
    case 12: r.r12 = val; break;  case 13: r.r13 = val; break;
    case 14: r.r14 = val; break;  case 15: r.r15 = val; break;
    default:
      fprintf(stderr, "canonical_set: bad reg %d\n", idx);
      exit(2);
  }
}

static inline uint64_t canonical_get(const struct user_regs_struct &r,
                                     int idx) {
  uint64_t c[kRegsPerStep];
  regs_to_canonical(r, c);
  return c[idx];
}

// Spawn the target stopped at exec, ASLR off (deterministic PCs — the same
// reason the reference pins guest state via checkpoints).  argv must be
// NULL-terminated.  Returns the child pid.
static inline pid_t spawn_traced(char **argv, int stdout_fd) {
  pid_t pid = fork();
  if (pid < 0) { perror("fork"); exit(2); }
  if (pid == 0) {
    personality(ADDR_NO_RANDOMIZE);
    if (stdout_fd >= 0) {
      dup2(stdout_fd, 1);
      close(stdout_fd);
    }
    ptrace(PTRACE_TRACEME, 0, nullptr, nullptr);
    execv(argv[0], argv);
    perror("execv");
    _exit(127);
  }
  int status = 0;
  if (waitpid(pid, &status, 0) < 0 || !WIFSTOPPED(status)) {
    fprintf(stderr, "child did not stop at exec\n");
    exit(2);
  }
  ptrace(PTRACE_SETOPTIONS, pid, nullptr, PTRACE_O_EXITKILL);
  return pid;
}

// Run to `addr` via an int3 breakpoint.  Returns false if the child exited
// before reaching it.
static inline bool run_to(pid_t pid, uint64_t addr) {
  errno = 0;
  long orig = ptrace(PTRACE_PEEKTEXT, pid, (void *)addr, nullptr);
  if (errno) { perror("peektext"); exit(2); }
  long patched = (orig & ~0xffL) | 0xccL;
  ptrace(PTRACE_POKETEXT, pid, (void *)addr, (void *)patched);
  ptrace(PTRACE_CONT, pid, nullptr, nullptr);
  int status = 0;
  waitpid(pid, &status, 0);
  if (!WIFSTOPPED(status)) return false;
  struct user_regs_struct regs;
  ptrace(PTRACE_GETREGS, pid, nullptr, &regs);
  if (regs.rip != addr + 1) {
    fprintf(stderr, "breakpoint: stopped at %llx, want %lx\n",
            (unsigned long long)regs.rip, (unsigned long)(addr + 1));
    return false;
  }
  regs.rip = addr;  // rewind over the int3
  ptrace(PTRACE_SETREGS, pid, nullptr, &regs);
  ptrace(PTRACE_POKETEXT, pid, (void *)addr, (void *)orig);
  return true;
}

// One single-step; returns false when the child exited.
static inline bool single_step(pid_t pid) {
  ptrace(PTRACE_SINGLESTEP, pid, nullptr, nullptr);
  int status = 0;
  waitpid(pid, &status, 0);
  return WIFSTOPPED(status);
}

#endif  // SHREWD_PTRACE_COMMON_H
