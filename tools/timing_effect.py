"""Timing-model AVF sensitivity artifact (TIMING_EFFECT_r{N}).

Per structure, the same trial budget under three fault-landing models:

- proxy:       1-IPC occupancy window (r2 baseline)
- scoreboard:  dependence-driven residency mass (r3)
- squash:      scoreboard + bimodal-mispredict wrong-path mass — faults
               landing in would-be-squashed entries are masked by the
               squash walk (VERDICT r3 #7; reference rob.hh:207)

Usage: python tools/timing_effect.py [--trials 8192] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=8192)
    ap.add_argument("--workload", default="workloads/sort.c")
    ap.add_argument("--out", default=str(REPO / "TIMING_EFFECT.json"))
    a = ap.parse_args()

    import numpy as np

    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.models.timing import TimingConfig
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng

    paths = hd.build_tools(a.workload)
    trace, meta = hd.capture_and_lift(paths)
    keys = prng.trial_keys(prng.campaign_key(17), a.trials)

    models = {
        "proxy": O3Config(timing="proxy"),
        "scoreboard": O3Config(timing="scoreboard"),
        "squash": O3Config(timing="scoreboard",
                           timing_cfg=TimingConfig(bpred="bimodal")),
    }
    out = {"workload": a.workload, "trials": a.trials,
           "window_uops": trace.n, "structures": {}}
    for structure in ("rob", "iq", "lsq", "fu"):
        row = {}
        for name, cfg in models.items():
            k = TrialKernel(trace, cfg)
            tally = np.asarray(k.run_keys(keys, structure))
            avf = float((tally[1] + tally[2]) / max(tally.sum(), 1))
            row[name] = {"avf": round(avf, 4),
                         "tally": [int(x) for x in tally]}
            if name == "squash":
                sb = k._scoreboard
                row[name]["mispredicts"] = int(sb.mispredict.sum())
                row[name]["wp_mass"] = sb.wrongpath_mass(structure)
        row["avf_delta_scoreboard"] = round(
            row["scoreboard"]["avf"] - row["proxy"]["avf"], 4)
        row["avf_delta_squash"] = round(
            row["squash"]["avf"] - row["scoreboard"]["avf"], 4)
        out["structures"][structure] = row
        print(f"{structure}: proxy {row['proxy']['avf']:.4f} "
              f"scoreboard {row['scoreboard']['avf']:.4f} "
              f"squash {row['squash']['avf']:.4f}", file=sys.stderr)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({s: {m: out["structures"][s][m]["avf"]
                          for m in models}
                      for s in out["structures"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
