"""Pallas TPU lowering smoke test: compile + run taint_fast_pallas at tiny
shapes on the current default device.  Run first in bench so a Mosaic
compile problem surfaces in seconds, not after the full warm-up
(VERDICT r2 next-round #1a).

Exit 0 and print "pallas-smoke: ok" on success; nonzero with traceback
otherwise.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke(n: int = 128, batch: int = 256, may_latch: bool = True) -> None:
    import jax
    import numpy as np

    from shrewd_tpu import native
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng

    dev = jax.devices()[0]
    trace = native.generate_trace(seed=7, n=n, nphys=64, mem_words=256,
                                  working_set_words=64)
    kernel = TrialKernel(trace, O3Config(pallas="on"))
    keys = prng.trial_keys(prng.campaign_key(3), batch)
    faults = kernel.sample_batch(keys, "regfile")
    t0 = time.monotonic()
    res = kernel.taint_fast(faults, may_latch=may_latch)
    out = np.asarray(res.outcome)
    dt = time.monotonic() - t0
    # cross-check against the XLA taint kernel (same fast-pass contract)
    ref = kernel._taint_batch_jit(faults, False)
    ref_out = np.asarray(ref.outcome)
    unresolved = np.asarray(res.escaped | res.overflow
                            | ref.escaped | ref.overflow)
    mism = int((out != ref_out)[~unresolved].sum())
    if mism:
        raise AssertionError(
            f"pallas-smoke: {mism}/{batch} outcome mismatches vs XLA kernel")
    print(f"pallas-smoke: ok device={dev.platform} n={n} batch={batch} "
          f"may_latch={may_latch} compile+run {dt:.1f}s", flush=True)


if __name__ == "__main__":
    smoke(may_latch=True)
    smoke(may_latch=False)
    sys.exit(0)
