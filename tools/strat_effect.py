"""Post-stratification effectiveness artifact (STRAT_EFFECT_r{N}).

For each fault tier, compare the plain Wilson estimator's variance
against the post-stratified estimator's on the same trial budget — the
variance-reduction factor is (trials to reach a CI target, plain) /
(trials, stratified), approximated here by the ratio of estimator
variances over repeated batches (VERDICT r3 weak #7: the r3 strata
carried almost no signal for mesi/noc; the NoC pipeline made outcomes
type-determined, and MESI gained structure-specific tiers).

Usage: python tools/strat_effect.py [--batches 24] [--batch 512]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _variance_ratio(kernel, structure: str, batches: int, batch: int,
                    seed0: int):
    """Trials-to-CI-target reduction factor: mean of
    (plain Wilson halfwidth / post-stratified halfwidth)² over repeated
    batches.  (The point estimates coincide by construction — observed-
    allocation weights telescope to the pooled proportion — so the win is
    entirely in the interval width, i.e. how soon run_until_ci stops.)"""
    import numpy as np

    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel.stopping import (pairs_from_strata,
                                              post_stratified, wilson)
    from shrewd_tpu.utils import prng

    avfs, factors = [], []
    for b in range(batches):
        keys = prng.trial_keys(prng.campaign_key(seed0 + b), batch)
        st_tally, _ = kernel.run_keys_stratified(keys, structure)
        st_tally = np.asarray(st_tally)
        tally = st_tally.sum(axis=0)
        avfs.append(float(C.avf(tally)))
        vuln = int(tally[C.OUTCOME_SDC] + tally[C.OUTCOME_DUE])
        hw_p = wilson(vuln, int(tally.sum())).halfwidth
        # the campaign stopping rule's own vulnerability definition —
        # never re-derive it here
        pairs = pairs_from_strata(st_tally)
        hw_s = post_stratified(pairs).halfwidth
        factors.append((hw_p / hw_s) ** 2)
    return {
        "avf_mean": round(float(np.mean(avfs)), 4),
        "batch": batch,
        "trials_reduction_factor": round(float(np.mean(factors)), 3)
        if factors else None,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--out", default=str(REPO / "STRAT_EFFECT.json"))
    a = ap.parse_args()

    import numpy as np

    from shrewd_tpu.models.mesi import MesiConfig, MesiKernel, torture_stream
    from shrewd_tpu.models.noc import NocConfig, NocKernel, build_message_trace
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu import native

    out = {"batches": a.batches,
           "batch": "per-tier (see tiers[*].batch)", "tiers": {}}

    trace = native.generate_trace(seed=1, n=2048, nphys=256, mem_words=2048,
                                  working_set_words=512)
    o3 = TrialKernel(trace, O3Config())
    for structure in ("regfile", "fu"):
        out["tiers"][f"o3:{structure}"] = _variance_ratio(
            o3, structure, a.batches, a.batch, 100)
        print(structure, out["tiers"][f"o3:{structure}"], file=sys.stderr)

    mcfg = MesiConfig(n_cores=4)
    mcfg.validate()
    stream = torture_stream(mcfg, 96, 64, seed=3, sharing=0.6)
    init = np.arange(64, dtype=np.uint32)
    mk = MesiKernel(stream, mcfg, init)
    for structure in ("state", "dir", "tbe"):
        out["tiers"][f"mesi:{structure}"] = _variance_ratio(
            mk, structure, a.batches, min(a.batch, 256), 200)
        print(structure, out["tiers"][f"mesi:{structure}"], file=sys.stderr)

    ncfg = NocConfig()
    ncfg.validate()
    nk = NocKernel(build_message_trace(stream, mcfg, ncfg), ncfg)
    out["tiers"]["noc:router"] = _variance_ratio(
        nk, "router", a.batches, min(a.batch, 256), 300)
    print("noc", out["tiers"]["noc:router"], file=sys.stderr)

    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({k: v["trials_reduction_factor"]
                      for k, v in out["tiers"].items()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
