"""Large-window pipeline driver (VERDICT r3 #4: scale windows 100×).

Captures the lzss compression workload once, lifts windows of several
lengths, caches them as .npz traces, and measures replay throughput per
window length on the current JAX platform.  The reference analog is the
SPEC-SimPoint flow (30B-instruction measured regions,
``x86_spec/x86-spec-cpu2017.py:404``); here the capture is a ptrace
single-step of the marked kernel and the window is the lifted µop stream.

Usage:
    python tools/bigwindow.py --build            # capture + lift + cache
    python tools/bigwindow.py --rate             # trials/s per length
    python tools/bigwindow.py --build --rate --out WINDOW_SCALE.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
CACHE = REPO / "tests" / "_build"
LENGTHS = (4096, 65536, 524288)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cache_path(n: int) -> Path:
    return CACHE / f"lzss_w{n}.npz"


def build(lengths=LENGTHS, workload="workloads/lzss.c") -> dict:
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.lift import lift, read_nativetrace, static_decode
    from shrewd_tpu.trace import format as tfmt

    paths = hd.build_tools(workload)
    trace_bin = CACHE / f"lzss_capture.{os.getpid()}.bin"
    info = {}
    try:
        t0 = time.time()
        subprocess.run([str(paths.tracer), str(trace_bin),
                        f"{paths.begin:x}", f"{paths.end:x}", "4000000",
                        str(paths.workload)],
                       check=True, capture_output=True, text=True)
        nt = read_nativetrace(trace_bin)
        insts = static_decode(str(paths.workload))
        info["capture_steps"] = len(nt.steps) - 1
        info["capture_seconds"] = round(time.time() - t0, 1)
        log(f"capture: {info['capture_steps']} macro-steps "
            f"in {info['capture_seconds']}s")
        for n in lengths:
            t0 = time.time()
            tr, meta = lift(str(trace_bin), str(paths.workload),
                            max_uops=n, nt=nt, insts=insts)
            tfmt.save(cache_path(n), tr, meta)
            info[f"lift_{n}"] = {
                "uops": tr.n,
                "lift_rate": round(meta["stats"]["lift_rate"], 4),
                "seconds": round(time.time() - t0, 1),
            }
            log(f"lift {n}: rate {info[f'lift_{n}']['lift_rate']} "
                f"in {info[f'lift_{n}']['seconds']}s → {cache_path(n)}")
    finally:
        trace_bin.unlink(missing_ok=True)
    return info


def rate(lengths=LENGTHS, batch=None, reps: int = 3) -> dict:
    import jax
    import numpy as np

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.trace import format as tfmt
    from shrewd_tpu.utils import prng

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    out = {"platform": dev.platform, "rates": {}}
    for n in lengths:
        p = cache_path(n)
        if not p.exists():
            log(f"skip {n}: {p} missing (run --build)")
            continue
        tr, meta = tfmt.load(p)
        # batch scaled so each length measures in seconds, not minutes:
        # per-trial work grows linearly with window length
        b = batch or max(256, min(131072 if on_tpu else 8192,
                                  (1 << 29) // max(tr.n, 1)))
        k = TrialKernel(tr, O3Config())
        keys = prng.trial_keys(prng.campaign_key(0), b)
        t0 = time.time()
        np.asarray(k.run_keys(keys, "regfile"))
        compile_s = time.time() - t0
        rates = []
        for _ in range(reps):
            t0 = time.time()
            np.asarray(k.run_keys(keys, "regfile"))
            rates.append(b / (time.time() - t0))
        rates.sort()
        out["rates"][str(tr.n)] = {
            "trials_per_sec": round(rates[len(rates) // 2], 2),
            "batch": b,
            "compile_seconds": round(compile_s, 1),
            "lift_rate": round(meta["stats"]["lift_rate"], 4)
            if "stats" in meta else None,
        }
        log(f"window {tr.n}: {out['rates'][str(tr.n)]['trials_per_sec']:,} "
            f"trials/s (batch {b})")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build", action="store_true")
    ap.add_argument("--rate", action="store_true")
    ap.add_argument("--lengths", type=int, nargs="*", default=list(LENGTHS))
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--workload", default="workloads/lzss.c")
    ap.add_argument("--out", default=None)
    a = ap.parse_args()
    result = {}
    if a.build:
        result["build"] = build(a.lengths, a.workload)
    if a.rate:
        result["rate"] = rate(a.lengths, a.batch, a.reps)
    if a.out:
        with open(a.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
