"""Large-window pipeline driver (VERDICT r3 #4 → r5 SimPoint scale).

Captures a workload once, lifts windows of several lengths, caches them
as .npz traces, and measures replay throughput per window length on the
current JAX platform — dense kernel and/or the chunked hierarchical
campaign (ops/chunked.py).  The reference analog is the SPEC-SimPoint
flow (30B-instruction measured regions,
``x86_spec/x86-spec-cpu2017.py:404``); here the capture is a ptrace
single-step of the marked kernel and the window is the lifted µop
stream.  ``workloads/lzss_big.c`` (~10M µops) is the r5 scaling target.

Usage:
    python tools/bigwindow.py --build                   # capture+lift
    python tools/bigwindow.py --rate                    # dense trials/s
    python tools/bigwindow.py --rate --chunked          # chunked trials/s
    python tools/bigwindow.py --build --rate --chunked \
        --workload workloads/lzss_big.c --lengths 0 \
        --max-steps 10000000 --out WINDOW_SCALE.json    # 0 = full window
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
CACHE = REPO / "tests" / "_build"
LENGTHS = (4096, 65536, 524288)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cache_path(stem: str, n: int) -> Path:
    return CACHE / f"{stem}_w{'full' if n == 0 else n}.npz"


def build(lengths=LENGTHS, workload="workloads/lzss.c",
          max_steps=4_000_000) -> dict:
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.lift import lift, read_nativetrace, static_decode
    from shrewd_tpu.trace import format as tfmt

    stem = Path(workload).stem
    paths = hd.build_tools(workload)
    trace_bin = CACHE / f"{stem}_capture.{os.getpid()}.bin"
    info = {}
    try:
        t0 = time.time()
        subprocess.run([str(paths.tracer), str(trace_bin),
                        f"{paths.begin:x}", f"{paths.end:x}",
                        str(max_steps), str(paths.workload)],
                       check=True, capture_output=True, text=True)
        nt = read_nativetrace(trace_bin)
        insts = static_decode(str(paths.workload))
        info["capture_steps"] = len(nt.steps) - 1
        info["capture_seconds"] = round(time.time() - t0, 1)
        log(f"capture: {info['capture_steps']} macro-steps "
            f"in {info['capture_seconds']}s")
        for n in lengths:
            t0 = time.time()
            tr, meta = lift(str(trace_bin), str(paths.workload),
                            max_uops=n or None, nt=nt, insts=insts)
            tfmt.save(cache_path(stem, n), tr, meta)
            key = f"lift_{n or 'full'}"
            info[key] = {
                "uops": tr.n,
                "lift_rate": round(meta["stats"]["lift_rate"], 4),
                "seconds": round(time.time() - t0, 1),
            }
            log(f"lift {n or 'full'}: {tr.n} µops, rate "
                f"{info[key]['lift_rate']} in {info[key]['seconds']}s")
    finally:
        trace_bin.unlink(missing_ok=True)
    return info


def rate(lengths=LENGTHS, batch=None, reps: int = 3,
         workload="workloads/lzss.c", chunked=False,
         chunk: int = 65536, trials: int = 0, horizon: int = 0) -> dict:
    import jax
    import numpy as np

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.trace import format as tfmt
    from shrewd_tpu.utils import prng

    stem = Path(workload).stem
    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    out = {"platform": dev.platform,
           "mode": "chunked" if chunked else "dense", "rates": {}}
    if chunked:
        out["chunk"] = chunk
    for n in lengths:
        p = cache_path(stem, n)
        if not p.exists():
            log(f"skip {n}: {p} missing (run --build)")
            continue
        tr, meta = tfmt.load(p)
        k = TrialKernel(tr, O3Config())
        row = {"lift_rate": round(meta["stats"]["lift_rate"], 4)
               if "stats" in meta else None}
        if chunked:
            from shrewd_tpu.ops.chunked import ChunkedCampaign

            b = trials or max(512, min(16384 if on_tpu else 2048,
                                       (1 << 26) // max(tr.n // 64, 1)))
            t0 = time.time()
            ch = ChunkedCampaign(k, chunk=chunk,
                                 carry_horizon=horizon or None)
            if horizon:
                row["carry_horizon"] = horizon
            row["setup_seconds"] = round(time.time() - t0, 1)
            keys = prng.trial_keys(prng.campaign_key(0), b)
            # warm at the SAME lane-width bucket the timed reps use (the
            # chunk kernel compiles per bucket)
            t0 = time.time()
            ch.run_keys(keys, "regfile")
            row["compile_seconds"] = round(time.time() - t0, 1)
            rates = []
            tally = None
            for _ in range(reps):
                t0 = time.time()
                tally = ch.run_keys(keys, "regfile")
                rates.append(b / (time.time() - t0))
            rates.sort()
            row.update(trials_per_sec=round(rates[len(rates) // 2], 2),
                       batch=b, chunks=ch.C,
                       lanes_per_call=ch.lane_width(b),
                       tally=[int(x) for x in tally],
                       resolution=dict(ch.last_stats))
        else:
            b = batch or max(256, min(131072 if on_tpu else 8192,
                                      (1 << 29) // max(tr.n, 1)))
            keys = prng.trial_keys(prng.campaign_key(0), b)
            t0 = time.time()
            np.asarray(k.run_keys(keys, "regfile"))
            row["compile_seconds"] = round(time.time() - t0, 1)
            rates = []
            for _ in range(reps):
                t0 = time.time()
                np.asarray(k.run_keys(keys, "regfile"))
                rates.append(b / (time.time() - t0))
            rates.sort()
            row.update(trials_per_sec=round(rates[len(rates) // 2], 2),
                       batch=b)
        out["rates"][str(tr.n)] = row
        log(f"window {tr.n}: {row['trials_per_sec']:,} trials/s "
            f"({out['mode']})")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build", action="store_true")
    ap.add_argument("--rate", action="store_true")
    ap.add_argument("--chunked", action="store_true")
    ap.add_argument("--chunk", type=int, default=65536)
    ap.add_argument("--horizon", type=int, default=0,
                    help="chunked mode: carry_horizon (0 = exact)")
    ap.add_argument("--lengths", type=int, nargs="*", default=list(LENGTHS),
                    help="window lengths in µops; 0 = the full capture")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--trials", type=int, default=0,
                    help="chunked mode: trial count per measurement")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--max-steps", type=int, default=4_000_000)
    ap.add_argument("--workload", default="workloads/lzss.c")
    ap.add_argument("--out", default=None)
    a = ap.parse_args()
    result = {}
    if a.build:
        result["build"] = build(a.lengths, a.workload, a.max_steps)
    if a.rate:
        result["rate"] = rate(a.lengths, a.batch, a.reps, a.workload,
                              chunked=a.chunked, chunk=a.chunk,
                              trials=a.trials, horizon=a.horizon)
    if a.out:
        with open(a.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
