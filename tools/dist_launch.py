"""Multi-process campaign launcher — the gem5-dist analog on localhost.

The reference runs multi-node simulations as N gem5 processes + a switch
process glued by a hand-rolled TCP barrier layer, launched over ssh
(``/root/reference/util/dist/gem5-dist.sh:227-321``,
``dev/net/dist_iface.hh:102``).  The TPU-native equivalent is
``jax.distributed``: N processes join one coordinator, the device mesh
spans all of them, and the psum tally reduction IS the barrier
(SURVEY §5.8).  This launcher demonstrates it on localhost with the CPU
backend (the dist-gem5-on-localhost testing posture, SURVEY §4 tier 5):

    python tools/dist_launch.py --num-processes 2 --local-devices 4

Each worker runs the SAME sharded campaign batch over the global mesh and
prints its replicated tally; the supervisor checks all workers agree and
that the tally equals a single-process run of the same batch bit-for-bit
(placement must not change outcomes — every trial's fate is a pure
function of its PRNG key).

``--mode elastic`` exercises the failure story the collective mode cannot
have: N *independent* orchestrator processes share one campaign through
the lease board (``shrewd_tpu/parallel/elastic.py`` — per-process meshes,
no cross-process collective to wedge), and ``--kill-worker N --at-batch
B`` hard-kills worker N at its B-th dispatched batch via the chaos
harness (``shrewd_tpu/chaos.py``).  The supervisor asserts that the
survivors revoke the dead worker's lease, finish the campaign, and that
the post-recovery tally equals an undisturbed single-process run of the
same plan BIT-FOR-BIT — where dist-gem5 would hang its TCP barrier
forever on the first dead node:

    python tools/dist_launch.py --mode elastic --num-processes 2 \
        --kill-worker 1 --at-batch 2
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _worker_env(local_devices: int) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count="
                        f"{local_devices}").strip()
    return env


def run_campaign_batch(batch: int, n_uops: int, seed: int):
    """One dense sharded batch on whatever mesh this process sees."""
    import numpy as np

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate
    from shrewd_tpu.utils import prng

    trace = generate(WorkloadConfig(
        n=n_uops, nphys=32, mem_words=64, working_set_words=32, seed=seed))
    # dense: the taint/hybrid escape resolution is host-driven and NOT yet
    # multi-host-safe (each process would re-run escapes redundantly)
    kernel = TrialKernel(trace, O3Config(replay_kernel="dense"))
    mesh = make_mesh()
    camp = ShardedCampaign(kernel, mesh, "regfile")
    keys = prng.trial_keys(prng.campaign_key(seed), batch)
    return np.asarray(camp.tally_batch(keys)), mesh.size


def worker(args) -> int:
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{args.port}",
        num_processes=args.num_processes,
        process_id=args.process_id)
    tally, mesh_size = run_campaign_batch(args.batch, args.uops, args.seed)
    print(json.dumps({
        "process_id": args.process_id,
        "process_count": jax.process_count(),
        "mesh_size": mesh_size,
        "tally": tally.tolist(),
    }), flush=True)
    return 0


def supervise(args) -> int:
    env = _worker_env(args.local_devices)
    if not args.skip_probe:
        # pre-flight health probe (tools/backend_probe.py): N workers
        # joining a coordinator all hang together if the backend is
        # wedged — spend one bounded subprocess finding out first
        try:
            probe = subprocess.run(
                [sys.executable, os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "backend_probe.py"),
                 "--platform", "cpu", "--timeout", str(args.probe_timeout)],
                env=env, capture_output=True, text=True,
                timeout=args.probe_timeout + 20)
            failed = probe.returncode != 0
            detail = f"{probe.stdout}{probe.stderr}" if failed else ""
        except subprocess.TimeoutExpired:
            # the child wedged before its own watchdog thread could start
            # (interpreter/site import hanging on the same broken backend
            # the probe exists to detect) — that is a failed probe, not a
            # supervisor crash
            failed = True
            detail = (f"probe child unresponsive after "
                      f"{args.probe_timeout + 20:.0f}s")
        if failed:
            print(f"backend probe failed:\n{detail}", file=sys.stderr)
            print(json.dumps({"ok": False,
                              "error": "backend probe failed"}))
            return 1
    procs = []
    for pid in range(args.num_processes):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", "worker",
             "--process-id", str(pid),
             "--num-processes", str(args.num_processes),
             "--port", str(args.port), "--batch", str(args.batch),
             "--uops", str(args.uops), "--seed", str(args.seed)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    tallies = {}
    ok = True
    for pid, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            print(f"worker {pid}: TIMEOUT\n{err[-500:]}", file=sys.stderr)
            ok = False
            continue
        if p.returncode != 0:
            print(f"worker {pid}: rc={p.returncode}\n{err[-800:]}",
                  file=sys.stderr)
            ok = False
            continue
        line = next((ln for ln in out.splitlines() if ln.startswith("{")),
                    None)
        if line is None:
            print(f"worker {pid}: no result line\n{err[-500:]}",
                  file=sys.stderr)
            ok = False
            continue
        tallies[pid] = json.loads(line)
    if not ok or len(tallies) != args.num_processes:
        print(json.dumps({"ok": False, "error": "worker failure"}))
        return 1

    vals = [tuple(t["tally"]) for t in tallies.values()]
    agree = len(set(vals)) == 1
    # single-process reference on the same global batch (same seed): the
    # tally must be placement-invariant, bit for bit
    total_dev = args.num_processes * args.local_devices
    ref_env = _worker_env(total_dev)
    ref_tally = None
    try:
        ref = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--role",
             "reference", "--batch", str(args.batch), "--uops",
             str(args.uops), "--seed", str(args.seed)],
            env=ref_env, capture_output=True, text=True,
            timeout=args.timeout)
    except subprocess.TimeoutExpired:
        print("reference run: TIMEOUT", file=sys.stderr)
        ref = None
    if ref is not None and ref.returncode == 0:
        line = next((ln for ln in ref.stdout.splitlines()
                     if ln.startswith("{")), None)
        if line is not None:
            ref_tally = json.loads(line)["tally"]
    result = {
        "ok": agree and ref_tally == list(vals[0]),
        "num_processes": args.num_processes,
        "global_devices": tallies[0]["mesh_size"],
        "workers_agree": agree,
        "tally": list(vals[0]),
        "single_process_tally": ref_tally,
        "matches_single_process": ref_tally == list(vals[0]),
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


def reference(args) -> int:
    import numpy as np  # noqa: F401

    tally, mesh_size = run_campaign_batch(args.batch, args.uops, args.seed)
    print(json.dumps({"tally": tally.tolist(), "mesh_size": mesh_size}),
          flush=True)
    return 0


# --------------------------------------------------------------------------
# elastic mode: lease-board campaign + chaos kill + bit-identity assertion
# --------------------------------------------------------------------------

def _elastic_plan(args):
    """The shared campaign every elastic role runs: min_trials==max_trials
    pins the batch count, so the undisturbed reference and the
    kill/recover run must converge on exactly the same batch set."""
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    trials = args.batch * args.num_batches
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=args.uops, nphys=32, mem_words=64, working_set_words=32,
            seed=args.seed))],
        structures=["regfile"], batch_size=args.batch,
        target_halfwidth=0.5, max_trials=trials, min_trials=trials,
        seed=args.seed)
    plan.machine.replay_kernel = "dense"
    plan.integrity.canary_trials = 0
    plan.integrity.audit_rate = 0.0
    plan.elastic.heartbeat_interval = 0.2
    plan.elastic.heartbeat_timeout = 2.0
    return plan


def _final_tallies(orch) -> dict:
    from shrewd_tpu.sim.exit_event import ExitEvent

    events = list(orch.events())
    ev, payload = events[-1]
    assert ev is ExitEvent.CAMPAIGN_COMPLETE, ev
    return {f"{sp}/{st}": r.tallies.tolist()
            for (sp, st), r in payload.items()}


def elastic_worker(args) -> int:
    import time

    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.parallel.elastic import ElasticContext

    plan = _elastic_plan(args)
    orch = Orchestrator(plan)
    if args.kill_at_batch >= 0:
        from shrewd_tpu.chaos import ChaosEngine

        orch.attach_chaos(ChaosEngine(
            {"faults": [{"kind": "kill_worker",
                         "after_dispatches": args.kill_at_batch}]},
            worker=args.worker))
    ctx = ElasticContext(args.coord_dir, args.worker, plan.elastic)
    if args.wait_for_lost:
        # deterministic kill/recover scenario: hold all claims until the
        # target worker has JOINED (first heartbeat seen) and then DIED
        # (heartbeat stale) — the survivor then demonstrably recovers the
        # dead worker's leased batches rather than winning a startup race
        hb = ctx.membership._hb_path(args.wait_for_lost)
        deadline = time.monotonic() + args.timeout / 2
        while time.monotonic() < deadline:
            if os.path.exists(hb) \
                    and not ctx.membership.alive(args.wait_for_lost):
                break
            time.sleep(0.1)
        else:
            print(f"timed out waiting for {args.wait_for_lost} to die",
                  file=sys.stderr)
            return 1
    orch.attach_elastic(ctx)
    tallies = _final_tallies(orch)
    ctx.stop()
    print(json.dumps({"worker": args.worker, "tallies": tallies,
                      "elastic": ctx.counters()}), flush=True)
    return 0


def elastic_reference(args) -> int:
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    print(json.dumps({"tallies": _final_tallies(
        Orchestrator(_elastic_plan(args)))}), flush=True)
    return 0


def supervise_elastic(args) -> int:
    import tempfile

    env = _worker_env(args.local_devices)
    with tempfile.TemporaryDirectory(prefix="shrewd_elastic_") as coord:
        procs = []
        for pid in range(args.num_processes):
            argv = [sys.executable, os.path.abspath(__file__),
                    "--role", "elastic-worker", "--coord-dir", coord,
                    "--worker", f"w{pid}", "--batch", str(args.batch),
                    "--uops", str(args.uops), "--seed", str(args.seed),
                    "--num-batches", str(args.num_batches),
                    "--timeout", str(args.timeout)]
            if pid == args.kill_worker:
                argv += ["--kill-at-batch", str(args.at_batch)]
            elif args.kill_worker >= 0:
                # survivors hold claims until the target has joined and
                # died, so the run demonstrably RECOVERS leased batches
                # instead of winning a startup race against the victim
                argv += ["--wait-for-lost", f"w{args.kill_worker}"]
            procs.append(subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        results, failures = {}, {}
        for pid, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=args.timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                failures[pid] = f"TIMEOUT\n{err[-500:]}"
                continue
            if pid == args.kill_worker:
                # the chaos kill exits rc 137 by design; a killed worker
                # that somehow "succeeded" means the kill never fired
                if p.returncode == 0:
                    failures[pid] = "kill target exited 0 (kill not fired)"
                continue
            if p.returncode != 0:
                failures[pid] = f"rc={p.returncode}\n{err[-800:]}"
                continue
            line = next((ln for ln in out.splitlines()
                         if ln.startswith("{")), None)
            if line is None:
                failures[pid] = f"no result line\n{err[-500:]}"
                continue
            results[pid] = json.loads(line)
        for pid, why in failures.items():
            print(f"worker {pid}: {why}", file=sys.stderr)

    # undisturbed single-process reference of the same plan
    ref_tallies = None
    try:
        ref = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--role",
             "elastic-reference", "--batch", str(args.batch),
             "--uops", str(args.uops), "--seed", str(args.seed),
             "--num-batches", str(args.num_batches)],
            env=env, capture_output=True, text=True, timeout=args.timeout)
        if ref.returncode == 0:
            line = next((ln for ln in ref.stdout.splitlines()
                         if ln.startswith("{")), None)
            if line is not None:
                ref_tallies = json.loads(line)["tallies"]
    except subprocess.TimeoutExpired:
        print("reference run: TIMEOUT", file=sys.stderr)

    survivor_tallies = [r["tallies"] for r in results.values()]
    expect_survivors = args.num_processes - (
        1 if 0 <= args.kill_worker < args.num_processes else 0)
    agree = (len(survivor_tallies) == expect_survivors > 0
             and all(t == survivor_tallies[0] for t in survivor_tallies))
    reclaimed = sum(r["elastic"]["batches_reclaimed"]
                    for r in results.values())
    result = {
        "ok": bool(not failures and agree and ref_tallies is not None
                   and survivor_tallies[0] == ref_tallies
                   and (args.kill_worker < 0 or reclaimed >= 1)),
        "mode": "elastic",
        "survivors": sorted(results),
        "survivors_agree": agree,
        "tallies": survivor_tallies[0] if survivor_tallies else None,
        "single_process_tallies": ref_tallies,
        "matches_single_process": (bool(survivor_tallies)
                                   and survivor_tallies[0] == ref_tallies),
        "batches_reclaimed": reclaimed,
        "elastic": {f"w{pid}": r["elastic"] for pid, r in results.items()},
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default="supervisor",
                    choices=("supervisor", "worker", "reference",
                             "elastic-worker", "elastic-reference"))
    ap.add_argument("--mode", default="collective",
                    choices=("collective", "elastic"),
                    help="collective: one jax.distributed mesh (a dead "
                         "worker wedges the psum); elastic: independent "
                         "per-process meshes over a shared lease board "
                         "(a dead worker's batches are reclaimed)")
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--port", type=int, default=47211)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--uops", type=int, default=96)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--skip-probe", action="store_true",
                    help="skip the pre-flight backend health probe")
    ap.add_argument("--probe-timeout", type=float, default=55.0,
                    help="backend_probe.py self-exit watchdog seconds")
    # elastic-mode arguments
    ap.add_argument("--num-batches", type=int, default=4,
                    help="elastic: batches in the shared campaign")
    ap.add_argument("--coord-dir", default="",
                    help="elastic worker: shared coordination directory")
    ap.add_argument("--worker", default="w0",
                    help="elastic worker: worker name")
    ap.add_argument("--kill-worker", type=int, default=-1,
                    help="elastic supervisor: worker index to hard-kill "
                         "(-1 = none)")
    ap.add_argument("--at-batch", type=int, default=2,
                    help="elastic supervisor: kill the worker at its Nth "
                         "dispatched batch (1-based)")
    ap.add_argument("--kill-at-batch", type=int, default=-1,
                    help="elastic worker (internal): self-kill at the Nth "
                         "dispatched batch")
    ap.add_argument("--wait-for-lost", default="",
                    help="elastic worker (internal): hold claims until "
                         "this worker has joined and died")
    args = ap.parse_args()
    if args.role == "worker":
        return worker(args)
    if args.role == "reference":
        return reference(args)
    if args.role == "elastic-worker":
        return elastic_worker(args)
    if args.role == "elastic-reference":
        return elastic_reference(args)
    if args.mode == "elastic":
        return supervise_elastic(args)
    return supervise(args)


if __name__ == "__main__":
    sys.exit(main())
