"""Scoreboard timing vs the host CPU's own cycle counter (rdtsc).

VERDICT r3 weak #4: the scoreboard was only self-consistent — no external
timing truth existed.  The host x86 core IS a wide out-of-order machine
(the same class the reference's O3 and this scoreboard approximate), so
its measured cycle count for the exact traced kernel is a legitimate
external anchor: the scoreboard's predicted cycles for the lifted window
should land within a small factor of silicon, and closer than the 1-IPC
proxy.  Writes TIMING_VALIDATE.json.

Usage: python tools/timing_validate.py [--workload workloads/sort.c]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="workloads/sort.c")
    ap.add_argument("--out", default=str(REPO / "TIMING_VALIDATE.json"))
    a = ap.parse_args()

    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.models.timing import TimingConfig, compute_scoreboard

    # 1. host truth: median rdtsc cycles over the exact kernel call
    bd = REPO / "tests" / "_build"
    bd.mkdir(exist_ok=True)
    harness = bd / f"rdtsc_{Path(a.workload).stem}"
    subprocess.run(
        ["gcc", "-O1", "-static", "-fno-pie", "-no-pie",
         f"-DWORKLOAD=\"{Path(a.workload).name}\"",
         str(REPO / "workloads" / "rdtsc_harness.c"), "-o", str(harness)],
        check=True, capture_output=True, text=True,
        cwd=str(REPO / "workloads"))
    host_cycles = int(subprocess.run(
        [str(harness)], check=True, capture_output=True,
        text=True).stdout.strip())

    # 2. model predictions on the lifted marker window
    paths = hd.build_tools(a.workload)
    trace, meta = hd.capture_and_lift(paths)
    sb = compute_scoreboard(trace, TimingConfig(bpred="none"))
    sb_sq = compute_scoreboard(trace, TimingConfig(bpred="bimodal"))
    out = {
        "workload": a.workload,
        "host_cycles_median": host_cycles,
        "macro_ops": meta["macro_ops"],
        "uops": trace.n,
        "host_ipc_macro": round(meta["macro_ops"] / host_cycles, 3),
        "proxy_cycles": trace.n,               # the 1-IPC occupancy proxy
        "scoreboard_cycles": sb.n_cycles,
        "scoreboard_squash_cycles": sb_sq.n_cycles,
        "scoreboard_ipc_uop": round(sb.ipc, 3),
        "ratio_proxy_vs_host": round(trace.n / host_cycles, 3),
        "ratio_scoreboard_vs_host": round(sb.n_cycles / host_cycles, 3),
        "ratio_squash_vs_host": round(sb_sq.n_cycles / host_cycles, 3),
        "note": ("host = this machine's OoO x86 core via rdtsc (median of "
                 "21 warm runs of the exact traced kernel); the model "
                 "closer to ratio 1.0 carries the more faithful residency "
                 "timeline.  The lift can contract macro-ops (deferred "
                 "flag compares emit no µops) or expand them (sub-word/"
                 "guard sequences), so µop and macro counts differ."),
    }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
