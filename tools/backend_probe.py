"""Standalone backend health probe — one JSON verdict, never hangs.

The shared health-check for everything that must decide "is this backend
usable right now" before committing minutes to it: ``bench.py``'s
supervisor, ``tools/dist_launch.py``'s pre-flight, cron jobs watching the
TPU tunnel, and the resilience layer's re-probe queue
(``shrewd_tpu.resilience.ReprobeQueue`` can use ``probe()`` in-process or
shell out to this file for full isolation).

Design rules learned the hard way (VERDICT r3 weak #1):

- The probe process *self-exits* via a watchdog thread rather than being
  SIGKILLed by its parent — a killed mid-compile process is exactly what
  wedges the TPU relay for every later python.
- One trivial device op is the whole health test; anything heavier risks
  timing out on a healthy-but-cold backend.
- Exactly one JSON line on stdout, always:
      {"platform": ..., "ok": bool, "seconds": ..., "device"|"error": ...}

With ``--canary`` the probe also runs one canary batch through the trial
kernel (shrewd_tpu/integrity.py: constructed MASKED-by-construction faults
plus a tally-invariant check on a real key batch), so operators can
distinguish "backend up" from "backend *trustworthy*" before committing a
campaign to it.  The JSON verdict then carries an ``integrity`` object and
``ok`` goes false on any canary miss.

Usage:
    python tools/backend_probe.py --platform axon --timeout 55
    python tools/backend_probe.py --platform cpu   # rc 0 healthy, 3 not
    python tools/backend_probe.py --platform cpu --canary --timeout 180
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _canary_check() -> dict:
    """One canary batch on the selected backend (requires the repo on the
    path — the probe may be launched from anywhere)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    from shrewd_tpu import integrity as integ
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.trace.synth import WorkloadConfig, generate
    from shrewd_tpu.utils import prng

    trace = generate(WorkloadConfig(n=64, nphys=32, mem_words=64,
                                    working_set_words=32, seed=11))
    kernel = TrialKernel(trace, O3Config(pallas="off"))
    fault, notes = integ.constructed_canaries(kernel)
    out = np.asarray(kernel.run_batch(fault))
    misses = [notes[i] for i in range(len(notes))
              if int(out[i]) != C.OUTCOME_MASKED]
    keys = prng.trial_keys(prng.campaign_key(0), 16)
    tally = np.asarray(kernel.run_keys(keys, "regfile"))
    viol = integ.tally_violations(tally, 16)
    return {"canaries": len(notes), "canary_misses": misses,
            "invariant_violations": viol,
            "trustworthy": not misses and not viol}


def probe(platform: str, timeout: float, canary: bool = False) -> int:
    t0 = time.monotonic()

    def _watchdog():
        time.sleep(timeout)
        # the main thread may be stuck inside a C-level relay dial where
        # no signal/exception can reach it — _exit from a thread works
        emit({"platform": platform, "ok": False,
              "seconds": round(time.monotonic() - t0, 1),
              "error": f"watchdog fired after {timeout:.0f}s (wedged)"})
        os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    try:
        import jax

        if platform:
            jax.config.update("jax_platforms", platform)
        dev = jax.devices()[0]
        val = int(jax.numpy.add(20, 22))       # one trivial device op
        assert val == 42
        integrity = _canary_check() if canary else None
    except Exception as e:  # noqa: BLE001 — any failure is "unhealthy"
        emit({"platform": platform, "ok": False,
              "seconds": round(time.monotonic() - t0, 1),
              "error": f"{type(e).__name__}: {str(e)[:300]}"})
        return 3
    verdict = {"platform": platform,
               "ok": integrity["trustworthy"] if integrity else True,
               "seconds": round(time.monotonic() - t0, 1),
               "device": str(dev)}
    if integrity is not None:
        verdict["integrity"] = integrity
    emit(verdict)
    return 0 if verdict["ok"] else 3


def probe_subprocess(platform: str, timeout: float,
                     python: str = sys.executable) -> bool:
    """Run the probe in a child interpreter; True iff healthy.  The grace
    margin lets the child's own watchdog fire first (self-exit, never
    SIGKILL — see module docstring)."""
    import subprocess

    cmd = [python, os.path.abspath(__file__),
           "--platform", platform, "--timeout", str(timeout)]
    try:
        proc = subprocess.run(cmd, timeout=timeout + 20, capture_output=True,
                              text=True, env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0:
        return False
    try:
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return False
    return bool(verdict.get("ok"))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", default=os.environ.get(
        "JAX_PLATFORMS", "cpu"), help="jax platform to probe")
    ap.add_argument("--timeout", type=float, default=55.0,
                    help="self-exit watchdog seconds")
    ap.add_argument("--canary", action="store_true",
                    help="also run one canary batch (integrity layer): "
                         "'backend up' vs 'backend trustworthy'")
    args = ap.parse_args()
    return probe(args.platform, args.timeout, canary=args.canary)


if __name__ == "__main__":
    sys.exit(main())
