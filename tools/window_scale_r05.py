"""Assemble WINDOW_SCALE_r05.json from the round's build + rate runs.

Collates the window-scaling story: lift pipeline at every length (4096 →
26.2M µops), dense vs chunked rates on the current platform, resolution
statistics, and the honest scaling model:

  per-trial work (exact)    ≈ S·E[chunks replayed]  — resolution-mix
                              dependent (SDC-heavy trials carry to the
                              window end)
  per-trial work (horizon)  ≤ S·(horizon+1)         — bounded, with only
                              vulnerable-preserving relabelings

plus the TPU projection: measured CPU lane-throughput scales by the
r4-measured TPU/CPU dense ratio on the same kernel family (934 vs 22.6
trials/s at 131k µops — BENCH/WINDOW_SCALE r4), clearly labeled as a
projection while the tunnel is down.

Usage: python tools/window_scale_r05.py --big-rate /tmp/ws_big.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="/tmp/bw_rate2.log",
                    help="log holding the lzss chunked-rate json line")
    ap.add_argument("--big-rate", default="/tmp/ws_big.json")
    ap.add_argument("--out", default=str(REPO / "WINDOW_SCALE_r05.json"))
    a = ap.parse_args()

    doc = {
        "build": {
            "lzss": {"capture_steps": 2124394, "capture_seconds": 205.0,
                     "lifts": {"4096": 1.0, "65546": 0.9999,
                               "524288": 0.9998, "5338673": 0.9998}},
            "lzss_big": {"capture_steps": 10490203,
                         "capture_seconds": 1427.4,
                         "lift_uops": 26220818, "lift_rate": 0.9998,
                         "lift_seconds": 7063.1},
        },
        "dense_cpu_r4": {"4096": 297.09, "65546": 22.56, "524288": 5.26},
        "dense_tpu_r4": {"131072": 934.0,
                         "note": "BENCH_TPU_r04 131k-µop stage"},
        "chunked_cpu_exact": {"4096": 137.03, "65546": 20.73,
                              "524288": 5.07, "5338673": 0.27},
        "notes": [
            "chunked == dense outcomes bit-for-bit (tests/test_chunked)",
            "exact chunked pays for the resolution mix: the 5.3M regfile "
            "campaign is 73% SDC — divergent trials replay to the window "
            "end, so exact per-trial work ≈ n/2 and chunking's win is "
            "the masked/frozen fraction plus constant-compile cost",
            "carry_horizon bounds per-trial work at (horizon+1) chunks "
            "with only masked→SDC / DUE→SDC relabelings (vulnerable set "
            "never shrinks)",
            "compile cost no longer scales with window length: the "
            "chunk kernel takes window arrays as arguments (one "
            "executable for any n); the r4 524k dense kernel spent 217 s "
            "compiling its embedded constants",
            "CPU numbers only — the TPU tunnel was wedged the whole "
            "session (bench.py --probe watchdog); the projection column "
            "applies the r4-measured TPU/CPU ratio of the same dense "
            "kernel family (41×) and is labeled as such",
        ],
    }
    big = Path(a.big_rate)
    if big.exists():
        d = json.loads(big.read_text())
        rates = d.get("rate", d).get("rates", {})
        for n, row in rates.items():
            doc.setdefault("chunked_cpu_horizon2", {})[n] = row
    doc["tpu_projection"] = {
        "method": "rate_tpu ≈ rate_cpu × (tpu lane-throughput / cpu "
                  "lane-throughput); r4 measured 934 trials/s at 131k "
                  "(TPU) vs 22.56 at 65.5k (CPU) → ~20.7× per-lane-step",
        "chunked_horizon2_26M_trials_per_sec": None,   # filled below
    }
    h2 = doc.get("chunked_cpu_horizon2", {})
    for n, row in h2.items():
        cpu_rate = row.get("trials_per_sec")
        res = row.get("resolution", {})
        if cpu_rate:
            doc["tpu_projection"]["chunked_horizon2_26M_trials_per_sec"] \
                = round(cpu_rate * 20.7, 1)
        if res.get("chunk_replays") and row.get("batch"):
            # at small CPU batches padding dominates (lanes_run real vs
            # chunk_replays padded); at TPU batch sizes (≥4096) fresh
            # trials pack the lanes, so the honest projection divides
            # REAL lane work by the r4-measured TPU lane throughput
            real_steps = res["lanes_run"] * 65536 / row["batch"]
            doc["tpu_projection"]["per_trial_lane_steps_real"] = int(
                real_steps)
            doc["tpu_projection"]["packed_batch_tpu_trials_per_sec"] = \
                round(1.22e8 / real_steps, 1)
            doc["tpu_projection"]["packed_note"] = (
                "1.22e8 lane-steps/s = r4-measured TPU dense throughput "
                "(934 trials/s × 131072); valid when the campaign batch "
                "is large enough to pack chunk waves (≥4096 trials)")
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({"chunked_cpu_horizon2": h2 and {
        n: r.get("trials_per_sec") for n, r in h2.items()},
        "projection": doc["tpu_projection"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
