// nativetrace — capture a real dynamic instruction stream via ptrace.
//
// The framework's ground-truth workload capture: the role the reference's
// ExecAll tracer (src/cpu/exetrace.cc), protobuf instruction traces
// (src/cpu/inst_pb_trace.cc) and ElasticTrace capture
// (src/cpu/o3/probe/elastic_trace.hh:93) play for gem5 — except the stream
// comes from the *host CPU itself* executing the workload, following the
// NativeTrace/statetrace precedent (src/cpu/nativetrace.cc).  The captured
// window feeds the macro→µop lifter (shrewd_tpu/ingest/lift.py), replacing
// synthetic traces (VERDICT r1 missing #1).
//
// Usage:
//   nativetrace <out.bin> <begin_hex> <end_hex> <max_steps> <prog> [args...]
//
// Single-steps the target from PC==begin to PC==end (exclusive), dumping the
// canonical register file each step, preceded by a snapshot of the writable
// memory regions at window start (the m5.cpt-analog of "architectural state
// at the SimPoint": registers + memory image, sim/serialize.hh semantics).
//
// Output format (little-endian):
//   magic  "SHTRACE2" (8 bytes)
//   u64 begin, u64 end, u64 n_steps (patched at close), u64 n_regions,
//   u64 fs_base   (TLS base — the %fs segment; the TLS block itself is a
//                  writable mapping and lands in the region snapshot, so
//                  fs_base makes %fs:disp accesses resolvable offline)
//   per region: u64 vaddr, u64 size, size bytes
//   per step:   18 × u64  (rax rcx rdx rbx rsp rbp rsi rdi r8..r15 rip
//                          eflags; encoding order — see ptrace_common.h)

#include "ptrace_common.h"

#include <string>
#include <vector>

struct Region {
  uint64_t vaddr;
  uint64_t size;
  std::vector<uint8_t> bytes;
};

// Writable private regions worth snapshotting, with the stack clipped to
// the live window around rsp (the rest of the 8 MB mapping is untouched).
static std::vector<Region> snapshot_memory(pid_t pid, uint64_t rsp) {
  std::vector<Region> out;
  char path[64];
  snprintf(path, sizeof path, "/proc/%d/maps", (int)pid);
  FILE *maps = fopen(path, "r");
  if (!maps) { perror("maps"); exit(2); }
  snprintf(path, sizeof path, "/proc/%d/mem", (int)pid);
  int memfd = open(path, O_RDONLY);
  if (memfd < 0) { perror("mem"); exit(2); }

  char line[512];
  while (fgets(line, sizeof line, maps)) {
    uint64_t lo, hi;
    char perms[8] = {0};
    char name[256] = {0};
    int n = sscanf(line, "%lx-%lx %7s %*s %*s %*s %255s",
                   (unsigned long *)&lo, (unsigned long *)&hi, perms, name);
    if (n < 3 || perms[1] != 'w') continue;            // writable only
    std::string nm(name);
    if (nm == "[vvar]" || nm == "[vvar_vclock]" || nm == "[vsyscall]" ||
        nm == "[vdso]")
      continue;
    if (nm == "[stack]") {
      // live stack only: a margin below rsp (red zone + callee frames to
      // come) up to the mapping top
      uint64_t lo_clip = rsp > 65536 ? rsp - 65536 : lo;
      if (lo_clip > lo) lo = lo_clip & ~0xfffULL;
    }
    if (hi - lo > (64ULL << 20)) continue;             // sanity cap
    Region r;
    r.vaddr = lo;
    r.size = hi - lo;
    r.bytes.resize(r.size);
    ssize_t got = pread(memfd, r.bytes.data(), r.size, (off_t)lo);
    if (got != (ssize_t)r.size) {
      // partial reads happen for guard pages; keep what we got
      if (got < 0) got = 0;
      r.size = (uint64_t)got;
      r.bytes.resize(r.size);
    }
    if (r.size) out.push_back(std::move(r));
  }
  fclose(maps);
  close(memfd);
  return out;
}

static void put_u64(FILE *f, uint64_t v) { fwrite(&v, 8, 1, f); }

int main(int argc, char **argv) {
  if (argc < 6) {
    fprintf(stderr,
            "usage: %s <out.bin> <begin_hex> <end_hex> <max_steps> "
            "<prog> [args...]\n", argv[0]);
    return 2;
  }
  const char *outpath = argv[1];
  uint64_t begin = strtoull(argv[2], nullptr, 16);
  uint64_t end = strtoull(argv[3], nullptr, 16);
  uint64_t max_steps = strtoull(argv[4], nullptr, 0);

  pid_t pid = spawn_traced(&argv[5], -1);
  if (!run_to(pid, begin)) {
    fprintf(stderr, "never reached begin marker %lx\n", (unsigned long)begin);
    return 2;
  }

  struct user_regs_struct regs;
  ptrace(PTRACE_GETREGS, pid, nullptr, &regs);
  std::vector<Region> regions = snapshot_memory(pid, regs.rsp);

  FILE *f = fopen(outpath, "wb");
  if (!f) { perror(outpath); return 2; }
  fwrite("SHTRACE3", 8, 1, f);
  put_u64(f, begin);
  put_u64(f, end);
  long n_steps_off = ftell(f);
  put_u64(f, 0);  // n_steps, patched below
  put_u64(f, regions.size());
  put_u64(f, (uint64_t)regs.fs_base);
  for (const Region &r : regions) {
    put_u64(f, r.vaddr);
    put_u64(f, r.size);
    fwrite(r.bytes.data(), 1, r.size, f);
  }

  uint64_t steps = 0;
  uint64_t c[kRegsPerStep + kXmmWords];
  struct user_fpregs_struct fpregs;
  bool clean_exit = false;
  while (steps < max_steps) {
    ptrace(PTRACE_GETREGS, pid, nullptr, &regs);
    if (regs.rip == end) { clean_exit = true; break; }
    regs_to_canonical(regs, c);
    ptrace(PTRACE_GETFPREGS, pid, nullptr, &fpregs);
    xmm_lo_to_canonical(fpregs, c + kRegsPerStep);
    fwrite(c, 8, kRegsPerStep + kXmmWords, f);
    steps++;
    if (!single_step(pid)) {
      fprintf(stderr, "child exited mid-window after %lu steps\n",
              (unsigned long)steps);
      break;
    }
  }
  // final state record (regs AT the end marker) so the lifter can check the
  // last macro-op's results too
  if (clean_exit) {
    regs_to_canonical(regs, c);
    ptrace(PTRACE_GETFPREGS, pid, nullptr, &fpregs);
    xmm_lo_to_canonical(fpregs, c + kRegsPerStep);
    fwrite(c, 8, kRegsPerStep + kXmmWords, f);
  }

  fseek(f, n_steps_off, SEEK_SET);
  put_u64(f, steps);
  fclose(f);

  kill(pid, SIGKILL);
  fprintf(stderr, "nativetrace: %lu steps, %zu regions, %s\n",
          (unsigned long)steps, regions.size(),
          clean_exit ? "hit end marker" : "TRUNCATED");
  return clean_exit ? 0 : 1;
}
