// hostsfi — statistical fault injection against the REAL host CPU.
//
// The framework's *independent* golden oracle (VERDICT r1 missing #2): for
// each trial, run the workload to a chosen dynamic instruction inside the
// measured window, flip one bit of one architectural register through
// ptrace (the direct analog of the reference's SFI perturbation through
// ThreadContext::setReg, src/cpu/thread_context.hh:190-207), let the
// program run to completion on real silicon, and classify by program
// outcome:
//   masked — exit 0 and stdout identical to the golden run
//   sdc    — exit 0 but different stdout (silent data corruption)
//   due    — fatal signal, nonzero exit, or hang (detectable/unrecoverable)
//
// Ground truth here is the x86 ISA + OS as implemented by the hardware —
// not any model of this framework — so AVF numbers from the TPU replay
// kernel can be differentially tested against physical reality
// (driver: shrewd_tpu/ingest/hostdiff.py, CI gate: tests/test_hostsfi.py).
//
// Usage:
//   hostsfi <coords.txt> <results.jsonl> <begin_hex> <end_hex> <prog>
//
// coords.txt: one trial per line, "step reg bit" — step is the dynamic
// instruction index within the window (0 = at the begin marker), reg is a
// canonical GPR index (ptrace_common.h), bit ∈ [0,64).  The Python side
// generates coordinates so the exact same (step, reg, bit) samples replay
// on the TPU kernel (paired-trial comparison, not just aggregate AVF).

#include "ptrace_common.h"

#include <string>
#include <vector>

static volatile sig_atomic_t g_alarm_fired = 0;
static void on_alarm(int) { g_alarm_fired = 1; }

struct RunResult {
  std::string out;
  int status = 0;       // waitpid status
  bool hang = false;
  bool fatal_signal = false;
  int term_sig = 0;
};

// Continue a traced child to completion, forwarding benign signals and
// treating fatal ones / hangs as DUE.
static RunResult run_to_exit(pid_t pid, int out_read_fd,
                             unsigned timeout_sec) {
  RunResult rr;
  g_alarm_fired = 0;
  alarm(timeout_sec);
  int deliver = 0;
  for (;;) {
    ptrace(PTRACE_CONT, pid, nullptr, (void *)(long)deliver);
    deliver = 0;
    int status = 0;
    pid_t w = waitpid(pid, &status, 0);
    if (w < 0) {
      if (errno == EINTR && g_alarm_fired) {
        kill(pid, SIGKILL);
        waitpid(pid, &status, 0);
        rr.hang = true;
        break;
      }
      continue;
    }
    if (WIFEXITED(status) || WIFSIGNALED(status)) {
      rr.status = status;
      if (WIFSIGNALED(status)) {
        rr.fatal_signal = true;
        rr.term_sig = WTERMSIG(status);
      }
      break;
    }
    if (WIFSTOPPED(status)) {
      int sig = WSTOPSIG(status);
      if (sig == SIGSEGV || sig == SIGBUS || sig == SIGFPE ||
          sig == SIGILL || sig == SIGSYS) {
        rr.fatal_signal = true;
        rr.term_sig = sig;
        kill(pid, SIGKILL);
        waitpid(pid, &status, 0);
        break;
      }
      if (sig != SIGTRAP) deliver = sig;   // forward benign signals
    }
  }
  alarm(0);
  // drain the child's stdout pipe (bounded)
  char buf[4096];
  ssize_t n;
  while ((n = read(out_read_fd, buf, sizeof buf)) > 0) {
    if (rr.out.size() < 65536) rr.out.append(buf, (size_t)n);
  }
  return rr;
}

struct Trial {
  long step;
  int reg;
  int bit;
};

int main(int argc, char **argv) {
  if (argc != 6) {
    fprintf(stderr,
            "usage: %s <coords.txt> <results.jsonl> <begin_hex> <end_hex> "
            "<prog>\n", argv[0]);
    return 2;
  }
  const char *coords_path = argv[1];
  const char *results_path = argv[2];
  uint64_t begin = strtoull(argv[3], nullptr, 16);
  uint64_t end = strtoull(argv[4], nullptr, 16);
  char *prog = argv[5];
  char *child_argv[] = {prog, nullptr};

  struct sigaction sa = {};
  sa.sa_handler = on_alarm;
  sigaction(SIGALRM, &sa, nullptr);   // no SA_RESTART: waitpid must EINTR

  // read trial coordinates
  std::vector<Trial> trials;
  {
    FILE *cf = fopen(coords_path, "r");
    if (!cf) { perror(coords_path); return 2; }
    Trial t;
    while (fscanf(cf, "%ld %d %d", &t.step, &t.reg, &t.bit) == 3) {
      // reg 0..15: GPR (bit < 64); reg 16..31: xmm[reg-16] low lane
      // (bit < 32) via PTRACE_GETFPREGS/SETFPREGS — the FP bank target
      // (reference: fpu/simd PhysRegFile banks, cpu/o3/regfile.hh:75-99)
      bool xmm_ok = t.reg >= kNumGPR && t.reg < kNumGPR + 16 &&
                    t.bit >= 0 && t.bit < 32;
      bool gpr_ok = t.reg >= 0 && t.reg < kNumGPR && t.bit >= 0 &&
                    t.bit < 64;
      if (!gpr_ok && !xmm_ok) {
        fprintf(stderr, "bad coord: %ld %d %d\n", t.step, t.reg, t.bit);
        return 2;
      }
      trials.push_back(t);
    }
    fclose(cf);
  }

  FILE *rf = fopen(results_path, "w");
  if (!rf) { perror(results_path); return 2; }

  // golden run through the same machinery
  int pfd[2];
  if (pipe(pfd) < 0) { perror("pipe"); return 2; }
  fcntl(pfd[0], F_SETFL, O_NONBLOCK);
  pid_t pid = spawn_traced(child_argv, pfd[1]);
  close(pfd[1]);
  if (!run_to(pid, begin)) { fprintf(stderr, "no begin\n"); return 2; }
  RunResult golden = run_to_exit(pid, pfd[0], 10);
  close(pfd[0]);
  if (golden.hang || golden.fatal_signal ||
      !WIFEXITED(golden.status) || WEXITSTATUS(golden.status) != 0) {
    fprintf(stderr, "golden run failed\n");
    return 2;
  }
  fprintf(stderr, "golden output: %s", golden.out.c_str());

  int n_masked = 0, n_sdc = 0, n_due = 0;
  for (size_t i = 0; i < trials.size(); i++) {
    const Trial &t = trials[i];
    if (pipe(pfd) < 0) { perror("pipe"); return 2; }
    fcntl(pfd[0], F_SETFL, O_NONBLOCK);
    pid = spawn_traced(child_argv, pfd[1]);
    close(pfd[1]);
    if (!run_to(pid, begin)) { fprintf(stderr, "no begin\n"); return 2; }
    bool alive = true;
    for (long s = 0; s < t.step && alive; s++) alive = single_step(pid);
    const char *outcome;
    if (!alive) {
      outcome = "due";          // exited inside the window (cannot happen
      n_due++;                  // for in-range steps; defensive)
      close(pfd[0]);
    } else {
      struct user_regs_struct regs;
      if (t.reg >= kNumGPR) {
        struct user_fpregs_struct fpr;
        ptrace(PTRACE_GETFPREGS, pid, nullptr, &fpr);
        fpr.xmm_space[4 * (t.reg - kNumGPR)] ^= (1U << t.bit);
        ptrace(PTRACE_SETFPREGS, pid, nullptr, &fpr);
      } else {
        ptrace(PTRACE_GETREGS, pid, nullptr, &regs);
        uint64_t v = canonical_get(regs, t.reg);
        canonical_set(regs, t.reg, v ^ (1ULL << t.bit));
        ptrace(PTRACE_SETREGS, pid, nullptr, &regs);
      }
      RunResult rr = run_to_exit(pid, pfd[0], 5);
      close(pfd[0]);
      if (rr.hang || rr.fatal_signal || !WIFEXITED(rr.status) ||
          WEXITSTATUS(rr.status) != 0) {
        outcome = "due";
        n_due++;
      } else if (rr.out != golden.out) {
        outcome = "sdc";
        n_sdc++;
      } else {
        outcome = "masked";
        n_masked++;
      }
    }
    fprintf(rf, "{\"trial\": %zu, \"step\": %ld, \"reg\": %d, \"bit\": %d, "
            "\"outcome\": \"%s\"}\n", i, t.step, t.reg, t.bit, outcome);
    if ((i + 1) % 200 == 0)
      fprintf(stderr, "hostsfi: %zu/%zu trials\n", i + 1, trials.size());
  }
  fclose(rf);
  double n = (double)trials.size();
  fprintf(stderr,
          "hostsfi: %zu trials — masked %d sdc %d due %d (avf %.4f)\n",
          trials.size(), n_masked, n_sdc, n_due,
          n > 0 ? (n_sdc + n_due) / n : 0.0);
  printf("{\"trials\": %zu, \"masked\": %d, \"sdc\": %d, \"due\": %d}\n",
         trials.size(), n_masked, n_sdc, n_due);
  return 0;
}
