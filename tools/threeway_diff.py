"""Three-way differential on identical fault coordinates:

  gem5    — the actual reference binary (gem5build/, checkpoint-patch-
            restore trials recorded in GEM5_GOLDEN_r04.json)
  host    — real x86 silicon (hostsfi ptrace flips; re-run here to pin
            run-to-run stability, must match the artifact's host column)
  device  — this framework's replay kernel, 64-bit pair-lane lift
            (ingest/lift64.py), diverged trials escalated to the
            whole-program emulator oracle

All three flip the same (reg, bit) of the same architected GPR at the
same kernel_begin marker of the same binary and classify by program
outcome (masked / sdc / due).  The gem5 leg is the reference's own
restore+perturb loop (serialized thread context, the
ThreadContext::setReg shape — reference src/cpu/thread_context.hh:190);
the device leg is the TPU-native kernel this framework exists to run.

Writes THREEWAY_r04.json.

Usage: PYTHONPATH=/root/repo python tools/threeway_diff.py \
           [--golden GEM5_GOLDEN_r04.json] [--out THREEWAY_r04.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent

CLASSES = ("masked", "sdc", "due")


def tally(seq):
    t = {c: 0 for c in CLASSES}
    for s in seq:
        t[s] += 1
    return t


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--golden", default=str(REPO / "GEM5_GOLDEN_r04.json"))
    ap.add_argument("--out", default=str(REPO / "THREEWAY_r04.json"))
    a = ap.parse_args()

    with open(a.golden) as f:
        golden = json.load(f)
    trials = golden["trials"]
    assert trials and "host" in trials[0], \
        "golden artifact lacks per-trial host outcomes"

    from shrewd_tpu.ingest.hostdiff import (HOST_OUTCOME, build_tools,
                                            capture_and_lift_to_output,
                                            run_device, run_host)
    from shrewd_tpu.ingest.lift64 import lift64

    names = {v: k for k, v in HOST_OUTCOME.items()}
    # the golden artifact records 'workloads/<x>.c (gcc ...)' — build the
    # SAME workload and prove it is the same binary gem5 perturbed
    workload_c = golden["workload"].split(" ")[0]
    if "/" not in workload_c:        # pre---workload artifacts: bare stem
        workload_c = f"workloads/{workload_c}"
    paths = build_tools(workload_c)
    import hashlib
    with open(paths.workload, "rb") as f:
        sha = hashlib.sha256(f.read()).hexdigest()
    assert sha == golden["binary_sha"], (
        f"built {workload_c} sha {sha[:12]} != golden artifact's "
        f"{golden['binary_sha'][:12]} — the three-way would compare "
        "different binaries")
    coords = np.array([[0, t["reg"], t["bit"]] for t in trials],
                      dtype=np.int64)

    # host leg re-run: silicon outcomes must reproduce the artifact's
    host = run_host(paths, coords)
    host_cls = [names[int(h)] for h in host]
    host_stable = sum(h == t["host"] for h, t in zip(host_cls, trials))

    # device leg: 64-bit pair-lane lift on the replay kernel
    trace, meta = capture_and_lift_to_output(paths, lifter=lift64)
    report: dict = {}
    dev = run_device(trace, meta, coords, paths=paths, report=report)
    dev_cls = [names[int(d)] for d in dev]

    n = len(trials)
    gem5_cls = [t["gem5"] for t in trials]
    pair = lambda x, y: sum(a == b for a, b in zip(x, y)) / n  # noqa: E731
    vuln = lambda x, y: sum((a != "masked") == (b != "masked")  # noqa: E731
                            for a, b in zip(x, y)) / n
    avf = lambda c: sum(v != "masked" for v in c) / n           # noqa: E731

    doc = {
        "experiment": golden["experiment"],
        "workload": golden["workload"],
        "binary_sha": golden["binary_sha"],
        "coords": n,
        "tallies": {"gem5": tally(gem5_cls), "host": tally(host_cls),
                    "device": tally(dev_cls)},
        "avf": {"gem5": avf(gem5_cls), "host": avf(host_cls),
                "device": avf(dev_cls)},
        "agreement_exact": {
            "gem5_vs_host": pair(gem5_cls, host_cls),
            "gem5_vs_device": pair(gem5_cls, dev_cls),
            "host_vs_device": pair(host_cls, dev_cls),
            "all_three": sum(g == h == d for g, h, d in
                             zip(gem5_cls, host_cls, dev_cls)) / n,
        },
        "agreement_vulnerable": {
            "gem5_vs_device": vuln(gem5_cls, dev_cls),
            "host_vs_device": vuln(host_cls, dev_cls),
        },
        "host_rerun_stability": host_stable / n,
        "device_report": {k: int(v) if isinstance(v, (int, np.integer))
                          else v for k, v in report.items()},
        "disagreements_total": sum(not (g == h == d) for g, h, d in
                                   zip(gem5_cls, host_cls, dev_cls)),
        "disagreements": [
            {"reg": t["reg"], "bit": t["bit"], "gem5": g, "host": h,
             "device": d}
            for t, g, h, d in zip(trials, gem5_cls, host_cls, dev_cls)
            if not (g == h == d)][:64],
        "note": ("One binary, one marker, one coordinate list, three "
                 "executors.  The gem5 column is the reference binary's "
                 "own checkpoint-perturb-restore loop; the device column "
                 "is computed by this framework's replay kernel over the "
                 "64-bit pair-lane lift, with diverged trials escalated "
                 "to the whole-program emulator oracle."),
    }
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({k: doc[k] for k in
                      ("avf", "agreement_exact", "agreement_vulnerable",
                       "host_rerun_stability")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
