"""Observability CLI — the operator surface over ``shrewd_tpu/obs/``.

Three modes:

- **summarize** — event counts, span statistics, tenants and lanes of a
  trace artifact (a raw event stream, a ``flightrec.json`` dump, or a
  Perfetto ``trace.json``)::

      python tools/obs.py --summarize out/trace.json

- **timeline** — human-readable seq-ordered rendering of a flight
  recorder dump (the "why did this tenant quarantine" artifact)::

      python tools/obs.py --timeline fleet_out/flightrec.json

- **tail** — the live fleet metrics snapshot the resident scheduler
  publishes each tick (``metrics.json`` / ``metrics.prom``)::

      python tools/obs.py --tail fleet_out            # one-shot
      python tools/obs.py --tail fleet_out --follow   # poll until ^C

All three read artifacts only — they never touch scheduler or
orchestrator internals, which is the point: everything an operator
needs is in the published files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: Perfetto async phases back to the tracer's span phases
_FROM_ASYNC = {"b": "B", "e": "E"}


def load_doc(path: str):
    with open(path) as f:
        return json.load(f)


def events_of(doc, path: str) -> list[dict]:
    """Events from any trace artifact this repo writes: a raw event
    list, a flight-recorder dump (``{"events": [...]}``) or a Perfetto
    ``trace_event`` document (``{"traceEvents": [...]}`` — metadata
    records are dropped, async phases map back to B/E)."""
    if isinstance(doc, list):
        return doc
    if "events" in doc:
        return doc["events"]
    if "traceEvents" in doc:
        out = []
        for i, rec in enumerate(r for r in doc["traceEvents"]
                                if r.get("ph") != "M"):
            # a Perfetto doc's ts axis is microseconds-from-t0 (or the
            # bare seq ordinal for clock-free traces) — not the second-
            # denominated timestamps summarize's span durations expect.
            # Drop it: counts/lanes/pairing still summarize; durations
            # come from the raw stream artifacts (flightrec.json).
            out.append({"seq": i, "name": rec.get("name", ""),
                        "cat": rec.get("cat", ""),
                        "ph": _FROM_ASYNC.get(rec.get("ph"),
                                              rec.get("ph", "i")),
                        "args": rec.get("args", {}),
                        "ts": None})
        return out
    raise ValueError(f"{path}: not a recognized trace artifact "
                     "(raw events / flightrec.json / trace.json)")


def cmd_summarize(path: str) -> int:
    from shrewd_tpu.obs import export

    doc = load_doc(path)
    summary = export.summarize(events_of(doc, path))
    if isinstance(doc, dict) and "reason" in doc:
        # flight-recorder dumps carry the abnormal-exit reason — the
        # first thing a post-mortem wants to see
        summary = {"reason": doc["reason"], "coords": doc.get("coords"),
                   "emitted": doc.get("emitted"),
                   "dropped": doc.get("dropped"), **summary}
    print(json.dumps(summary, indent=1))
    return 0


def cmd_timeline(path: str, width: int) -> int:
    from shrewd_tpu.obs import export

    doc = load_doc(path)
    if isinstance(doc, dict) and "reason" in doc:
        _log(f"flight recorder: reason={doc['reason']} "
             f"coords={doc.get('coords')} emitted={doc.get('emitted')} "
             f"dropped={doc.get('dropped')}")
    print(export.render_text(events_of(doc, path), width=width))
    return 0


def _render_snapshot(snap: dict) -> str:
    fleet = snap.get("fleet", {})
    lines = [f"tick {snap.get('tick', 0)}: "
             f"{fleet.get('tenants', 0)} tenants {fleet.get('by_status')}"
             f" fairness={fleet.get('fairness_index')}"
             f" cache_hit={fleet.get('cache_hit_rate')}"
             f" journal_depth={fleet.get('journal_depth')}"]
    for name, row in sorted(snap.get("tenants", {}).items()):
        hw = row.get("halfwidth") or {}
        hw_s = (" hw=" + ",".join(f"{k}:{v}" for k, v in sorted(hw.items()))
                if hw else "")
        lines.append(
            f"  {name}: {row.get('status')} trials={row.get('trials')}"
            f" ({row.get('trials_per_s')}/s) vtime={row.get('vtime')}"
            f" ticks={row.get('ticks')}"
            + (f" failures={row['failures']}" if row.get("failures") else "")
            + (f" eta={row['eta_trials']:g}tr"
               + (f"/{row['eta_s']:g}s" if row.get("eta_s") is not None
                  else "")
               if row.get("eta_trials") is not None else "")
            + hw_s)
    return "\n".join(lines)


def cmd_tail(outdir: str, follow: bool, interval: float) -> int:
    from shrewd_tpu.obs import metrics

    last_tick = None
    while True:
        try:
            snap = metrics.read(outdir)
        except (OSError, ValueError):
            if not follow:
                _log(f"{outdir}: no metrics.json (is the fleet serving "
                     "with an --outdir?)")
                return 1
            time.sleep(interval)
            continue
        if snap.get("tick") != last_tick:
            last_tick = snap.get("tick")
            print(_render_snapshot(snap), flush=True)
        if not follow:
            return 0
        time.sleep(interval)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="trace/metrics/flight-recorder tooling "
                    "(shrewd_tpu/obs/)")
    ap.add_argument("--summarize", metavar="TRACE",
                    help="event counts + span statistics of a trace "
                         "artifact (raw events / flightrec.json / "
                         "Perfetto trace.json)")
    ap.add_argument("--timeline", metavar="FLIGHTREC",
                    help="render a flight-recorder dump (or any event "
                         "stream) as a seq-ordered timeline")
    ap.add_argument("--tail", metavar="OUTDIR",
                    help="print the fleet's latest metrics snapshot")
    ap.add_argument("--follow", action="store_true",
                    help="[tail] keep polling; print on every new tick")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="[tail --follow] poll seconds (default 1)")
    ap.add_argument("--width", type=int, default=100,
                    help="[timeline] max line width")
    a = ap.parse_args(argv)

    if a.summarize:
        return cmd_summarize(a.summarize)
    if a.timeline:
        return cmd_timeline(a.timeline, a.width)
    if a.tail:
        try:
            return cmd_tail(a.tail, a.follow, a.interval)
        except KeyboardInterrupt:
            return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:      # `obs.py --timeline ... | head` is normal
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
