"""Multi-tenant fleet driver — the CLI over ``shrewd_tpu/service/``.

Three modes:

- **serve** — run the resident scheduler over a submission spool; tenants
  can be submitted while the fleet runs, and SIGTERM drains every tenant
  to a namespaced resumable checkpoint (rc 4)::

      python tools/fleet.py --serve --queue /spool --outdir fleet_out

- **submit** — spool one tenant (a plan JSON + scheduling identity) from
  any process; returns the ticket name::

      python tools/fleet.py --submit plan.json --queue /spool \\
          --name exp42 --priority 1 --weight 2 --quota-batches 100

- **direct** — admit plan files straight into a fleet and run it to
  completion (the embarrassingly-simple mode benchmarks and the
  northstar fleet sweep use)::

      python tools/fleet.py --plans a.json b.json --outdir fleet_out

``--resume fleet_out`` rebuilds a CLEANLY drained fleet from its
checkpoint; ``--recover fleet_out`` replays checkpoint + write-ahead
journal after a HARD kill (SIGKILL/OOM — ``service/journal.py``) and
continues every resumable tenant bit-identically.  ``--resume``
auto-detects a dirty shutdown and routes to recovery; ``--serve`` over
a dirty outdir refuses (run ``--recover`` first).  Every server mode
takes an O_EXCL+pid lock on the spool (or fleet) directory so two
servers cannot double-claim one fleet; a lock whose pid is dead is
reaped automatically.  ``--chaos-plan`` arms the service-level chaos
kinds (``kill_fleet`` / ``torn_journal`` / ``corrupt_submission``) for
reproducible survivability drills.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cmd_submit(a) -> int:
    from shrewd_tpu.service import SubmissionQueue, TenantSpec

    if not a.queue:
        _log("--submit needs --queue")
        return 2
    with open(a.submit) as f:
        plan = json.load(f)
    name = a.name or os.path.splitext(os.path.basename(a.submit))[0]
    ticket = SubmissionQueue(a.queue).submit(TenantSpec(
        name=name, plan=plan, priority=a.priority, weight=a.weight,
        quota_batches=a.quota_batches))
    print(json.dumps({"ticket": ticket, "tenant": name}))
    return 0


def _report(sched) -> None:
    for name, t in sched.tenants.items():
        _log(f"  {name}: {t.status} (rc={t.rc}, {t.trials} trials, "
             f"{t.ticks} ticks, {t.wall_s:.1f}s"
             + (f", {t.kills} kills survived" if t.kills else "")
             + (f", {t.failures} failures" if t.failures else "") + ")")
    _log(f"fleet: {sched.ticks} ticks, fairness "
         f"{sched.fairness_index():.3f}, statuses {sched._by_status()}"
         + (f", {sched.recoveries} recoveries" if sched.recoveries
            else ""))


def cmd_serve(a) -> int:
    from shrewd_tpu.service import (CampaignScheduler, LockHeld,
                                    ServerLock, SubmissionQueue,
                                    TenantSpec, is_dirty)

    if a.trace:
        from shrewd_tpu.obs import trace as obs_trace

        obs_trace.enable(ring=a.trace_ring or obs_trace.DEFAULT_RING)
    queue = SubmissionQueue(a.queue) if a.queue else None
    chaos = None
    if a.chaos_plan:
        from shrewd_tpu.chaos import ChaosEngine

        chaos = ChaosEngine.from_path(a.chaos_plan, worker="fleet")
    # single-server guard: one O_EXCL+pid lock per spool (or, spool-less,
    # per fleet dir) — two servers racing one fleet would silently split
    # its tenants across two schedulers and two journals
    lock = ServerLock(a.queue or a.recover or a.resume or a.outdir)
    try:
        lock.acquire()
    except LockHeld as e:
        _log(f"another server owns this fleet: {e}")
        return 2
    try:
        common = dict(queue=queue, certify=a.certify,
                      idle_exit=not a.stay_resident, chaos=chaos)
        # only explicit CLI values override the snapshot's persisted
        # knobs on --resume/--recover (argparse default is None)
        if a.retry_budget is not None:
            common["retry_budget"] = a.retry_budget
        if a.tick_timeout is not None:
            common["tick_timeout"] = a.tick_timeout
        if a.recover:
            sched = CampaignScheduler.recover(a.recover, **common)
            _log(f"recovered fleet: {sched.recoveries} recoveries, "
                 f"{sched.journal_torn} torn journal records dropped")
        elif a.resume:
            if is_dirty(a.resume):
                _log("dirty shutdown detected (journal ahead of "
                     "snapshot) — recovering")
                sched = CampaignScheduler.recover(a.resume, **common)
            else:
                sched = CampaignScheduler.resume(a.resume, **common)
        else:
            if is_dirty(a.outdir):
                _log(f"{a.outdir}: dirty shutdown detected — refusing "
                     "to serve over un-recovered state; run --recover "
                     "first")
                return 2
            sched = CampaignScheduler(
                outdir=a.outdir, depth_budget=a.depth_budget,
                policy=a.policy, **common)
        for i, path in enumerate(a.plans):
            with open(path) as f:
                plan = json.load(f)
            name = f"t{i}_{os.path.splitext(os.path.basename(path))[0]}"
            sched.admit(TenantSpec(name=name, plan=plan))
        if a.matrix:
            # open-loop scenario matrix: admit the expanded cell set as
            # plain tenants (full cross-product, no Pareto prune — the
            # closed loop lives in tools/scenario.py --serve).  The
            # matrix document is persisted so tools/scenario.py
            # --status/--pareto work over this fleet's outdir too.
            from shrewd_tpu.resilience import write_json_atomic
            from shrewd_tpu.scenario import MATRIX_DOC, ScenarioMatrix

            with open(a.matrix) as f:
                matrix = ScenarioMatrix.from_dict(json.load(f))
            if sched.outdir:
                os.makedirs(sched.outdir, exist_ok=True)
                write_json_atomic(os.path.join(sched.outdir, MATRIX_DOC),
                                  matrix.to_dict())
            n = 0
            for spec in matrix.tenant_specs():
                if spec.name not in sched.tenants:
                    sched.admit(spec)
                    n += 1
            _log(f"matrix {matrix.tag!r}: admitted {n} cells "
                 "(open loop — no Pareto prune; use tools/scenario.py "
                 "--serve for the closed loop)")
        restore = sched.install_signal_handlers()
        try:
            rc = sched.run()
        finally:
            restore()
        _report(sched)
        if sched.outdir:
            _log(f"live metrics: {sched.outdir}/metrics.json (+ .prom) — "
                 "tail with tools/obs.py --tail")
        return rc
    finally:
        lock.release()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-tenant campaign fleet (shrewd_tpu/service/)")
    ap.add_argument("--serve", action="store_true",
                    help="run the resident scheduler")
    ap.add_argument("--submit", metavar="PLAN_JSON", default="",
                    help="spool one tenant into --queue and exit")
    ap.add_argument("--plans", nargs="*", default=[],
                    help="plan JSONs admitted directly (no spool needed)")
    ap.add_argument("--matrix", default="",
                    help="ScenarioMatrix JSON: admit the expanded "
                         "cross-product cell set as plain tenants "
                         "(open loop; closed-loop pruning lives in "
                         "tools/scenario.py --serve)")
    ap.add_argument("--queue", default="",
                    help="submission spool directory (service/queue.py)")
    ap.add_argument("--outdir", default="fleet_out",
                    help="fleet artifact root (per-tenant namespaces under "
                         "tenants/, fleet checkpoint under fleet_ckpt/)")
    ap.add_argument("--resume", default="",
                    help="resume a drained fleet from this outdir "
                         "(auto-recovers on a detected dirty shutdown)")
    ap.add_argument("--recover", default="",
                    help="replay checkpoint + write-ahead journal after "
                         "a hard kill and continue the fleet")
    ap.add_argument("--chaos-plan", default="",
                    help="fleet-level chaos plan JSON (kill_fleet / "
                         "torn_journal / corrupt_submission) for "
                         "reproducible survivability drills")
    ap.add_argument("--retry-budget", type=int, default=None,
                    help="tick-exception retries per tenant before "
                         "durable quarantine (backoff is tick-counted "
                         "exponential; default 3, resume/recover keep "
                         "the snapshot's value unless overridden)")
    ap.add_argument("--tick-timeout", type=float, default=None,
                    help="per-tenant tick watchdog deadline seconds "
                         "(0 = off, the default): a livelocked tenant "
                         "is preempted and quarantined instead of "
                         "wedging the fleet")
    ap.add_argument("--depth-budget", type=int, default=4,
                    help="global dispatch-depth budget shared by running "
                         "tenants")
    ap.add_argument("--policy", default="fair",
                    choices=("fair", "priority"),
                    help="fair = strict priority classes + weighted "
                         "fair-share stride within a class; priority = "
                         "strict priority, FIFO within a class")
    ap.add_argument("--certify", default="",
                    choices=("", "off", "warn", "strict"),
                    help="admission-time graftlint certification floor "
                         "applied to every tenant's executables")
    ap.add_argument("--trace", action="store_true",
                    help="install the process-wide tracer "
                         "(shrewd_tpu/obs/): per-tenant event lanes, "
                         "Perfetto trace.json, flight-recorder dump on "
                         "quarantine/hard-kill")
    ap.add_argument("--trace-ring", type=int, default=0,
                    help="flight-recorder ring capacity in events "
                         "(default 8192)")
    ap.add_argument("--stay-resident", action="store_true",
                    help="keep serving an empty queue (SIGTERM drains); "
                         "default exits when all tenants are terminal "
                         "and the spool is empty")
    ap.add_argument("--name", default="", help="[submit] tenant name")
    ap.add_argument("--priority", type=int, default=0,
                    help="[submit] strict-priority class (higher first)")
    ap.add_argument("--weight", type=float, default=1.0,
                    help="[submit] fair-share weight within the class")
    ap.add_argument("--quota-batches", type=int, default=0,
                    help="[submit] scheduler-level batch quota "
                         "(0 = none; at quota the tenant drains to a "
                         "resumable checkpoint)")
    ap.add_argument("--platform", default=None,
                    help="jax platform override (cpu/tpu/axon)")
    a = ap.parse_args(argv)

    if a.platform:
        import jax
        jax.config.update("jax_platforms", a.platform)
    if a.submit:
        return cmd_submit(a)
    if a.serve or a.plans or a.matrix or a.resume or a.recover:
        return cmd_serve(a)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
