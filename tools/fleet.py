"""Multi-tenant fleet driver — the CLI over ``shrewd_tpu/service/``.

Three modes:

- **serve** — run the resident scheduler over a submission spool; tenants
  can be submitted while the fleet runs, and SIGTERM drains every tenant
  to a namespaced resumable checkpoint (rc 4)::

      python tools/fleet.py --serve --queue /spool --outdir fleet_out

- **submit** — spool one tenant (a plan JSON + scheduling identity) from
  any process; returns the ticket name::

      python tools/fleet.py --submit plan.json --queue /spool \\
          --name exp42 --priority 1 --weight 2 --quota-batches 100

- **direct** — admit plan files straight into a fleet and run it to
  completion (the embarrassingly-simple mode benchmarks and the
  northstar fleet sweep use)::

      python tools/fleet.py --plans a.json b.json --outdir fleet_out

``--resume fleet_out`` rebuilds a drained fleet from its checkpoint and
continues every resumable tenant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cmd_submit(a) -> int:
    from shrewd_tpu.service import SubmissionQueue, TenantSpec

    if not a.queue:
        _log("--submit needs --queue")
        return 2
    with open(a.submit) as f:
        plan = json.load(f)
    name = a.name or os.path.splitext(os.path.basename(a.submit))[0]
    ticket = SubmissionQueue(a.queue).submit(TenantSpec(
        name=name, plan=plan, priority=a.priority, weight=a.weight,
        quota_batches=a.quota_batches))
    print(json.dumps({"ticket": ticket, "tenant": name}))
    return 0


def _report(sched) -> None:
    for name, t in sched.tenants.items():
        _log(f"  {name}: {t.status} (rc={t.rc}, {t.trials} trials, "
             f"{t.ticks} ticks, {t.wall_s:.1f}s"
             + (f", {t.kills} kills survived" if t.kills else "") + ")")
    _log(f"fleet: {sched.ticks} ticks, fairness "
         f"{sched.fairness_index():.3f}, statuses {sched._by_status()}")


def cmd_serve(a) -> int:
    from shrewd_tpu.service import (CampaignScheduler, SubmissionQueue,
                                    TenantSpec)

    queue = SubmissionQueue(a.queue) if a.queue else None
    if a.resume:
        sched = CampaignScheduler.resume(
            a.resume, queue=queue, certify=a.certify,
            idle_exit=not a.stay_resident)
    else:
        sched = CampaignScheduler(
            outdir=a.outdir, queue=queue, depth_budget=a.depth_budget,
            policy=a.policy, certify=a.certify,
            idle_exit=not a.stay_resident)
    for i, path in enumerate(a.plans):
        with open(path) as f:
            plan = json.load(f)
        name = f"t{i}_{os.path.splitext(os.path.basename(path))[0]}"
        sched.admit(TenantSpec(name=name, plan=plan))
    restore = sched.install_signal_handlers()
    try:
        rc = sched.run()
    finally:
        restore()
    _report(sched)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-tenant campaign fleet (shrewd_tpu/service/)")
    ap.add_argument("--serve", action="store_true",
                    help="run the resident scheduler")
    ap.add_argument("--submit", metavar="PLAN_JSON", default="",
                    help="spool one tenant into --queue and exit")
    ap.add_argument("--plans", nargs="*", default=[],
                    help="plan JSONs admitted directly (no spool needed)")
    ap.add_argument("--queue", default="",
                    help="submission spool directory (service/queue.py)")
    ap.add_argument("--outdir", default="fleet_out",
                    help="fleet artifact root (per-tenant namespaces under "
                         "tenants/, fleet checkpoint under fleet_ckpt/)")
    ap.add_argument("--resume", default="",
                    help="resume a drained fleet from this outdir")
    ap.add_argument("--depth-budget", type=int, default=4,
                    help="global dispatch-depth budget shared by running "
                         "tenants")
    ap.add_argument("--policy", default="fair",
                    choices=("fair", "priority"),
                    help="fair = strict priority classes + weighted "
                         "fair-share stride within a class; priority = "
                         "strict priority, FIFO within a class")
    ap.add_argument("--certify", default="",
                    choices=("", "off", "warn", "strict"),
                    help="admission-time graftlint certification floor "
                         "applied to every tenant's executables")
    ap.add_argument("--stay-resident", action="store_true",
                    help="keep serving an empty queue (SIGTERM drains); "
                         "default exits when all tenants are terminal "
                         "and the spool is empty")
    ap.add_argument("--name", default="", help="[submit] tenant name")
    ap.add_argument("--priority", type=int, default=0,
                    help="[submit] strict-priority class (higher first)")
    ap.add_argument("--weight", type=float, default=1.0,
                    help="[submit] fair-share weight within the class")
    ap.add_argument("--quota-batches", type=int, default=0,
                    help="[submit] scheduler-level batch quota "
                         "(0 = none; at quota the tenant drains to a "
                         "resumable checkpoint)")
    ap.add_argument("--platform", default=None,
                    help="jax platform override (cpu/tpu/axon)")
    a = ap.parse_args(argv)

    if a.platform:
        import jax
        jax.config.update("jax_platforms", a.platform)
    if a.submit:
        return cmd_submit(a)
    if a.serve or a.plans or a.resume:
        return cmd_serve(a)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
