"""Scenario-matrix driver — the CLI over ``shrewd_tpu/scenario/``.

One declarative plan (see README "Scenario matrix" for the schema)
expands to the full (workloads × windows × fault targets × protection
schemes × thermal envelopes) cross-product and runs it as a tenant set
through the resident fleet, with the closed Pareto loop pruning
dominated cells and emitting ``PARETO_<tag>.json``:

- **serve** — expand, admit, run the fleet to completion with the
  closed loop folding every ``--pareto-every`` ticks::

      python tools/scenario.py --plan matrix.json --serve --outdir m_out

- **recover** — rebuild a killed matrix fleet from its persisted
  ``matrix.json`` + the write-ahead journal and continue (completed
  cells keep their results, journaled prune decisions re-apply
  exactly)::

      python tools/scenario.py --recover m_out

- **status** — read-only matrix progress from the persisted surfaces
  (``matrix.json`` + per-tick ``metrics.json`` + the PARETO artifact);
  safe against a live server::

      python tools/scenario.py --status m_out

- **pareto** — one-shot fold: rebuild the fleet state (no cells run)
  and re-emit the artifact from the recorded tallies::

      python tools/scenario.py --pareto m_out

- **expand** — print the expanded cell set without running anything
  (plan debugging)::

      python tools/scenario.py --plan matrix.json --expand

``tools/fleet.py --matrix matrix.json`` is the OPEN-loop sibling: it
admits the same expanded cell set into a plain fleet (no Pareto fold,
no pruning) for when the full cross-product is wanted measured.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _load_matrix(path: str):
    from shrewd_tpu.scenario import ScenarioMatrix

    with open(path) as f:
        return ScenarioMatrix.from_dict(json.load(f))


def cmd_expand(a) -> int:
    matrix = _load_matrix(a.plan)
    cells = matrix.expand()
    print(json.dumps({"tag": matrix.tag, "n_cells": len(cells),
                      "cells": [c.to_dict() for c in cells]}, indent=1))
    return 0


def cmd_serve(a) -> int:
    from shrewd_tpu.scenario import ScenarioRunner
    from shrewd_tpu.service import LockHeld, ServerLock, is_dirty

    if a.trace:
        from shrewd_tpu.obs import trace as obs_trace

        obs_trace.enable()
    lock = ServerLock(a.recover or a.outdir)
    try:
        lock.acquire()
    except LockHeld as e:
        _log(f"another server owns this fleet: {e}")
        return 2
    try:
        kw = dict(prune=not a.no_prune, pareto_every=a.pareto_every,
                  certify=a.certify)
        if a.chaos_plan:
            from shrewd_tpu.chaos import ChaosEngine

            kw["chaos"] = ChaosEngine.from_path(a.chaos_plan,
                                                worker="fleet")
        if a.recover:
            runner = ScenarioRunner.recover(a.recover, **kw)
            _log(f"recovered matrix {runner.matrix.tag!r}: "
                 f"{runner.sched.recoveries} recoveries, "
                 f"{len(runner.decisions(runner.sched))} prune "
                 "decisions replayed")
            rc = runner.run()
        else:
            if is_dirty(a.outdir):
                _log(f"{a.outdir}: dirty shutdown detected — refusing "
                     "to serve over un-recovered state; run --recover "
                     "first")
                return 2
            runner = ScenarioRunner(_load_matrix(a.plan), a.outdir, **kw)
            rc = runner.serve()
        sched = runner.sched
        for name, t in sched.tenants.items():
            _log(f"  {name}: {t.status} ({t.trials} trials"
                 + (f", pruned: {t.revoked}" if t.revoked else "") + ")")
        from shrewd_tpu.scenario import pareto as par

        _log(f"matrix {runner.matrix.tag!r}: {sched.ticks} ticks, "
             f"statuses {sched._by_status()}; artifact "
             f"{par.artifact_path(runner.outdir, runner.matrix.tag)}")
        return rc
    finally:
        lock.release()


def cmd_status(a) -> int:
    from shrewd_tpu.scenario import ScenarioRunner

    print(json.dumps(ScenarioRunner.status(a.status), indent=1))
    return 0


def cmd_pareto(a) -> int:
    """One-shot fold over the recorded state: recover the fleet ledgers
    (no cell runs — recovery only replays the journal) and re-emit the
    artifact."""
    from shrewd_tpu.scenario import ScenarioRunner
    from shrewd_tpu.service import LockHeld, ServerLock

    lock = ServerLock(a.pareto)
    try:
        lock.acquire()
    except LockHeld as e:
        _log(f"another server owns this fleet: {e}")
        return 2
    try:
        runner = ScenarioRunner.recover(a.pareto, prune=False)
        doc = runner.emit_artifact()
        print(json.dumps({"tag": doc["tag"],
                          "cells": len(doc["cells"]),
                          "decisions": len(doc["decisions"]),
                          "groups": list(doc["search"])}, indent=1))
        return 0
    finally:
        lock.release()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scenario-matrix campaigns (shrewd_tpu/scenario/)")
    ap.add_argument("--plan", default="",
                    help="ScenarioMatrix JSON document (see README "
                         "'Scenario matrix' for the schema)")
    ap.add_argument("--serve", action="store_true",
                    help="expand --plan and run it through the resident "
                         "fleet with the closed Pareto loop")
    ap.add_argument("--expand", action="store_true",
                    help="print the expanded cell set of --plan and exit")
    ap.add_argument("--recover", default="",
                    help="rebuild a killed matrix fleet from this outdir "
                         "(matrix.json + write-ahead journal) and "
                         "continue it")
    ap.add_argument("--status", default="",
                    help="read-only matrix progress from this outdir")
    ap.add_argument("--pareto", default="",
                    help="one-shot fold: re-emit PARETO_<tag>.json from "
                         "this outdir's recorded state")
    ap.add_argument("--outdir", default="scenario_out",
                    help="fleet artifact root for --serve")
    ap.add_argument("--no-prune", action="store_true",
                    help="disable the closed-loop quota revocation "
                         "(measure the FULL cross-product; the artifact "
                         "still folds every --pareto-every ticks)")
    ap.add_argument("--pareto-every", type=int, default=4,
                    help="fleet ticks between Pareto folds (tick-"
                         "counted, never wall clock; default 4)")
    ap.add_argument("--certify", default="",
                    choices=("", "off", "warn", "strict"),
                    help="admission-time graftlint certification floor "
                         "applied to every cell's executables")
    ap.add_argument("--chaos-plan", default="",
                    help="fleet-level chaos plan JSON (survivability "
                         "drills)")
    ap.add_argument("--trace", action="store_true",
                    help="install the process-wide tracer (obs/)")
    ap.add_argument("--platform", default=None,
                    help="jax platform override (cpu/tpu/axon)")
    a = ap.parse_args(argv)

    if a.platform:
        import jax

        jax.config.update("jax_platforms", a.platform)
    if a.expand:
        if not a.plan:
            _log("--expand needs --plan")
            return 2
        return cmd_expand(a)
    if a.status:
        return cmd_status(a)
    if a.pareto:
        return cmd_pareto(a)
    if a.serve or a.recover:
        if a.serve and not (a.plan or a.recover):
            _log("--serve needs --plan")
            return 2
        return cmd_serve(a)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
