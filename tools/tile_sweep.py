"""On-chip Pallas tile sweep (VERDICT r3 #10).

Measures the taint fast pass at b_tile ∈ {256, 512, 1024, 2048} on the
real device, using the same flagship shape as bench.py (4096-µop window,
131072-trial batch, regfile tier), and reports trials/s per configuration
plus the XLA-kernel reference point.  One process, strictly sequential
device sessions, and an internal watchdog that *self-exits* rather than
being killed mid-compile (the axon relay wedge mechanism — see
.claude/skills/verify/SKILL.md).

Usage:  PYTHONPATH=/root/repo:$PYTHONPATH python tools/tile_sweep.py \
            [--batch N] [--uops N] [--reps N] [--out TILE_SWEEP.json]

Prints one JSON document at the end; writes it to --out too.
"""

import argparse
import json
import os
import statistics
import sys
import threading
import time

WATCHDOG_S = 2700.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=131072)
    ap.add_argument("--uops", type=int, default=4096)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tiles", type=str, default="256,512,1024,2048")
    ap.add_argument("--out", type=str, default="TILE_SWEEP.json")
    args = ap.parse_args()

    # self-exit watchdog: never leave this process to be SIGKILLed
    # mid-compile by an impatient caller
    def _watchdog():
        time.sleep(WATCHDOG_S)
        sys.stderr.write("tile_sweep: watchdog fired — self-exiting\n")
        os._exit(9)

    threading.Thread(target=_watchdog, daemon=True).start()

    import jax
    import numpy as np

    from shrewd_tpu import native
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    print(f"device: {dev} ({dev.platform})", file=sys.stderr, flush=True)

    trace = native.generate_trace(seed=1, n=args.uops, nphys=256,
                                  mem_words=4096,
                                  working_set_words=1024)
    keys = prng.trial_keys(prng.campaign_key(0), args.batch)

    doc = {"device": str(dev), "platform": dev.platform,
           "batch": args.batch, "uops": args.uops, "reps": args.reps,
           "configs": []}
    ref_tally = None
    # tile 0 = the XLA taint kernel (pallas off) — runs FIRST so it is the
    # tally reference every Pallas tile is checked against.  Entries are
    # TILE or TILE:U (U = pallas_u_steps unroll factor, default 1).
    def parse(spec):
        tile, _, u = spec.partition(":")
        return int(tile), int(u or 1)

    for tile, u in [(0, 1)] + [parse(t) for t in args.tiles.split(",")]:
        label = "xla" if tile == 0 else (
            f"b_tile={tile}" + (f",u={u}" if u != 1 else ""))
        try:
            cfg = O3Config(pallas="off") if tile == 0 else \
                O3Config(pallas="auto" if on_tpu else "on",
                         pallas_b_tile=tile, pallas_u_steps=u)
            kern = TrialKernel(trace, cfg)
            t0 = time.monotonic()
            tally = np.asarray(kern.run_keys(keys, "regfile"))
            compile_s = time.monotonic() - t0
            rates = []
            for _ in range(args.reps):
                t0 = time.monotonic()
                np.asarray(kern.run_keys(keys, "regfile"))
                rates.append(args.batch / (time.monotonic() - t0))
            entry = {"config": label,
                     "trials_per_sec": round(statistics.median(rates), 1),
                     "rate_min": round(min(rates), 1),
                     "rate_max": round(max(rates), 1),
                     "compile_plus_first_s": round(compile_s, 1),
                     "tally": tally.tolist()}
            if tile == 0:
                ref_tally = tally.tolist()
            entry["tally_matches_xla"] = (ref_tally is not None
                                          and tally.tolist() == ref_tally)
            doc["configs"].append(entry)
            print(json.dumps(entry), file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — record and continue sweep
            doc["configs"].append({"config": label,
                                   "error": f"{type(e).__name__}: "
                                            f"{str(e)[:300]}"})
            print(f"{label} FAILED: {e}", file=sys.stderr, flush=True)
        # incremental write: a watchdog self-exit mid-sweep must not
        # discard the configs already measured
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)

    ok = [c for c in doc["configs"]
          if "trials_per_sec" in c and c["config"] != "xla"]
    if ok:
        best = max(ok, key=lambda c: c["trials_per_sec"])
        doc["best"] = best["config"]
        doc["best_trials_per_sec"] = best["trials_per_sec"]
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
