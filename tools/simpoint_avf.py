"""SimPoint-weighted AVF, end to end on a real program (VERDICT r3 #4).

The reference methodology at SPEC scale: profile basic-block vectors over
the whole measured region, k-means to representative intervals, simulate
each representative, report the population-weighted metric
(/root/reference/src/cpu/simple/probes/simpoint.hh:82,
x86_spec/x86-spec-cpu2017.py).  Here: capture the marker window of a real
compression workload, select K representative intervals, emulate+lift
each (restore-then-rewarm, no checkpoint file), run a REGFILE campaign
per window on the replay kernel, and report the weighted AVF next to the
whole-window AVF it approximates (--whole-window lifts and campaigns the
full capture as the validation baseline).

Usage: python tools/simpoint_avf.py [--workload workloads/lzss_small.c]
           [--k 4] [--interval 4000] [--trials 2048] [--whole-window]
           [--seed 0] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="workloads/lzss_small.c")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--interval", type=int, default=4000)
    ap.add_argument("--trials", type=int, default=2048)
    ap.add_argument("--max-steps", type=int, default=2_000_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--whole-window", action="store_true",
                    help="also lift + campaign the FULL capture: the "
                         "baseline the weighted AVF approximates")
    ap.add_argument("--out", default=str(REPO / "SIMPOINT_AVF.json"))
    a = ap.parse_args()

    import numpy as np

    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.simpoint import simpoint_windows
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng

    t0 = time.time()
    paths = hd.build_tools(a.workload)
    windows, sps, profile = simpoint_windows(
        paths, interval=a.interval, k=a.k, max_steps=a.max_steps,
        seed=a.seed)
    out = {"workload": a.workload, "interval_macro_ops": a.interval,
           "seed": a.seed,
           "k_requested": a.k, "k_selected": len(windows),
           "n_intervals": int(len(sps.labels)),
           "trials_per_window": a.trials,
           "select_seconds": round(time.time() - t0, 1),
           "windows": []}
    root = prng.campaign_key(a.seed)
    weighted = 0.0
    for trace, meta in windows:
        t1 = time.time()
        k = TrialKernel(trace, O3Config())
        # full PRNG address: (seed, simpoint, structure, batch) — keeps
        # every window's samples independent and single-trial replayable
        keys = prng.trial_keys(prng.batch_key(prng.structure_key(
            prng.simpoint_key(root, meta["simpoint_interval"]), 0), 0),
            a.trials)
        tally = np.asarray(k.run_keys(keys, "regfile"))
        avf = float(C.avf(tally))
        weighted += meta["simpoint_weight"] * avf
        row = {"interval": meta["simpoint_interval"],
               "weight": round(meta["simpoint_weight"], 4),
               "uops": trace.n,
               "lift_rate": round(meta["stats"]["lift_rate"], 4),
               "avf": round(avf, 4),
               "tally": [int(x) for x in tally],
               "seconds": round(time.time() - t1, 1)}
        out["windows"].append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
    out["weighted_avf"] = round(weighted, 4)
    if a.whole_window:
        t1 = time.time()
        from shrewd_tpu.ingest.hostdiff import capture_and_lift
        trace, meta = capture_and_lift(paths, max_steps=a.max_steps)
        k = TrialKernel(trace, O3Config())
        keys = prng.trial_keys(prng.batch_key(prng.structure_key(
            prng.simpoint_key(root, 10**6), 0), 0), a.trials)
        tally = np.asarray(k.run_keys(keys, "regfile"))
        out["whole_window"] = {
            "uops": trace.n,
            "lift_rate": round(meta["stats"]["lift_rate"], 4),
            "avf": round(float(C.avf(tally)), 4),
            "tally": [int(x) for x in tally],
            "seconds": round(time.time() - t1, 1)}
        out["weighted_vs_whole_abs_err"] = round(
            abs(out["weighted_avf"] - out["whole_window"]["avf"]), 4)
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({"weighted_avf": out["weighted_avf"],
                      "k": len(windows)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
