"""Protection-search validation: analytic prediction vs a measured
protected campaign (VERDICT r4 weak #7).

``search/protect.py`` evaluates protection schemes *analytically* over
raw unprotected outcome distributions: a scheme with detection
probability ``d`` predicts ``sdc' = (1-d)·P(sdc | fault)``.  That
algebra assumes detection is independent of the trial's would-be
outcome.  The SHREWD shadow scheme violates independence in principle —
coverage is a *structural* function of the fault's µop (pool pressure
at its issue cycle), and SDC propensity is a *dataflow* function of the
same µop — so this tool measures the real thing:

  unprotected:  TrialKernel(enable_shrewd=False), ``fu`` faults
  protected:    TrialKernel(shadow_model="fupool"), same keys
  prediction:   the Scheme algebra with d = shadow_scheme(kernel).detect
                (mean availability-derated coverage) applied to the
                unprotected tally
  parity leg:   regfile + parity (detect=1) — predicted sdc' = 0; the
                measured analog reclassifies every consumed regfile
                fault as detected (a parity read check fires on first
                use), so the two must agree exactly.

Pass ⇔ measured protected SDC fraction lies inside the analytic
prediction ± the Wilson 95% CI of the measurement, for the shadow leg;
and the parity leg agrees identically.

Writes PROTECT_VALIDATE_r05.json.

Usage: python tools/protect_validate.py [--trials 8192]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def wilson(k: int, n: int, z: float = 1.96) -> tuple[float, float]:
    if n == 0:
        return (0.0, 1.0)
    p = k / n
    d = 1 + z * z / n
    c = (p + z * z / (2 * n)) / d
    h = z * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n)) / d
    return (max(0.0, c - h), min(1.0, c + h))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=8192)
    ap.add_argument("--workload", default="workloads/sort.c")
    ap.add_argument("--out", default=str(REPO / "PROTECT_VALIDATE_r05.json"))
    a = ap.parse_args()

    import numpy as np

    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.search.protect import Scheme, shadow_scheme
    from shrewd_tpu.utils import prng

    paths = hd.build_tools(a.workload)
    trace, meta = hd.capture_and_lift(paths)
    memmap = hd.memmap_from_meta(meta)
    keys = prng.trial_keys(prng.campaign_key(77), a.trials)

    # ---- shadow-FU leg (fu faults) --------------------------------------
    k_off = TrialKernel(trace, O3Config(enable_shrewd=False), memmap=memmap)
    t_off = np.asarray(k_off.run_keys(keys, "fu"), np.float64)
    k_on = TrialKernel(trace, O3Config(shadow_model="fupool"),
                       memmap=memmap)
    t_on = np.asarray(k_on.run_keys(keys, "fu"), np.float64)

    # conditioned detection estimated on an INDEPENDENT key set (out-of-
    # sample: the validation keys never feed the estimator)
    est_keys = prng.trial_keys(prng.campaign_key(78), a.trials)
    sch = shadow_scheme(k_on, keys=est_keys)
    sch_naive = shadow_scheme(k_on)
    n = t_off.sum()
    p_off = t_off / n
    pred = {
        "sdc": (1.0 - sch.d_sdc) * p_off[C.OUTCOME_SDC],
        "due": (1.0 - sch.d_due) * p_off[C.OUTCOME_DUE],
        "detected": sch.detect,      # E[cov] (unprotected never detects)
    }
    pred["masked"] = 1.0 - pred["sdc"] - pred["due"] - pred["detected"]
    pred_naive_sdc = (1.0 - sch_naive.detect) * p_off[C.OUTCOME_SDC]
    meas = {name: t_on[code] / n for name, code in
            (("masked", C.OUTCOME_MASKED), ("sdc", C.OUTCOME_SDC),
             ("due", C.OUTCOME_DUE), ("detected", C.OUTCOME_DETECTED))}
    ci = {name: wilson(int(t_on[code]), int(n)) for name, code in
          (("sdc", C.OUTCOME_SDC), ("detected", C.OUTCOME_DETECTED))}
    shadow_ok = (ci["sdc"][0] <= pred["sdc"] <= ci["sdc"][1]
                 and ci["detected"][0] <= pred["detected"]
                 <= ci["detected"][1])

    # ---- parity leg (regfile faults) ------------------------------------
    # parity (detect=1) intercepts every *consumed* fault at its first
    # read; faults that would be masked by overwrite/non-consumption stay
    # masked.  Prediction from the unprotected campaign: everything that
    # was NOT masked becomes detected; measured analog: reclassify the
    # unprotected per-trial outcomes the same way — exact agreement is
    # the test that the Scheme algebra's bookkeeping (not the kernel)
    # is consistent, since the kernel has no regfile-parity mechanism.
    t_rf = np.asarray(k_off.run_keys(keys, "regfile"), np.float64)
    parity = Scheme("parity", 1.0, 0.0, 1.0 + 1 / 32).validate()
    resid_p = 1.0 - parity.detect
    pred_parity_sdc = resid_p * (t_rf[C.OUTCOME_SDC] / n)
    out_rf = np.asarray(k_off.outcomes_from_keys(keys, "regfile"))
    meas_parity = np.where(out_rf == C.OUTCOME_MASKED,
                           C.OUTCOME_MASKED, C.OUTCOME_DETECTED)
    meas_parity_sdc = float((meas_parity == C.OUTCOME_SDC).sum()) / n
    parity_ok = abs(meas_parity_sdc - pred_parity_sdc) < 1e-12

    doc = {
        "workload": a.workload,
        "trials": a.trials,
        "window_uops": int(trace.n),
        "shadow_leg": {
            "scheme_detect": round(sch.detect, 4),
            "scheme_detect_sdc": round(sch.d_sdc, 4),
            "scheme_detect_due": round(sch.d_due, 4),
            "naive_uniform_predicted_sdc": round(float(pred_naive_sdc), 4),
            "note": "the uniform-mean model underpredicts SDC (coverage "
                    "anti-correlates with SDC-prone fault sites); the "
                    "outcome-conditioned estimator (unprotected campaign "
                    "+ coverage array, out-of-sample keys) is the "
                    "search-facing fix",
            "unprotected_tally": [int(x) for x in t_off],
            "protected_tally": [int(x) for x in t_on],
            "predicted": {k: round(v, 4) for k, v in pred.items()},
            "measured": {k: round(v, 4) for k, v in meas.items()},
            "measured_ci95": {k: [round(x, 4) for x in v]
                              for k, v in ci.items()},
            "sdc_within_ci": bool(ci["sdc"][0] <= pred["sdc"]
                                  <= ci["sdc"][1]),
            "detected_within_ci": bool(ci["detected"][0] <= pred["detected"]
                                       <= ci["detected"][1]),
            "pass": bool(shadow_ok),
        },
        "parity_leg": {
            "predicted_sdc": round(float(pred_parity_sdc), 4),
            "measured_sdc": round(meas_parity_sdc, 4),
            "pass": bool(parity_ok),
        },
        "pass": bool(shadow_ok and parity_ok),
    }
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({"pass": doc["pass"],
                      "shadow_pred_sdc": doc["shadow_leg"]["predicted"]["sdc"],
                      "shadow_meas_sdc": doc["shadow_leg"]["measured"]["sdc"],
                      "ci": doc["shadow_leg"]["measured_ci95"]["sdc"]}))
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
