"""Roofline analysis of the Pallas taint kernel (VERDICT r4 weak #5).

Quantifies which hardware bound the flagship kernel sits against, so
"N trials/s" stops being a bare number:

- **HBM traffic model** (analytic, from the committed shapes): per grid
  block of ``B_TILE`` lanes the kernel streams the golden record
  (15 per-step values, shared across lanes) from HBM once, plus the
  per-lane deviation-set outputs.  bytes/trial ≈ 15·n·4/B_TILE + out.
- **VPU work model**: per step each lane updates a k-deep deviation set
  (tag compare + select per slot) on (8,128) int32 tiles — ~`k · C_OPS`
  vector ops per lane-step.
- **measurement**: the committed-default kernel rate on the current
  device; achieved bytes/s and ops/s against the device peaks (v4 chip:
  ~1.2 TB/s HBM, ~4·10¹¹ int32 VPU lane-ops/s/core × 2 cores).

The binding bound and the achieved fraction go to ROOFLINE_r05.json.
On CPU the traffic/ops model still prints (the measurement is labeled
platform=cpu and is not a roofline claim).

Usage: python tools/roofline.py [--batch 131072] [--uops 4096]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# v4-chip peaks (public TPU v4 datasheet figures; per chip = 2 cores)
HBM_PEAK_GBS = 1200.0
VPU_PEAK_OPS = 8e11        # int32 lane-ops/s/chip (8x128 VPU, ~940 MHz, 2 cores, ~4 issue)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=131072)
    ap.add_argument("--uops", type=int, default=4096)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default=str(REPO / "ROOFLINE_r05.json"))
    a = ap.parse_args()

    import jax
    import numpy as np

    from shrewd_tpu import native
    from shrewd_tpu.models.o3 import PALLAS_S_CHUNK, O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    n = a.uops
    batch = a.batch if on_tpu else min(a.batch, 8192)

    cfg = O3Config()
    b_tile = int(getattr(cfg, "pallas_b_tile", 1024))
    k = int(getattr(cfg, "taint_k", 8))

    # ---- analytic models (committed shapes) -----------------------------
    n_blocks = max(batch // b_tile, 1)
    stream_bytes = 15 * n * 4                  # golden record per block
    out_bytes_per_lane = k * 8 + 16            # dev set (tag+val) + flags
    bytes_per_trial = (stream_bytes * n_blocks / batch
                       + out_bytes_per_lane)
    # per lane-step deviation-set update: tag compare, select, ALU lanes
    C_OPS = 12                                  # vector ops per slot-step
    ops_per_trial = n * k * C_OPS

    doc = {
        "platform": dev.platform,
        "window_uops": n, "batch": batch,
        "b_tile": b_tile, "taint_k": k, "s_chunk": int(PALLAS_S_CHUNK),
        "traffic_model": {
            "bytes_per_trial": round(bytes_per_trial, 1),
            "stream_bytes_per_block": stream_bytes,
            "note": "golden streams shared per block; deviation sets "
                    "live in VMEM for the whole window",
        },
        "compute_model": {
            "vpu_lane_ops_per_trial": ops_per_trial,
            "ops_per_slot_step": C_OPS,
        },
    }

    # ---- measurement ----------------------------------------------------
    trace = native.generate_trace(seed=1, n=n, nphys=256, mem_words=4096,
                                  working_set_words=1024)
    kernel = TrialKernel(trace, cfg)
    keys = prng.trial_keys(prng.campaign_key(0), batch)
    np.asarray(kernel.run_keys(keys, "regfile"))       # compile
    rates = []
    for _ in range(a.reps):
        t0 = time.monotonic()
        np.asarray(kernel.run_keys(keys, "regfile"))
        rates.append(batch / (time.monotonic() - t0))
    rates.sort()
    rate = rates[len(rates) // 2]
    doc["measured_trials_per_sec"] = round(rate, 1)

    if on_tpu:
        hbm = rate * bytes_per_trial
        vpu = rate * ops_per_trial
        doc["roofline"] = {
            "achieved_hbm_gbs": round(hbm / 1e9, 2),
            "hbm_peak_gbs": HBM_PEAK_GBS,
            "hbm_fraction": round(hbm / (HBM_PEAK_GBS * 1e9), 4),
            "achieved_vpu_ops": round(vpu / 1e9, 2),
            "vpu_peak_gops": VPU_PEAK_OPS / 1e9,
            "vpu_fraction": round(vpu / VPU_PEAK_OPS, 4),
            "binding_bound": ("vpu" if vpu / VPU_PEAK_OPS
                              > hbm / (HBM_PEAK_GBS * 1e9) else "hbm"),
        }
        bb = doc["roofline"]["binding_bound"]
        frac = doc["roofline"][f"{bb}_fraction"]
        doc["headroom_note"] = (
            f"binding bound {bb} at {frac:.1%} of peak — "
            + ("near-roofline; higher rates need algorithmic change "
               "(smaller k, shorter windows, chunked replay)"
               if frac > 0.5 else
               "headroom exists; the gap is lowering overheads "
               "(scalar-loop step dispatch, S_CHUNK re-reads), not the "
               "hardware bound"))
    else:
        doc["roofline"] = None
        doc["headroom_note"] = ("CPU measurement only — roofline claims "
                                "need the TPU (tunnel was wedged; rerun "
                                "on a healthy chip)")

    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({k: doc[k] for k in
                      ("platform", "measured_trials_per_sec",
                       "headroom_note")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
