#!/usr/bin/env python
"""graftlint — static determinism & replay-safety certification CLI.

Two layers (shrewd_tpu/analysis/):

- **AST lint** (always): repo-specific passes over ``shrewd_tpu/`` —
  exec-cache routing for jits (GL101), no wall clock in deterministic
  chaos/elastic regions (GL102), atomic checkpoint writes (GL103), PRNG
  key hygiene (GL104/GL105).  Rule scoping and severity come from the
  ``[tool.graftlint]`` block in ``pyproject.toml``; findings are waived
  in-source with ``# graftlint: allow-<rule> -- <reason>``.
- **jaxpr/HLO audit** (skippable with ``--no-jaxpr``): build the
  standard campaign executables (dense / hybrid / stratified per-batch
  steps + the pipelined interval steps) over a probe window and certify
  the replay-safety rules — frozen-key RNG lineage, no host callbacks,
  ONE device→host transfer per invocation, donation consistency — and
  prove the auditor has teeth by rejecting a seeded-violation fixture.

Exit status: 0 = clean (or only waived/baseline findings), 1 = new
violations (or a standard executable failed certification / the
violation fixture was NOT rejected), 2 = usage/environment error.

Usage::

    python tools/graftlint.py --strict --json LINT_r06.json   # the CI gate
    python tools/graftlint.py --no-jaxpr                      # fast, AST only
    python tools/graftlint.py --baseline LINT_r06.json        # only NEW
                                                              # violations fail
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _violation_key(v: dict) -> tuple:
    # path + rule + message identifies a finding across runs; LINE does
    # not participate (pre-existing findings must not become "new" when
    # unrelated edits shift them)
    return (v["path"], v["rule"], v["msg"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0])
    ap.add_argument("--strict", action="store_true",
                    help="record the strict posture in the JSON artifact "
                         "(violations always gate the exit code; "
                         "--baseline is the one escape hatch)")
    ap.add_argument("--baseline", default=None, metavar="LINT.json",
                    help="previous lint artifact: only violations NOT in "
                         "it are fatal (pre-existing findings report but "
                         "don't gate)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the machine-readable lint artifact "
                         "(the LINT_r06.json the CI gate records)")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr/HLO executable audit (fast "
                         "AST-only mode; no jax import)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (default: the checkout this script "
                         "lives in)")
    args = ap.parse_args(argv)

    from shrewd_tpu.analysis import lint_tree, load_config

    try:
        cfg = load_config(args.root)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    report = lint_tree(args.root, cfg)

    doc = {
        "tool": "graftlint",
        "strict": bool(args.strict),
        "transfer_budget": cfg.transfer_budget,
        **report.to_dict(),
    }

    certify_ok = True
    if not args.no_jaxpr:
        from shrewd_tpu.analysis.certify import certify_standard_executables

        cert_doc = certify_standard_executables(
            transfer_budget=cfg.transfer_budget)
        doc["executables"] = cert_doc
        certify_ok = cert_doc["ok"]

    new_violations = [f.to_dict() for f in report.violations]
    if args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as f:
            base = {_violation_key(v)
                    for v in json.load(f).get("violations", [])}
        new_violations = [v for v in new_violations
                         if _violation_key(v) not in base]
    doc["new_violations"] = new_violations
    doc["ok"] = certify_ok and not new_violations

    # --- human-readable report ---
    for f in report.violations:
        print(f"VIOLATION {f}")
    for f in report.warnings:
        print(f"warning   {f}")
    for f in report.waivers:
        print(f"waived    {f.path}:{f.line} {f.rule} -- {f.waiver_reason}")
    if not args.no_jaxpr:
        ex = doc["executables"]
        for name, c in sorted(ex["certificates"].items()):
            verdict = "certified" if c["ok"] else "REJECTED"
            print(f"executable {name}: {verdict} "
                  f"(transfers={c['transfers']}/"
                  f"{ex['transfer_budget']})")
        print("violation fixture: "
              + ("rejected (auditor has teeth)" if ex["fixture_rejected"]
                 else "NOT REJECTED — the auditor is blind"))
    n_v, n_w = len(report.violations), len(report.waivers)
    print(f"graftlint: {n_v} violation(s) "
          f"({len(new_violations)} new), {len(report.warnings)} "
          f"warning(s), {n_w} waiver(s)"
          + ("" if args.no_jaxpr else
             f", executables {'ok' if certify_ok else 'FAILED'}"))

    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {args.json}")

    # violations gate unconditionally; --baseline is the one escape hatch
    # (it already filtered new_violations above) and --strict only names
    # the posture in the artifact
    return 1 if (new_violations or not certify_ok) else 0


if __name__ == "__main__":
    sys.exit(main())
