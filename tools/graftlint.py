#!/usr/bin/env python
"""graftlint — static determinism & replay-safety certification CLI.

Two layers (shrewd_tpu/analysis/):

- **AST lint** (always): repo-specific passes over ``shrewd_tpu/`` —
  exec-cache routing for jits (GL101), no wall clock in deterministic
  chaos/elastic regions (GL102), atomic checkpoint writes (GL103), PRNG
  key hygiene (GL104/GL105).  Rule scoping and severity come from the
  ``[tool.graftlint]`` block in ``pyproject.toml``; findings are waived
  in-source with ``# graftlint: allow-<rule> -- <reason>``.
- **jaxpr/HLO audit** (skippable with ``--no-jaxpr``): build the
  standard campaign executables (dense / hybrid / stratified per-batch
  steps + the pipelined interval steps) over a probe window and certify
  the replay-safety rules — frozen-key RNG lineage, no host callbacks,
  ONE device→host transfer per invocation, donation consistency — and
  prove the auditor has teeth by rejecting a seeded-violation fixture.

The AST layer includes the GL2xx crash/replay-safety family
(``analysis/replay_lint.py``): journal-before-mutate dominance,
journal-record-kind exhaustiveness, fsync-before-rename ordering and
best-effort-seam guards.  On top:

- ``--audit-waivers`` additionally FAILS on stale waivers (GL205) —
  waiver comments whose rule no longer fires at that site — so the
  reasoned-waiver ledger cannot rot;
- ``--sarif OUT`` exports findings as SARIF 2.1.0 so CI renders them
  as annotations instead of log greps;
- ``--crashcheck`` runs the bounded dynamic model checker
  (``analysis/crashcheck.py``): a small real fleet under the
  instrumented VFS shim, then exhaustive ``recover()`` re-execution
  from EVERY durability boundary (+ torn-append variants), asserting
  bit-identical final tallies at each; ``--crash-json`` records the
  artifact (the ``CRASH_r11.json`` the CI gate pins).

Exit status: 0 = clean (or only waived/baseline findings), 1 = new
violations (or a standard executable failed certification / the
violation fixture was NOT rejected / stale waivers under
``--audit-waivers`` / a crash point failed under ``--crashcheck``),
2 = usage/environment error.

Usage::

    python tools/graftlint.py --strict --audit-waivers \
        --json LINT_r11.json --sarif LINT_r11.sarif       # the CI gate
    python tools/graftlint.py --no-jaxpr                  # fast, AST only
    python tools/graftlint.py --no-jaxpr --crashcheck \
        --crash-json CRASH_r11.json                       # the crash gate
    python tools/graftlint.py --baseline LINT_r11.json    # only NEW
                                                          # violations fail
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _violation_key(v: dict) -> tuple:
    # path + rule + message identifies a finding across runs; LINE does
    # not participate (pre-existing findings must not become "new" when
    # unrelated edits shift them)
    return (v["path"], v["rule"], v["msg"])


_SARIF_LEVELS = {"error": "error", "warn": "warning"}


def to_sarif(doc: dict) -> dict:
    """SARIF 2.1.0 over the lint artifact: violations (error), warnings
    (warning) and stale waivers (error) — waived findings stay out (they
    are ledger, not actionable)."""
    from shrewd_tpu.analysis import RULES

    results = []
    for group, level in (("violations", None), ("warnings", None),
                         ("stale_waivers", "error")):
        for v in doc.get(group, []):
            results.append({
                "ruleId": v["rule"],
                "level": level or _SARIF_LEVELS.get(
                    v.get("severity", "error"), "error"),
                "message": {"text": v["msg"]},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": v["path"]},
                    "region": {"startLine": max(1, int(v["line"]))}}}],
            })
    return {
        "version": "2.1.0",
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "shrewd_tpu/analysis/ (ast_lint + replay_lint)",
                "rules": [{"id": rid, "name": name,
                           "shortDescription": {"text": name}}
                          for rid, name in sorted(RULES.items())],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0])
    ap.add_argument("--strict", action="store_true",
                    help="record the strict posture in the JSON artifact "
                         "(violations always gate the exit code; "
                         "--baseline is the one escape hatch)")
    ap.add_argument("--baseline", default=None, metavar="LINT.json",
                    help="previous lint artifact: only violations NOT in "
                         "it are fatal (pre-existing findings report but "
                         "don't gate)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the machine-readable lint artifact "
                         "(the LINT_r06.json the CI gate records)")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr/HLO executable audit (fast "
                         "AST-only mode; no jax import)")
    ap.add_argument("--audit-waivers", action="store_true",
                    help="fail on STALE waivers (GL205): waiver "
                         "comments whose rule no longer fires at that "
                         "site — the reasoned-waiver ledger must not "
                         "rot")
    ap.add_argument("--sarif", default=None, metavar="OUT",
                    help="export findings as SARIF 2.1.0 (CI "
                         "annotations instead of log greps)")
    ap.add_argument("--crashcheck", action="store_true",
                    help="run the bounded dynamic crash-point model "
                         "checker (analysis/crashcheck.py): exhaustive "
                         "recover() re-execution from every durability "
                         "boundary of a small real fleet")
    ap.add_argument("--crash-json", default=None, metavar="OUT",
                    help="write the crashcheck artifact (CRASH_r11.json)")
    ap.add_argument("--gateway-crashcheck", action="store_true",
                    help="also sweep the federation GATEWAY's WAL "
                         "(analysis/crashcheck.py run_gateway_crashcheck)"
                         ": recover a 2-pod federation from every "
                         "gateway durability boundary — the "
                         "route-decision-vs-pod-handoff window must "
                         "replay, never double-place a tenant")
    ap.add_argument("--gateway-crash-json", default=None, metavar="OUT",
                    help="write the gateway sweep artifact")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (default: the checkout this script "
                         "lives in)")
    args = ap.parse_args(argv)

    from shrewd_tpu.analysis import lint_tree, load_config

    try:
        cfg = load_config(args.root)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    report = lint_tree(args.root, cfg)

    doc = {
        "tool": "graftlint",
        "strict": bool(args.strict),
        "transfer_budget": cfg.transfer_budget,
        **report.to_dict(),
    }

    certify_ok = True
    if not args.no_jaxpr:
        from shrewd_tpu.analysis.certify import certify_standard_executables

        cert_doc = certify_standard_executables(
            transfer_budget=cfg.transfer_budget)
        doc["executables"] = cert_doc
        certify_ok = cert_doc["ok"]

    crash_ok = True
    if args.crashcheck:
        import shutil
        import tempfile

        from shrewd_tpu.analysis.crashcheck import run_crashcheck

        workdir = tempfile.mkdtemp(prefix="crashcheck_")
        try:
            crash_doc = run_crashcheck(workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        crash_ok = crash_doc["ok"]
        doc["crashcheck"] = {k: crash_doc[k] for k in (
            "points", "checks", "torn_checks", "boundaries_by_event",
            "seq_monotonic", "ok")}
        if args.crash_json:
            with open(args.crash_json, "w") as f:
                json.dump(crash_doc, f, indent=1)
                f.write("\n")
            print(f"wrote {args.crash_json}")

    if args.gateway_crashcheck:
        import shutil
        import tempfile

        from shrewd_tpu.analysis.crashcheck import run_gateway_crashcheck

        workdir = tempfile.mkdtemp(prefix="gwcrash_")
        try:
            gw_doc = run_gateway_crashcheck(workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        crash_ok = crash_ok and gw_doc["ok"]
        doc["gateway_crashcheck"] = {k: gw_doc[k] for k in (
            "points", "checks", "torn_checks", "boundaries_by_event",
            "ok")}
        if args.gateway_crash_json:
            with open(args.gateway_crash_json, "w") as f:
                json.dump(gw_doc, f, indent=1)
                f.write("\n")
            print(f"wrote {args.gateway_crash_json}")

    new_violations = [f.to_dict() for f in report.violations]
    if args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as f:
            base = {_violation_key(v)
                    for v in json.load(f).get("violations", [])}
        new_violations = [v for v in new_violations
                         if _violation_key(v) not in base]
    stale_gate = bool(report.stale) and args.audit_waivers
    doc["new_violations"] = new_violations
    doc["ok"] = certify_ok and crash_ok and not new_violations \
        and not stale_gate

    # --- human-readable report ---
    for f in report.violations:
        print(f"VIOLATION {f}")
    for f in report.warnings:
        print(f"warning   {f}")
    for f in report.stale:
        tag = "STALE" if args.audit_waivers else "stale"
        print(f"{tag}     {f}")
    for f in report.waivers:
        print(f"waived    {f.path}:{f.line} {f.rule} -- {f.waiver_reason}")
    if not args.no_jaxpr:
        ex = doc["executables"]
        for name, c in sorted(ex["certificates"].items()):
            verdict = "certified" if c["ok"] else "REJECTED"
            print(f"executable {name}: {verdict} "
                  f"(transfers={c['transfers']}/"
                  f"{ex['transfer_budget']})")
        print("violation fixture: "
              + ("rejected (auditor has teeth)" if ex["fixture_rejected"]
                 else "NOT REJECTED — the auditor is blind"))
    if args.crashcheck:
        cc = doc["crashcheck"]
        print(f"crashcheck: {cc['checks']} recoveries from "
              f"{cc['points']} crash points ({cc['torn_checks']} torn) "
              f"-> {'bit-identical at every one' if cc['ok'] else 'FAILED'}")
    if args.gateway_crashcheck:
        gc = doc["gateway_crashcheck"]
        print(f"gateway crashcheck: {gc['checks']} federation "
              f"recoveries from {gc['points']} gateway boundaries "
              f"({gc['torn_checks']} torn) -> "
              + ("bit-identical, every tenant placed once"
                 if gc["ok"] else "FAILED"))
    n_v, n_w = len(report.violations), len(report.waivers)
    print(f"graftlint: {n_v} violation(s) "
          f"({len(new_violations)} new), {len(report.warnings)} "
          f"warning(s), {n_w} waiver(s), {len(report.stale)} stale"
          + ("" if args.no_jaxpr else
             f", executables {'ok' if certify_ok else 'FAILED'}"))

    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {args.json}")
    if args.sarif:
        with open(args.sarif, "w") as f:
            json.dump(to_sarif(doc), f, indent=1)
            f.write("\n")
        print(f"wrote {args.sarif}")

    # violations gate unconditionally; --baseline is the one escape hatch
    # (it already filtered new_violations above) and --strict only names
    # the posture in the artifact
    return 1 if (new_violations or not certify_ok or not crash_ok
                 or stale_gate) else 0


if __name__ == "__main__":
    sys.exit(main())
