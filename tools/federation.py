"""Federated fleet-of-fleets driver — the CLI over ``shrewd_tpu/federation/``.

Modes:

- **direct** — admit plan files through the gateway and serve the
  federation to convergence (the FED smoke / benchmark mode)::

      python tools/federation.py --plans a.json b.json c.json \\
          --outdir fed_out --pods 3

- **serve** — run the federation resident over the gateway spool;
  tenants arrive while it runs (``--submit`` below, or the HTTP front
  with ``--http PORT``)::

      python tools/federation.py --serve --outdir fed_out --pods 3

- **submit** — spool one tenant at the gateway from any process::

      python tools/federation.py --submit plan.json \\
          --outdir fed_out --name exp42 --slo 600

- **recover** — rebuild the whole tier after a hard kill of the driver
  process: the gateway replays its routing WAL (finishing any
  interrupted placement without double-placing), each pod replays its
  own WAL, and every tenant continues from its namespaced checkpoint
  bit-identically::

      python tools/federation.py --recover fed_out

- **status** — print the gateway's persisted routing ledger.

``--chaos-plan`` arms the federation-level chaos kinds (``kill_pod`` /
``partition_pod``) for reproducible survivability drills; pod-level
and campaign-level chaos ride the tenant plans as always.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pod_names(n: int) -> tuple:
    return tuple(f"pod{i}" for i in range(n))


def cmd_submit(a) -> int:
    from shrewd_tpu.service import SubmissionQueue, TenantSpec

    with open(a.submit) as f:
        plan = json.load(f)
    name = a.name or os.path.splitext(os.path.basename(a.submit))[0]
    ticket = SubmissionQueue(
        os.path.join(a.outdir, "gateway", "spool")).submit(TenantSpec(
            name=name, plan=plan, priority=a.priority, weight=a.weight,
            quota_batches=a.quota_batches, slo_s=a.slo))
    print(json.dumps({"ticket": ticket, "tenant": name}))
    return 0


def cmd_status(a) -> int:
    from shrewd_tpu.federation import gateway_snap_path
    from shrewd_tpu.resilience import load_json_verified

    snap = load_json_verified(gateway_snap_path(
        os.path.join(a.status, "gateway")))
    out = {"pods": snap.get("pods"), "dead_pods": snap.get("dead_pods"),
           "recoveries": snap.get("recoveries"),
           "tenants": {e["spec"]["name"]: {
               "status": e["status"], "pod": e["pod"],
               "epoch": e["epoch"], "deadline_s": e["deadline_s"],
               "slo_s": e["spec"].get("slo_s", 0.0)}
               for e in snap.get("entries", [])}}
    print(json.dumps(out, indent=1))
    return 0


def _report(fed) -> None:
    for name, e in sorted(fed.gateway.entries.items()):
        path = "->".join(h["pod"] for h in e.history) or "-"
        _log(f"  {name}: {e.status} on {e.pod or '-'} "
             f"(epoch {e.epoch}, path {path})")
    _log(f"federation: {json.dumps(fed.counters())}")


def cmd_run(a) -> int:
    from shrewd_tpu.federation import Federation, GatewayHTTPFront
    from shrewd_tpu.service import LockHeld, ServerLock, TenantSpec

    if a.trace:
        from shrewd_tpu.obs import trace as obs_trace

        obs_trace.enable(ring=a.trace_ring or obs_trace.DEFAULT_RING)
    chaos = None
    if a.chaos_plan:
        from shrewd_tpu.chaos import ChaosEngine

        chaos = ChaosEngine.from_path(a.chaos_plan, worker="federation")
    lock = ServerLock(a.recover or a.outdir)
    try:
        lock.acquire()
    except LockHeld as e:
        _log(f"another driver owns this federation: {e}")
        return 2
    front = None
    try:
        kw = dict(chaos=chaos, quantum=a.quantum,
                  expiry_rounds=a.expiry_rounds,
                  rebalance_every=a.rebalance_every,
                  idle_exit=not a.serve)
        if a.certify:
            kw["certify"] = a.certify
        if a.recover:
            fed = Federation.recover(a.recover,
                                     pod_names=_pod_names(a.pods), **kw)
            _log(f"recovered federation: gateway recoveries "
                 f"{fed.gateway.recoveries}, dead pods "
                 f"{sorted(fed.gateway.dead_pods)}")
        else:
            fed = Federation(a.outdir, pod_names=_pod_names(a.pods),
                             **kw)
        for path in a.plans or ():
            with open(path) as f:
                plan = json.load(f)
            name = f"t{len(fed.gateway.entries)}_" \
                   f"{os.path.splitext(os.path.basename(path))[0]}"
            doc = fed.submit(TenantSpec(name=name, plan=plan,
                                        slo_s=a.slo))
            _log(f"admitted {name} -> {doc['pod']} "
                 f"(deadline ~{doc['deadline_s']}s, "
                 f"eta {doc['eta_trials']} trials)")
        if a.http is not None:
            front = GatewayHTTPFront(
                os.path.join(fed.root, "gateway"), port=a.http).start()
            _log(f"http front on 127.0.0.1:{front.port}")
        rc = fed.serve()
        _report(fed)
        return rc
    finally:
        if front is not None:
            front.stop()
        lock.release()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="federated fleet-of-fleets driver "
                    "(shrewd_tpu/federation/)")
    ap.add_argument("--outdir", default="fed_out",
                    help="federation root (gateway/ + pods/ + coord/)")
    ap.add_argument("--pods", type=int, default=3,
                    help="number of scheduler pods (default 3)")
    ap.add_argument("--plans", nargs="+", default=None,
                    help="plan JSONs to admit directly (direct mode)")
    ap.add_argument("--serve", action="store_true",
                    help="serve resident over the gateway spool")
    ap.add_argument("--submit", default=None, metavar="PLAN",
                    help="spool one tenant at the gateway and exit")
    ap.add_argument("--recover", default=None, metavar="DIR",
                    help="recover a federation after a hard kill and "
                         "continue serving")
    ap.add_argument("--status", default=None, metavar="DIR",
                    help="print the gateway's routing ledger and exit")
    ap.add_argument("--name", default=None, help="tenant name (--submit)")
    ap.add_argument("--priority", type=int, default=0)
    ap.add_argument("--weight", type=float, default=1.0)
    ap.add_argument("--quota-batches", type=int, default=0)
    ap.add_argument("--slo", type=float, default=0.0,
                    help="completion SLO in seconds (advisory; the "
                         "admission doc reports feasibility against "
                         "the deadline estimate)")
    ap.add_argument("--quantum", type=int, default=1,
                    help="scheduler steps per pod per federation round")
    ap.add_argument("--expiry-rounds", type=int, default=3,
                    help="supervisor polls without a heartbeat before "
                         "a pod's lease expires")
    ap.add_argument("--rebalance-every", type=int, default=0,
                    help="rounds between ETA-runaway rebalancing "
                         "checks (0 = off)")
    ap.add_argument("--certify", default="",
                    choices=["", "off", "warn", "strict"],
                    help="admission-time certification floor applied "
                         "by every pod")
    ap.add_argument("--chaos-plan", default=None,
                    help="federation-level chaos plan JSON "
                         "(kill_pod / partition_pod)")
    ap.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="also serve the HTTP front (0 = ephemeral)")
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--trace-ring", type=int, default=0)
    a = ap.parse_args(argv)

    if a.submit:
        return cmd_submit(a)
    if a.status:
        return cmd_status(a)
    if not (a.plans or a.serve or a.recover):
        ap.error("one of --plans / --serve / --submit / --recover / "
                 "--status is required")
    return cmd_run(a)


if __name__ == "__main__":
    sys.exit(main())
