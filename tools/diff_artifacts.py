"""Round-artifact driver for the host-silicon differential campaigns.

Runs ``ingest.hostdiff.run_diff`` over a workload list in one process and
writes the aggregate artifact the judge reads (DIFF_AVF_WORKLOADS_r{N},
DIFF_AVF_64BIT_r{N}, big-window DIFFs).  Every per-workload failure is
recorded instead of aborting the sweep.

Usage:
    python tools/diff_artifacts.py --mode device64 --trials 200 \
        --out DIFF_AVF_64BIT_r04.json
    python tools/diff_artifacts.py --mode output --trials 500 \
        --out DIFF_AVF_WORKLOADS_r04.json
    python tools/diff_artifacts.py --mode output --trials 300 \
        --workloads workloads/lzss_small.c --out DIFF_AVF_BIGWIN_r04.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

DEFAULT_WORKLOADS = [
    "workloads/sort.c", "workloads/intmm.c", "workloads/divmix.c",
    "workloads/bytehash.c", "workloads/memops.c", "workloads/ptrchase.c",
    "workloads/rotmix.c", "workloads/strmix.c",
]

KEEP = ("trials", "host_avf", "device_avf", "avf_abs_err",
        "agreement_exact", "agreement_vulnerable", "cis_overlap",
        "device_diverged", "resync_severed", "escalated_total",
        "diverged_resolved",
        "diverged_resolution_failed", "window_macro_ops_sampled")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="output")
    ap.add_argument("--trials", type=int, default=200)
    ap.add_argument("--seed", type=int, default=9)
    ap.add_argument("--workloads", nargs="*", default=DEFAULT_WORKLOADS)
    ap.add_argument("--max-steps", type=int, default=2_000_000,
                    help="ptrace capture budget (raise for full-length "
                         "windows, e.g. the 2.1M-macro lzss capture)")
    ap.add_argument("--out", required=True)
    a = ap.parse_args()

    from shrewd_tpu.ingest.hostdiff import run_diff

    out = {"mode": a.mode, "trials_per_workload": a.trials, "seed": a.seed,
           "bit_range": 64 if a.mode in ("emu64", "device64") else 32,
           "workloads": {}}
    for wl in a.workloads:
        t0 = time.time()
        try:
            import jax
            jax.clear_caches()     # bound XLA-CPU compile-state growth
            rep = run_diff(a.trials, a.seed, wl, mode=a.mode,
                           max_steps=a.max_steps)
            row = {k: rep[k] for k in KEEP if k in rep}
            if "lift_stats" in rep:
                row["lift_rate"] = round(rep["lift_stats"]["lift_rate"], 4)
                row["uops"] = rep["lift_stats"]["uops"]
        except Exception as e:  # noqa: BLE001 — record, keep sweeping
            row = {"error": f"{type(e).__name__}: {e}"[:300]}
        row["seconds"] = round(time.time() - t0, 1)
        out["workloads"][wl] = row
        print(f"{wl}: {json.dumps(row)[:200]}", file=sys.stderr, flush=True)
    ok = [w for w in out["workloads"].values() if "agreement_exact" in w]
    if ok:
        out["summary"] = {
            "workloads_ok": len(ok),
            "min_agreement_exact": min(w["agreement_exact"] for w in ok),
            "min_agreement_vulnerable": min(w["agreement_vulnerable"]
                                            for w in ok),
            "max_avf_abs_err": max(w["avf_abs_err"] for w in ok),
        }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out.get("summary", {})))
    return 0


if __name__ == "__main__":
    sys.exit(main())
