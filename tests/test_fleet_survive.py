"""Fleet survivability (shrewd_tpu/service/): write-ahead journal,
hard-kill recovery, poison-tenant quarantine, service-level chaos.

The contract under test is the ISSUE acceptance criterion: a fleet of
3+ tenants killed HARD mid-tick (``kill_fleet`` chaos on a
deterministic schedule — the in-process stand-in raises ``FleetKilled``
through the same ``kill_action`` seam whose default is ``os._exit``)
recovers with ``CampaignScheduler.recover()`` and every tenant's final
tallies are bit-identical to its undisturbed solo serial run; a seeded
poison tenant exhausts its tick-counted retry budget, lands in durable
``quarantined`` status with its exception ledger persisted, and the
other tenants' results and fair-share ordering are unaffected.  Around
that: journal append/replay/torn-tail units, compaction, dirty-shutdown
detection, the per-tenant tick watchdog, the bad-submission spool, and
the single-server lock.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from test_fleet import _assert_tenant_matches, _plan, _solo_tallies

from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.chaos import ChaosEngine, ChaosPlanError
from shrewd_tpu.resilience import load_json_verified
from shrewd_tpu.service import journal as journal_mod
from shrewd_tpu.service import (CampaignScheduler, FleetJournal,
                                FleetKilled, LockHeld, ServerLock,
                                SubmissionQueue, TenantSpec, is_dirty,
                                journal_path)


def _raising_kill(eng):
    """The test-side kill seam: a 'hard death' that the pytest process
    survives (the CI smoke exercises the real os._exit default in a
    subprocess)."""
    def _k(rc):
        raise FleetKilled(rc)

    eng.kill_action = _k
    return eng


# --- journal units (jax-free) -----------------------------------------------

def test_journal_append_replay_and_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = FleetJournal(path)
    for i in range(5):
        assert j.append("tick", {"tenant": "t", "i": i}) == i
    j.close()
    recs, torn, valid = FleetJournal.replay_path(path)
    assert [r["seq"] for r in recs] == list(range(5)) and torn == 0
    assert valid == os.path.getsize(path)
    # a SIGKILL mid-append leaves a partial last line: replay drops ONLY
    # the torn record, everything acknowledged before it survives
    os.truncate(path, os.path.getsize(path) - 5)
    recs, torn, _ = FleetJournal.replay_path(path)
    assert [r["seq"] for r in recs] == list(range(4)) and torn == 1
    # reopen truncates the untrusted bytes and seq stays monotonic —
    # appends never land behind garbage
    j2 = FleetJournal(path)
    assert j2.torn_dropped == 1
    assert j2.append("tick", {"i": 9}) == 4
    j2.close()
    recs, torn, _ = FleetJournal.replay_path(path)
    assert [r["seq"] for r in recs] == list(range(5)) and torn == 0
    # a corrupted record invalidates itself and everything after it
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"X")
    recs, torn, _ = FleetJournal.replay_path(path)
    assert recs == [] and torn == 1


def test_journal_seq_floor_spans_compaction(tmp_path):
    # after compaction the file is empty but seq must continue from the
    # snapshot floor, or replay would skip fresh records as already
    # snapshotted
    path = str(tmp_path / "j.jsonl")
    j = FleetJournal(path)
    for i in range(3):
        j.append("tick", {"i": i})
    j.compact()
    assert os.path.getsize(path) == 0 and j.compactions == 1
    assert j.append("tick", {"i": 3}) == 3
    j.close()
    j2 = FleetJournal(path, next_seq=7)    # a floor beyond the file wins
    assert j2.next_seq == 7
    j2.close()


def test_journal_nondict_line_reads_as_torn(tmp_path):
    # corruption can leave a line that parses as non-object JSON; it is
    # torn, not a crash in the recovery path
    path = str(tmp_path / "j.jsonl")
    j = FleetJournal(path)
    j.append("tick", {})
    j.close()
    with open(path, "a") as f:
        f.write("[1, 2, 3]\n")
    recs, torn, _ = FleetJournal.replay_path(path)
    assert len(recs) == 1 and torn == 1


def test_fleet_chaos_plan_validation():
    # the service-level kinds carry their own trigger vocabulary
    ChaosEngine({"faults": [{"kind": "kill_fleet", "at_tick": 3}]})
    ChaosEngine({"faults": [{"kind": "kill_fleet", "at_journal": [1, 2]}]})
    ChaosEngine({"faults": [{"kind": "torn_journal", "at_journal": 0}]})
    ChaosEngine({"faults": [{"kind": "corrupt_submission",
                             "at_submission": 0}]})
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "kill_fleet", "at_batch": 0}]})
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "torn_journal", "at_tick": 0}]})


# --- hard-kill recovery (the acceptance criterion) --------------------------

def test_hard_kill_mid_fleet_recovers_bit_identical(tmp_path):
    # 3 tenants, killed hard at fleet tick 5 — no drain, no fleet
    # checkpoint call; the WAL and the per-tenant checkpoints (one
    # tenant checkpoints per batch, the others not at all) are the only
    # survivors.  recover() must finish all three bit-identical to
    # their solo serial runs.
    plans = {"a": _plan(3, ckpt_every=1), "b": _plan(5, n_batches=4),
             "c": _plan(7, n_batches=3)}
    solos = {n: _solo_tallies(p) for n, p in plans.items()}
    eng = _raising_kill(ChaosEngine(
        {"faults": [{"kind": "kill_fleet", "at_tick": 5}]},
        worker="fleet"))
    sched = CampaignScheduler(outdir=str(tmp_path), chaos=eng)
    for n, p in plans.items():
        sched.admit(TenantSpec(name=n, plan=p.to_dict()))
    with pytest.raises(FleetKilled):
        sched.run()
    assert eng.injected == {"kill_fleet": 1}
    # the WAL holds the fleet's whole life up to the kill
    recs, torn, _ = FleetJournal.replay_path(journal_path(str(tmp_path)))
    kinds = [r["kind"] for r in recs]
    assert kinds.count("admit") == 3 and "tick" in kinds and torn == 0
    assert is_dirty(str(tmp_path))
    rec = CampaignScheduler.recover(str(tmp_path))
    assert rec.recoveries == 1
    assert not is_dirty(str(tmp_path))     # recovery folded the journal
    # replay restored the fair-share ledgers, not just the roster
    assert sum(t.trials for t in rec.tenants.values()) == \
        sum(t.trials for t in sched.tenants.values())
    assert rec.run() == 0
    assert rec._by_status() == {"complete": 3}
    for n in plans:
        _assert_tenant_matches(rec, n, solos[n])


def test_kill_fleet_at_journal_ordinal_recovers(tmp_path):
    # the mid-tick boundary: the kill lands right after journal record 4
    # becomes durable (between a tenant's tick and its bookkeeping)
    solo = _solo_tallies(_plan(3, ckpt_every=1))
    eng = _raising_kill(ChaosEngine(
        {"faults": [{"kind": "kill_fleet", "at_journal": 4}]},
        worker="fleet"))
    sched = CampaignScheduler(outdir=str(tmp_path), chaos=eng)
    sched.admit(TenantSpec(name="t", plan=_plan(3,
                                                ckpt_every=1).to_dict()))
    with pytest.raises(FleetKilled):
        sched.run()
    rec = CampaignScheduler.recover(str(tmp_path))
    assert rec.recoveries == 1 and rec.run() == 0
    _assert_tenant_matches(rec, "t", solo)


def test_torn_journal_tail_recovers_bit_identical(tmp_path):
    # power loss mid-append: record 6 persists only a prefix and the
    # process dies; replay drops the torn tail, loses nothing
    # acknowledged before it, and the fleet still finishes bit-identical
    solo = _solo_tallies(_plan(3, ckpt_every=1))
    eng = _raising_kill(ChaosEngine(
        {"faults": [{"kind": "torn_journal", "at_journal": 6}]},
        worker="fleet"))
    sched = CampaignScheduler(outdir=str(tmp_path), chaos=eng)
    sched.admit(TenantSpec(name="t", plan=_plan(3,
                                                ckpt_every=1).to_dict()))
    with pytest.raises(FleetKilled):
        sched.run()
    assert eng.injected == {"torn_journal": 1}
    recs, torn, _ = FleetJournal.replay_path(journal_path(str(tmp_path)))
    assert torn == 1 and all(r["seq"] < 6 for r in recs)
    with pytest.raises(ValueError, match="dirty"):
        CampaignScheduler.resume(str(tmp_path))
    rec = CampaignScheduler.recover(str(tmp_path))
    assert rec.journal_torn == 1 and rec.recoveries == 1
    assert rec.run() == 0
    _assert_tenant_matches(rec, "t", solo)


def test_journal_compaction_and_clean_shutdown(tmp_path):
    # a tiny compact_every folds the journal into the snapshot mid-run;
    # a clean shutdown leaves an EMPTY journal behind a current snapshot
    sched = CampaignScheduler(outdir=str(tmp_path), compact_every=3)
    sched.admit(TenantSpec(name="a", plan=_plan(3, n_batches=3).to_dict()))
    assert sched.run() == 0
    assert sched._journal is not None and sched._journal.compactions >= 2
    assert not is_dirty(str(tmp_path))
    recs, torn, _ = FleetJournal.replay_path(journal_path(str(tmp_path)))
    assert recs == [] and torn == 0
    snap = load_json_verified(
        os.path.join(str(tmp_path), "fleet_ckpt", "fleet.json"))
    assert snap["version"] == 2 and snap["journal_seq"] >= 3
    assert snap["recoveries"] == 0


# --- poison-tenant quarantine -----------------------------------------------

def test_poison_tenant_quarantined_backoff_and_fairness(tmp_path):
    # the poison tenant's plan raises at every elaboration (missing
    # trace file): it must retry on an exponential TICK-counted backoff,
    # land in durable quarantine with its ledger persisted, and leave
    # the good tenants' results AND fair-share ordering untouched
    from shrewd_tpu.campaign.plan import CampaignPlan, TraceFileSpec

    good = {"g1": _plan(3), "g2": _plan(5, n_batches=4)}
    solos = {n: _solo_tallies(p) for n, p in good.items()}
    clean = CampaignScheduler()
    for n, p in good.items():
        clean.admit(TenantSpec(name=n, plan=p.to_dict()))
    assert clean.run() == 0

    poison = CampaignPlan(simpoints=[TraceFileSpec(
        name="w0", path=str(tmp_path / "missing.npz"))],
        structures=["regfile"], batch_size=32, max_trials=64,
        min_trials=64)
    sched = CampaignScheduler(outdir=str(tmp_path), retry_budget=3,
                              backoff_ticks=1)
    sched.admit(TenantSpec(name="poison", plan=poison.to_dict()))
    for n, p in good.items():
        sched.admit(TenantSpec(name=n, plan=p.to_dict()))
    assert sched.run() == 0
    t = sched.tenants["poison"]
    assert t.status == "quarantined"
    assert t.failures == 4                  # initial try + 3 retries
    # exponential, tick-counted: gap k >= backoff_ticks * 2**(k-1)
    ticks = [e["tick"] for e in t.errors]
    gaps = np.diff(ticks)
    assert all(g >= 1 * 2 ** k for k, g in enumerate(gaps))
    # durable evidence: journal saw the quarantine, the namespace holds
    # the ledger, the snapshot carries the status
    qdoc = load_json_verified(os.path.join(
        str(tmp_path), "tenants", "poison", "quarantine.json"))
    assert qdoc["failures"] == 4 and len(qdoc["errors"]) == 4
    snap = load_json_verified(
        os.path.join(str(tmp_path), "fleet_ckpt", "fleet.json"))
    st = {d["spec"]["name"]: d["status"] for d in snap["tenants"]}
    assert st["poison"] == "quarantined"
    # the goods never noticed: bit-identical, and their relative
    # fair-share ordering matches the poison-free fleet exactly — the
    # poison tenant's doomed ticks never perturb the goods' stride
    # order, and once quarantined it cannot burn a share at all
    assert [n for n in sched.schedule_log
            if n != "poison"] == clean.schedule_log
    for n in good:
        _assert_tenant_matches(sched, n, solos[n])


def test_quarantine_is_durable_across_recover(tmp_path):
    # a quarantined tenant must NOT be retried by recover()/resume():
    # quarantine is terminal until an operator resubmits
    from shrewd_tpu.campaign.plan import CampaignPlan, TraceFileSpec

    poison = CampaignPlan(simpoints=[TraceFileSpec(
        name="w0", path=str(tmp_path / "missing.npz"))],
        structures=["regfile"], batch_size=32, max_trials=64,
        min_trials=64)
    sched = CampaignScheduler(outdir=str(tmp_path), retry_budget=0)
    sched.admit(TenantSpec(name="poison", plan=poison.to_dict()))
    assert sched.run() == 0
    assert sched.tenants["poison"].status == "quarantined"
    rec = CampaignScheduler.recover(str(tmp_path))
    assert rec.tenants["poison"].status == "quarantined"
    assert rec.tenants["poison"].failures == 1
    assert rec.run() == 0                 # nothing to do, nothing retried
    assert rec.tenants["poison"].status == "quarantined"


def test_tick_watchdog_preempts_livelocked_tenant():
    # a livelocked tick (host loop that never returns) is abandoned at
    # the DeviceWatchdog deadline and the tenant takes the quarantine
    # path — the scheduler loop itself never wedges
    sched = CampaignScheduler(tick_timeout=0.3, retry_budget=0)
    sched.admit(TenantSpec(name="live", plan=_plan(3,
                                                   n_batches=2).to_dict()))
    [t] = sched._candidates()

    class Wedged:
        done = False
        results = None
        rc = 0

        def tick(self):
            time.sleep(5)

        def request_drain(self):
            pass

    t.driver = Wedged()
    t0 = time.monotonic()
    assert sched.run() == 0
    assert time.monotonic() - t0 < 4      # preempted, not waited out
    assert t.status == "quarantined"
    assert "DispatchTimeout" in t.errors[0]["error"]


def test_recover_republishes_lost_done_doc(tmp_path):
    # a kill landing between the terminal journal record and mark_done
    # must not leave the submitter's ticket claimed (and unanswered)
    # forever: recover treats the replayed state as authoritative and
    # publishes the done-doc
    q = SubmissionQueue(str(tmp_path / "spool"))
    ticket = q.submit(TenantSpec(name="t",
                                 plan=_plan(3, n_batches=2).to_dict()))
    sched = CampaignScheduler(outdir=str(tmp_path / "out"), queue=q)
    assert sched.run() == 0
    assert q.done(ticket)["status"] == "complete"
    # simulate the lost mark_done (the journal recorded the completion,
    # the spool never heard about it)
    os.unlink(os.path.join(q.done_dir, ticket))
    open(os.path.join(q.claimed_dir, ticket), "w").close()
    CampaignScheduler.recover(str(tmp_path / "out"), queue=q)
    done = q.done(ticket)
    assert done["status"] == "complete" and done["results"]
    assert not os.path.exists(os.path.join(q.claimed_dir, ticket))


# --- service-level chaos: corrupt submissions -------------------------------

def test_corrupt_submission_chaos_routes_to_bad_spool(tmp_path):
    # the chaos kind corrupts the scheduled pending doc in place
    # (parses, checksum fails); the claim path quarantines it to bad/
    # with a reason doc and the fleet keeps serving
    q = SubmissionQueue(str(tmp_path / "spool"))
    ticket = q.submit(TenantSpec(name="late",
                                 plan=_plan(13, n_batches=2).to_dict()))
    eng = ChaosEngine({"faults": [{"kind": "corrupt_submission",
                                   "at_submission": 0}]}, worker="fleet")
    good_solo = _solo_tallies(_plan(3, n_batches=2))
    sched = CampaignScheduler(queue=q, chaos=eng)
    sched.admit(TenantSpec(name="good", plan=_plan(3,
                                                   n_batches=2).to_dict()))
    assert sched.run() == 0
    assert eng.injected == {"corrupt_submission": 1}
    assert "late" not in sched.tenants
    assert q.bad_count() == 1 and q.pending() == []
    reason = load_json_verified(
        os.path.join(q.bad_dir, ticket + ".reason"))
    assert "checksum" in reason["error"]
    _assert_tenant_matches(sched, "good", good_solo)


def test_bad_checksum_submission_goes_to_bad_spool(tmp_path):
    # queue-level unit, no chaos: a complete document whose checksum
    # fails (bit-rot) moves to bad/; a document that does not PARSE
    # stays pending (the in-flight signature of the atomic submit)
    q = SubmissionQueue(str(tmp_path / "spool"))
    t1 = q.submit(TenantSpec(name="ok", plan={"seed": 1}))
    t2 = q.submit(TenantSpec(name="rot", plan={"seed": 2}))
    doc = json.load(open(os.path.join(q.pending_dir, t2)))
    doc["checksum"] = "0" * 64
    with open(os.path.join(q.pending_dir, t2), "w") as f:
        json.dump(doc, f)
    (tmp_path / "spool" / "pending" / "000099_torn.json").write_text(
        "{\"name\": \"to")
    claimed = q.claim()
    assert [tk for tk, _ in claimed] == [t1]
    assert q.bad_count() == 1
    assert os.path.exists(os.path.join(q.bad_dir, t2))
    assert os.path.exists(os.path.join(q.bad_dir, t2 + ".reason"))
    # the torn one is still pending, never quarantined
    assert q.pending() == ["000099_torn.json"]
    # a valid-JSON document the spec validator rejects is also poison
    t3 = q.submit(TenantSpec(name="w", plan={"seed": 3}))
    doc = json.load(open(os.path.join(q.pending_dir, t3)))
    del doc["name"], doc["checksum"]
    with open(os.path.join(q.pending_dir, t3), "w") as f:
        json.dump(doc, f)
    assert q.claim() == [] and q.bad_count() == 2


# --- single-server guard ----------------------------------------------------

def test_server_lock_excl_and_stale_takeover(tmp_path):
    root = str(tmp_path / "spool")
    lock = ServerLock(root).acquire()
    with pytest.raises(LockHeld, match="held by live pid"):
        ServerLock(root).acquire()
    lock.release()
    with ServerLock(root):                 # re-acquirable after release
        pass
    # stale lock: the recorded pid is dead (the previous server was
    # SIGKILLed) — reaped and re-raced, no human rm needed
    proc = subprocess.run([sys.executable, "-c",
                           "import os; print(os.getpid())"],
                          capture_output=True, text=True, check=True)
    dead = int(proc.stdout.strip())
    with open(os.path.join(root, "server.lock"), "w") as f:
        f.write(f"{dead}\n")
    l3 = ServerLock(root).acquire()
    assert l3._holder() == os.getpid()
    l3.release()
    # unreadable content (torn pid write) is stale too
    with open(os.path.join(root, "server.lock"), "w") as f:
        f.write("not-a-pid")
    ServerLock(root).acquire().release()


# --- drain racing admission-time certification ------------------------------

def test_drain_during_admission_certification(tmp_path, monkeypatch):
    # a drain signal landing while the certify floor is elaborating a
    # tenant must not leave a half-admitted tenant in fleet.json or the
    # journal: the tenant is either fully resumable or absent
    solo = _solo_tallies(_plan(3))
    sched = CampaignScheduler(outdir=str(tmp_path), certify="warn")
    sched.admit(TenantSpec(name="t", plan=_plan(3).to_dict()))
    from shrewd_tpu.campaign import orchestrator as omod

    real_init = omod.Orchestrator.__init__

    def init_with_signal(self, *a, **kw):
        sched.request_drain()            # SIGTERM arrives mid-admission
        return real_init(self, *a, **kw)

    monkeypatch.setattr(omod.Orchestrator, "__init__", init_with_signal)
    assert sched.run() == 4 and sched.preempted
    snap = load_json_verified(
        os.path.join(str(tmp_path), "fleet_ckpt", "fleet.json"))
    tds = [d for d in snap["tenants"] if d["spec"]["name"] == "t"]
    assert len(tds) == 1                  # exactly one admission record
    assert tds[0]["status"] == "preempted"
    TenantSpec.from_dict(tds[0]["spec"])  # the spec round-trips whole
    assert not is_dirty(str(tmp_path))
    monkeypatch.setattr(omod.Orchestrator, "__init__", real_init)
    resumed = CampaignScheduler.resume(str(tmp_path))
    assert resumed.run() == 0
    # the certify floor still holds on the resumed tenant
    assert resumed.tenants["t"].orch.plan.analysis.certify == "warn"
    _assert_tenant_matches(resumed, "t", solo)


# --- the WAL contract: journal BEFORE mutate (GL201, dynamically) -----------

def _append_raising_on(monkeypatch, kind):
    """Patch FleetJournal.append to die INSIDE the append of one record
    kind — the tightest crash window the journal-before-mutate ordering
    must survive: the decision is either durable or unmade, never
    half-applied in memory."""
    real = journal_mod.FleetJournal.append

    def boom(self, k, data=None):
        if k == kind:
            raise RuntimeError(f"kill inside append({k!r})")
        return real(self, k, data)

    monkeypatch.setattr(journal_mod.FleetJournal, "append", boom)
    return real


def test_revoke_journals_before_any_mutation(tmp_path, monkeypatch):
    sched = CampaignScheduler(outdir=str(tmp_path))
    sched.admit(TenantSpec(name="t", plan=_plan(3,
                                                n_batches=1).to_dict()))
    t = sched.tenants["t"]
    real = _append_raising_on(monkeypatch, "revoke")
    with pytest.raises(RuntimeError, match="inside append"):
        sched.revoke_quota("t", "pareto:rival")
    # the kill landed inside the append: the in-memory decision is
    # UNMADE (journal-first), so nothing disagrees with the journal
    assert t.revoked == "" and t.status == "queued"
    # and the seam still works once the journal is healthy again
    monkeypatch.setattr(journal_mod.FleetJournal, "append", real)
    assert sched.revoke_quota("t", "pareto:rival") is True
    assert t.revoked == "pareto:rival" and t.status == "pruned"


def test_admit_journals_before_roster_insert(tmp_path, monkeypatch):
    sched = CampaignScheduler(outdir=str(tmp_path))
    _append_raising_on(monkeypatch, "admit")
    with pytest.raises(RuntimeError, match="inside append"):
        sched.admit(TenantSpec(name="t", plan=_plan(3).to_dict()))
    assert "t" not in sched.tenants


def test_note_failure_journals_before_ledger(tmp_path, monkeypatch):
    sched = CampaignScheduler(outdir=str(tmp_path), retry_budget=3)
    sched.admit(TenantSpec(name="t", plan=_plan(3).to_dict()))
    t = sched.tenants["t"]
    _append_raising_on(monkeypatch, "failure")
    with pytest.raises(RuntimeError, match="inside append"):
        sched._note_failure(t, ValueError("boom"))
    assert t.failures == 0 and t.errors == [] and t.retry_at == 0


def test_quarantine_journals_before_ledger(tmp_path, monkeypatch):
    sched = CampaignScheduler(outdir=str(tmp_path), retry_budget=0)
    sched.admit(TenantSpec(name="t", plan=_plan(3).to_dict()))
    t = sched.tenants["t"]
    _append_raising_on(monkeypatch, "quarantine")
    with pytest.raises(RuntimeError, match="inside append"):
        sched._note_failure(t, ValueError("boom"))
    assert t.status == "queued" and t.failures == 0 and t.results is None


# --- crashcheck: exhaustive crash-point model checking ----------------------

def test_tear_journal_tail_semantics(tmp_path):
    # the torn-write model: the last record loses its tail mid-line,
    # replay drops ONLY it, and an empty/absent journal refuses to tear
    outdir = str(tmp_path)
    path = journal_path(outdir)
    assert crashcheck.tear_journal_tail(outdir) is False    # no journal
    j = FleetJournal(path)
    for i in range(3):
        j.append("tick", {"i": i})
    j.close()
    assert crashcheck.tear_journal_tail(outdir) is True
    recs, torn, _ = FleetJournal.replay_path(path)
    assert [r["seq"] for r in recs] == [0, 1] and torn == 1
    # an already-torn tail cannot tear again
    assert crashcheck.tear_journal_tail(outdir) is False


def test_snapshot_tree_scrubs_non_durable(tmp_path):
    src = tmp_path / "src"
    (src / "fleet_ckpt").mkdir(parents=True)
    (src / "fleet_ckpt" / "fleet.json").write_text("{}")
    (src / "metrics.json").write_text("{}")
    (src / "fleet_stats.json").write_text("{}")
    (src / "fleet_ckpt" / "fleet.json.tmp").write_text("{")
    dst = str(tmp_path / "dst")
    crashcheck.snapshot_tree(str(src), dst)
    kept = sorted(os.path.relpath(os.path.join(r, f), dst)
                  for r, _d, fs in os.walk(dst) for f in fs)
    # durable state survives; unsynced observability and tmp legs do not
    assert kept == [os.path.join("fleet_ckpt", "fleet.json")]


def _record_points(tmp_path, tag):
    plans = crashcheck.small_fleet_plans(seeds=(3,), n_batches=1)
    rec_dir = str(tmp_path / f"rec{tag}")
    pts_dir = str(tmp_path / f"pts{tag}")
    os.makedirs(pts_dir)
    with crashcheck.DurabilityRecorder(rec_dir, pts_dir) as rec:
        _sched, rc = crashcheck._run_fleet(rec_dir, plans)
    assert rc == 0
    return rec.points


def test_crash_point_enumeration_is_deterministic(tmp_path):
    # two identical fleets must expose the identical crash surface:
    # same boundaries, same order, same journal seqs — crashcheck's
    # exhaustiveness claim rests on this
    a = [pt.label() for pt in _record_points(tmp_path, "a")]
    b = [pt.label() for pt in _record_points(tmp_path, "b")]
    assert a == b
    assert any(pt["event"] == "append" for pt in a)
    assert any(pt["event"] == "rename" for pt in a)


def test_crashcheck_catches_divergence(tmp_path):
    # negative control: the checker must FAIL when recovery does not
    # reproduce the reference tallies (here: a corrupted reference)
    plans = crashcheck.small_fleet_plans(seeds=(3,), n_batches=1)
    points = _record_points(tmp_path, "neg")
    base_sched, rc = crashcheck._run_fleet(str(tmp_path / "base"), plans)
    assert rc == 0
    baseline = crashcheck._tallies(base_sched)
    for lanes in baseline.values():
        for k in lanes:
            lanes[k] = lanes[k] + 1          # nobody can reach this
    res = crashcheck.check_point(points[-1], str(tmp_path / "chk"),
                                 plans, baseline)
    assert res["ok"] is False and res["identical"] is False


def test_crashcheck_three_tenant_fleet_exhaustive(tmp_path):
    # the acceptance pin: EVERY durability boundary of a 3-tenant fleet
    # (plus a torn-tail variant of every journal append) recovers to
    # bit-identical final tallies with journal seqs never regressing —
    # the single-kill-point chaos smoke generalized to the whole crash
    # surface
    plans = crashcheck.small_fleet_plans(seeds=(3, 5, 7), n_batches=1)
    doc = crashcheck.run_crashcheck(str(tmp_path), plans=plans)
    assert doc["ok"], doc["failures"][:3]
    assert doc["failures"] == [] and doc["seq_monotonic"]
    assert doc["points"] >= 15 and doc["torn_checks"] >= 8
    assert set(doc["boundaries_by_event"]) >= {"append", "rename"}
    assert sorted(doc["tenants"]) == ["t0", "t1", "t2"]
    assert doc["points_dropped"] == 0


# --- observability ----------------------------------------------------------

def test_survivability_stats_in_fleet_dump(tmp_path):
    eng = _raising_kill(ChaosEngine(
        {"faults": [{"kind": "kill_fleet", "at_tick": 3}]},
        worker="fleet"))
    sched = CampaignScheduler(outdir=str(tmp_path), chaos=eng)
    sched.admit(TenantSpec(name="a", plan=_plan(3, n_batches=3).to_dict()))
    with pytest.raises(FleetKilled):
        sched.run()
    rec = CampaignScheduler.recover(str(tmp_path))
    assert rec.run() == 0
    with open(os.path.join(str(tmp_path), "fleet_stats.json")) as f:
        doc = json.load(f)
    fleet = doc["fleet"]
    assert fleet["recoveries"] == 1
    assert fleet["quarantined"] == 0
    assert fleet["journal_records"] > 0
    assert fleet["journal_compactions"] >= 1
    assert fleet["journal_torn_dropped"] == 0
    assert fleet["submissions_bad"] == 0
    assert fleet["tenants_by_status"] == {"complete": 1}
