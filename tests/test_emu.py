"""Snapshot-seeded emulation + lifted checkpoint restore (ingest/emu.py,
warm.window_from_snapshot_lifted).

The strongest check pins the emulator's step stream bit-for-bit against a
REAL ptrace capture of the same window: two independent executions of the
same program (host silicon vs emulator) must produce identical per-step
register files.  The checkpoint round-trip then proves the full
restore-then-rewarm path: capture → m5.cpt (+config.json sidecar) →
restore → emulate forward → lift → golden replay, with the lifted golden
matching the emulator's final state.  Reference:
restore-then-rewarm (``/root/reference/src/cpu/o3/cpu.cc:706-799``),
CheckerCPU lockstep oracle (``/root/reference/src/cpu/checker/cpu.hh``).
"""

import shutil

import numpy as np
import pytest

from shrewd_tpu.ingest import hostdiff as hd
from shrewd_tpu.ingest.cpt import (load_arch_snapshot, snapshot_from_capture,
                                   write_arch_snapshot)
from shrewd_tpu.ingest.emu import emulate_window
from shrewd_tpu.ingest.lift import read_nativetrace
from shrewd_tpu.ingest.warm import window_from_snapshot_lifted

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("objdump") is None,
    reason="host toolchain required")


@pytest.fixture(scope="module")
def capture(tmp_path_factory):
    """A real ptrace capture of sort.c's marker window."""
    import subprocess

    paths = hd.build_tools()
    bd = tmp_path_factory.mktemp("emu")
    trace_bin = bd / "sort_win.bin"
    subprocess.run([str(paths.tracer), str(trace_bin), f"{paths.begin:x}",
                    f"{paths.end:x}", "2000000", str(paths.workload)],
                   check=True, capture_output=True, text=True)
    return paths, read_nativetrace(trace_bin)


def test_emulator_matches_host_capture(capture):
    """Emulator seeded from the capture's initial state reproduces the
    host CPU's per-step register stream exactly."""
    paths, nt = capture
    n = len(nt.steps) - 1
    res = emulate_window(str(paths.workload), nt.steps[0][:16],
                         [(v, d) for v, d in nt.regions],
                         int(nt.steps[0][16]), max_steps=n)
    assert res.steps == n, res.stop_reason
    # regs + pc columns must match the silicon bit-for-bit, every step
    assert np.array_equal(res.nt.steps[:, :17], nt.steps[:, :17])


def test_checkpoint_roundtrip_lifted_window(capture, tmp_path):
    """capture → m5.cpt → restore → emulate+lift → clean golden replay
    whose final registers equal the emulator's."""
    import jax

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    paths, nt = capture
    cpt_dir = tmp_path / "cpt"
    write_arch_snapshot(str(cpt_dir), snapshot_from_capture(nt))
    snap = load_arch_snapshot(str(cpt_dir))
    assert snap.regions, "config.json sidecar must carry region vaddrs"
    assert snap.pc == int(nt.steps[0][16])
    assert np.array_equal(snap.int_regs[:16], nt.steps[0][:16])

    n = len(nt.steps) - 1
    trace, meta = window_from_snapshot_lifted(
        snap, str(paths.workload), max_steps=n)
    assert meta["emu_steps"] == n
    assert meta["stats"]["lift_rate"] >= 0.95

    k = TrialKernel(trace, O3Config(enable_shrewd=False))
    g = k.golden
    assert not bool(g.diverged) and not bool(g.trapped)
    # golden replay final regs == capture's final regs (32-bit projection)
    exp = nt.steps[n][:16].astype(np.uint64) & np.uint64(0xFFFFFFFF)
    got = np.asarray(g.reg)[:16]
    assert np.array_equal(got, exp.astype(np.uint32))


def test_checkpoint_spec_builds_lifted_trace(capture, tmp_path):
    """CheckpointSpec(binary=...) end-to-end through the campaign config."""
    from shrewd_tpu.campaign.plan import CheckpointSpec

    paths, nt = capture
    cpt_dir = tmp_path / "cpt2"
    write_arch_snapshot(str(cpt_dir), snapshot_from_capture(nt))
    spec = CheckpointSpec(cpt_dir=str(cpt_dir), binary=str(paths.workload),
                          max_steps=500)
    trace = spec.build_trace()
    assert trace.opcode.shape[0] > 0
    trace.validate()


class TestSuffixStems:
    """Size-suffix stripping must take at most ONE suffix char and only
    with a known remainder (review r3: rstrip ate stem letters — "subl" →
    "su", "roll" → "ro", "imulq" → "imu" — demoting those forms to the
    unsupported-mnemonic path)."""

    def test_stem_strips_one_known_suffix(self):
        from shrewd_tpu.ingest.emu import _ALU, _SHIFT, _stem

        assert _stem("subl", _ALU) == "sub"
        assert _stem("subb", _ALU) == "sub"
        assert _stem("imulq", _ALU) == "imul"
        assert _stem("roll", _SHIFT) == "rol"
        assert _stem("shlb", _SHIFT) == "shl"
        assert _stem("sall", _SHIFT) == "sal"

    def test_stem_never_eats_stem_letters(self):
        from shrewd_tpu.ingest.emu import _ALU, _SHIFT, _stem

        assert _stem("shl", _SHIFT) == "shl"      # bare stem untouched
        assert _stem("sub", _ALU) == "sub"
        assert _stem("su", _ALU) is None
        assert _stem("xyzzy", _ALU) is None

    def test_gs_relative_stops_loudly(self):
        """%gs: must not silently resolve against fs_base."""
        from shrewd_tpu.ingest.emu import Emulator, StopEmu
        from shrewd_tpu.ingest.lift import Operand, _parse_operand

        op = _parse_operand("%gs:0x28", None)
        assert op.base == -5
        fs = _parse_operand("%fs:0x28", None)
        assert fs.base == -4
        import numpy as np

        emu = Emulator({}, np.zeros(18, np.uint64), [], pc=0)
        with pytest.raises(StopEmu, match="gs-relative"):
            emu.ea(op)
        # fs still resolves (synthetic fallback base)
        assert emu.ea(fs) == emu.fs_base + 0x28


def test_fs_register_indirect_segment_override():
    """'%fs:(%rax)'-style register-indirect TLS operands parse with the
    seg override and resolve against fs_base (review r3: they previously
    fell to base=-3 'unparsed' and killed whole-program emulation in
    glibc's TLS-heavy paths)."""
    import numpy as np

    from shrewd_tpu.ingest.emu import Emulator
    from shrewd_tpu.ingest.lift import _parse_operand

    op = _parse_operand("%fs:(%rax)", None)
    assert op.kind == "mem" and op.base == 0 and op.seg == "fs"
    op2 = _parse_operand("%fs:0x10(,%rbx,8)", None)
    assert op2.seg == "fs" and op2.index == 3 and op2.scale == 8 \
        and op2.disp == 0x10
    regs = np.zeros(18, np.uint64)
    regs[0] = 0x40                         # rax
    emu = Emulator({}, regs, [], pc=0)
    assert emu.ea(op) == emu.fs_base + 0x40
    gs = _parse_operand("%gs:(%rax)", None)
    assert gs.seg == "gs"
    from shrewd_tpu.ingest.emu import StopEmu
    with pytest.raises(StopEmu, match="gs-relative"):
        emu.ea(gs)


class TestSimdSubset:
    """glibc str/mem SIMD vocabulary (xmm/ymm/zmm + AVX-512 masks +
    rep-string): enough to run __strlen_evex / __memset_evex /
    __memcpy_avx_unaligned_erms whole-program (workloads/strmix.c)."""

    def _emu(self):
        import numpy as np

        from shrewd_tpu.ingest.emu import Emulator

        return Emulator({}, np.zeros(18, np.uint64), [], pc=0)

    def _op(self, kind, **kw):
        from shrewd_tpu.ingest.lift import Operand

        return Operand(kind, **kw)

    def test_parse_simd_registers(self):
        from shrewd_tpu.ingest.lift import _parse_operand

        assert _parse_operand("%xmm3", None).width == 128
        assert _parse_operand("%ymm19", None).width == 256
        assert _parse_operand("%zmm31", None).width == 512
        k = _parse_operand("%k5", None)
        assert k.kind == "kreg" and k.reg == 5

    def test_pcmpeqb_and_movemask(self):
        e = self._emu()
        e.xmm[0] = int.from_bytes(b"abczefgzijkzmnoz", "little")
        e.xmm[1] = int.from_bytes(b"z" * 16, "little")
        x0 = self._op("xmm", reg=0, width=128)
        x1 = self._op("xmm", reg=1, width=128)
        x2 = self._op("xmm", reg=2, width=128)
        e.xmm[2] = e.xmm[0]
        e._simd("pcmpeqb", [x1, x2])
        gpr = self._op("reg", reg=0, width=32)
        e._simd("pmovmskb", [x2, gpr])
        assert e.reg[0] == 0b1000100010001000

    def test_evex_compare_into_mask_and_kmov(self):
        e = self._emu()
        e.xmm[16] = 0                              # vpxor zero
        e.xmm[17] = int.from_bytes(b"ab\0cdefg" + b"\0" * 24, "little")
        k0 = self._op("kreg", reg=0)
        e._simd("vpcmpeqb", [self._op("xmm", reg=17, width=256),
                             self._op("xmm", reg=16, width=256), k0])
        gpr = self._op("reg", reg=0, width=32)
        e._simd("kmovd", [k0, gpr])
        expected = (1 << 2) | (0xFFFFFFFF & ~((1 << 8) - 1))
        assert e.reg[0] == expected                # NULs at 2 and 8..31

    def test_rep_movsb_and_stosb(self):
        import numpy as np

        from shrewd_tpu.ingest.emu import RAX, RCX, RDI, RSI, Emulator, Region
        from shrewd_tpu.ingest.lift import Inst

        e = Emulator({}, np.zeros(18, np.uint64), [(0x1000, bytes(64))],
                     pc=0)
        for i, b in enumerate(b"hello!"):
            e.store(0x1000 + i, 1, b)
        e.reg[RSI], e.reg[RDI], e.reg[RCX] = 0x1000, 0x1010, 6
        e.insts[0] = Inst(0, 2, "rep movsb", [], None)
        # ONE iteration per step(), pc held until rcx==0 — the ptrace
        # single-step contract (a trap fires per rep iteration), which
        # keeps fault-coordinate step counts aligned with hostsfi and
        # the capture.  Whole-rep-per-step desynced every later coord.
        for i in range(6):
            assert e.pc == 0
            e.step()
            assert e.reg[RCX] == 5 - i
        assert bytes(e.load(0x1010 + i, 1) for i in range(6)) == b"hello!"
        assert e.pc == 2                    # advanced on the last iteration
        e.pc = 0
        e.insts[0] = Inst(0, 2, "rep stos",
                          [self._op("reg", reg=RAX, width=8)], None)
        e.reg[RAX], e.reg[RDI], e.reg[RCX] = ord("x"), 0x1020, 5
        for _ in range(5):
            e.step()
        assert bytes(e.load(0x1020 + i, 1) for i in range(5)) == b"xxxxx"
        assert e.reg[RCX] == 0 and e.pc == 2
        # rcx == 0 at entry: no-op, pc advances in one step
        e.pc = 0
        e.reg[RDI], e.reg[RCX] = 0x1030, 0
        e.step()
        assert e.pc == 2 and e.load(0x1030, 1) == 0

    def test_bsf_tzcnt(self):
        import numpy as np

        from shrewd_tpu.ingest.emu import Emulator
        from shrewd_tpu.ingest.lift import Inst

        e = Emulator({}, np.zeros(18, np.uint64), [], pc=0)
        src = self._op("reg", reg=1, width=64)
        dst = self._op("reg", reg=0, width=64)
        e.reg[1] = 0b101000
        e.insts[0] = Inst(0, 3, "bsf", [src, dst], None)
        e.step()
        assert e.reg[0] == 3
        e.pc = 0
        e.reg[1] = 0
        e.insts[0] = Inst(0, 3, "tzcnt", [src, dst], None)
        e.step()
        assert e.reg[0] == 64                      # defined-at-zero

    def test_strmix_emu64_runs_to_exit(self):
        """Whole-program golden emulation of the libc-string workload
        reaches clean exit with the same stdout as the real host run."""
        import subprocess

        from shrewd_tpu.ingest import hostdiff as hd

        paths = hd.build_tools("workloads/strmix.c")
        real = subprocess.run([str(paths.workload)], capture_output=True)
        coords = hd.sample_coords(1, 10, 0, bit_range=64)
        res = hd.run_device_emu64(paths, coords)
        assert res is not None                     # golden ran to exit 0

    def test_evex_zmm_logical_writes_full_512(self):
        """vpxord zmm,zmm,zmm self-zero must clear ALL 512 bits — a
        256-bit write would leave stale bits 256-511 (glibc evex strlen
        uses zmm vpminub/vpxor, so truncation skews host-diff silently)."""
        e = self._emu()
        e.xmm[5] = (1 << 511) | (1 << 300) | 0xDEAD
        z5 = self._op("xmm", reg=5, width=512)
        e._simd("vpxord", [z5, z5, z5])
        assert e.xmm[5] == 0
        # and vpminub at zmm width covers the full register too
        e.xmm[6] = (0xFF << 504) | 0x01
        e.xmm[7] = (0x02 << 504) | 0x05
        z6 = self._op("xmm", reg=6, width=512)
        z7 = self._op("xmm", reg=7, width=512)
        e._simd("vpminub", [z6, z7, z7])
        assert e.xmm[7] == (0x02 << 504) | 0x01

    def test_vex128_zeroes_through_maxvl(self):
        """The AVX-512 zeroing idiom `vpxor %xmm0,%xmm0,%xmm0` clears the
        whole zmm (VEX/EVEX writes zero through MAXVL, bit 511) — zeroing
        only to 255 would leave stale zmm bits for a later vpcmpb."""
        e = self._emu()
        e.xmm[3] = (0xAB << 500) | (0xCD << 128) | 0xF0
        x3 = self._op("xmm", reg=3, width=128)
        e._simd("vpxor", [x3, x3, x3])
        assert e.xmm[3] == 0
        # and a VEX.128 move zeroes 128..511 as well
        e.xmm[4] = 1 << 300
        e.xmm[5] = 0x42
        e._simd("vmovdqu", [self._op("xmm", reg=5, width=128),
                            self._op("xmm", reg=4, width=128)])
        assert e.xmm[4] == 0x42

    def test_vpcmpb_unsupported_predicate_stops_loudly(self):
        from shrewd_tpu.ingest.emu import StopEmu

        e = self._emu()
        k0 = self._op("kreg", reg=0)
        x0 = self._op("xmm", reg=0, width=128)
        x1 = self._op("xmm", reg=1, width=128)
        for imm in (1, 2, 5, 6):                   # LT/LE/NLT/NLE
            with pytest.raises(StopEmu):
                e._simd("vpcmpb", [self._op("imm", imm=imm), x0, x1, k0])

    def test_tzcnt_zf_tracks_result_not_source(self):
        """TZCNT ZF=1 iff result==0 (bit 0 set); BSF-style ZF=(src==0)
        would invert the branch after `tzcnt; je`."""
        import numpy as np

        from shrewd_tpu.ingest.emu import Emulator
        from shrewd_tpu.ingest.lift import Inst

        e = Emulator({}, np.zeros(18, np.uint64), [], pc=0)
        src = self._op("reg", reg=1, width=64)
        dst = self._op("reg", reg=0, width=64)
        e.reg[1] = 0b1                             # result 0 → ZF set
        e.insts[0] = Inst(0, 3, "tzcnt", [src, dst], None)
        e.step()
        assert e.reg[0] == 0 and e.cond("e")
        e.pc = 0
        e.reg[1] = 0b1000                          # result 3 → ZF clear
        e.step()
        assert e.reg[0] == 3 and not e.cond("e")
        e.pc = 0
        e.reg[1] = 0                               # result 64 → ZF clear
        e.step()
        assert e.reg[0] == 64 and not e.cond("e")
        # bsf keeps source-tracking ZF: src==0 → ZF set
        e.pc = 0
        e.insts[0] = Inst(0, 3, "bsf", [src, dst], None)
        e.step()
        assert e.cond("e")
