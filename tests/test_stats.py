import io
import json

import numpy as np
import pytest

from shrewd_tpu import stats
from shrewd_tpu.stats import (Distribution, Formula, Group, Histogram, Scalar,
                              Vector)


def make_group():
    g = Group("campaign")
    g.trials = Scalar("trials", "trials run")
    g.outcomes = Vector("outcomes", 4, "per-class outcome tallies",
                        subnames=["masked", "sdc", "due", "detected"])
    g.avf = Formula("avf", lambda: (g.outcomes[1] + g.outcomes[2]) /
                    max(g.trials.value, 1), "architectural vulnerability factor")
    o3 = Group("o3")
    o3.lat = Distribution("inject_cycle", 0, 100, 10, "fault cycle distribution")
    g.o3 = o3
    return g


def test_scalar_vector():
    g = make_group()
    g.trials += 1000
    g.outcomes += np.array([900, 50, 40, 10])
    assert g.trials.value == 1000
    assert g.outcomes[0] == 900
    assert g.outcomes[1] == 50
    assert g.outcomes.total() == 1000
    assert g.avf.to_value() == pytest.approx(0.09)
    with pytest.raises(ValueError):
        g.outcomes += np.zeros(3)


def test_distribution_moments():
    d = Distribution("d", 0, 10, 10)
    vals = np.array([1.0, 2.0, 3.0, 15.0, -1.0])
    d.sample(vals)
    assert d.samples == 5
    assert d.overflow == 1 and d.underflow == 1
    assert d.mean() == pytest.approx(vals.mean())
    assert d.stdev() == pytest.approx(vals.std(ddof=1))
    assert d.counts[1] == 1 and d.counts[2] == 1 and d.counts[3] == 1


def test_histogram_autorange():
    h = Histogram("h", 8)
    h.sample(np.arange(8))
    assert h.hi == 8
    h.sample([100.0])
    assert h.hi >= 101 or h.overflow == 0
    assert h.samples == 9
    # all original samples still counted after merging
    assert h.counts.sum() == 9


def test_distribution_edge_bucket():
    # value just below hi must not index out of bounds
    d = Distribution("d", 0, 3.3, 3)
    d.sample([np.nextafter(3.3, 0)])
    assert d.counts[2] == 1 and d.overflow == 0


def test_histogram_nonfinite_rejected():
    h = Histogram("h", 8)
    with pytest.raises(ValueError):
        h.sample([float("inf")])


def test_histogram_reset_restores_range():
    h = Histogram("h", 8)
    h.sample([1e6])
    assert h.hi > 1e6
    h.reset()
    assert h.hi == 8 and h.bucket_size == 1.0


def test_group_rebind_drops_old():
    g = Group("g")
    g.x = Scalar("old")
    g.x = Scalar("new")
    names = [n for n, _, _ in g.rows()]
    assert names == ["g.new"]


def test_group_duplicate_name_rejected():
    g = Group("g")
    g.a = Scalar("x")
    with pytest.raises(ValueError):
        g.b = Scalar("x")
    # the surviving registration is untouched
    assert [n for n, _, _ in g.rows()] == ["g.x"]


def test_group_rejected_rebind_keeps_registry_intact():
    g = Group("g")
    g.a = Scalar("x")
    g.b = Scalar("y")
    with pytest.raises(ValueError):
        g.b = Scalar("x")        # clashes with g.a's name
    # g.b's original stat must still be registered and dumpable
    assert sorted(n for n, _, _ in g.rows()) == ["g.x", "g.y"]
    # renaming an attribute to a stat with the SAME name is fine
    g.a = Scalar("x", "replacement")
    assert sorted(n for n, _, _ in g.rows()) == ["g.x", "g.y"]


def test_distribution_weights():
    d = Distribution("d", 0, 10, 10)
    d.sample([1.0, 2.0], weights=2.0)           # scalar broadcast
    assert d.samples == 4
    with pytest.raises(ValueError):
        d.sample([1.0, 2.0], weights=[1.0, 2.0, 3.0])


def test_histogram_negative_rejected():
    h = Histogram("h", 8)
    with pytest.raises(ValueError):
        h.sample([-1.0])


def test_format_count_tera():
    from shrewd_tpu.utils import units
    assert units.format_count(1e12) == "1T"
    assert units.format_count(2.5e13) == "25T"


def test_reset():
    g = make_group()
    g.trials += 5
    g.o3.lat.sample([1.0])
    g.reset()
    assert g.trials.value == 0
    assert g.o3.lat.samples == 0


def test_text_dump_format():
    g = make_group()
    g.trials += 10
    g.outcomes += np.array([9, 1, 0, 0])
    buf = io.StringIO()
    text = stats.dump_text(g, buf)
    assert buf.getvalue() == text
    assert "Begin Simulation Statistics" in text
    assert "campaign.trials" in text
    assert "campaign.outcomes::masked" in text
    assert "campaign.outcomes::total" in text
    assert "campaign.avf" in text
    assert "campaign.o3.inject_cycle::samples" in text
    # value column parses back
    line = [l for l in text.splitlines() if l.startswith("campaign.trials")][0]
    assert int(line.split()[1]) == 10


def test_json_dump():
    g = make_group()
    g.trials += 4
    d = json.loads(stats.dump_json(g))
    assert d["trials"] == 4
    assert d["outcomes"]["total"] == 0
    assert "inject_cycle" in d["o3"]


def test_json_dump_serializes_non_finite_as_null():
    """A Distribution with zero samples has mean()/stdev() = NaN and
    min/max = ±inf; json.dumps' non-strict default would emit bare
    NaN/Infinity tokens that strict parsers reject — they must land as
    null (regression: stats.json from any fresh campaign group)."""
    from shrewd_tpu.stats import Distribution, Formula, Group

    g = Group("c")
    g.lat = Distribution("lat", 0, 10, 5, "empty distribution")
    g.bad = Formula("bad", lambda: float("inf"), "derived inf")
    text = stats.dump_json(g)
    d = json.loads(text, parse_constant=lambda s: pytest.fail(
        f"non-strict JSON token {s!r} leaked into stats.json"))
    assert d["lat"]["mean"] is None
    assert d["lat"]["min"] is None and d["lat"]["max"] is None
    assert d["bad"] is None
    assert d["lat"]["samples"] == 0          # finite values untouched


def test_dump_hdf5_roundtrip(tmp_path):
    """HDF5 backend (reference src/base/stats/hdf5.cc analog)."""
    import numpy as np

    h5py = pytest.importorskip("h5py")
    from shrewd_tpu.stats import (Distribution, Formula, Group, Scalar,
                                  Vector, dump_hdf5)

    g = Group("campaign")
    g.trials = Scalar("trials", "total trials")
    g.trials += 128
    g.outcomes = Vector("outcomes", 4, "tallies",
                        subnames=["masked", "sdc", "due", "detected"])
    g.outcomes += np.array([100, 20, 7, 1])
    g.lat = Distribution("lat", 0, 10, 5, "latency")
    g.lat.sample(np.array([1.0, 9.0]))
    g.avf = Formula("avf", lambda: (g.outcomes[1] + g.outcomes[2])
                    / g.trials.value)
    sub = Group("o3")
    g.o3 = sub
    sub.escapes = Scalar("escapes", "escapes")
    path = tmp_path / "stats.h5"
    dump_hdf5(g, str(path))
    with h5py.File(path) as f:
        assert float(f["campaign/trials"][()]) == 128
        assert list(f["campaign/outcomes"][:]) == [100, 20, 7, 1]
        assert list(f["campaign/outcomes"].attrs["subnames"])[1] == "sdc"
        assert f["campaign/lat"].attrs["samples"] == 2
        assert abs(float(f["campaign/avf"][()]) - 27 / 128) < 1e-12
        assert float(f["campaign/o3/escapes"][()]) == 0


def test_dump_hdf5_dict_formula(tmp_path):
    """Dict-valued Formulas land as a subgroup of scalars (the text/json
    backends already support them)."""
    h5py = pytest.importorskip("h5py")
    from shrewd_tpu.stats import Formula, Group, dump_hdf5

    g = Group("x")
    g.ratios = Formula("ratios", lambda: {"a": 0.25, "b": 0.75}, "split")
    path = tmp_path / "d.h5"
    dump_hdf5(g, str(path))
    with h5py.File(path) as f:
        assert float(f["x/ratios/a"][()]) == 0.25
        assert float(f["x/ratios/b"][()]) == 0.75


def test_text_stat_string_safe_everywhere(tmp_path):
    """``stats.Text`` (the reference's string-valued Info fields): prose
    survives text/json dumps, and the HDF5 backend writes a string
    dataset instead of tripping the numeric Formula contract."""
    import io
    import json as _json

    from shrewd_tpu.stats import (Group, Text, dump_json, dump_text,
                                  to_dict)

    g = Group("run")
    g.posture = Text("posture", "certify=strict", "run posture label")
    assert to_dict(g)["posture"] == "certify=strict"
    buf = io.StringIO()
    dump_text(g, buf)
    assert "certify=strict" in buf.getvalue()
    buf = io.StringIO()
    dump_json(g, buf)
    assert _json.loads(buf.getvalue())["posture"] == "certify=strict"
    g.posture.set("aborted: escalation")
    assert g.posture.to_value() == "aborted: escalation"
    g.posture.reset()
    assert g.posture.to_value() == ""

    h5py = pytest.importorskip("h5py")
    from shrewd_tpu.stats import dump_hdf5

    g.posture.set("resumable")
    path = tmp_path / "t.h5"
    dump_hdf5(g, str(path))
    with h5py.File(path) as f:
        raw = f["run/posture"][()]
        val = raw.decode() if isinstance(raw, bytes) else str(raw)
        assert val == "resumable"


def test_dump_hdf5_names_the_offending_stat(tmp_path):
    """A non-numeric Formula fails with the full stat PATH in the error
    (the bare "Formula must be numeric" float() TypeError once cost a
    session 17 tests of archaeology), and points at stats.Text."""
    pytest.importorskip("h5py")
    from shrewd_tpu.stats import Formula, Group, dump_hdf5

    g = Group("campaign")
    sub = Group("perf")
    g.perf = sub
    sub.bad = Formula("bad", lambda: None, "returns None by mistake")
    with pytest.raises(TypeError) as ei:
        dump_hdf5(g, str(tmp_path / "bad.h5"))
    msg = str(ei.value)
    assert "campaign.perf.bad" in msg
    assert "Formula must be numeric" in msg
    assert "stats.Text" in msg
    # the nested dict-Formula path names the full LEAF path too
    g2 = Group("campaign")
    g2.ledger = Formula("ledger", lambda: {"a": {"b": [1, 2]}}, "oops")
    with pytest.raises(TypeError) as ei:
        dump_hdf5(g2, str(tmp_path / "bad2.h5"))
    assert "campaign.ledger.a.b" in str(ei.value)
