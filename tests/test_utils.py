import pytest

from shrewd_tpu.utils import config, debug, prng, probes, units
from shrewd_tpu.utils.config import (Child, ConfigObject, Frequency,
                                     MemorySize, Param, Time, VectorParam)


# --- units ---

def test_to_bytes():
    assert units.to_bytes("64KiB") == 64 * 1024
    assert units.to_bytes("2GB") == 2 << 30
    assert units.to_bytes("512") == 512
    assert units.to_bytes(4096) == 4096
    assert units.to_bytes("1.5KiB") == 1536
    with pytest.raises(units.UnitError):
        units.to_bytes("xyz")


def test_to_frequency_and_time():
    assert units.to_frequency("3GHz") == 3e9
    assert units.to_frequency("200MHz") == 2e8
    assert units.to_seconds("10ns") == pytest.approx(1e-8)
    assert units.to_seconds("1.5us") == pytest.approx(1.5e-6)


def test_format():
    assert units.format_bytes(64 * 1024) == "64KiB"
    assert units.format_bytes(1000) == "1000B"


# --- config ---

class CacheCfg(ConfigObject):
    size = Param(MemorySize, "32KiB", "capacity")
    assoc = Param(int, 8, "ways")


class CoreCfg(ConfigObject):
    clock = Param(Frequency, "1GHz")
    rob_entries = Param(int, 192, check=lambda v: v > 0)
    widths = VectorParam(int, [8, 8, 8])
    l1 = Child(CacheCfg)


def test_config_defaults_and_overrides(tmp_path):
    cfg = CoreCfg(clock="2GHz", l1=CacheCfg(size="64KiB"))
    assert cfg.clock == 2e9
    assert cfg.rob_entries == 192
    assert cfg.l1.size == 64 * 1024
    assert cfg.widths == [8, 8, 8]

    cfg.rob_entries = "256"          # string conversion via descriptor
    assert cfg.rob_entries == 256
    with pytest.raises(ValueError):
        cfg.rob_entries = -1          # check() enforcement
    with pytest.raises(TypeError):
        CoreCfg(clock="2GHz", nonsense=1)


def test_config_roundtrip(tmp_path):
    cfg = CoreCfg(clock="2GHz")
    d = cfg.to_dict()
    cfg2 = CoreCfg.from_dict(d)
    assert cfg2.clock == cfg.clock
    assert cfg2.l1.size == cfg.l1.size

    ini = tmp_path / "config.ini"
    js = tmp_path / "config.json"
    cfg.dump_ini(ini)
    cfg.dump_json(js)
    text = ini.read_text()
    assert "[root]" in text and "[root.l1]" in text
    assert "rob_entries=192" in text


def test_config_polymorphic_child_roundtrip():
    class FancyCache(CacheCfg):
        banks = Param(int, 4)

    cfg = CoreCfg(clock="1GHz", l1=FancyCache(banks=8))
    d = cfg.to_dict()
    cfg2 = CoreCfg.from_dict(d)
    assert type(cfg2.l1) is FancyCache
    assert cfg2.l1.banks == 8


def test_format_count_boundaries():
    assert units.format_count(999999) == "1M"
    assert units.format_count(12500000) == "12.5M"
    assert units.format_count(999) == "999"
    assert units.format_count(0) == "0"
    assert units.format_count(1234) == "1.23k"


def test_to_bytes_float():
    assert units.to_bytes(4096.0) == 4096
    with pytest.raises(units.UnitError):
        units.to_bytes(4096.5)


def test_required_param():
    class NeedsIt(ConfigObject):
        x = Param(int)
    with pytest.raises(ValueError):
        NeedsIt()
    assert NeedsIt(x=3).x == 3


# --- prng ---

def test_trial_key_deterministic():
    import jax
    k1 = prng.trial_key(0, 1, 2, 3, 4)
    k2 = prng.trial_key(0, 1, 2, 3, 4)
    k3 = prng.trial_key(0, 1, 2, 3, 5)
    assert (jax.random.key_data(k1) == jax.random.key_data(k2)).all()
    assert not (jax.random.key_data(k1) == jax.random.key_data(k3)).all()


def test_sample_fault_bounds():
    import jax
    keys = prng.trial_keys(prng.campaign_key(0), 128)
    entries, bits, cycles = jax.vmap(
        lambda k: prng.sample_fault(k, 64, 32, 1000))(keys)
    assert int(entries.min()) >= 0 and int(entries.max()) < 64
    assert int(bits.min()) >= 0 and int(bits.max()) < 32
    assert int(cycles.min()) >= 0 and int(cycles.max()) < 1000


# --- debug ---

def test_debug_flags(capsys):
    debug.register_flag("TestFlag", "test")
    assert not debug.enabled("TestFlag")
    debug.enable("TestFlag")
    debug.dprintf("TestFlag", "hello %d", 42)
    debug.disable("TestFlag")
    err = capsys.readouterr().err
    assert "hello 42" in err and "TestFlag" in err
    with pytest.raises(KeyError):
        debug.enable("NoSuchFlag")


def test_debug_compound():
    debug.enable("All")
    assert debug.enabled("Campaign") and debug.enabled("Replay")
    assert debug.enabled("All")          # compound name itself is enabled
    debug.disable("All")
    assert not debug.enabled("Campaign") and not debug.enabled("All")


def test_debug_enable_atomic():
    # an unknown name anywhere in the list must enable nothing
    with pytest.raises(KeyError):
        debug.enable("Campaign", "Bogus")
    assert not debug.enabled("Campaign")


def test_trial_keys_match_trial_key():
    # batch-derived and fully-addressed keys must be bitwise identical
    import jax
    bk = prng.batch_key(prng.structure_key(
        prng.simpoint_key(prng.campaign_key(9), 1), 2), 3)
    ks = prng.trial_keys(bk, 8)
    k5 = prng.trial_key(9, 1, 2, 3, 5)
    assert (jax.random.key_data(ks[5]) == jax.random.key_data(k5)).all()


# --- probes ---

def test_probes():
    pm = probes.ProbeManager("o3")
    pp = pm.add_point("retired_batch")
    seen = []
    pm.listen("retired_batch", seen.append)
    pp.notify([1, 2, 3])
    assert seen == [[1, 2, 3]]
    assert pm.points() == ["retired_batch"]
    with pytest.raises(KeyError):
        pm.add_point("retired_batch")
