"""Campaign orchestrator / Simulator / checkpoint-resume tests.

Runs on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count) — the multi-chip-on-localhost test
pattern (SURVEY §4 tier 5: dist-gem5 on localhost / NULL-build analogs).
"""

import json
import os

import numpy as np
import pytest

from shrewd_tpu.campaign import (CampaignPlan, Orchestrator, WorkloadSpec)
from shrewd_tpu.campaign.orchestrator import BatchInfo, StructureResult
from shrewd_tpu.ingest import load_stats_txt
from shrewd_tpu.ops import classify as C
from shrewd_tpu.sim import ExitEvent, Simulator
from shrewd_tpu.trace.synth import WorkloadConfig


def _tiny_plan(**kw) -> CampaignPlan:
    sps = [WorkloadSpec(name="w0",
                        workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                                working_set_words=32, seed=7)),
           WorkloadSpec(name="w1",
                        workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                                working_set_words=32, seed=8))]
    defaults = dict(structures=["regfile", "fu"], batch_size=64,
                    target_halfwidth=0.2, confidence=0.95,
                    max_trials=256, min_trials=64)
    defaults.update(kw)
    return CampaignPlan(simpoints=sps, **defaults)


def test_plan_round_trip():
    plan = _tiny_plan()
    doc = plan.to_dict()
    back = CampaignPlan.from_dict(json.loads(json.dumps(doc)))
    assert [sp.name for sp in back.simpoints] == ["w0", "w1"]
    assert back.simpoints[0].workload.n == 96
    assert back.structures == ["regfile", "fu"]
    assert back.batch_size == 64


def test_orchestrator_runs_to_completion():
    orch = Orchestrator(_tiny_plan())
    events = list(orch.events())
    kinds = [e for e, _ in events]
    assert kinds.count(ExitEvent.SIMPOINT_COMPLETE) == 2
    assert kinds[-1] == ExitEvent.CAMPAIGN_COMPLETE
    results = events[-1][1]
    assert set(results) == {("w0", "regfile"), ("w0", "fu"),
                            ("w1", "regfile"), ("w1", "fu")}
    for r in results.values():
        assert isinstance(r, StructureResult)
        assert r.trials > 0 and r.tallies.sum() == r.trials
        assert 0.0 <= r.avf <= 1.0
        assert r.converged or r.trials >= 256


def test_batch_events_carry_progress():
    orch = Orchestrator(_tiny_plan())
    batches = [p for e, p in orch.events() if e is ExitEvent.BATCH_COMPLETE]
    assert all(isinstance(b, BatchInfo) for b in batches)
    w0 = [b for b in batches if b.simpoint == "w0" and b.structure == "regfile"]
    assert [b.batch_id for b in w0] == list(range(len(w0)))
    assert w0[-1].trials == 64 * len(w0)


def test_simulator_handler_stops_run(tmp_path):
    plan = _tiny_plan(max_trials=100000, target_halfwidth=0.001)

    def stop_after(n):
        seen = 0
        while True:
            seen += 1
            yield seen >= n

    sim = Simulator(plan, outdir=str(tmp_path / "out"),
                    on_exit_event={ExitEvent.BATCH_COMPLETE: stop_after(3)})
    results = sim.run()
    assert sim.last_event is ExitEvent.BATCH_COMPLETE
    assert sim.last_payload.batch_id == 2          # stopped on third batch
    assert results == {}                            # nothing converged yet
    # outputs still written on early stop
    assert (tmp_path / "out" / "stats.txt").exists()


def test_simulator_runs_and_writes_outputs(tmp_path):
    out = tmp_path / "m5out"
    sim = Simulator(_tiny_plan(), outdir=str(out))
    results = sim.run()
    assert len(results) == 4
    blocks = load_stats_txt(str(out / "stats.txt"))
    assert len(blocks) == 1
    stats = blocks[0]
    r = results[("w0", "regfile")]
    assert stats["campaign.w0.regfile.trials"] == r.trials
    assert stats["campaign.w0.regfile.outcomes::sdc"] == \
        r.tallies[C.OUTCOME_SDC]
    assert stats["campaign.w0.regfile.avf"] == pytest.approx(r.avf)
    cfg = json.loads((out / "config.json").read_text())
    assert cfg["type"] == "CampaignPlan"
    assert len(cfg["simpoints"]) == 2


def test_checkpoint_resume_bitwise_equal(tmp_path):
    """A resumed campaign must produce bitwise-identical final tallies —
    the PRNG-discipline reproducibility contract."""
    plan = _tiny_plan(checkpoint_every=1)
    # straight-through run
    orch_a = Orchestrator(plan)
    events_a = list(orch_a.events())
    final_a = events_a[-1][1]

    # run that checkpoints and is killed after the first CHECKPOINT event
    out = str(tmp_path / "out")
    orch_b = Orchestrator(_tiny_plan(checkpoint_every=1), outdir=out)
    ckpt_dir = None
    for ev, payload in orch_b.events():
        if ev is ExitEvent.CHECKPOINT:
            ckpt_dir = payload
            break
    assert ckpt_dir is not None and os.path.exists(
        os.path.join(ckpt_dir, "campaign.json"))

    # resume and finish
    orch_c = Orchestrator.resume(ckpt_dir, outdir=out)
    mid_trials = {k: st.trials for k, st in orch_c.state.items()}
    assert any(t > 0 for t in mid_trials.values())
    events_c = list(orch_c.events())
    final_c = events_c[-1][1]

    assert set(final_a) == set(final_c)
    for k in final_a:
        np.testing.assert_array_equal(final_a[k].tallies, final_c[k].tallies)
        assert final_a[k].trials == final_c[k].trials


def test_resume_rejects_unknown_version(tmp_path):
    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    ckpt = orch.checkpoint()
    doc = json.loads((tmp_path / "campaign_ckpt" / "campaign.json").read_text())
    doc["version"] = 99
    doc.pop("checksum", None)   # forged doc: no stale-checksum rejection
    (tmp_path / "campaign_ckpt" / "campaign.json").write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="upgrade path"):
        Orchestrator.resume(ckpt)


def test_resume_upgrades_v1_checkpoint(tmp_path):
    """A version-1 campaign checkpoint (no escape counters) upgrades in
    sequence and resumes — the util/cpt_upgraders contract, working
    instead of a raise (VERDICT r2 weak #10)."""
    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    ckpt = orch.checkpoint()
    path = tmp_path / "campaign_ckpt" / "campaign.json"
    doc = json.loads(path.read_text())
    doc["version"] = 1
    doc.pop("checksum", None)   # v1-era checkpoints predate checksums
    for per_structure in doc["state"].values():
        for st_doc in per_structure.values():
            st_doc.pop("escapes", None)
            st_doc.pop("taint_trials", None)
    path.write_text(json.dumps(doc))
    orch2 = Orchestrator.resume(ckpt)
    for st in orch2.state.values():
        assert st.escapes == 0 and st.taint_trials == 0


def test_tier_structures_run_to_completion(tmp_path):
    """Tier-qualified structures (cache:/mesi:/noc:) route to the cache,
    MESI, and NoC fault kernels through the same plan/orchestrator path as
    the O3 structures (campaign/orchestrator.py kernel_for)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0",
            workload=WorkloadConfig(n=128, nphys=64, mem_words=64,
                                    working_set_words=32, seed=3))],
        structures=["regfile", "cache:data", "mesi:state", "noc:router"],
        batch_size=64, max_trials=128, min_trials=64,
        target_halfwidth=0.5, coherence_accesses=96,
        coherence_mem_words=64)
    orch = Orchestrator(plan, outdir=str(tmp_path))
    results = {}
    for event, payload in orch.events():
        if event in (ExitEvent.CI_CONVERGED, ExitEvent.MAX_TRIALS):
            results[payload.structure] = payload
        elif event == ExitEvent.CAMPAIGN_COMPLETE:
            break
    assert set(results) == {"regfile", "cache:data", "mesi:state",
                            "noc:router"}
    for r in results.values():
        assert r.trials >= 64
        assert r.tallies.sum() == r.trials
        assert 0.0 <= r.avf <= 1.0
    orch.write_outputs()
    assert (tmp_path / "stats.txt").exists()
    text = (tmp_path / "stats.txt").read_text()
    assert "noc:router" in text and "mesi:state" in text


def test_plan_roundtrip_with_tier_structures():
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec

    plan = CampaignPlan(
        simpoints=[WorkloadSpec(name="a",
                                workload=WorkloadConfig(n=64))],
        structures=["lsq", "cache:tag", "noc:router"],
        coherence_accesses=32)
    d = plan.to_dict()
    back = CampaignPlan.from_dict(d)
    assert back.structures == ["lsq", "cache:tag", "noc:router"]
    assert back.coherence_accesses == 32
    assert back.noc.mesh_x == plan.noc.mesh_x


def test_invalid_tier_structure_rejected():
    import pytest

    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec

    with pytest.raises(ValueError):
        CampaignPlan(simpoints=[WorkloadSpec(
            name="a", workload=WorkloadConfig(n=64))],
                     structures=["cache:bogus"])


def test_structure_ids_frozen_and_complete():
    """Every drivable structure has a frozen PRNG id; the map must cover
    the O3 set and the tier set exactly once each (renumbering would
    silently change resumed campaigns' fault samples)."""
    from shrewd_tpu.campaign.orchestrator import _STRUCTURE_IDS
    from shrewd_tpu.campaign.plan import TIER_STRUCTURES
    from shrewd_tpu.models.o3 import STRUCTURES

    universe = set(STRUCTURES) | set(TIER_STRUCTURES)
    assert set(_STRUCTURE_IDS) == universe
    ids = list(_STRUCTURE_IDS.values())
    assert len(ids) == len(set(ids))


def test_plan_level_tiers_run_once_across_simpoints():
    """mesi:/noc: tiers measure plan-level synthetic traffic: with two
    simpoints they run ONCE (under the 'coherence' pseudo-simpoint), not
    once per simpoint."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = CampaignPlan(
        simpoints=[
            WorkloadSpec(name="w0",
                         workload=WorkloadConfig(n=96, nphys=32,
                                                 mem_words=64,
                                                 working_set_words=32,
                                                 seed=1)),
            WorkloadSpec(name="w1",
                         workload=WorkloadConfig(n=96, nphys=32,
                                                 mem_words=64,
                                                 working_set_words=32,
                                                 seed=2))],
        structures=["regfile", "mesi:state"],
        batch_size=64, max_trials=64, min_trials=64,
        target_halfwidth=0.5, coherence_accesses=64,
        coherence_mem_words=64)
    orch = Orchestrator(plan)
    done = []
    for event, payload in orch.events():
        if event in (ExitEvent.CI_CONVERGED, ExitEvent.MAX_TRIALS):
            done.append((payload.simpoint, payload.structure))
        elif event == ExitEvent.CAMPAIGN_COMPLETE:
            break
    assert done.count(("coherence", "mesi:state")) == 1
    assert ("w0", "regfile") in done and ("w1", "regfile") in done
    assert not any(sp in ("w0", "w1") and s == "mesi:state"
                   for sp, s in done)


def test_orchestrator_probe_points():
    """Orchestrator probe points fire for listeners (utils/probes; the
    gem5 ProbePoint pattern — instrumentation without coupling)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = _tiny_plan(structures=["regfile"], max_trials=64, min_trials=64)
    orch = Orchestrator(plan)
    batches, structures = [], []
    orch.pp_batch.connect(batches.append)
    orch.pp_structure.connect(structures.append)
    for event, _ in orch.events():
        if event == ExitEvent.CAMPAIGN_COMPLETE:
            break
    assert len(batches) >= 1
    assert len(structures) == len(plan.simpoints)   # one per (sp, regfile)
    assert all(b.structure == "regfile" for b in batches)
    assert {s.simpoint for s in structures} == {"w0", "w1"}


def test_coherence_simpoint_name_reserved():
    import pytest

    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec

    with pytest.raises(ValueError, match="reserved"):
        CampaignPlan(simpoints=[WorkloadSpec(
            name="coherence", workload=WorkloadConfig(n=64))],
            structures=["regfile"])


def test_stratified_plan_runs_and_checkpoints(tmp_path):
    """plan.stratify=True: O3 structures use the post-stratified estimator
    (every tier kernel now has one), strata survive
    checkpoint/resume, and v2-era checkpoints upgrade to v3."""
    import json

    from shrewd_tpu.campaign.orchestrator import (CKPT_VERSION,
                                                  Orchestrator,
                                                  upgrade_checkpoint)
    from shrewd_tpu.campaign.plan import CampaignPlan
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = _tiny_plan(structures=["regfile", "mesi:state"], stratify=True,
                      max_trials=128, min_trials=64,
                      checkpoint_every=1, coherence_accesses=64,
                      coherence_mem_words=64)
    orch = Orchestrator(plan, outdir=str(tmp_path))
    for event, _ in orch.events():
        if event == ExitEvent.CAMPAIGN_COMPLETE:
            break
    st = orch.state[("w0", "regfile")]
    assert st.strata is not None
    assert int(st.strata.sum()) == st.trials
    np.testing.assert_array_equal(st.strata.sum(axis=0), st.tallies)
    # the MESI tier carries its own stratified path (landing-access
    # octiles), so plan-level stratify covers it too
    mst = orch.state[("coherence", "mesi:state")]
    assert mst.strata is not None
    assert int(mst.strata.sum()) == mst.trials

    ckpt = orch.checkpoint()
    orch2 = Orchestrator.resume(ckpt)
    st2 = orch2.state[("w0", "regfile")]
    np.testing.assert_array_equal(st2.strata, st.strata)

    # v2-format document upgrades in place
    with open(f"{ckpt}/campaign.json") as f:
        doc = json.load(f)
    assert doc["version"] == CKPT_VERSION
    for per_s in doc["state"].values():
        for st_doc in per_s.values():
            st_doc.pop("strata")
    doc["version"] = 2
    upgrade_checkpoint(doc)
    assert doc["version"] == CKPT_VERSION
    assert all("strata" in st_doc for per_s in doc["state"].values()
               for st_doc in per_s.values())
