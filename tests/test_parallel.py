"""Multi-device campaign tests on the 8-device virtual CPU mesh — the
dist-on-localhost analog (SURVEY §4 tier 5)."""

import jax
import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.parallel import (ShardedCampaign, make_mesh, run_until_ci,
                                 shard_keys, stopping)
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


@pytest.fixture(scope="module")
def kernel():
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=256,
                                working_set_words=128, seed=33))
    return TrialKernel(t)


def test_mesh_has_8_devices():
    m = make_mesh()
    assert m.size == 8


def test_sharded_tally_matches_single_device(kernel):
    """The SPMD path must produce exactly the single-device tallies —
    determinism across sharding layouts (the PRNG discipline's promise)."""
    m = make_mesh()
    camp = ShardedCampaign(kernel, m, "regfile")
    keys = prng.trial_keys(prng.campaign_key(5), 128)
    sharded = np.asarray(camp.tally_batch(keys))
    single = np.asarray(kernel.run_keys(keys, "regfile"))
    np.testing.assert_array_equal(sharded, single)
    assert sharded.sum() == 128


def test_shard_keys_rejects_indivisible(kernel):
    m = make_mesh()
    keys = prng.trial_keys(prng.campaign_key(0), 12)
    with pytest.raises(ValueError):
        shard_keys(m, keys)


def test_run_until_ci_converges(kernel):
    m = make_mesh()
    camp = ShardedCampaign(kernel, m, "regfile")
    res = run_until_ci(camp, seed=0, simpoint_id=0, structure_id=0,
                       batch_size=512, target_halfwidth=0.05,
                       max_trials=100_000, min_trials=500)
    assert res.converged
    assert res.trials == res.tallies.sum()
    assert res.avf_interval.halfwidth <= 0.05
    assert 0.0 <= res.avf <= 1.0
    assert res.trials_per_second > 0


def test_run_until_ci_resume_is_exact(kernel):
    """Resuming from a checkpointed (tallies, batch) must give the same
    final tallies as an uninterrupted run."""
    m = make_mesh()
    camp = ShardedCampaign(kernel, m, "fu")
    full = run_until_ci(camp, seed=1, simpoint_id=0, structure_id=1,
                        batch_size=256, target_halfwidth=1e-9,
                        max_trials=1024, min_trials=1)
    # run 2 batches, "checkpoint", resume for the remaining 2
    part1 = run_until_ci(camp, seed=1, simpoint_id=0, structure_id=1,
                         batch_size=256, target_halfwidth=1e-9,
                         max_trials=512, min_trials=1)
    part2 = run_until_ci(camp, seed=1, simpoint_id=0, structure_id=1,
                         batch_size=256, target_halfwidth=1e-9,
                         max_trials=1024, min_trials=1,
                         start_batch=part1.batches,
                         initial_tallies=part1.tallies)
    np.testing.assert_array_equal(full.tallies, part2.tallies)


# --- stopping math ---

def test_wilson_basics():
    iv = stopping.wilson(50, 100)
    assert iv.estimate == pytest.approx(0.5)
    assert iv.lo < 0.5 < iv.hi
    # tighter with more trials
    iv2 = stopping.wilson(5000, 10000)
    assert iv2.halfwidth < iv.halfwidth
    # doesn't collapse at p=0
    iv0 = stopping.wilson(0, 1000)
    assert iv0.hi > 0


def test_should_stop():
    assert not stopping.should_stop(5, 10, 0.5)          # below min_trials
    assert stopping.should_stop(500, 10000, 0.05, min_trials=100)
    assert not stopping.should_stop(500, 1000, 0.001, min_trials=100)


def test_z_value_bisection_matches_table():
    assert stopping.z_value(0.95) == pytest.approx(1.959964, abs=1e-5)
    assert stopping.z_value(0.98) == pytest.approx(2.326348, abs=1e-4)


def test_trials_needed_monotone():
    assert (stopping.trials_needed(0.5, 0.01)
            > stopping.trials_needed(0.5, 0.02)
            > stopping.trials_needed(0.05, 0.02))


class TestDeviceResolution:
    """In-graph budgeted escape resolution (VERDICT r2 weak #9)."""

    def _kernel(self, **cfg_kw):
        from shrewd_tpu.models.o3 import O3Config
        from shrewd_tpu.ops.trial import TrialKernel
        from shrewd_tpu.trace.synth import WorkloadConfig, generate

        tr = generate(WorkloadConfig(n=192, nphys=64, mem_words=128,
                                     working_set_words=32, seed=13))
        return TrialKernel(tr, O3Config(replay_kernel="hybrid", **cfg_kw))

    def test_device_matches_host_resolution(self):
        from shrewd_tpu.parallel import make_mesh
        mesh8 = make_mesh()
        import numpy as np

        from shrewd_tpu.parallel.campaign import ShardedCampaign
        from shrewd_tpu.utils import prng

        kernel = self._kernel()
        keys = prng.trial_keys(prng.campaign_key(5), 512)
        dev = ShardedCampaign(kernel, mesh8, "lsq", resolution="device")
        host = ShardedCampaign(self._kernel(), mesh8, "lsq",
                               resolution="host")
        t_dev = np.asarray(dev.tally_batch(keys))
        t_host = np.asarray(host.tally_batch(keys))
        assert t_dev.sum() == t_host.sum() == 512
        np.testing.assert_array_equal(t_dev, t_host)

    def test_zero_budget_is_conservative(self):
        import numpy as np

        from shrewd_tpu.ops import classify as C
        from shrewd_tpu.utils import prng

        kernel = self._kernel(escape_budget=0)
        exact = self._kernel()
        keys = prng.trial_keys(prng.campaign_key(6), 256)
        t0, n0 = (np.asarray(x) for x in kernel.run_keys_device(keys, "lsq"))
        t1, n1 = (np.asarray(x) for x in exact.run_keys_device(keys, "lsq"))
        assert t0.sum() == t1.sum() == 256
        assert n0 == n1                       # same faults, same escapes
        # conservative path can only move mass INTO the SDC bucket
        assert t0[C.OUTCOME_SDC] >= t1[C.OUTCOME_SDC]

    def test_device_matches_single_chip_hybrid(self):
        from shrewd_tpu.parallel import make_mesh
        mesh8 = make_mesh()
        import numpy as np

        from shrewd_tpu.parallel.campaign import ShardedCampaign
        from shrewd_tpu.utils import prng

        kernel = self._kernel()
        keys = prng.trial_keys(prng.campaign_key(7), 256)
        camp = ShardedCampaign(kernel, mesh8, "regfile")
        sharded = np.asarray(camp.tally_batch(keys))
        single = np.asarray(self._kernel().run_keys(keys, "regfile"))
        np.testing.assert_array_equal(sharded, single)
