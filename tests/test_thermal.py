"""Thermal RC network tests (utils/thermal.py).

Pinned against closed-form RC physics — the same checks one would run on
the reference's ThermalModel (``src/sim/power/thermal_model.cc``):
single-RC exponential step response, steady-state nodal balance, and the
activity→power→temperature→fault-rate chain end to end."""

import numpy as np
import pytest

from shrewd_tpu.utils.thermal import (KELVIN, ThermalNetwork,
                                      activity_power)


def single_rc(r=2.0, c=5.0, step=0.01, ambient=45.0):
    return (ThermalNetwork(n_nodes=1, ambient_c=ambient, step_s=step)
            .resistor(0, -1, r).capacitor(0, -1, c).build())


def test_step_response_matches_closed_form():
    # constant power P into one RC node: T(t) = amb + P·R·(1 − e^{−t/RC})
    r, c, p = 2.0, 5.0, 10.0
    step = 0.01
    model = single_rc(r=r, c=c, step=step)
    steps = 12000                       # 12·RC: fully settled
    traj = np.asarray(model.trajectory(np.full((steps, 1), p)))
    t = (np.arange(1, steps + 1)) * step
    exact = 45.0 + p * r * (1.0 - np.exp(-t / (r * c)))
    # backward Euler at dt = RC/1000: sub-0.1K accuracy
    assert np.abs(traj[:, 0] - exact).max() < 0.1
    # equilibrium: amb + P·R
    assert traj[-1, 0] == pytest.approx(45.0 + p * r, abs=0.05)


def test_steady_state_solve():
    model = single_rc(r=3.0, c=1.0)
    ss = np.asarray(model.steady_state(np.array([7.0])))
    assert ss[0] == pytest.approx(45.0 + 21.0, abs=1e-3)


def test_two_node_chain_gradient():
    # die → heat-spreader → ambient: power at the die; at equilibrium the
    # full P flows through both resistors, so T_die = amb + P(R1+R2),
    # T_spread = amb + P·R2
    net = (ThermalNetwork(n_nodes=2, ambient_c=40.0, step_s=0.01)
           .resistor(0, 1, 1.5).resistor(1, -1, 0.5)
           .capacitor(0, -1, 2.0).capacitor(1, -1, 10.0))
    model = net.build()
    ss = np.asarray(model.steady_state(np.array([8.0, 0.0])))
    assert ss[0] == pytest.approx(40.0 + 8.0 * 2.0, abs=1e-3)
    assert ss[1] == pytest.approx(40.0 + 8.0 * 0.5, abs=1e-3)
    # the transient converges to the same point
    traj = np.asarray(model.trajectory(
        np.broadcast_to(np.array([8.0, 0.0]), (30000, 2))))
    np.testing.assert_allclose(traj[-1], ss, atol=0.05)


def test_cooling_from_hot_start():
    model = single_rc(r=2.0, c=5.0)
    traj = np.asarray(model.trajectory(np.zeros((12000, 1)),
                                       t0_c=np.array([95.0])))
    assert traj[0, 0] < 95.0 and traj[-1, 0] == pytest.approx(45.0,
                                                              abs=0.2)
    assert (np.diff(traj[:, 0]) <= 1e-9).all()      # monotone cooling


def test_activity_power_chain_to_fault_rate():
    """window activity → power trace → temperature → NoC fault-rate
    acceleration (the reference's power/thermal/fault chain)."""
    from shrewd_tpu.models.noc import temperature_factor
    from shrewd_tpu.models.timing import TimingConfig, compute_scoreboard
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    tr = generate(WorkloadConfig(n=2048, nphys=64, mem_words=256,
                                 working_set_words=64, seed=3))
    sb = compute_scoreboard(tr, TimingConfig())
    p = activity_power(tr, sb, interval_cycles=256)
    assert p.shape[0] >= 1 and (p > 0).all()
    model = single_rc(r=1.0, c=0.05, step=0.001)
    traj = np.asarray(model.trajectory(p[:, None]))
    assert (traj >= 45.0 - 1e-6).all()
    # hotter die ⇒ accelerated upset rates in every susceptibility class
    f_hot = np.asarray(temperature_factor(float(traj.max())))
    f_amb = np.asarray(temperature_factor(45.0))
    assert (f_hot >= f_amb - 1e-12).all()


def test_empty_network_rejected():
    with pytest.raises(ValueError):
        ThermalNetwork(n_nodes=1).build()
    with pytest.raises(ValueError):
        ThermalNetwork(n_nodes=1).resistor(0, -1, -2.0)
    with pytest.raises(ValueError):
        ThermalNetwork(n_nodes=1).capacitor(0, -1, 0.0)
