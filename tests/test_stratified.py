"""Post-stratified AVF estimation (parallel/stopping.post_stratified,
ops/trial.run_keys_stratified, ShardedCampaign(stratify=True)).

Variance reduction via post-stratification over fault-cycle octiles
(regfile) / struck OpClass (others): measured ≈1.2-1.3× fewer trials to a
fixed CI on synthetic traces."""

import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.trial import N_STRATA, TrialKernel
from shrewd_tpu.parallel import ShardedCampaign, make_mesh, run_until_ci, stopping
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


@pytest.fixture(scope="module")
def kernel():
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=256,
                                working_set_words=128, seed=33))
    return TrialKernel(t)


class TestEstimator:
    def test_reduces_to_wilson_scale_when_homogeneous(self):
        pairs = [(25, 100), (25, 100), (25, 100), (25, 100)]
        strat = stopping.post_stratified(pairs)
        wil = stopping.wilson(100, 400)
        assert abs(strat.estimate - 0.25) < 1e-12
        # same information → near-identical widths (normal vs Wilson)
        assert abs(strat.halfwidth - wil.halfwidth) < 0.01

    def test_tighter_when_strata_differ(self):
        hetero = [(5, 100), (20, 100), (50, 100), (95, 100)]
        total_v = sum(s for s, _n in hetero)
        strat = stopping.post_stratified(hetero)
        wil = stopping.wilson(total_v, 400)
        assert abs(strat.estimate - total_v / 400) < 1e-12
        assert strat.halfwidth < wil.halfwidth * 0.85

    def test_empty_strata_ignored(self):
        pairs = [(10, 50), (0, 0), (40, 50)]
        r = stopping.post_stratified(pairs)
        assert abs(r.estimate - 0.5) < 1e-12

    def test_stopping_rule(self):
        pairs = [(100, 10_000), (900, 10_000)]
        assert stopping.should_stop_stratified(pairs, 0.01)
        assert not stopping.should_stop_stratified(pairs, 0.001)
        assert not stopping.should_stop_stratified(
            pairs, 0.5, min_trials=100_000)


class TestDeviceTally:
    def test_strata_sum_matches_plain_tally(self, kernel):
        keys = prng.trial_keys(prng.campaign_key(3), 256)
        for structure in ("regfile", "fu", "lsq"):
            th, n1 = kernel.run_keys_stratified(keys, structure)
            t, n2 = kernel.run_keys_device(keys, structure)
            th, t = np.asarray(th), np.asarray(t)
            assert th.shape == (N_STRATA, C.N_OUTCOMES)
            np.testing.assert_array_equal(th.sum(axis=0), t)
            assert int(n1) == int(n2)

    def test_opclass_strata_populated(self, kernel):
        keys = prng.trial_keys(prng.campaign_key(5), 512)
        th, _ = kernel.run_keys_stratified(keys, "fu")
        th = np.asarray(th)
        assert (th.sum(axis=1) > 0).sum() >= 2    # several opclasses hit

    def test_sharded_stratified_matches_single_chip(self, kernel):
        mesh = make_mesh()
        camp = ShardedCampaign(kernel, mesh, "regfile", stratify=True)
        keys = prng.trial_keys(prng.campaign_key(7), 256)
        sharded = np.asarray(camp.tally_batch_stratified(keys))
        single = np.asarray(kernel.run_keys_stratified(keys, "regfile")[0])
        np.testing.assert_array_equal(sharded, single)

    def test_stratify_requires_capable_kernel(self):
        class Stub:
            def outcomes_from_keys(self, keys, structure):
                raise NotImplementedError

        with pytest.raises(ValueError, match="stratified"):
            ShardedCampaign(Stub(), make_mesh(), "x", stratify=True)


class TestRunUntilCI:
    def test_stratified_run_converges_consistently(self, kernel):
        mesh = make_mesh()
        camp = ShardedCampaign(kernel, mesh, "regfile", stratify=True)
        res = run_until_ci(camp, seed=1, simpoint_id=0, structure_id=0,
                           batch_size=512, target_halfwidth=0.05,
                           max_trials=100_000, min_trials=512)
        assert res.converged
        assert res.strata_tallies is not None
        np.testing.assert_array_equal(res.strata_tallies.sum(axis=0),
                                      res.tallies)
        assert res.avf_interval.halfwidth <= 0.05
        assert abs(res.avf_interval.estimate - res.avf) < 0.05

    def test_stratified_interval_not_wider_than_wilson(self, kernel):
        """At identical trials the stratified interval must not be
        meaningfully wider than the pooled Wilson interval."""
        mesh = make_mesh()
        camp = ShardedCampaign(kernel, mesh, "fu", stratify=True)
        keys = prng.trial_keys(prng.campaign_key(9), 2048)
        th = np.asarray(camp.tally_batch_stratified(keys), dtype=np.int64)
        vul_h = th[:, C.OUTCOME_SDC] + th[:, C.OUTCOME_DUE]
        n_h = th.sum(axis=1)
        strat = stopping.post_stratified(list(zip(vul_h, n_h)))
        wil = stopping.wilson(int(vul_h.sum()), int(n_h.sum()))
        assert strat.halfwidth <= wil.halfwidth * 1.05


class TestReviewRegressions:
    def test_extreme_tiny_stratum_keeps_variance(self):
        """A 3-trial all-vulnerable stratum must still contribute variance
        (Agresti-Coull adjustment) — raw p̂(1-p̂) would be zero and stop
        the campaign early."""
        pairs = [(3, 3), (50, 100)]
        r = stopping.post_stratified(pairs)
        assert r.halfwidth > 0.05          # tiny stratum keeps CI honest

    def test_stratified_resume_with_initial_strata(self, kernel):
        mesh = make_mesh()
        camp = ShardedCampaign(kernel, mesh, "regfile", stratify=True)
        r1 = run_until_ci(camp, seed=4, simpoint_id=0, structure_id=0,
                          batch_size=512, target_halfwidth=0.03,
                          max_trials=4096, min_trials=512)
        r2 = run_until_ci(camp, seed=4, simpoint_id=0, structure_id=0,
                          batch_size=512, target_halfwidth=0.03,
                          max_trials=8192, min_trials=512,
                          start_batch=r1.batches,
                          initial_tallies=r1.tallies,
                          initial_strata=r1.strata_tallies)
        assert r2.strata_tallies.sum() == r2.trials
        np.testing.assert_array_equal(r2.strata_tallies.sum(axis=0),
                                      r2.tallies)

    def test_stratified_resume_without_strata_falls_back_to_wilson(
            self, kernel):
        """Resumed without strata history, the interval must cover every
        counted trial (pooled Wilson), never just the post-resume slice."""
        mesh = make_mesh()
        camp = ShardedCampaign(kernel, mesh, "regfile", stratify=True)
        tallies = np.array([3000, 500, 500, 0], dtype=np.int64)
        res = run_until_ci(camp, seed=4, simpoint_id=0, structure_id=0,
                           batch_size=512, target_halfwidth=0.5,
                           max_trials=4096, min_trials=512,
                           initial_tallies=tallies)
        wil = stopping.wilson(
            int(res.tallies[C.OUTCOME_SDC] + res.tallies[C.OUTCOME_DUE]),
            res.trials)
        assert abs(res.avf_interval.halfwidth - wil.halfwidth) < 1e-12

    def test_host_resolution_plus_stratify_rejected(self, kernel):
        with pytest.raises(ValueError, match="device"):
            ShardedCampaign(kernel, make_mesh(), "regfile",
                            resolution="host", stratify=True)


class TestTierKernels:
    """Tier kernels expose the same stratified-tally contract, so
    plan.stratify covers them through the orchestrator automatically."""

    def _mesi(self):
        from shrewd_tpu.models.mesi import (MesiConfig, MesiKernel,
                                            torture_stream)

        cfg = MesiConfig()
        return MesiKernel(torture_stream(cfg, 96, 64, seed=2), cfg,
                          np.arange(64, dtype=np.uint32))

    def test_mesi_strata_sum_matches_plain(self):
        k = self._mesi()
        keys = prng.trial_keys(prng.campaign_key(11), 128)
        th, _ = k.run_keys_stratified(keys, "state")
        t = k.run_keys(keys, "state")
        np.testing.assert_array_equal(np.asarray(th).sum(axis=0),
                                      np.asarray(t))

    def test_cache_strata_sum_matches_plain(self):
        from shrewd_tpu.models.ruby import (CacheConfig, CacheKernel,
                                            golden_access_stream,
                                            simulate_cache)
        from shrewd_tpu.trace.synth import WorkloadConfig, generate

        tr = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                     working_set_words=32, seed=5))
        cfg = CacheConfig(n_sets=4, n_ways=2, words_per_line=4)
        tl, _ = simulate_cache(golden_access_stream(tr), cfg, n_cycles=tr.n)
        k = CacheKernel(tl, cfg)
        keys = prng.trial_keys(prng.campaign_key(12), 128)
        for s in ("data", "tag", "state"):
            th, _ = k.run_keys_stratified(keys, s)
            t = k.run_keys(keys, s)
            np.testing.assert_array_equal(np.asarray(th).sum(axis=0),
                                          np.asarray(t))

    def test_noc_strata_follow_type_classes(self):
        from shrewd_tpu.models.mesi import MesiConfig, torture_stream
        from shrewd_tpu.models.noc import (NocConfig, NocKernel,
                                           build_message_trace)

        mcfg = MesiConfig()
        ncfg = NocConfig()
        msgs = build_message_trace(torture_stream(mcfg, 96, 64, seed=3),
                                   mcfg, ncfg)
        k = NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(13), 256)
        th, _ = k.run_keys_stratified(keys)
        th = np.asarray(th)
        t = np.asarray(k.run_keys(keys))
        np.testing.assert_array_equal(th.sum(axis=0), t)
        from shrewd_tpu.models.noc import N_TYPE_CLASSES
        assert (th[:N_TYPE_CLASSES].sum(axis=1) > 0).sum() >= 2
        assert th[N_TYPE_CLASSES:].sum() == 0

    def test_sharded_campaign_accepts_tier_stratify(self):
        from shrewd_tpu.parallel import ShardedCampaign, make_mesh

        k = self._mesi()
        camp = ShardedCampaign(k, make_mesh(), "state", stratify=True)
        keys = prng.trial_keys(prng.campaign_key(14), 128)
        th = np.asarray(camp.tally_batch_stratified(keys))
        assert th.sum() == 128
