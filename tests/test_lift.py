"""Native-trace capture + macro→µop lift tests (ingest/lift.py).

The real-workload ingestion path (VERDICT r1 missing #1): compile a
deterministic guest program (workloads/sort.c — the Bubblesort of the
reference's tests/gem5/cpu_tests), capture its dynamic instruction stream on
the host CPU via ptrace (tools/nativetrace.cc, the NativeTrace/statetrace
pattern), lift it to the µop ISA, and verify the device replay reproduces
the *captured hardware execution* — a differential chain rooted outside the
framework's own code.
"""

import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "tests" / "_build"


def _run(cmd, **kw):
    return subprocess.run(cmd, check=True, capture_output=True, text=True,
                          **kw)


@pytest.fixture(scope="session")
def sort_capture(tmp_path_factory):
    """Build workload + tracer, capture the sort kernel window once."""
    BUILD.mkdir(exist_ok=True)
    wl = BUILD / "sort"
    tracer = BUILD / "nativetrace"
    trace_bin = BUILD / "sort_trace.bin"
    _run(["gcc", "-O1", "-static", "-fno-pie", "-no-pie", "-o", str(wl),
          str(REPO / "workloads" / "sort.c")])
    _run(["g++", "-O2", "-std=c++17", "-o", str(tracer),
          str(REPO / "tools" / "nativetrace.cc")])
    nm = _run(["nm", str(wl)]).stdout
    syms = {parts[2]: int(parts[0], 16)
            for parts in (ln.split() for ln in nm.splitlines())
            if len(parts) == 3}
    begin, end = syms["kernel_begin"], syms["kernel_end"]
    _run([str(tracer), str(trace_bin), f"{begin:x}", f"{end:x}", "2000000",
          str(wl)])
    return trace_bin, wl


@pytest.fixture(scope="session")
def lifted(sort_capture):
    from shrewd_tpu.ingest.lift import lift
    trace_bin, wl = sort_capture
    return lift(str(trace_bin), str(wl))


def test_capture_has_real_shape(sort_capture):
    from shrewd_tpu.ingest.lift import read_nativetrace
    nt = read_nativetrace(str(sort_capture[0]))
    assert len(nt.steps) > 5000          # a real dynamic stream, not a stub
    assert len(nt.regions) >= 2          # data + stack at minimum
    # PCs advance through the text segment
    pcs = nt.steps[:, 16]
    assert len(np.unique(pcs)) > 20


def test_lift_rate_is_high(lifted):
    _, meta = lifted
    s = meta["stats"]
    assert s["lift_rate"] >= 0.95, s
    assert s["branches_lifted"] >= 0.95 * max(s["branches"], 1)
    assert s["uops"] > 1000


def test_golden_replay_reproduces_captured_registers(lifted):
    """The decisive check: the dense device kernel's fault-free replay of
    the lifted trace ends in the same (low-32) register state the host CPU
    was captured in at the end marker."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    trace, meta = lifted
    k = TrialKernel(trace, O3Config())
    assert not bool(k.golden.diverged)
    assert not bool(k.golden.trapped)
    got = np.asarray(k.golden.reg)[:16]
    want = np.asarray(meta["final_reg_expect"], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_scalar_oracle_agrees_on_lifted_trace(lifted):
    from shrewd_tpu.isa import semantics
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    trace, _ = lifted
    reg, mem = trace.init_reg.copy(), trace.init_mem.copy()
    semantics.scalar_replay(trace, reg, mem)
    k = TrialKernel(trace, O3Config())
    np.testing.assert_array_equal(np.asarray(k.golden.reg), reg)
    np.testing.assert_array_equal(np.asarray(k.golden.mem), mem)


def test_sorted_array_lands_in_replay_memory(lifted):
    """The replayed memory holds the actually-sorted array: lift the data
    cluster back out and check monotonicity (the workload's own output
    criterion, like MatchStdout on a gem5 cpu_test)."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    trace, meta = lifted
    k = TrialKernel(trace, O3Config())
    mem = np.asarray(k.golden.mem)
    # find a 48-run of nondecreasing int32 words in the data cluster
    lo, hi, off = meta["clusters"][0]
    words = mem[off:off + (hi - lo) // 4].astype(np.int32)
    ok = False
    for s in range(0, max(1, len(words) - 48)):
        w = words[s:s + 48]
        if (np.diff(w) >= 0).all() and len(np.unique(w)) > 8:
            ok = True
            break
    assert ok, "no sorted 48-element window found in replay memory"


def test_campaign_runs_on_lifted_trace(lifted):
    """End-to-end: a hybrid SFI campaign batch on a real-workload window
    (the round-1 gap: campaigns only ever ran on synthetic streams)."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.utils import prng
    trace, _ = lifted
    k = TrialKernel(trace, O3Config())
    keys = prng.trial_keys(prng.campaign_key(77), 32)
    tally = np.asarray(k.run_keys(keys, "regfile"))
    assert tally.sum() == 32
    assert tally[C.OUTCOME_MASKED] > 0   # most regfile flips mask


def test_trace_roundtrip_with_meta(lifted, tmp_path):
    from shrewd_tpu.trace import format as TF
    trace, meta = lifted
    p = tmp_path / "lifted.npz"
    slim = {k: v for k, v in meta.items() if k != "uop_start"}
    TF.save(p, trace, slim)
    tr2, meta2 = TF.load(p)
    np.testing.assert_array_equal(tr2.opcode, trace.opcode)
    assert meta2["source"] == "nativetrace"


def test_rotate_xchg_subword_test_lift_clean():
    """rol/ror (32-bit), xchg (reg/reg and reg/mem), and plain-mnemonic
    sub-word tests ("test $1,%sil") lift without demotion — the r3 lifter
    additions, self-checked against the captured register stream on the
    rotmix torture workload."""
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/rotmix.c")
    _trace, meta = hd.capture_and_lift(paths)
    st = meta["stats"]
    assert st["lift_rate"] == 1.0, st["opaque_mnemonics"]
    assert st["branches_dropped"] == 0


def test_mem_cluster_metadata_consistent(lifted):
    """Every LOAD/STORE µop carries its cluster index (the VA crash
    model's un-fold key); non-memory µops carry -1; the mapped-region
    table covers every cluster so no golden access could ever trap."""
    from shrewd_tpu.isa import uops as U

    trace, meta = lifted
    mc = np.asarray(meta["mem_cluster"])
    assert mc.shape[0] == trace.n
    is_mem = np.isin(trace.opcode, (U.LOAD, U.STORE))
    assert (mc[~is_mem] == -1).all()
    k = len(meta["clusters"])
    assert ((mc[is_mem] >= 0) & (mc[is_mem] < k)).all()
    regions = meta["map_regions"]
    assert regions and any(w for _, _, w in regions)
    for lo, hi, _off in meta["clusters"]:
        assert any(rlo <= lo and hi <= rlo + span
                   for rlo, span, _w in regions), hex(lo)


def test_memmap_golden_replay_identical(lifted):
    """Attaching the VA crash model must not change the golden replay —
    every golden access stays in its own cluster by the folded-affine
    invariant, so slots and values are bit-identical."""
    import jax

    from shrewd_tpu.ingest.hostdiff import memmap_from_meta
    from shrewd_tpu.models.o3 import O3Config, null_fault
    from shrewd_tpu.ops.trial import TrialKernel

    trace, meta = lifted
    k_plain = TrialKernel(trace, O3Config(enable_shrewd=False))
    k_mm = TrialKernel(trace, O3Config(enable_shrewd=False),
                       memmap=memmap_from_meta(meta))
    assert k_mm.memmap is not None
    np.testing.assert_array_equal(np.asarray(k_plain.golden.reg),
                                  np.asarray(k_mm.golden.reg))
    np.testing.assert_array_equal(np.asarray(k_plain.golden.mem),
                                  np.asarray(k_mm.golden.mem))
    assert not bool(k_mm.golden.trapped)


def test_byte_cmp_mem_form_lifts_clean():
    """`cmp %cl,(%rax)` — a byte compare whose size comes from the
    register operand, the hot form of compression match loops — must lift
    via the sub-word compare path, not demote (it was 112k of the lzss
    window's 113k demotions)."""
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/lzss_small.c")
    _trace, meta = hd.capture_and_lift(paths)
    st = meta["stats"]
    assert st["lift_rate"] > 0.999, st["opaque_mnemonics"]
    assert "cmp" not in st["opaque_mnemonics"]
    assert st["branches_dropped"] == 0


def test_string_ops_lift_clean():
    """rep movsq/movsl and rep stosq/stosl/stosb — the erms memcpy/memset
    loops glibc leans on (43% of strmix's opaque tail before the string-op
    handlers) — lift exactly on both datapaths: the 32-bit projection and
    the pair-lane 64-bit lift with hi-guarded addresses."""
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.lift64 import lift64

    paths = hd.build_tools("workloads/strops.c")

    _trace, meta = hd.capture_and_lift(paths)
    st = meta["stats"]
    # residual demotions are 64-bit right shifts (documented projection
    # limit) — never the string ops themselves
    assert st["lift_rate"] > 0.95, st["opaque_mnemonics"]
    assert not any("movs" in m or "stos" in m
                   for m in st["opaque_mnemonics"]), st["opaque_mnemonics"]

    _trace64, meta64 = hd.capture_and_lift_to_output(paths, lifter=lift64)
    st64 = meta64["stats"]
    assert st64["lift_rate"] > 0.98, st64["opaque_mnemonics"]
    assert not any("movs" in m or "stos" in m
                   for m in st64["opaque_mnemonics"]), st64["opaque_mnemonics"]


def test_evex_strlen_chain_lifts():
    """The glibc __strlen_evex head (vpxorq zero → mem-form vpcmpeqb→k →
    kmovd → tzcnt) lifts via symbolic vector tracking with the byte-mask
    materialized from replay memory — strmix's lift rate rises from 0.70
    (r4 session 1) to ≥0.93, and the k-mask chain no longer dominates the
    opaque tail."""
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/strmix.c")
    _trace, meta = hd.capture_and_lift(paths)
    st = meta["stats"]
    assert st["lift_rate"] > 0.93, st["opaque_mnemonics"]
    assert st["opaque_mnemonics"].get("kmovd", 0) <= 10
    assert "vpxorq" not in st["opaque_mnemonics"]
    assert st["opaque_mnemonics"].get("tzcnt", 0) <= 4  # 64-bit forms only


def test_implicit_read_keys_reachable_from_own_spelling():
    """Every _IMPLICIT_READS key must be reachable from its own mnemonic
    spelling and from a one-letter size-suffixed form (ADVICE r4: a greedy
    rstrip("bwldq") turned 'call'→'ca', 'mul'→'mu', 'cwd'/'cdq'→'c',
    silently orphaning those entries — their implicit rsp / rax+rdx reads
    never escalated demoted fault coordinates)."""
    from shrewd_tpu.ingest.lift import Inst, Lifter

    lf = Lifter.__new__(Lifter)     # method uses only class attrs

    def reads(mnemonic):
        return lf._demoted_read_set(
            Inst(pc=0x1000, length=2, mnemonic=mnemonic, operands=[],
                 comment_addr=None))

    for key, want in Lifter._IMPLICIT_READS.items():
        assert set(want) <= set(reads(key)), (key, reads(key))
    # one-letter size suffixes resolve to the family
    assert 4 in reads("pushq") and 4 in reads("popq")
    assert {0, 2} <= set(reads("divq")) and {0, 2} <= set(reads("mulq"))
    # the exact spellings the old rstrip orphaned
    assert 4 in reads("call")           # rsp
    assert {0, 2} <= set(reads("mul"))  # rax, rdx
    assert 0 in reads("cwd") and 0 in reads("cdq")
    # AT&T spellings objdump actually emits for the sign-extend family
    assert 0 in reads("cltd") and 0 in reads("cqto") and 0 in reads("cwtd")
    # no false family hit: plain movsd/movslq (2-operand moves) are only
    # string-family reads when the operand list says so (stringish gate) —
    # with a register operand present, no rsi/rdi injection
    from shrewd_tpu.ingest.lift import Operand
    non_string = lf._demoted_read_set(
        Inst(pc=0x1000, length=4, mnemonic="movsd",
             operands=[Operand(kind="reg", reg=3)], comment_addr=None))
    assert 6 not in non_string and 7 not in non_string
