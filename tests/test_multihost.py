"""Real multi-process distribution: 2 local processes join via
jax.distributed (CPU backend), the mesh spans both, and the sharded
campaign's psum'd tally matches a single-process run of the same batch
bit-for-bit (placement invariance).

The dist-gem5-on-localhost posture (SURVEY §4 tier 5): the reference
validates its TCP-barrier multi-node path with N processes on one machine
(``util/dist/gem5-dist.sh``); tools/dist_launch.py is that launcher's
analog and this test drives it end-to-end.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_campaign_matches_single_process():
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dist_launch.py"),
         "--num-processes", "2", "--local-devices", "2",
         "--batch", "128", "--uops", "64", "--port", "47213"],
        capture_output=True, text=True, env=env, timeout=420, cwd=REPO)
    assert r.returncode == 0, r.stdout[-500:] + r.stderr[-500:]
    line = next(ln for ln in r.stdout.splitlines() if ln.startswith("{"))
    res = json.loads(line)
    assert res["ok"], res
    assert res["workers_agree"] and res["matches_single_process"], res
    assert res["global_devices"] == 4
    assert sum(res["tally"]) == 128


def test_killed_worker_no_longer_wedges_survivor_elastic():
    """The ISSUE acceptance criterion: a hard-killed worker in a
    2-process CPU launch must not wedge the survivor.  In elastic mode
    the survivor revokes the dead worker's batch lease (stale heartbeat),
    re-dispatches it on the frozen PRNG keys, and finishes with a tally
    bit-identical to an undisturbed single-process run — where the
    collective mode (and dist-gem5's TCP barrier) would hang forever."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dist_launch.py"),
         "--mode", "elastic", "--num-processes", "2",
         "--local-devices", "2", "--batch", "64", "--uops", "64",
         "--num-batches", "4", "--kill-worker", "1", "--at-batch", "2",
         "--timeout", "300"],
        capture_output=True, text=True, env=env, timeout=420, cwd=REPO)
    assert r.returncode == 0, r.stdout[-800:] + r.stderr[-800:]
    line = next(ln for ln in r.stdout.splitlines() if ln.startswith("{"))
    res = json.loads(line)
    assert res["ok"], res
    assert res["matches_single_process"], res
    assert res["survivors"] == [0]
    assert res["batches_reclaimed"] >= 1, res
    assert res["elastic"]["w0"]["workers_lost"] == 1
