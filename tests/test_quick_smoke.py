"""Quick-tier end-to-end smoke: one tiny campaign through the real
orchestrator, the thing every other quick test only exercises piecewise.
The reference's quick tier runs miniature full configs the same way
(TESTING.md); shapes here are chosen so the whole module stays under ~15 s
on one CPU core, compile included."""

from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.sim.exit_event import ExitEvent
from shrewd_tpu.trace.synth import WorkloadConfig


def test_tiny_campaign_end_to_end():
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="smoke",
            workload=WorkloadConfig(n=64, nphys=32, mem_words=64,
                                    working_set_words=32, seed=3))],
        structures=["regfile"], batch_size=32, target_halfwidth=0.25,
        confidence=0.95, max_trials=64, min_trials=32)
    events = list(Orchestrator(plan).events())
    assert events[-1][0] == ExitEvent.CAMPAIGN_COMPLETE
    (res,) = events[-1][1].values()
    assert res.trials >= 32 and res.tallies.sum() == res.trials
    assert 0.0 <= res.avf <= 1.0
