"""Exec-style tracer (trace/exec_trace.py; reference src/cpu/exetrace.cc)."""

import io

import numpy as np

from shrewd_tpu.isa import uops as U
from shrewd_tpu.trace import exec_trace as X
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import debug


def _trace(n=64, seed=2):
    return generate(WorkloadConfig(n=n, nphys=64, mem_words=256,
                                   working_set_words=64, seed=seed))


def teardown_function(_fn):
    debug.disable("Exec", "ExecResult", "ExecOpClass")


def test_flag_gated_noop():
    tr = _trace()
    buf = io.StringIO()
    assert X.exec_trace(tr, out=buf) == 0
    assert buf.getvalue() == ""


def test_basic_lines():
    tr = _trace()
    debug.enable("Exec")
    buf = io.StringIO()
    n = X.exec_trace(tr, out=buf, count=10)
    lines = buf.getvalue().splitlines()
    assert n == 10 and len(lines) == 10
    assert lines[0].startswith("     0:")


def test_execall_appends_results_and_opclass():
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    tr = _trace(n=32)
    kern = TrialKernel(tr, O3Config(pallas="off"))
    debug.enable("ExecAll")
    buf = io.StringIO()
    X.exec_trace(tr, kern.golden_rec, out=buf)
    text = buf.getvalue()
    assert "IntAlu" in text or "MemRead" in text
    assert "D=0x" in text


def test_disassemble_forms():
    tr = _trace()
    op = np.asarray(tr.opcode).copy()
    op[0] = U.LOAD
    op[1] = U.STORE
    op[2] = U.ADDI
    op[3] = U.NOP
    tr = tr._replace(opcode=op)
    assert X.disassemble(tr, 0).startswith("load")
    assert "[r" in X.disassemble(tr, 1)
    assert X.disassemble(tr, 2).startswith("addi")
    assert X.disassemble(tr, 3) == "nop"


def test_fault_annotation():
    import jax.numpy as jnp

    from shrewd_tpu.models.o3 import Fault, KIND_FU

    tr = _trace(n=16)
    debug.enable("Exec")
    f = Fault(kind=jnp.int32(KIND_FU), cycle=jnp.int32(5),
              entry=jnp.int32(5), bit=jnp.int32(3),
              shadow_u=jnp.float32(1.0))
    buf = io.StringIO()
    X.exec_trace(tr, fault=f, out=buf)
    marked = [ln for ln in buf.getvalue().splitlines() if "<-- fault" in ln]
    assert len(marked) == 1 and marked[0].startswith("     5:")


def test_cli_trace_subcommand(capsys):
    from shrewd_tpu.main import main

    rc = main(["trace", "-n", "8", "--all"])
    assert rc == 0
    out = capsys.readouterr().out
    assert len(out.splitlines()) == 8


class TestPipeview:
    """Pipeline-activity renderer (trace/pipeview.py; the o3-pipeview
    analog over the scoreboard timing model)."""

    def test_rows_render_markers_in_order(self):
        from shrewd_tpu.models.timing import compute_scoreboard
        from shrewd_tpu.trace.pipeview import dump_pipeview

        tr = _trace(n=32)
        sb = compute_scoreboard(tr)
        buf = io.StringIO()
        n = dump_pipeview(tr, sb, out=buf, count=16)
        lines = buf.getvalue().splitlines()
        assert n == 16 and len(lines) == 17      # header + rows
        for ln in lines[1:]:
            body = ln[ln.index("[") + 1:ln.index("]")]
            for a, b in (("D", "I"), ("I", "W"), ("W", "C")):
                if a in body and b in body:
                    assert body.index(a) <= body.index(b), ln

    def test_window_clamps_and_scales(self):
        from shrewd_tpu.models.timing import compute_scoreboard
        from shrewd_tpu.trace.pipeview import dump_pipeview

        tr = _trace(n=64)
        sb = compute_scoreboard(tr)
        buf = io.StringIO()
        assert dump_pipeview(tr, sb, out=buf, start=1000, count=8) == 0
        buf = io.StringIO()
        n = dump_pipeview(tr, sb, out=buf, count=64, max_width=20)
        body = buf.getvalue().splitlines()[1]
        assert n == 64
        assert body.index("]") - body.index("[") <= 22   # compressed

    def test_cli_pipeline_flag(self, capsys):
        from shrewd_tpu.main import main

        rc = main(["trace", "--pipeline", "-n", "6"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "D dispatch" in out
        assert len(out.splitlines()) == 7
