"""CLI end-to-end: ``python -m shrewd_tpu run/resume`` (the m5.main analog,
/root/reference/src/python/m5/main.py:387) — a campaign is reproducible
from its plan JSON alone, artifacts land in --outdir, and resume restores
the checkpointed state."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plan_doc():
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    return CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=64, nphys=32, mem_words=64, working_set_words=32, seed=3))],
        structures=["regfile"], batch_size=128, max_trials=512,
        min_trials=256, target_halfwidth=0.5, checkpoint_every=1).to_dict()


def _run_cli(args, cwd):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PYTEST_CURRENT_TEST", None)
    return subprocess.run([sys.executable, "-m", "shrewd_tpu"] + args,
                          capture_output=True, text=True, env=env,
                          cwd=str(cwd), timeout=420)


def test_run_and_resume(tmp_path):
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(json.dumps(_plan_doc()))
    out = tmp_path / "out"
    r = _run_cli(["run", str(plan_path), "--outdir", str(out),
                  "--debug-flags", "Campaign"], tmp_path)
    assert r.returncode == 0, r.stderr[-800:]
    for art in ("config.json", "stats.txt", "stats.json",
                "campaign_ckpt/campaign.json"):
        assert (out / art).exists(), art
    # the dumped config round-trips into an identical plan (the
    # m5.instantiate reproducibility contract)
    dumped = json.loads((out / "config.json").read_text())
    assert dumped["structures"] == ["regfile"]
    # resume of a finished campaign restores state and runs zero batches
    out2 = tmp_path / "out2"
    r2 = _run_cli(["resume", str(out / "campaign_ckpt"),
                   "--outdir", str(out2)], tmp_path)
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "0 batches" in r2.stderr
    assert (out2 / "stats.txt").exists()


def test_bad_subcommand_fails():
    r = _run_cli(["frobnicate"], REPO)
    assert r.returncode != 0
