"""graftlint static analysis (shrewd_tpu/analysis/, tools/graftlint.py).

The contracts under test, per the ISSUE acceptance criteria:

- every AST rule fires on a positive fixture and stays quiet on the
  negative one (and the waiver syntax covers, but only WITH a reason);
- the repo itself lints clean (the CI gate's precondition);
- the jaxpr auditor certifies the pipelined interval step at EXACTLY one
  device→host transfer and rejects a deliberately broken step (hidden
  ``debug_callback`` → 2 transfers, side-effect violation);
- a strict-mode auditor installed on the executable cache REFUSES to
  admit a violating executable (``exec_cache.AdmissionError``) on both
  the AOT-admission and first-eager-call paths;
- the ``[tool.graftlint]`` pyproject block parses (TOML subset — the
  container has no tomllib).
"""

import os
import textwrap

import numpy as np
import pytest

from shrewd_tpu.analysis import (GraftlintConfig, ast_lint, lint_tree,
                                 load_config)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- AST rule fixtures ------------------------------------------------------

def _lint_src(tmp_path, src: str, cfg: GraftlintConfig | None = None,
              rel: str = "shrewd_tpu/parallel/campaign.py"):
    """Lint ``src`` as if it lived at ``rel`` in the repo."""
    cfg = cfg if cfg is not None else GraftlintConfig()
    path = tmp_path / "fixture.py"
    path.write_text(textwrap.dedent(src))
    return ast_lint.lint_file(str(path), rel, cfg)


def _rules(findings, waived=False):
    return sorted({f.rule for f in findings if f.waived == waived})


def test_gl101_bare_jit_positive_and_negative(tmp_path):
    bad = _lint_src(tmp_path, """
        import jax
        step = jax.jit(lambda x: x + 1)
    """)
    assert _rules(bad) == ["GL101"]
    # routed through the cache (builder fn / router call): quiet
    good = _lint_src(tmp_path, """
        import jax
        from shrewd_tpu.parallel import exec_cache

        def build_step():
            return jax.jit(lambda x: x + 1)

        step = exec_cache.cache().get(("k",), None,
                                      lambda: jax.jit(lambda x: x))
    """)
    assert _rules(good) == []
    # partial(jax.jit, ...) decorators are the instance-keyed offender
    bad2 = _lint_src(tmp_path, """
        from functools import partial
        import jax

        class K:
            @partial(jax.jit, static_argnums=0)
            def step(self, x):
                return x
    """)
    assert _rules(bad2) == ["GL101"]
    # out-of-scope module: rule does not apply
    off = _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n",
                    rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []


def test_gl102_wall_clock_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/chaos.py"
    # chaos.py is BOTH wall-clock-gated (GL102) and obs-clock-gated
    # (GL106) since the obs PR: a wall-clock read trips both rules
    bad = _lint_src(tmp_path, """
        import time
        def should_fire(batch_id):
            return time.time() % 2 < 1
    """, rel=rel)
    assert _rules(bad) == ["GL102", "GL106"]
    # sleeps are not schedule-bearing reads (and not clock reads either)
    good = _lint_src(tmp_path, """
        import time
        def wedge():
            time.sleep(0.1)
    """, rel=rel)
    assert _rules(good) == []
    # a monotonic perf ledger is GL102-clean (not a wall-clock read) but
    # must still route through the obs.clock seam in instrumented modules
    mono = _lint_src(tmp_path, """
        import time
        def ledger():
            return time.monotonic()
    """, rel=rel)
    assert _rules(mono) == ["GL106"]


def test_gl103_raw_write_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/campaign/orchestrator.py"
    bad = _lint_src(tmp_path, """
        import json
        def save(doc, path):
            with open(path, "w") as f:
                json.dump(doc, f)
    """, rel=rel)
    assert _rules(bad) == ["GL103"]
    good = _lint_src(tmp_path, """
        from shrewd_tpu.resilience import write_json_atomic
        def save(doc, path):
            write_json_atomic(path, doc)
    """, rel=rel)
    assert _rules(good) == []
    # the sanctioned implementation itself is exempt by name
    impl = _lint_src(tmp_path, """
        import json
        def write_json_atomic(path, doc):
            with open(path + ".tmp", "w") as f:
                json.dump(doc, f)
    """, rel="shrewd_tpu/resilience.py")
    assert _rules(impl) == []


def test_gl104_key_reuse_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/models/o3.py"
    bad = _lint_src(tmp_path, """
        import jax
        def sample(key):
            ka, kb = jax.random.split(key)
            return jax.random.uniform(key, (4,))   # consumed key reused
    """, rel=rel)
    assert _rules(bad) == ["GL104"]
    good = _lint_src(tmp_path, """
        import jax
        def sample(key):
            ka, kb = jax.random.split(key)
            return jax.random.uniform(ka, (4,)) + jax.random.uniform(kb, (4,))
        def derive(root):
            k1 = jax.random.fold_in(root, 1)       # fold_in is sanctioned
            k2 = jax.random.fold_in(root, 2)
            return k1, k2
        def rebind(key):
            key = jax.random.split(key, 1)[0]      # consume-and-rebind
            return jax.random.uniform(key, ())
    """, rel=rel)
    assert _rules(good) == []


def test_gl105_key_genesis_positive_and_negative(tmp_path):
    bad = _lint_src(tmp_path, """
        import jax
        k = jax.random.PRNGKey(0)
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(bad) == ["GL105"]
    allowed = _lint_src(tmp_path, """
        import jax
        def campaign_key(seed):
            return jax.random.key(seed)
    """, rel="shrewd_tpu/utils/prng.py")
    assert _rules(allowed) == []


def test_gl106_clock_seam_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/parallel/pipeline.py"
    # every direct clock read (wall, monotonic, perf_counter, _ns
    # variants) must route through obs.clock in instrumented modules
    bad = _lint_src(tmp_path, """
        import time
        def ledger():
            return time.monotonic(), time.perf_counter_ns()
    """, rel=rel)
    assert _rules(bad) == ["GL106"]
    # the sanctioned seam is quiet, and sleep is not a read
    good = _lint_src(tmp_path, """
        import time
        from shrewd_tpu.obs import clock
        def ledger():
            time.sleep(0.01)
            return clock.monotonic(), clock.now()
    """, rel=rel)
    assert _rules(good) == []
    # out-of-scope module: rule does not apply
    off = _lint_src(tmp_path, """
        import time
        t = time.monotonic()
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []
    # waiverable with a reason, like every other rule
    waived = _lint_src(tmp_path, """
        import time
        # graftlint: allow-clock -- fixture: sanctioned-seam bootstrap
        t = time.monotonic()
    """, rel=rel)
    assert _rules(waived) == [] and _rules(waived, waived=True) == ["GL106"]


def test_gl106_obs_clock_is_the_one_sanctioned_seam():
    """obs/clock.py itself is deliberately NOT clock-gated (it IS the
    seam) and carries the one reasoned GL102 waiver for its wall-clock
    read; the other obs modules are fully gated."""
    cfg = load_config(REPO_ROOT)
    assert "shrewd_tpu/obs/clock.py" not in cfg.clock_modules
    assert "shrewd_tpu/obs/trace.py" in cfg.clock_modules
    assert "shrewd_tpu/obs/trace.py" in cfg.deterministic_modules
    assert "shrewd_tpu/obs/metrics.py" in cfg.checkpoint_modules
    report = lint_tree(REPO_ROOT, cfg)
    seam = [f for f in report.waivers
            if f.path.endswith("obs/clock.py") and f.rule == "GL102"]
    assert len(seam) == 1 and "sanctioned" in seam[0].waiver_reason


def test_waiver_covers_but_only_with_reason(tmp_path):
    waived = _lint_src(tmp_path, """
        import jax
        # graftlint: allow-jit -- fixture: identity is process-wide here
        step = jax.jit(lambda x: x)
    """)
    assert _rules(waived) == [] and _rules(waived, waived=True) == ["GL101"]
    assert "process-wide" in [f for f in waived if f.waived][0].waiver_reason
    # a reasonless waiver is itself a violation, not an off switch
    reasonless = _lint_src(tmp_path, """
        import jax
        # graftlint: allow-jit
        step = jax.jit(lambda x: x)
    """)
    assert len(reasonless) == 1 and not reasonless[0].waived
    assert "missing its reason" in reasonless[0].msg


def test_severity_warn_and_off(tmp_path):
    cfg = GraftlintConfig()
    cfg.severity["GL101"] = "warn"
    warn = _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n", cfg=cfg)
    assert warn and warn[0].severity == "warn"
    cfg.severity["GL101"] = "off"
    assert _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n",
                     cfg=cfg) == []


def test_repo_lints_clean_with_reasoned_waivers():
    """The CI gate's precondition: zero unwaived violations across the
    package, and every waiver carries its reason."""
    report = lint_tree(REPO_ROOT, load_config(REPO_ROOT))
    assert report.violations == [], [str(f) for f in report.violations]
    assert report.waivers, "the known waived sites should be visible"
    for f in report.waivers:
        assert f.waiver_reason


def test_pyproject_graftlint_block_parses():
    cfg = load_config(REPO_ROOT)
    assert cfg.transfer_budget == 1
    assert "shrewd_tpu/parallel/campaign.py" in cfg.jit_modules
    assert "shrewd_tpu/chaos.py" in cfg.deterministic_modules
    assert cfg.rule_severity("GL101") == "error"


# --- jaxpr auditor ----------------------------------------------------------

@pytest.fixture(scope="module")
def probe_campaign():
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    tr = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                 working_set_words=32, seed=7))
    kernel = TrialKernel(tr, O3Config(replay_kernel="hybrid"))
    return ShardedCampaign(kernel, make_mesh(), "regfile")


def test_interval_step_certifies_at_exactly_one_transfer(probe_campaign):
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import _interval_args

    cert = audit_callable(probe_campaign._build_interval_step(4),
                          _interval_args(probe_campaign, 4, 32),
                          kind="interval", transfer_budget=1)
    assert cert["ok"], cert["violations"]
    assert cert["transfers"] == 1
    assert cert["callbacks"] == {}
    # the randomness that IS there is the frozen-key threefry lineage
    assert set(cert["rng"]) <= set(
        __import__("shrewd_tpu.analysis", fromlist=["x"]).ALLOWED_RNG)


def test_broken_interval_step_is_rejected(probe_campaign):
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import (_interval_args,
                                             violating_interval_step)

    cert = audit_callable(violating_interval_step(probe_campaign, 4),
                          _interval_args(probe_campaign, 4, 32),
                          kind="interval", transfer_budget=1)
    assert not cert["ok"]
    assert cert["transfers"] == 2
    assert any("debug_callback" in v for v in cert["violations"])
    assert any("transfer budget" in v for v in cert["violations"])


def test_forbidden_rng_and_undeclared_donation_detected():
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.analysis import audit_callable

    def stateful_rng(x):
        key = jnp.zeros((2,), jnp.uint32)
        bits, _ = jax.lax.rng_bit_generator(key, (4,), dtype=jnp.uint32)
        return x + bits.sum()

    cert = audit_callable(stateful_rng, (jnp.uint32(0),), check_hlo=False)
    assert not cert["ok"]
    assert any("rng_bit_generator" in v for v in cert["violations"])

    donating = jax.jit(lambda x, y: x + y, donate_argnums=(0,))
    cert = audit_callable(donating, (jnp.ones(4), jnp.ones(4)))
    assert any("donation" in v for v in cert["violations"])
    assert cert["donated_args"] == [0]
    # declared donation is consistent, not a violation
    cert_ok = audit_callable(donating, (jnp.ones(4), jnp.ones(4)),
                             declared_donations=(0,))
    assert cert_ok["ok"], cert_ok["violations"]


# --- strict-mode executable-cache admission ---------------------------------

def _broken_build():
    import jax

    def fn(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    return jax.jit(fn)


def test_strict_auditor_refuses_admission_aot_and_first_call():
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        owner = object()
        args = (jnp.ones(4),)
        # AOT path: refused at ADMISSION, before compile
        with pytest.raises(exec_cache.AdmissionError) as ei:
            cache.get_aot(("interval", "broken"), owner, _broken_build,
                          args)
        assert "debug_callback" in str(ei.value)
        assert cache.refused == 1
        # plain path: admitted lazily, refused on the first eager call,
        # and the refusal evicts the entry (nothing stays admitted)
        fn = cache.get(("step", "broken"), owner, _broken_build)
        with pytest.raises(exec_cache.AdmissionError):
            fn(*args)
        assert ("step", "broken") not in cache._entries
        # a clean step admits and is certified, content-keyed
        import jax
        good = cache.get(("step", "good"), owner,
                         lambda: jax.jit(lambda x: x + 1))
        assert float(good(jnp.ones(1))[0]) == 2.0
        assert exec_cache.key_digest(("step", "good")) in cache.certificates
        assert cache.stats()["certified"] == 1
    finally:
        exec_cache.clear_auditor()


def test_warn_auditor_certifies_without_refusing():
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    auditor = StepAuditor(transfer_budget=1, strict=False)
    exec_cache.install_auditor(auditor)
    try:
        fn = cache.get(("step", "warn-broken"), object(), _broken_build)
        out = fn(jnp.ones(2))                  # audited, NOT refused
        np.testing.assert_array_equal(np.asarray(out), [2.0, 2.0])
        assert auditor.audited == 1 and auditor.failed == 1
        cert = cache.certificates[
            exec_cache.key_digest(("step", "warn-broken"))]
        assert not cert["ok"]
        _ = jax
    finally:
        exec_cache.clear_auditor()


def test_strict_refusal_is_sticky_on_held_wrapper():
    """A refused executable STAYS refused: holders that cached the
    wrapper (kernel._shared_jits, chunk fns) and catch the first error
    must not execute the refused step on a later call."""
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        fn = cache.get(("step", "sticky"), object(), _broken_build)
        for _ in range(2):                 # second call: no re-audit path
            with pytest.raises(exec_cache.AdmissionError):
                fn(jnp.ones(2))
    finally:
        exec_cache.clear_auditor()


def test_unauditable_executable_admits_with_error_certificate():
    """An auditor that merely CRASHES proves nothing: the executable
    admits (even under strict), and the certificate records the audit
    error instead of counting as certified — a warn-mode run must never
    abort because the auditor couldn't analyze something."""
    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        # a host-side callable make_jaxpr cannot trace (string argument)
        fn = cache.get(("step", "host"), object(),
                       lambda: (lambda name: f"hello {name}"))
        assert fn("world") == "hello world"      # admitted, not refused
        cert = cache.certificates[exec_cache.key_digest(("step", "host"))]
        assert not cert["ok"] and "audit_error" in cert
        assert cache.refused == 0
    finally:
        exec_cache.clear_auditor()


def test_warn_does_not_downgrade_installed_strict_auditor():
    """Certification is process-wide: a second campaign asking for
    'warn' must not silently disarm a strict posture already installed
    (the stricter wins; explicit disarm is the CLI's --certify off)."""
    from shrewd_tpu.analysis import StepAuditor, install_step_auditor
    from shrewd_tpu.parallel import exec_cache

    strict = StepAuditor(transfer_budget=1, strict=True)
    exec_cache.install_auditor(strict)
    try:
        assert install_step_auditor("warn") is strict
        assert exec_cache.current_auditor() is strict
        assert install_step_auditor("off") is None
        assert exec_cache.current_auditor() is strict   # off: no disarm
    finally:
        exec_cache.clear_auditor()


def test_orchestrator_strict_certification_end_to_end():
    """plan.analysis.certify='strict' on a real (tiny) campaign: every
    admitted step certifies, nothing is refused, and the tallies equal
    the uncertified run bit-for-bit (auditing is observation only)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.parallel import exec_cache
    from shrewd_tpu.sim.exit_event import ExitEvent
    from shrewd_tpu.trace.synth import WorkloadConfig

    def plan(certify):
        p = CampaignPlan(
            simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
                n=64, nphys=32, mem_words=64, working_set_words=32,
                seed=3))],
            structures=["regfile"], batch_size=32, target_halfwidth=0.5,
            max_trials=64, min_trials=64)
        p.integrity.canary_trials = 0
        p.integrity.audit_rate = 0.0
        p.resilience.backoff_base = 0.0
        p.analysis.certify = certify
        return p

    def run(p):
        orch = Orchestrator(p)
        events = list(orch.events())
        assert events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
        return orch, dict(events[-1][1])

    try:
        _, clean = run(plan("off"))
        # certification happens at ADMISSION: entries already compiled by
        # the uncertified run are cache hits and stay uncertified, so
        # drop them — the strict run must re-admit everything
        exec_cache.cache().clear()
        orch, certified = run(plan("strict"))
        for key in clean:
            np.testing.assert_array_equal(clean[key].tallies,
                                          certified[key].tallies)
        assert orch.auditor is not None
        assert orch.auditor.audited > 0 and orch.auditor.failed == 0
        assert exec_cache.cache().certificates      # evidence persisted
        assert exec_cache.cache().refused == 0
    finally:
        exec_cache.clear_auditor()
