"""graftlint static analysis (shrewd_tpu/analysis/, tools/graftlint.py).

The contracts under test, per the ISSUE acceptance criteria:

- every AST rule fires on a positive fixture and stays quiet on the
  negative one (and the waiver syntax covers, but only WITH a reason);
- the repo itself lints clean (the CI gate's precondition);
- the jaxpr auditor certifies the pipelined interval step at EXACTLY one
  device→host transfer and rejects a deliberately broken step (hidden
  ``debug_callback`` → 2 transfers, side-effect violation);
- a strict-mode auditor installed on the executable cache REFUSES to
  admit a violating executable (``exec_cache.AdmissionError``) on both
  the AOT-admission and first-eager-call paths;
- the ``[tool.graftlint]`` pyproject block parses (TOML subset — the
  container has no tomllib).
"""

import os
import textwrap

import numpy as np
import pytest

from shrewd_tpu.analysis import (GraftlintConfig, ast_lint, lint_tree,
                                 load_config)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- AST rule fixtures ------------------------------------------------------

def _lint_src(tmp_path, src: str, cfg: GraftlintConfig | None = None,
              rel: str = "shrewd_tpu/parallel/campaign.py"):
    """Lint ``src`` as if it lived at ``rel`` in the repo."""
    cfg = cfg if cfg is not None else GraftlintConfig()
    path = tmp_path / "fixture.py"
    path.write_text(textwrap.dedent(src))
    return ast_lint.lint_file(str(path), rel, cfg)


def _rules(findings, waived=False):
    return sorted({f.rule for f in findings if f.waived == waived})


def test_gl101_bare_jit_positive_and_negative(tmp_path):
    bad = _lint_src(tmp_path, """
        import jax
        step = jax.jit(lambda x: x + 1)
    """)
    assert _rules(bad) == ["GL101"]
    # routed through the cache (builder fn / router call): quiet
    good = _lint_src(tmp_path, """
        import jax
        from shrewd_tpu.parallel import exec_cache

        def build_step():
            return jax.jit(lambda x: x + 1)

        step = exec_cache.cache().get(("k",), None,
                                      lambda: jax.jit(lambda x: x))
    """)
    assert _rules(good) == []
    # partial(jax.jit, ...) decorators are the instance-keyed offender
    bad2 = _lint_src(tmp_path, """
        from functools import partial
        import jax

        class K:
            @partial(jax.jit, static_argnums=0)
            def step(self, x):
                return x
    """)
    assert _rules(bad2) == ["GL101"]
    # out-of-scope module: rule does not apply
    off = _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n",
                    rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []


def test_gl102_wall_clock_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/chaos.py"
    # chaos.py is BOTH wall-clock-gated (GL102) and obs-clock-gated
    # (GL106) since the obs PR: a wall-clock read trips both rules
    bad = _lint_src(tmp_path, """
        import time
        def should_fire(batch_id):
            return time.time() % 2 < 1
    """, rel=rel)
    assert _rules(bad) == ["GL102", "GL106"]
    # sleeps are not schedule-bearing reads (and not clock reads either)
    good = _lint_src(tmp_path, """
        import time
        def wedge():
            time.sleep(0.1)
    """, rel=rel)
    assert _rules(good) == []
    # a monotonic perf ledger is GL102-clean (not a wall-clock read) but
    # must still route through the obs.clock seam in instrumented modules
    mono = _lint_src(tmp_path, """
        import time
        def ledger():
            return time.monotonic()
    """, rel=rel)
    assert _rules(mono) == ["GL106"]


def test_gl103_raw_write_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/campaign/orchestrator.py"
    bad = _lint_src(tmp_path, """
        import json
        def save(doc, path):
            with open(path, "w") as f:
                json.dump(doc, f)
    """, rel=rel)
    assert _rules(bad) == ["GL103"]
    good = _lint_src(tmp_path, """
        from shrewd_tpu.resilience import write_json_atomic
        def save(doc, path):
            write_json_atomic(path, doc)
    """, rel=rel)
    assert _rules(good) == []
    # the sanctioned implementation itself is exempt by name
    impl = _lint_src(tmp_path, """
        import json
        def write_json_atomic(path, doc):
            with open(path + ".tmp", "w") as f:
                json.dump(doc, f)
    """, rel="shrewd_tpu/resilience.py")
    assert _rules(impl) == []


def test_gl104_key_reuse_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/models/o3.py"
    bad = _lint_src(tmp_path, """
        import jax
        def sample(key):
            ka, kb = jax.random.split(key)
            return jax.random.uniform(key, (4,))   # consumed key reused
    """, rel=rel)
    assert _rules(bad) == ["GL104"]
    good = _lint_src(tmp_path, """
        import jax
        def sample(key):
            ka, kb = jax.random.split(key)
            return jax.random.uniform(ka, (4,)) + jax.random.uniform(kb, (4,))
        def derive(root):
            k1 = jax.random.fold_in(root, 1)       # fold_in is sanctioned
            k2 = jax.random.fold_in(root, 2)
            return k1, k2
        def rebind(key):
            key = jax.random.split(key, 1)[0]      # consume-and-rebind
            return jax.random.uniform(key, ())
    """, rel=rel)
    assert _rules(good) == []


def test_gl105_key_genesis_positive_and_negative(tmp_path):
    bad = _lint_src(tmp_path, """
        import jax
        k = jax.random.PRNGKey(0)
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(bad) == ["GL105"]
    allowed = _lint_src(tmp_path, """
        import jax
        def campaign_key(seed):
            return jax.random.key(seed)
    """, rel="shrewd_tpu/utils/prng.py")
    assert _rules(allowed) == []


def test_gl106_clock_seam_positive_and_negative(tmp_path):
    rel = "shrewd_tpu/parallel/pipeline.py"
    # every direct clock read (wall, monotonic, perf_counter, _ns
    # variants) must route through obs.clock in instrumented modules
    bad = _lint_src(tmp_path, """
        import time
        def ledger():
            return time.monotonic(), time.perf_counter_ns()
    """, rel=rel)
    assert _rules(bad) == ["GL106"]
    # the sanctioned seam is quiet, and sleep is not a read
    good = _lint_src(tmp_path, """
        import time
        from shrewd_tpu.obs import clock
        def ledger():
            time.sleep(0.01)
            return clock.monotonic(), clock.now()
    """, rel=rel)
    assert _rules(good) == []
    # out-of-scope module: rule does not apply
    off = _lint_src(tmp_path, """
        import time
        t = time.monotonic()
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []
    # waiverable with a reason, like every other rule
    waived = _lint_src(tmp_path, """
        import time
        # graftlint: allow-clock -- fixture: sanctioned-seam bootstrap
        t = time.monotonic()
    """, rel=rel)
    assert _rules(waived) == [] and _rules(waived, waived=True) == ["GL106"]


def test_gl106_obs_clock_is_the_one_sanctioned_seam():
    """obs/clock.py itself is deliberately NOT clock-gated (it IS the
    seam) and carries the one reasoned GL102 waiver for its wall-clock
    read; the other obs modules are fully gated."""
    cfg = load_config(REPO_ROOT)
    assert "shrewd_tpu/obs/clock.py" not in cfg.clock_modules
    assert "shrewd_tpu/obs/trace.py" in cfg.clock_modules
    assert "shrewd_tpu/obs/trace.py" in cfg.deterministic_modules
    assert "shrewd_tpu/obs/metrics.py" in cfg.checkpoint_modules
    report = lint_tree(REPO_ROOT, cfg)
    seam = [f for f in report.waivers
            if f.path.endswith("obs/clock.py") and f.rule == "GL102"]
    assert len(seam) == 1 and "sanctioned" in seam[0].waiver_reason


def test_waiver_covers_but_only_with_reason(tmp_path):
    waived = _lint_src(tmp_path, """
        import jax
        # graftlint: allow-jit -- fixture: identity is process-wide here
        step = jax.jit(lambda x: x)
    """)
    assert _rules(waived) == [] and _rules(waived, waived=True) == ["GL101"]
    assert "process-wide" in [f for f in waived if f.waived][0].waiver_reason
    # a reasonless waiver is itself a violation, not an off switch
    reasonless = _lint_src(tmp_path, """
        import jax
        # graftlint: allow-jit
        step = jax.jit(lambda x: x)
    """)
    assert len(reasonless) == 1 and not reasonless[0].waived
    assert "missing its reason" in reasonless[0].msg


def test_severity_warn_and_off(tmp_path):
    cfg = GraftlintConfig()
    cfg.severity["GL101"] = "warn"
    warn = _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n", cfg=cfg)
    assert warn and warn[0].severity == "warn"
    cfg.severity["GL101"] = "off"
    assert _lint_src(tmp_path, "import jax\nf = jax.jit(abs)\n",
                     cfg=cfg) == []


def test_off_rule_keeps_waivers_live_and_beats_overrides(tmp_path):
    """Turning a rule off must not (1) rot its waivers into GL205 stale
    findings — re-enabling the rule needs them back — or (2) leak
    findings whose call site passes an explicit severity override."""
    from shrewd_tpu.analysis import replay_lint
    from shrewd_tpu.analysis.ast_lint import (_run_file_passes,
                                              stale_waivers)

    cfg = GraftlintConfig()
    cfg.severity["GL101"] = "off"
    fl = _file_lint(tmp_path, """
        import jax
        # graftlint: allow-jit -- fixture: live waiver under an off rule
        step = jax.jit(lambda x: x)
    """, "shrewd_tpu/parallel/campaign.py", cfg)
    _run_file_passes(fl, cfg)
    assert fl.findings == []                  # off: nothing reported
    assert stale_waivers(fl) == []            # ...and nothing rots
    # an explicit severity= at the call site must not resurrect an off
    # rule (GL202's dead-arm warning is the one override user)
    cfg2 = GraftlintConfig()
    cfg2.severity["GL202"] = "off"
    fl2 = _file_lint(tmp_path, """
        class S:
            def act(self):
                self._jlog("admit", {})

            def _apply_record(self, r):
                kind = r.get("kind")
                if kind in ("admit", "ghost"):
                    return
    """, SCHED_REL, cfg2)
    replay_lint.check_journal_exhaustive([fl2], cfg2)
    assert fl2.findings == []


def test_repo_lints_clean_with_reasoned_waivers():
    """The CI gate's precondition: zero unwaived violations across the
    package, and every waiver carries its reason."""
    report = lint_tree(REPO_ROOT, load_config(REPO_ROOT))
    assert report.violations == [], [str(f) for f in report.violations]
    assert report.waivers, "the known waived sites should be visible"
    for f in report.waivers:
        assert f.waiver_reason


def test_pyproject_graftlint_block_parses():
    cfg = load_config(REPO_ROOT)
    assert cfg.transfer_budget == 1
    assert "shrewd_tpu/parallel/campaign.py" in cfg.jit_modules
    assert "shrewd_tpu/chaos.py" in cfg.deterministic_modules
    assert cfg.rule_severity("GL101") == "error"


# --- GL2xx: crash/replay-safety (analysis/replay_lint.py) -------------------

def _file_lint(tmp_path, src: str, rel: str,
               cfg: GraftlintConfig | None = None):
    """A ready-to-pass _FileLint over fixture source at a virtual repo
    path (the GL2xx passes and the stale-waiver audit consume the
    object, not just its findings)."""
    from shrewd_tpu.analysis.ast_lint import _FileLint

    cfg = cfg if cfg is not None else GraftlintConfig()
    path = tmp_path / (rel.replace("/", "+") + ".fixture.py")
    path.write_text(textwrap.dedent(src))
    return _FileLint(str(path), rel, cfg)


SCHED_REL = "shrewd_tpu/service/scheduler.py"


def test_gl201_journal_before_mutate_positive_and_negative(tmp_path):
    # mutation BEFORE the journal call: the WAL contract inverted
    bad = _lint_src(tmp_path, """
        class S:
            def finish(self, t):
                t.status = "complete"
                self._jlog("status", {"status": t.status})
    """, rel=SCHED_REL)
    assert _rules(bad) == ["GL201"]
    # journal-first is quiet, straight-line or branchy
    good = _lint_src(tmp_path, """
        class S:
            def finish(self, t, rc):
                status = "aborted" if rc else "complete"
                self._jlog("status", {"status": status})
                t.status = status
                t.trials = 0
    """, rel=SCHED_REL)
    assert _rules(good) == []
    # a branch that can SKIP the journal call does not dominate
    branchy = _lint_src(tmp_path, """
        class S:
            def finish(self, t, loud):
                if loud:
                    self._jlog("status", {})
                t.status = "complete"
    """, rel=SCHED_REL)
    assert _rules(branchy) == ["GL201"]
    # an early-return arm above an unconditional journal stays dominated
    early = _lint_src(tmp_path, """
        class S:
            def revoke(self, t, reason):
                if t.revoked:
                    return False
                self._jlog("revoke", {"reason": reason})
                t.revoked = reason
                return True
    """, rel=SCHED_REL)
    assert _rules(early) == []


def test_gl201_exemptions_and_waiver(tmp_path):
    # constructors and the replay path are exempt: they must NOT journal
    exempt = _lint_src(tmp_path, """
        class T:
            def __init__(self):
                self.status = "queued"

        class S:
            def _apply_record(self, t, r):
                t.status = r["status"]
    """, rel=SCHED_REL)
    assert _rules(exempt) == []
    # out-of-scope module: the rule does not apply
    off = _lint_src(tmp_path, """
        def f(t):
            t.status = "x"
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []
    # waiverable with a reason, like every other rule
    waived = _lint_src(tmp_path, """
        class S:
            def fixup(self, t):
                # graftlint: allow-journal-before-mutate -- fixture:
                # in-memory scratch copy, never journaled
                t.status = "x"
    """, rel=SCHED_REL)
    assert _rules(waived) == [] and _rules(waived, waived=True) == ["GL201"]
    # reads are not mutations: subscript KEYS and rvalues stay quiet
    reads = _lint_src(tmp_path, """
        class S:
            def _by_status(self, out, t):
                out[t.status] = out.get(t.status, 0) + 1
                return t.status
    """, rel=SCHED_REL)
    assert _rules(reads) == []


def test_gl202_exhaustiveness_positive_and_negative(tmp_path):
    from shrewd_tpu.analysis import replay_lint

    cfg = GraftlintConfig()
    # 'orphan' is appended but the dispatch never handles it
    fl = _file_lint(tmp_path, """
        class S:
            def act(self):
                self._jlog("admit", {})
                self._jlog("orphan", {})

            def _apply_record(self, r):
                kind = r.get("kind")
                if kind == "admit":
                    return
    """, SCHED_REL, cfg)
    replay_lint.check_journal_exhaustive([fl], cfg)
    errs = [f for f in fl.findings if not f.waived
            and f.severity == "error"]
    assert [f.rule for f in errs] == ["GL202"]
    assert "'orphan'" in errs[0].msg
    # a dead dispatch arm is a warning, not an error
    warns = [f for f in fl.findings if f.severity == "warn"]
    assert warns == []
    fl2 = _file_lint(tmp_path, """
        class S:
            def act(self):
                self._jlog("admit", {})

            def _apply_record(self, r):
                kind = r.get("kind")
                if kind in ("admit", "ghost"):
                    return
    """, SCHED_REL, cfg)
    replay_lint.check_journal_exhaustive([fl2], cfg)
    assert [f.rule for f in fl2.findings
            if f.severity == "warn"] == ["GL202"]
    # field probes like '"rc" in r' must NOT read as handled kinds
    assert replay_lint._handled_kinds(
        fl2.tree.body[0].body[1]) == {"admit", "ghost"}


def test_gl202_no_dispatch_is_an_error(tmp_path):
    from shrewd_tpu.analysis import replay_lint

    cfg = GraftlintConfig()
    fl = _file_lint(tmp_path, """
        class S:
            def act(self):
                self._jlog("admit", {})
    """, SCHED_REL, cfg)
    replay_lint.check_journal_exhaustive([fl], cfg)
    assert [f.rule for f in fl.findings] == ["GL202"]
    assert "no replay dispatch" in fl.findings[0].msg


def test_gl203_fsync_before_rename(tmp_path):
    rel = "shrewd_tpu/service/journal.py"
    bad = _lint_src(tmp_path, """
        import os
        def compact(path):
            with open(path + ".tmp", "w") as f:
                f.write("")
            os.replace(path + ".tmp", path)
    """, rel=rel)
    assert _rules(bad) == ["GL203"]
    good = _lint_src(tmp_path, """
        import os
        def compact(path):
            with open(path + ".tmp", "w") as f:
                f.flush()
                os.fsync(f.fileno())
            os.replace(path + ".tmp", path)
    """, rel=rel)
    assert _rules(good) == []
    # fsync in only ONE branch does not dominate
    branchy = _lint_src(tmp_path, """
        import os
        def compact(path, sync):
            if sync:
                os.fsync(0)
            os.rename(path + ".tmp", path)
    """, rel=rel)
    assert _rules(branchy) == ["GL203"]


def test_gl203_recovery_read_raw_write(tmp_path):
    # the same module both recovers from fleet.json and writes it raw:
    # the crash surface itself can tear
    rel = SCHED_REL
    bad = _lint_src(tmp_path, """
        import json, os
        def recover(outdir):
            with open(os.path.join(outdir, "fleet.json")) as f:
                return json.load(f)
        def save(outdir, doc):
            with open(os.path.join(outdir, "fleet.json"), "w") as f:
                f.write("x")
    """, rel=rel)
    assert "GL203" in _rules(bad)
    # routed through the atomic writer (no raw open of the artifact)
    good = _lint_src(tmp_path, """
        import json, os
        from shrewd_tpu.resilience import write_json_atomic
        def recover(outdir):
            with open(os.path.join(outdir, "fleet.json")) as f:
                return json.load(f)
        def save(outdir, doc):
            write_json_atomic(os.path.join(outdir, "fleet.json"), doc)
    """, rel=rel)
    assert "GL203" not in _rules(good)
    # a non-recovery artifact may be written raw (GL103 scoping aside)
    unrelated = _lint_src(tmp_path, """
        import json, os
        def recover(outdir):
            with open(os.path.join(outdir, "fleet.json")) as f:
                return json.load(f)
        def save(outdir, doc):
            with open(os.path.join(outdir, "notes.txt"), "w") as f:
                f.write("x")
    """, rel=rel)
    assert "GL203" not in _rules(unrelated)


def test_gl204_best_effort_guard(tmp_path):
    rel = SCHED_REL
    bad = _lint_src(tmp_path, """
        from shrewd_tpu.obs import trace as obs_trace
        def quarantine(outdir):
            obs_trace.flight_dump(outdir, "why")
    """, rel=rel)
    assert _rules(bad) == ["GL204"]
    good = _lint_src(tmp_path, """
        from shrewd_tpu.obs import trace as obs_trace
        def quarantine(outdir):
            try:
                obs_trace.flight_dump(outdir, "why")
            except Exception:
                pass
    """, rel=rel)
    assert _rules(good) == []
    # a narrow handler is not a guard — the seam can still take the
    # fleet down with anything it did not anticipate
    narrow = _lint_src(tmp_path, """
        from shrewd_tpu.obs import trace as obs_trace
        def quarantine(outdir):
            try:
                obs_trace.flight_dump(outdir, "why")
            except OSError:
                pass
    """, rel=rel)
    assert _rules(narrow) == ["GL204"]
    # out-of-scope module: quiet
    off = _lint_src(tmp_path, """
        from shrewd_tpu.obs import trace as obs_trace
        def f(outdir):
            obs_trace.flight_dump(outdir, "why")
    """, rel="shrewd_tpu/models/o3.py")
    assert _rules(off) == []


# --- stale-waiver audit (GL205) ---------------------------------------------

def test_stale_waiver_detected_and_live_waiver_not(tmp_path):
    from shrewd_tpu.analysis.ast_lint import (_run_file_passes,
                                              stale_waivers)

    cfg = GraftlintConfig()
    fl = _file_lint(tmp_path, """
        import jax
        # graftlint: allow-jit -- fixture: a LIVE waiver (jit below)
        step = jax.jit(lambda x: x)
        # graftlint: allow-jit -- fixture: STALE (nothing to waive here)
        plain = 1
    """, "shrewd_tpu/parallel/campaign.py", cfg)
    _run_file_passes(fl, cfg)
    stale = stale_waivers(fl)
    assert [f.rule for f in stale] == ["GL205"]
    assert stale[0].line == 5 and "stale waiver" in stale[0].msg
    # the live waiver was consumed, not reported
    assert [f.rule for f in fl.findings if f.waived] == ["GL101"]


def test_repo_has_no_stale_waivers():
    """The --audit-waivers CI gate's precondition: every waiver in the
    package still covers a live finding."""
    report = lint_tree(REPO_ROOT, load_config(REPO_ROOT))
    assert report.stale == [], [str(f) for f in report.stale]


def test_repo_journal_kinds_are_exhaustive():
    """The GL202 ground truth on the real scheduler: the set of kinds
    appended anywhere equals the set _apply_record handles, exactly —
    a new journal record without a replay handler cannot land."""
    from shrewd_tpu.analysis import replay_lint
    from shrewd_tpu.analysis.ast_lint import _FileLint

    cfg = load_config(REPO_ROOT)
    fls = [_FileLint(os.path.join(REPO_ROOT, rel), rel, cfg)
           for rel in sorted(set(cfg.journaled_modules)
                             | set(cfg.durability_modules))]
    appended, handled, dispatch = replay_lint.collect_journal_kinds(
        fls, cfg)
    assert dispatch is not None
    assert set(appended) == {
        # the fleet scheduler's ledger
        "config", "admit", "status", "tick", "failure", "quarantine",
        "tenant_kill", "revoke", "evict", "shutdown", "recover",
        # the federation gateway's routing ledger
        "gw_config", "accept", "route", "place", "migrate",
        "pod_dead", "pod_heal", "done", "gw_shutdown", "gw_recover",
        # the gateway's sharded-merge ledger (single-campaign sharding)
        "shard_split", "shard_fold", "shard_converged",
        # the gateway's elastic-pool ledger (journaled autoscaling)
        "pool_scale_up", "pool_retire_begin", "pool_retire_done",
        # the streaming-ingest pipeline's per-tenant WAL
        "ingest_stage", "ingest_done", "ingest_quarantine"}
    assert set(appended) == handled


# --- SARIF export + CLI gates ----------------------------------------------

def _fixture_repo(tmp_path) -> str:
    """A tiny virtual repo with one violation and one stale waiver."""
    pkg = tmp_path / "shrewd_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "shrewd_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "campaign.py").write_text(textwrap.dedent("""
        import jax
        step = jax.jit(lambda x: x)
        # graftlint: allow-wall-clock -- fixture: stale on purpose
        plain = 1
    """))
    return str(tmp_path)


def test_cli_sarif_export_and_audit_waivers_gate(tmp_path):
    import json
    import subprocess
    import sys

    root = _fixture_repo(tmp_path)
    out_sarif = str(tmp_path / "out.sarif")
    out_json = str(tmp_path / "out.json")
    cmd = [sys.executable, os.path.join(REPO_ROOT, "tools", "graftlint.py"),
           "--no-jaxpr", "--root", root, "--sarif", out_sarif,
           "--json", out_json]
    r = subprocess.run(cmd, capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr   # the GL101 violation
    sarif = json.load(open(out_sarif))
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert any(res["ruleId"] == "GL101" and res["level"] == "error"
               for res in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("campaign.py")
    assert loc["region"]["startLine"] >= 1
    rules = {x["id"] for x in sarif["runs"][0]["tool"]["driver"]["rules"]}
    assert {"GL101", "GL201", "GL202", "GL203", "GL204",
            "GL205"} <= rules
    doc = json.load(open(out_json))
    # the stale waiver is REPORTED either way, but gates only under
    # --audit-waivers
    assert len(doc["stale_waivers"]) == 1
    assert doc["violations"] and not doc["ok"]
    # with the violation waived, the stale waiver alone decides the rc
    (tmp_path / "shrewd_tpu" / "parallel" / "campaign.py").write_text(
        textwrap.dedent("""
            import jax
            # graftlint: allow-jit -- fixture: waived for the gate test
            step = jax.jit(lambda x: x)
            # graftlint: allow-wall-clock -- fixture: stale on purpose
            plain = 1
        """))
    r = subprocess.run(cmd, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(cmd + ["--audit-waivers"], capture_output=True,
                       text=True)
    assert r.returncode == 1
    assert "STALE" in r.stdout


# --- jaxpr auditor ----------------------------------------------------------

@pytest.fixture(scope="module")
def probe_campaign():
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    tr = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                 working_set_words=32, seed=7))
    kernel = TrialKernel(tr, O3Config(replay_kernel="hybrid"))
    return ShardedCampaign(kernel, make_mesh(), "regfile")


def test_interval_step_certifies_at_exactly_one_transfer(probe_campaign):
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import _interval_args

    cert = audit_callable(probe_campaign._build_interval_step(4),
                          _interval_args(probe_campaign, 4, 32),
                          kind="interval", transfer_budget=1)
    assert cert["ok"], cert["violations"]
    assert cert["transfers"] == 1
    assert cert["callbacks"] == {}
    # the randomness that IS there is the frozen-key threefry lineage
    assert set(cert["rng"]) <= set(
        __import__("shrewd_tpu.analysis", fromlist=["x"]).ALLOWED_RNG)


def test_broken_interval_step_is_rejected(probe_campaign):
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import (_interval_args,
                                             violating_interval_step)

    cert = audit_callable(violating_interval_step(probe_campaign, 4),
                          _interval_args(probe_campaign, 4, 32),
                          kind="interval", transfer_budget=1)
    assert not cert["ok"]
    assert cert["transfers"] == 2
    assert any("debug_callback" in v for v in cert["violations"])
    assert any("transfer budget" in v for v in cert["violations"])


def test_forbidden_rng_and_undeclared_donation_detected():
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.analysis import audit_callable

    def stateful_rng(x):
        key = jnp.zeros((2,), jnp.uint32)
        bits, _ = jax.lax.rng_bit_generator(key, (4,), dtype=jnp.uint32)
        return x + bits.sum()

    cert = audit_callable(stateful_rng, (jnp.uint32(0),), check_hlo=False)
    assert not cert["ok"]
    assert any("rng_bit_generator" in v for v in cert["violations"])

    donating = jax.jit(lambda x, y: x + y, donate_argnums=(0,))
    cert = audit_callable(donating, (jnp.ones(4), jnp.ones(4)))
    assert any("donation" in v for v in cert["violations"])
    assert cert["donated_args"] == [0]
    # declared donation is consistent, not a violation
    cert_ok = audit_callable(donating, (jnp.ones(4), jnp.ones(4)),
                             declared_donations=(0,))
    assert cert_ok["ok"], cert_ok["violations"]


# --- strict-mode executable-cache admission ---------------------------------

def _broken_build():
    import jax

    def fn(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    return jax.jit(fn)


def test_strict_auditor_refuses_admission_aot_and_first_call():
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        owner = object()
        args = (jnp.ones(4),)
        # AOT path: refused at ADMISSION, before compile
        with pytest.raises(exec_cache.AdmissionError) as ei:
            cache.get_aot(("interval", "broken"), owner, _broken_build,
                          args)
        assert "debug_callback" in str(ei.value)
        assert cache.refused == 1
        # plain path: admitted lazily, refused on the first eager call,
        # and the refusal evicts the entry (nothing stays admitted)
        fn = cache.get(("step", "broken"), owner, _broken_build)
        with pytest.raises(exec_cache.AdmissionError):
            fn(*args)
        assert ("step", "broken") not in cache._entries
        # a clean step admits and is certified, content-keyed
        import jax
        good = cache.get(("step", "good"), owner,
                         lambda: jax.jit(lambda x: x + 1))
        assert float(good(jnp.ones(1))[0]) == 2.0
        assert exec_cache.key_digest(("step", "good")) in cache.certificates
        assert cache.stats()["certified"] == 1
    finally:
        exec_cache.clear_auditor()


def test_warn_auditor_certifies_without_refusing():
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    auditor = StepAuditor(transfer_budget=1, strict=False)
    exec_cache.install_auditor(auditor)
    try:
        fn = cache.get(("step", "warn-broken"), object(), _broken_build)
        out = fn(jnp.ones(2))                  # audited, NOT refused
        np.testing.assert_array_equal(np.asarray(out), [2.0, 2.0])
        assert auditor.audited == 1 and auditor.failed == 1
        cert = cache.certificates[
            exec_cache.key_digest(("step", "warn-broken"))]
        assert not cert["ok"]
        _ = jax
    finally:
        exec_cache.clear_auditor()


def test_strict_refusal_is_sticky_on_held_wrapper():
    """A refused executable STAYS refused: holders that cached the
    wrapper (kernel._shared_jits, chunk fns) and catch the first error
    must not execute the refused step on a later call."""
    import jax.numpy as jnp

    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        fn = cache.get(("step", "sticky"), object(), _broken_build)
        for _ in range(2):                 # second call: no re-audit path
            with pytest.raises(exec_cache.AdmissionError):
                fn(jnp.ones(2))
    finally:
        exec_cache.clear_auditor()


def test_unauditable_executable_admits_with_error_certificate():
    """An auditor that merely CRASHES proves nothing: the executable
    admits (even under strict), and the certificate records the audit
    error instead of counting as certified — a warn-mode run must never
    abort because the auditor couldn't analyze something."""
    from shrewd_tpu.analysis import StepAuditor
    from shrewd_tpu.parallel import exec_cache

    cache = exec_cache.ExecutableCache()
    exec_cache.install_auditor(StepAuditor(transfer_budget=1, strict=True))
    try:
        # a host-side callable make_jaxpr cannot trace (string argument)
        fn = cache.get(("step", "host"), object(),
                       lambda: (lambda name: f"hello {name}"))
        assert fn("world") == "hello world"      # admitted, not refused
        cert = cache.certificates[exec_cache.key_digest(("step", "host"))]
        assert not cert["ok"] and "audit_error" in cert
        assert cache.refused == 0
    finally:
        exec_cache.clear_auditor()


def test_warn_does_not_downgrade_installed_strict_auditor():
    """Certification is process-wide: a second campaign asking for
    'warn' must not silently disarm a strict posture already installed
    (the stricter wins; explicit disarm is the CLI's --certify off)."""
    from shrewd_tpu.analysis import StepAuditor, install_step_auditor
    from shrewd_tpu.parallel import exec_cache

    strict = StepAuditor(transfer_budget=1, strict=True)
    exec_cache.install_auditor(strict)
    try:
        assert install_step_auditor("warn") is strict
        assert exec_cache.current_auditor() is strict
        assert install_step_auditor("off") is None
        assert exec_cache.current_auditor() is strict   # off: no disarm
    finally:
        exec_cache.clear_auditor()


def test_orchestrator_strict_certification_end_to_end():
    """plan.analysis.certify='strict' on a real (tiny) campaign: every
    admitted step certifies, nothing is refused, and the tallies equal
    the uncertified run bit-for-bit (auditing is observation only)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.parallel import exec_cache
    from shrewd_tpu.sim.exit_event import ExitEvent
    from shrewd_tpu.trace.synth import WorkloadConfig

    def plan(certify):
        p = CampaignPlan(
            simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
                n=64, nphys=32, mem_words=64, working_set_words=32,
                seed=3))],
            structures=["regfile"], batch_size=32, target_halfwidth=0.5,
            max_trials=64, min_trials=64)
        p.integrity.canary_trials = 0
        p.integrity.audit_rate = 0.0
        p.resilience.backoff_base = 0.0
        p.analysis.certify = certify
        return p

    def run(p):
        orch = Orchestrator(p)
        events = list(orch.events())
        assert events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
        return orch, dict(events[-1][1])

    try:
        _, clean = run(plan("off"))
        # certification happens at ADMISSION: entries already compiled by
        # the uncertified run are cache hits and stay uncertified, so
        # drop them — the strict run must re-admit everything
        exec_cache.cache().clear()
        orch, certified = run(plan("strict"))
        for key in clean:
            np.testing.assert_array_equal(clean[key].tallies,
                                          certified[key].tallies)
        assert orch.auditor is not None
        assert orch.auditor.audited > 0 and orch.auditor.failed == 0
        assert exec_cache.cache().certificates      # evidence persisted
        assert exec_cache.cache().refused == 0
    finally:
        exec_cache.clear_auditor()
