"""Device-resident run-until-CI (the fused stopping rule).

The contract under test is the ISSUE acceptance criterion: the
``lax.while_loop`` until-CI step (``ShardedCampaign.dispatch_until_ci``)
stops at EXACTLY the batch boundary the host rule would have chosen at
the same per-batch check cadence, so final tallies AND the consumed
batch/trial count are bit-identical to the serial host loop — for the
dense, hybrid (device-resolution) and stratified paths, across a
checkpoint/resume that lands mid-(would-be)-super-interval, under an
injected mid-super-interval tally corruption (quarantine → serial
host-rule recovery), and through the multi-tenant fleet scheduler
(variable batches-per-tick must keep fair-share vtime correct).  The
host↔device decision-parity pin sweeps the jnp Wilson/post-stratified
mirrors against the float64 host reference on campaign-realistic
tallies, and the new while-loop executable must certify at ONE
device→host transfer per super-interval (with the seeded-violation
fixture demonstrably rejected).
"""

import json
import os

import numpy as np
import pytest

from shrewd_tpu.parallel import stopping

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- stopping-layer units ----------------------------------------------------

def test_z_value_memoizes_nontabulated_confidences():
    # the 80-iteration erf bisection must run at most once per confidence
    stopping._Z.pop(0.975, None)
    z = stopping.z_value(0.975)
    assert abs(z - 2.241402727604944) < 1e-12
    assert 0.975 in stopping._Z          # memoized for the next call
    assert stopping.z_value(0.975) == z
    # tabulated entries still hit the table
    assert stopping.z_value(0.95) == 1.959963984540054


def test_pairs_from_strata_uses_module_level_imports():
    # the per-call numpy/classify imports were hoisted; the function is a
    # pure module-level computation now
    strata = np.array([[10, 2, 1, 0], [5, 0, 0, 3]])
    pairs = stopping.pairs_from_strata(strata)
    assert pairs == [(3, 13), (0, 8)]
    import shrewd_tpu.parallel.stopping as sm
    assert hasattr(sm, "np") and hasattr(sm, "C")


# --- host <-> device decision parity ----------------------------------------

def _realistic_tallies():
    """(vulnerable, trials) decision points the NORTHSTAR sweep actually
    visits: for every per-simpoint campaign in NORTHSTAR_r05.json, the
    per-batch trajectory at its converged AVF (p̂ is stable well before
    the rule fires, so round(avf·n) at each batch boundary is the tally
    the host rule actually evaluated)."""
    with open(os.path.join(REPO_ROOT, "NORTHSTAR_r05.json")) as f:
        doc = json.load(f)
    out = []
    for wl in doc["workloads"].values():
        for st in wl["structures"].values():
            for sp in st["simpoints"]:
                n_final, avf = int(sp["trials"]), float(sp["avf"])
                for n in range(4096, n_final + 1, 4096):
                    out.append((int(round(avf * n)), n))
    return sorted(set(out))


def test_device_wilson_parity_on_northstar_tallies():
    import jax.numpy as jnp

    pts = _realistic_tallies()
    assert len(pts) > 100                   # the sweep is real
    z64 = stopping.z_value(0.95)
    z32 = jnp.float32(z64)
    target = 0.01                           # the NORTHSTAR precision
    for vul, n in pts:
        host_hw = stopping.wilson(vul, n, 0.95).halfwidth
        dev_hw = float(stopping.wilson_halfwidth_device(
            jnp.int32(vul), jnp.int32(n), z32))
        assert abs(dev_hw - host_hw) <= 2e-6 + 1e-5 * host_hw, (vul, n)
        # the stop/continue DECISION matches exactly at every point the
        # sweep produces (min_trials=1000, the plan default)
        host_stop = stopping.should_stop(vul, n, target, 0.95, 1000)
        dev_stop = bool(
            stopping.should_stop_device(
                stopping.wilson_halfwidth_device(jnp.int32(vul),
                                                 jnp.int32(n), z32),
                jnp.int32(n), jnp.float32(target), jnp.int32(1000)))
        assert dev_stop == host_stop, (vul, n, host_hw, dev_hw)


def test_device_wilson_parity_grid():
    """Synthetic sweep over (p, n, confidence): half-widths agree to
    float32 slack including the lo/hi clamp corners (p → 0 and 1)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    for conf in (0.90, 0.95, 0.975, 0.99):
        z = jnp.float32(stopping.z_value(conf))
        for n in (1000, 4096, 32768, 704512):
            for p in (0.0, 1e-5, 1e-3, 0.01, 0.1, 0.3137, 0.5,
                      0.9, 0.999, 1.0):
                vul = int(round(p * n))
                host_hw = stopping.wilson(vul, n, conf).halfwidth
                dev_hw = float(stopping.wilson_halfwidth_device(
                    jnp.int32(vul), jnp.int32(n), z))
                assert abs(dev_hw - host_hw) <= 2e-6 + 1e-5 * host_hw
        # random strata for the post-stratified mirror
        for _ in range(24):
            strata = rng.integers(0, 2000, size=(8, 4))
            if rng.random() < 0.3:
                strata[rng.integers(0, 8)] = 0     # empty stratum
            host_hw = stopping.post_stratified(
                stopping.pairs_from_strata(strata), conf).halfwidth
            dev_hw = float(stopping.post_stratified_halfwidth_device(
                jnp.asarray(strata, jnp.int32), z))
            assert abs(dev_hw - host_hw) <= 2e-6 + 1e-5 * host_hw


# --- campaign-level bit-identity ---------------------------------------------

def _tiny_campaign(mode, stratify):
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    tr = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                 working_set_words=32, seed=7))
    kernel = TrialKernel(tr, O3Config(replay_kernel=mode))
    return kernel, ShardedCampaign(kernel, make_mesh(), "regfile",
                                   stratify=stratify, integrity_check=True)


@pytest.mark.parametrize("mode,stratify", [
    ("hybrid", False), ("dense", False), ("hybrid", True)])
def test_until_ci_step_matches_host_loop(mode, stratify):
    """The device while-loop consumes EXACTLY the batches the per-batch
    host stopping loop would, with identical tallies/strata and escape
    counters — for a rule that fires mid-budget."""
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.utils import prng

    kernel, camp = _tiny_campaign(mode, stratify)
    B, S = 32, 16
    target, conf, min_trials = 0.12, 0.95, 64
    sk = prng.structure_key(prng.simpoint_key(prng.campaign_key(0), 0), 0)

    def keys(b):
        return prng.trial_keys(prng.batch_key(sk, b), B)

    tal = np.zeros(C.N_OUTCOMES, np.int64)
    strat = np.zeros((8, C.N_OUTCOMES), np.int64)
    trials, consumed_host = 0, None
    for b in range(S):
        if stratify:
            th = np.asarray(camp.tally_batch_stratified(keys(b)), np.int64)
            strat += th
            t = th.sum(axis=0)
        else:
            t = np.asarray(camp.tally_batch(keys(b)), np.int64)
        tal += t
        trials += B
        vul = int(tal[C.OUTCOME_SDC] + tal[C.OUTCOME_DUE])
        if stratify:
            stop = stopping.should_stop_stratified(
                stopping.pairs_from_strata(strat), target, conf,
                min_trials)
        else:
            stop = stopping.should_stop(vul, trials, target, conf,
                                        min_trials)
        if stop:
            consumed_host = b + 1
            break
    assert consumed_host is not None and consumed_host < S  # mid-budget
    esc_host = kernel.escapes
    kernel.escapes = kernel.taint_trials = 0

    h = camp.dispatch_until_ci(
        [keys(b) for b in range(S)], np.zeros(C.N_OUTCOMES, np.int64),
        np.zeros((8, C.N_OUTCOMES), np.int64) if stratify else None,
        0, min_trials, target, conf, strat_rule=stratify)
    dtal, dstrat, consumed, hw_tail = camp.materialize_until_ci(h)
    assert consumed == consumed_host
    assert len(hw_tail) == consumed           # the trajectory tail rides
    np.testing.assert_array_equal(dtal, tal)
    if stratify:
        np.testing.assert_array_equal(dstrat, strat)
    assert kernel.escapes == esc_host


# --- orchestrator-level bit-identity ----------------------------------------

def _tiny_plan(until_ci, target=0.1, stratify=False, batch_size=32,
               max_batches=64, min_trials=64, **kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        structures=["regfile"], batch_size=batch_size,
        target_halfwidth=target, confidence=0.95,
        max_trials=batch_size * max_batches, min_trials=min_trials,
        stratify=stratify, **kw)
    plan.integrity.audit_rate = 0.0
    plan.resilience.backoff_base = 0.0
    plan.pipeline.until_ci = until_ci
    return plan


def _run(plan, outdir=None):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(plan, outdir=outdir)
    events = list(orch.events())
    results = (dict(events[-1][1])
               if events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE else None)
    return orch, results


def test_orchestrator_until_ci_bit_identical_and_observable():
    from shrewd_tpu import stats as statsmod

    _, serial = _run(_tiny_plan(False, target=0.08))
    orch, fused = _run(_tiny_plan(True, target=0.08))
    assert serial is not None and fused is not None
    for key in serial:
        np.testing.assert_array_equal(serial[key].tallies,
                                      fused[key].tallies)
        # trial-count equality IS consumed-batch-count equality: the
        # device decided where to stop, and it chose the host's boundary
        assert serial[key].trials == fused[key].trials
        assert serial[key].converged and fused[key].converged
    # the fused loop is observable: one transfer per super-interval, the
    # saved round-trips ledgered, the planner's budget and the final
    # half-width on the record
    perf = statsmod.to_dict(orch.stats)["perf"]
    assert perf["super_intervals"] >= 1
    assert perf["host_roundtrips_saved"] >= 1
    assert perf["auto_sync_every"] >= 1
    assert perf["hw_trajectory_final"] is not None
    assert perf["hw_trajectory_final"] <= 0.08
    assert perf["serial_fallbacks"] == 0


def test_orchestrator_until_ci_stratified_bit_identical():
    _, serial = _run(_tiny_plan(False, target=0.08, stratify=True))
    _, fused = _run(_tiny_plan(True, target=0.08, stratify=True))
    for key in serial:
        np.testing.assert_array_equal(serial[key].tallies,
                                      fused[key].tallies)
        assert serial[key].trials == fused[key].trials
        # the post-stratified interval is a pure function of the strata
        assert serial[key].avf_interval == fused[key].avf_interval


def test_until_ci_resume_mid_super_interval(tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    _, clean = _run(_tiny_plan(False, target=0.08))
    # the serial run leaves its last checkpoint mid-run at a boundary the
    # fused run's super-interval grid would have jumped past — the
    # resumed device loop must still stop at the host boundary
    plan = _tiny_plan(False, target=0.08, checkpoint_every=3)
    _run(plan, outdir=str(tmp_path / "out"))
    ckpt = str(tmp_path / "out" / "campaign_ckpt")
    doc = Orchestrator.load_checkpoint_doc(ckpt)
    st = doc["state"]["w0"]["regfile"]
    assert 0 < st["next_batch"] * 32 < clean[("w0", "regfile")].trials
    orch2 = Orchestrator.resume(ckpt, outdir=str(tmp_path / "out2"))
    orch2.pcfg.until_ci = True             # resume FUSED
    events = list(orch2.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
        assert clean[key].trials == results[key].trials


def test_until_ci_corrupt_tally_mid_super_interval_recovers():
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.chaos import ChaosEngine

    clean_orch, clean = _run(_tiny_plan(False, target=0.08))
    plan = _tiny_plan(True, target=0.08)
    orch = Orchestrator(plan)
    # batch 2 lands inside the first super-interval: the corrupted
    # cumulative delta must quarantine and recover through the serial
    # ladder with the HOST rule re-deriving the stopping boundary
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "corrupt_tally", "at_batch": 2, "delta": 3}]}))
    events = list(orch.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
        assert clean[key].trials == results[key].trials
    assert orch.chaos.injected == {"corrupt_tally": 1}
    assert orch.chaos.survived == orch.chaos.injected
    assert orch.monitor.quarantined >= 1
    assert orch._perf.serial_fallbacks >= 1
    assert orch.monitor.recovered >= 1
    # escape-counter parity under quarantine (the rollback discipline)
    key = ("w0", "regfile")
    assert orch.state[key].escapes == clean_orch.state[key].escapes


def test_until_ci_fault_past_convergence_never_arms():
    """Serial parity of the chaos ledgers: a batch-granular fault
    scheduled PAST the convergence boundary never fires in the serial
    loop, so the fused planner must bound its super-interval budget
    before the fault's batch instead of spuriously arming it
    (`ChaosEngine.next_batch_fault`)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.chaos import ChaosEngine

    _, clean = _run(_tiny_plan(False, target=0.08))
    # the serial loop converges at batch 5 (160 trials) — batch 10 is
    # never reached, so the fault must never arm in the fused run either
    orch = Orchestrator(_tiny_plan(True, target=0.08))
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "corrupt_tally", "at_batch": 10, "delta": 3}]}))
    results = dict(list(orch.events())[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
        assert clean[key].trials == results[key].trials
    assert dict(orch.chaos.injected) == {}
    assert orch.monitor.quarantined == 0


def test_until_ci_after_dispatches_counter_parity():
    """The per-process ``after_dispatches`` trigger counts batches the
    process COMPUTED: the fused path arms a whole budget up front, so it
    must rewind the counter to the consumed count — and the planner must
    clamp the budget before the trigger's mapped batch — or the fault
    fires at different campaign coordinates than the serial loop."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.chaos import ChaosEngine

    plan_fault = {"faults": [
        {"kind": "corrupt_tally", "after_dispatches": 4, "delta": 3}]}
    orch_s = Orchestrator(_tiny_plan(False, target=0.08))
    orch_s.attach_chaos(ChaosEngine(dict(plan_fault)))
    serial = dict(list(orch_s.events())[-1][1])
    orch_f = Orchestrator(_tiny_plan(True, target=0.08))
    orch_f.attach_chaos(ChaosEngine(dict(plan_fault)))
    fused = dict(list(orch_f.events())[-1][1])
    for key in serial:
        np.testing.assert_array_equal(serial[key].tallies,
                                      fused[key].tallies)
        assert serial[key].trials == fused[key].trials
    # same fault fired in both runs, at the same campaign coordinates,
    # and the dispatch counters agree after convergence
    assert dict(orch_f.chaos.injected) == dict(orch_s.chaos.injected)
    assert dict(orch_f.chaos.survived) == dict(orch_s.chaos.survived)
    assert orch_f.chaos.dispatches == orch_s.chaos.dispatches


def test_until_ci_through_fleet_scheduler_bit_identical():
    """Variable batches-per-tick through the multi-tenant scheduler: a
    fused tenant's tick consumes a whole super-interval, and fair-share
    vtime (trials/weight, recomputed from orchestrator state) stays
    correct — tallies bit-identical to the solo run either way."""
    from shrewd_tpu.service import CampaignScheduler, TenantSpec

    _, solo = _run(_tiny_plan(True, target=0.08))
    sched = CampaignScheduler()
    sched.admit(TenantSpec(name="fused",
                           plan=_tiny_plan(True, target=0.08).to_dict()))
    sched.admit(TenantSpec(name="host",
                           plan=_tiny_plan(False, target=0.08).to_dict()))
    assert sched.run() == 0
    fused_t = sched.tenants["fused"]
    host_t = sched.tenants["host"]
    for k, r in solo.items():
        got = sched.tenant_tallies("fused")[k]
        np.testing.assert_array_equal(got, r.tallies)
        got_h = sched.tenant_tallies("host")[k]
        np.testing.assert_array_equal(got_h, r.tallies)
    # equal work at equal weight: the recomputed-vtime accounting agrees
    # between a per-batch tenant and a per-super-interval tenant
    assert fused_t.trials == host_t.trials > 0
    assert fused_t.vtime == host_t.vtime
    # the fused tenant reached the same trials in far fewer ticks
    assert fused_t.ticks < host_t.ticks


# --- certification -----------------------------------------------------------

def test_until_ci_step_certifies_at_one_transfer():
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import _until_ci_args

    _, camp = _tiny_campaign("hybrid", False)
    cert = audit_callable(camp._build_until_ci_step(4, strat_rule=False),
                          _until_ci_args(camp, 4, 32),
                          kind="until_ci", transfer_budget=1)
    assert cert["ok"], cert["violations"]
    assert cert["transfers"] == 1
    assert cert["callbacks"] == {}


def test_broken_until_ci_step_is_rejected():
    from shrewd_tpu.analysis import audit_callable
    from shrewd_tpu.analysis.certify import (_until_ci_args,
                                             violating_until_ci_step)

    _, camp = _tiny_campaign("dense", False)
    cert = audit_callable(violating_until_ci_step(camp, 4),
                          _until_ci_args(camp, 4, 32),
                          kind="until_ci", transfer_budget=1)
    assert not cert["ok"]
    assert cert["transfers"] == 2
    assert any("debug_callback" in v for v in cert["violations"])
    assert any("transfer budget" in v for v in cert["violations"])
