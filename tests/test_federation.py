"""Federated fleet-of-fleets (shrewd_tpu/federation/): gateway routing,
migration by bit-identity, pod-death failover, partition fencing, and
the gateway-WAL crash sweep.

The contract under test is the ISSUE acceptance criterion: a matrix of
tenants across >=3 federated scheduler pods, under a chaos schedule
that kills one pod and partitions another mid-campaign, completes with
final tallies bit-identical to solo serial runs — each tenant counted
exactly once, per the gateway's journaled routing ledger, never per
whoever happened to compute.  Around that: the new chaos kinds'
trigger-vocab validation, the published half-width-trajectory ETA the
gateway routes on, the scheduler's cooperative step()/evict() seams,
the two-phase placement's crash windows (swept exhaustively by
``analysis/crashcheck.run_gateway_crashcheck``), and the thin HTTP
front.
"""

import json
import os
import shutil
import urllib.request

import numpy as np
import pytest

from test_fleet import _plan, _solo_tallies

from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.chaos import ChaosEngine, ChaosPlanError
from shrewd_tpu.federation import (Federation, Gateway, GatewayHTTPFront,
                                   PodSupervisor, find_spool_ticket)
from shrewd_tpu.parallel import stopping
from shrewd_tpu.service import CampaignScheduler, TenantSpec
from shrewd_tpu.service.scheduler import IDLE


def _spec(name, seed=3, n_batches=4, **kw):
    return TenantSpec(name=name,
                      plan=_plan(seed, n_batches=n_batches).to_dict(),
                      **kw)


def _assert_matches(fed, name, solo):
    got = fed.tenant_tallies(name)
    assert got.keys() == solo.keys()
    for k, t in solo.items():
        np.testing.assert_array_equal(got[k], t)


# --- chaos DSL: federation kinds (jax-free units) ---------------------------

def test_pod_chaos_kinds_validation():
    # required trigger vocabulary
    with pytest.raises(ChaosPlanError, match="at_tick / at_round"):
        ChaosEngine({"faults": [{"kind": "kill_pod", "pod": "p0"}]})
    with pytest.raises(ChaosPlanError, match="at_round"):
        ChaosEngine({"faults": [{"kind": "partition_pod"}]})
    # per-kind vocab: an id key outside the kind's vocabulary is a plan
    # error, not a silently-dead trigger
    with pytest.raises(ChaosPlanError, match="does not take 'at_batch'"):
        ChaosEngine({"faults": [{"kind": "kill_pod", "at_tick": 1,
                                 "at_batch": 2}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_tick'"):
        ChaosEngine({"faults": [{"kind": "partition_pod", "at_round": 1,
                                 "at_tick": 2}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_batch'"):
        ChaosEngine({"faults": [{"kind": "kill_fleet", "at_tick": 1,
                                 "at_batch": 0}]})
    with pytest.raises(ChaosPlanError, match="rounds"):
        ChaosEngine({"faults": [{"kind": "partition_pod", "at_round": 1,
                                 "rounds": 0}]})


def test_pod_chaos_hooks_fire_deterministically():
    eng = ChaosEngine({"faults": [
        {"kind": "kill_pod", "pod": "p0", "at_tick": 5},
        {"kind": "partition_pod", "pod": "p1", "at_round": 2,
         "rounds": 3}]})
    killed = []
    eng.kill_action = lambda rc: killed.append(rc)
    eng.maybe_kill_pod("p1", tick=5)          # wrong pod: no fire
    eng.maybe_kill_pod("p0", tick=4)          # wrong tick: no fire
    assert not killed
    eng.maybe_kill_pod("p0", tick=5)
    assert killed == [137]
    eng.maybe_kill_pod("p0", tick=5)          # consumed: fires once
    assert killed == [137]
    # partition window [2, 5): active rounds fire the ledger ONCE
    assert not eng.partition_active("p1", 1)
    assert eng.partition_active("p1", 2)
    assert eng.partition_active("p1", 4)
    assert not eng.partition_active("p1", 5)
    assert not eng.partition_active("p0", 3)  # wrong pod
    assert eng.injected == {"kill_pod": 1, "partition_pod": 1}
    # federation kinds are never armed by batch arming
    eng2 = ChaosEngine({"faults": [
        {"kind": "kill_pod", "pod": "p", "at_tick": 0},
        {"kind": "partition_pod", "pod": "p", "at_round": 0}]})
    eng2.begin_batch(0)
    assert eng2._armed == {}


# --- the ETA estimator + its metrics publication ----------------------------

def test_eta_trials_estimator():
    # below the floor: the whole remaining min_trials is owed
    assert stopping.eta_trials(0, 0, None, False, 0.95, 0.1, 500) == 500
    # converged (hw <= target): nothing owed
    hw = stopping.wilson(5, 4000, 0.95).halfwidth
    assert hw < 0.05
    assert stopping.eta_trials(5, 4000, None, False, 0.95, 0.05,
                               100) == 0.0
    # mid-trajectory: n*((hw/target)^2 - 1) dominates the floor
    eta = stopping.eta_trials(50, 200, None, False, 0.95, 0.01, 100)
    hw = stopping.wilson(50, 200, 0.95).halfwidth
    assert eta == pytest.approx(200 * ((hw / 0.01) ** 2 - 1.0))


def test_metrics_publish_eta(tmp_path):
    outdir = str(tmp_path / "fleet")
    seen = []

    def grab(s):
        from shrewd_tpu.obs import metrics as obs_metrics

        try:
            seen.append(obs_metrics.read(outdir))
        except (OSError, ValueError):
            pass

    sched = CampaignScheduler(outdir=outdir, on_tick=grab)
    sched.admit(TenantSpec(name="t", plan=_plan(3, n_batches=4,
                                                ).to_dict()))
    assert sched.run() == 0
    rows = [s["tenants"]["t"] for s in seen
            if "eta_trials" in s.get("tenants", {}).get("t", {})]
    assert rows, "no mid-run snapshot carried the ETA"
    # the published ETA is the convergence distance: monotonically
    # non-increasing over this fixed-trials campaign, 0 by the end
    etas = [r["eta_trials"] for r in rows]
    assert all(a >= b for a, b in zip(etas, etas[1:]))
    assert etas[-1] == 0.0
    assert "eta_ticks" in rows[-1] and "eta_s" in rows[-1]
    # Prometheus exposition carries the gauge family
    from shrewd_tpu.obs import metrics as obs_metrics

    prom = obs_metrics.prometheus_text(seen[-1])
    assert "shrewd_fleet_tenant_eta_trials" in prom


# --- scheduler seams: step() and evict() ------------------------------------

def test_step_loop_is_exactly_run():
    a = CampaignScheduler()
    a.admit(_spec("x", 3))
    a.admit(_spec("y", 5))
    assert a.run() == 0
    b = CampaignScheduler()
    b.admit(_spec("x", 3))
    b.admit(_spec("y", 5))
    while True:
        rc = b.step()
        assert rc is not IDLE       # no spool: never resident-idle
        if rc is not None:
            break
    assert rc == 0
    assert a.schedule_log == b.schedule_log
    for n in ("x", "y"):
        for k, t in a.tenant_tallies(n).items():
            np.testing.assert_array_equal(b.tenant_tallies(n)[k], t)


def test_evict_drains_and_recovers_elsewhere_bit_identical(tmp_path):
    solo = _solo_tallies(_plan(3, n_batches=6))
    pod_a = str(tmp_path / "podA")
    pod_b = str(tmp_path / "podB")
    sched = CampaignScheduler(outdir=pod_a)
    sched.admit(TenantSpec(name="m", plan=_plan(3,
                                                n_batches=6).to_dict()))
    steps = 0
    while True:
        rc = sched.step()
        if isinstance(rc, int):
            break
        steps += 1
        if steps == 3:
            assert sched.evict("m", "rebalance") is True
    t = sched.tenants["m"]
    assert rc == 0 and t.status == "evicted" and t.evicted == "rebalance"
    assert 0 < t.trials < 32 * 6    # genuinely mid-campaign
    assert sched.evict("m") is False            # terminal: idempotent
    with pytest.raises(KeyError):
        sched.evict("nobody")
    # migrate by bit-identity: the namespaced checkpoint moves, the
    # campaign continues on another pod, tallies equal the solo run
    os.makedirs(os.path.join(pod_b, "tenants"), exist_ok=True)
    shutil.copytree(os.path.join(pod_a, "tenants", "m"),
                    os.path.join(pod_b, "tenants", "m"))
    sched_b = CampaignScheduler(outdir=pod_b)
    sched_b.admit(TenantSpec(name="m", plan=_plan(3,
                                                  n_batches=6).to_dict()))
    assert sched_b.run() == 0
    got = sched_b.tenant_tallies("m")
    for k, v in solo.items():
        np.testing.assert_array_equal(got[k], v)


def test_evict_queued_releases_without_elaboration(tmp_path):
    sched = CampaignScheduler(outdir=str(tmp_path))
    # an unbuildable plan: release must not cost a plan build
    sched.admit(TenantSpec(name="q", plan={"nonsense": True}))
    assert sched.evict("q", "moved") is True
    t = sched.tenants["q"]
    assert t.status == "evicted" and t.orch is None and t.failures == 0


def test_evict_decision_survives_hard_kill(tmp_path):
    # the eviction is journaled before the drain: a hard kill between
    # the two replays the decision — the recovered pod releases the
    # tenant without ever elaborating it
    outdir = str(tmp_path / "pod")
    sched = CampaignScheduler(outdir=outdir)
    sched.admit(TenantSpec(name="m", plan=_plan(3,
                                                n_batches=6).to_dict()))
    for _ in range(3):
        sched.step()
    assert sched.evict("m", "migrate") is True
    # hard kill here: no drain, no checkpoint — abandon the scheduler
    rec = CampaignScheduler.recover(outdir)
    t = rec.tenants["m"]
    assert t.evicted == "migrate" and t.status == "queued"
    assert rec.run() == 0
    assert t.status == "evicted" and t.orch is None


# --- the pod supervisor (jax-free unit) -------------------------------------

def test_pod_supervisor_round_counted_lease_expiry(tmp_path):
    from shrewd_tpu.parallel.elastic import HeartbeatWriter

    coord = str(tmp_path / "coord")
    sup = PodSupervisor(coord, expiry_rounds=2)
    hb = HeartbeatWriter(coord, "p0")
    hb.beat()
    assert sup.observe(["p0"])["p0"] is True
    hb.beat()
    assert sup.observe(["p0"])["p0"] is True
    # beats stop: the lease expires after exactly expiry_rounds polls
    assert sup.observe(["p0"])["p0"] is True     # stale poll 1
    assert sup.observe(["p0"])["p0"] is False    # stale poll 2: expired
    # beats resume: alive again on the next poll (the heal signal)
    hb.beat()
    assert sup.observe(["p0"])["p0"] is True
    # a pod that never beat at all expires too
    sup2 = PodSupervisor(coord, expiry_rounds=2)
    sup2.observe(["ghost"])
    assert sup2.observe(["ghost"])["ghost"] is False


def test_tenant_spec_slo_roundtrip():
    spec = TenantSpec(name="t", plan={"seed": 1}, slo_s=120.0)
    assert TenantSpec.from_dict(spec.to_dict()).slo_s == 120.0
    assert TenantSpec.from_dict({"name": "old", "plan": {}}).slo_s == 0.0
    with pytest.raises(ValueError):
        TenantSpec(name="t", plan={}, slo_s=-1.0)


# --- the federation (gateway + pods + driver) -------------------------------

def test_federation_routes_serves_bit_identical(tmp_path):
    seeds = (3, 5, 7)
    solos = {s: _solo_tallies(_plan(s, n_batches=4)) for s in seeds}
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0", "pod1"))
    for s in seeds:
        doc = fed.submit(_spec(f"t{s}", s))
        assert doc["pod"] in ("pod0", "pod1")
        assert doc["eta_trials"] > 0
    assert fed.serve() == 0
    for s in seeds:
        _assert_matches(fed, f"t{s}", solos[s])
    # load routing spread the tenants over both pods
    pods_used = {e.pod for e in fed.gateway.entries.values()}
    assert pods_used == {"pod0", "pod1"}
    # the routing ledger snapshot is durable + checksummed
    from shrewd_tpu.resilience import load_json_verified

    snap = load_json_verified(os.path.join(
        str(tmp_path / "fed"), "gateway", "gateway_ckpt",
        "gateway.json"))
    assert {e["status"] for e in snap["entries"]} == {"done"}


def test_federation_kill_pod_failover_bit_identical(tmp_path):
    # the acceptance pin: a pod dies HARD mid-campaign (kill_pod chaos
    # at a deterministic tick — no drain, dirty WAL, stale heartbeat),
    # the supervisor's lease expires, the gateway fails its tenants
    # over from their namespaced checkpoints, and every tenant's final
    # tallies are bit-identical to its solo serial run
    seeds = (3, 5, 7)
    solos = {s: _solo_tallies(_plan(s, n_batches=6)) for s in seeds}
    chaos = ChaosEngine({"faults": [
        {"kind": "kill_pod", "pod": "pod0", "at_tick": 3}]})
    fed = Federation(str(tmp_path / "fed"),
                     pod_names=("pod0", "pod1", "pod2"),
                     chaos=chaos, expiry_rounds=2)
    for s in seeds:
        fed.submit(TenantSpec(name=f"t{s}",
                              plan=_plan(s, n_batches=6).to_dict()))
    assert fed.serve() == 0
    assert chaos.injected == {"kill_pod": 1}
    assert chaos.survived == {"kill_pod": 1}
    assert fed.gateway.dead_pods == {"pod0"}
    assert fed.failovers >= 1
    for s in seeds:
        _assert_matches(fed, f"t{s}", solos[s])
    # the failed-over tenant's history shows the move off the dead pod
    moved = [e for e in fed.gateway.entries.values()
             if any(h["pod"] == "pod0" for h in e.history)]
    assert moved and all(e.pod != "pod0" for e in moved)


def test_federation_partition_heals_without_duplicate(tmp_path):
    # partition = heartbeat suppression WITHOUT death: the pod keeps
    # computing, the supervisor declares it lost, the gateway fails
    # over — then the partition heals and the stale placement is
    # fenced.  Each tenant must be counted exactly once (the ledger
    # decides who reports; the stale copy's tallies are bit-identical
    # anyway, which is why fencing is safe at any point)
    seeds = (3, 5)
    solos = {s: _solo_tallies(_plan(s, n_batches=8)) for s in seeds}
    chaos = ChaosEngine({"faults": [
        {"kind": "partition_pod", "pod": "pod0", "at_round": 2,
         "rounds": 4}]})
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0", "pod1"),
                     chaos=chaos, expiry_rounds=2)
    for s in seeds:
        fed.submit(TenantSpec(name=f"t{s}",
                              plan=_plan(s, n_batches=8).to_dict()))
    assert fed.serve() == 0
    assert chaos.injected == {"partition_pod": 1}
    assert "pod0" not in fed.gateway.dead_pods    # healed, not dead
    assert fed.failovers >= 1
    for s in seeds:
        _assert_matches(fed, f"t{s}", solos[s])
    # no duplicate accounting: every tenant reports from exactly one
    # authoritative placement, and any stale copy on the healed pod
    # was fenced (evicted) rather than adopted
    for s in seeds:
        e = fed.gateway.entries[f"t{s}"]
        assert e.status == "done" and e.result is not None
    if fed.fenced:
        pod0 = fed.pods["pod0"].sched
        stale = [t for t in (pod0.tenants.values() if pod0 else [])
                 if t.status == "evicted"]
        assert stale, "fencing reported but no evicted stale tenant"


def test_federation_rebalances_on_eta_runaway(tmp_path):
    # an aggressive rebalance posture (factor < 1) forces at least one
    # drain-here/recover-there migration mid-campaign; tallies must
    # stay bit-identical through it (migration is free by construction)
    seeds = (3, 5, 7)
    solos = {s: _solo_tallies(_plan(s, n_batches=6)) for s in seeds}
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0", "pod1"),
                     rebalance_every=2, rebalance_factor=0.5)
    for s in seeds:
        fed.submit(TenantSpec(name=f"t{s}",
                              plan=_plan(s, n_batches=6).to_dict()))
    assert fed.serve() == 0
    assert fed.migrations >= 1
    migrated = [e for e in fed.gateway.entries.values() if e.epoch > 1]
    assert migrated
    for s in seeds:
        _assert_matches(fed, f"t{s}", solos[s])


def test_gateway_recover_replays_route_without_double_place(tmp_path):
    # the satellite's crash window made explicit: kill between the
    # route-decision journal and the pod handoff — recovery must
    # replay the journaled decision (same pod, one ticket), never
    # re-decide into a second placement
    solo = _solo_tallies(_plan(3, n_batches=4))
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1"))

    class Boom(Exception):
        pass

    def explode(self, e):
        raise Boom()

    orig = Gateway._place
    Gateway._place = explode
    try:
        with pytest.raises(Boom):
            fed.submit(_spec("t3", 3))
    finally:
        Gateway._place = orig
    e = fed.gateway.entries["t3"]
    assert e.status == "routed" and not e.pod_ticket
    decided = e.pod
    ports = {n: p.port for n, p in fed.pods.items()}
    for p in fed.pods.values():
        assert find_spool_ticket(p.spool_dir, "t3") is None
    # first recovery: replay the decision, place exactly once
    gw2 = Gateway.recover(os.path.join(root, "gateway"), pods=ports)
    assert gw2.recoveries == 1
    assert gw2.entries["t3"].status == "placed"
    assert gw2.entries["t3"].pod == decided
    # second recovery (crash straight after the repair): still one
    hits = [n for n, p in fed.pods.items()
            if find_spool_ticket(p.spool_dir, "t3")]
    assert hits == [decided]
    gw3 = Gateway.recover(os.path.join(root, "gateway"), pods=ports)
    hits = [n for n, p in fed.pods.items()
            if find_spool_ticket(p.spool_dir, "t3")]
    assert hits == [decided]
    pending = os.listdir(os.path.join(
        fed.pods[decided].spool_dir, "pending"))
    assert len(pending) == 1
    # the recovered gateway serves to completion, bit-identically
    fed.gateway = gw3
    assert fed.serve() == 0
    _assert_matches(fed, "t3", solo)


def test_gateway_recover_with_smaller_pod_set_fails_over(tmp_path):
    # a recovery handed fewer pods than the snapshot knew (--recover
    # --pods N after shrinking the deployment): entries on the
    # now-unknown pod are orphans and must fail over to the recovered
    # pod set, not crash recovery or strand silently
    solo = _solo_tallies(_plan(3, n_batches=4))
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1", "pod2"))
    fed.submit(_spec("t3", 3))
    placed_on = fed.gateway.entries["t3"].pod
    fed.gateway.checkpoint()    # durable ledger, then "lose" the pod
    survivors = tuple(n for n in ("pod0", "pod1", "pod2")
                      if n != placed_on)
    fed2 = Federation.recover(root, pod_names=survivors)
    e = fed2.gateway.entries["t3"]
    assert e.pod in survivors and e.status == "placed"
    assert any(h["reason"] == "failover" for h in e.history)
    assert fed2.serve() == 0
    _assert_matches(fed2, "t3", solo)


def test_gateway_refused_placement_is_rerouted_not_adopted(tmp_path):
    # a pod that refuses a placement (e.g. a healed partition's stale
    # terminal copy still holds the roster slot) publishes a
    # results-free "refused" done-doc: the gateway must re-place the
    # tenant elsewhere, never adopt the refusal as the final result
    from shrewd_tpu.service import SubmissionQueue

    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1"))
    fed.submit(_spec("t3", 3))
    e = fed.gateway.entries["t3"]
    first = e.pod
    SubmissionQueue(fed.pods[first].spool_dir).mark_done(
        e.pod_ticket, {"tenant": "t3", "status": "refused",
                       "error": "tenant 't3' already admitted"})
    fed.gateway.poll()
    assert e.status == "placed" and e.pod != first
    assert any(h["reason"] == "refused" for h in e.history)
    assert fed.serve() == 0
    _assert_matches(fed, "t3", _solo_tallies(_plan(3, n_batches=4)))


def test_gateway_crashcheck_sweep(tmp_path):
    # the exhaustive version of the window above: recover the whole
    # federation from EVERY gateway-WAL durability boundary (+ torn
    # variants of every gateway append) — bit-identical aggregate and
    # single placement at each.  Bounded here; the CI smoke runs the
    # full sweep
    plans = crashcheck.small_fleet_plans(seeds=(3,), n_batches=2)
    doc = crashcheck.run_gateway_crashcheck(
        str(tmp_path / "sweep"), plans=plans, max_points=8)
    assert doc["failures"] == []
    assert doc["points_checked"] >= 5
    assert doc["torn_checks"] >= 1
    assert doc["boundaries_by_event"].get("append", 0) >= 3


# --- single-campaign sharding (the merge fold) ------------------------------

def test_shard_chaos_kinds_validation():
    # required trigger vocabulary
    with pytest.raises(ChaosPlanError, match="at_tick / at_round"):
        ChaosEngine({"faults": [{"kind": "kill_shard",
                                 "shard": "t+shard0"}]})
    with pytest.raises(ChaosPlanError, match="at_fold"):
        ChaosEngine({"faults": [{"kind": "partition_during_merge"}]})
    # per-kind vocab: an id key outside the kind's vocabulary is a plan
    # error, not a silently-dead trigger
    with pytest.raises(ChaosPlanError, match="does not take 'at_batch'"):
        ChaosEngine({"faults": [{"kind": "kill_shard", "at_tick": 1,
                                 "at_batch": 2}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_round'"):
        ChaosEngine({"faults": [{"kind": "partition_during_merge",
                                 "at_fold": 1, "at_round": 2}]})
    with pytest.raises(ChaosPlanError, match="rounds"):
        ChaosEngine({"faults": [{"kind": "partition_during_merge",
                                 "at_fold": 1, "rounds": 0}]})


def test_shard_chaos_hooks_fire_deterministically():
    eng = ChaosEngine({"faults": [
        {"kind": "kill_shard", "shard": "camp+shard1", "at_round": 3},
        {"kind": "partition_during_merge", "pod": "p1", "at_fold": 2,
         "rounds": 3}]})
    killed = []
    eng.kill_action = lambda rc: killed.append(rc)
    eng.maybe_kill_shard("camp+shard0", round=3)   # wrong shard: no fire
    eng.maybe_kill_shard("camp+shard1", round=2)   # wrong round: no fire
    assert not killed
    eng.maybe_kill_shard("camp+shard1", round=3)
    assert killed == [137]
    eng.maybe_kill_shard("camp+shard1", round=3)   # consumed: fires once
    assert killed == [137]
    # merge partition: inert until the journaled fold ordinal reaches
    # at_fold, then a round-counted window [r0, r0+rounds) on the pod
    assert not eng.partition_merge_active("p1", folds=1, round=4)
    assert eng.partition_merge_active("p1", folds=2, round=5)   # opens
    assert eng.partition_merge_active("p1", folds=7, round=7)
    assert not eng.partition_merge_active("p1", folds=7, round=8)
    assert not eng.partition_merge_active("p0", folds=9, round=6)
    assert eng.injected == {"kill_shard": 1,
                            "partition_during_merge": 1}
    # federation kinds are never armed by batch arming
    eng2 = ChaosEngine({"faults": [
        {"kind": "kill_shard", "shard": "s", "at_round": 0},
        {"kind": "partition_during_merge", "pod": "p", "at_fold": 0}]})
    eng2.begin_batch(0)
    assert eng2._armed == {}


def test_federation_sharded_campaign_bit_identical(tmp_path):
    # the tentpole pin: ONE campaign striped across three pods
    # (shards: 3 — round-robin partition of the frozen batch-id
    # space), merged at the gateway with the order-fixed fold, final
    # tallies bit-identical to the solo serial run
    plan = _plan(3, n_batches=6)
    solo = _solo_tallies(plan)
    fed = Federation(str(tmp_path / "fed"),
                     pod_names=("pod0", "pod1", "pod2"))
    doc = fed.submit(TenantSpec(name="camp", plan=plan.to_dict(),
                                shards=3))
    # admission reports the split, and the ETA is the campaign's own
    # trial budget — not overstated by N× (each shard owes its slice)
    assert doc["shards"] == [f"camp+shard{i}" for i in range(3)]
    assert doc["eta_trials"] == pytest.approx(192.0)
    assert fed.serve() == 0
    e = fed.gateway.entries["camp"]
    assert e.status == "done" and e.converged
    assert e.result["status"] == "complete" and e.result["rc"] == 0
    assert e.result["trials"] == 192 and e.result["folds"] >= 1
    _assert_matches(fed, "camp", solo)
    # the stripes ran on three DISTINCT pods
    kids = [fed.gateway.entries[n] for n in e.shards]
    assert len({c.history[0]["pod"] for c in kids}) == 3
    # convergence revoked every shard's remaining quota through the
    # journaled seam; no orphan sub-tenants linger in any pod ledger
    for pod in fed.pods.values():
        if pod.sched is None:
            continue
        assert not [t for t in pod.sched.tenants.values()
                    if t.status in ("queued", "running")]
    # the speedup evidence the CI artifact pins: per-pod busy seconds
    assert set(fed.counters()["busy_s"]) == {"pod0", "pod1", "pod2"}


def test_federation_shards_one_is_unsharded(tmp_path):
    # degenerate shards: 1 — byte-for-byte the unsharded path: same
    # ledger shape, same WAL record kinds, no "+shard" sub-tenants
    from shrewd_tpu.federation.gateway import gateway_journal_path
    from shrewd_tpu.service.journal import FleetJournal

    plan = _plan(3, n_batches=4)
    solo = _solo_tallies(plan)
    kinds = {}
    for tag, spec in (("sharded1", TenantSpec(name="t", plan=plan.to_dict(),
                                              shards=1)),
                      ("plain", TenantSpec(name="t", plan=plan.to_dict()))):
        root = str(tmp_path / tag)
        fed = Federation(root, pod_names=("pod0", "pod1"))
        fed.submit(spec)
        assert fed.serve() == 0
        _assert_matches(fed, "t", solo)
        assert list(fed.gateway.entries) == ["t"]
        e = fed.gateway.entries["t"]
        assert e.shards == [] and e.fold_seq == 0
        records, _torn, _valid = FleetJournal.replay_path(
            gateway_journal_path(os.path.join(root, "gateway")))
        kinds[tag] = [r["kind"] for r in records]
    assert kinds["sharded1"] == kinds["plain"]
    assert "shard_split" not in kinds["sharded1"]


def test_federation_shards_exceed_pods_queue_surplus(tmp_path):
    # shards > pods: the surplus stays queued at the gateway (never
    # refused) and backfills as siblings finish; the merge still folds
    # every stripe and stays bit-identical
    plan = _plan(3, n_batches=4)
    solo = _solo_tallies(plan)
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0", "pod1"))
    doc = fed.submit(TenantSpec(name="camp", plan=plan.to_dict(),
                                shards=4))
    assert len(doc["shards"]) == 4
    e = fed.gateway.entries["camp"]
    kids = [fed.gateway.entries[n] for n in e.shards]
    assert len([c for c in kids if c.status == "placed"]) == 2
    assert len([c for c in kids if c.status == "accepted"]) == 2
    assert fed.serve() == 0
    assert all(c.status == "done" for c in kids)
    assert e.result["trials"] == 128
    _assert_matches(fed, "camp", solo)
    for pod in fed.pods.values():
        if pod.sched is None:
            continue
        assert not [t for t in pod.sched.tenants.values()
                    if t.status in ("queued", "running")]


def test_federation_kill_shard_failover_bit_identical(tmp_path):
    # shard death is not a new failure mode: kill_shard addresses the
    # pod by the SUB-TENANT it hosts, the supervisor's lease expires,
    # and the stripe fails over drain-here/recover-there exactly like
    # any tenant (PR-13 machinery) — merged tallies stay bit-identical
    plan = _plan(3, n_batches=6)
    solo = _solo_tallies(plan)
    chaos = ChaosEngine({"faults": [
        {"kind": "kill_shard", "shard": "camp+shard1", "at_round": 2}]})
    fed = Federation(str(tmp_path / "fed"),
                     pod_names=("pod0", "pod1", "pod2"),
                     chaos=chaos, expiry_rounds=2)
    fed.submit(TenantSpec(name="camp", plan=plan.to_dict(), shards=3))
    assert fed.serve() == 0
    assert chaos.injected == {"kill_shard": 1}
    assert chaos.survived == {"kill_shard": 1}
    assert len(fed.gateway.dead_pods) == 1
    assert fed.failovers >= 1
    _assert_matches(fed, "camp", solo)
    # the killed stripe moved off the dead pod and finished elsewhere
    dead = next(iter(fed.gateway.dead_pods))
    c = fed.gateway.entries["camp+shard1"]
    assert any(h["pod"] == dead for h in c.history)
    assert c.pod != dead and c.status == "done"


def test_shard_failover_prefers_pod_without_siblings(tmp_path):
    # stripe-aware failover placement, the choice pinned: the stranded
    # stripe prefers the pod NOT hosting a sibling shard even when that
    # pod carries MORE load — losing one more pod must not take out two
    # stripes of the same campaign (soft preference: _sibling_pods is
    # an ``avoid``, so a stripe still lands when every survivor hosts
    # a sibling)
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1", "pod2", "pod3"))
    fed.submit(TenantSpec(name="camp",
                          plan=_plan(3, n_batches=6).to_dict(), shards=3))
    gw = fed.gateway
    hosts = {gw.entries[f"camp+shard{i}"].pod for i in range(3)}
    assert len(hosts) == 3                    # distinct pods, hard rule
    spare = next(n for n in ("pod0", "pod1", "pod2", "pod3")
                 if n not in hosts)
    # load the sibling-free pod ABOVE the shard hosts: a purely
    # load-based pick would now choose a sibling host instead
    fed.submit(_spec("filler", 5, n_batches=6))
    assert gw.entries["filler"].pod == spare
    victim = gw.entries["camp+shard1"].pod
    gw.pod_dead(victim)
    e = gw.entries["camp+shard1"]
    assert e.pod == spare                     # spread beats load
    assert any(h["reason"] == "failover" for h in e.history)
    # and with no sibling-free pod left, liveness wins over spread:
    # the next death still places its stripe on a sibling host
    victim2 = gw.entries["camp+shard0"].pod
    gw.pod_dead(victim2)
    e0 = gw.entries["camp+shard0"]
    assert e0.pod in {n for n in ("pod0", "pod1", "pod2", "pod3")
                      if n not in (victim, victim2)}


def test_federation_partition_during_merge_bit_identical(tmp_path):
    # a pod partitions exactly while the merge is in flight (at_fold
    # keys on the journaled fold ordinal): its stripe fails over, the
    # partition heals, the stale placement is fenced — and the merged
    # trajectory still folds to the solo tallies (enough batches per
    # stripe that the campaign outlives the window and sees the heal)
    plan = _plan(3, n_batches=9)
    solo = _solo_tallies(plan)
    chaos = ChaosEngine({"faults": [
        {"kind": "partition_during_merge", "pod": "pod2", "at_fold": 1,
         "rounds": 3}]})
    fed = Federation(str(tmp_path / "fed"),
                     pod_names=("pod0", "pod1", "pod2"),
                     chaos=chaos, expiry_rounds=2)
    fed.submit(TenantSpec(name="camp", plan=plan.to_dict(), shards=3))
    assert fed.serve() == 0
    assert chaos.injected == {"partition_during_merge": 1}
    assert chaos.survived == {"partition_during_merge": 1}
    assert "pod2" not in fed.gateway.dead_pods    # healed, not dead
    e = fed.gateway.entries["camp"]
    assert e.result["converged"] is True
    _assert_matches(fed, "camp", solo)


def test_gateway_sharded_crashcheck_sweep(tmp_path):
    # the merge-ledger durability pin: recover the federation from
    # EVERY gateway-WAL boundary of a SHARDED run — including each
    # shard_split / shard_fold / shard_converged append and its torn
    # variant — and require bit-identical merged tallies at each
    plans = crashcheck.small_fleet_plans(seeds=(3,), n_batches=4)
    doc = crashcheck.run_gateway_crashcheck(
        str(tmp_path / "sweep"), plans=plans,
        pod_names=("pod0", "pod1"), shards={"t0": 2})
    assert doc["ok"] is True and doc["failures"] == []
    assert doc["shards"] == {"t0": 2}
    by_kind = doc["boundaries_by_kind"]
    assert by_kind.get("shard_split", 0) >= 1
    assert by_kind.get("shard_fold", 0) >= 1
    assert by_kind.get("shard_converged", 0) >= 1
    assert doc["torn_checks"] >= 3


# --- the thin HTTP front ----------------------------------------------------

def test_http_front_submit_and_status(tmp_path):
    solo = _solo_tallies(_plan(3, n_batches=3))
    root = str(tmp_path / "fed")
    gw_dir = os.path.join(root, "gateway")
    front = GatewayHTTPFront(gw_dir, port=0).start()
    try:
        base = f"http://127.0.0.1:{front.port}"
        # health
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert json.load(r)["ok"] is True
        # submit a tenant over the wire -> the gateway spool
        spec = TenantSpec(name="web", plan=_plan(3, n_batches=3
                                                 ).to_dict(), slo_s=600)
        req = urllib.request.Request(
            f"{base}/submit", data=json.dumps(spec.to_dict()).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.load(r)
        assert doc["tenant"] == "web" and doc["ticket"]
        # a malformed submission is a 400, not a wedge
        bad = urllib.request.Request(f"{base}/submit", data=b"{nope")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
        # the federation claims the spooled submission and serves it
        fed = Federation(root, pod_names=("pod0", "pod1"))
        assert fed.serve() == 0
        _assert_matches(fed, "web", solo)
        assert fed.gateway.entries["web"].spec.slo_s == 600
        # /status serves the persisted routing ledger
        with urllib.request.urlopen(f"{base}/status", timeout=10) as r:
            snap = json.load(r)
        assert snap["entries"][0]["spec"]["name"] == "web"
        assert snap["entries"][0]["status"] == "done"
    finally:
        front.stop()
