"""Ingestion-layer tests: m5.cpt, config.ini/json, stats.txt, re-warm."""

import gzip
import io
import os

import numpy as np
import pytest

from shrewd_tpu import stats as statsmod
from shrewd_tpu.ingest import (ArchSnapshot, CheckpointIn, load_arch_snapshot,
                               load_config_ini, load_stats_txt,
                               window_from_snapshot, write_arch_snapshot)
from shrewd_tpu.ingest.configfile import find_params, tree_from_ini
from shrewd_tpu.ingest.statsfile import diff_stats
from shrewd_tpu.ingest.warm import lift_memory, lift_registers
from shrewd_tpu.isa import semantics
from shrewd_tpu.trace.format import Trace
from shrewd_tpu.trace.synth import WorkloadConfig
from shrewd_tpu.utils.config import ConfigObject, Param

# A literal checkpoint in the reference's on-disk shape (hand-written, NOT
# produced by our writer — guards reader and writer against sharing a bug).
# 4 uint64 int regs (little-endian byte dumps), pc, one 32-byte memory store.
_CPT_TEXT = """\
[Globals]
curTick=1000500
version_tags=mover-64 x86-gs-base

[system.cpu0.xc.0]
regs.integer=1 0 0 0 0 0 0 0 2 0 0 0 0 0 0 0 255 255 255 255 0 0 0 0 0 1 0 0 0 0 0 0
regs.floating_point=0 0 0 0 0 0 0 0 64 64 0 0 0 0 0 0
_pc=4198400
_upc=0

[system.physmem.store0]
store_id=0
filename=system.physmem.store0.pmem
range_size=32
"""


@pytest.fixture
def cpt_dir(tmp_path):
    d = tmp_path / "cpt.1000500"
    d.mkdir()
    (d / "m5.cpt").write_text(_CPT_TEXT)
    mem = bytes(range(32))
    with gzip.open(d / "system.physmem.store0.pmem", "wb") as f:
        f.write(mem)
    return str(d)


def test_checkpoint_reader(cpt_dir):
    cpt = CheckpointIn(cpt_dir)
    assert cpt.section_exists("Globals")
    assert cpt.get_int("Globals", "curTick") == 1000500
    assert cpt.find("system.physmem.store0", "filename").endswith(".pmem")
    size, data = cpt.load_store("system.physmem.store0")
    assert size == 32 and data[5] == 5
    assert list(cpt.find_sections(r"system\.cpu\d+\.xc\.\d+")) == \
        ["system.cpu0.xc.0"]


def test_arch_snapshot(cpt_dir):
    snap = load_arch_snapshot(cpt_dir)
    assert snap.cur_tick == 1000500
    assert snap.version_tags == ("mover-64", "x86-gs-base")
    assert snap.pc == 4198400
    assert snap.int_regs.tolist() == [1, 2, 0xFFFFFFFF, 0x100]
    assert snap.float_regs.tolist() == [0, 0x4040]
    assert snap.mem.size == 32 and snap.mem[31] == 31


def test_snapshot_round_trip(cpt_dir, tmp_path):
    snap = load_arch_snapshot(cpt_dir)
    out = str(tmp_path / "cpt.out")
    write_arch_snapshot(out, snap)
    back = load_arch_snapshot(out)
    assert back.cur_tick == snap.cur_tick
    assert back.pc == snap.pc
    np.testing.assert_array_equal(back.int_regs, snap.int_regs)
    np.testing.assert_array_equal(back.float_regs, snap.float_regs)
    np.testing.assert_array_equal(back.mem, snap.mem)


def test_missing_entry_raises(cpt_dir):
    cpt = CheckpointIn(cpt_dir)
    with pytest.raises(KeyError):
        cpt.find("Globals", "nonesuch")


def test_bad_thread_index_raises(cpt_dir):
    with pytest.raises(ValueError, match="thread index 1 out of range"):
        load_arch_snapshot(cpt_dir, thread=1)
    with pytest.raises(ValueError, match="out of range"):
        load_arch_snapshot(cpt_dir, thread=-1)


def test_store_layout_recorded(cpt_dir):
    snap = load_arch_snapshot(cpt_dir)
    assert snap.store_layout == (("system.physmem.store0", 32),)


def test_lift_registers_rejects_truncation():
    snap = _mk_snapshot(nregs=8)           # 16 uint32 halves
    with pytest.raises(ValueError, match="nphys >= 16"):
        lift_registers(snap, 8)


# --- config.ini -------------------------------------------------------------

class _Leaf(ConfigObject):
    depth = Param(int, 3, "leaf depth")


class _Root(ConfigObject):
    width = Param(int, 7, "root width")


def test_config_ini_round_trip(tmp_path):
    from shrewd_tpu.utils.config import Child

    class _Tree(ConfigObject):
        width = Param(int, 7, "")
        leaf = Child(_Leaf)

    path = tmp_path / "config.ini"
    _Tree(width=9).dump_ini(path)
    sections = load_config_ini(str(path))
    assert sections["root"]["width"] == "9"
    assert sections["root.leaf"]["depth"] == "3"
    tree = tree_from_ini(sections)
    assert tree["root"]["leaf"]["depth"] == "3"
    assert find_params(tree, "depth") == [("root.leaf.depth", "3")]


# --- stats.txt --------------------------------------------------------------

def test_stats_txt_round_trip():
    g = statsmod.Group("sim")
    g.trials = statsmod.Scalar("trials", "trials run")
    g.trials += 12345
    g.outcomes = statsmod.Vector("outcomes", 2, subnames=["masked", "sdc"])
    g.outcomes += [10, 2]
    text = statsmod.dump_text(g)
    blocks = load_stats_txt(io.StringIO(text))
    assert len(blocks) == 1
    b = blocks[0]
    assert b["sim.trials"] == 12345
    assert b["sim.outcomes::sdc"] == 2
    assert b["sim.outcomes::total"] == 12


def test_stats_txt_multiple_blocks_and_diff():
    text = "\n".join([
        "---------- Begin Simulation Statistics ----------",
        "simSeconds 0.001 # seconds simulated",
        "simTicks 1000000  # ticks",
        "---------- End Simulation Statistics   ----------",
        "---------- Begin Simulation Statistics ----------",
        "simSeconds 0.002 # seconds simulated",
        "simTicks 2000000  # ticks",
        "---------- End Simulation Statistics   ----------",
    ])
    blocks = load_stats_txt(io.StringIO(text))
    assert len(blocks) == 2
    assert blocks[1]["simTicks"] == 2000000
    bad = diff_stats(blocks[0], blocks[1])
    assert set(bad) == {"simSeconds", "simTicks"}
    assert diff_stats(blocks[0], blocks[0]) == []
    # masked comparison: ignore timing-dependent stats (MatchStdoutNoPerf)
    assert diff_stats(blocks[0], blocks[1], ignore=("sim",)) == []


def test_diff_stats_nan_transitions_flagged():
    nan = float("nan")
    assert diff_stats({"x": nan}, {"x": 1.0}) == ["x"]
    assert diff_stats({"x": 1.0}, {"x": nan}) == ["x"]
    assert diff_stats({"x": nan}, {"x": nan}) == []


def test_numeric_aware_section_sort():
    from shrewd_tpu.ingest.cpt import _numeric_aware_key
    names = ["s.cpu10.xc.0", "s.cpu2.xc.0", "s.cpu1.xc.0"]
    assert sorted(names, key=_numeric_aware_key) == \
        ["s.cpu1.xc.0", "s.cpu2.xc.0", "s.cpu10.xc.0"]
    stores = ["p.store10", "p.store2"]
    assert sorted(stores, key=_numeric_aware_key) == ["p.store2", "p.store10"]


def test_stats_txt_markerless():
    blocks = load_stats_txt(io.StringIO("a 1\nb 2.5\n"))
    assert blocks == [{"a": 1, "b": 2.5}]


# --- re-warm ----------------------------------------------------------------

def _mk_snapshot(nregs=8, mem_bytes=256, pc=0x1000):
    rng = np.random.default_rng(3)
    return ArchSnapshot(
        cur_tick=42, version_tags=("t",), pc=pc,
        int_regs=rng.integers(0, 1 << 63, size=nregs, dtype=np.uint64),
        float_regs=np.zeros(0, np.uint64),
        mem=rng.integers(0, 256, size=mem_bytes, dtype=np.uint8).astype(np.uint8),
        thread_section="system.cpu.xc.0")


def test_lift_registers_interleaves_halves():
    snap = _mk_snapshot(nregs=2)
    out = lift_registers(snap, 16)
    assert out[0] == snap.int_regs[0] & 0xFFFFFFFF
    assert out[1] == snap.int_regs[0] >> 32
    assert out[2] == snap.int_regs[1] & 0xFFFFFFFF
    # deterministic fill beyond arch state
    again = lift_registers(snap, 16)
    np.testing.assert_array_equal(out, again)


def test_lift_memory_words_and_zero_fill():
    snap = _mk_snapshot(mem_bytes=8)
    out = lift_memory(snap, 4)
    expect0 = int.from_bytes(snap.mem[:4].tobytes(), "little")
    assert out[0] == expect0
    assert out[2] == 0 and out[3] == 0


def test_window_from_snapshot_replayable():
    snap = _mk_snapshot(mem_bytes=4096)
    cfg = WorkloadConfig(n=64, nphys=32, mem_words=64,
                         working_set_words=32, seed=11)
    trace = window_from_snapshot(snap, cfg, warmup=16)
    assert trace.n == 64
    # golden scalar replay runs clean over the warmed window (in-range
    # addresses) and reproduces the recorded branch outcomes
    from shrewd_tpu.isa import uops as U
    reg = trace.init_reg.copy()
    mem = trace.init_mem.copy()
    got = semantics.scalar_replay(trace, reg, mem)
    is_br = (trace.opcode >= U.BEQ) & (trace.opcode <= U.BGE)
    np.testing.assert_array_equal(np.asarray(got, dtype=np.int32),
                                  trace.taken[is_br])


def test_window_from_snapshot_warmup_changes_state():
    snap = _mk_snapshot(mem_bytes=4096)
    cfg = WorkloadConfig(n=32, nphys=32, mem_words=64,
                         working_set_words=32, seed=5)
    cold = window_from_snapshot(snap, cfg, warmup=0)
    warm = window_from_snapshot(snap, cfg, warmup=32)
    assert not np.array_equal(cold.init_reg, warm.init_reg)
