"""Design-space search tests (search/protect.py)."""

import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.search import (DEFAULT_SCHEMES, DesignSpace, Scheme,
                               StructureProfile, shadow_scheme)
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def profile(name, bits, masked, sdc, due, det=0, fit=1e-3):
    return StructureProfile.from_tally(
        name, bits, np.array([masked, sdc, due, det]), fit_per_bit=fit)


def test_from_tally_normalizes():
    p = profile("regfile", 8192, 60, 30, 10)
    np.testing.assert_allclose(p.probs.sum(), 1.0)
    assert p.probs[C.OUTCOME_SDC] == 0.3
    assert p.fit == pytest.approx(8192 * 1e-3)


def test_scheme_validation():
    with pytest.raises(ValueError):
        Scheme("bad", 0.7, 0.5, 1.2).validate()   # detect+correct > 1
    with pytest.raises(ValueError):
        Scheme("bad", 0.0, 0.0, 0.5).validate()   # area < 1


def test_unprotected_baseline_math():
    p = profile("regfile", 1000, 50, 40, 10)
    ds = DesignSpace([p], schemes=[DEFAULT_SCHEMES[0]])
    sdc, due, area = (np.asarray(x) for x in ds.evaluate(ds.enumerate()))
    assert sdc[0] == pytest.approx(1000 * 1e-3 * 0.4)
    assert due[0] == pytest.approx(1000 * 1e-3 * 0.1)
    assert area[0] == pytest.approx(1000.0)


def test_correction_converts_to_masked_detection_to_detected():
    p = profile("rf", 1000, 0, 100, 0)
    ds = DesignSpace([p])
    cfgs = ds.enumerate()
    sdc, due, area = (np.asarray(x) for x in ds.evaluate(cfgs))
    by_name = {DEFAULT_SCHEMES[k].name: i
               for i, (k,) in enumerate(cfgs)}
    assert sdc[by_name["parity"]] == pytest.approx(0.0)    # full detection
    assert sdc[by_name["tmr"]] == pytest.approx(0.0)       # full correction
    # DMR doubles the fault targets but detects everything
    assert sdc[by_name["dmr"]] == pytest.approx(0.0)
    assert area[by_name["dmr"]] == pytest.approx(2000.0)


def test_search_picks_min_area_feasible():
    # big vulnerable structure + small benign one: protecting only the big
    # one should win; schemes: none / cheap-detect / expensive-correct
    schemes = [Scheme("none", 0, 0, 1.0),
               Scheme("parity", 1.0, 0, 1.1),
               Scheme("tmr", 0, 1.0, 3.0)]
    big = profile("rob", 10_000, 20, 70, 10)
    small = profile("iq", 100, 90, 5, 5)
    ds = DesignSpace([big, small], schemes=schemes)
    target = small.fit * 0.05 * 1.5     # small's raw SDC passes; big's cannot
    res = ds.search(target)
    assert res.feasible
    assert res.assignment == {"rob": "parity", "iq": "none"}
    assert res.area == pytest.approx(10_000 * 1.1 + 100)
    assert res.sdc_rate <= target
    assert res.baseline_sdc > target
    assert res.n_configs == 9


def test_search_infeasible_reports_closest():
    p = profile("rf", 1000, 0, 100, 0)
    ds = DesignSpace([p], schemes=[Scheme("none", 0, 0, 1.0),
                                   Scheme("weak", 0.5, 0, 1.2)])
    res = ds.search(0.0)    # unreachable: weak residual SDC > 0
    assert not res.feasible
    assert res.assignment == {"rf": "weak"}


def test_pareto_front_monotone():
    ds = DesignSpace([profile("a", 1000, 50, 40, 10),
                      profile("b", 2000, 80, 15, 5)])
    res = ds.search(1e-9)
    areas = [a for a, _, _ in res.pareto]
    sdcs = [s for _, s, _ in res.pareto]
    assert areas == sorted(areas)
    assert sdcs == sorted(sdcs, reverse=True)
    assert len(res.pareto) >= 2


def test_allowed_restricts_space():
    ds = DesignSpace([profile("fu", 500, 40, 50, 10),
                      profile("rf", 1000, 70, 20, 10)],
                     schemes=[Scheme("none", 0, 0, 1.0),
                              Scheme("shadow", 0.8, 0, 1.5),
                              Scheme("secded", 0, 1.0, 1.2)],
                     allowed={"fu": [0, 1], "rf": [0, 2]})
    cfgs = ds.enumerate()
    assert len(cfgs) == 4
    assert set(map(tuple, cfgs)) == {(0, 0), (0, 2), (1, 0), (1, 2)}
    with pytest.raises(KeyError):
        DesignSpace([profile("fu", 1, 1, 0, 0)], allowed={"nope": [0]})


def test_shadow_scheme_from_kernel():
    from shrewd_tpu.ops.trial import TrialKernel
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=11))
    k = TrialKernel(t, O3Config(shadow_model="fupool"))
    s = shadow_scheme(k, area=1.4)
    assert s.name == "shadow"
    assert 0.0 < s.detect <= 1.0
    assert s.correct == 0.0 and s.area == 1.4
    # disabled SHREWD → zero detection
    assert shadow_scheme(k.with_shrewd(enable=False)).detect == 0.0


def test_end_to_end_campaign_to_search():
    """Measured raw tallies (enable_shrewd=False) → profiles → search."""
    import jax
    from shrewd_tpu.ops.trial import TrialKernel
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=128,
                                working_set_words=64, seed=12))
    k = TrialKernel(t, O3Config(enable_shrewd=False))
    keys = jax.random.split(jax.random.key(3), 256)
    profiles = []
    bits = {"regfile": 64 * 32, "rob": 192 * 16, "lsq": 32 * 64}
    for s, b in bits.items():
        tally = np.asarray(k.run_keys(keys, s))
        profiles.append(StructureProfile.from_tally(s, b, tally))
    ds = DesignSpace(profiles)
    res = ds.search(res_target := ds.search(0.0).baseline_sdc * 0.01)
    assert res.n_configs == len(DEFAULT_SCHEMES) ** 3
    assert res.feasible            # TMR everywhere always reaches 1% residual
    assert res.sdc_rate <= res_target
