"""Design-space search tests (search/protect.py)."""

import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.search import (DEFAULT_SCHEMES, DesignSpace, Scheme,
                               StructureProfile, shadow_scheme)
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def profile(name, bits, masked, sdc, due, det=0, fit=1e-3):
    return StructureProfile.from_tally(
        name, bits, np.array([masked, sdc, due, det]), fit_per_bit=fit)


def test_from_tally_normalizes():
    p = profile("regfile", 8192, 60, 30, 10)
    np.testing.assert_allclose(p.probs.sum(), 1.0)
    assert p.probs[C.OUTCOME_SDC] == 0.3
    assert p.fit == pytest.approx(8192 * 1e-3)


def test_scheme_validation():
    with pytest.raises(ValueError):
        Scheme("bad", 0.7, 0.5, 1.2).validate()   # detect+correct > 1
    with pytest.raises(ValueError):
        Scheme("bad", 0.0, 0.0, 0.5).validate()   # area < 1


def test_unprotected_baseline_math():
    p = profile("regfile", 1000, 50, 40, 10)
    ds = DesignSpace([p], schemes=[DEFAULT_SCHEMES[0]])
    sdc, due, area = (np.asarray(x) for x in ds.evaluate(ds.enumerate()))
    assert sdc[0] == pytest.approx(1000 * 1e-3 * 0.4)
    assert due[0] == pytest.approx(1000 * 1e-3 * 0.1)
    assert area[0] == pytest.approx(1000.0)


def test_correction_converts_to_masked_detection_to_detected():
    p = profile("rf", 1000, 0, 100, 0)
    ds = DesignSpace([p])
    cfgs = ds.enumerate()
    sdc, due, area = (np.asarray(x) for x in ds.evaluate(cfgs))
    by_name = {DEFAULT_SCHEMES[k].name: i
               for i, (k,) in enumerate(cfgs)}
    assert sdc[by_name["parity"]] == pytest.approx(0.0)    # full detection
    assert sdc[by_name["tmr"]] == pytest.approx(0.0)       # full correction
    # DMR doubles the fault targets but detects everything
    assert sdc[by_name["dmr"]] == pytest.approx(0.0)
    assert area[by_name["dmr"]] == pytest.approx(2000.0)


def test_search_picks_min_area_feasible():
    # big vulnerable structure + small benign one: protecting only the big
    # one should win; schemes: none / cheap-detect / expensive-correct
    schemes = [Scheme("none", 0, 0, 1.0),
               Scheme("parity", 1.0, 0, 1.1),
               Scheme("tmr", 0, 1.0, 3.0)]
    big = profile("rob", 10_000, 20, 70, 10)
    small = profile("iq", 100, 90, 5, 5)
    ds = DesignSpace([big, small], schemes=schemes)
    target = small.fit * 0.05 * 1.5     # small's raw SDC passes; big's cannot
    res = ds.search(target)
    assert res.feasible
    assert res.assignment == {"rob": "parity", "iq": "none"}
    assert res.area == pytest.approx(10_000 * 1.1 + 100)
    assert res.sdc_rate <= target
    assert res.baseline_sdc > target
    assert res.n_configs == 9


def test_search_infeasible_reports_closest():
    p = profile("rf", 1000, 0, 100, 0)
    ds = DesignSpace([p], schemes=[Scheme("none", 0, 0, 1.0),
                                   Scheme("weak", 0.5, 0, 1.2)])
    res = ds.search(0.0)    # unreachable: weak residual SDC > 0
    assert not res.feasible
    assert res.assignment == {"rf": "weak"}


def test_pareto_front_monotone():
    ds = DesignSpace([profile("a", 1000, 50, 40, 10),
                      profile("b", 2000, 80, 15, 5)])
    res = ds.search(1e-9)
    areas = [a for a, _, _ in res.pareto]
    sdcs = [s for _, s, _ in res.pareto]
    assert areas == sorted(areas)
    assert sdcs == sorted(sdcs, reverse=True)
    assert len(res.pareto) >= 2


def test_allowed_restricts_space():
    ds = DesignSpace([profile("fu", 500, 40, 50, 10),
                      profile("rf", 1000, 70, 20, 10)],
                     schemes=[Scheme("none", 0, 0, 1.0),
                              Scheme("shadow", 0.8, 0, 1.5),
                              Scheme("secded", 0, 1.0, 1.2)],
                     allowed={"fu": [0, 1], "rf": [0, 2]})
    cfgs = ds.enumerate()
    assert len(cfgs) == 4
    assert set(map(tuple, cfgs)) == {(0, 0), (0, 2), (1, 0), (1, 2)}
    with pytest.raises(KeyError):
        DesignSpace([profile("fu", 1, 1, 0, 0)], allowed={"nope": [0]})


def test_shadow_scheme_from_kernel():
    from shrewd_tpu.ops.trial import TrialKernel
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=11))
    k = TrialKernel(t, O3Config(shadow_model="fupool"))
    s = shadow_scheme(k, area=1.4)
    assert s.name == "shadow"
    assert 0.0 < s.detect <= 1.0
    assert s.correct == 0.0 and s.area == 1.4
    # disabled SHREWD → zero detection
    assert shadow_scheme(k.with_shrewd(enable=False)).detect == 0.0


def test_end_to_end_campaign_to_search():
    """Measured raw tallies (enable_shrewd=False) → profiles → search."""
    import jax
    from shrewd_tpu.ops.trial import TrialKernel
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=128,
                                working_set_words=64, seed=12))
    k = TrialKernel(t, O3Config(enable_shrewd=False))
    keys = jax.random.split(jax.random.key(3), 256)
    profiles = []
    bits = {"regfile": 64 * 32, "rob": 192 * 16, "lsq": 32 * 64}
    for s, b in bits.items():
        tally = np.asarray(k.run_keys(keys, s))
        profiles.append(StructureProfile.from_tally(s, b, tally))
    ds = DesignSpace(profiles)
    res = ds.search(res_target := ds.search(0.0).baseline_sdc * 0.01)
    assert res.n_configs == len(DEFAULT_SCHEMES) ** 3
    assert res.feasible            # TMR everywhere always reaches 1% residual
    assert res.sdc_rate <= res_target


# --- live (half-width-aware) profiles + exec-cache routing -----------------
#
# The scenario-matrix Pareto loop (shrewd_tpu/scenario/) re-fits
# profiles from RUNNING campaigns after every fleet fold: from_tally
# must accept unconverged tallies with their live CI half-width, expose
# conservative bounds, and the DesignSpace sweep must route through the
# content-keyed executable cache so every fold over unchanged tallies
# reuses one compiled executable.

def test_from_tally_records_halfwidth_and_bounds():
    p = StructureProfile.from_tally(
        "rf", 1024, np.array([60, 30, 10, 0]), halfwidth=0.05)
    assert p.halfwidth == 0.05
    assert p.p_hi(C.OUTCOME_SDC) == pytest.approx(0.35)
    assert p.p_lo(C.OUTCOME_SDC) == pytest.approx(0.25)
    # bounds clip to [0, 1]
    z = StructureProfile.from_tally(
        "rf", 1024, np.array([100, 0, 0, 0]), halfwidth=0.1)
    assert z.p_lo(C.OUTCOME_SDC) == 0.0
    with pytest.raises(ValueError, match="halfwidth"):
        StructureProfile.from_tally("rf", 1024, np.array([1, 0, 0, 0]),
                                    halfwidth=1.5)


def test_from_tally_conservative_takes_upper_vulnerable_bounds():
    p = StructureProfile.from_tally(
        "rf", 1024, np.array([50, 40, 10, 0]), halfwidth=0.1,
        conservative=True)
    # SDC/DUE at their +halfwidth bounds, non-vulnerable mass rescaled,
    # still a distribution
    assert p.probs[C.OUTCOME_SDC] == pytest.approx(0.5)
    assert p.probs[C.OUTCOME_DUE] == pytest.approx(0.2)
    assert p.probs.sum() == pytest.approx(1.0)
    # converged (hw=0) conservative fit is the plain fit
    q = StructureProfile.from_tally(
        "rf", 1024, np.array([50, 40, 10, 0]), conservative=True)
    np.testing.assert_allclose(q.probs, [0.5, 0.4, 0.1, 0.0])
    # saturation: when the +hw bounds cannot all fit, the added mass is
    # capped at the remaining headroom — NEVER renormalized below the
    # observed point estimates (the worst-case contract)
    s = StructureProfile.from_tally(
        "rf", 1024, np.array([0, 90, 10, 0]), halfwidth=0.3,
        conservative=True)
    assert s.probs[C.OUTCOME_SDC] >= 0.9 - 1e-12
    assert s.probs[C.OUTCOME_DUE] >= 0.1 - 1e-12
    assert s.probs.sum() == pytest.approx(1.0)
    h = StructureProfile.from_tally(
        "rf", 1024, np.array([10, 70, 20, 0]), halfwidth=0.3,
        conservative=True)
    assert h.probs[C.OUTCOME_SDC] >= 0.7 and h.probs[C.OUTCOME_DUE] >= 0.2
    assert h.probs.sum() == pytest.approx(1.0)


def test_design_space_evaluate_routes_through_exec_cache():
    from shrewd_tpu.parallel import exec_cache

    p = profile("rf", 1000, 50, 40, 10)
    before = exec_cache.cache().stats()
    ds1 = DesignSpace([p])
    mid = exec_cache.cache().stats()
    assert mid["compiled"] == before["compiled"] + 1
    # an equal-content space REUSES the compiled sweep (the per-fold
    # economy of the scenario Pareto loop)...
    ds2 = DesignSpace([p])
    after = exec_cache.cache().stats()
    assert after["compiled"] == mid["compiled"]
    assert after["reused"] == mid["reused"] + 1
    r1 = [np.asarray(x) for x in ds1.evaluate(ds1.enumerate())]
    r2 = [np.asarray(x) for x in ds2.evaluate(ds2.enumerate())]
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)
    # ...while different content compiles its own executable
    p2 = profile("rf", 1000, 50, 41, 9)
    DesignSpace([p2])
    assert exec_cache.cache().stats()["compiled"] == \
        after["compiled"] + 1
