"""MinorCPU pipeline-latch fault model: directed + sampler tests."""

import jax.numpy as jnp
import numpy as np

from shrewd_tpu.isa import uops as U
from shrewd_tpu.models.minor import (FIELD_NAMES, MinorConfig,
                                     MinorFaultSampler, OPCODE_BITS)
from shrewd_tpu.models.o3 import (KIND_IQ_SRC1, KIND_IQ_SRC2, KIND_LATCH_IMM,
                                  KIND_LATCH_OP, KIND_ROB_DST)
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng

from tests.test_replay import fault, mini_trace, run


def test_opcode_flip_to_illegal_is_due():
    # With 32 opcodes the 5-bit field saturates: every single-bit flip
    # lands on a defined opcode (MULHU filled slot 31), so the illegal
    # path needs a wider (multi-latch) flip — bit 5 → 15^32 = 47 ≥ 32 →
    # illegal µop → DUE.  The kernel semantics (out-of-range opcode
    # traps) is what this pins, not the sampler's reachable bit range.
    t = mini_trace([
        (U.SLT, 1, 2, 3, 0, 0),
        (U.ADD, 4, 1, 2, 0, 0),
    ])
    assert U.SLT ^ (1 << 5) >= U.N_OPCODES
    r = run(t, fault(kind=KIND_LATCH_OP, cycle=0, entry=0, bit=5))
    assert bool(r.trapped)
    golden = run(t, fault())
    assert C.classify(r, golden) == C.OUTCOME_DUE


def test_opcode_flip_to_other_legal_op_corrupts():
    # ADD (1) bit 2 → 5 = XOR: r1 = r2 ^ r3 instead of r2 + r3 → SDC
    t = mini_trace([(U.ADD, 1, 2, 3, 0, 0)])
    r = run(t, fault(kind=KIND_LATCH_OP, cycle=0, entry=0, bit=2))
    golden = run(t, fault())
    reg = np.asarray(r.reg)
    greg = np.asarray(golden.reg)
    assert reg[1] == (7 ^ 10)          # init_reg[i] = 3i+1
    assert greg[1] == 7 + 10
    assert C.classify(r, golden) == C.OUTCOME_SDC


def test_opcode_flip_branch_to_nonbranch_diverges():
    # BNE (20) with unequal srcs (taken=1); flip bit 4 → 4 = OR → no branch
    # executed where golden took one → control divergence
    t = mini_trace([(U.BNE, 0, 2, 3, 0, 1)])
    r = run(t, fault(kind=KIND_LATCH_OP, cycle=0, entry=0, bit=4))
    assert bool(r.diverged)
    golden = run(t, fault())
    assert C.classify(r, golden) == C.OUTCOME_SDC


def test_imm_flip_changes_result():
    # ADDI r1 = r2 + 4; flip imm bit 5 → +36
    t = mini_trace([(U.ADDI, 1, 2, 0, 4, 0)])
    r = run(t, fault(kind=KIND_LATCH_IMM, cycle=0, entry=0, bit=5))
    assert np.asarray(r.reg)[1] == 7 + (4 ^ 32)
    golden = run(t, fault())
    assert C.classify(r, golden) == C.OUTCOME_SDC


def test_imm_flip_on_dead_value_masked():
    # flip imm of an ADDI whose destination is overwritten before any read
    t = mini_trace([
        (U.ADDI, 1, 2, 0, 4, 0),
        (U.LUI, 1, 0, 0, 99, 0),       # overwrites r1
    ])
    r = run(t, fault(kind=KIND_LATCH_IMM, cycle=0, entry=0, bit=5))
    golden = run(t, fault())
    assert C.classify(r, golden) == C.OUTCOME_MASKED


def test_bubble_fault_is_masked():
    # entry outside the window (latch held a bubble) → no effect
    t = mini_trace([(U.ADD, 1, 2, 3, 0, 0)])
    for entry in (-1, -3, 5):
        r = run(t, fault(kind=KIND_LATCH_OP, cycle=entry, entry=entry, bit=1))
        golden = run(t, fault())
        assert C.classify(r, golden) == C.OUTCOME_MASKED


def test_sampler_fields_and_bits_in_range():
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=128,
                                working_set_words=64, seed=3))
    s = MinorFaultSampler(t, MinorConfig())
    keys = prng.trial_keys(prng.campaign_key(11), 2048)
    f = s.sample_batch(keys)
    kind = np.asarray(f.kind)
    bit = np.asarray(f.bit)
    entry = np.asarray(f.entry)
    idx_bits = int(np.log2(t.nphys))
    widths = {KIND_LATCH_OP: OPCODE_BITS, KIND_ROB_DST: idx_bits,
              KIND_IQ_SRC1: idx_bits, KIND_IQ_SRC2: idx_bits,
              KIND_LATCH_IMM: 32}
    # every latch field kind gets drawn, bits stay within field widths
    assert set(widths) == set(np.unique(kind))
    for k, w in widths.items():
        sel = kind == k
        assert sel.any()
        assert (bit[sel] >= 0).all() and (bit[sel] < w).all()
    # field probability ∝ width (imm is 32 of the 55-bit latch for nphys=64)
    total = sum(widths.values())
    frac_imm = (kind == KIND_LATCH_IMM).mean()
    assert abs(frac_imm - 32 / total) < 0.05
    # entries span the window incl. out-of-range bubbles at both edges
    assert entry.min() < 0
    assert entry.max() >= t.n - 1
    assert (entry < t.n + s.n_latches).all()


def test_latch_structure_via_trial_kernel():
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=128,
                                working_set_words=64, seed=4))
    k = TrialKernel(t)
    keys = prng.trial_keys(prng.campaign_key(12), 512)
    tally = np.asarray(k.run_keys(keys, "latch"))
    assert tally.sum() == 512
    assert tally[C.OUTCOME_MASKED] > 0      # bubbles + dead values exist
    assert tally[C.OUTCOME_SDC] > 0         # latch faults do corrupt


def test_field_names_table():
    assert FIELD_NAMES == ["opcode", "dst", "src1", "src2", "imm"]


def test_minor_cfg_plumbed_through_trial_kernel():
    t = generate(WorkloadConfig(n=64, nphys=16, mem_words=64,
                                working_set_words=32, seed=6))
    k = TrialKernel(t, minor_cfg=MinorConfig(depth=6))
    assert k.sampler("latch").n_latches == 5


def test_trace_validate_rejects_taken_on_nonbranch():
    import pytest
    t = mini_trace([(U.ADD, 1, 2, 3, 0, 0)])
    bad = t._replace(taken=np.array([1], dtype=np.int32))
    with pytest.raises(ValueError, match="non-branch"):
        bad.validate()
