import numpy as np
import pytest

from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.trace import format as tfmt, synth
from shrewd_tpu.trace.synth import WorkloadConfig


def test_opclass_table_total():
    assert len(U.OPCODE_NAMES) == U.N_OPCODES
    ocs = U.opclass_of(np.arange(U.N_OPCODES))
    assert ocs.shape == (U.N_OPCODES,)
    assert U.opclass_of(U.MUL) == U.OC_INT_MULT
    assert U.opclass_of(U.LOAD) == U.OC_MEM_READ


def test_alu_semantics():
    M = 0xFFFFFFFF
    assert semantics.alu(U.ADD, M, 1, 0) == 0            # wraparound
    assert semantics.alu(U.SUB, 0, 1, 0) == M
    assert semantics.alu(U.SRA, 0x80000000, 31, 0) == M  # sign extension
    assert semantics.alu(U.SRL, 0x80000000, 31, 0) == 1
    assert semantics.alu(U.SLT, 0xFFFFFFFF, 0, 0) == 1   # -1 < 0 signed
    assert semantics.alu(U.SLTU, 0xFFFFFFFF, 0, 0) == 0
    assert semantics.alu(U.MUL, 0x10000, 0x10000, 0) == 0
    assert semantics.alu(U.BGE, 5, 5, 0) == 1
    assert semantics.alu(U.LOAD, 100, 0, 24) == 124      # effective address


def test_generate_valid_and_deterministic():
    cfg = WorkloadConfig(n=512, nphys=64, mem_words=256,
                         working_set_words=128, seed=7)
    t1 = synth.generate(cfg)
    t2 = synth.generate(cfg)
    for f in tfmt.Trace._fields:
        np.testing.assert_array_equal(getattr(t1, f), getattr(t2, f))
    t1.validate()
    # mix roughly matches request
    frac_load = (t1.opcode == U.LOAD).mean()
    assert 0.1 < frac_load < 0.3


def test_generated_addresses_in_working_set():
    cfg = WorkloadConfig(n=1024, nphys=64, mem_words=256,
                         working_set_words=64, seed=3)
    t = synth.generate(cfg)
    # re-run golden replay; asserts inside check every address is in range
    reg, mem = t.init_reg.copy(), t.init_mem.copy()
    taken = semantics.scalar_replay(t, reg, mem)
    # branch outcomes recorded in trace match replay
    np.testing.assert_array_equal(
        np.array(taken), t.taken[U.is_branch(t.opcode)])


def test_replay_is_deterministic_from_snapshot():
    cfg = WorkloadConfig(n=256, nphys=64, mem_words=256, seed=11)
    t = synth.generate(cfg)
    r1, m1 = t.init_reg.copy(), t.init_mem.copy()
    r2, m2 = t.init_reg.copy(), t.init_mem.copy()
    semantics.scalar_replay(t, r1, m1)
    semantics.scalar_replay(t, r2, m2)
    np.testing.assert_array_equal(r1, r2)
    np.testing.assert_array_equal(m1, m2)
    # replay changed something (workload is not a no-op)
    assert not np.array_equal(m1, t.init_mem) or not np.array_equal(r1, t.init_reg)


def test_trace_save_load_roundtrip(tmp_path):
    cfg = WorkloadConfig(n=128, nphys=64, mem_words=128, working_set_words=64, seed=5)
    t = synth.generate(cfg)
    p = tmp_path / "w.npz"
    tfmt.save(p, t, meta={"name": "synth-test"})
    t2, meta = tfmt.load(p)
    assert meta["name"] == "synth-test"
    for f in tfmt.Trace._fields:
        np.testing.assert_array_equal(getattr(t, f), getattr(t2, f))


def test_trace_validate_rejects_bad():
    cfg = WorkloadConfig(n=32, nphys=64, mem_words=128, working_set_words=64)
    t = synth.generate(cfg)
    bad = t._replace(opcode=np.full(32, 99, dtype=np.int32))
    with pytest.raises(ValueError):
        bad.validate()
    bad2 = t._replace(init_reg=t.init_reg[:63])   # non-power-of-two
    with pytest.raises(ValueError):
        bad2.validate()
