"""Scenario-matrix campaigns (shrewd_tpu/scenario/): expansion
determinism, fleet execution, and the closed Pareto loop.

The contracts under test are the ISSUE acceptance criteria: a matrix
spanning ≥3 fault-model families (O3 + MESI + NoC) and ≥2 protection
schemes runs through the resident fleet with per-cell tallies
BIT-IDENTICAL to solo serial runs — including after a mid-matrix hard
kill + recover — cells sharing a window admit with ZERO new kernel
compiles (exec-cache counters), Pareto-dominated cells are pruned
through the scheduler's journaled ``revoke_quota`` seam with decisions
that replay exactly, and the ``PARETO_<tag>.json`` artifact schema is
pinned.
"""

import json
import os

import numpy as np
import pytest

from shrewd_tpu.parallel import exec_cache
from shrewd_tpu.scenario import (Cell, ScenarioMatrix, ScenarioRunner,
                                 cell_seed, pareto)
from shrewd_tpu.scenario.runner import PRUNE_REASON
from shrewd_tpu.service import FleetKilled


# --- matrix fixtures --------------------------------------------------------

def _sp(name="w0", seed=7, n=96):
    return {"type": "WorkloadSpec", "name": name,
            "workload": {"n": n, "nphys": 32, "mem_words": 64,
                         "working_set_words": 32, "seed": seed}}


def _base(n_batches=2, batch_size=32, **kw):
    base = {"batch_size": batch_size, "target_halfwidth": 0.2,
            "max_trials": batch_size * n_batches,
            "min_trials": batch_size * n_batches,
            "integrity": {"canary_trials": 0, "audit_rate": 0.0},
            "resilience": {"backoff_base": 0.0},
            "coherence_accesses": 64, "coherence_mem_words": 64}
    base.update(kw)
    return base


SCHEMES = [{"name": "none"},
           {"name": "parity", "detect": 1.0, "area": 1.03}]


def _matrix(tag="m", targets=("regfile",), schemes=None, thermal=None,
            workloads=None, base=None, seed=3, **kw):
    return ScenarioMatrix(
        tag=tag,
        workloads=workloads or [{"name": "wl", "simpoints": [_sp()]}],
        targets=list(targets),
        schemes=schemes or [dict(s) for s in SCHEMES],
        thermal=thermal, base=base or _base(), seed=seed, **kw)


def _solo_tallies(cell):
    """One run-to-completion serial campaign of a cell's own plan — the
    reference point every matrix assertion compares against."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(cell.build_plan())
    events = list(orch.events())
    assert events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
    return orch, {f"{sp}/{st}": np.asarray(v.tallies, dtype=np.int64)
                  for (sp, st), v in dict(events[-1][1]).items()}


# --- expansion determinism (jax-free units) ---------------------------------

def test_expand_determinism_and_stable_naming():
    """Identical documents expand to identical cells — names, order,
    seeds, plans — every time (the cell name is the tenant identity,
    the checkpoint namespace, and the Pareto provenance key)."""
    m1 = _matrix(targets=["regfile", "mesi:state"],
                 thermal=[{"name": "tnom"},
                          {"name": "hot", "temperature_c": 100.0}])
    m2 = ScenarioMatrix.from_dict(
        json.loads(json.dumps(m1.to_dict())))       # disk round trip
    c1, c2 = m1.expand(), m2.expand()
    assert [c.name for c in c1] == [c.name for c in c2]
    assert [c.plan for c in c1] == [c.plan for c in c2]
    assert [c.name for c in c1] == [
        "m.wl.w0.regfile.none.tnom", "m.wl.w0.regfile.none.hot",
        "m.wl.w0.regfile.parity.tnom", "m.wl.w0.regfile.parity.hot",
        "m.coherence.coherence.mesi+state.none.tnom",
        "m.coherence.coherence.mesi+state.none.hot",
        "m.coherence.coherence.mesi+state.parity.tnom",
        "m.coherence.coherence.mesi+state.parity.hot"]


def test_measurement_seed_shared_by_scheme_and_thermal_mates():
    """Campaign seeds derive from MEASUREMENT coordinates only:
    scheme-/thermal-mates replay identical frozen keys (their raw
    tallies are directly comparable and their executables shared);
    different windows/targets draw distinct seeds."""
    m = _matrix(targets=["regfile", "rob"],
                thermal=[{"name": "tnom"},
                         {"name": "hot", "temperature_c": 100.0}])
    cells = m.expand()
    by = {}
    for c in cells:
        by.setdefault((c.workload, c.window, c.target), []).append(c)
    for (wl, win, tg), mates in by.items():
        assert len({c.plan["seed"] for c in mates}) == 1
        assert {c.plan["seed"] for c in mates} == {
            cell_seed(m.seed, wl, win, tg)}
        # non-NoC mates share the ENTIRE plan document (the zero-new-
        # compiles economy): scheme/thermal are analytic axes
        assert len({json.dumps(c.plan, sort_keys=True)
                    for c in mates}) == 1
    seeds = {cell_seed(m.seed, "wl", "w0", t) for t in ("regfile", "rob")}
    assert len(seeds) == 2


def test_noc_cells_bake_thermal_envelope_into_plan():
    """Only NoC cells carry the envelope into the campaign (the flit
    fault-type mix is temperature-dependent); every other family keeps
    one plan across envelopes."""
    m = _matrix(targets=["regfile", "noc:router"],
                thermal=[{"name": "tnom"},
                         {"name": "hot", "temperature_c": 101.5}])
    for c in m.expand():
        if c.target == "noc:router":
            assert c.plan["noc"]["temperature_c"] == \
                c.thermal["temperature_c"]
        else:
            assert "noc" not in c.plan or "temperature_c" not in c.plan[
                "noc"]


def test_coherence_targets_collapse_workload_axes():
    """Plan-level targets (mesi:/noc:) measure plan-level synthetic
    traffic: one cell per (target, scheme, thermal), never one per
    window."""
    m = _matrix(targets=["mesi:state", "noc:router"],
                workloads=[{"name": "wl",
                            "simpoints": [_sp("w0"), _sp("w1", seed=9)]}])
    cells = m.expand()
    assert len(cells) == 2 * 2       # 2 targets × 2 schemes × 1 thermal
    assert all(c.window == "coherence" for c in cells)
    assert all(c.plan["simpoints"] == [] for c in cells)


def test_axis_scheduling_inheritance():
    """priority sums across axes, weight multiplies, tightest non-zero
    quota wins."""
    m = _matrix(
        targets=[{"name": "regfile", "priority": 2, "weight": 0.5,
                  "quota_batches": 8}],
        schemes=[{"name": "none", "priority": 1, "weight": 2.0,
                  "quota_batches": 3}],
        workloads=[{"name": "wl", "priority": 4, "simpoints": [_sp()]}],
        tenant={"priority": 1, "weight": 2.0, "quota_batches": 0})
    (c,) = m.expand()
    assert c.priority == 1 + 2 + 1 + 4
    assert c.weight == pytest.approx(2.0 * 0.5 * 2.0)
    assert c.quota_batches == 3
    spec = c.spec()
    assert (spec.priority, spec.weight, spec.quota_batches) == \
        (c.priority, c.weight, c.quota_batches)


def test_matrix_validation_rejects_bad_documents():
    with pytest.raises(ValueError, match="unknown target"):
        _matrix(targets=["bogus"])
    with pytest.raises(ValueError, match="duplicate scheme"):
        _matrix(schemes=[{"name": "a"}, {"name": "a"}])
    with pytest.raises(ValueError, match="empty scheme axis"):
        ScenarioMatrix(tag="m", workloads=[{"name": "wl",
                                            "simpoints": [_sp()]}],
                       targets=["regfile"], schemes=[])
    with pytest.raises(ValueError, match="detect\\+correct"):
        _matrix(schemes=[{"name": "bad", "detect": 0.8, "correct": 0.5}])
    with pytest.raises(ValueError, match="area"):
        _matrix(schemes=[{"name": "bad", "area": 0.5}])
    with pytest.raises(ValueError, match="at least one workload simpoint"):
        _matrix(workloads=[{"name": "wl", "simpoints": []}])
    # ... even when plan-level targets would still expand: silently
    # dropping the per-window coverage is the failure mode this guards
    with pytest.raises(ValueError, match="at least one workload simpoint"):
        _matrix(targets=["regfile", "mesi:state"],
                workloads=[{"name": "wl"}])
    with pytest.raises(ValueError, match="schema"):
        ScenarioMatrix.from_dict({"schema": 99, "tag": "x",
                                  "targets": [], "schemes": []})


def test_default_bits_deterministic_for_every_target():
    from shrewd_tpu.scenario.matrix import KNOWN_TARGETS, default_bits

    plan = _base()
    bits = {t: default_bits(t, plan) for t in KNOWN_TARGETS}
    assert all(b > 0 for b in bits.values())
    assert bits == {t: default_bits(t, plan) for t in KNOWN_TARGETS}


# --- Pareto algebra (jax-free units) ----------------------------------------

def _pt(name, area, sdc_lo, sdc_hi, status="running", converged=False):
    return {"cell": name, "status": status, "converged": converged,
            "area": area, "sdc_lo": sdc_lo, "sdc_hi": sdc_hi}


def _cellstub(name, target="regfile", scheme="s"):
    return Cell(name=name, workload="wl", window="w0", target=target,
                scheme={"name": scheme}, thermal={"name": "tnom",
                                                  "temperature_c": 71.0},
                plan={}, priority=0, weight=1.0, quota_batches=0,
                bits=1024, fit_per_bit=1e-3)


def test_dominates_is_conservative_against_halfwidth():
    dom = _pt("a", area=100.0, sdc_lo=0.0, sdc_hi=0.1, converged=True)
    # running cell whose optimistic bound could still beat dom: NOT prunable
    assert not pareto.dominates(dom, _pt("b", 120.0, 0.05, 0.5))
    # even the optimistic bound loses on both axes: prunable
    assert pareto.dominates(dom, _pt("b", 120.0, 0.2, 0.6))
    # equal on both axes (no strict edge): not domination
    assert not pareto.dominates(dom, _pt("b", 100.0, 0.1, 0.1))
    # strictly better area alone suffices when sdc ties
    assert pareto.dominates(dom, _pt("b", 120.0, 0.1, 0.5))


def test_cell_point_sdc_bounds_use_sdc_specific_wilson():
    """The prune bounds must be a valid CI on p_sdc ITSELF: at a large
    DUE share the stopping rule's combined vulnerable interval is
    narrower than the SDC proportion's own, and borrowing it would let
    a dominator prune a cell whose converged SDC rate could still beat
    it."""
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel import stopping

    tallies = np.zeros(C.N_OUTCOMES, dtype=np.int64)
    tallies[C.OUTCOME_SDC] = 15          # p_sdc ≈ 0.47: widest Wilson
    tallies[C.OUTCOME_DUE] = 15          # p_vul ≈ 0.94: narrow Wilson
    tallies[C.OUTCOME_MASKED] = 2
    trials = int(tallies.sum())
    hw_vul = stopping.live_halfwidth(30, trials, None, False, 0.95)
    pt = pareto.cell_point(_cellstub("a"), tallies, trials, hw_vul,
                           converged=False, status="running")
    iv = stopping.wilson(15, trials, 0.95)
    rate_resid = pt["sdc"] / pt["p_sdc"]
    assert pt["sdc_lo"] == pytest.approx(rate_resid * iv.lo)
    assert pt["sdc_hi"] == pytest.approx(rate_resid * iv.hi)
    # the combined hw really is tighter here — the bug this pins against
    assert hw_vul < iv.halfwidth
    assert pt["sdc_hi"] > rate_resid * (pt["p_sdc"] + hw_vul)
    # halfwidth still reports the stopping rule's convergence distance
    assert pt["halfwidth"] == pytest.approx(hw_vul)
    # zero-trial points keep the full [0, 1] bracket
    z = pareto.cell_point(_cellstub("z"), np.zeros(C.N_OUTCOMES), 0,
                          1.0, converged=False, status="queued")
    assert z["sdc_lo"] == 0.0
    assert z["sdc_hi"] == pytest.approx(rate_resid)   # rate·resid·1.0


def test_prune_decisions_only_running_unconverged_cells():
    a, b, c = (_cellstub("a", scheme="cheap"),
               _cellstub("b", scheme="mid"),
               _cellstub("c", scheme="big"))
    points = {
        "a": _pt("a", 100.0, 0.0, 0.0, status="complete", converged=True),
        "b": _pt("b", 200.0, 0.0, 0.0, status="running", converged=True),
        "c": _pt("c", 300.0, 0.0, 0.0, status="running"),
    }
    dec = pareto.prune_decisions([a, b, c], points)
    # b has converged (prune would save nothing provable), c is dominated
    assert dec == [{"cell": "c", "dominated_by": "a"}]
    # already-revoked cells are the journal's decisions, not ours
    assert pareto.prune_decisions([a, b, c], points,
                                  revoked={"c": "a"}) == []
    # cells in other prune groups never dominate each other
    d = _cellstub("d", target="rob", scheme="big")
    points["d"] = _pt("d", 300.0, 0.0, 0.0, status="running")
    assert pareto.prune_decisions([a, b, c, d], points,
                                  revoked={"c": "a"}) == []


# --- the fleet integrations -------------------------------------------------

def test_matrix_vs_solo_bit_identity_heterogeneous(tmp_path):
    """≥3 fault-model families (O3 regfile + MESI directory + NoC
    router) × 2 schemes through one fleet: every cell's tallies
    bit-identical to a solo serial run of that cell's own plan."""
    m = _matrix(tag="hetero",
                targets=["regfile", "mesi:state", "noc:router"])
    cells = m.expand()
    # 1 window × 1 per-window target × 2 schemes + 2 coherence targets
    # × 2 schemes
    assert len(cells) == 2 + 4
    solos = {}
    warm = []        # keep kernels alive: cache entries are owner-guarded
    for c in cells:
        orch, tallies = _solo_tallies(c)
        warm.append(orch)
        solos[c.name] = tallies
    runner = ScenarioRunner(m, str(tmp_path / "out"), prune=False)
    assert runner.serve() == 0
    for c in cells:
        t = runner.sched.tenants[c.name]
        assert t.status == "complete"
        assert set(t.results) == set(solos[c.name])
        for k, want in solos[c.name].items():
            np.testing.assert_array_equal(
                np.asarray(t.results[k]["tallies"], dtype=np.int64), want)
    # the artifact folded every cell and searched every system group
    doc = json.load(open(pareto.artifact_path(str(tmp_path / "out"),
                                              "hetero")))
    assert len(doc["cells"]) == len(cells)
    assert all(pt["converged"] for pt in doc["cells"].values())


def test_shared_window_cells_admit_with_zero_new_compiles(tmp_path):
    """Scheme-mates over one window share content-keyed executables:
    after one solo warm run of the measurement, the whole matrix admits
    and runs with ZERO new kernel-step compiles (the only new key is
    the protect-eval sweep, which is not a campaign step)."""
    m = _matrix(tag="dedupe")
    cells = m.expand()
    orch, _ = _solo_tallies(cells[0])     # warm the window's executables
    before = {d: s["misses"]
              for d, s in exec_cache.cache().per_key_stats().items()}
    runner = ScenarioRunner(m, str(tmp_path / "out"), prune=False)
    assert runner.serve() == 0
    new_step_misses = {
        d: (s["misses"] - before.get(d, 0), s["kind"])
        for d, s in exec_cache.cache().per_key_stats().items()
        if s["kind"] != "protect_eval"
        and s["misses"] - before.get(d, 0) > 0}
    assert new_step_misses == {}, new_step_misses
    del orch


def test_kill_fleet_mid_matrix_recovers_completed_cells_intact(tmp_path):
    """A hard-killed matrix fleet recovers from matrix.json + the WAL:
    cells completed before the kill keep their recorded results, the
    rest resume from namespaced checkpoints, and the final state is
    bit-identical to an undisturbed run."""
    def mk(tag):
        return _matrix(
            tag=tag,
            base=_base(n_batches=2),
            # de-weight the parity cell so the none cell finishes first
            # (a completed cell exists when the kill lands)
            schemes=[{"name": "none"},
                     {"name": "parity", "detect": 1.0, "area": 1.03,
                      "weight": 0.25}])

    clean = ScenarioRunner(mk("undisturbed"),
                           str(tmp_path / "clean"), prune=False)
    assert clean.serve() == 0
    want = {c.name.replace("undisturbed", "killed"):
            {k: np.asarray(v["tallies"], dtype=np.int64)
             for k, v in clean.sched.tenants[c.name].results.items()}
            for c in clean.cells}

    armed = []

    def kill_after_first_completion(sched):
        # the in-process hard-kill stand-in (the FleetKilled idiom of
        # tests/test_fleet_survive.py): one tick AFTER the first cell
        # completes — a still-running cell's tick record then sits in
        # the journal beyond the completion checkpoint (the dirty-
        # shutdown signature), while a completed cell's results are on
        # the line
        if armed:
            raise FleetKilled(137)
        by = {t.status for t in sched.tenants.values()}
        if "complete" in by and by != {"complete"}:
            armed.append(sched.ticks)

    outdir = str(tmp_path / "killed")
    runner = ScenarioRunner(mk("killed"), outdir, prune=False,
                            on_tick=kill_after_first_completion)
    with pytest.raises(FleetKilled):
        runner.serve()
    done_at_kill = {n: t.results for n, t in runner.sched.tenants.items()
                    if t.status == "complete"}
    assert done_at_kill       # at least one cell completed pre-kill

    rec = ScenarioRunner.recover(outdir, prune=False)
    assert rec.matrix.tag == "killed"
    assert rec.sched.recoveries == 1
    assert rec.run() == 0
    for name, res in done_at_kill.items():
        # completed cells' recorded results survived the kill verbatim
        assert rec.sched.tenants[name].results == res
    for name, tallies in want.items():
        t = rec.sched.tenants[name]
        assert t.status == "complete"
        for k, w in tallies.items():
            np.testing.assert_array_equal(
                np.asarray(t.results[k]["tallies"], dtype=np.int64), w)


def _prune_matrix(tag):
    """parity strictly dominates dmr (equal residual SDC, lower area);
    dmr is de-weighted so parity converges while dmr still runs — the
    closed loop must revoke dmr's remaining quota."""
    return _matrix(
        tag=tag, base=_base(n_batches=6),
        schemes=[{"name": "parity", "detect": 1.0, "area": 1.03},
                 {"name": "dmr", "detect": 1.0, "area": 2.0,
                  "weight": 0.2}])


def test_pareto_prune_fires_and_is_replay_exact(tmp_path):
    runner = ScenarioRunner(_prune_matrix("pr"), str(tmp_path / "a"),
                            pareto_every=1)
    assert runner.serve() == 0
    sched = runner.sched
    parity, dmr = "pr.wl.w0.regfile.parity.tnom", "pr.wl.w0.regfile.dmr.tnom"
    assert sched.tenants[parity].status == "complete"
    t = sched.tenants[dmr]
    assert t.status == "pruned"
    assert t.revoked == PRUNE_REASON + parity
    assert 0 < t.trials < 6 * 32          # partial service, not zero/full
    doc = json.load(open(pareto.artifact_path(str(tmp_path / "a"), "pr")))
    assert doc["decisions"] == [{"cell": dmr, "dominated_by": parity}]
    # the pruned cell's partial tallies stay first-class provenance
    assert doc["cells"][dmr]["status"] == "pruned"
    assert doc["cells"][dmr]["trials"] == t.trials

    # determinism: an identical matrix in a fresh outdir makes the SAME
    # decision at the same tally state (tick-counted fold, frozen keys)
    r2 = ScenarioRunner(_prune_matrix("pr"), str(tmp_path / "b"),
                        pareto_every=1)
    assert r2.serve() == 0
    assert r2.decisions(r2.sched) == {dmr: parity}
    assert r2.sched.tenants[dmr].trials == t.trials


def test_prune_decision_survives_hard_kill_exactly(tmp_path):
    """The journaled revoke record IS the decision: a fleet hard-killed
    BETWEEN the decision and the drain replays it on recovery — the
    revoked cell prunes without re-elaboration, keeps exactly the
    partial trials the decision left it with, and the final artifact
    cites the same decision set as the undisturbed run."""
    from shrewd_tpu.chaos import ChaosEngine

    # undisturbed reference run; learn the fleet tick the revoke landed
    # on (deterministic: tick-counted fold over frozen-key tallies)
    seen = {}

    def watch(sched):
        if "tick" not in seen and any(t.revoked
                                      for t in sched.tenants.values()):
            seen["tick"] = sched.ticks      # first tick AFTER the revoke

    r0 = ScenarioRunner(_prune_matrix("pk"), str(tmp_path / "ref"),
                        pareto_every=1, on_tick=watch)
    assert r0.serve() == 0
    dmr = "pk.wl.w0.regfile.dmr.tnom"
    parity = "pk.wl.w0.regfile.parity.tnom"
    ref = r0.sched.tenants[dmr]
    assert ref.status == "pruned"
    revoke_tick = seen["tick"] - 1          # the revoke's own fleet tick

    # kill_fleet at the revoke's tick fires at the NEXT loop top: after
    # the journaled decision, before the revoked tenant's drain tick
    eng = ChaosEngine({"faults": [{"kind": "kill_fleet",
                                   "at_tick": revoke_tick}]})
    eng.kill_action = lambda rc: (_ for _ in ()).throw(FleetKilled(rc))
    outdir = str(tmp_path / "killed")
    runner = ScenarioRunner(_prune_matrix("pk"), outdir, pareto_every=1,
                            chaos=eng)
    with pytest.raises(FleetKilled):
        runner.serve()
    killed = runner.sched.tenants[dmr]
    assert killed.revoked == PRUNE_REASON + parity
    assert killed.status == "running"       # decision made, drain not

    rec = ScenarioRunner.recover(outdir, pareto_every=1)
    t = rec.sched.tenants[dmr]
    assert t.revoked == PRUNE_REASON + parity   # replayed from the WAL
    assert rec.run() == 0
    # the re-queued revoked tenant pruned WITHOUT elaborating (no
    # failures burned) and with exactly the decision-time service
    t = rec.sched.tenants[dmr]
    assert t.status == "pruned" and t.failures == 0
    assert t.trials == ref.trials
    assert rec.decisions(rec.sched) == {dmr: parity}
    assert rec.sched.tenants[parity].status == "complete"
    doc = json.load(open(pareto.artifact_path(outdir, "pk")))
    assert doc["decisions"] == [{"cell": dmr, "dominated_by": parity}]


# --- artifact schema pin ----------------------------------------------------

def test_pareto_artifact_schema_pin(tmp_path):
    """The PARETO document layout is an interchange surface: schema
    version, axes, per-cell point fields, decisions, and the search
    groups are pinned here so downstream consumers can rely on them."""
    m = _matrix(tag="pin")
    runner = ScenarioRunner(m, str(tmp_path / "out"), prune=False)
    assert runner.serve() == 0
    doc = json.load(open(pareto.artifact_path(str(tmp_path / "out"),
                                              "pin")))
    assert doc["schema"] == pareto.PARETO_SCHEMA == 1
    assert set(doc) == {"schema", "tag", "sdc_target", "axes", "cells",
                        "decisions", "search", "fleet"}
    assert set(doc["axes"]) == {"workloads", "windows", "targets",
                                "schemes", "thermal"}
    assert doc["axes"]["schemes"] == ["none", "parity"]
    pt = doc["cells"]["pin.wl.w0.regfile.none.tnom"]
    assert set(pt) == {"cell", "status", "trials", "converged",
                       "halfwidth", "tallies", "p_sdc", "area", "sdc",
                       "due", "sdc_lo", "sdc_hi", "thermal_factor",
                       "prune_group", "system_group"}
    assert pt["sdc_lo"] <= pt["sdc"] <= pt["sdc_hi"]
    (group,) = doc["search"].values()
    assert set(group) == {"cells", "feasible", "assignment", "area",
                          "sdc_rate", "due_rate", "baseline_area",
                          "baseline_sdc", "n_configs", "pareto"}
    # profile fit picks the converged mate with the most trials, ties on
    # cell name (scheme-mates measure the same distribution, so the
    # choice only has to be deterministic)
    assert group["cells"] == {"regfile": "pin.wl.w0.regfile.parity.tnom"}
    # the front is over the matrix's OWN schemes
    assert {p["assignment"]["regfile"] for p in group["pareto"]} <= {
        "none", "parity"}


def test_stratified_cells_fold_with_the_stratified_estimator(tmp_path):
    """Terminal cells' summaries carry the per-stratum tally history, so
    a stratified matrix's fold recomputes half-widths with the SAME
    estimator the stopping rule used — never silently degrading to
    pooled Wilson (which would stall the prune loop exactly where
    stratification converges fastest)."""
    from shrewd_tpu.ops import classify as C
    from shrewd_tpu.parallel import stopping

    m = _matrix(tag="strat", base=_base(stratify=True),
                schemes=[{"name": "none"}])
    runner = ScenarioRunner(m, str(tmp_path / "out"), prune=False)
    assert runner.serve() == 0
    (cell,) = runner.cells
    row = runner.sched.tenants[cell.name].results["w0/regfile"]
    strata = row["strata"]
    assert strata is not None
    assert int(np.asarray(strata).sum()) == row["trials"]
    pt = runner.points(runner.sched)[cell.name]
    t = np.asarray(row["tallies"])
    vul = int(t[C.OUTCOME_SDC] + t[C.OUTCOME_DUE])
    want = stopping.live_halfwidth(vul, row["trials"], strata, True, 0.95)
    assert pt["halfwidth"] == pytest.approx(want)
    # and the stratified selection really differs from pooled Wilson
    assert want != pytest.approx(
        stopping.live_halfwidth(vul, row["trials"], None, False, 0.95))


def test_failed_final_fold_keeps_the_fleet_rc(tmp_path, monkeypatch):
    """The artifact is derived state: a fold that cannot compute (e.g. a
    design space past the enumeration guard) must not discard the rc of
    a fully served matrix — the journal stays the ground truth and
    --pareto can re-fold later."""
    from shrewd_tpu.scenario import pareto as par

    def boom(*a, **kw):
        raise ValueError("design space too large")

    monkeypatch.setattr(par, "design_search", boom)
    runner = ScenarioRunner(_matrix(tag="ff"), str(tmp_path / "out"),
                            prune=False)
    assert runner.serve() == 0              # rc survives the fold failure
    assert {t.status for t in runner.sched.tenants.values()} == \
        {"complete"}
    with pytest.raises(ValueError, match="too large"):
        runner.emit_artifact()              # the one-shot surface raises


def test_runner_status_reads_persisted_surfaces(tmp_path):
    m = _matrix(tag="st")
    runner = ScenarioRunner(m, str(tmp_path / "out"), prune=False)
    assert runner.serve() == 0
    status = ScenarioRunner.status(str(tmp_path / "out"))
    assert status["tag"] == "st"
    assert set(status["tenants"]) == {c.name for c in m.expand()}
    assert status["decisions"] == []
    assert list(status["search"]) == ["wl/w0/tnom"]


# --- lint gates -------------------------------------------------------------

def test_graftlint_gates_cover_scenario_and_search():
    """The ISSUE pins shrewd_tpu/scenario/ under GL101/GL102/GL103/GL106
    and search/protect.py under GL101 (jit routed through exec_cache)."""
    from shrewd_tpu.analysis.config import load_config

    cfg = load_config(os.path.join(os.path.dirname(__file__), ".."))
    scenario = {f"shrewd_tpu/scenario/{f}" for f in
                ("__init__.py", "matrix.py", "pareto.py", "runner.py")}
    assert scenario <= set(cfg.jit_modules)
    assert scenario <= set(cfg.deterministic_modules)
    assert scenario <= set(cfg.checkpoint_modules)
    assert scenario <= set(cfg.clock_modules)
    assert "shrewd_tpu/search/protect.py" in set(cfg.jit_modules)
