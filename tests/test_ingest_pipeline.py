"""Streaming ingest: binary in, campaign-ready plan out — crash-safe.

The PR-17 pins: the digest-keyed artifact store's semantics (dedup hit
is O(1) and byte-identical to a cold lift, torn/rotted artifacts read
as misses and re-lift, two concurrent submissions share one lift), the
journaled pipeline's quarantine verdicts (unparseable ELF, digest rot,
lift divergence — all durable, all evidence-carrying), the new chaos
kinds (``corrupt_binary`` / ``kill_during_lift`` with ``at_stage``
vocab), the spool's poisoned-binary split, and the service-tier e2e: a
raw binary submitted as a ``TenantSpec`` runs to final tallies
bit-identical to the same windows via the pre-lifted plan path, a
resubmission warm-starts with zero lifts, a poisoned binary
quarantines while its co-resident tenant finishes untouched, and the
federation crashcheck sweep recovers bit-identically from ingest-WAL
and artifact-store boundaries (+ torn variants).
"""

import base64
import json
import os
import shutil
import threading

import pytest

from shrewd_tpu.chaos import ChaosEngine, ChaosPlanError, rot_file, tear_file
from shrewd_tpu.ingest.pipeline import (DEFAULT_AXES, STAGES, IngestPipeline,
                                        IngestQuarantine, normalize_axes)
from shrewd_tpu.ingest.store import ArtifactStore, axes_key, data_digest
from shrewd_tpu.service.journal import FleetJournal
from shrewd_tpu.service.queue import SubmissionQueue, TenantSpec

needs_toolchain = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("objdump") is None
    or shutil.which("nm") is None,
    reason="host toolchain required")

AXES = {"interval": 1500, "k": 2, "max_steps": 20000}


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _sort_binary():
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/sort.c")
    return open(paths.workload, "rb").read()


def _window_bytes(store: ArtifactStore, digest: str, key: str,
                  plan: dict) -> dict:
    return {e["file"]: open(store.payload_path(digest, key,
                                               e["file"]), "rb").read()
            for e in plan["simpoints"]}


# --- axes / store units (no toolchain, no jax compiles) ---------------------

def test_axes_normalize_and_key():
    assert normalize_axes(None) == DEFAULT_AXES
    # {} and explicit defaults must share one store address
    assert axes_key(normalize_axes({})) == axes_key(
        normalize_axes(dict(DEFAULT_AXES)))
    assert axes_key(normalize_axes({"k": 5})) != axes_key(
        normalize_axes({}))
    with pytest.raises(ValueError, match="unknown ingest axes"):
        normalize_axes({"interval": 10, "bogus": 1})


def test_store_binary_content_addressing(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    data = b"\x7fELF fake payload"
    digest = store.put_binary(data)
    assert digest == data_digest(data)
    assert store.put_binary(data) == digest          # idempotent
    assert store.verify_binary(digest)
    assert open(store.binary_path(digest), "rb").read() == data
    # rot = poison: verify says no, and the bytes stay rotted (no
    # silent self-heal — healing would hide the tamper)
    rot_file(store.binary_path(digest))
    assert not store.verify_binary(digest)
    assert not store.verify_binary("0" * 64)         # absent = unverifiable


def test_store_doc_verifies_every_payload(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    digest, key = "d" * 64, "k" * 16
    sha = store.write_payload(digest, key, "w.bin", b"window bytes")
    store.put_doc(digest, key, "stage", {"n": 1,
                                         "payloads": {"w.bin": sha}})
    assert store.get_doc(digest, key, "stage")["n"] == 1
    # rotted payload → the whole doc is a MISS, never a partial hit
    rot_file(store.payload_path(digest, key, "w.bin"))
    assert store.get_doc(digest, key, "stage") is None
    # torn doc → miss too
    store.write_payload(digest, key, "w.bin", b"window bytes")
    store.put_doc(digest, key, "stage", {"n": 2,
                                         "payloads": {"w.bin": sha}})
    assert store.get_doc(digest, key, "stage")["n"] == 2
    tear_file(os.path.join(store.obj_dir(digest, key), "stage.json"), 0.4)
    assert store.get_doc(digest, key, "stage") is None
    assert store.get_doc(digest, key, "absent") is None


def test_single_flight_lock_reaps_stale(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    lock = store.lock("a" * 64, "b" * 16)
    # a dead-pid lock is stale and reaped without waiting
    os.makedirs(os.path.dirname(lock.path), exist_ok=True)
    with open(lock.path, "w") as f:
        f.write("999999999\n")
    with store.lock("a" * 64, "b" * 16):
        pass
    # our own pid but NOT held by this process (the residue of an
    # in-process chaos kill that unwound past the release) is stale too
    with open(lock.path, "w") as f:
        f.write(f"{os.getpid()}\n")
    with store.lock("a" * 64, "b" * 16):
        pass
    assert not os.path.exists(lock.path)


# --- chaos kinds ------------------------------------------------------------

def test_ingest_chaos_kind_vocab():
    with pytest.raises(ChaosPlanError, match="corrupt_binary needs "
                                             "at_stage"):
        ChaosEngine({"faults": [{"kind": "corrupt_binary"}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_batch'"):
        ChaosEngine({"faults": [{"kind": "kill_during_lift",
                                 "at_stage": [1], "at_batch": [0]}]})
    eng = ChaosEngine({"faults": [
        {"kind": "corrupt_binary", "at_stage": [1]},
        {"kind": "kill_during_lift", "at_stage": [3]}]})
    assert eng.take_corrupt_binary(0) is None
    assert eng.take_corrupt_binary(1) is not None
    assert eng.take_corrupt_binary(1) is None        # consumed
    fired = []
    eng.kill_action = lambda rc: fired.append(rc)
    eng.maybe_kill_during_lift(2)
    assert fired == []
    eng.maybe_kill_during_lift(3)
    assert fired == [137]


# --- TenantSpec binary fields / poisoned spool ------------------------------

def test_tenant_spec_binary_roundtrip_and_validation():
    data = b"\x7fELF payload"
    spec = TenantSpec(name="b", plan={"seed": 1}, binary_b64=_b64(data),
                      binary_digest=data_digest(data),
                      ingest={"k": 2})
    back = TenantSpec.from_dict(spec.to_dict())
    assert back.verify_binary() == data
    assert back.ingest == {"k": 2}
    # plan-only specs stay byte-stable (no binary keys in the doc)
    assert "binary_b64" not in TenantSpec(name="p", plan={}).to_dict()
    with pytest.raises(ValueError, match="come together"):
        TenantSpec(name="b", plan={}, binary_b64=_b64(data))
    with pytest.raises(ValueError, match="come together"):
        TenantSpec(name="b", plan={}, binary_digest="0" * 64)
    with pytest.raises(ValueError, match="ingest axes"):
        TenantSpec(name="b", plan={}, ingest={"k": 2})
    with pytest.raises(ValueError, match="digest mismatch"):
        TenantSpec(name="b", plan={}, binary_b64=_b64(data),
                   binary_digest="0" * 64).verify_binary()
    with pytest.raises(ValueError, match="does not decode"):
        TenantSpec(name="b", plan={}, binary_b64="!!!",
                   binary_digest="0" * 64).binary_bytes()


def test_claim_quarantines_digest_mismatched_binary(tmp_path):
    q = SubmissionQueue(str(tmp_path / "spool"))
    data = b"\x7fELF payload"
    good = TenantSpec(name="ok", plan={"seed": 1}, binary_b64=_b64(data),
                      binary_digest=data_digest(data))
    bad = TenantSpec(name="evil", plan={"seed": 1}, binary_b64=_b64(data),
                     binary_digest="0" * 64)
    t_good = q.submit(good)
    t_bad = q.submit(bad)
    claimed = q.claim()
    # the poisoned payload goes to bad/ with evidence; the good one is
    # claimed normally — the spool never wedges on poison
    assert [t for t, _ in claimed] == [t_good]
    assert os.path.exists(os.path.join(q.bad_dir, t_bad))
    assert q.bad_count() == 1
    reason = json.load(open(os.path.join(q.bad_dir, t_bad + ".reason")))
    assert "digest mismatch" in reason["error"]


# --- quarantine verdicts (toolchain, no jax compiles) -----------------------

@needs_toolchain
def test_unparseable_elf_quarantines_durably(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(b"this is not an ELF")
    pipe = IngestPipeline(str(tmp_path / "t" / "ingest"), store, digest)
    with pytest.raises(IngestQuarantine) as ei:
        pipe.run()
    assert ei.value.stage == "capture"
    # the verdict is durable: a fresh pipeline over the same WAL replays
    # straight back into quarantine without re-running anything
    pipe2 = IngestPipeline(str(tmp_path / "t" / "ingest"), store, digest)
    assert pipe2.quarantine_rec is not None
    with pytest.raises(IngestQuarantine):
        pipe2.run()
    assert pipe2.captures == 0 and pipe2.lifts == 0
    kinds = [r["kind"] for r in FleetJournal.replay_path(
        str(tmp_path / "t" / "ingest" / "ingest.jsonl"))[0]]
    assert "ingest_quarantine" in kinds


@needs_toolchain
def test_lift_divergence_floor_quarantines(tmp_path):
    # min_lift_rate above 1.0 makes ANY lift a divergence verdict — the
    # deterministic stand-in for a real host-oracle mismatch
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(_sort_binary())
    pipe = IngestPipeline(str(tmp_path / "t" / "ingest"), store, digest,
                          axes={**AXES, "min_lift_rate": 1.01})
    with pytest.raises(IngestQuarantine, match="divergence"):
        pipe.run()
    assert pipe.quarantine_rec["stage"] == "lift"


@needs_toolchain
def test_corrupt_binary_chaos_quarantines_at_stage(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(_sort_binary())
    eng = ChaosEngine({"faults": [{"kind": "corrupt_binary",
                                   "at_stage": [1]}]})
    pipe = IngestPipeline(str(tmp_path / "t" / "ingest"), store, digest,
                          axes=AXES, chaos=eng)
    with pytest.raises(IngestQuarantine, match="no longer hashes") as ei:
        pipe.run()
    # deterministically at the scheduled ordinal: capture (stage 0)
    # completed and is durable; lift (stage 1) found the rot
    assert ei.value.stage == "lift"
    assert store.get_doc(digest, pipe.key, "capture") is not None
    assert not store.verify_binary(digest)


# --- digest-store semantics (toolchain, no jax compiles) --------------------

@needs_toolchain
def test_dedup_hit_is_o1_and_byte_identical(tmp_path):
    data = _sort_binary()
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(data)
    cold = IngestPipeline(str(tmp_path / "a" / "ingest"), store, digest,
                          axes=AXES)
    plan = cold.run()
    assert cold.captures == 1
    assert cold.lifts == 1 + len(plan["simpoints"])  # full + windows
    # warm start: a different tenant, same (digest, axes) — zero work
    warm = IngestPipeline(str(tmp_path / "b" / "ingest"), store, digest,
                          axes=AXES)
    plan2 = warm.run()
    assert (warm.captures, warm.lifts) == (0, 0)
    assert plan2 == plan
    # the warm tenant's WAL is self-contained evidence of the cache hit
    recs = FleetJournal.replay_path(
        str(tmp_path / "b" / "ingest" / "ingest.jsonl"))[0]
    assert [r["kind"] for r in recs] == \
        ["ingest_stage"] * len(STAGES) + ["ingest_done"]
    assert all(r["cached"] for r in recs if r["kind"] == "ingest_stage")
    # byte-identity: re-lifting the SAME stored capture in a fresh
    # store reproduces every window bit-for-bit (the downstream stages
    # are deterministic functions of the capture)
    store2 = ArtifactStore(str(tmp_path / "store2"))
    d2 = store2.put_binary(data)
    cap = open(store.payload_path(digest, cold.key, "capture.bin"),
               "rb").read()
    store2.write_payload(d2, cold.key, "capture.bin",
                         cap)
    cdoc = store.get_doc(digest, cold.key, "capture")
    store2.put_doc(d2, cold.key, "capture", cdoc)
    redo = IngestPipeline(str(tmp_path / "c" / "ingest"), store2, d2,
                          axes=AXES)
    plan3 = redo.run()
    assert redo.captures == 0        # the seeded capture was reused
    assert _window_bytes(store2, d2, redo.key, plan3) == \
        _window_bytes(store, digest, cold.key, plan)


@needs_toolchain
def test_torn_store_doc_falls_back_to_relift(tmp_path):
    data = _sort_binary()
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(data)
    cold = IngestPipeline(str(tmp_path / "a" / "ingest"), store, digest,
                          axes=AXES)
    plan = cold.run()
    golden = _window_bytes(store, digest, cold.key, plan)
    # tear the terminal plan doc AND the window stage doc: the probe
    # misses, the stage re-verifies as incomplete, and the pipeline
    # silently re-lifts — a damaged ARTIFACT is a cache decision,
    # never a quarantine
    tear_file(os.path.join(store.obj_dir(digest, cold.key),
                           "plan.json"), 0.4)
    tear_file(os.path.join(store.obj_dir(digest, cold.key),
                           "window.json"), 0.4)
    redo = IngestPipeline(str(tmp_path / "b" / "ingest"), store, digest,
                          axes=AXES)
    plan2 = redo.run()
    assert redo.lifts == len(plan["simpoints"])   # windows only
    assert redo.captures == 0
    assert plan2["simpoints"] == plan["simpoints"]
    assert _window_bytes(store, digest, cold.key, plan2) == golden


@needs_toolchain
def test_concurrent_submissions_share_one_lift(tmp_path):
    data = _sort_binary()
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(data)
    pipes = [IngestPipeline(str(tmp_path / f"t{i}" / "ingest"), store,
                            digest, axes=AXES) for i in range(2)]
    plans, errs = [None, None], []

    def _run(i):
        try:
            plans[i] = pipes[i].run()
        except Exception as e:  # noqa: BLE001 — surface in the test
            errs.append(e)

    threads = [threading.Thread(target=_run, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert plans[0] == plans[1]
    # single-flight: exactly ONE pipeline did the cold work; the loser
    # waited on the lock and warm-started from the winner's artifacts
    total = [(p.captures, p.lifts) for p in pipes]
    assert sorted(total) == [(0, 0),
                             (1, 1 + len(plans[0]["simpoints"]))]


@needs_toolchain
def test_kill_during_lift_resumes_from_durable_stage(tmp_path):
    data = _sort_binary()
    store = ArtifactStore(str(tmp_path / "store"))
    digest = store.put_binary(data)

    class Killed(Exception):
        pass

    eng = ChaosEngine({"faults": [{"kind": "kill_during_lift",
                                   "at_stage": [1]}]})
    eng.kill_action = lambda rc: (_ for _ in ()).throw(Killed())
    wal_dir = str(tmp_path / "t" / "ingest")
    pipe = IngestPipeline(wal_dir, store, digest, axes=AXES, chaos=eng)
    with pytest.raises(Killed):
        pipe.run()
    # capture landed durably (WAL + store) before the kill at stage 1
    recs = FleetJournal.replay_path(
        os.path.join(wal_dir, "ingest.jsonl"))[0]
    assert [r["kind"] for r in recs] == ["ingest_stage"]
    assert recs[0]["stage"] == "capture"
    # recovery resumes mid-pipeline: no re-capture, windows complete
    redo = IngestPipeline(wal_dir, store, digest, axes=AXES)
    plan = redo.run()
    assert redo.captures == 0
    assert redo.lifts == 1 + len(plan["simpoints"])
    assert store.get_doc(digest, redo.key, "plan") is not None


# --- service-tier e2e (jax campaigns) ---------------------------------------

def _scenario_plan(**kw):
    plan = {"structures": ["regfile"], "batch_size": 16, "max_trials": 32,
            "min_trials": 32, "target_halfwidth": 0.5, "seed": 3}
    plan.update(kw)
    return plan


@needs_toolchain
def test_binary_tenant_bit_identical_to_plan_path(tmp_path):
    import numpy as np

    from shrewd_tpu.service.scheduler import CampaignScheduler

    data = _sort_binary()
    digest = data_digest(data)
    store_dir = str(tmp_path / "store")
    sched = CampaignScheduler(outdir=str(tmp_path / "fleet"),
                              store_dir=store_dir)
    sched.admit(TenantSpec(name="bin0", plan=_scenario_plan(),
                           binary_b64=_b64(data), binary_digest=digest,
                           ingest=AXES))
    assert sched.run() == 0
    assert sched.tenants["bin0"].status == "complete"
    assert sched.ingest_captures == 1 and sched.ingest_lifts >= 2
    bt = sched.tenant_tallies("bin0")

    # the pre-lifted plan path over the SAME store windows
    pipe = IngestPipeline(str(tmp_path / "probe"),
                          ArtifactStore(store_dir), digest, axes=AXES)
    pipe.run()
    assert pipe.lifts == 0                      # pure warm start
    sched2 = CampaignScheduler(outdir=str(tmp_path / "fleet2"))
    sched2.admit(TenantSpec(name="plan0",
                            plan=pipe.resolved_plan(_scenario_plan())))
    assert sched2.run() == 0
    pt = sched2.tenant_tallies("plan0")
    assert bt.keys() == pt.keys() and len(bt) > 0
    for k in bt:
        np.testing.assert_array_equal(np.asarray(bt[k]),
                                      np.asarray(pt[k]))

    # resubmission over the same store: zero ingest work
    sched3 = CampaignScheduler(outdir=str(tmp_path / "fleet3"),
                               store_dir=store_dir)
    sched3.admit(TenantSpec(name="bin1", plan=_scenario_plan(),
                            binary_b64=_b64(data), binary_digest=digest,
                            ingest=AXES))
    assert sched3.run() == 0
    assert (sched3.ingest_captures, sched3.ingest_lifts) == (0, 0)


@needs_toolchain
def test_poisoned_binary_quarantines_coresident_finishes(tmp_path):
    from test_fleet import _plan, _solo_tallies, _assert_tenant_matches

    from shrewd_tpu.service.scheduler import CampaignScheduler

    data = _sort_binary()
    digest = data_digest(data)
    # corrupt_binary chaos rots the stored ELF at stage ordinal 1: the
    # submission deterministically quarantines (digest re-verify at the
    # lift stage) while the co-resident plan tenant finishes untouched
    eng = ChaosEngine({"faults": [{"kind": "corrupt_binary",
                                   "at_stage": [1]}]})
    plan = _plan(3, n_batches=2)
    solo = _solo_tallies(plan)
    sched = CampaignScheduler(outdir=str(tmp_path / "fleet"), chaos=eng)
    sched.admit(TenantSpec(name="good", plan=plan.to_dict()))
    sched.admit(TenantSpec(name="evil", plan=_scenario_plan(),
                           binary_b64=_b64(data), binary_digest=digest,
                           ingest=AXES))
    assert sched.run() == 0
    assert sched.tenants["good"].status == "complete"
    assert sched.tenants["evil"].status == "quarantined"
    # one elaboration failure, zero retries: poison never burns budget
    assert sched.tenants["evil"].failures == 1
    assert "no longer hashes" in sched.tenants["evil"].results["error"]
    _assert_tenant_matches(sched, "good", solo)
    # the quarantine evidence doc is durable in the tenant's namespace
    qdoc = json.load(open(os.path.join(
        sched.tenant_outdir("evil"), "quarantine.json")))
    assert qdoc["failures"] == 1
    # and the pipeline's own WAL carries the journaled verdict
    recs = FleetJournal.replay_path(os.path.join(
        sched.tenant_outdir("evil"), "ingest", "ingest.jsonl"))[0]
    assert any(r["kind"] == "ingest_quarantine" for r in recs)


@needs_toolchain
def test_ingest_crashcheck_sweep_bounded(tmp_path):
    # recover the federation from ingest-WAL appends and artifact-store
    # renames (+ torn-tail / torn-payload variants) — bit-identical
    # final tallies at every boundary.  Bounded to the ingest surface
    # here; the CI gate records the fuller sweep in INGEST_r14.json
    from shrewd_tpu.analysis import crashcheck

    data = _sort_binary()
    binaries = {"b0": {"binary_b64": _b64(data),
                       "binary_digest": data_digest(data),
                       "ingest": AXES}}
    doc = crashcheck.run_gateway_crashcheck(
        str(tmp_path / "sweep"),
        plans={"b0": _scenario_plan(batch_size=8, max_trials=8,
                                    min_trials=8)},
        binaries=binaries, max_points=4,
        point_filter=lambda pt: (pt.kind or "").startswith(("ingest",
                                                            "store")))
    assert doc["failures"] == []
    assert doc["binaries"] == ["b0"]
    assert doc["points_selected"] >= 8       # the full ingest surface
    assert doc["points_checked"] == 4
    assert doc["torn_checks"] >= 1
    by_kind = doc["boundaries_by_kind"]
    assert by_kind.get("ingest_stage", 0) >= len(STAGES)
    assert by_kind.get("ingest_done", 0) >= 1
    assert by_kind.get("store_payload", 0) >= 4
