"""Scoreboard timing model (models/timing.py): pipeline-invariant checks,
residency-weighted sampling, and the O3 integration path.

Reference role: the O3 pipeline's structure residency
(src/cpu/o3/cpu.cc:363-417, inst_queue.cc:845-1027) — validated here by
invariants rather than by tick-for-tick comparison, since the model is a
scoreboard, not a ticked pipeline."""

import numpy as np
import pytest

from shrewd_tpu.isa import uops as U
from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.models.timing import (ResidencySampler, TimingConfig,
                                      compute_scoreboard)
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def _trace(n=512, seed=11, **kw):
    return generate(WorkloadConfig(n=n, nphys=64, mem_words=256,
                                   working_set_words=64, seed=seed, **kw))


class TestScoreboardInvariants:
    def setup_method(self):
        self.trace = _trace()
        self.cfg = TimingConfig()
        self.sb = compute_scoreboard(self.trace, self.cfg)

    def test_stage_ordering(self):
        sb = self.sb
        assert (sb.dispatch <= sb.issue).all()
        assert (sb.issue < sb.writeback).all()
        assert (sb.writeback < sb.commit).all()

    def test_commit_in_order(self):
        assert (np.diff(self.sb.commit) >= 0).all()

    def test_dispatch_in_order_and_width_limited(self):
        d = self.sb.dispatch
        assert (np.diff(d) >= 0).all()
        _, counts = np.unique(d, return_counts=True)
        assert counts.max() <= self.cfg.dispatch_width

    def test_issue_width_respected(self):
        _, counts = np.unique(self.sb.issue, return_counts=True)
        assert counts.max() <= self.cfg.issue_width

    def test_commit_width_respected(self):
        _, counts = np.unique(self.sb.commit, return_counts=True)
        assert counts.max() <= self.cfg.commit_width

    def test_rob_capacity_never_exceeded(self):
        sb = self.sb
        n_cyc = sb.n_cycles
        occ = np.zeros(n_cyc + 2, np.int64)
        np.add.at(occ, sb.dispatch, 1)
        np.add.at(occ, np.maximum(sb.commit, sb.dispatch + 1), -1)
        assert np.cumsum(occ).max() <= self.cfg.rob_size

    def test_dependences_respected(self):
        tr, sb = self.trace, self.sb
        op = np.asarray(tr.opcode)
        last_wb = {}
        for i in range(tr.n):
            for use, src in ((U.uses_src1(op[i]), int(tr.src1[i])),
                             (U.uses_src2(op[i]), int(tr.src2[i]))):
                if use and src in last_wb:
                    assert sb.issue[i] >= last_wb[src], i
            if U.writes_dest(op[i]):
                last_wb[int(tr.dst[i])] = sb.writeback[i]

    def test_latency_applied(self):
        tr, sb = self.trace, self.sb
        div = np.asarray(U.is_div(tr.opcode))
        if div.any():
            lat = (sb.writeback - sb.issue)[div]
            assert (lat == self.cfg.div_latency).all()

    def test_ipc_below_width_and_positive(self):
        assert 0 < self.sb.ipc <= self.cfg.issue_width


class TestScoreboardScaling:
    def test_serial_dependence_chain_is_latency_bound(self):
        """Every µop reading the previous µop's dest serializes the window."""
        tr = _trace(n=128)
        op = np.full(128, U.ADD, np.int32)
        chain = tr._replace(opcode=op,
                            dst=np.full(128, 5, np.int32),
                            src1=np.full(128, 5, np.int32),
                            src2=np.full(128, 5, np.int32))
        sb = compute_scoreboard(chain, TimingConfig())
        assert sb.n_cycles >= 128           # one per cycle at best
        wide = compute_scoreboard(
            chain._replace(src1=np.zeros(128, np.int32),
                           src2=np.zeros(128, np.int32),
                           dst=np.arange(128, dtype=np.int32) % 60),
            TimingConfig())
        assert wide.n_cycles < sb.n_cycles  # independent ops overlap

    def test_narrow_machine_slower(self):
        tr = _trace()
        fast = compute_scoreboard(tr, TimingConfig())
        slow = compute_scoreboard(tr, TimingConfig(
            dispatch_width=1, issue_width=1, commit_width=1))
        assert slow.n_cycles > fast.n_cycles

    def test_small_rob_stalls_dispatch(self):
        tr = _trace()
        big = compute_scoreboard(tr, TimingConfig())
        small = compute_scoreboard(tr, TimingConfig(rob_size=8, iq_size=8,
                                                    lsq_size=4))
        assert small.n_cycles >= big.n_cycles

    def test_validate_rejects_bad_config(self):
        with pytest.raises(ValueError):
            TimingConfig(issue_width=0).validate()


class TestResidencySampler:
    def test_mass_proportional_sampling(self):
        """Entry draw frequency tracks residency length."""
        import jax

        start = np.array([0, 10, 20], np.int64)
        end = np.array([1, 19, 21], np.int64)      # lengths 1, 9, 1
        s = ResidencySampler(start, end)
        keys = prng.trial_keys(prng.campaign_key(0), 4096)
        entries, steps = jax.vmap(s.sample)(keys)
        counts = np.bincount(np.asarray(entries), minlength=3)
        frac = counts / counts.sum()
        np.testing.assert_allclose(frac, [1 / 11, 9 / 11, 1 / 11], atol=0.03)
        assert (np.asarray(steps) >= 0).all()

    def test_zero_mass_entries_never_drawn(self):
        import jax

        start = np.array([0, 5, 9], np.int64)
        end = np.array([4, 5, 12], np.int64)       # middle has zero mass
        s = ResidencySampler(start, end)
        keys = prng.trial_keys(prng.campaign_key(1), 512)
        entries, _ = jax.vmap(s.sample)(keys)
        assert not (np.asarray(entries) == 1).any()

    def test_step_equals_struck_entry(self):
        """Non-REGFILE faults apply at their µop (at_uop); the sampler's
        landing step is the entry itself."""
        import jax

        start = np.array([0, 4, 8], np.int64)
        end = np.array([4, 8, 12], np.int64)
        s = ResidencySampler(start, end)
        keys = prng.trial_keys(prng.campaign_key(9), 64)
        entries, steps = jax.vmap(s.sample)(keys)
        np.testing.assert_array_equal(np.asarray(entries),
                                      np.asarray(steps))

    def test_overflowing_mass_rescales_instead_of_wrapping(self):
        """A residency mass past 2^31 (large window × stall-heavy
        structure) must coarsen, not wrap the i32 cumsum: the cumulative
        table stays non-negative/monotone and zero-mass entries stay
        unreachable."""
        import jax

        n = 64
        start = np.zeros(n, np.int64)
        end = np.full(n, 2 ** 26, np.int64)        # total 2^32 > i32
        end[7] = 0                                 # one zero-mass entry
        s = ResidencySampler(start, end)
        cum = np.asarray(s.cum)
        assert s.total < 2 ** 31 and s.total > 0
        assert (np.diff(np.concatenate([[0], cum])) >= 0).all()
        keys = prng.trial_keys(prng.campaign_key(3), 512)
        entries, _ = jax.vmap(s.sample)(keys)
        assert not (np.asarray(entries) == 7).any()


class TestO3Integration:
    def test_scoreboard_sampler_runs_and_tallies(self):
        from shrewd_tpu.ops.trial import TrialKernel

        tr = _trace(n=256)
        kern = TrialKernel(tr, O3Config(timing="scoreboard"))
        keys = prng.trial_keys(prng.campaign_key(2), 64)
        for structure in ("rob", "iq", "lsq", "fu"):
            tally = np.asarray(kern.run_keys(keys, structure))
            assert tally.sum() == 64, structure

    def test_fu_faults_favor_long_latency_ops(self):
        import jax

        tr = _trace(n=512, seed=5)
        # the synth generator emits no divides (those arrive via the
        # lifter); plant a 1/16 static div mix explicitly
        op = np.asarray(tr.opcode).copy()
        op[::16] = U.DIV
        tr = tr._replace(opcode=op)
        div_frac_static = float(np.asarray(U.is_div(tr.opcode)).mean())
        from shrewd_tpu.models.o3 import FaultSampler

        s = FaultSampler(tr, "fu", O3Config(timing="scoreboard"))
        keys = prng.trial_keys(prng.campaign_key(3), 2048)
        f = jax.vmap(s.sample)(keys)
        entry = np.asarray(f.entry)
        # wrong-path FU mass draws the past-window sentinel (entry == n,
        # squash-masked in replay, r5); the on-path draws carry the
        # latency weighting
        onpath = entry[entry < tr.n]
        assert onpath.size > 0.5 * entry.size
        struck_div = float(
            np.asarray(U.is_div(np.asarray(tr.opcode)[onpath])).mean())
        # 20-cycle divides must be struck far above their static share
        assert struck_div > 3 * div_frac_static

    def test_lsq_scoreboard_only_strikes_mem_ops(self):
        import jax

        from shrewd_tpu.models.o3 import FaultSampler

        tr = _trace(n=256, seed=9)
        s = FaultSampler(tr, "lsq", O3Config(timing="scoreboard"))
        keys = prng.trial_keys(prng.campaign_key(4), 512)
        f = jax.vmap(s.sample)(keys)
        entry = np.asarray(f.entry)
        onpath = entry[entry < tr.n]       # drop wrong-path sentinels (r5)
        # non-vacuous: enough on-path draws to test the mem-only property
        # (this tiny cold-miss-dominated window legitimately carries a
        # LARGE wrong-path LSQ share: miss-fed mispredicts let the wrong
        # path run ~90 cycles deep, filling the LSQ — so no 50% floor)
        assert onpath.size >= 30
        struck = np.asarray(tr.opcode)[onpath]
        assert np.asarray(U.is_mem(struck)).all()

    def test_scoreboard_is_default_proxy_optin(self):
        """Round-4 default flip (O3_TIMING_VALIDATE_r04): the validated
        scoreboard drives residency by default; proxy stays available."""
        from shrewd_tpu.models.o3 import FaultSampler

        tr = _trace(n=128)
        assert FaultSampler(tr, "rob", O3Config())._res is not None
        assert FaultSampler(tr, "rob", O3Config(timing="proxy"))._res is None


class TestSquashModel:
    """Speculation/wrong-path (VERDICT r3 #7): bimodal mispredict points,
    redirect bubbles, and squash masking.  Reference: ROB squash walk
    (src/cpu/o3/rob.hh:207), bpred_unit.hh:99."""

    def _branchy_trace(self):
        """A real counted loop: r5 counts 12 down to 0, the back-edge
        (identical static row every iteration) is taken 11 times then
        falls through — dataflow-consistent, so the golden replay is
        divergence-free."""
        from shrewd_tpu.trace.format import Trace

        rows = []
        for it in range(12):
            rows.append((U.SUB, 5, 5, 6, 0, 0))            # r5 -= 1
            rows.append((U.BNE, 0, 5, 0, 64, 1 if it < 11 else 0))
        arr = np.array(rows, np.int64)
        init_reg = np.arange(64, dtype=np.uint32)
        init_reg[0] = 0
        init_reg[5] = 12
        init_reg[6] = 1
        t = Trace(opcode=arr[:, 0].astype(np.int32),
                  dst=arr[:, 1].astype(np.int32),
                  src1=arr[:, 2].astype(np.int32),
                  src2=arr[:, 3].astype(np.int32),
                  imm=arr[:, 4].astype(np.uint32),
                  taken=arr[:, 5].astype(np.int32),
                  init_reg=init_reg,
                  init_mem=np.zeros(64, dtype=np.uint32))
        t.validate()
        return t

    def test_bimodal_learns_the_loop_and_misses_the_exit(self):
        from shrewd_tpu.models.timing import predict_mispredicts

        t = self._branchy_trace()
        cfg = TimingConfig(bpred="bimodal")
        mp = predict_mispredicts(t, cfg)
        br = np.nonzero(np.asarray(U.is_branch(t.opcode)))[0]
        # cold counters mispredict early iterations; once warm the taken
        # loop back-edge predicts correctly; the final not-taken exit is
        # the classic end-of-loop miss
        assert mp[br[0]]                        # cold first encounter
        assert not mp[br[6]] and not mp[br[10]]  # warmed up
        assert mp[br[-1]]                        # loop exit mispredicts
        assert not mp[~np.asarray(U.is_branch(t.opcode))].any()

    def test_redirect_bubble_delays_next_dispatch(self):
        t = self._branchy_trace()
        sb_off = compute_scoreboard(t, TimingConfig(bpred="none"))
        sb_on = compute_scoreboard(
            t, TimingConfig(bpred="bimodal", redirect_penalty=5))
        mp = sb_on.mispredict
        i = int(np.nonzero(mp)[0][0])
        # the µop after a mispredicted branch cannot dispatch before the
        # branch resolves + the refill penalty
        assert sb_on.dispatch[i + 1] >= sb_on.writeback[i] + 5
        assert sb_off.mispredict is None
        # and total runtime got longer, never shorter
        assert sb_on.commit[-1] >= sb_off.commit[-1]

    def test_wrongpath_mass_accounted_for_rob_and_iq(self):
        t = self._branchy_trace()
        sb = compute_scoreboard(t, TimingConfig(bpred="bimodal"))
        assert sb.wp_mass_rob > 0
        assert 0 < sb.wp_mass_iq <= sb.wp_mass_rob
        assert sb.wrongpath_mass("rob") == sb.wp_mass_rob
        assert sb.wrongpath_mass("lsq") == 0

    def test_squashed_draw_is_sentinel_and_masked(self):
        """A draw landing in wrong-path mass returns the sentinel entry n;
        the replay kernel never matches that coordinate, so the trial is
        masked — squashed-entry faults die in the squash walk."""
        import jax

        from shrewd_tpu.ops import classify as C
        from shrewd_tpu.ops.trial import TrialKernel

        t = self._branchy_trace()
        n = t.n
        start = np.zeros(n, np.int64)
        end = np.ones(n, np.int64)              # real mass n
        s = ResidencySampler(start, end, squashed_mass=10_000_000)
        keys = prng.trial_keys(prng.campaign_key(5), 256)
        entries, steps = jax.vmap(s.sample)(keys)
        frac_sent = float((np.asarray(entries) == n).mean())
        assert frac_sent > 0.95                 # mass-dominated
        np.testing.assert_array_equal(np.asarray(entries),
                                      np.asarray(steps))
        # end-to-end: scoreboard+bimodal sampler outcomes on rob faults
        # include the squash-masked draws, and every sentinel is MASKED
        cfg = O3Config(timing="scoreboard",
                       timing_cfg=TimingConfig(bpred="bimodal"))
        k = TrialKernel(t, cfg)
        faults = k.sampler("rob").sample_batch(
            prng.trial_keys(prng.campaign_key(6), 512))
        ent = np.asarray(faults.entry)
        assert (ent == n).any()                 # wrong-path draws present
        out = np.asarray(k.run_batch(faults))
        assert (out[ent == n] == C.OUTCOME_MASKED).all()
