"""bench.py supervisor logic: JSON-line selection, pinned-baseline
loading, and the tunnel-probe contract (VERDICT r3 weak #1/#2 — the
official metric pipeline must not lie downward silently)."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def test_last_json_line_picks_refined_result():
    out = "\n".join([
        "noise",
        json.dumps({"metric": "m", "value": 1, "provisional": True}),
        "more noise",
        json.dumps({"metric": "m", "value": 2}),
        "{broken",
    ])
    line = bench._last_json_line(out)
    assert json.loads(line)["value"] == 2


def test_last_json_line_none_when_absent():
    assert bench._last_json_line("no json here\nat all") is None


def test_pinned_baseline_roundtrip(tmp_path, monkeypatch):
    pin = tmp_path / "BASELINE_MEASURED.json"
    monkeypatch.setattr(bench, "BASELINE_PIN", str(pin))
    assert bench._load_pinned_baseline(4096) is None      # missing file
    pin.write_text(json.dumps(
        {"metric": "serial_golden_trials_per_sec", "n_uops": 4096,
         "median": 14772.6}))
    assert bench._load_pinned_baseline(4096) == 14772.6
    assert bench._load_pinned_baseline(256) is None       # window mismatch
    pin.write_text("null")                                # malformed pin
    assert bench._load_pinned_baseline(4096) is None      # never raises


def test_strip_axon_site_removes_tunnel_path():
    env = bench._strip_axon_site(
        {"PYTHONPATH": "/root/.axon_site:/root/repo", "OTHER": "x"})
    assert "axon_site" not in env["PYTHONPATH"]
    assert "/root/repo" in env["PYTHONPATH"]


def test_probe_self_exits_never_hangs():
    """The probe process must terminate on its own well inside the
    supervisor's grace window even when the backend blocks — the watchdog
    self-exit is what keeps killed-mid-dial wedges impossible."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--probe",
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=bench.PROBE_WAIT_S,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": "/root"})
    assert proc.returncode == 0 and "PROBE_OK" in proc.stdout


def test_committed_pin_matches_schema():
    pin = json.loads((REPO / "BASELINE_MEASURED.json").read_text())
    assert pin["n_uops"] == 4096 and pin["median"] > 0
    assert pin["reps"] >= 5
