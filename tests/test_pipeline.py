"""Pipelined campaign engine (parallel/pipeline.py, exec_cache.py,
ShardedCampaign interval steps, orchestrator wiring).

The contract under test is the ISSUE acceptance criterion: pipelined
tallies are BIT-IDENTICAL to the serial loop — per-batch tallies are pure
functions of their frozen PRNG keys and integer sums commute — for
sync_every ∈ {1, >1, ragged final interval}, on the dense, hybrid
(device-resolution) and stratified paths, under injected chaos
(wedge / corrupt tally / worker kill mid-interval), and across a
mid-interval checkpoint/resume.  The watchdog's future-based mode must
preserve the wedge-detection guarantee (deadline armed at dispatch,
enforced at materialization), and the ``campaign.perf.*`` group must make
the pipelining observable.
"""

import time

import numpy as np
import pytest

from shrewd_tpu import resilience as resil
from shrewd_tpu.parallel import exec_cache


# --- executable cache (unit) ------------------------------------------------

def test_exec_cache_reuse_and_owner_guard():
    cache = exec_cache.ExecutableCache(max_entries=2)

    class Owner:
        pass

    o1 = Owner()
    built = []

    def build():
        built.append(1)
        return lambda x: x + 1

    fn = cache.get(("k1",), o1, build)
    assert fn(1) == 2 and cache.compiled == 1
    assert cache.get(("k1",), o1, build) is fn
    assert cache.reused == 1 and len(built) == 1
    # dead owner invalidates the entry (id() reuse guard)
    del o1
    cache.get(("k1",), Owner(), build)
    assert cache.compiled == 2
    # LRU eviction keeps the cache bounded
    keep = Owner()
    cache.get(("k2",), keep, build)
    cache.get(("k3",), keep, build)
    assert len(cache._entries) == 2 and cache.evicted >= 1


def test_trace_digest_is_content_keyed():
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    cfg = dict(n=64, nphys=32, mem_words=64, working_set_words=32, seed=3)
    a = generate(WorkloadConfig(**cfg))
    b = generate(WorkloadConfig(**cfg))          # distinct object, same content
    c = generate(WorkloadConfig(**{**cfg, "seed": 4}))
    assert a is not b
    assert exec_cache.trace_digest(a) == exec_cache.trace_digest(b)
    assert exec_cache.trace_digest(a) != exec_cache.trace_digest(c)


# --- watchdog future mode (unit) -------------------------------------------

def test_watchdog_armed_deadline_enforced_at_materialization():
    wd = resil.DeviceWatchdog(0.3)
    # a result that is already complete materializes instantly even when
    # the armed deadline has fully elapsed (the floor grace)
    armed = wd.arm() - 10.0
    assert wd.call_armed(lambda: 42, armed) == 42
    # a wedged materialization surfaces within the REMAINING deadline,
    # measured from dispatch — not a fresh full deadline
    armed = wd.arm()
    t0 = time.monotonic()
    with pytest.raises(resil.DispatchTimeout):
        wd.call_armed(lambda: time.sleep(30), armed)
    assert time.monotonic() - t0 < 5.0
    assert wd.timeouts == 1
    # timeout 0 disables the deadline entirely (serial parity)
    wd0 = resil.DeviceWatchdog(0.0)
    assert wd0.call_armed(lambda: 7, wd0.arm() - 99) == 7


# --- chaos interval arming (unit) -------------------------------------------

def test_chaos_begin_batches_arms_union():
    from shrewd_tpu.chaos import ChaosEngine

    eng = ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": 1, "tier": "device"},
        {"kind": "corrupt_tally", "at_batch": 3},
    ]})
    eng.begin_batches(range(0, 4), "w0", "regfile")
    # both faults (on different batches of the interval) are armed at once
    assert set(eng._armed) == {"backend_error", "corrupt_tally"}
    assert eng.dispatches == 4          # per-batch counter still advances


# --- campaign + plan fixtures ----------------------------------------------

def _tiny_plan(sync_every=1, depth=2, n_batches=6, batch_size=32,
               canaries=0, **kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    defaults = dict(structures=["regfile"], batch_size=batch_size,
                    target_halfwidth=0.2, confidence=0.95,
                    max_trials=batch_size * n_batches,
                    min_trials=batch_size * n_batches)
    defaults.update(kw)
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        **defaults)
    # audit off (pure jax compute, identical either loop — test_integrity
    # owns it); canaries per test — interval-boundary canaries are part of
    # the pipelined design and get their own coverage below
    plan.integrity.canary_trials = canaries
    plan.integrity.audit_rate = 0.0
    plan.resilience.backoff_base = 0.0
    plan.pipeline.sync_every = sync_every
    plan.pipeline.depth = depth
    return plan


def _run(plan, outdir=None):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(plan, outdir=outdir)
    events = list(orch.events())
    results = (dict(events[-1][1])
               if events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE else None)
    return orch, results


# --- interval step bit-identity (campaign level) ----------------------------

@pytest.mark.parametrize("mode,stratify", [
    ("hybrid", False), ("dense", False), ("hybrid", True)])
def test_interval_step_matches_serial_batches(mode, stratify):
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate
    from shrewd_tpu.utils import prng

    tr = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                 working_set_words=32, seed=7))
    kernel = TrialKernel(tr, O3Config(replay_kernel=mode))
    camp = ShardedCampaign(kernel, make_mesh(), "regfile",
                           stratify=stratify, integrity_check=True)
    assert camp.supports_intervals
    B = 32
    sk = prng.structure_key(prng.simpoint_key(prng.campaign_key(0), 0), 0)

    def keys(b):
        return prng.trial_keys(prng.batch_key(sk, b), B)

    serial = None
    for b in range(6):
        t = np.asarray(camp.tally_batch_stratified(keys(b)) if stratify
                       else camp.tally_batch(keys(b)), dtype=np.int64)
        serial = t if serial is None else serial + t
    esc_serial = kernel.escapes
    kernel.escapes = kernel.taint_trials = 0
    acc = np.zeros_like(serial)
    for b0, k in ((0, 4), (4, 2)):      # sync 4 + ragged final interval
        tally, strata = camp.tally_interval(
            [keys(b) for b in range(b0, b0 + k)])
        acc += strata if stratify else tally
    np.testing.assert_array_equal(acc, serial)
    assert kernel.escapes == esc_serial   # counters match the serial loop


# --- orchestrator bit-identity ----------------------------------------------

def test_orchestrator_pipelined_bit_identical_with_ragged_interval():
    # 9 batches, sync 4 → intervals of 4 + 4 + a 1-batch ragged TAIL
    # (which must be consumed from the engine's in-flight queue, not
    # recomputed serially); canaries ON so the interval-boundary canary
    # path is exercised in the believed flow
    _, serial = _run(_tiny_plan(sync_every=1, canaries=2, n_batches=9))
    orch, piped = _run(_tiny_plan(sync_every=4, canaries=2, n_batches=9))
    assert serial is not None and piped is not None
    for key in serial:
        np.testing.assert_array_equal(serial[key].tallies,
                                      piped[key].tallies)
        assert serial[key].trials == piped[key].trials
    assert orch._perf.intervals == 3
    # dispatch-ahead covered every batch exactly once: the 1-batch tail
    # came out of the in-flight queue, not a duplicate serial compute
    assert orch._perf.dispatches == 3
    assert orch._perf.serial_fallbacks == 0
    assert orch.monitor.canary_runs == 3     # per interval, not per batch
    # the perf group is a first-class stats citizen
    from shrewd_tpu import stats as statsmod
    perf = statsmod.to_dict(orch.stats)["perf"]
    assert perf["dispatch_depth"] >= 1
    assert 0.0 <= perf["overlap_fraction"] <= 1.0
    assert perf["executables_compiled"] > 0


def test_orchestrator_pipelined_stratified_bit_identical():
    _, serial = _run(_tiny_plan(sync_every=1, stratify=True))
    _, piped = _run(_tiny_plan(sync_every=4, stratify=True))
    for key in serial:
        np.testing.assert_array_equal(serial[key].tallies,
                                      piped[key].tallies)
        # the post-stratified interval is a pure function of the strata,
        # so it must agree too
        assert serial[key].avf_interval == piped[key].avf_interval


# --- chaos mid-interval ------------------------------------------------------

def test_pipelined_corrupt_tally_mid_interval_recovers_bit_identical():
    from shrewd_tpu.chaos import ChaosEngine

    clean_orch, clean = _run(_tiny_plan(sync_every=1))
    plan = _tiny_plan(sync_every=4)
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    orch = Orchestrator(plan)
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "corrupt_tally", "at_batch": 5, "delta": 3}]}))
    events = list(orch.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
    assert orch.chaos.injected == {"corrupt_tally": 1}
    assert orch.chaos.survived == orch.chaos.injected
    assert orch.monitor.quarantined >= 1       # interval quarantined ...
    assert orch._perf.serial_fallbacks >= 1    # ... and recovered serially
    assert orch.monitor.recovered >= 1
    # escape-counter parity under quarantine: the untrusted interval's
    # counter bump is rolled back before serial recovery re-adds the
    # believed values (the hybrid path counts escapes on every dispatch)
    key = ("w0", "regfile")
    assert orch.state[key].escapes == clean_orch.state[key].escapes
    assert (orch.state[key].taint_trials
            == clean_orch.state[key].taint_trials)


def test_pipelined_wedge_mid_interval_recovers_bit_identical():
    from shrewd_tpu.chaos import ChaosEngine

    _, clean = _run(_tiny_plan(sync_every=1))
    plan = _tiny_plan(sync_every=4)
    plan.resilience.dispatch_timeout = 30.0     # deadline-bearing dispatch
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    orch = Orchestrator(plan)
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "wedge", "at_batch": 5, "deadline": 0.2}]}))
    events = list(orch.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
    # the wedge fired through the REAL watchdog machinery at
    # materialization (armed at dispatch) and the interval recovered
    # through the serial ladder on frozen keys
    assert orch.chaos.injected.get("wedge", 0) >= 1
    assert orch.chaos.survived.get("wedge", 0) >= 1
    assert orch.watchdog.timeouts >= 1
    assert orch._perf.serial_fallbacks >= 1


def test_pipelined_kill_worker_mid_interval_resumes_bit_identical(
        tmp_path, monkeypatch):
    import os as _os

    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.chaos import ChaosEngine

    class _Killed(BaseException):
        pass

    _, clean = _run(_tiny_plan(sync_every=1, n_batches=8))
    # the worker dies at the boundary of the interval containing batch 5
    # (mid-sync-grid); checkpoint_every=2 leaves a resumable checkpoint
    # at the previous interval boundary
    plan = _tiny_plan(sync_every=4, n_batches=8, checkpoint_every=2)
    orch = Orchestrator(plan, outdir=str(tmp_path / "out"))
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "kill_worker", "at_batch": 5}]}))
    monkeypatch.setattr(_os, "_exit",
                        lambda rc: (_ for _ in ()).throw(_Killed()))
    with pytest.raises(_Killed):
        for _ in orch.events():
            pass
    ckpt = str(tmp_path / "out" / "campaign_ckpt")
    orch2 = Orchestrator.resume(ckpt, outdir=str(tmp_path / "out2"))
    # the dead worker is not re-injected on resume (a real kill is once)
    orch2.chaos = None
    orch2.watchdog.chaos = None
    events = list(orch2.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
        assert clean[key].trials == results[key].trials


# --- mid-interval checkpoint / resume ---------------------------------------

def test_resume_from_mid_grid_checkpoint_matches_undisturbed(tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    _, clean = _run(_tiny_plan(sync_every=1))
    # serial run leaves its LAST checkpoint at batch 4 — not a multiple
    # of the resumed run's sync_every, so the resumed pipelined campaign
    # starts mid-grid and its first interval is ragged
    plan = _tiny_plan(sync_every=1, checkpoint_every=4)
    orch, _ = _run(plan, outdir=str(tmp_path / "out"))
    ckpt = str(tmp_path / "out" / "campaign_ckpt")
    doc = Orchestrator.load_checkpoint_doc(ckpt)
    st = doc["state"]["w0"]["regfile"]
    assert st["next_batch"] == 4           # genuinely mid-run
    orch2 = Orchestrator.resume(ckpt, outdir=str(tmp_path / "out2"))
    orch2.pcfg.sync_every = 4              # resume PIPELINED
    events = list(orch2.events())
    results = dict(events[-1][1])
    for key in clean:
        np.testing.assert_array_equal(clean[key].tallies,
                                      results[key].tallies)
        assert clean[key].trials == results[key].trials
