"""Scalar-SSE FP lift + FP-bank fault injection (VERDICT r3 #6).

The FP bank (phys FX0+k = xmm_k low lane) becomes a device-side REGFILE
fault target on real lifted code, verified per-macro-op against the
tracer's captured xmm lanes (SHTRACE3) and host-diffed against silicon
xmm flips (hostsfi PTRACE_SETFPREGS).  Reference: the FP/SIMD
PhysRegFile banks (/root/reference/src/cpu/o3/regfile.hh:75-99) and FP
OpClasses (src/cpu/FuncUnitConfig.py) — the shadow-FU story the fork
exists for is chiefly FP."""

import shutil

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("objdump") is None,
    reason="host toolchain required")


@pytest.fixture(scope="module")
def fpmix():
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/fpmix.c")
    trace, meta = hd.capture_and_lift_to_output(paths)
    return paths, trace, meta


def test_capture_carries_xmm_lanes(fpmix):
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.lift import read_nativetrace

    paths, _, _ = fpmix

    def probe(p):
        nt = read_nativetrace(p)
        assert nt.steps.shape[1] == 26      # SHTRACE3: +8 xmm-lane words
        # the FP kernel's xmm0 lane must move during the window
        lanes0 = nt.steps[:, 18] & np.uint64(0xFFFFFFFF)
        assert len(np.unique(lanes0)) > 4
        return True

    assert hd._capture(paths, "xmmprobe", probe)


def test_fp_lift_rate_and_golden(fpmix):
    from shrewd_tpu.isa import semantics

    _, trace, meta = fpmix
    st = meta["stats"]
    assert st["lift_rate"] > 0.985, st["opaque_mnemonics"]
    assert trace.nphys == 64 and meta["fp_bank"] == 32
    reg, mem = trace.init_reg.copy(), trace.init_mem.copy()
    semantics.scalar_replay(trace, reg, mem)
    exp = np.asarray(meta["final_reg_expect"], np.uint64)
    np.testing.assert_array_equal(reg[:16], exp.astype(np.uint32))


def test_golden_output_bytes_exact(fpmix):
    """The lifted window runs through the float kernel AND the integer
    digit formatting (imul/shr divide-by-constant via the MULHU peephole)
    to produce the program's exact stdout bytes in replay memory."""
    import subprocess

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    paths, trace, meta = fpmix
    real = subprocess.run([str(paths.workload)], capture_output=True)
    ev = meta["output_events"][0]
    k = TrialKernel(trace, O3Config(enable_shrewd=False))
    words = np.asarray(k.golden.mem)[np.asarray(ev["words"])]
    got = b"".join(int(w).to_bytes(4, "little") for w in words)
    assert got[:len(real.stdout)] == real.stdout


@pytest.mark.parametrize("a,b", [
    (0xCCCCCCCD, 12345678), (0xFFFFFFFF, 0xFFFFFFFF), (7, 9),
    (1 << 31, 1 << 31), (0, 0xDEADBEEF),
])
def test_mulhu_bit_exact_across_backends(a, b):
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.isa import semantics, uops as U
    from shrewd_tpu.ops.replay import _mulhi

    want = ((a * b) >> 32) & 0xFFFFFFFF
    assert semantics.alu(U.MULHU, a, b, 0) == want
    assert int(jax.jit(_mulhi)(jnp.uint32(a), jnp.uint32(b))) == want


def test_fp_bank_fault_reaches_program_output(fpmix):
    """A fault in an xmm lane mid-kernel must corrupt the formatted
    output digits — the int/float boundary (movd) and the digit loop's
    64-bit divide idiom both lift, so nothing severs the propagation."""
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.ingest.hostdiff import memmap_from_meta
    from shrewd_tpu.models.o3 import Fault, KIND_REGFILE, O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    _, trace, meta = fpmix
    us = np.asarray(meta["uop_start"])
    ev = meta["output_events"][0]
    k = TrialKernel(trace, O3Config(enable_shrewd=False),
                    memmap=memmap_from_meta(meta))
    # a BIG flip (exponent bit 30) of xmm0 early in the kernel
    f = Fault(kind=jnp.int32(KIND_REGFILE), cycle=jnp.int32(us[200]),
              entry=jnp.int32(32), bit=jnp.int32(30),
              shadow_u=jnp.float32(1.0))
    r = jax.jit(k._replay_one)(f)
    words = np.asarray(ev["words"])
    masks = np.asarray(ev["byte_masks"], np.uint32)
    delta = (np.asarray(r.mem)[words] ^ np.asarray(k.golden.mem)[words])
    assert ((delta & masks) != 0).any() or bool(r.trapped) \
        or bool(r.diverged)


@pytest.mark.slow
def test_fp_hostdiff_agreement(fpmix):
    """Paired silicon-vs-device FP campaign: xmm+GPR coordinates, host
    flips via PTRACE_SETFPREGS — vulnerable agreement ≥ 0.97 (VERDICT r3
    #6 acceptance)."""
    from shrewd_tpu.ingest import hostdiff as hd

    rep = hd.run_diff(80, 3, "workloads/fpmix.c", mode="fp")
    assert rep["agreement_vulnerable"] >= 0.97, rep
    assert rep["avf_abs_err"] <= 0.05
