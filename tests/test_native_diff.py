"""Differential tests: native C++ runtime vs JAX device path vs scalar oracle.

The framework's CheckerCPU pattern (SURVEY §4 tier 4): three independent
implementations of the trial semantics must agree bit-for-bit.
"""

import jax
import numpy as np
import pytest

from shrewd_tpu import native
from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.models.o3 import O3Config, null_fault
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


@pytest.fixture(scope="module")
def built():
    native.build()
    return True


@pytest.fixture(scope="module")
def py_trace():
    return generate(WorkloadConfig(n=384, nphys=64, mem_words=256,
                                   working_set_words=128, seed=21))


def test_native_golden_matches_scalar_oracle(built, py_trace):
    reg, mem = py_trace.init_reg.copy(), py_trace.init_mem.copy()
    semantics.scalar_replay(py_trace, reg, mem)
    creg, cmem = native.golden_replay(py_trace)
    np.testing.assert_array_equal(creg, reg)
    np.testing.assert_array_equal(cmem, mem)


def test_native_engine_trace_is_valid_and_deterministic(built):
    t1 = native.generate_trace(seed=5, n=1024, nphys=128, mem_words=512,
                               working_set_words=256)
    t2 = native.generate_trace(seed=5, n=1024, nphys=128, mem_words=512,
                               working_set_words=256)
    for f in t1._fields:
        np.testing.assert_array_equal(getattr(t1, f), getattr(t2, f))
    # the recorded branch outcomes must be consistent under scalar replay
    reg, mem = t1.init_reg.copy(), t1.init_mem.copy()
    taken = semantics.scalar_replay(t1, reg, mem)
    np.testing.assert_array_equal(np.array(taken),
                                  t1.taken[U.is_branch(t1.opcode)])
    # mix sanity
    assert (t1.opcode == U.LOAD).mean() > 0.1
    assert (t1.opcode == U.STORE).mean() > 0.05


@pytest.mark.parametrize("structure",
                         ["regfile", "fu", "rob", "iq", "lsq", "latch"])
@pytest.mark.parametrize("source", ["python", "native"])
def test_jax_vs_native_trial_outcomes(built, structure, source, py_trace):
    """The core differential contract: identical fault coords → identical
    outcome classes on the JAX batched path and the C++ serial path."""
    if source == "python":
        t = py_trace
    else:
        t = native.generate_trace(seed=9, n=384, nphys=64, mem_words=256,
                                  working_set_words=128)
    cfg = O3Config(shadow_coverage=[0.4] * U.N_OPCLASSES)
    k = TrialKernel(t, cfg)
    keys = prng.trial_keys(prng.campaign_key(3), 96)
    faults = k.sampler(structure).sample_batch(keys)
    jax_out = np.asarray(k.run_batch(faults))

    native_out = native.golden_trials(
        t,
        np.asarray(faults.kind), np.asarray(faults.cycle),
        np.asarray(faults.entry), np.asarray(faults.bit),
        np.asarray(faults.shadow_u),
        np.asarray(k.shadow_cov),          # per-µop coverage
        compare_regs=cfg.compare_regs)
    np.testing.assert_array_equal(jax_out, native_out)


def test_native_null_fault_masked(built, py_trace):
    out = native.golden_trials(
        py_trace, [0], [0], [0], [0], [1.0],
        np.zeros(py_trace.n, dtype=np.float32))
    assert out[0] == 0


def test_native_rejects_bad_params(built):
    with pytest.raises(ValueError):
        native.generate_trace(seed=1, n=64, nphys=100,  # not a power of two
                              mem_words=256, working_set_words=64)
