"""Fast chunked engines + preprocessed window store (ops/chunked.py,
ops/window.py).

test_chunked.py pins chunked-vs-dense parity for the default engine; this
file pins the rest of the SimPoint-scale contract: the deviation-set
engines ("taint", and "pallas" in interpret mode) are bit-identical to the
exact engine across structures and ragged tails, the carry-horizon
relabeling is engine-independent, the content-addressed window store
round-trips byte-identical (and rot reads as a rebuild, never as
corruption), warm starts re-preprocess nothing, and a corrupted chunked
tally quarantines and recovers bit-identical through the integrity layer.
"""

import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops import window as W
from shrewd_tpu.ops.chunked import ChunkedCampaign, preprocess_window
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def mk_kernel(n=384, seed=11, **cfg):
    t = generate(WorkloadConfig(n=n, nphys=32, mem_words=64,
                                working_set_words=32, seed=seed))
    return TrialKernel(t, O3Config(**cfg))


# --- fast-vs-exact bit-identity ----------------------------------------------

@pytest.mark.parametrize("structure",
                         ["regfile", "fu", "rob", "iq", "lsq", "latch"])
def test_taint_engine_matches_exact(structure):
    # 300 = 3*77 + 69: a ragged tail, so the NOP-padded final chunk and
    # the out-of-window resolver are both in play
    kernel = mk_kernel(n=300)
    keys = prng.trial_keys(prng.campaign_key(41), 64)
    exact = ChunkedCampaign(kernel, chunk=77, engine="exact")
    fast = ChunkedCampaign(kernel, chunk=77, engine="taint")
    np.testing.assert_array_equal(
        fast.outcomes_from_keys(keys, structure),
        exact.outcomes_from_keys(keys, structure), err_msg=structure)
    assert fast.last_stats["engine"] == "taint"
    assert exact.last_stats["engine"] == "exact"


@pytest.mark.slow
@pytest.mark.parametrize("structure", ["regfile", "fu"])
def test_pallas_engine_matches_exact(structure):
    # interpret mode off-TPU: small window keeps the cost bounded
    kernel = mk_kernel(n=160, pallas="on")
    keys = prng.trial_keys(prng.campaign_key(41), 32)
    exact = ChunkedCampaign(kernel, chunk=96, engine="exact")
    fast = ChunkedCampaign(kernel, chunk=96, engine="pallas")
    np.testing.assert_array_equal(
        fast.outcomes_from_keys(keys, structure),
        exact.outcomes_from_keys(keys, structure), err_msg=structure)
    assert fast.last_stats["engine"] == "pallas"


def test_fast_fallback_lanes_still_bit_identical():
    # a tiny deviation-set budget forces overflow fallbacks through the
    # per-trial exact path — outcomes must not change
    kernel = mk_kernel(n=300, seed=3, taint_k=4)
    keys = prng.trial_keys(prng.campaign_key(9), 64)
    exact = ChunkedCampaign(kernel, chunk=77, engine="exact")
    fast = ChunkedCampaign(kernel, chunk=77, engine="taint")
    np.testing.assert_array_equal(
        fast.outcomes_from_keys(keys, "regfile"),
        exact.outcomes_from_keys(keys, "regfile"))
    assert fast.last_stats["fallback_lanes"] > 0


# --- carry-horizon parity -----------------------------------------------------

def test_carry_horizon_relabeling_is_engine_independent():
    """The horizon cut is part of the outcome semantics, not of any one
    engine: fast and exact with the same horizon produce identical
    outcomes AND relabel the same number of trials."""
    kernel = mk_kernel(n=512, seed=17)
    keys = prng.trial_keys(prng.campaign_key(23), 96)
    exact = ChunkedCampaign(kernel, chunk=64, carry_horizon=1,
                            engine="exact")
    fast = ChunkedCampaign(kernel, chunk=64, carry_horizon=1,
                           engine="taint")
    oe = exact.outcomes_from_keys(keys, "regfile")
    of = fast.outcomes_from_keys(keys, "regfile")
    np.testing.assert_array_equal(of, oe)
    assert fast.last_stats["horizon_sdc"] == exact.last_stats["horizon_sdc"]
    assert fast.last_stats["horizon_sdc"] > 0


# --- window store -------------------------------------------------------------

def test_store_roundtrip_byte_identical(tmp_path):
    from shrewd_tpu.ingest.store import ArtifactStore

    kernel = mk_kernel(n=300)
    store = ArtifactStore(str(tmp_path))
    W.clear_registry()
    stored0 = W.STATS["stored"]
    w1 = preprocess_window(kernel, 77, store=store)
    assert w1.source == "built"
    assert W.STATS["stored"] == stored0 + 1

    # a fresh process (registry cleared) loads the stored window mmap'd,
    # byte-identical, with zero re-preprocessing
    W.clear_registry()
    builds0, hits0 = W.STATS["builds"], W.STATS["store_hits"]
    w2 = preprocess_window(kernel, 77, store=store)
    assert w2.source == "store"
    assert W.STATS["builds"] == builds0
    assert W.STATS["store_hits"] == hits0 + 1
    for f in W.TRACE_FIELDS:
        np.testing.assert_array_equal(np.asarray(w2.tr[f]),
                                      np.asarray(w1.tr[f]), err_msg=f)
    np.testing.assert_array_equal(np.asarray(w2.gb_reg), w1.gb_reg)
    np.testing.assert_array_equal(np.asarray(w2.gb_mem), w1.gb_mem)

    # and a campaign over the loaded window is bit-identical
    keys = prng.trial_keys(prng.campaign_key(5), 48)
    np.testing.assert_array_equal(
        ChunkedCampaign(kernel, chunk=77, window=w2)
        .outcomes_from_keys(keys, "fu"),
        ChunkedCampaign(kernel, chunk=77, window=w1)
        .outcomes_from_keys(keys, "fu"))


def test_store_rot_reads_as_rebuild(tmp_path):
    """A rotted payload must never load as corruption: get_arrays
    re-verifies every byte, so the window rebuilds byte-identical."""
    from shrewd_tpu.ingest.store import ArtifactStore

    kernel = mk_kernel(n=300)
    store = ArtifactStore(str(tmp_path))
    W.clear_registry()
    w1 = preprocess_window(kernel, 77, store=store)
    payloads = sorted(tmp_path.rglob("*.npy"))
    assert payloads
    blob = bytearray(payloads[0].read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    payloads[0].write_bytes(bytes(blob))

    assert W.load_from_store(store, w1.trace_digest, 77) is None

    W.clear_registry()
    builds0 = W.STATS["builds"]
    w3 = preprocess_window(kernel, 77, store=store)
    assert w3.source == "built"
    assert W.STATS["builds"] == builds0 + 1
    for f in W.TRACE_FIELDS:
        np.testing.assert_array_equal(np.asarray(w3.tr[f]),
                                      np.asarray(w1.tr[f]), err_msg=f)
    np.testing.assert_array_equal(np.asarray(w3.gb_reg), w1.gb_reg)
    np.testing.assert_array_equal(np.asarray(w3.gb_mem), w1.gb_mem)


def test_native_boundary_pass_matches_jax_pass(monkeypatch):
    """The C++ boundary pass (the 26M-µop setup enabler) and the jax
    chunk-scan fallback produce byte-identical boundary states."""
    import shrewd_tpu.ops.chunked as chunked_mod

    kernel = mk_kernel(n=300, seed=5)
    W.clear_registry()
    wn = preprocess_window(kernel, 77)
    if not chunked_mod._native_boundary_pass(wn):
        pytest.skip("native library unavailable")
    W.clear_registry()
    monkeypatch.setattr(chunked_mod, "NATIVE_BOUNDARY", False)
    wj = preprocess_window(kernel, 77)
    np.testing.assert_array_equal(wn.gb_reg, wj.gb_reg)
    np.testing.assert_array_equal(wn.gb_mem, wj.gb_mem)


def test_registry_warm_start_skips_boundary_pass():
    kernel = mk_kernel()
    W.clear_registry()
    builds0 = W.STATS["builds"]
    w1 = preprocess_window(kernel, 128)
    assert W.STATS["builds"] == builds0 + 1
    hits0 = W.STATS["registry_hits"]
    assert preprocess_window(kernel, 128) is w1
    assert W.STATS["registry_hits"] == hits0 + 1
    # a second campaign over the same (trace, S) re-preprocesses nothing
    ChunkedCampaign(kernel, chunk=128)
    assert W.STATS["builds"] == builds0 + 1
    # ...but a different chunk length is a different window
    preprocess_window(kernel, 96)
    assert W.STATS["builds"] == builds0 + 2


# --- integrity over the chunked path -------------------------------------------

def test_chunked_quarantine_recovers_bit_identical():
    """A corrupted chunked tally trips the batch invariants, quarantines,
    and the re-dispatch on the SAME frozen keys recovers bit-identical —
    the chunked route composes with the integrity layer unchanged."""
    from shrewd_tpu import resilience as resil
    from shrewd_tpu.integrity import (IntegrityConfig, IntegrityMonitor,
                                      checked_dispatcher_for)
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh

    kernel = mk_kernel(n=256, seed=7)
    ch = ChunkedCampaign(kernel, chunk=96, max_batch=64)
    camp = ShardedCampaign(kernel, make_mesh(), "fu", chunked=ch)
    keys = prng.trial_keys(prng.campaign_key(3), 64)
    want = np.asarray(camp.tally_batch(keys))
    assert int(want.sum()) == 64

    rcfg = resil.ResilienceConfig()
    rcfg.backoff_base = rcfg.backoff_max = 0.0
    mon = IntegrityMonitor(IntegrityConfig(canary_trials=0, audit_rate=0.0))
    cd = checked_dispatcher_for(resil.dispatcher_for_campaign(camp, rcfg),
                                camp, mon, "w0", "fu")

    def corrupt(t):
        t = t.copy()
        t[C.OUTCOME_MASKED] += 7        # breaks sum == batch
        return t

    mon.arm_corruption(corrupt)
    res = cd.tally_batch(keys, batch_id=0)
    assert mon.quarantined == 1 and mon.requeues == 1 and mon.recovered == 1
    np.testing.assert_array_equal(np.asarray(res.tally), want)
