"""Pallas fast-pass kernel tests (ops/pallas_taint.py).

Differential contract: the Pallas kernel's TaintResult (outcome, escaped,
overflow) is bit-identical to the XLA taint fast pass for every structure.
Runs in interpreter mode on CPU (the NULL-build analog); the real lowering
is exercised on the TPU by bench.py."""

import jax
import numpy as np
import pytest

from shrewd_tpu.isa import uops as U
from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def make_kernel(seed=31, n=160, pallas="on", **cfg_kw):
    t = generate(WorkloadConfig(n=n, nphys=32, mem_words=64,
                                working_set_words=32, seed=seed))
    return TrialKernel(t, O3Config(pallas=pallas, **cfg_kw))


@pytest.mark.parametrize("structure",
                         ["regfile", "fu", "rob", "iq", "lsq", "latch"])
def test_pallas_matches_xla_taint(structure):
    k = make_kernel()
    keys = prng.trial_keys(prng.campaign_key(12), 32)
    faults = k.sample_batch(keys, structure)
    ref = k.taint_batch(faults, False)
    got = k.taint_fast(faults, may_latch=True)
    np.testing.assert_array_equal(np.asarray(got.escaped),
                                  np.asarray(ref.escaped))
    np.testing.assert_array_equal(np.asarray(got.overflow),
                                  np.asarray(ref.overflow))
    resolved = ~np.asarray(ref.escaped | ref.overflow)
    np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                  np.asarray(ref.outcome)[resolved])


@pytest.mark.parametrize("structure", ["regfile", "fu", "iq"])
def test_scalar_alu_path_matches(structure):
    """may_latch=False (lax.switch scalar ALU) on non-latch structures."""
    k = make_kernel(seed=32)
    keys = prng.trial_keys(prng.campaign_key(13), 32)
    faults = k.sample_batch(keys, structure)
    ref = k.taint_batch(faults, False)
    got = k.taint_fast(faults, may_latch=False)
    resolved = ~np.asarray(ref.escaped | ref.overflow)
    np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                  np.asarray(ref.outcome)[resolved])
    np.testing.assert_array_equal(np.asarray(got.escaped),
                                  np.asarray(ref.escaped))


def test_hybrid_with_pallas_equals_dense():
    k = make_kernel(seed=33)
    keys = prng.trial_keys(prng.campaign_key(14), 48)
    faults = k.sample_batch(keys, "regfile")
    np.testing.assert_array_equal(k.run_batch_hybrid(faults),
                                  np.asarray(k.run_batch(faults)))


def test_batch_padding():
    """Batch sizes that are not multiples of b_tile are padded internally."""
    k = make_kernel(seed=34)
    keys = prng.trial_keys(prng.campaign_key(15), 33)   # odd batch
    faults = k.sample_batch(keys, "fu")
    ref = k.taint_batch(faults, False)
    got = k.taint_fast(faults)
    assert got.outcome.shape == ref.outcome.shape == (33,)
    resolved = ~np.asarray(ref.escaped | ref.overflow)
    np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                  np.asarray(ref.outcome)[resolved])


def test_pallas_off_uses_xla():
    k = make_kernel(seed=35, pallas="off")
    assert not k._pallas_enabled()


@pytest.mark.parametrize("u", [2, 8])
def test_unrolled_kernel_matches(u):
    """u_steps-unrolled kernel is bit-identical to the XLA fast pass."""
    k = make_kernel(seed=36, pallas_u_steps=u)
    keys = prng.trial_keys(prng.campaign_key(16), 24)
    for structure in ("regfile", "latch"):
        faults = k.sample_batch(keys, structure)
        ref = k.taint_batch(faults, False)
        got = k.taint_fast(faults, may_latch=True)
        np.testing.assert_array_equal(np.asarray(got.escaped),
                                      np.asarray(ref.escaped))
        np.testing.assert_array_equal(np.asarray(got.overflow),
                                      np.asarray(ref.overflow))
        resolved = ~np.asarray(ref.escaped | ref.overflow)
        np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                      np.asarray(ref.outcome)[resolved])


def test_unrolled_kernel_overrun_padding():
    """u=64 on n=160: the last grid step over-runs by 32 zero-padded (NOP)
    columns, which must be inert (scalar ALU path keeps the trace small)."""
    k = make_kernel(seed=37, pallas_u_steps=64)
    keys = prng.trial_keys(prng.campaign_key(17), 12)
    faults = k.sample_batch(keys, "regfile")
    ref = k.taint_batch(faults, False)
    got = k.taint_fast(faults, may_latch=False)
    resolved = ~np.asarray(ref.escaped | ref.overflow)
    np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                  np.asarray(ref.outcome)[resolved])
    np.testing.assert_array_equal(np.asarray(got.escaped),
                                  np.asarray(ref.escaped))


def test_unrolled_kernel_overrun_latch_faults():
    """The dangerous combination: over-run phantom steps (u=64, n=160) with
    LATCH faults whose cycle/entry can land in [n, n+n_latches) (the minor
    sampler's range).  Without the i<n mask a LATCH_OP firing on a phantom
    NOP column fabricates a real opcode; the XLA kernel runs exactly n
    steps, so the two must stay bit-identical."""
    from shrewd_tpu.models.o3 import (Fault, KIND_LATCH_IMM, KIND_LATCH_OP)

    k = make_kernel(seed=38, pallas_u_steps=64)
    keys = prng.trial_keys(prng.campaign_key(18), 8)
    s = k.sample_batch(keys, "latch")
    # direct the first lanes into the phantom range [n, ceil(n/64)*64)
    faults = Fault(
        kind=s.kind.at[0].set(KIND_LATCH_OP).at[1].set(KIND_LATCH_IMM)
                   .at[2].set(KIND_LATCH_OP),
        cycle=s.cycle.at[0].set(161).at[1].set(170).at[2].set(188),
        entry=s.entry.at[0].set(161).at[1].set(170).at[2].set(188),
        bit=s.bit.at[0].set(3).at[1].set(7).at[2].set(30),
        shadow_u=s.shadow_u)
    ref = k.taint_batch(faults, False)
    got = k.taint_fast(faults, may_latch=True)
    np.testing.assert_array_equal(np.asarray(got.escaped),
                                  np.asarray(ref.escaped))
    np.testing.assert_array_equal(np.asarray(got.overflow),
                                  np.asarray(ref.overflow))
    resolved = ~np.asarray(ref.escaped | ref.overflow)
    np.testing.assert_array_equal(np.asarray(got.outcome)[resolved],
                                  np.asarray(ref.outcome)[resolved])
