"""Taint (deviation-set) kernel tests (ops/taint.py).

The core contract: hybrid (taint + dense-rerun-of-escapes) outcomes are
bit-identical to the dense kernel for every structure and fault batch —
the same differential discipline the dense kernel holds against the C++
oracle (tests/test_native_diff.py), one level up."""

import jax
import numpy as np
import pytest

from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.taint import record_golden
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def make_trace(seed=1, n=256, nphys=64, mem_words=128):
    return generate(WorkloadConfig(n=n, nphys=nphys, mem_words=mem_words,
                                   working_set_words=mem_words // 2,
                                   seed=seed))


def test_record_golden_matches_scalar_oracle():
    t = make_trace(seed=21)
    gold = record_golden(
        TrialKernel(t).tr,
        jax.numpy.asarray(t.init_reg), jax.numpy.asarray(t.init_mem),
        mem_timeline=True)
    reg, mem = t.init_reg.copy(), t.init_mem.copy()
    semantics.scalar_replay(t, reg, mem)
    np.testing.assert_array_equal(np.asarray(gold.final_reg), reg)
    np.testing.assert_array_equal(np.asarray(gold.final_mem), mem)
    # reg_t[0] is the initial state; timelines are "state BEFORE step i"
    np.testing.assert_array_equal(np.asarray(gold.reg_t[0]), t.init_reg)
    np.testing.assert_array_equal(np.asarray(gold.mem_t[0]), t.init_mem)


def test_null_fault_is_masked_no_escape():
    t = make_trace(seed=22)
    k = TrialKernel(t)
    from shrewd_tpu.models.o3 import null_fault
    res = k.taint_batch(jax.tree.map(lambda x: x[None], null_fault()))
    assert int(res.outcome[0]) == C.OUTCOME_MASKED
    assert not bool(res.escaped[0]) and not bool(res.overflow[0])


@pytest.mark.parametrize("structure",
                         ["regfile", "fu", "rob", "iq", "lsq", "latch"])
def test_hybrid_equals_dense(structure):
    t = make_trace(seed=23)
    k = TrialKernel(t, O3Config(shadow_coverage=[0.4] * U.N_OPCLASSES))
    keys = prng.trial_keys(prng.campaign_key(5), 128)
    faults = k.sample_batch(keys, structure)
    dense = np.asarray(k.run_batch(faults))
    hybrid = k.run_batch_hybrid(faults)
    np.testing.assert_array_equal(hybrid, dense)


def test_overflow_escapes_and_hybrid_still_exact():
    # k=1 deviation slot: almost any propagating fault overflows; the
    # hybrid path must still match dense exactly.
    t = make_trace(seed=24)
    k = TrialKernel(t, O3Config(taint_k=1))
    keys = prng.trial_keys(prng.campaign_key(6), 64)
    faults = k.sample_batch(keys, "regfile")
    res = k.taint_batch(faults)
    assert int(np.asarray(res.overflow).sum()) > 0
    np.testing.assert_array_equal(k.run_batch_hybrid(faults),
                                  np.asarray(k.run_batch(faults)))


def test_lsq_without_mem_timeline_still_exact():
    # Disable the memory timeline: LSQ_ADDR-faulted loads escape, and the
    # dense re-run keeps the hybrid result exact.
    t = make_trace(seed=25)
    k_no = TrialKernel(t, O3Config(taint_mem_timeline_mb=0))
    k_yes = TrialKernel(t, O3Config())
    assert k_no.golden_rec.mem_t is None
    assert k_yes.golden_rec.mem_t is not None
    keys = prng.trial_keys(prng.campaign_key(7), 96)
    for k in (k_no, k_yes):
        faults = k.sample_batch(keys, "lsq")
        np.testing.assert_array_equal(k.run_batch_hybrid(faults),
                                      np.asarray(k.run_batch(faults)))
    # the timeline resolves load-address faults in-kernel → fewer escapes
    assert k_yes.escapes <= k_no.escapes


def test_run_keys_modes_agree():
    t = make_trace(seed=26)
    keys = prng.trial_keys(prng.campaign_key(8), 128)
    tallies = {}
    for mode in ("dense", "hybrid"):
        k = TrialKernel(t, O3Config(replay_kernel=mode))
        tallies[mode] = np.asarray(k.run_keys(keys, "regfile"))
    np.testing.assert_array_equal(tallies["hybrid"], tallies["dense"])
    # taint-only mode is conservative: SDC can only grow, masked only shrink
    k = TrialKernel(t, O3Config(replay_kernel="taint"))
    taint_tally = np.asarray(k.run_keys(keys, "regfile"))
    assert taint_tally.sum() == tallies["dense"].sum()
    assert taint_tally[C.OUTCOME_SDC] >= tallies["dense"][C.OUTCOME_SDC]


def test_escape_rate_is_low_for_regfile():
    t = make_trace(seed=27, n=512)
    k = TrialKernel(t)
    keys = prng.trial_keys(prng.campaign_key(9), 256)
    k.run_batch_hybrid(k.sample_batch(keys, "regfile"))
    assert k.taint_trials == 256
    assert k.escapes / k.taint_trials < 0.25


def test_graft_entry_fn_is_jittable():
    """entry()'s documented contract: (jittable_fn, example_args)."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    fn, args = g.entry()
    tally = np.asarray(jax.jit(fn)(*args))
    assert tally.sum() == args[0].shape[0]


def test_run_keys_traceable_matches_modes():
    t = make_trace(seed=29)
    k = TrialKernel(t)     # hybrid default
    keys = prng.trial_keys(prng.campaign_key(11), 64)
    traced = np.asarray(jax.jit(k.run_keys_traceable,
                                static_argnums=1)(keys, "regfile"))
    hybrid = np.asarray(k.run_keys(keys, "regfile"))
    assert traced.sum() == hybrid.sum() == 64
    # traceable path is conservative: SDC can only grow vs exact hybrid
    assert traced[C.OUTCOME_SDC] >= hybrid[C.OUTCOME_SDC]


def test_setup_scan_matches_timeline_gathers():
    # The O(nphys)-carry setup scan must reproduce the reg_t timeline
    # gathers exactly for every structure's fault coordinates.
    from shrewd_tpu.ops.taint import fault_setup, setup_scan
    t = make_trace(seed=30)
    k = TrialKernel(t)
    keys = prng.trial_keys(prng.campaign_key(12), 64)
    for structure in ("regfile", "fu", "rob", "iq", "lsq"):
        faults = k.sample_batch(keys, structure)
        want = fault_setup(k.golden_rec, k.tr, faults)
        got = setup_scan(k.tr, k.init_reg, k.init_mem, faults)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


def test_reg_timeline_budget_path_exact():
    # Over-budget register timeline (reg_t=None): taint uses setup_scan and
    # hybrid outcomes stay bit-identical to dense.
    t = make_trace(seed=31)
    k_no = TrialKernel(t, O3Config(taint_reg_timeline_mb=0))
    assert k_no.golden_rec.reg_t is None
    keys = prng.trial_keys(prng.campaign_key(13), 96)
    for structure in ("regfile", "iq", "rob"):
        faults = k_no.sample_batch(keys, structure)
        np.testing.assert_array_equal(k_no.run_batch_hybrid(faults),
                                      np.asarray(k_no.run_batch(faults)))


def test_out_of_range_regfile_entry_agrees_across_kernels():
    # Hand-constructed REGFILE fault with entry >= nphys: dense, taint, and
    # Pallas all mask the entry to the register space (ADVICE r1).
    import jax.numpy as jnp
    from shrewd_tpu.models.o3 import Fault, KIND_REGFILE
    t = make_trace(seed=32)
    k = TrialKernel(t)
    nphys = t.nphys
    faults = Fault(
        kind=jnp.full((8,), KIND_REGFILE, dtype=jnp.int32),
        cycle=jnp.arange(8, dtype=jnp.int32) * 13,
        entry=jnp.asarray([nphys, nphys + 3, 2 * nphys - 1, 5,
                           nphys + 7, 3, nphys + 1, nphys + 63],
                          dtype=jnp.int32),
        bit=jnp.arange(8, dtype=jnp.int32),
        shadow_u=jnp.ones((8,), dtype=jnp.float32))
    dense = np.asarray(k.run_batch(faults))
    hybrid = k.run_batch_hybrid(faults)
    np.testing.assert_array_equal(hybrid, dense)
    # masked entry ≡ same fault with in-range entry
    faults_masked = faults._replace(entry=faults.entry & (nphys - 1))
    np.testing.assert_array_equal(np.asarray(k.run_batch(faults_masked)),
                                  dense)


def test_shadow_detection_in_taint():
    t = make_trace(seed=28)
    k = TrialKernel(t, O3Config(shadow_coverage=[1.0] * U.N_OPCLASSES))
    keys = prng.trial_keys(prng.campaign_key(10), 64)
    faults = k.sample_batch(keys, "fu")
    res = k.taint_batch(faults)
    out = np.asarray(res.outcome)
    esc = np.asarray(res.escaped | res.overflow)
    assert (out[~esc] == C.OUTCOME_DETECTED).all()
