"""MESI protocol-state fault model (models/mesi.py).

Differential contract: the lax.scan device kernel walks the identical
protocol as the independent scalar oracle, golden and under injected
state/tag faults (the CheckerCPU pattern).  Directed scenarios pin the
protocol-accurate outcomes the reference's .sm state machine implies:
a dirty M silently demoted loses its writeback (SDC), an I flipped valid
serves a stale hit (SDC), a tag flip aliases another address.
Reference: MESI_Two_Level-L1cache.sm, CacheMemory.hh:70, DataBlock.hh:61.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shrewd_tpu.models import mesi as M
from shrewd_tpu.models.mesi import (AccessTrace, MesiConfig, MesiFault,
                                    MesiKernel, ST_I, ST_M, TGT_STATE,
                                    TGT_TAG, mesi_replay, scalar_mesi,
                                    torture_stream)
from shrewd_tpu.ops import classify as C

i32 = jnp.int32
MEM_WORDS = 64


def _cfg(**kw):
    return MesiConfig(**{**dict(n_sets=4, n_ways=2, words_per_line=2), **kw})


def _mem():
    rng = np.random.default_rng(9)
    return rng.integers(0, 1 << 32, MEM_WORDS, dtype=np.uint64).astype(
        np.uint32)


def _fault(target=TGT_STATE, core=0, mset=0, way=0, bit=0, cycle=-1):
    return MesiFault(target=i32(target), core=i32(core), mset=i32(mset),
                     way=i32(way), bit=i32(bit), cycle=i32(cycle))


def _stream(events):
    """events: (core, word, is_store, value)"""
    c, w, s, v = zip(*events)
    return AccessTrace(core=jnp.asarray(c, i32), word=jnp.asarray(w, i32),
                       is_store=jnp.asarray(s, bool),
                       value=jnp.asarray(np.asarray(v, dtype=np.uint32)))


def test_golden_kernel_matches_scalar_oracle():
    cfg = _cfg()
    mem = _mem()
    tr = torture_stream(cfg, 200, MEM_WORDS, seed=3)
    loads_s, mem_s = scalar_mesi(tr, cfg, mem)
    loads_d, mem_d = jax.jit(
        lambda: mesi_replay(tr, cfg, jnp.asarray(mem), _fault()))()
    ld = np.asarray(loads_d)[~np.asarray(tr.is_store)]
    assert np.array_equal(ld, loads_s)
    assert np.array_equal(np.asarray(mem_d), mem_s)


@pytest.mark.parametrize("target,nbits", [(TGT_STATE, 2), (TGT_TAG, 6)])
def test_faulty_kernel_matches_scalar_oracle(target, nbits):
    """Paired trials: every (site, bit, cycle) sample classifies identically
    on the device kernel and the perturbed scalar oracle."""
    cfg = _cfg()
    mem = _mem()
    tr = torture_stream(cfg, 120, MEM_WORDS, seed=5)
    rng = np.random.default_rng(11)
    mismatches = 0
    for _ in range(40):
        co = (int(rng.integers(0, 2)), int(rng.integers(0, cfg.n_sets)),
              int(rng.integers(0, cfg.n_ways)), int(rng.integers(0, nbits)),
              int(rng.integers(0, 120)))
        loads_s, mem_s = scalar_mesi(
            tr, cfg, mem, fault=(target, *co))
        loads_d, mem_d = mesi_replay(
            tr, cfg, jnp.asarray(mem),
            _fault(target, co[0], co[1], co[2], co[3], co[4]))
        ld = np.asarray(loads_d)[~np.asarray(tr.is_store)]
        if not (np.array_equal(ld, loads_s)
                and np.array_equal(np.asarray(mem_d), mem_s)):
            mismatches += 1
    assert mismatches == 0


def test_m_demoted_to_s_loses_dirty_writeback():
    # core0 stores to word 0 (line 0 → set 0): line becomes M with the only
    # up-to-date copy.  Flip state bit 1 (M=3 → S=1): the final flush skips
    # the writeback and memory keeps the stale value → SDC.
    cfg = _cfg()
    mem = _mem()
    tr = _stream([(0, 0, True, 0xDEAD0001), (0, 1, False, 0)])
    k = MesiKernel(tr, cfg, mem)
    out = jax.vmap(lambda f: k._classify(f))(
        jax.tree.map(lambda x: jnp.asarray(x)[None],
                     _fault(TGT_STATE, 0, 0, 0, 1, 1)))
    assert int(out[0]) == C.OUTCOME_SDC
    # and the failure is exactly the lost store
    _, mem_f = mesi_replay(tr, cfg, jnp.asarray(mem),
                           _fault(TGT_STATE, 0, 0, 0, 1, 1))
    assert int(np.asarray(mem_f)[0]) == int(mem[0])          # stale
    assert int(np.asarray(k.golden_mem)[0]) == 0xDEAD0001


def test_i_flipped_valid_serves_stale_hit():
    # core0 loads word 8 (set 0 under 4-set/2-word lines), line later
    # invalidated by core1's store; core0's I entry flipped back valid
    # serves the STALE value on the next load → SDC.
    cfg = _cfg()
    mem = _mem()
    tr = _stream([
        (0, 8, False, 0),              # core0 fills line (set 0)
        (1, 8, True, 0xBEEF0002),      # core1 store → invalidates core0
        (0, 8, False, 0),              # golden: coherence miss → fresh value
    ])
    k = MesiKernel(tr, cfg, mem)
    golden = np.asarray(k.golden_loads)
    assert int(golden[2]) == 0xBEEF0002
    # flip core0's entry (set 0, way 0) I→S just before the last load
    loads_f, _ = mesi_replay(tr, cfg, jnp.asarray(mem),
                             _fault(TGT_STATE, 0, 0, 0, 0, 2))
    assert int(np.asarray(loads_f)[2]) == int(mem[8])        # stale hit
    out = k._classify(_fault(TGT_STATE, 0, 0, 0, 0, 2))
    assert int(out) == C.OUTCOME_SDC


def test_tag_fault_aliases_wrong_line():
    # dirty line's tag flipped: the final writeback lands at the aliased
    # address → BOTH the home word (stale) and the aliased word (clobbered)
    cfg = _cfg()
    mem = _mem()
    # second access touches set 2 only — it exists so the cycle-1 flip has
    # a step to land on (flips apply at access boundaries)
    tr = _stream([(0, 0, True, 0x12340003), (0, 12, False, 0)])
    k = MesiKernel(tr, cfg, mem)
    _, mem_f = mesi_replay(tr, cfg, jnp.asarray(mem),
                           _fault(TGT_TAG, 0, 0, 0, 0, 1))
    mem_f = np.asarray(mem_f)
    assert mem_f[0] == mem[0]                                # stale home
    # tag 0 ^ 1 = 1 → line 1*4+0 = set 0, tag 1 → words 8..9
    assert mem_f[8] == 0x12340003                            # clobbered
    assert int(k._classify(_fault(TGT_TAG, 0, 0, 0, 0, 1))) == C.OUTCOME_SDC


def test_untouched_way_fault_is_masked():
    cfg = _cfg()
    mem = _mem()
    tr = _stream([(0, 0, False, 0), (0, 1, False, 0)])
    k = MesiKernel(tr, cfg, mem)
    # set 3 never touched: flips there change nothing program-visible...
    # but a spurious valid line could still write back garbage; state bit 0
    # on an I line makes it S (clean) → no writeback → masked
    assert int(k._classify(_fault(TGT_STATE, 1, 3, 1, 0, 1))) \
        == C.OUTCOME_MASKED


def test_protection_transforms_outcomes():
    cfg = _cfg(state_protection="parity")
    mem = _mem()
    tr = _stream([(0, 0, True, 0xDEAD0001), (0, 1, False, 0)])
    k = MesiKernel(tr, cfg, mem)
    # parity = detected-uncorrectable = DUE (the models/ruby.py mapping)
    assert int(k._classify(_fault(TGT_STATE, 0, 0, 0, 1, 1))) \
        == C.OUTCOME_DUE
    cfg2 = _cfg(state_protection="ecc")
    k2 = MesiKernel(tr, cfg2, mem)
    assert int(k2._classify(_fault(TGT_STATE, 0, 0, 0, 1, 1))) \
        == C.OUTCOME_MASKED


def test_campaign_protocol_and_sharded_run():
    """MesiKernel speaks the campaign protocol: run_keys tallies and the
    sharded campaign drives it over the 8-device mesh."""
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.utils import prng

    cfg = _cfg()
    tr = torture_stream(cfg, 64, MEM_WORDS, seed=7)
    k = MesiKernel(tr, cfg, _mem())
    keys = prng.trial_keys(prng.campaign_key(2), 32)
    t = np.asarray(k.run_keys(keys, "state"))
    assert t.sum() == 32
    camp = ShardedCampaign(k, make_mesh(), "state")
    keys8 = prng.trial_keys(prng.campaign_key(3), 64)
    t8 = np.asarray(camp.tally_batch(keys8))
    assert t8.sum() == 64
    _ = M


@pytest.mark.parametrize("n_cores", [4, 8])
def test_ncore_torture_differential(n_cores):
    """VERDICT r3 #8 acceptance: the N-core directory walk agrees with the
    scalar oracle, golden and under faults in every protocol array —
    L1 state/tag, directory entries (DirectoryMemory.hh:60 analog), and
    the in-flight TBE record (TBETable analog)."""
    cfg = _cfg(n_cores=n_cores)
    cfg.validate()
    mem = _mem()
    tr = torture_stream(cfg, 100, MEM_WORDS, seed=13, sharing=0.6)
    rng = np.random.default_rng(21)
    targets = [(TGT_STATE, 2), (TGT_TAG, 6),
               (M.TGT_DIR, cfg.dir_bits()), (M.TGT_TBE, cfg.tbe_bits())]
    mismatches = 0
    for target, nbits in targets:
        for _ in range(6):
            co = (int(rng.integers(0, n_cores)),
                  int(rng.integers(0, MEM_WORDS // cfg.words_per_line
                                   if target == M.TGT_DIR else cfg.n_sets)),
                  int(rng.integers(0, cfg.n_ways)),
                  int(rng.integers(0, nbits)),
                  int(rng.integers(0, 100)))
            loads_s, mem_s = scalar_mesi(tr, cfg, mem, fault=(target, *co))
            loads_d, mem_d = mesi_replay(
                tr, cfg, jnp.asarray(mem), _fault(target, *co))
            ld = np.asarray(loads_d)[~np.asarray(tr.is_store)]
            if not (np.array_equal(ld, loads_s)
                    and np.array_equal(np.asarray(mem_d), mem_s)):
                mismatches += 1
    assert mismatches == 0


def test_dropped_sharer_bit_serves_stale_hit():
    """Directory fault: clearing core1's sharer bit makes a later store by
    core0 skip core1's invalidation — core1 then serves a stale hit (the
    classic directory-corruption SDC)."""
    cfg = _cfg(n_cores=4)
    cfg.validate()
    mem = _mem()
    tr = _stream([
        (0, 0, False, 0),      # core0 loads line 0 (E)
        (1, 0, False, 0),      # core1 loads line 0 → both S
        # fault lands here: drop core1's sharer bit for line 0
        (0, 0, True, 77),      # core0 store → invalidates per directory
        (1, 0, False, 0),      # core1 still has S → stale value
    ])
    golden_loads, _ = scalar_mesi(tr, cfg, mem)
    assert golden_loads[-1] == 77          # fault-free run sees the store
    # dir bit map: 2 state bits, then sharer bit per core → core1 = bit 3
    f = (M.TGT_DIR, 0, 0, 0, 3, 2)
    loads_s, _ = scalar_mesi(tr, cfg, mem, fault=f)
    # the faulted run must NOT see core0's new value on core1's last load
    assert loads_s[-1] != 77
    loads_d, _ = mesi_replay(tr, cfg, jnp.asarray(mem),
                             _fault(M.TGT_DIR, 0, 0, 0, 3, 2))
    ld = np.asarray(loads_d)[~np.asarray(tr.is_store)]
    assert np.array_equal(ld, loads_s)


def test_tbe_addr_fault_misroutes_fill():
    """TBE fault: corrupting the in-flight miss's address bit fetches the
    wrong line into the wrong frame; the requester retries from L2 and
    the mis-filled frame pollutes the cache."""
    cfg = _cfg(n_cores=4)
    cfg.validate()
    mem = _mem()
    tr = _stream([(0, 0, False, 0)])
    f = (M.TGT_TBE, 0, 0, 0, 1, 0)         # flip line-address bit 1
    loads_s, _ = scalar_mesi(tr, cfg, mem, fault=f)
    # the load still returns the RIGHT data (L2 retry path)...
    assert loads_s[0] == mem[0]
    loads_d, _ = mesi_replay(tr, cfg, jnp.asarray(mem),
                             _fault(M.TGT_TBE, 0, 0, 0, 1, 0))
    assert int(np.asarray(loads_d)[0]) == mem[0]


def test_dir_and_tbe_campaign_structures_run():
    """MesiKernel exposes the new structures through the TrialKernel
    protocol so campaigns drive them unchanged."""
    from shrewd_tpu.utils import prng

    cfg = _cfg(n_cores=4)
    cfg.validate()
    tr = torture_stream(cfg, 80, MEM_WORDS, seed=2)
    k = MesiKernel(tr, cfg, _mem())
    keys = prng.trial_keys(prng.campaign_key(3), 24)
    for structure in ("dir", "tbe"):
        tally = np.asarray(k.run_keys(keys, structure))
        assert tally.sum() == 24 and (tally >= 0).all()
