import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.models.o3 import (Fault, KIND_FU, KIND_LSQ_ADDR,
                                  KIND_REGFILE, KIND_ROB_DST, O3Config,
                                  null_fault)
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.replay import TraceArrays, replay
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.format import Trace
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def mini_trace(rows, nphys=16, mem_words=64, init_reg=None, init_mem=None):
    """rows: list of (opcode, dst, src1, src2, imm, taken)."""
    arr = np.array(rows, dtype=np.int64)
    t = Trace(
        opcode=arr[:, 0].astype(np.int32),
        dst=arr[:, 1].astype(np.int32),
        src1=arr[:, 2].astype(np.int32),
        src2=arr[:, 3].astype(np.int32),
        imm=arr[:, 4].astype(np.uint32),
        taken=arr[:, 5].astype(np.int32),
        init_reg=(np.arange(nphys, dtype=np.uint32) * 3 + 1
                  if init_reg is None else init_reg),
        init_mem=(np.arange(mem_words, dtype=np.uint32) * 7 + 5
                  if init_mem is None else init_mem),
    )
    t.validate()
    return t


def fault(kind=0, cycle=0, entry=0, bit=0, shadow_u=1.0):
    return Fault(kind=jnp.int32(kind), cycle=jnp.int32(cycle),
                 entry=jnp.int32(entry), bit=jnp.int32(bit),
                 shadow_u=jnp.float32(shadow_u))


def run(trace, f, coverage=None):
    """coverage: per-µop shadow detection probability (default all-zero)."""
    tr = TraceArrays.from_trace(trace)
    if coverage is None:
        coverage = jnp.zeros(trace.n, dtype=jnp.float32)
    return replay(tr, jnp.asarray(trace.init_reg), jnp.asarray(trace.init_mem),
                  f, coverage)


# --- golden equivalence against the scalar oracle (CheckerCPU pattern) ---

def test_golden_replay_matches_scalar_oracle():
    cfg = WorkloadConfig(n=512, nphys=64, mem_words=256,
                         working_set_words=128, seed=42)
    t = generate(cfg)
    reg, mem = t.init_reg.copy(), t.init_mem.copy()
    semantics.scalar_replay(t, reg, mem)
    res = run(t, null_fault())
    np.testing.assert_array_equal(np.asarray(res.reg), reg)
    np.testing.assert_array_equal(np.asarray(res.mem), mem)
    assert not bool(res.diverged) and not bool(res.trapped) and not bool(res.detected)


# --- handcrafted fault scenarios ---

def test_null_fault_is_masked():
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),     # r2 = 8
        (U.ADDI, 1, 0, 0, 5, 0),    # r1 = r0 + 5
        (U.STORE, 0, 2, 1, 0, 0),   # mem[8>>2] = r1
    ])
    k = TrialKernel(t)
    out = k.run_batch(jax.tree.map(lambda x: x[None], null_fault()))
    assert int(out[0]) == C.OUTCOME_MASKED


def test_regfile_fault_consumed_is_sdc():
    # r1 = r0 + 5 ; store r1 → flipping r0 before the add corrupts memory
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=1, entry=0, bit=3))
    golden = run(t, null_fault())
    out = C.classify(res, golden)
    assert int(out) == C.OUTCOME_SDC
    # the store wrote a value differing in bit 3
    diff = int(np.asarray(res.mem[2])) ^ int(np.asarray(golden.mem[2]))
    assert diff == 8


def test_regfile_fault_overwritten_is_masked():
    # flip r1 BEFORE it is rewritten by the ADDI → dead value, masked
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=0, entry=1, bit=7))
    golden = run(t, null_fault())
    assert int(C.classify(res, golden)) == C.OUTCOME_MASKED


def test_regfile_fault_after_last_read_unconsumed():
    # flip a register no µop ever reads → register-state diff only
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=2, entry=9, bit=0))
    golden = run(t, null_fault())
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC       # conservative
    assert int(C.classify(res, golden, compare_regs=False)) == C.OUTCOME_MASKED


def test_fu_fault_detected_with_full_coverage():
    t = mini_trace([
        (U.ADD, 1, 2, 3, 0, 0),
        (U.ADD, 4, 1, 1, 0, 0),
    ])
    cov = jnp.ones(t.n, dtype=jnp.float32)
    res = run(t, fault(KIND_FU, cycle=0, entry=0, bit=5, shadow_u=0.5), cov)
    golden = run(t, null_fault(), cov)
    assert bool(res.detected)
    assert int(C.classify(res, golden)) == C.OUTCOME_DETECTED
    # detection freezes the trial: faulty value never committed
    np.testing.assert_array_equal(np.asarray(res.reg), np.asarray(t.init_reg))


def test_fu_fault_undetected_is_sdc():
    t = mini_trace([
        (U.ADD, 1, 2, 3, 0, 0),
    ])
    res = run(t, fault(KIND_FU, cycle=0, entry=0, bit=5, shadow_u=0.5))
    golden = run(t, null_fault())
    assert not bool(res.detected)
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC


def test_lsq_addr_highbit_fault_traps_due():
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.STORE, 0, 2, 3, 0, 0),
    ])
    res = run(t, fault(KIND_LSQ_ADDR, cycle=1, entry=1, bit=31))
    golden = run(t, null_fault())
    assert bool(res.trapped)
    assert int(C.classify(res, golden)) == C.OUTCOME_DUE


def test_branch_divergence_is_sdc():
    # r1=5, r2=5 → BEQ taken; flip r1 → not taken → divergence
    t = mini_trace([
        (U.ADDI, 1, 15, 0, 5, 0),
        (U.ADDI, 2, 15, 0, 5, 0),
        (U.BEQ, 0, 1, 2, 0, 1),
    ], init_reg=np.zeros(16, dtype=np.uint32))
    res = run(t, fault(KIND_REGFILE, cycle=2, entry=1, bit=0))
    golden = run(t, null_fault())
    assert bool(res.diverged)
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC


def test_rob_dst_fault_misdirects_writeback():
    # ADDI writes r1; ROB dst fault flips index bit 2 → writes r5 instead
    t = mini_trace([
        (U.ADDI, 1, 0, 0, 5, 0),
    ])
    res = run(t, fault(KIND_ROB_DST, cycle=0, entry=0, bit=2))
    golden = run(t, null_fault())
    g = np.asarray(golden.reg)
    r = np.asarray(res.reg)
    assert r[5] == g[1]            # value landed in the wrong register
    assert r[1] == t.init_reg[1]   # intended register went stale


# --- batched path ---

def test_trial_kernel_batch_deterministic():
    cfg = WorkloadConfig(n=256, nphys=64, mem_words=256,
                         working_set_words=128, seed=1)
    t = generate(cfg)
    k = TrialKernel(t)
    keys = jax.random.split(jax.random.key(0), 64)
    t1 = k.run_keys(keys, "regfile")
    t2 = k.run_keys(keys, "regfile")
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    assert int(t1.sum()) == 64
    # regfile faults on a random trace: some masked, typically some not
    assert int(t1[C.OUTCOME_MASKED]) > 0


@pytest.mark.parametrize("structure", ["regfile", "fu", "rob", "iq", "lsq"])
def test_all_structures_produce_valid_outcomes(structure):
    cfg = WorkloadConfig(n=128, nphys=64, mem_words=128,
                         working_set_words=64, seed=2)
    t = generate(cfg)
    k = TrialKernel(t, O3Config(shadow_coverage=[0.5] * U.N_OPCLASSES))
    keys = jax.random.split(jax.random.key(1), 32)
    tally = np.asarray(k.run_keys(keys, structure))
    assert tally.sum() == 32
    assert (tally >= 0).all()


# --- VA-space crash model (MemMap) ---

class TestMemMap:
    """The silicon DUE channel: un-fold replay addresses to virtual
    addresses and trap exactly when the host would segfault (reference
    analog: program-outcome classes, ``tests/gem5/verifier.py:158``).
    Layout under test — two clusters in a sparse VA map:

      cluster 0: VA [0x10000, 0x10100), words 0..63, inside writable
                 region A = [0x10000, 0x10200)
      cluster 1: VA [0x50000, 0x50100), words 64..127, inside READ-ONLY
                 region R = [0x50000, 0x50200)
    """

    LO0, LO1 = 0x10000, 0x50000

    def _memmap(self, uop_cluster):
        from shrewd_tpu.ops.replay import MemMap

        u32a = lambda xs: jnp.asarray(np.asarray(xs, np.uint32))  # noqa: E731
        return MemMap(
            uop_cluster=jnp.asarray(np.asarray(uop_cluster, np.int32)),
            cl_lo=u32a([self.LO0, self.LO1]),
            cl_span=u32a([0x100, 0x100]),
            cl_word_off=jnp.asarray(np.asarray([0, 64], np.int32)),
            ld_lo=u32a([self.LO0, self.LO1]),
            ld_span=u32a([0x200, 0x200]),
            st_lo=u32a([self.LO0]),
            st_span=u32a([0x200]))

    def _trace(self, store=False):
        delta0 = (0 * 4 - self.LO0) & 0xFFFFFFFF
        rows = [
            (U.LUI, 2, 0, 0, (self.LO0 + 0x10 + delta0) & 0xFFFFFFFF, 0),
            (U.LOAD, 3, 2, 0, 0, 0),         # replay addr 0x10 → word 4
        ]
        if store:
            rows.append((U.STORE, 0, 2, 3, 0, 0))
        t = mini_trace(rows, nphys=16, mem_words=128)
        return t, self._memmap([-1, 0] + ([0] if store else []))

    def _run(self, t, mm, f):
        tr = TraceArrays.from_trace(t)
        cov = jnp.zeros(t.n, dtype=jnp.float32)
        return replay(tr, jnp.asarray(t.init_reg), jnp.asarray(t.init_mem),
                      f, cov, memmap=mm)

    def test_golden_unchanged(self):
        t, mm = self._trace()
        res = self._run(t, mm, null_fault())
        assert not bool(res.trapped) and not bool(res.diverged)
        assert int(np.asarray(res.reg)[3]) == int(t.init_mem[4])

    def test_unmapped_va_traps(self):
        # flip bit 23 of the folded address: VA 0x810010 — outside every
        # mapped region → the silicon outcome is SIGSEGV → DUE
        t, mm = self._trace()
        res = self._run(t, mm, fault(kind=KIND_LSQ_ADDR, entry=1, bit=23))
        assert bool(res.trapped)

    def test_cross_cluster_load_routes_not_traps(self):
        # flip bit 18: VA 0x50010 — a *mapped* read-only page; silicon
        # reads it fine, and the replay must serve cluster 1's word 68
        t, mm = self._trace()
        res = self._run(t, mm, fault(kind=KIND_LSQ_ADDR, entry=1, bit=18))
        assert not bool(res.trapped)
        assert int(np.asarray(res.reg)[3]) == int(t.init_mem[64 + 4])

    def test_store_to_readonly_region_traps(self):
        t, mm = self._trace(store=True)
        res = self._run(t, mm, fault(kind=KIND_LSQ_ADDR, entry=2, bit=18))
        assert bool(res.trapped)

    def test_store_in_cluster_corrupts_right_word(self):
        t, mm = self._trace(store=True)
        res = self._run(t, mm, fault(kind=KIND_LSQ_ADDR, entry=2, bit=2))
        assert not bool(res.trapped)
        m = np.asarray(res.mem)
        assert m[5] == int(t.init_mem[4])      # VA 0x10014 → word 5
        assert m[4] == int(t.init_mem[4])      # original word untouched

    def test_mapped_untracked_absorbs_to_scratch_word(self):
        # flip bit 8: VA 0x10110 — inside region A but past cluster 0's
        # span; silicon touches bytes the image never compares → no trap,
        # the write absorbs at the scratch word past every cluster
        # (mem_words-1, outside all liveness masks)
        t, mm = self._trace(store=True)
        res = self._run(t, mm, fault(kind=KIND_LSQ_ADDR, entry=2, bit=8))
        assert not bool(res.trapped)
        m = np.asarray(res.mem)
        assert m[127] == int(t.init_mem[4])
        assert m[4] == int(t.init_mem[4])

    def test_legacy_uop_keeps_dense_semantics(self):
        # uop_cluster = -1 rows fall back to the dense-range validity
        t, _ = self._trace()
        mm = self._memmap([-1, -1])
        # folded replay addr 0x10 is in [0, mem_words*4) → valid
        res = self._run(t, mm, null_fault())
        assert not bool(res.trapped)
        assert int(np.asarray(res.reg)[3]) == int(t.init_mem[4])
