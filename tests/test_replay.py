import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.models.o3 import (Fault, KIND_FU, KIND_LSQ_ADDR,
                                  KIND_REGFILE, KIND_ROB_DST, O3Config,
                                  null_fault)
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.replay import TraceArrays, replay
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.format import Trace
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def mini_trace(rows, nphys=16, mem_words=64, init_reg=None, init_mem=None):
    """rows: list of (opcode, dst, src1, src2, imm, taken)."""
    arr = np.array(rows, dtype=np.int64)
    t = Trace(
        opcode=arr[:, 0].astype(np.int32),
        dst=arr[:, 1].astype(np.int32),
        src1=arr[:, 2].astype(np.int32),
        src2=arr[:, 3].astype(np.int32),
        imm=arr[:, 4].astype(np.uint32),
        taken=arr[:, 5].astype(np.int32),
        init_reg=(np.arange(nphys, dtype=np.uint32) * 3 + 1
                  if init_reg is None else init_reg),
        init_mem=(np.arange(mem_words, dtype=np.uint32) * 7 + 5
                  if init_mem is None else init_mem),
    )
    t.validate()
    return t


def fault(kind=0, cycle=0, entry=0, bit=0, shadow_u=1.0):
    return Fault(kind=jnp.int32(kind), cycle=jnp.int32(cycle),
                 entry=jnp.int32(entry), bit=jnp.int32(bit),
                 shadow_u=jnp.float32(shadow_u))


def run(trace, f, coverage=None):
    """coverage: per-µop shadow detection probability (default all-zero)."""
    tr = TraceArrays.from_trace(trace)
    if coverage is None:
        coverage = jnp.zeros(trace.n, dtype=jnp.float32)
    return replay(tr, jnp.asarray(trace.init_reg), jnp.asarray(trace.init_mem),
                  f, coverage)


# --- golden equivalence against the scalar oracle (CheckerCPU pattern) ---

def test_golden_replay_matches_scalar_oracle():
    cfg = WorkloadConfig(n=512, nphys=64, mem_words=256,
                         working_set_words=128, seed=42)
    t = generate(cfg)
    reg, mem = t.init_reg.copy(), t.init_mem.copy()
    semantics.scalar_replay(t, reg, mem)
    res = run(t, null_fault())
    np.testing.assert_array_equal(np.asarray(res.reg), reg)
    np.testing.assert_array_equal(np.asarray(res.mem), mem)
    assert not bool(res.diverged) and not bool(res.trapped) and not bool(res.detected)


# --- handcrafted fault scenarios ---

def test_null_fault_is_masked():
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),     # r2 = 8
        (U.ADDI, 1, 0, 0, 5, 0),    # r1 = r0 + 5
        (U.STORE, 0, 2, 1, 0, 0),   # mem[8>>2] = r1
    ])
    k = TrialKernel(t)
    out = k.run_batch(jax.tree.map(lambda x: x[None], null_fault()))
    assert int(out[0]) == C.OUTCOME_MASKED


def test_regfile_fault_consumed_is_sdc():
    # r1 = r0 + 5 ; store r1 → flipping r0 before the add corrupts memory
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=1, entry=0, bit=3))
    golden = run(t, null_fault())
    out = C.classify(res, golden)
    assert int(out) == C.OUTCOME_SDC
    # the store wrote a value differing in bit 3
    diff = int(np.asarray(res.mem[2])) ^ int(np.asarray(golden.mem[2]))
    assert diff == 8


def test_regfile_fault_overwritten_is_masked():
    # flip r1 BEFORE it is rewritten by the ADDI → dead value, masked
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=0, entry=1, bit=7))
    golden = run(t, null_fault())
    assert int(C.classify(res, golden)) == C.OUTCOME_MASKED


def test_regfile_fault_after_last_read_unconsumed():
    # flip a register no µop ever reads → register-state diff only
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.ADDI, 1, 0, 0, 5, 0),
        (U.STORE, 0, 2, 1, 0, 0),
    ])
    res = run(t, fault(KIND_REGFILE, cycle=2, entry=9, bit=0))
    golden = run(t, null_fault())
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC       # conservative
    assert int(C.classify(res, golden, compare_regs=False)) == C.OUTCOME_MASKED


def test_fu_fault_detected_with_full_coverage():
    t = mini_trace([
        (U.ADD, 1, 2, 3, 0, 0),
        (U.ADD, 4, 1, 1, 0, 0),
    ])
    cov = jnp.ones(t.n, dtype=jnp.float32)
    res = run(t, fault(KIND_FU, cycle=0, entry=0, bit=5, shadow_u=0.5), cov)
    golden = run(t, null_fault(), cov)
    assert bool(res.detected)
    assert int(C.classify(res, golden)) == C.OUTCOME_DETECTED
    # detection freezes the trial: faulty value never committed
    np.testing.assert_array_equal(np.asarray(res.reg), np.asarray(t.init_reg))


def test_fu_fault_undetected_is_sdc():
    t = mini_trace([
        (U.ADD, 1, 2, 3, 0, 0),
    ])
    res = run(t, fault(KIND_FU, cycle=0, entry=0, bit=5, shadow_u=0.5))
    golden = run(t, null_fault())
    assert not bool(res.detected)
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC


def test_lsq_addr_highbit_fault_traps_due():
    t = mini_trace([
        (U.LUI, 2, 0, 0, 8, 0),
        (U.STORE, 0, 2, 3, 0, 0),
    ])
    res = run(t, fault(KIND_LSQ_ADDR, cycle=1, entry=1, bit=31))
    golden = run(t, null_fault())
    assert bool(res.trapped)
    assert int(C.classify(res, golden)) == C.OUTCOME_DUE


def test_branch_divergence_is_sdc():
    # r1=5, r2=5 → BEQ taken; flip r1 → not taken → divergence
    t = mini_trace([
        (U.ADDI, 1, 15, 0, 5, 0),
        (U.ADDI, 2, 15, 0, 5, 0),
        (U.BEQ, 0, 1, 2, 0, 1),
    ], init_reg=np.zeros(16, dtype=np.uint32))
    res = run(t, fault(KIND_REGFILE, cycle=2, entry=1, bit=0))
    golden = run(t, null_fault())
    assert bool(res.diverged)
    assert int(C.classify(res, golden)) == C.OUTCOME_SDC


def test_rob_dst_fault_misdirects_writeback():
    # ADDI writes r1; ROB dst fault flips index bit 2 → writes r5 instead
    t = mini_trace([
        (U.ADDI, 1, 0, 0, 5, 0),
    ])
    res = run(t, fault(KIND_ROB_DST, cycle=0, entry=0, bit=2))
    golden = run(t, null_fault())
    g = np.asarray(golden.reg)
    r = np.asarray(res.reg)
    assert r[5] == g[1]            # value landed in the wrong register
    assert r[1] == t.init_reg[1]   # intended register went stale


# --- batched path ---

def test_trial_kernel_batch_deterministic():
    cfg = WorkloadConfig(n=256, nphys=64, mem_words=256,
                         working_set_words=128, seed=1)
    t = generate(cfg)
    k = TrialKernel(t)
    keys = jax.random.split(jax.random.key(0), 64)
    t1 = k.run_keys(keys, "regfile")
    t2 = k.run_keys(keys, "regfile")
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    assert int(t1.sum()) == 64
    # regfile faults on a random trace: some masked, typically some not
    assert int(t1[C.OUTCOME_MASKED]) > 0


@pytest.mark.parametrize("structure", ["regfile", "fu", "rob", "iq", "lsq"])
def test_all_structures_produce_valid_outcomes(structure):
    cfg = WorkloadConfig(n=128, nphys=64, mem_words=128,
                         working_set_words=64, seed=2)
    t = generate(cfg)
    k = TrialKernel(t, O3Config(shadow_coverage=[0.5] * U.N_OPCLASSES))
    keys = jax.random.split(jax.random.key(1), 32)
    tally = np.asarray(k.run_keys(keys, structure))
    assert tally.sum() == 32
    assert (tally >= 0).all()
