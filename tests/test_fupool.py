"""Shadow-FU pool model tests (models/fupool.py).

Validates the structural availability model against hand-computable
allocations, the reference's priorityToShadow semantics, and the end-to-end
effect on trial classification (detected vs SDC)."""

import numpy as np
import pytest

from shrewd_tpu.isa import uops as U
from shrewd_tpu.models.fupool import (FUPoolConfig, FUPoolModel, GRANT_APPROX,
                                      GRANT_EXACT, GRANT_NONE, IntALU,
                                      IntMultDiv, RdWrPort)
from shrewd_tpu.models.o3 import O3Config, compute_shadow_cov
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def oc_seq(*classes):
    return np.array(classes, dtype=np.int32)


def test_underutilized_cycle_grants_all_shadows():
    # 2 ALU µops in one 8-wide cycle against 6 IntALU units:
    # 2 primaries + 2 shadows = 4 ≤ 6 → both granted exact.
    m = FUPoolModel(oc_seq(U.OC_INT_ALU, U.OC_INT_ALU), issue_width=8)
    assert list(m.grants) == [GRANT_EXACT, GRANT_EXACT]
    assert m.shadow_denied.sum() == 0
    np.testing.assert_array_equal(m.coverage(), [1.0, 1.0])


def test_saturated_cycle_denies_late_shadows():
    # 4 ALU µops, one cycle, 6 units: 4 primaries + shadows for the first 2
    # exhaust the pool; shadows 3 and 4 are denied (NoShadowFU).
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=8)
    assert list(m.grants) == [GRANT_EXACT, GRANT_EXACT, GRANT_NONE, GRANT_NONE]
    assert m.shadow_denied[U.OC_INT_ALU] == 2
    assert m.fu_busy.sum() == 0


def test_issue_width_splits_cycles():
    # Same 4 µops but width 2 → two cycles of 2, each underutilized.
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=2)
    assert list(m.grants) == [GRANT_EXACT] * 4


def test_mult_shadow_falls_back_to_approx_alu():
    # 2 MUL µops, 2 IntMultDiv units: both primaries consume the mult units;
    # shadows find no exact unit and fall back to approximate ALU checking.
    m = FUPoolModel(oc_seq(U.OC_INT_MULT, U.OC_INT_MULT), issue_width=8)
    assert list(m.grants) == [GRANT_APPROX, GRANT_APPROX]
    assert m.shadow_granted_approx[U.OC_INT_MULT] == 2
    cov = FUPoolConfig(approx_coverage=0.75)
    m2 = FUPoolModel(oc_seq(U.OC_INT_MULT, U.OC_INT_MULT), issue_width=8,
                     pool=cov)
    np.testing.assert_allclose(m2.coverage(), [0.75, 0.75])


def test_priority_to_shadow_starves_later_primaries_of_shadows():
    # 3 ALU µops, pool shrunk to 4 ALU units.
    # deferred (priorityToShadow=False): primaries take 3, one shadow unit
    #   left → only µop 0's shadow granted.
    # interleaved (True): µop0 primary+shadow (2), µop1 primary+shadow (2),
    #   µop2 primary finds pool empty (fu_busy) and shadow denied.
    pool = FUPoolConfig(int_alu=IntALU(count=4))
    oc = oc_seq(*[U.OC_INT_ALU] * 3)
    m_def = FUPoolModel(oc, issue_width=8, pool=pool, priority_to_shadow=False)
    assert list(m_def.grants) == [GRANT_EXACT, GRANT_NONE, GRANT_NONE]
    assert m_def.fu_busy.sum() == 0
    m_pri = FUPoolModel(oc, issue_width=8, pool=pool, priority_to_shadow=True)
    assert list(m_pri.grants) == [GRANT_EXACT, GRANT_EXACT, GRANT_NONE]
    assert m_pri.fu_busy[U.OC_INT_ALU] == 1


def test_op_lat_keeps_units_busy_across_cycles():
    # One MUL per cycle (issue_width=1) against 2 IntMultDiv units with
    # op_lat=3: cycle 0 claims unit A (busy through cycle 2), its shadow
    # claims unit B — so cycles 1 and 2 have no mult unit free: the primary
    # fails (fu_busy) and, per the reference's issue-stage guard
    # (requestShadow only fires for a successfully issued primary,
    # inst_queue.cc:1082+), NO shadow is requested for those µops.
    # Cycle 3 sees both units free again.
    m = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 4), issue_width=1)
    assert list(m.grants) == [GRANT_EXACT, GRANT_NONE, GRANT_NONE,
                              GRANT_EXACT]
    assert m.fu_busy[U.OC_INT_MULT] == 2
    assert m.shadow_requests[U.OC_INT_MULT] == 2   # µops 0 and 3 only
    # with op_lat=1 units, every cycle is fresh
    pool = FUPoolConfig(int_mult=IntMultDiv(op_lat=1))
    m1 = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 4), issue_width=1, pool=pool)
    assert list(m1.grants) == [GRANT_EXACT] * 4


def test_mem_and_nop_not_shadow_eligible():
    m = FUPoolModel(oc_seq(U.OC_MEM_READ, U.OC_MEM_WRITE, U.OC_NONE),
                    issue_width=8)
    assert list(m.grants) == [GRANT_NONE] * 3
    assert m.shadow_requests.sum() == 0


def test_stats_group_rows():
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=8)
    g = m.stats_group()
    d = g.to_dict()
    assert d["shadow_requests"]["IntAlu"] == 4
    assert d["shadow_granted"]["IntAlu"] == 2
    assert d["shadow_denied"]["IntAlu"] == 2


def test_compute_shadow_cov_paths():
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=3))
    oc = U.opclass_of(t.opcode)
    # coverage model: straight per-OpClass gather
    cfg = O3Config(shadow_coverage=[0.3, 0.5, 0.0, 0.0, 0.0, 0.0,
                                    0.0])
    cov, m = compute_shadow_cov(oc, cfg)
    assert m is None
    np.testing.assert_allclose(
        cov, np.array([0.3, 0.5, 0.0, 0.0, 0.0], np.float32)[oc])
    # disabled: all zero regardless of model
    cov0, _ = compute_shadow_cov(oc, O3Config(
        enable_shrewd=False, shadow_coverage=[1.0] * U.N_OPCLASSES))
    assert not cov0.any()
    # structural model: binary coverage (approx_coverage=1 default)
    covf, mf = compute_shadow_cov(oc, O3Config(shadow_model="fupool"))
    assert mf is not None
    assert set(np.unique(covf)) <= {0.0, 1.0}
    # shadows only ever granted to eligible classes
    assert not covf[(oc != U.OC_INT_ALU) & (oc != U.OC_INT_MULT)].any()


def test_trial_kernel_fupool_end_to_end():
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=4))
    import jax
    k = TrialKernel(t, O3Config(shadow_model="fupool"))
    assert k.fu_model is not None
    keys = jax.random.split(jax.random.key(7), 64)
    tally = np.asarray(k.run_keys(keys, "fu"))
    assert tally.sum() == 64
    # an 8-wide window of mostly-ALU code leaves shadow units free most
    # cycles → FU faults are frequently detected
    from shrewd_tpu.ops import classify as C
    assert tally[C.OUTCOME_DETECTED] > 0


def test_with_shrewd_toggle():
    t = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                working_set_words=32, seed=5))
    import jax
    k_on = TrialKernel(t, O3Config(shadow_model="fupool"))
    k_off = k_on.with_shrewd(enable=False)
    assert not np.asarray(k_off.shadow_cov).any()
    keys = jax.random.split(jax.random.key(8), 48)
    from shrewd_tpu.ops import classify as C
    t_on = np.asarray(k_on.run_keys(keys, "fu"))
    t_off = np.asarray(k_off.run_keys(keys, "fu"))
    assert t_off[C.OUTCOME_DETECTED] == 0
    assert t_on[C.OUTCOME_DETECTED] >= t_off[C.OUTCOME_DETECTED]
    # detection converts would-be SDC/masked outcomes, never creates trials
    assert t_on.sum() == t_off.sum() == 48
