"""Shadow-FU pool model tests (models/fupool.py).

Validates the structural availability model against hand-computable
allocations, the reference's priorityToShadow semantics, and the end-to-end
effect on trial classification (detected vs SDC)."""

import numpy as np
import pytest

from shrewd_tpu.isa import uops as U
from shrewd_tpu.models.fupool import (FUPoolConfig, FUPoolModel, GRANT_APPROX,
                                      GRANT_EXACT, GRANT_NONE, IntALU,
                                      IntMultDiv, RdWrPort)
from shrewd_tpu.models.o3 import O3Config, compute_shadow_cov
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def oc_seq(*classes):
    return np.array(classes, dtype=np.int32)


def test_underutilized_cycle_grants_all_shadows():
    # 2 ALU µops in one 8-wide cycle against 6 IntALU units:
    # 2 primaries + 2 shadows = 4 ≤ 6 → both granted exact.
    m = FUPoolModel(oc_seq(U.OC_INT_ALU, U.OC_INT_ALU), issue_width=8)
    assert list(m.grants) == [GRANT_EXACT, GRANT_EXACT]
    assert m.shadow_denied.sum() == 0
    np.testing.assert_array_equal(m.coverage(), [1.0, 1.0])


def test_saturated_cycle_spills_shadows_to_fp_alu():
    # 4 ALU µops, one cycle, 6 IntALU units: 4 primaries + shadows for the
    # first 2 exhaust the integer pool; shadows 3 and 4 fall back to the
    # FP_ALU units — the reference's IntAlu → FloatAdd/FloatCmp approx
    # fallback (fu_pool.cc:193-209).
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=8)
    assert list(m.grants) == [GRANT_EXACT, GRANT_EXACT,
                              GRANT_APPROX, GRANT_APPROX]
    assert m.shadow_granted_approx[U.OC_INT_ALU] == 2
    assert m.shadow_denied.sum() == 0
    assert m.fu_busy.sum() == 0


def test_full_pool_denies_shadows():
    # 7 ALU µops, one cycle: 6 primaries on IntALU; the 7th finds no unit
    # and RETRIES — it slips to cycle 1 (fu_busy counts the wait,
    # inst_queue.cc:1020-1024) where it issues with an exact shadow.  The
    # 6 cycle-0 shadows contend for the 4 FP_ALU approx units → 2 denied.
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 7), issue_width=8)
    assert m.fu_busy[U.OC_INT_ALU] == 1
    assert m.slip[6] == 1
    assert m.shadow_requests[U.OC_INT_ALU] == 7
    assert m.shadow_granted[U.OC_INT_ALU] == 1       # the slipped µop
    assert m.shadow_granted_approx[U.OC_INT_ALU] == 4
    assert m.shadow_denied[U.OC_INT_ALU] == 2
    av = m.availability()["IntAlu"]
    assert av["requests"] == 7 and av["available"] == 5
    # without retry the over-subscribed µop abandons (pre-r5 behavior)
    m0 = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 7), issue_width=8,
                     retry_primary=False)
    assert m0.shadow_requests[U.OC_INT_ALU] == 6
    assert list(m0.grants[6:]) == [GRANT_NONE]


def test_issue_width_splits_cycles():
    # Same 4 µops but width 2 → two cycles of 2, each underutilized.
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=2)
    assert list(m.grants) == [GRANT_EXACT] * 4


def test_mult_shadow_falls_back_to_fp_multdiv():
    # 2 MUL µops, 2 IntMultDiv units: both primaries consume the mult units;
    # shadows find no exact unit and fall back to the FP_MultDiv units —
    # the reference's IntMult → FloatMult approx fallback
    # (fu_pool.cc:210-219).
    m = FUPoolModel(oc_seq(U.OC_INT_MULT, U.OC_INT_MULT), issue_width=8)
    assert list(m.grants) == [GRANT_APPROX, GRANT_APPROX]
    assert m.shadow_granted_approx[U.OC_INT_MULT] == 2
    cov = FUPoolConfig(approx_coverage=0.75)
    m2 = FUPoolModel(oc_seq(U.OC_INT_MULT, U.OC_INT_MULT), issue_width=8,
                     pool=cov)
    np.testing.assert_allclose(m2.coverage(), [0.75, 0.75])


def test_fp_shadow_falls_back_to_int_alu():
    # 4 FADD µops, 4 FP_ALU units: primaries take all four; shadows fall
    # back to the IntALU units — the reference's FloatAdd → IntAlu approx
    # fallback (fu_pool.cc:233-241).
    m = FUPoolModel(oc_seq(*[U.OC_FP_ALU] * 4), issue_width=8)
    assert list(m.grants) == [GRANT_APPROX] * 4
    assert m.shadow_granted_approx[U.OC_FP_ALU] == 4


def test_priority_to_shadow_starves_later_primaries():
    # 3 ALU µops, pool shrunk to 4 ALU units and no FP fallback.
    # deferred (priorityToShadow=False): primaries take 3, one shadow unit
    #   left → only µop 0's shadow granted.
    # interleaved (True): µop0 primary+shadow (2), µop1 primary+shadow (2),
    #   µop2 primary finds the pool empty and retries into cycle 1, where
    #   it issues with an exact shadow (retry_primary default).
    from shrewd_tpu.models.fupool import FP_ALU
    pool = FUPoolConfig(int_alu=IntALU(count=4),
                        fp_alu=FP_ALU(approx_capabilities=[]))
    oc = oc_seq(*[U.OC_INT_ALU] * 3)
    m_def = FUPoolModel(oc, issue_width=8, pool=pool, priority_to_shadow=False)
    assert list(m_def.grants) == [GRANT_EXACT, GRANT_NONE, GRANT_NONE]
    assert m_def.fu_busy.sum() == 0
    m_pri = FUPoolModel(oc, issue_width=8, pool=pool, priority_to_shadow=True)
    assert list(m_pri.grants) == [GRANT_EXACT, GRANT_EXACT, GRANT_EXACT]
    assert m_pri.fu_busy[U.OC_INT_ALU] == 1 and m_pri.slip[2] == 1
    # without retry: the starved µop proceeds unshadowed
    m_nr = FUPoolModel(oc, issue_width=8, pool=pool, priority_to_shadow=True,
                       retry_primary=False)
    assert list(m_nr.grants) == [GRANT_EXACT, GRANT_EXACT, GRANT_NONE]


def test_pipelined_units_free_next_cycle():
    # One MUL per cycle (issue_width=1) against 2 IntMultDiv units: MUL is
    # pipelined (reference OpDesc opLat=3 pipelined, FuncUnitConfig.py:52),
    # so a claimed unit is free again the next cycle
    # (FUPool::freeUnitNextCycle) — every µop gets primary + exact shadow.
    m = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 4), issue_width=1)
    assert list(m.grants) == [GRANT_EXACT] * 4
    assert m.fu_busy.sum() == 0


def test_busy_cycles_models_nonpipelined_divides():
    # Stream of 20-cycle non-pipelined divides (reference IntDiv OpDesc,
    # FuncUnitConfig.py:53), no retry: cycle 0 claims both IntMultDiv
    # units (primary + exact shadow, each busy 20 cycles); cycles 1-3 find
    # no unit → primary fails (fu_busy) and, per the issue guard
    # (inst_queue.cc:1082+), no shadow is requested.  The FP_MultDiv
    # fallback can't help the *primary* (primaries never approximate).
    busy = np.full(4, 20, np.int64)
    m = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 4), issue_width=1,
                    busy_cycles=busy, retry_primary=False)
    assert list(m.grants) == [GRANT_EXACT, GRANT_NONE, GRANT_NONE,
                              GRANT_NONE]
    assert m.fu_busy[U.OC_INT_MULT] == 3
    assert m.shadow_requests[U.OC_INT_MULT] == 1


def test_retry_slips_divides_and_approx_busy_holds():
    # With the IQ retry loop (default) and the width-1 issue bound, the
    # retried divides serialize: div1 matures at cycle 20 and issues
    # alone (its exact sibling unit frees the same cycle), div2 is
    # width-bumped to 21, re-slips to 40, and issues exact there too.
    # (At issue_width 8 the two would issue together and their deferred
    # shadows would spill to the FP dividers — the gem5 divmix pattern.)
    busy = np.full(3, 20, np.int64)
    m = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 3), issue_width=1,
                    busy_cycles=busy)
    assert list(m.grants) == [GRANT_EXACT, GRANT_EXACT, GRANT_EXACT]
    # div2: 18 cycles to the first maturity + 19 after the width bump
    # (the bump itself is not FU-busy wait — no unit was asked)
    assert m.slip[0] == 0 and m.slip[1] == 19 and m.slip[2] == 37
    # width-8: both retries issue at 20; exact pool exhausted -> approx
    m8 = FUPoolModel(oc_seq(*[U.OC_INT_MULT] * 3),
                     issue_cycle=np.array([0, 1, 2], np.int64),
                     busy_cycles=busy)
    assert list(m8.grants) == [GRANT_EXACT, GRANT_APPROX, GRANT_APPROX]
    # approx_busy: force the fallback by removing the second exact unit
    pool = FUPoolConfig(int_mult=IntMultDiv(count=1))
    ab = np.full(2, 12, np.int64)
    m2 = FUPoolModel(oc_seq(U.OC_INT_MULT, U.OC_INT_MULT), issue_width=8,
                     pool=pool, busy_cycles=np.full(2, 20, np.int64),
                     approx_busy_cycles=ab)
    # µop0: primary takes the only IntMultDiv unit; shadow falls back to
    # FP_MultDiv unit 0 holding it 12 cycles.  µop1: primary retries to
    # cycle 20; shadow exact unavailable (same unit) → falls back to the
    # second FP unit (unit 0 busy until 12 < 20 → actually free) — both
    # approx grants; the 12-cycle hold is observable in unit state.
    assert list(m2.grants) == [GRANT_APPROX, GRANT_APPROX]


def test_phantom_contention_degrades_real_availability():
    # 2 real ALU µops in cycle 0 + 8 phantoms (wrong-path mass) in the
    # same cycle: phantoms claim 4 of the 6 IntALU units and on the
    # shadow pass soak the FP_ALU fallbacks — real shadows spill or deny.
    oc = oc_seq(U.OC_INT_ALU, U.OC_INT_ALU)
    ph = np.full(8, U.OC_INT_ALU, np.int32)
    phc = np.zeros(8, np.int64)
    m = FUPoolModel(oc, issue_width=8, issue_cycle=np.zeros(2, np.int64),
                    phantom_opclass=ph, phantom_cycle=phc)
    assert m.phantom_requests[U.OC_INT_ALU] > 0
    # phantoms contend: not every real shadow can be exact any more
    assert m.shadow_granted[U.OC_INT_ALU] < 2
    # without phantoms both real shadows are exact
    m0 = FUPoolModel(oc, issue_width=8, issue_cycle=np.zeros(2, np.int64))
    assert m0.shadow_granted[U.OC_INT_ALU] == 2
    # availability() folds phantoms only when asked
    av_real = m.availability()["IntAlu"]["requests"]
    av_all = m.availability(include_phantoms=True)["IntAlu"]["requests"]
    assert av_all > av_real == 2


def test_issue_cycle_schedule_drives_contention():
    # Eight ALU µops that a dense i//8 proxy would cram into one cycle
    # (saturating the pool) issue two-per-cycle under a scoreboard-style
    # schedule — pool never saturates, every shadow exact.
    oc = oc_seq(*[U.OC_INT_ALU] * 8)
    sched = np.repeat(np.arange(4, dtype=np.int64), 2)
    m = FUPoolModel(oc, issue_width=8, issue_cycle=sched)
    assert list(m.grants) == [GRANT_EXACT] * 8
    dense = FUPoolModel(oc, issue_width=8)
    assert (np.asarray(dense.grants) == GRANT_EXACT).sum() < 8


def test_mem_and_nop_not_shadow_eligible():
    m = FUPoolModel(oc_seq(U.OC_MEM_READ, U.OC_MEM_WRITE, U.OC_NONE),
                    issue_width=8)
    assert list(m.grants) == [GRANT_NONE] * 3
    assert m.shadow_requests.sum() == 0


def test_stats_group_rows():
    m = FUPoolModel(oc_seq(*[U.OC_INT_ALU] * 4), issue_width=8)
    g = m.stats_group()
    d = g.to_dict()
    assert d["shadow_requests"]["IntAlu"] == 4
    assert d["shadow_granted"]["IntAlu"] == 2
    assert d["shadow_granted_approx"]["IntAlu"] == 2
    assert d["shadow_denied"]["IntAlu"] == 0


def test_compute_shadow_cov_paths():
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=3))
    oc = U.opclass_of(t.opcode)
    # coverage model: straight per-OpClass gather
    cfg = O3Config(shadow_coverage=[0.3, 0.5, 0.0, 0.0, 0.0, 0.0,
                                    0.0])
    cov, m = compute_shadow_cov(oc, cfg)
    assert m is None
    np.testing.assert_allclose(
        cov, np.array([0.3, 0.5, 0.0, 0.0, 0.0], np.float32)[oc])
    # disabled: all zero regardless of model
    cov0, _ = compute_shadow_cov(oc, O3Config(
        enable_shrewd=False, shadow_coverage=[1.0] * U.N_OPCLASSES))
    assert not cov0.any()
    # structural model: binary coverage (approx_coverage=1 default)
    covf, mf = compute_shadow_cov(oc, O3Config(shadow_model="fupool"))
    assert mf is not None
    assert set(np.unique(covf)) <= {0.0, 1.0}
    # shadows only ever granted to eligible classes
    assert not covf[(oc != U.OC_INT_ALU) & (oc != U.OC_INT_MULT)].any()


def test_trial_kernel_fupool_end_to_end():
    t = generate(WorkloadConfig(n=128, nphys=32, mem_words=64,
                                working_set_words=32, seed=4))
    import jax
    k = TrialKernel(t, O3Config(shadow_model="fupool"))
    assert k.fu_model is not None
    keys = jax.random.split(jax.random.key(7), 64)
    tally = np.asarray(k.run_keys(keys, "fu"))
    assert tally.sum() == 64
    # an 8-wide window of mostly-ALU code leaves shadow units free most
    # cycles → FU faults are frequently detected
    from shrewd_tpu.ops import classify as C
    assert tally[C.OUTCOME_DETECTED] > 0


def test_with_shrewd_toggle():
    t = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                working_set_words=32, seed=5))
    import jax
    k_on = TrialKernel(t, O3Config(shadow_model="fupool"))
    k_off = k_on.with_shrewd(enable=False)
    assert not np.asarray(k_off.shadow_cov).any()
    keys = jax.random.split(jax.random.key(8), 48)
    from shrewd_tpu.ops import classify as C
    t_on = np.asarray(k_on.run_keys(keys, "fu"))
    t_off = np.asarray(k_off.run_keys(keys, "fu"))
    assert t_off[C.OUTCOME_DETECTED] == 0
    assert t_on[C.OUTCOME_DETECTED] >= t_off[C.OUTCOME_DETECTED]
    # detection converts would-be SDC/masked outcomes, never creates trials
    assert t_on.sum() == t_off.sum() == 48
