"""Ruby cache SRAM SEU model: directed lifetime scenarios + campaign wiring."""

import jax.numpy as jnp
import numpy as np
import pytest

from shrewd_tpu.models.ruby import (AccessStream, CacheConfig, CacheFault,
                                    CacheHierarchy, CacheKernel,
                                    EV_CONSUME, EV_INVALIDATE, EV_OVERWRITE,
                                    golden_access_stream, simulate_cache)
from shrewd_tpu.ops import classify as C
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def stream(entries):
    """entries: list of (cycle, word, is_store)."""
    c, w, s = zip(*entries)
    return AccessStream(cycle=np.asarray(c, np.int32),
                        word=np.asarray(w, np.int32),
                        is_store=np.asarray(s, bool),
                        width=np.ones(len(entries), np.int32))


TINY = dict(n_sets=2, n_ways=1, words_per_line=2)


@pytest.fixture(scope="module")
def tiny_kernel():
    # line0 = words {0,1} → set0; line2 = words {4,5} → set0 (conflict)
    tl, miss = simulate_cache(stream([
        (0, 0, False),    # miss → fill line0, read word0
        (5, 1, False),    # hit, read word1
        (10, 0, True),    # hit, store word0 → dirty
        (20, 4, False),   # conflict miss → dirty evict line0, fill line2
    ]), CacheConfig(**TINY), n_cycles=32)
    return CacheKernel(tl, CacheConfig(**TINY)), miss


def classify_data(kernel, slot, word, cycle):
    f = CacheFault(slot=jnp.int32(slot), word=jnp.int32(word),
                   bit=jnp.int32(0), cycle=jnp.int32(cycle))
    return int(kernel._classify_data(f))


def classify_meta(kernel, slot, cycle):
    f = CacheFault(slot=jnp.int32(slot), word=jnp.int32(0),
                   bit=jnp.int32(0), cycle=jnp.int32(cycle))
    return int(kernel._classify_line_meta(f))


def test_data_fault_lifetimes(tiny_kernel):
    k, _ = tiny_kernel
    # fault at fill cycle: overwritten by the fill itself → masked
    assert classify_data(k, 0, 0, 0) == C.OUTCOME_MASKED
    # word0 after its read, next event is the store overwrite → masked
    assert classify_data(k, 0, 0, 1) == C.OUTCOME_MASKED
    # word1 before its read at cycle 5 → consumed → SDC
    assert classify_data(k, 0, 1, 1) == C.OUTCOME_SDC
    # word0 after the store, next event is the dirty writeback → SDC
    assert classify_data(k, 0, 0, 11) == C.OUTCOME_SDC
    # after the conflict fill, line2 clean, no further events → masked
    assert classify_data(k, 0, 0, 21) == C.OUTCOME_MASKED
    # set1 slot never touched → masked
    assert classify_data(k, 1, 0, 3) == C.OUTCOME_MASKED


def test_meta_fault_dirty_window(tiny_kernel):
    k, _ = tiny_kernel
    # clean between fill and store → masked
    assert classify_meta(k, 0, 5) == C.OUTCOME_MASKED
    # dirty between store@10 and evict@20 → SDC
    assert classify_meta(k, 0, 11) == C.OUTCOME_SDC
    # after evict+refill, clean again → masked
    assert classify_meta(k, 0, 21) == C.OUTCOME_MASKED
    # invalid way → masked
    assert classify_meta(k, 1, 11) == C.OUTCOME_MASKED


def test_miss_stream_carries_writeback(tiny_kernel):
    _, miss = tiny_kernel
    # fills for line0 and line2 (reads) + one dirty writeback of line0 (store)
    assert len(miss.cycle) == 3
    wb = miss.is_store
    assert wb.sum() == 1
    assert miss.word[wb][0] == 0            # line0 base word
    assert (miss.width == 2).all()          # transfers carry source wpl


def test_end_of_window_dirty_residue():
    tl, _ = simulate_cache(stream([
        (0, 0, True),                       # fill + store → dirty forever
    ]), CacheConfig(**TINY), n_cycles=8)
    k = CacheKernel(tl, CacheConfig(**TINY))
    assert classify_data(k, 0, 0, 4) == C.OUTCOME_SDC
    assert classify_meta(k, 0, 4) == C.OUTCOME_SDC


def test_protection_transforms():
    st = [(0, 0, True)]
    for prot, expect in [("parity", C.OUTCOME_DUE), ("ecc", C.OUTCOME_MASKED)]:
        cfg = CacheConfig(data_protection=prot, tag_protection=prot, **TINY)
        tl, _ = simulate_cache(stream(st), cfg, n_cycles=8)
        k = CacheKernel(tl, cfg)
        assert classify_data(k, 0, 0, 4) == expect
        assert classify_meta(k, 0, 4) == expect


def test_config_validation():
    with pytest.raises(ValueError, match="power of two"):
        CacheConfig(n_sets=3).validate()
    with pytest.raises(ValueError, match="protection"):
        CacheConfig(data_protection="raid5").validate()


def test_hierarchy_end_to_end_and_campaign_protocol():
    t = generate(WorkloadConfig(n=1024, nphys=64, mem_words=512,
                                working_set_words=256, seed=8))
    hier = CacheHierarchy.build(
        t, CacheConfig(n_sets=8, n_ways=2, words_per_line=4),
        CacheConfig(n_sets=32, n_ways=4, words_per_line=4))
    assert hier.l2.wkey.shape[0] > 0        # L1 misses reached L2
    keys = prng.trial_keys(prng.campaign_key(21), 1024)
    for name, k in hier.kernels().items():
        for structure in ("data", "tag", "state"):
            tally = np.asarray(k.run_keys(keys, structure))
            assert tally.sum() == 1024, (name, structure)
    # a live working set must show nonzero L1 data AVF
    tally = np.asarray(hier.l1.run_keys(keys, "data"))
    assert tally[C.OUTCOME_SDC] > 0
    # determinism
    np.testing.assert_array_equal(
        np.asarray(hier.l1.run_keys(keys, "data")), tally)


def test_sharded_campaign_over_cache_kernel():
    import jax
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh

    t = generate(WorkloadConfig(n=512, nphys=64, mem_words=256,
                                working_set_words=128, seed=9))
    hier = CacheHierarchy.build(
        t, CacheConfig(n_sets=8, n_ways=2, words_per_line=4))
    mesh = make_mesh(jax.devices())
    camp = ShardedCampaign(hier.l1, mesh, "data")
    keys = prng.trial_keys(prng.campaign_key(22), 64 * len(jax.devices()))
    tally = np.asarray(camp.tally_batch(keys))
    assert tally.sum() == keys.shape[0]


def test_empty_timeline_classifies_masked():
    # a trace with no memory traffic → empty timelines → everything masked
    cfg = CacheConfig(**TINY)
    empty = AccessStream(
        cycle=np.zeros(0, np.int32), word=np.zeros(0, np.int32),
        is_store=np.zeros(0, bool), width=np.zeros(0, np.int32))
    tl, miss = simulate_cache(empty, cfg, n_cycles=8)
    k = CacheKernel(tl, cfg)
    assert classify_data(k, 0, 0, 2) == C.OUTCOME_MASKED
    assert classify_meta(k, 1, 2) == C.OUTCOME_MASKED
    keys = prng.trial_keys(prng.campaign_key(30), 64)
    assert np.asarray(k.run_keys(keys, "data")).sum() == 64


def test_mismatched_line_sizes_expand_by_transfer_width():
    # L1 line = 2 words, L2 line = 4 words: an L1 writeback of words {0,1}
    # must overwrite only half of the L2 line — a fault in the untouched
    # half stays live and is consumed by the next writeback's... eviction
    l1 = CacheConfig(n_sets=2, n_ways=1, words_per_line=2)
    l2 = CacheConfig(n_sets=2, n_ways=1, words_per_line=4)
    # L1: store word0 (dirty line0); conflict with line2 (words 4,5 → set0)
    # evicts line0 → writeback {0,1} to L2 at cycle 10
    tl1, miss = simulate_cache(stream([
        (0, 0, True),
        (10, 4, False),
    ]), l1, n_cycles=32)
    tl2, _ = simulate_cache(miss, l2, n_cycles=32)
    k2 = CacheKernel(tl2, l2)
    # L2 slot0 holds line0 (words 0-3) after the writeback; words 0,1 were
    # overwritten by the writeback, words 2,3 only by the initial fill.
    # Writeback makes the L2 line dirty → end-of-window residue is SDC for
    # any word, but BEFORE the writeback (cycle 11 vs 9):
    assert classify_data(k2, 0, 0, 11) == C.OUTCOME_SDC   # dirty residue
    # fault in word0 just before the writeback overwrite → masked would be
    # wrong only if nothing overwrote it; the writeback at 10 overwrites
    assert classify_data(k2, 0, 0, 9) == C.OUTCOME_MASKED
    # fault in word2 (untouched by the 2-word writeback) at cycle 9 is NOT
    # overwritten — line ends dirty → SDC (the old line_wide model would
    # have wrongly masked it)
    assert classify_data(k2, 0, 2, 9) == C.OUTCOME_SDC


def test_golden_access_stream_matches_trace():
    t = generate(WorkloadConfig(n=256, nphys=64, mem_words=128,
                                working_set_words=64, seed=10))
    s = golden_access_stream(t)
    from shrewd_tpu.isa import uops as U
    n_mem = int(U.is_mem(t.opcode).sum())
    assert len(s.cycle) == n_mem
    assert (np.diff(s.cycle) > 0).all()     # one access per µop, ordered
    assert (s.word < t.mem_words).all()
