"""NoC fault model: probability calculator + message-level injection
(models/noc.py; reference garnet FaultModel.hh:59-126)."""

import numpy as np
import pytest

from shrewd_tpu.models import noc as N
from shrewd_tpu.models.mesi import MesiConfig, torture_stream
from shrewd_tpu.ops import classify as C
from shrewd_tpu.utils import prng


def _model(**kw):
    cfg = N.NocConfig(**kw)
    return cfg, N.FaultModel.for_mesh(cfg)


class TestFaultModel:
    def test_mesh_declares_every_router(self):
        cfg, fm = _model(mesh_x=3, mesh_y=2)
        assert fm.n_routers == 6

    def test_fault_vector_shape_and_range(self):
        _, fm = _model()
        v = fm.fault_vector(0)
        assert v.shape == (N.N_FAULT_TYPES,)
        assert (v > 0).all() and (v < 1e-3).all()

    def test_vectorized_matches_scalar(self):
        cfg, fm = _model(mesh_x=3, mesh_y=3)
        all_v = np.asarray(fm.fault_vectors(80.0))
        for r in range(fm.n_routers):
            np.testing.assert_allclose(all_v[r], fm.fault_vector(r, 80.0),
                                       rtol=1e-6)

    def test_temperature_monotone_and_clamped(self):
        _, fm = _model()
        cold = fm.fault_prob(0, 10.0)
        base = fm.fault_prob(0, N.BASELINE_TEMPERATURE_C)
        hot = fm.fault_prob(0, 120.0)
        assert cold < base < hot
        # out-of-range clamps (FaultModel.cc:189-201 recovery, not a fail)
        assert fm.fault_prob(0, 500.0) == fm.fault_prob(0, 125.0)
        assert fm.fault_prob(0, -40.0) == fm.fault_prob(0, 0.0)

    def test_bigger_buffers_raise_data_corruption(self):
        _, small = _model(buffers_per_data_vc=1)
        _, big = _model(buffers_per_data_vc=8)
        assert (big.fault_vector(0)[N.FT_DATA_FEW_BITS]
                > small.fault_vector(0)[N.FT_DATA_FEW_BITS])

    def test_corner_router_less_vulnerable_than_interior(self):
        cfg, fm = _model(mesh_x=3, mesh_y=3)
        corner, interior = 0, 4           # (0,0) vs (1,1)
        assert fm.fault_prob(corner) < fm.fault_prob(interior)

    def test_aggregate_and_mtbf(self):
        cfg, fm = _model()
        agg = fm.aggregate_prob()
        assert 0 < agg < 1
        assert abs(fm.mtbf_cycles() * agg - 1.0) < 1e-6
        assert fm.mtbf_cycles(120.0) < fm.mtbf_cycles(40.0)

    def test_declare_router_validates(self):
        fm = N.FaultModel()
        with pytest.raises(ValueError):
            fm.declare_router(0, 5, 4, 4, 1)

    def test_type_names_cover_all(self):
        assert len(N.FAULT_TYPE_NAMES) == N.N_FAULT_TYPES
        assert N.fault_type_to_string(N.FT_MISROUTE) == "misrouting"


def _msgs(n_accesses=64, seed=3, **noc_kw):
    mcfg = MesiConfig()
    ncfg = N.NocConfig(**noc_kw)
    trace = torture_stream(mcfg, n_accesses, mem_words=64, seed=seed)
    return trace, mcfg, ncfg, N.build_message_trace(trace, mcfg, ncfg)


class TestMessageTrace:
    def test_routes_are_adjacent_xy_paths(self):
        _, _, ncfg, msgs = _msgs(mesh_x=3, mesh_y=2)
        route = np.asarray(msgs.route)
        hops = np.asarray(msgs.hops)
        for m in range(route.shape[0]):
            r = route[m, :hops[m]]
            assert (r >= 0).all() and (r < ncfg.n_routers).all()
            for a, b in zip(r, r[1:]):
                ax, ay = a % ncfg.mesh_x, a // ncfg.mesh_x
                bx, by = b % ncfg.mesh_x, b // ncfg.mesh_x
                assert abs(ax - bx) + abs(ay - by) == 1
            assert (route[m, hops[m]:] == -1).all()

    def test_misses_emit_request_and_response(self):
        _, _, _, msgs = _msgs()
        kind = np.asarray(msgs.kind)
        assert (kind == N.MSG_REQ).sum() == (kind == N.MSG_RESP).sum()
        assert (kind == N.MSG_REQ).sum() > 0

    def test_repeat_access_hits_after_fill(self):
        """The same core touching the same word twice misses only once."""
        import jax.numpy as jnp
        mcfg = MesiConfig()
        ncfg = N.NocConfig()
        trace_args = dict(
            core=jnp.zeros(2, jnp.int32), word=jnp.zeros(2, jnp.int32),
            is_store=jnp.zeros(2, bool), value=jnp.zeros(2, jnp.uint32))
        from shrewd_tpu.models.mesi import AccessTrace
        msgs = N.build_message_trace(AccessTrace(**trace_args), mcfg, ncfg)
        assert (np.asarray(msgs.kind) == N.MSG_REQ).sum() == 1


class TestNocKernel:
    def test_tally_sums_to_batch(self):
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(0), 128)
        tally = np.asarray(kern.run_keys(keys))
        assert tally.sum() == 128

    def test_fault_off_route_is_masked(self):
        _, _, ncfg, msgs = _msgs(mesh_x=4, mesh_y=4)
        kern = N.NocKernel(msgs, ncfg)
        route = np.asarray(msgs.route)
        used = set(route[route >= 0].ravel().tolist())
        idle = [r for r in range(ncfg.n_routers) if r not in used]
        if not idle:
            pytest.skip("every router carries traffic")
        import jax.numpy as jnp
        f = N.NocFault(router=jnp.int32(idle[0]), cycle=jnp.int32(1),
                       ftype=jnp.int32(N.FT_FLIT_LOSS))
        assert int(kern._classify(f)) == C.OUTCOME_MASKED

    def test_flit_loss_on_message_is_due(self):
        import jax.numpy as jnp
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        r0 = int(np.asarray(msgs.route)[0, 0])
        c0 = int(np.asarray(msgs.depart)[0])
        f = N.NocFault(router=jnp.int32(r0), cycle=jnp.int32(c0),
                       ftype=jnp.int32(N.FT_FLIT_LOSS))
        assert int(kern._classify(f)) == C.OUTCOME_DUE

    def test_data_corruption_on_response_is_sdc(self):
        import jax.numpy as jnp
        _, _, ncfg, msgs = _msgs()
        kind = np.asarray(msgs.kind)
        resp = int(np.nonzero(kind == N.MSG_RESP)[0][0])
        kern = N.NocKernel(msgs, ncfg)
        f = N.NocFault(
            router=jnp.int32(np.asarray(msgs.route)[resp, 0]),
            cycle=jnp.int32(np.asarray(msgs.depart)[resp]),
            ftype=jnp.int32(N.FT_DATA_FEW_BITS))
        out = int(kern._classify(f))
        assert out in (C.OUTCOME_SDC, C.OUTCOME_DUE)  # DUE if a REQ shares
        # pin the unambiguous case: isolate on a cycle/router where only
        # the response sits
        route = np.asarray(msgs.route)
        depart = np.asarray(msgs.depart)
        hops = np.asarray(msgs.hops)
        for h in range(int(hops[resp])):
            r, c = int(route[resp, h]), int(depart[resp]) + h
            others = [m for m in range(route.shape[0]) if m != resp
                      and 0 <= c - depart[m] < hops[m]
                      and route[m, c - depart[m]] == r]
            if not others:
                f = N.NocFault(router=jnp.int32(r), cycle=jnp.int32(c),
                               ftype=jnp.int32(N.FT_DATA_FEW_BITS))
                assert int(kern._classify(f)) == C.OUTCOME_SDC
                return
        pytest.skip("response never alone at a router")

    def test_type_distribution_follows_fault_vector(self):
        """Sampled fault types should favor the dominant (SRAM) classes."""
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(7), 512)
        f = kern.sample_batch(keys)
        types = np.asarray(f.ftype)
        assert (types >= 0).all() and (types < N.N_FAULT_TYPES).all()
        data_frac = ((types == N.FT_DATA_FEW_BITS)
                     | (types == N.FT_DATA_ALL_BITS)).mean()
        assert data_frac > 0.5        # buffer SRAM dominates the area model

    def test_hot_die_raises_aggregate_but_not_distribution_shape(self):
        _, _, ncfg, msgs = _msgs()
        hot_cfg = N.NocConfig(temperature_c=110.0)
        kern_hot = N.NocKernel(msgs, hot_cfg)
        kern_base = N.NocKernel(msgs, ncfg)
        # scaling is uniform across types → sampled distribution unchanged
        np.testing.assert_allclose(np.asarray(kern_hot._type_cdf),
                                   np.asarray(kern_base._type_cdf),
                                   atol=1e-6)
        assert (kern_hot.fm.aggregate_prob(110.0)
                > kern_base.fm.aggregate_prob())
