"""NoC fault model: probability calculator + message-level injection
(models/noc.py; reference garnet FaultModel.hh:59-126)."""

import numpy as np
import pytest

from shrewd_tpu.models import noc as N
from shrewd_tpu.models.mesi import MesiConfig, torture_stream
from shrewd_tpu.ops import classify as C
from shrewd_tpu.utils import prng


def _model(**kw):
    cfg = N.NocConfig(**kw)
    return cfg, N.FaultModel.for_mesh(cfg)


class TestFaultModel:
    def test_mesh_declares_every_router(self):
        cfg, fm = _model(mesh_x=3, mesh_y=2)
        assert fm.n_routers == 6

    def test_fault_vector_shape_and_range(self):
        _, fm = _model()
        v = fm.fault_vector(0)
        assert v.shape == (N.N_FAULT_TYPES,)
        assert (v > 0).all() and (v < 1e-3).all()

    def test_vectorized_matches_scalar(self):
        cfg, fm = _model(mesh_x=3, mesh_y=3)
        all_v = np.asarray(fm.fault_vectors(80.0))
        for r in range(fm.n_routers):
            np.testing.assert_allclose(all_v[r], fm.fault_vector(r, 80.0),
                                       rtol=1e-6)

    def test_temperature_monotone_and_clamped(self):
        _, fm = _model()
        cold = fm.fault_prob(0, 10.0)
        base = fm.fault_prob(0, N.BASELINE_TEMPERATURE_C)
        hot = fm.fault_prob(0, 120.0)
        assert cold < base < hot
        # out-of-range clamps (FaultModel.cc:189-201 recovery, not a fail)
        assert fm.fault_prob(0, 500.0) == fm.fault_prob(0, 125.0)
        assert fm.fault_prob(0, -40.0) == fm.fault_prob(0, 0.0)

    def test_bigger_buffers_raise_data_corruption(self):
        _, small = _model(buffers_per_data_vc=1)
        _, big = _model(buffers_per_data_vc=8)
        assert (big.fault_vector(0)[N.FT_DATA_FEW_BITS]
                > small.fault_vector(0)[N.FT_DATA_FEW_BITS])

    def test_corner_router_less_vulnerable_than_interior(self):
        cfg, fm = _model(mesh_x=3, mesh_y=3)
        corner, interior = 0, 4           # (0,0) vs (1,1)
        assert fm.fault_prob(corner) < fm.fault_prob(interior)

    def test_aggregate_and_mtbf(self):
        cfg, fm = _model()
        agg = fm.aggregate_prob()
        assert 0 < agg < 1
        assert abs(fm.mtbf_cycles() * agg - 1.0) < 1e-6
        assert fm.mtbf_cycles(120.0) < fm.mtbf_cycles(40.0)

    def test_declare_router_validates(self):
        fm = N.FaultModel()
        with pytest.raises(ValueError):
            fm.declare_router(0, 5, 4, 4, 1)

    def test_type_names_cover_all(self):
        assert len(N.FAULT_TYPE_NAMES) == N.N_FAULT_TYPES
        assert N.fault_type_to_string(N.FT_MISROUTE) == "misrouting"


def _msgs(n_accesses=64, seed=3, **noc_kw):
    mcfg = MesiConfig()
    ncfg = N.NocConfig(**noc_kw)
    trace = torture_stream(mcfg, n_accesses, mem_words=64, seed=seed)
    return trace, mcfg, ncfg, N.build_message_trace(trace, mcfg, ncfg)


class TestMessageTrace:
    def test_routes_are_adjacent_xy_paths(self):
        _, _, ncfg, msgs = _msgs(mesh_x=3, mesh_y=2)
        route = np.asarray(msgs.route)
        hops = np.asarray(msgs.hops)
        for m in range(route.shape[0]):
            r = route[m, :hops[m]]
            assert (r >= 0).all() and (r < ncfg.n_routers).all()
            for a, b in zip(r, r[1:]):
                ax, ay = a % ncfg.mesh_x, a // ncfg.mesh_x
                bx, by = b % ncfg.mesh_x, b // ncfg.mesh_x
                assert abs(ax - bx) + abs(ay - by) == 1
            assert (route[m, hops[m]:] == -1).all()

    def test_misses_emit_request_and_response(self):
        _, _, _, msgs = _msgs()
        kind = np.asarray(msgs.kind)
        assert (kind == N.MSG_REQ).sum() == (kind == N.MSG_RESP).sum()
        assert (kind == N.MSG_REQ).sum() > 0

    def test_repeat_access_hits_after_fill(self):
        """The same core touching the same word twice misses only once."""
        import jax.numpy as jnp
        mcfg = MesiConfig()
        ncfg = N.NocConfig()
        trace_args = dict(
            core=jnp.zeros(2, jnp.int32), word=jnp.zeros(2, jnp.int32),
            is_store=jnp.zeros(2, bool), value=jnp.zeros(2, jnp.uint32))
        from shrewd_tpu.models.mesi import AccessTrace
        msgs = N.build_message_trace(AccessTrace(**trace_args), mcfg, ncfg)
        assert (np.asarray(msgs.kind) == N.MSG_REQ).sum() == 1


class TestNocKernel:
    def test_tally_sums_to_batch(self):
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(0), 128)
        tally = np.asarray(kern.run_keys(keys))
        assert tally.sum() == 128

    def test_fault_off_route_is_masked(self):
        _, _, ncfg, msgs = _msgs(mesh_x=4, mesh_y=4)
        kern = N.NocKernel(msgs, ncfg)
        route = np.asarray(msgs.route)
        used = set(route[route >= 0].ravel().tolist())
        idle = [r for r in range(ncfg.n_routers) if r not in used]
        if not idle:
            pytest.skip("every router carries traffic")
        import jax.numpy as jnp
        f = N.NocFault(router=jnp.int32(idle[0]), cycle=jnp.int32(1),
                       ftype=jnp.int32(N.FT_FLIT_LOSS))
        assert int(kern._classify(f)) == C.OUTCOME_MASKED

    def test_flit_loss_on_message_is_due(self):
        import jax.numpy as jnp
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        r0 = int(np.asarray(msgs.route)[0, 0])
        c0 = int(np.asarray(msgs.depart)[0])
        f = N.NocFault(router=jnp.int32(r0), cycle=jnp.int32(c0),
                       ftype=jnp.int32(N.FT_FLIT_LOSS))
        assert int(kern._classify(f)) == C.OUTCOME_DUE

    def test_data_corruption_on_response_is_sdc(self):
        import jax.numpy as jnp
        _, _, ncfg, msgs = _msgs()
        kind = np.asarray(msgs.kind)
        resp = int(np.nonzero(kind == N.MSG_RESP)[0][0])
        kern = N.NocKernel(msgs, ncfg)
        f = N.NocFault(
            router=jnp.int32(np.asarray(msgs.route)[resp, 0]),
            cycle=jnp.int32(np.asarray(msgs.depart)[resp]),
            ftype=jnp.int32(N.FT_DATA_FEW_BITS))
        out = int(kern._classify(f))
        assert out in (C.OUTCOME_SDC, C.OUTCOME_DUE)  # DUE if a REQ shares
        # pin the unambiguous case: isolate on a cycle/router where only
        # the response sits
        route = np.asarray(msgs.route)
        depart = np.asarray(msgs.depart)
        hops = np.asarray(msgs.hops)
        for h in range(int(hops[resp])):
            r, c = int(route[resp, h]), int(depart[resp]) + h
            others = [m for m in range(route.shape[0]) if m != resp
                      and 0 <= c - depart[m] < hops[m]
                      and route[m, c - depart[m]] == r]
            if not others:
                f = N.NocFault(router=jnp.int32(r), cycle=jnp.int32(c),
                               ftype=jnp.int32(N.FT_DATA_FEW_BITS))
                assert int(kern._classify(f)) == C.OUTCOME_SDC
                return
        pytest.skip("response never alone at a router")

    def test_type_distribution_follows_fault_vector(self):
        """Sampled fault types should favor the dominant (SRAM) classes."""
        _, _, ncfg, msgs = _msgs()
        kern = N.NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(7), 512)
        f = kern.sample_batch(keys)
        types = np.asarray(f.ftype)
        assert (types >= 0).all() and (types < N.N_FAULT_TYPES).all()
        data_frac = ((types == N.FT_DATA_FEW_BITS)
                     | (types == N.FT_DATA_ALL_BITS)).mean()
        assert data_frac > 0.5        # buffer SRAM dominates the area model

    def test_hot_die_raises_aggregate_but_not_distribution_shape(self):
        _, _, ncfg, msgs = _msgs()
        hot_cfg = N.NocConfig(temperature_c=110.0)
        kern_hot = N.NocKernel(msgs, hot_cfg)
        kern_base = N.NocKernel(msgs, ncfg)
        # scaling is uniform across types → sampled distribution unchanged
        np.testing.assert_allclose(np.asarray(kern_hot._type_cdf),
                                   np.asarray(kern_base._type_cdf),
                                   atol=1e-6)
        assert (kern_hot.fm.aggregate_prob(110.0)
                > kern_base.fm.aggregate_prob())


class TestFlitCreditPipeline:
    """Credit/VC-level faults simulated on the wormhole flit pipeline
    (VERDICT r3 #8; garnet credit flow control, Router.hh:74): outcomes
    emerge from flow control, differentially pinned against the scalar
    oracle."""

    def _setup(self, n_accesses=60, seed=3):
        import jax

        mcfg = MesiConfig(n_cores=4, n_sets=4, n_ways=2, words_per_line=2)
        mcfg.validate()
        ncfg = N.NocConfig(mesh_x=2, mesh_y=2)
        ncfg.validate()
        tr = torture_stream(mcfg, n_accesses, 64, seed=seed)
        msgs = N.build_message_trace(tr, mcfg, ncfg)
        return msgs, ncfg, jax

    def test_kernel_matches_oracle_on_pipeline_faults(self):
        from functools import partial

        msgs, ncfg, jax = self._setup()
        gd, gc = N.scalar_flit_sim(msgs, ncfg)
        assert (gd >= 0).all() and not gc.any()
        hor = int(gd.max() * 2 + 32)
        rng = np.random.default_rng(11)
        sim = jax.jit(partial(N.flit_sim, horizon=hor), static_argnums=1)
        for ft in N.PIPELINE_TYPES:
            for _ in range(5):
                f = (int(rng.integers(0, 4)), int(rng.integers(0, gd.max())),
                     ft, int(rng.integers(0, N.N_VC)))
                sd, sc = N.scalar_flit_sim(msgs, ncfg, fault=f, horizon=hor)
                dd, dc = sim(msgs, ncfg, N.NocFault(*map(N.i32, f)))
                assert (np.asarray(dd) == sd).all(), (ft, f)
                assert (np.asarray(dc) == sc).all(), (ft, f)

    def test_credit_loss_on_capacity_one_class_starves(self):
        """Losing the single control-VC credit of a busy router starves
        every later REQ through it — undelivered at the horizon → the
        deadlock/timeout DUE."""
        msgs, _, jax = self._setup()
        ncfg = N.NocConfig(mesh_x=2, mesh_y=2, vcs_per_vnet=1,
                           buffers_per_ctrl_vc=1)
        ncfg.validate()
        gd, _ = N.scalar_flit_sim(msgs, ncfg)
        assert (gd >= 0).all()
        hor = int(gd.max() * 2 + 32)
        # find a router traversed by a REQ after some cycle
        route = np.asarray(msgs.route)
        kind = np.asarray(msgs.kind)
        req = np.nonzero(kind == N.MSG_REQ)[0]
        target = int(route[req[len(req) // 2], 1])   # mid-stream REQ hop
        f = (target, 0, N.FT_CREDIT_LOSS, N.VC_REQ)
        sd, _ = N.scalar_flit_sim(msgs, ncfg, fault=f, horizon=hor)
        assert (sd < 0).any()                        # someone starved

    def test_spurious_credit_overflows_and_corrupts(self):
        """A generated credit lets a flit advance into a full capacity-1
        pool while its resident is arbitration-blocked; the overflow
        clobbers both flits, and oracle and kernel agree on exactly which.

        Construction: m1 sits in router 2 waiting for router 3 (it loses
        the arbitration for 3 to the lower-index m0); the spurious credit
        at router 2 lets m2 pile in behind during that cycle."""
        import jax as _jax
        import jax.numpy as jnp
        from functools import partial

        ncfg = N.NocConfig(mesh_x=2, mesh_y=2, vcs_per_vnet=1,
                           buffers_per_ctrl_vc=1, buffers_per_data_vc=1)
        ncfg.validate()
        msgs = N.MessageTrace(
            kind=jnp.asarray([N.MSG_REQ] * 3, jnp.int32),
            route=jnp.asarray([[0, 3, -1], [1, 2, 3], [1, 2, 3]],
                              jnp.int32),
            hops=jnp.asarray([2, 3, 3], jnp.int32),
            depart=jnp.asarray([1, 0, 0], jnp.int32))
        f = (2, 1, N.FT_CREDIT_GEN, N.VC_REQ)
        sd, sc = N.scalar_flit_sim(msgs, ncfg, fault=f, horizon=40)
        assert sc[1] and sc[2] and not sc[0]
        dd, dc = _jax.jit(partial(N.flit_sim, horizon=40),
                          static_argnums=1)(
            msgs, ncfg, N.NocFault(*map(N.i32, f)))
        assert (np.asarray(dc) == sc).all()
        assert (np.asarray(dd) == sd).all()

    def test_campaign_path_classifies_pipeline_types(self):
        """NocKernel routes credit/alloc types through the pipeline and
        the rest through the hit table — outcomes stay in-taxonomy and
        the tally is conserved."""
        msgs, ncfg, _ = self._setup(n_accesses=40)
        kern = N.NocKernel(msgs, ncfg)
        keys = prng.trial_keys(prng.campaign_key(13), 32)
        tally = np.asarray(kern.run_keys(keys))
        assert tally.sum() == 32 and (tally >= 0).all()
