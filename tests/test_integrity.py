"""Result-integrity layer (shrewd_tpu/integrity.py + orchestrator wiring).

The contract under test is the ISSUE acceptance criterion: a campaign with
the differential audit on completes with zero mismatches and reports
canary/audit/invariant stats; an injected tally corruption (test hook)
triggers quarantine + re-dispatch with bit-identical recovered tallies; and
exceeding the audit threshold with audit_action=abort exits rc 3 and
resumes cleanly from the v5 checkpoint.
"""

import json
import os

import numpy as np
import pytest

from shrewd_tpu import integrity as integ
from shrewd_tpu import resilience as resil
from shrewd_tpu.ops import classify as C


# --- tally invariants (pure host checks) ------------------------------------

def test_clean_tally_passes_all_invariants():
    assert integ.tally_violations([10, 3, 2, 1], 16) == []
    strata = np.zeros((8, 4), np.int64)
    strata[0] = [10, 3, 2, 1]
    assert integ.tally_violations([10, 3, 2, 1], 16, strata) == []


def test_each_corruption_trips_exactly_one_invariant():
    # each deliberately corrupted tally trips its own check, exactly once
    cases = [
        ([10, 3, 2, 2], 16, "tally sum"),          # sum != batch
        ([17, -1, 0, 0], 16, "negative"),          # negative count
        ([float("nan"), 0, 0, 0], 16, "non-finite"),
        ([15.5, 0.5, 0, 0], 16, "non-integral"),
    ]
    for tally, batch, needle in cases:
        viol = integ.tally_violations(tally, batch)
        assert len(viol) == 1, (tally, viol)
        assert needle in viol[0]


def test_strata_must_refine_the_pooled_tally():
    strata = np.zeros((8, 4), np.int64)
    strata[0] = [9, 3, 2, 1]                       # sums to 15, tally says 16
    viol = integ.tally_violations([10, 3, 2, 1], 16, strata)
    assert len(viol) == 1 and "strata" in viol[0]


def test_monotone_and_shard_sum_checks():
    assert integ.monotone_violations([5, 1, 0, 0], [6, 1, 0, 0]) == []
    assert len(integ.monotone_violations([5, 1, 0, 0], [4, 1, 0, 0])) == 1
    local = np.asarray([[3, 1, 0, 0], [2, 0, 1, 0]])
    assert integ.shard_sum_violations(local, [5, 1, 1, 0]) == []
    assert len(integ.shard_sum_violations(local, [5, 1, 0, 0])) == 1


def test_mismatch_ledger_accounting_and_roundtrip():
    led = integ.MismatchLedger()
    led.record(10, [])
    led.record(10, [{"reason": "sdc->masked@oracle", "trial_index": 3}],
               context={"batch_id": 7})
    assert led.audited == 20 and led.mismatched == 1
    assert led.rate() == pytest.approx(0.05)
    assert led.over(0.01) and not led.over(0.10)
    assert led.entries[0]["batch_id"] == 7
    back = integ.MismatchLedger.from_dict(
        json.loads(json.dumps(led.to_dict())))
    assert back.audited == 20 and back.reasons == led.reasons


def test_evidence_ring_is_bounded():
    led = integ.MismatchLedger()
    for i in range(integ.MAX_EVIDENCE + 50):
        led.record(1, [{"reason": "x", "trial_index": i}])
    assert led.mismatched == integ.MAX_EVIDENCE + 50   # counters exact
    assert len(led.entries) == integ.MAX_EVIDENCE      # ring bounded


# --- canary construction ------------------------------------------------------

def _kernel(n=96, **cfg_kw):
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    t = generate(WorkloadConfig(n=n, nphys=32, mem_words=64,
                                working_set_words=32, seed=7))
    return TrialKernel(t, O3Config(**cfg_kw))


def test_constructed_canaries_masked_on_dense_kernel():
    kernel = _kernel()
    fault, notes = integ.constructed_canaries(kernel)
    out = np.asarray(kernel.run_batch(fault))
    assert len(notes) == out.shape[0]
    for i, note in enumerate(notes):
        assert int(out[i]) == C.OUTCOME_MASKED, note


def test_constructed_canaries_masked_on_hybrid_kernel():
    kernel = _kernel()
    fault, notes = integ.constructed_canaries(kernel)
    out = np.asarray(kernel.run_batch_hybrid(fault))
    for i, note in enumerate(notes):
        assert int(out[i]) == C.OUTCOME_MASKED, note


def test_constructed_canaries_masked_on_chunked_kernel_ragged():
    from shrewd_tpu.ops.chunked import ChunkedCampaign

    kernel = _kernel()
    n = int(kernel.trace.n)
    chunk = 40
    assert n % chunk != 0       # the ragged-tail shape the ISSUE pins
    camp = ChunkedCampaign(kernel, chunk=chunk)
    fault, notes = integ.constructed_canaries(kernel)
    out = np.asarray(camp.outcomes_of_faults(fault))
    for i, note in enumerate(notes):
        assert int(out[i]) == C.OUTCOME_MASKED, note
    # the zero-mask canary lands IN-window: it must have replayed its
    # landing chunk and converged state-equal, not taken the oow shortcut
    assert camp.last_stats["oow_masked"] == 2
    assert camp.last_stats["resolved_eq"] >= 1


def test_canary_battery_catches_corrupt_tier():
    """A tier function returning a wrong tally for the frozen seed keys is
    a canary miss — the whole batch is declared corrupt."""
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.utils import prng

    kernel = _kernel()
    camp = ShardedCampaign(kernel, make_mesh(), "regfile")
    keys = prng.trial_keys(prng.batch_key(
        prng.campaign_key(0), integ.CANARY_BATCH_ID), 8)
    battery = integ.CanaryBattery(camp, "regfile", seed_keys=keys)

    good = lambda k, s: (np.asarray(camp.tally_batch(k)), None)
    res = battery.check(resil.TIER_DEVICE, good)
    assert res.ok and res.trials > 0

    def corrupt(k, s):
        t = np.asarray(camp.tally_batch(k)).copy()
        t[C.OUTCOME_MASKED] -= 1
        t[C.OUTCOME_SDC] += 1
        return t, None

    res = battery.check(resil.TIER_DEVICE, corrupt)
    assert not res.ok
    assert any(f["canary"].startswith("seed@") for f in res.failures)


def test_shard_consistency_check_raises_on_mismatch():
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.utils import prng

    camp = ShardedCampaign(_kernel(), make_mesh(), "regfile",
                           integrity_check=True)
    keys = prng.trial_keys(prng.campaign_key(0), 64)
    tally = np.asarray(camp.tally_batch(keys))
    assert int(tally.sum()) == 64
    assert camp.shard_checks == 1 and camp.shard_mismatches == 0
    with pytest.raises(integ.IntegrityError, match="shard"):
        camp._verify_shards(np.asarray([[1, 0, 0, 0]]), tally)
    assert camp.shard_mismatches == 1


# --- orchestrator integration -------------------------------------------------

def _tiny_plan(integrity=None, **kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    defaults = dict(structures=["regfile"], batch_size=64,
                    target_halfwidth=0.2, confidence=0.95,
                    max_trials=128, min_trials=64)
    defaults.update(kw)
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        **defaults)
    for k, v in (integrity or {}).items():
        setattr(plan.integrity, k, v)
    return plan


def _final_results(orch):
    from shrewd_tpu.sim.exit_event import ExitEvent

    events = list(orch.events())
    return events, (dict(events[-1][1])
                    if events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
                    else None)


@pytest.fixture(scope="module")
def clean_results():
    """Reference tallies from an integrity-off run (the bit-identity
    baseline every integrity-on run must reproduce)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=0, audit_rate=0.0, invariants=False)))
    _, res = _final_results(orch)
    assert res is not None
    return res


def test_integrity_on_campaign_is_bit_identical_and_audits_clean(
        clean_results, tmp_path):
    """The acceptance-criterion core: audit on → zero mismatches, canary/
    audit/invariant stats in stats.txt, tallies unperturbed (canary keys
    are drawn from a reserved PRNG stream, audits re-run existing keys)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=2, audit_rate=0.05)),
        outdir=str(tmp_path))
    _, res = _final_results(orch)
    assert res is not None
    for k in clean_results:
        np.testing.assert_array_equal(clean_results[k].tallies,
                                      res[k].tallies)
    mon = orch.monitor
    assert mon.canary_trials > 0 and mon.canary_failures == 0
    assert mon.ledger.audited > 0 and mon.ledger.mismatched == 0
    assert mon.invariant_checks > 0 and mon.invariant_violations == 0
    assert mon.quarantined == 0
    orch.write_outputs()
    text = (tmp_path / "stats.txt").read_text()
    for name in ("canary_trials", "canary_failures", "audited_trials",
                 "audit_mismatch_rate", "invariant_checks",
                 "quarantined_batches"):
        assert name in text, name
    # stats.json stays strict-parseable with the integrity group present
    json.loads((tmp_path / "stats.json").read_text(),
               parse_constant=lambda s: pytest.fail(f"non-strict {s}"))


def test_injected_corruption_quarantines_and_recovers_bit_identical(
        clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=0, audit_rate=0.0)))

    def corrupt(t):
        t = t.copy()
        t[C.OUTCOME_MASKED] += 7        # breaks sum == batch
        return t

    orch.monitor.arm_corruption(corrupt)
    events, res = _final_results(orch)
    assert res is not None
    kinds = [e for e, _ in events]
    assert ExitEvent.INTEGRITY_VIOLATION in kinds
    payloads = [p for e, p in events
                if e is ExitEvent.INTEGRITY_VIOLATION]
    assert any(p.get("kind") == "invariant" for p in payloads)
    assert any(p.get("kind") == "recovered" for p in payloads)
    # bit-identical recovery: the requeue re-ran the SAME frozen keys
    for k in clean_results:
        np.testing.assert_array_equal(clean_results[k].tallies,
                                      res[k].tallies)
    mon = orch.monitor
    assert mon.quarantined == 1 and mon.requeues == 1 and mon.recovered == 1
    assert mon.invariant_violations == 1


def test_unrecoverable_corruption_aborts_resumably(tmp_path, clean_results):
    """Corruption that survives every re-dispatch is fatal: resumable
    checkpoint, no CAMPAIGN_COMPLETE, evidence on disk; a resume with the
    hook disarmed completes bit-identical."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=0, audit_rate=0.0, max_requeue=1)),
        outdir=str(tmp_path))
    orch.monitor.arm_corruption(lambda t: t + 1, times=100)
    events = list(orch.events())
    kinds = [e for e, _ in events]
    assert orch.aborted and orch.abort_reason == "integrity violation"
    assert ExitEvent.CAMPAIGN_COMPLETE not in kinds
    assert ExitEvent.INTEGRITY_VIOLATION in kinds
    evidence = json.loads(
        (tmp_path / "integrity_evidence.json").read_text())
    assert evidence["quarantine"]
    assert any(q.get("fatal") for q in evidence["quarantine"])

    orch2 = Orchestrator.resume(os.path.join(str(tmp_path),
                                             "campaign_ckpt"))
    assert orch2.monitor.quarantined >= 2     # ledger survived resume
    _, res = _final_results(orch2)
    assert res is not None
    for k in clean_results:
        np.testing.assert_array_equal(clean_results[k].tallies,
                                      res[k].tallies)


def test_audit_threshold_abort_rc3_and_v5_resume(tmp_path, monkeypatch,
                                                 clean_results):
    """Exceeding --audit-threshold with --audit-action abort exits rc 3
    (CLI) and resumes cleanly from the v5 checkpoint once the kernels
    agree again (re-arm baseline, mirroring the escalation gate)."""
    from shrewd_tpu import main as cli
    from shrewd_tpu.campaign.orchestrator import CKPT_VERSION

    plan_path = tmp_path / "plan.json"
    plan_path.write_text(json.dumps(_tiny_plan().to_dict()))
    out = tmp_path / "out"

    # force every audited trial to mismatch
    def fake_audit(self, keys, idx):
        return [{"trial_index": int(i), "primary": "masked",
                 "alternate": "sdc", "reason": "masked->sdc@test"}
                for i in idx]

    monkeypatch.setattr(integ.AuditOracle, "audit", fake_audit)
    rc = cli.main(["run", str(plan_path), "--outdir", str(out),
                   "--audit-rate", "0.05", "--audit-threshold", "0.01",
                   "--audit-action", "abort", "--canary-trials", "0"])
    assert rc == 3
    ckpt = out / "campaign_ckpt"
    doc = resil.load_json_verified(str(ckpt / "campaign.json"))
    assert doc["version"] == CKPT_VERSION == 5
    assert doc["integrity"]["ledger"]["mismatched"] > 0
    evidence = json.loads((out / "integrity_evidence.json").read_text())
    assert evidence["ledger"]["reasons"]["masked->sdc@test"] > 0

    # healed kernels: the restored mismatch rate is the baseline; clean
    # audits only lower it, so the resumed run completes (rc 0)
    monkeypatch.undo()
    out2 = tmp_path / "out2"
    rc2 = cli.main(["resume", str(ckpt), "--outdir", str(out2),
                    "--audit-action", "abort"])
    assert rc2 == 0
    stats = json.loads((out2 / "stats.json").read_text())
    camp = stats["w0"]["regfile"]
    want = clean_results[("w0", "regfile")].tallies
    got = [camp["outcomes"][name] for name in C.OUTCOME_NAMES]
    np.testing.assert_array_equal(want, np.asarray(got, np.int64))


def test_canary_dispatch_failure_degrades_not_crashes(monkeypatch,
                                                      clean_results):
    """A backend failure DURING the canary run (wedge, transient XLA
    error) must behave like any dispatch failure — quarantine + requeue
    down the ladder — never crash the campaign (the PR-1 degradation
    guarantee extends to the integrity layer's own device work)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    real_check = integ.CanaryBattery.check
    calls = [0]

    def flaky(self, tier, fn):
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("transient XLA error")
        return real_check(self, tier, fn)

    monkeypatch.setattr(integ.CanaryBattery, "check", flaky)
    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=2, audit_rate=0.0)))
    events, res = _final_results(orch)
    assert res is not None                   # completed despite the crash
    payloads = [p for e, p in events
                if e is ExitEvent.INTEGRITY_VIOLATION]
    assert any(p.get("kind") == "canary_dispatch" for p in payloads)
    assert orch.monitor.recovered == 1
    for k in clean_results:
        np.testing.assert_array_equal(clean_results[k].tallies,
                                      res[k].tallies)


def test_tier_structure_campaign_with_canaries():
    """Tier-qualified structures (cache:data) route kernel-facing canary
    calls through the SUBSTRUCTURE name; constructed canaries and the
    audit are TrialKernel-only and must skip silently — the seed canary
    (sharded psum path vs unsharded protocol) still runs."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(
        structures=["cache:data"], max_trials=64,
        integrity=dict(canary_trials=2, audit_rate=0.05)))
    _, res = _final_results(orch)
    assert res is not None
    mon = orch.monitor
    assert mon.canary_trials > 0 and mon.canary_failures == 0
    assert mon.ledger.audited == 0          # no fault-level API → skipped
    assert mon.quarantined == 0


# --- checkpoint upgrader chain ------------------------------------------------

def test_upgrade_chain_v1_to_v5_roundtrip(tmp_path):
    from shrewd_tpu.campaign.orchestrator import (CKPT_VERSION,
                                                  Orchestrator,
                                                  upgrade_checkpoint)

    orch = Orchestrator(_tiny_plan(
        integrity=dict(canary_trials=0, audit_rate=0.0)),
        outdir=str(tmp_path))
    _, res = _final_results(orch)
    ckpt = orch.checkpoint()
    path = os.path.join(ckpt, "campaign.json")
    doc = resil.load_json_verified(path)

    # strip the document back to v1 shape (no escape counters, no strata,
    # no tier ledger, no integrity state)
    for per_s in doc["state"].values():
        for st_doc in per_s.values():
            for key in ("escapes", "taint_trials", "strata", "tier_trials"):
                del st_doc[key]
    del doc["integrity"]
    doc["version"] = 1

    up = json.loads(json.dumps(doc))
    upgrade_checkpoint(up)
    assert up["version"] == CKPT_VERSION == 5
    for per_s in up["state"].values():
        for st_doc in per_s.values():
            assert st_doc["escapes"] == 0 and st_doc["taint_trials"] == 0
            assert st_doc["strata"] is None
            assert st_doc["tier_trials"] == [0] * len(resil.TIERS)
    assert up["integrity"] is None      # pre-v5 history reads as unaudited

    # a v1 document on disk resumes through the whole chain
    doc["checksum"] = resil.doc_checksum(doc)
    resil.write_json_atomic(path, doc)
    prev = os.path.join(ckpt, "campaign.prev.json")
    if os.path.exists(prev):      # the v5 prev would shadow the v1 doc
        os.unlink(prev)
    orch2 = Orchestrator.resume(ckpt)
    assert orch2.monitor.ledger.audited == 0
    st = orch2.state[("w0", "regfile")]
    assert st.trials == res[("w0", "regfile")].trials


def test_unknown_version_still_raises():
    from shrewd_tpu.campaign.orchestrator import upgrade_checkpoint

    with pytest.raises(ValueError, match="no upgrade path"):
        upgrade_checkpoint({"version": -1})


def test_torn_latest_falls_back_then_resumes_with_ledger(tmp_path,
                                                         clean_results):
    """Kill-mid-checkpoint with integrity state present: the torn latest
    is detected, resume falls back to .prev (quarantine/audit ledger
    intact), and the finished campaign is bit-identical."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = _tiny_plan(checkpoint_every=1, target_halfwidth=0.001,
                      max_trials=192,
                      integrity=dict(canary_trials=0, audit_rate=0.05))
    clean = Orchestrator(_tiny_plan(
        target_halfwidth=0.001, max_trials=192,
        integrity=dict(canary_trials=0, audit_rate=0.0,
                       invariants=False)))
    _, want = _final_results(clean)

    orch = Orchestrator(plan, outdir=str(tmp_path))
    orch.monitor.arm_corruption(lambda t: t - 1)   # one quarantine early
    ckpts = 0
    ckpt_dir = None
    for ev, payload in orch.events():
        if ev is ExitEvent.CHECKPOINT:
            ckpts += 1
            ckpt_dir = payload
            if ckpts == 2:
                break
    assert ckpt_dir is not None
    latest = os.path.join(ckpt_dir, "campaign.json")
    blob = open(latest).read()
    with open(latest, "w") as f:
        f.write(blob[:len(blob) // 3])

    orch2 = Orchestrator.resume(ckpt_dir)
    mon = orch2.monitor
    assert mon.quarantined == 1 and mon.ledger.audited > 0   # ledger there
    _, res = _final_results(orch2)
    assert res is not None
    for k in want:
        np.testing.assert_array_equal(want[k].tallies, res[k].tallies)


# --- probe --canary -----------------------------------------------------------

def test_backend_probe_canary_reports_trustworthy():
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "backend_probe.py"),
         "--platform", "cpu", "--timeout", "150", "--canary"],
        capture_output=True, text=True, timeout=200,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-500:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    assert verdict["integrity"]["trustworthy"] is True
    assert verdict["integrity"]["canaries"] == 3
    assert verdict["integrity"]["canary_misses"] == []
    assert verdict["integrity"]["invariant_violations"] == []
