"""Deterministic chaos harness + elastic layer (shrewd_tpu/chaos.py,
shrewd_tpu/parallel/elastic.py, orchestrator wiring).

The contract under test is the ISSUE acceptance criterion: for each
injected fault class — wedged dispatch, tier failure, torn checkpoint,
corrupt tally, killed/lost worker — the campaign survives through the
machinery that fault targets, and the final tally equals the undisturbed
run of the same seed BIT-FOR-BIT.  Every injected and survived fault must
land in the ``campaign.chaos.*`` / ``campaign.elastic.*`` stats groups.
"""

import json
import os
import time

import numpy as np
import pytest

from shrewd_tpu import stats as statsmod
from shrewd_tpu.chaos import ChaosEngine, ChaosPlanError, tear_file
from shrewd_tpu.parallel.elastic import (ElasticConfig, ElasticContext,
                                         HeartbeatWriter, LeaseBoard,
                                         Membership)
from shrewd_tpu.resilience import TIER_DEVICE, TIER_ORACLE


# --- the chaos-plan DSL ------------------------------------------------------

def test_plan_validation_rejects_bad_specs():
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "meteor", "at_batch": 0}]})
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "wedge"}]})        # no trigger
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "torn_checkpoint"}]})
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"faults": [{"kind": "backend_error", "at_batch": 0,
                                 "tier": "gpu"}]})
    with pytest.raises(ChaosPlanError):
        ChaosEngine({"not_faults": []})


def test_seeded_schedule_is_deterministic_and_wall_clock_free():
    plan = {"seed": 11, "faults": [
        {"kind": "corrupt_tally", "sample": {"k": 3, "of": 50}}]}
    a = ChaosEngine(plan).faults[0]["at_batch"]
    b = ChaosEngine(plan).faults[0]["at_batch"]
    assert a == b and len(a) == 3 and all(0 <= x < 50 for x in a)
    # a different seed draws a different schedule (same mechanism)
    c = ChaosEngine({"seed": 12, "faults": plan["faults"]}
                    ).faults[0]["at_batch"]
    assert c != a


def test_each_hook_fires_exactly_per_plan():
    eng = ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": [1, 3], "tier": "device"},
        {"kind": "corrupt_tally", "at_batch": 2},
    ]})
    fired = []
    for b in range(5):
        eng.begin_batch(b, "w0", "regfile")
        try:
            eng.maybe_backend_error(TIER_DEVICE)
        except Exception:
            fired.append(("be", b))
        if eng.take_corrupt_tally() is not None:
            fired.append(("ct", b))
        eng.end_batch()
    assert fired == [("be", 1), ("ct", 2), ("be", 3)]
    assert eng.injected == {"backend_error": 2}    # corruption counts at
    # apply time (note_fired), which this loop never reaches


def test_same_kind_faults_on_one_batch_all_arm():
    # two backend_error faults on one batch (device AND cpu tier — the
    # double-descent scenario) must BOTH arm; kind-keyed state that
    # overwrites would silently drop one
    eng = ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": 0, "tier": "device"},
        {"kind": "backend_error", "at_batch": 0, "tier": "cpu"}]})
    eng.begin_batch(0, "w0", "regfile")
    raised = []
    for tier in (0, 1, 0, 1):       # device, cpu, device, cpu
        try:
            eng.maybe_backend_error(tier)
        except Exception:
            raised.append(tier)
    assert raised == [0, 1]         # each tier's fault fired exactly once
    assert eng.injected == {"backend_error": 2}
    eng.end_batch()
    assert eng.survived == {"backend_error": 2}


def test_structure_filter_and_times_budget():
    eng = ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": 0, "structure": "fu",
         "times": 2}]})
    eng.begin_batch(0, "w0", "regfile")     # filtered out
    eng.maybe_backend_error(TIER_DEVICE)    # no raise
    eng2 = ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": 0, "times": 2}]})
    eng2.begin_batch(0, "w0", "fu")
    raises = 0
    for _ in range(4):
        try:
            eng2.maybe_backend_error(TIER_DEVICE)
        except Exception:
            raises += 1
    assert raises == 2                      # the attempt budget, exactly
    assert eng2.injected == {"backend_error": 1}   # one FAULT, two raises


def test_kill_worker_spec_and_worker_filter(monkeypatch):
    exits = []
    monkeypatch.setattr(os, "_exit", exits.append)
    eng = ChaosEngine({"faults": [
        {"kind": "kill_worker", "after_dispatches": 2, "worker": "w1",
         "rc": 99}]}, worker="w0")
    eng.begin_batch(0, "w0", "regfile")
    eng.maybe_kill()                        # wrong worker: no exit
    # an engine with NO worker identity must not match a targeted kill
    # (a config-built engine predates attach_elastic naming it)
    anon = ChaosEngine({"faults": [
        {"kind": "kill_worker", "after_dispatches": 1, "worker": "w1"}]})
    anon.begin_batch(0, "w0", "regfile")
    anon.maybe_kill()
    assert exits == []
    eng = ChaosEngine({"faults": [
        {"kind": "kill_worker", "after_dispatches": 2, "rc": 99}]},
        worker="w1")
    eng.begin_batch(0, "w0", "regfile")
    eng.maybe_kill()                        # 1st dispatch: not yet
    eng.begin_batch(1, "w0", "regfile")
    eng.maybe_kill()
    assert exits == [99]
    assert eng.injected == {"kill_worker": 1}


def test_wedge_warns_when_it_never_fires():
    # no deadline-bearing dispatch ever consumed the armed wedge (e.g.
    # resilience.dispatch_timeout left at 0): the batch ends with the
    # wedge unfired and the engine says so instead of reading as success
    eng = ChaosEngine({"faults": [{"kind": "wedge", "at_batch": 0}]})
    eng.begin_batch(0, "w0", "regfile")
    assert eng.take_wedge(0.0) is None      # tmo<=0: not consumed
    with pytest.warns(RuntimeWarning, match="never fired"):
        eng.end_batch()
    assert eng.injected == {}
    # a consumed wedge ends the batch silently (survived instead)
    eng2 = ChaosEngine({"faults": [{"kind": "wedge", "at_batch": 0}]})
    eng2.begin_batch(0, "w0", "regfile")
    assert eng2.take_wedge(1.0) is not None
    eng2.end_batch()
    assert eng2.survived == {"wedge": 1}


# --- campaign-level chaos: bit-identical survival ---------------------------

def _tiny_plan(**kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    defaults = dict(structures=["regfile"], batch_size=64,
                    target_halfwidth=0.2, confidence=0.95,
                    max_trials=128, min_trials=128)
    defaults.update(kw)
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        **defaults)
    # canaries/audit off: these tests target the chaos/elastic machinery;
    # the per-campaign canary/audit compiles would only slow the smoke
    # (tests/test_integrity.py owns that coverage; invariants stay on —
    # they are the detector the corrupt-tally fault must trip)
    plan.integrity.canary_trials = 0
    plan.integrity.audit_rate = 0.0
    return plan


def _final_results(orch):
    from shrewd_tpu.sim.exit_event import ExitEvent

    events = list(orch.events())
    return events, (dict(events[-1][1])
                    if events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
                    else None)


@pytest.fixture(scope="module")
def clean_results():
    """The undisturbed run every chaos scenario must reproduce exactly
    (two batches: min_trials == max_trials == 2 * batch_size)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    _, results = _final_results(Orchestrator(_tiny_plan()))
    assert results is not None
    return results


def _assert_bit_identical(clean, results):
    assert results is not None
    for k in clean:
        np.testing.assert_array_equal(clean[k].tallies, results[k].tallies)


def test_injected_tier_failure_survives_via_ladder(clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    plan = _tiny_plan()
    plan.resilience.max_retries = 0
    plan.resilience.backoff_base = 0.0
    orch = Orchestrator(plan)
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "backend_error", "at_batch": 0, "tier": "device",
         "permanent": True}]}))
    events, results = _final_results(orch)
    _assert_bit_identical(clean_results, results)
    assert ExitEvent.BACKEND_DEGRADED in [e for e, _ in events]
    assert orch.chaos.injected == {"backend_error": 1}
    assert orch.chaos.survived == {"backend_error": 1}
    # batch 0 escaped to the oracle tier, batch 1 stayed on device
    st = orch.state[("w0", "regfile")]
    assert int(st.tier_trials[TIER_ORACLE]) == 64
    assert int(st.tier_trials[TIER_DEVICE]) == 64


def test_injected_wedge_exercises_real_watchdog(clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    plan = _tiny_plan()
    # generous real deadline (first-compile safe); the injected wedge
    # carries its own short one, so the test still runs in seconds
    plan.resilience.dispatch_timeout = 60.0
    plan.resilience.backoff_base = 0.0
    orch = Orchestrator(plan)
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "wedge", "at_batch": 0, "times": 1}]}))
    _, results = _final_results(orch)
    _assert_bit_identical(clean_results, results)
    assert orch.watchdog.timeouts == 1          # the wedge, nothing else
    assert orch.chaos.injected == {"wedge": 1}
    assert orch.chaos.survived == {"wedge": 1}
    # recovered by RETRY on the device tier (transient wedge, no descent)
    st = orch.state[("w0", "regfile")]
    assert int(st.tier_trials[TIER_DEVICE]) == st.trials


def test_injected_tally_corruption_quarantined_and_recovered(clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan())
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "corrupt_tally", "at_batch": 1, "delta": 3}]}))
    _, results = _final_results(orch)
    _assert_bit_identical(clean_results, results)
    assert orch.monitor.quarantined == 1
    assert orch.monitor.recovered == 1
    assert orch.chaos.injected == {"corrupt_tally": 1}
    assert orch.chaos.survived == {"corrupt_tally": 1}
    # the chaos stats group is populated in the dumps
    text = statsmod.dump_text(orch.stats)
    assert "campaign.chaos.injected" in text and "corrupt_tally" in text


def test_injected_torn_checkpoint_survives_via_fallback(tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(checkpoint_every=1),
                        outdir=str(tmp_path))
    orch.attach_chaos(ChaosEngine({"faults": [
        {"kind": "torn_checkpoint", "at_ckpt": 1}]}))
    _, results = _final_results(orch)
    assert results is not None
    assert orch.chaos.injected == {"torn_checkpoint": 1}
    assert orch.chaos.survived == {"torn_checkpoint": 1}
    # and the torn latest is still resumable end to end (prev fallback)
    ckpt = os.path.join(str(tmp_path), "campaign_ckpt")
    doc = Orchestrator.load_checkpoint_doc(ckpt)
    assert doc["version"] >= 5


def test_chaos_config_rides_the_plan(tmp_path):
    """plan.chaos is a config child: a plan dumped with an inline spec
    rebuilds an armed engine (the reproducibility contract)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.campaign.plan import CampaignPlan

    plan = _tiny_plan()
    plan.chaos.spec = json.dumps({"faults": [
        {"kind": "corrupt_tally", "at_batch": 0}]})
    plan2 = CampaignPlan.from_dict(plan.to_dict())
    orch = Orchestrator(plan2)
    assert orch.chaos is not None
    assert orch.chaos.faults[0]["kind"] == "corrupt_tally"
    assert orch.watchdog.chaos is orch.chaos    # wedge hook wired


# --- graceful preemption ----------------------------------------------------

def test_sigterm_drain_checkpoints_and_resumes_bit_identical(
        tmp_path, clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    events = []
    for ev, payload in orch.events():
        events.append((ev, payload))
        if ev == ExitEvent.BATCH_COMPLETE:
            orch.request_drain()       # what the SIGTERM handler does
    kinds = [e for e, _ in events]
    assert ExitEvent.PREEMPTED in kinds
    assert ExitEvent.CAMPAIGN_COMPLETE not in kinds
    assert orch.preempted and not orch.aborted
    # the drain landed a checkpoint after exactly one batch
    ckpt = events[-1][1]
    assert ckpt and os.path.isdir(ckpt)
    orch2 = Orchestrator.resume(ckpt)
    assert orch2.state[("w0", "regfile")].trials == 64
    _, results = _final_results(orch2)
    _assert_bit_identical(clean_results, results)


def test_signal_handler_requests_drain_then_escalates():
    import signal

    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan())
    restore = orch.install_signal_handlers()
    try:
        handler = signal.getsignal(signal.SIGTERM)
        handler(signal.SIGTERM, None)
        assert orch._drain and not orch.preempted
        with pytest.raises(KeyboardInterrupt):
            handler(signal.SIGTERM, None)      # second signal: escape
    finally:
        restore()


# --- elastic layer ----------------------------------------------------------

def test_lease_board_claim_is_atomic_and_publish_roundtrips(tmp_path):
    a = LeaseBoard(str(tmp_path), "a")
    b = LeaseBoard(str(tmp_path), "b")
    assert a.claim("w0.regfile.0")
    assert not b.claim("w0.regfile.0")          # exactly one winner
    assert a.owner("w0.regfile.0") == "a"
    assert b.done("w0.regfile.0") is None
    b.publish("w0.regfile.0", {"tally": [1, 2], "worker": "b"})
    assert a.done("w0.regfile.0")["tally"] == [1, 2]
    assert a.revoke("w0.regfile.0")
    assert not a.revoke("w0.regfile.0")         # one winner among revokers
    assert b.claim("w0.regfile.0")              # reclaimable after revoke


def test_membership_sees_graceful_leave_and_staleness(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), "alpha", interval=0.05)
    m = Membership(str(tmp_path), timeout=5.0)
    assert not m.alive("alpha")
    hb.beat()
    assert m.alive("alpha") and m.workers() == ["alpha"]
    old = time.time() - 100
    os.utime(hb.path, (old, old))
    assert not m.alive("alpha")                 # stale = lost
    hb.beat()
    hb.stop()
    assert not m.alive("alpha")                 # graceful leave = gone


def test_elastic_single_worker_matches_plain_run(tmp_path, clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    ctx = ElasticContext(str(tmp_path), "solo")
    orch = Orchestrator(_tiny_plan())
    orch.attach_elastic(ctx)
    _, results = _final_results(orch)
    ctx.stop()
    _assert_bit_identical(clean_results, results)
    assert ctx.claimed == 2 and ctx.adopted == 0
    # published documents carry everything adoption needs
    doc = ctx.board.done(ctx.key("w0", "regfile", 0))
    assert doc["worker"] == "solo" and sum(doc["tally"]) == 64
    assert "tier" in doc and "escapes" in doc


def test_elastic_adopts_peer_results_bit_identically(tmp_path,
                                                     clean_results):
    """Worker B joins after worker A published everything: B adopts every
    batch (compute-free) and still lands the identical cumulative state —
    the agreement-without-a-barrier property."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    a = ElasticContext(str(tmp_path), "a")
    oa = Orchestrator(_tiny_plan())
    oa.attach_elastic(a)
    _, ra = _final_results(oa)
    a.stop()
    b = ElasticContext(str(tmp_path), "b")
    ob = Orchestrator(_tiny_plan())
    ob.attach_elastic(b)
    _, rb = _final_results(ob)
    b.stop()
    _assert_bit_identical(clean_results, ra)
    _assert_bit_identical(clean_results, rb)
    assert b.adopted == 2 and b.claimed == 0


def test_elastic_revokes_lost_workers_lease_and_recovers(
        tmp_path, clean_results):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    # a ghost worker claims batch 0, heartbeats once, then "dies"
    ghost = ElasticContext(str(tmp_path), "ghost")
    ghost.heartbeat.beat()
    assert ghost.board.claim(ghost.key("w0", "regfile", 0))
    old = time.time() - 100
    os.utime(ghost.heartbeat.path, (old, old))

    plan = _tiny_plan()
    plan.elastic.heartbeat_timeout = 1.0
    ctx = ElasticContext(str(tmp_path), "survivor", plan.elastic)
    orch = Orchestrator(plan)
    orch.attach_elastic(ctx)
    events, results = _final_results(orch)
    ctx.stop()
    _assert_bit_identical(clean_results, results)
    lost = [p for e, p in events if e == ExitEvent.WORKER_LOST]
    assert len(lost) == 1 and lost[0].worker == "ghost"
    assert "survivor" in lost[0].survivors
    assert ctx.revoked == 1 and ctx.reclaimed == 1
    text = statsmod.dump_text(orch.stats)
    assert "campaign.elastic.leases_revoked" in text
    assert ctx.counters()["workers_lost"] == 1


def test_elastic_refuses_heterogeneous_batch_size_adoption(tmp_path):
    """Workers whose local meshes imply different effective batch sizes
    would lease differently-KEYED batches under the same batch_id —
    adoption must fail loudly, not corrupt the trials accounting."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.parallel.elastic import ElasticError

    ctx = ElasticContext(str(tmp_path), "b")
    # a peer published batch 0 computed under a different batch size
    ctx.board.publish(ctx.key("w0", "regfile", 0), {
        "worker": "a", "batch_id": 0, "batch_size": 72,
        "tally": [72, 0, 0, 0], "strata": None, "tier": 0, "attempts": 1,
        "escapes": 0, "taint_trials": 0})
    orch = Orchestrator(_tiny_plan())
    orch.attach_elastic(ctx)
    with pytest.raises(ElasticError, match="batch_size"):
        list(orch.events())
    ctx.stop()


def test_elastic_retracts_invalid_adopted_result_and_recomputes(
        tmp_path, clean_results):
    """A peer's published result with a VALID checksum but an invalid
    tally (stale/buggy peer build) must be caught at the adoption trust
    boundary, retracted, and recomputed — not absorbed into the AVF."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    ctx = ElasticContext(str(tmp_path), "b")
    orch = Orchestrator(_tiny_plan())
    ctx.board.publish(ctx.key("w0", "regfile", 0), {
        "worker": "evil", "batch_id": 0,
        "batch_size": orch.batch_size,
        "tally": [orch.batch_size, 0, 1, 0],   # sum != batch_size
        "strata": None, "tier": 0, "attempts": 1,
        "escapes": 0, "taint_trials": 0})
    orch.attach_elastic(ctx)
    _, results = _final_results(orch)
    ctx.stop()
    _assert_bit_identical(clean_results, results)
    assert orch.monitor.quarantined == 1
    assert orch.monitor.quarantine_log[0]["kind"] == "adopted"
    # ...and a torn done-doc on disk reads as absent (checksum guard)
    k2 = ctx.key("w0", "regfile", 1)
    path = ctx.board._done(k2)
    assert ctx.board.done(k2) is not None
    tear_file(path)
    assert ctx.board.done(k2) is None


def test_elastic_gives_up_on_live_holders_claim_wait(tmp_path):
    cfg = ElasticConfig(poll_interval=0.01, claim_wait=0.1, lookahead=0)
    holder = ElasticContext(str(tmp_path), "holder", cfg)
    holder.heartbeat.beat()                     # stays "alive"
    assert holder.board.claim("k")
    ctx = ElasticContext(str(tmp_path), "waiter", cfg)
    from shrewd_tpu.parallel.elastic import DrainRequested, ElasticError
    with pytest.raises(ElasticError):
        ctx.obtain("k", lambda: {"tally": []})
    # a drain request while blocked must NOT wait out claim_wait
    with pytest.raises(DrainRequested):
        ctx.obtain("k", lambda: {"tally": []},
                   should_abort=lambda: True)


def test_resume_refuses_mismatched_effective_batch_size(tmp_path):
    """The effective batch size (plan rounded to the mesh) derives the
    batch PRNG keys: resuming on a mesh that rounds differently would
    mix incompatible key streams — resume must refuse, not diverge."""
    import json as jsonmod

    from shrewd_tpu import resilience as resil
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    ckpt = orch.checkpoint()
    path = os.path.join(ckpt, "campaign.json")
    doc = jsonmod.load(open(path))
    assert doc["batch_size"] == orch.batch_size
    doc["batch_size"] = orch.batch_size + 8     # a different mesh's view
    doc["checksum"] = resil.doc_checksum(doc)
    resil.write_json_atomic(path, doc)
    with pytest.raises(ValueError, match="PRNG keys would diverge"):
        Orchestrator.resume(ckpt)


# --- satellite: batch_size auto-round vs mesh size --------------------------

def test_plan_batch_size_rounds_up_to_mesh_multiple():
    import jax

    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.parallel.mesh import (make_mesh, round_up_to_mesh,
                                          shard_keys)
    from shrewd_tpu.utils import prng

    assert round_up_to_mesh(60, 8) == 64
    assert round_up_to_mesh(64, 8) == 64
    assert round_up_to_mesh(1, 8) == 8
    with pytest.raises(ValueError):
        round_up_to_mesh(4, 0)
    with pytest.warns(RuntimeWarning, match="rounded up"):
        orch = Orchestrator(_tiny_plan(batch_size=60, min_trials=64,
                                       max_trials=64))
    assert orch.batch_size == 64
    _, results = _final_results(orch)
    assert results is not None
    assert results[("w0", "regfile")].trials % 64 == 0
    # the explicit low-level contract keeps the hard raise
    mesh = make_mesh(jax.devices())
    with pytest.raises(ValueError, match="not divisible"):
        shard_keys(mesh, prng.trial_keys(prng.campaign_key(0),
                                         mesh.size + 1))


# --- satellite: checkpoint-directory fsync durability -----------------------

def test_write_json_atomic_fsyncs_directory_after_rename(tmp_path,
                                                         monkeypatch):
    import stat as statmod

    from shrewd_tpu import resilience as resil

    calls = []
    real_replace, real_fsync = os.replace, os.fsync

    def spy_replace(src, dst):
        calls.append(("replace", dst))
        return real_replace(src, dst)

    def spy_fsync(fd):
        is_dir = statmod.S_ISDIR(os.fstat(fd).st_mode)
        calls.append(("fsync_dir" if is_dir else "fsync_file", fd))
        return real_fsync(fd)

    monkeypatch.setattr(os, "replace", spy_replace)
    monkeypatch.setattr(os, "fsync", spy_fsync)
    path = str(tmp_path / "doc.json")
    resil.write_json_atomic(path, {"x": 1})
    kinds = [k for k, _ in calls]
    # file fsync BEFORE the rename, directory fsync AFTER it
    assert kinds == ["fsync_file", "replace", "fsync_dir"]


def test_checkpoint_rotation_fsyncs_dir_between_renames(tmp_path,
                                                        monkeypatch):
    from shrewd_tpu import resilience as resil
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(min_trials=64, max_trials=64),
                        outdir=str(tmp_path))
    ckpt = orch.checkpoint()               # first write: no rotation yet
    seq = []
    monkeypatch.setattr(os, "replace",
                        lambda s, d, _r=os.replace: (seq.append("replace"),
                                                     _r(s, d))[1])
    monkeypatch.setattr(resil, "fsync_dir",
                        lambda p, _f=resil.fsync_dir: (seq.append("fsync"),
                                                       _f(p))[1])
    orch.checkpoint()                      # rotation + fresh write
    # rotation rename → dir fsync → tmp rename → dir fsync
    assert seq == ["replace", "fsync", "replace", "fsync"]
    assert os.path.exists(os.path.join(ckpt, "campaign.prev.json"))


# --- satellite: watchdog leaked-thread accounting ---------------------------

def test_watchdog_tracks_and_prunes_leaked_threads():
    import threading

    from shrewd_tpu.resilience import DeviceWatchdog, DispatchTimeout

    w = DeviceWatchdog(timeout=0.05)
    release = threading.Event()
    for _ in range(3):
        with pytest.raises(DispatchTimeout):
            w.call(release.wait, 5.0)
    assert w.leaked_threads == 3 and w.timeouts == 3
    release.set()                          # the wedge "heals"
    deadline = time.monotonic() + 5.0
    while w.leaked_threads and time.monotonic() < deadline:
        time.sleep(0.01)
    assert w.leaked_threads == 0           # accounting prunes dead orphans


def test_watchdog_warns_past_leak_cap():
    import threading

    from shrewd_tpu.resilience import DeviceWatchdog, DispatchTimeout

    w = DeviceWatchdog(timeout=0.02)
    w.leak_warn_cap = 1
    release = threading.Event()
    try:
        with pytest.warns(RuntimeWarning, match="abandoned"):
            for _ in range(3):
                with pytest.raises(DispatchTimeout):
                    w.call(release.wait, 5.0)
    finally:
        release.set()
    assert w.leaked_threads >= 2
