"""Elastic pod pool (shrewd_tpu/federation/autoscale.py + the gateway
pool ledger): journaled scale-up/retire decisions, retire fencing,
pool-level chaos, and the pool-boundary crash sweep.

The contract under test is the ISSUE acceptance criterion: the pool
only ever changes through GL201-certified WAL kinds (``pool_scale_up``
/ ``pool_retire_begin`` / ``pool_retire_done`` journaled BEFORE any
pod is touched), a retiring pod is fenced out of every placement the
instant its retire lands (the journaled retire IS the fence — a hung
retire may keep heartbeating forever and still never win a placement),
retirement drains through the ordinary migration path, and an
autoscaled 3-at-the-floor pool serves the same submissions to
bit-identical tallies as a solo run.  Around that: the ``at_scale``
chaos kinds' trigger-vocab validation and deterministic firing, the
pressure-score control loop's thresholds/cooldown/victim policy, the
WAL-derived obs surfaces (``pool.json`` / ``pool.prom`` / ``GET
/pool``), the cross-pod compile-reuse artifact kind, and the
exhaustive pool-boundary recovery sweep
(``analysis/crashcheck.run_gateway_crashcheck(autoscale=...)``).
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from test_fleet import _plan, _solo_tallies

from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.chaos import ChaosEngine, ChaosPlanError
from shrewd_tpu.federation import (Autoscaler, Federation, Gateway,
                                   GatewayHTTPFront)
from shrewd_tpu.federation.gateway import gateway_journal_path
from shrewd_tpu.obs import metrics as obs_metrics
from shrewd_tpu.service import TenantSpec
from shrewd_tpu.service.journal import FleetJournal


def _spec(name, seed=3, n_batches=4, **kw):
    return TenantSpec(name=name,
                      plan=_plan(seed, n_batches=n_batches).to_dict(),
                      **kw)


def _assert_matches(fed, name, solo):
    got = fed.tenant_tallies(name)
    assert got.keys() == solo.keys()
    for k, t in solo.items():
        np.testing.assert_array_equal(got[k], t)


# --- chaos DSL: pool kinds (jax-free units) ---------------------------------

def test_pool_chaos_kinds_validation():
    # at_scale is the WHOLE trigger vocabulary for the pool kinds: a
    # fault that would silently never fire is a plan error, loudly
    with pytest.raises(ChaosPlanError, match="needs at_scale"):
        ChaosEngine({"faults": [{"kind": "kill_during_retire"}]})
    with pytest.raises(ChaosPlanError, match="needs at_scale"):
        ChaosEngine({"faults": [{"kind": "kill_new_pod"}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_tick'"):
        ChaosEngine({"faults": [
            {"kind": "kill_new_pod", "at_scale": [1], "at_tick": [1]}]})
    with pytest.raises(ChaosPlanError, match="does not take 'at_round'"):
        ChaosEngine({"faults": [
            {"kind": "kill_during_retire", "at_scale": [1],
             "at_round": [2]}]})
    # and at_scale belongs to the pool kinds alone
    with pytest.raises(ChaosPlanError, match="does not take 'at_scale'"):
        ChaosEngine({"faults": [
            {"kind": "kill_pod", "at_tick": [1], "at_scale": [1]}]})


def test_pool_chaos_hooks_fire_deterministically():
    # the trigger coordinate is the gateway's journaled scale ordinal —
    # a WAL append, never a clock: wrong ordinal / wrong pod filter
    # never fire, the right one fires exactly once
    eng = ChaosEngine({"faults": [
        {"kind": "kill_during_retire", "at_scale": [3], "pod": "auto1"},
        {"kind": "kill_new_pod", "at_scale": [2]},
    ]})
    fired = []
    eng.kill_action = lambda rc=None: fired.append(rc)
    eng.maybe_kill_during_retire("auto1", 2)        # wrong ordinal
    eng.maybe_kill_during_retire("pod0", 3)         # wrong pod filter
    assert "kill_during_retire" not in eng.injected
    eng.maybe_kill_during_retire("auto1", 3)
    assert eng.injected == {"kill_during_retire": 1} and len(fired) == 1
    eng.maybe_kill_during_retire("auto1", 3)        # consumed: once
    assert eng.injected == {"kill_during_retire": 1}
    eng.maybe_kill_new_pod("auto2", 1)              # wrong ordinal
    assert "kill_new_pod" not in eng.injected
    eng.maybe_kill_new_pod("auto2", 2)
    assert eng.injected["kill_new_pod"] == 1 and len(fired) == 2


# --- the journaled pool ledger ----------------------------------------------

def test_gateway_pool_ledger_journaled_and_recoverable(tmp_path):
    # every pool transition is a WAL record BEFORE the in-memory pool
    # is trusted, auto pod names derive from the never-reused scale
    # ordinal, and recovery mid-retire reconstructs the exact ledger —
    # scaled pod ports re-derived, fence still up
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1"))
    gw = fed.gateway
    name = gw.pool_scale_up(reason="pressure",
                            pressure={"score": 9000.0}, round=4)
    assert name == "auto1" and gw.scale_seq == 1
    assert gw.scaled_pods == {"auto1": 1}
    assert "auto1" in gw.pods and "auto1" in gw.live_pods()
    recs, _, _ = FleetJournal.replay_path(gateway_journal_path(gw.outdir))
    up = [r for r in recs if r["kind"] == "pool_scale_up"]
    assert len(up) == 1 and up[0]["pod"] == "auto1"
    assert up[0]["scale"] == 1
    assert up[0]["pressure"]["score"] == 9000.0           # auditable
    # the retire consumes the NEXT ordinal off the same sequence
    scale = gw.pool_retire_begin("auto1", reason="idle", round=7)
    assert scale == 2 and gw.scale_seq == 2
    assert "auto1" in gw.retiring and "auto1" not in gw.live_pods()
    st = gw.pool_status()
    assert st["pending_scale_decisions"] == 1 and st["size"] == 3
    assert st["retire_drain_rounds"] == {"auto1": None}   # in flight
    with pytest.raises(ValueError):
        gw.pool_retire_begin("auto1")                     # already retiring
    with pytest.raises(ValueError):
        gw.pool_retire_begin("nope")                      # unknown pod
    # crash here: recovery replays the ledger — pool intact, fence up
    ports = {n: p.port for n, p in fed.pods.items()}
    gw2 = Gateway.recover(gw.outdir, pods=ports)
    assert gw2.scale_seq == 2 and gw2.scaled_pods == {"auto1": 1}
    assert "auto1" in gw2.pods and "auto1" in gw2.retiring
    assert gw2.retires["auto1"]["scale"] == 2
    assert "auto1" not in gw2.live_pods()
    # completion drops the pod; the retire history is durable evidence
    gw2.pool_retire_done("auto1", round=9)
    assert "auto1" not in gw2.pods and not gw2.retiring
    assert gw2.retires["auto1"]["done_round"] == 9
    gw2.pool_retire_done("auto1", round=10)               # idempotent
    assert gw2.retires["auto1"]["done_round"] == 9
    assert gw2.pool_status()["retire_drain_rounds"] == {"auto1": 2}
    # the next scale-up never reuses the ordinal or the name
    assert gw2.pool_scale_up(reason="again") == "auto3"


def test_gateway_refuses_retire_that_empties_pool(tmp_path):
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0",))
    with pytest.raises(RuntimeError, match="no live pod would remain"):
        fed.gateway.pool_retire_begin("pod0")


# --- retire fencing: the lease-expiry race (satellite) ----------------------

def test_retiring_pod_heartbeat_cannot_win_placement(tmp_path):
    # the race the satellite pins: a pod keeps heartbeating AFTER its
    # pool_retire_begin landed (a hung retire holds a fresh lease for a
    # long time) — the journaled retire is the fence, not the lease, so
    # no new admission, pick, or migration may ever land on it
    solo3 = _solo_tallies(_plan(3, n_batches=2))
    solo5 = _solo_tallies(_plan(5, n_batches=2))
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1"))
    gw = fed.gateway
    fed.submit(_spec("t3", 3, n_batches=2))
    victim = gw.entries["t3"].pod
    other = [n for n in ("pod0", "pod1") if n != victim][0]
    gw.pool_retire_begin(victim, reason="test", round=1)
    fed.pods[victim].beat()                       # the lease stays fresh
    assert victim not in gw.live_pods()           # ...the fence holds
    assert gw._pick_pod() == other
    fed.submit(_spec("t5", 5, n_batches=2))       # new admission: fenced
    assert gw.entries["t5"].pod == other
    assert gw.migrate("t5", victim, "test") is False   # no back-migration
    # WAL evidence: the fence was journaled before t5's route decision
    recs, _, _ = FleetJournal.replay_path(gateway_journal_path(gw.outdir))
    kinds = [(r["kind"], r.get("tenant") or r.get("pod")) for r in recs]
    assert kinds.index(("pool_retire_begin", victim)) \
        < kinds.index(("route", "t5"))
    # the drain completes through the ordinary migration path and the
    # whole campaign still folds bit-identically
    assert fed.serve() == 0
    assert fed.retired == 1
    assert gw.retires[victim]["done_round"] is not None
    assert gw.entries["t3"].pod == other
    assert any(h["reason"] == "migrate" and h["pod"] == other
               for h in gw.entries["t3"].history)
    _assert_matches(fed, "t3", solo3)
    _assert_matches(fed, "t5", solo5)


# --- the pressure control loop (jax-free unit) ------------------------------

class _FakeGW:
    """A duck-typed gateway exposing exactly the decision surface the
    Autoscaler reads (live pods + their published loads) and the two
    journaling seams it is allowed to call."""

    def __init__(self, live, scores):
        self._live = list(live)
        self.scores = dict(scores)
        self.entries = {}
        self.retiring = set()
        self.scaled_pods = {n: i + 1 for i, n in enumerate(self._live)
                            if n.startswith("auto")}
        self.ups, self.downs = [], []

    def live_pods(self):
        return sorted(self._live)

    def pod_load(self, name):
        return {"score": self.scores[name]}

    def pool_scale_up(self, reason="", pressure=None, round=None):
        name = f"auto{len(self.ups) + 1}"
        self.ups.append((name, round, pressure))
        self._live.append(name)
        self.scores[name] = 0.0
        self.scaled_pods[name] = len(self.ups)
        return name

    def pool_retire_begin(self, pod, reason="", round=None):
        self.downs.append((pod, round))
        self._live.remove(pod)
        self.retiring.add(pod)
        return 99


def test_autoscaler_thresholds_cooldown_and_victim_policy():
    gw = _FakeGW(["pod0", "pod1"], {"pod0": 9000.0, "pod1": 7000.0})
    auto = Autoscaler(min_pods=1, max_pods=4, up_trials=1000.0,
                      down_trials=100.0, cooldown_rounds=2)
    d = auto.tick(gw, 0)
    assert d["action"] == "scale_up" and d["pod"] == "auto1"
    assert gw.ups[0][2]["score"] == 8000.0        # evidence rides along
    assert auto.tick(gw, 1) is None               # cooldown window
    d = auto.tick(gw, 2)                          # still hot: grow again
    assert d["action"] == "scale_up" and d["pod"] == "auto2"
    gw.scores.update({n: 9000.0 for n in gw.scores})
    assert auto.tick(gw, 4) is None               # at max_pods: capped
    # pressure collapses: the coldest AUTOSCALED pod retires first,
    # even when a static pod is colder — the pool contracts to its
    # static floor before any hand-built pod is considered
    gw.scores.update({"pod0": 0.0, "pod1": 50.0,
                      "auto1": 30.0, "auto2": 10.0})
    d = auto.tick(gw, 6)
    assert d["action"] == "retire" and d["pod"] == "auto2"
    # one retire at a time: the pending drain blocks the next decision
    assert auto.tick(gw, 8) is None
    gw.retiring.clear()
    assert auto.tick(gw, 10)["pod"] == "auto1"
    gw.retiring.clear()
    del gw.scaled_pods["auto1"], gw.scaled_pods["auto2"]
    d = auto.tick(gw, 12)                         # floor-bound: pod0 is
    assert d["pod"] == "pod0"                     # coldest, pool > min
    gw.retiring.clear()
    assert auto.tick(gw, 14) is None              # at min_pods: held


def test_autoscaler_pressure_reads_unplaced_backlog(tmp_path):
    # the backlog signal: accepted-but-unplaced entries add their
    # estimated trials to the score even before any pod publishes load
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0",))
    gw = fed.gateway
    fed.submit(_spec("t3", 3, n_batches=2))
    auto = Autoscaler()
    p = auto.pressure(gw)
    assert p["live"] == 1 and p["unplaced"] == 0
    assert p["score"] > 0          # the placed entry's backlog counts
    e = gw.entries["t3"]
    e.status, e.pod = "accepted", ""        # rewind to pre-route
    p2 = auto.pressure(gw)
    assert p2["unplaced"] == 1 and p2["backlog_trials"] > 0


# --- the elastic pool end-to-end --------------------------------------------

def test_federation_autoscaled_pool_grows_and_contracts(tmp_path):
    # the headline: one static pod, pressure forks the pool out to its
    # cap, convergence drains it back to the floor — every transition
    # journaled, every tenant bit-identical to solo, the obs surface a
    # pure rendering of the WAL-derived ledger
    seeds = (3, 5, 7, 11)
    solo = {s: _solo_tallies(_plan(s, n_batches=2)) for s in seeds}
    root = str(tmp_path / "fed")
    auto = Autoscaler(min_pods=1, max_pods=3, up_trials=64.0,
                      down_trials=16.0, cooldown_rounds=1)
    fed = Federation(root, pod_names=("pod0",), autoscale=auto)
    for s in seeds:
        fed.submit(_spec(f"t{s}", s, n_batches=2))
    assert fed.serve() == 0
    gw = fed.gateway
    assert fed.scale_ups >= 1                  # pressure forked the pool
    assert fed.retired == fed.scale_ups        # ...and it contracted back
    assert sorted(gw.pods) == ["pod0"] and not gw.scaled_pods
    assert not gw.retiring
    st = gw.pool_status()
    assert st["scale_seq"] == fed.scale_ups + fed.retired
    assert st["pending_scale_decisions"] == 0
    # the retire history is durable evidence of the full cycle
    assert len(gw.retires) == fed.retired
    for pod, rec in gw.retires.items():
        assert pod.startswith("auto")
        assert rec["done_round"] is not None
    for s in seeds:
        _assert_matches(fed, f"t{s}", solo[s])
    # the obs pool surface is the WAL-derived ledger, round-fresh
    pool = obs_metrics.read_pool(gw.outdir)
    assert pool["scale_seq"] == st["scale_seq"]
    assert pool["retiring"] == []
    prom = open(os.path.join(gw.outdir, "pool.prom")).read()
    assert f"shrewd_fleet_pool_scale_seq {st['scale_seq']}" in prom


def test_federation_pool_chaos_killed_pods_survived(tmp_path):
    # kill_new_pod fells auto1 the moment the driver first steps it
    # (placements already journaled onto it); kill_during_retire fells
    # the first retiring pod mid-drain — both addressed by the
    # journaled scale ordinal, both survived to bit-identical tallies
    seeds = (3, 5, 7, 11)
    solo = {s: _solo_tallies(_plan(s, n_batches=2)) for s in seeds}
    chaos = ChaosEngine({"faults": [
        {"kind": "kill_new_pod", "at_scale": [1]},
        {"kind": "kill_during_retire", "at_scale": [4]},
    ]})
    auto = Autoscaler(min_pods=1, max_pods=3, up_trials=64.0,
                      down_trials=16.0, cooldown_rounds=1)
    fed = Federation(str(tmp_path / "fed"), pod_names=("pod0",),
                     autoscale=auto, chaos=chaos, expiry_rounds=2)
    for s in seeds:
        fed.submit(_spec(f"t{s}", s, n_batches=2))
    assert fed.serve() == 0
    assert chaos.injected == {"kill_new_pod": 1, "kill_during_retire": 1}
    assert chaos.survived == {"kill_new_pod": 1, "kill_during_retire": 1}
    gw = fed.gateway
    assert sorted(gw.pods) == ["pod0"] and not gw.retiring
    for pod, rec in gw.retires.items():
        assert rec["done_round"] is not None
    for s in seeds:
        _assert_matches(fed, f"t{s}", solo[s])


def test_federation_recover_mid_retire_completes_transition(tmp_path):
    # crash after pool_retire_begin, recover WITHOUT an autoscaler:
    # completing the transition is the driver's job — the journaled
    # ledger alone must drain the pod and land pool_retire_done
    solo = _solo_tallies(_plan(3, n_batches=2))
    root = str(tmp_path / "fed")
    fed = Federation(root, pod_names=("pod0", "pod1"))
    fed.submit(_spec("t3", 3, n_batches=2))
    victim = fed.gateway.entries["t3"].pod
    fed.gateway.pool_retire_begin(victim, reason="test", round=0)
    fed.gateway.checkpoint()                  # durable ledger, then die
    fed2 = Federation.recover(root, pod_names=("pod0", "pod1"))
    assert victim in fed2.gateway.retiring
    assert fed2.serve() == 0
    assert not fed2.gateway.retiring
    assert fed2.gateway.retires[victim]["done_round"] is not None
    assert fed2.gateway.entries["t3"].pod != victim
    _assert_matches(fed2, "t3", solo)


# --- the pool-boundary crash sweep ------------------------------------------

def test_gateway_autoscaled_pool_boundary_crashcheck(tmp_path):
    # the CI gate in miniature: recovery re-executed from EVERY pool
    # WAL append (plain + torn tail), autoscaler detached on recovery,
    # zero divergent recoveries
    pool_kinds = ("pool_scale_up", "pool_retire_begin",
                  "pool_retire_done")
    doc = crashcheck.run_gateway_crashcheck(
        str(tmp_path / "cc"),
        crashcheck.small_fleet_plans(seeds=(3, 5), n_batches=2),
        pod_names=("pod0",),
        autoscale=lambda: Autoscaler(min_pods=1, max_pods=2,
                                     up_trials=64.0, down_trials=16.0,
                                     cooldown_rounds=1),
        point_filter=lambda pt: pt.kind in pool_kinds)
    assert doc["autoscaled"] is True
    assert doc["failures"] == [] and doc["ok"] is True
    for kind in pool_kinds:
        assert doc["boundaries_by_kind"].get(kind, 0) >= 1


# --- obs + HTTP surfaces ----------------------------------------------------

def test_pool_obs_surfaces_roundtrip(tmp_path):
    pool = {"size": 3, "live": 2, "retiring": ["auto1"],
            "pending_scale_decisions": 1, "scale_seq": 3,
            "scaled_pods": {"auto1": 1},
            "retire_drain_rounds": {"auto1": None, "auto2": 2}}
    obs_metrics.publish_pool(str(tmp_path), pool)
    assert obs_metrics.read_pool(str(tmp_path)) == pool
    text = (tmp_path / "pool.prom").read_text()
    assert "shrewd_fleet_pool_size 3" in text
    assert "shrewd_fleet_pool_live 2" in text
    assert "shrewd_fleet_pool_pending_scale_decisions 1" in text
    assert "shrewd_fleet_pool_scale_seq 3" in text
    assert 'shrewd_fleet_pool_retire_drain_rounds{pod="auto2"} 2' in text
    # an in-flight drain has no duration yet: no gauge, not a NaN
    assert 'pod="auto1"' not in text


def test_http_front_pool_endpoint(tmp_path):
    gw_dir = str(tmp_path / "gateway")
    front = GatewayHTTPFront(gw_dir, port=0).start()
    try:
        base = f"http://127.0.0.1:{front.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/pool", timeout=10)
        assert ei.value.code == 404               # no surface published
        obs_metrics.publish_pool(gw_dir, {"size": 2, "live": 2,
                                          "scale_seq": 1})
        with urllib.request.urlopen(f"{base}/pool", timeout=10) as r:
            doc = json.load(r)
        assert doc["size"] == 2 and doc["scale_seq"] == 1
    finally:
        front.stop()


# --- cross-pod compile reuse (satellite) ------------------------------------

def test_store_exec_dir_is_an_artifact_kind(tmp_path):
    from shrewd_tpu.ingest.store import ArtifactStore
    st = ArtifactStore(str(tmp_path / "store"))
    d = st.exec_dir()
    assert os.path.isdir(d)
    assert d == os.path.join(st.root, "exec")
    assert st.exec_dir() == d                     # idempotent


def test_scheduler_enables_cross_pod_compile_cache(tmp_path):
    # a store-backed scheduler points jax's persistent compilation
    # cache at the store's exec/ kind — one digest-keyed cache root
    # shared by every pod of the federation
    from shrewd_tpu.service.scheduler import CampaignScheduler
    sched = CampaignScheduler(outdir=str(tmp_path / "pod"),
                              store_dir=str(tmp_path / "store"))
    _ = sched.mesh
    import jax
    assert jax.config.jax_compilation_cache_dir \
        == os.path.join(str(tmp_path / "store"), "exec")
