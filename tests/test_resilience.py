"""Backend resilience layer (shrewd_tpu/resilience.py + orchestrator wiring).

The contract under test is the ISSUE acceptance criterion: a campaign with
injected backend faults (wedged dispatch, dispatch timeout, kill
mid-checkpoint) completes via the degradation ladder and, after resume,
produces tallies bit-identical to an uninterrupted run — with every trial's
execution tier accounted for and the escalation budget enforced.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from shrewd_tpu import resilience as resil
from shrewd_tpu.resilience import (BackendError, BackoffPolicy,
                                   DeviceWatchdog, DispatchTimeout,
                                   EscalationBudget, LadderExhausted,
                                   ReprobeQueue, ResilienceConfig,
                                   ResilientDispatcher, TIER_CPU,
                                   TIER_DEVICE, TIER_ORACLE, TIERS)


# --- backoff -----------------------------------------------------------------

def test_backoff_exponential_and_capped():
    p = BackoffPolicy(base=0.1, cap=1.0, jitter=0.0)
    assert p.delay(0) == pytest.approx(0.1)
    assert p.delay(1) == pytest.approx(0.2)
    assert p.delay(2) == pytest.approx(0.4)
    assert p.delay(10) == pytest.approx(1.0)    # capped


def test_backoff_jitter_bounded_and_sleeper_injectable():
    slept = []
    p = BackoffPolicy(base=0.2, cap=5.0, jitter=0.5, seed=1,
                      sleeper=slept.append)
    for a in range(20):
        d = p.delay(0)
        assert 0.1 <= d <= 0.3                  # ±50% around base
    p.sleep(0)
    assert len(slept) == 1                      # never wall-waited


# --- watchdog ----------------------------------------------------------------

def test_watchdog_passes_fast_calls_and_counts():
    w = DeviceWatchdog(timeout=5.0)
    assert w.call(lambda a, b: a + b, 2, 3) == 5
    assert w.dispatches == 1 and w.timeouts == 0 and w.healthy


def test_watchdog_zero_timeout_runs_in_caller_thread():
    w = DeviceWatchdog(timeout=0.0)
    assert w.call(threading.get_ident) == threading.get_ident()


def test_watchdog_times_out_wedged_dispatch_then_recovers():
    w = DeviceWatchdog(timeout=0.1)
    with pytest.raises(DispatchTimeout):
        w.call(time.sleep, 10.0)
    assert not w.healthy and w.timeouts == 1
    # the wedged thread is orphaned: the next dispatch gets a fresh one
    assert w.call(lambda: 42) == 42
    assert w.healthy


def test_watchdog_wedged_thread_is_daemon():
    # a ThreadPoolExecutor worker would be non-daemon and joined by the
    # concurrent.futures atexit hook — a wedged dispatch would then block
    # interpreter exit forever; the watchdog must leave only daemon threads
    w = DeviceWatchdog(timeout=0.05)
    with pytest.raises(DispatchTimeout):
        w.call(time.sleep, 3.0)
    stuck = [t for t in threading.enumerate()
             if t.name.startswith("watchdog-device")]
    assert stuck and all(t.daemon for t in stuck)


def test_watchdog_propagates_exceptions_unchanged():
    w = DeviceWatchdog(timeout=5.0)
    with pytest.raises(ZeroDivisionError):
        w.call(lambda: 1 // 0)


def test_watchdog_probe_verdicts():
    w = DeviceWatchdog(timeout=1.0)
    assert w.probe(lambda: None)
    assert not w.probe(lambda: (_ for _ in ()).throw(RuntimeError("down")))
    assert not w.healthy


# --- re-probe queue ----------------------------------------------------------

def test_reprobe_queue_fires_deferred_at_first_healthy_window():
    verdicts = [False, False, True]
    fired = []
    q = ReprobeQueue(lambda: verdicts.pop(0),
                     backoff=BackoffPolicy(base=0.01, jitter=0.0))
    q.defer(lambda: fired.append("a"))
    q.start()
    assert q.wait(5.0)
    q.stop()
    assert fired == ["a"]
    assert q.probes == 3                        # exactly at first healthy


def test_reprobe_defer_when_already_healthy_runs_immediately():
    q = ReprobeQueue(lambda: True,
                     backoff=BackoffPolicy(base=0.01, jitter=0.0)).start()
    assert q.wait(5.0)
    fired = []
    q.defer(lambda: fired.append(1))
    q.stop()
    assert fired == [1]


def test_reprobe_probe_exception_counts_as_unhealthy():
    calls = []

    def probe():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("tunnel reset")
        return True

    q = ReprobeQueue(probe, backoff=BackoffPolicy(base=0.01, jitter=0.0))
    q.start()
    assert q.wait(5.0)
    q.stop()
    assert len(calls) == 2


# --- escalation budget -------------------------------------------------------

def test_escalation_budget_accounting():
    b = EscalationBudget()
    b.record(TIER_DEVICE, 900)
    b.record(TIER_CPU, 64)
    b.record(TIER_ORACLE, 36)
    assert b.total == 1000 and b.escalated == 100
    assert b.rate() == pytest.approx(0.1)
    assert b.over(0.05) and not b.over(0.15)
    d = b.to_dict()
    assert d["tier_trials"] == {"device": 900, "cpu": 64, "oracle": 36}


def test_escalation_budget_empty_is_not_over():
    assert not EscalationBudget().over(0.0)


def test_escalation_budget_from_states():
    b = EscalationBudget.from_states([[10, 2, 0], [5, 0, 3]])
    assert b.total == 20 and b.escalated == 5


# --- dispatcher ladder (fake tiers: mechanism, not kernels) ------------------

def _tally_of(keys):
    """Deterministic stand-in kernel: a pure function of the keys."""
    return np.bincount(np.asarray(keys, dtype=np.int64).ravel() % 4,
                       minlength=4)


def _fast_cfg(**kw):
    cfg = ResilienceConfig()
    cfg.backoff_base = 0.0
    cfg.backoff_max = 0.0
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def test_dispatcher_retries_then_succeeds_on_same_tier():
    keys = np.arange(16)
    calls = []

    def flaky(k, stratified):
        calls.append(1)
        if len(calls) == 1:
            raise BackendError("transient")
        return _tally_of(k), None

    d = ResilientDispatcher([(TIER_DEVICE, flaky)],
                            _fast_cfg(max_retries=2))
    res = d.tally_batch(keys)
    assert res.tier == TIER_DEVICE and res.attempts == 2
    assert d.retries == 1 and d.degradations == 0
    np.testing.assert_array_equal(res.tally, _tally_of(keys))


def test_dispatcher_degrades_with_bit_identical_tally():
    keys = np.arange(32)

    def wedged(k, stratified):
        raise BackendError("injected wedge")

    d = ResilientDispatcher(
        [(TIER_DEVICE, wedged), (TIER_CPU, lambda k, s: (_tally_of(k), None))],
        _fast_cfg(max_retries=1))
    res = d.tally_batch(keys)
    assert res.tier == TIER_CPU
    assert d.degradations == 1
    np.testing.assert_array_equal(res.tally, _tally_of(keys))


def test_dispatcher_watchdog_timeout_triggers_degradation():
    keys = np.arange(8)

    def wedged(k, stratified):
        time.sleep(10.0)

    d = ResilientDispatcher(
        [(TIER_DEVICE, wedged), (TIER_CPU, lambda k, s: (_tally_of(k), None))],
        _fast_cfg(max_retries=0, dispatch_timeout=0.1))
    res = d.tally_batch(keys)
    assert res.tier == TIER_CPU
    assert d.watchdog.timeouts == 1


def test_dispatcher_ladder_exhausted_raises():
    def wedged(k, stratified):
        raise BackendError("down")

    d = ResilientDispatcher([(TIER_DEVICE, wedged), (TIER_CPU, wedged)],
                            _fast_cfg(max_retries=0))
    with pytest.raises(LadderExhausted):
        d.tally_batch(np.arange(4))


# --- crash-safe document IO --------------------------------------------------

def test_atomic_write_and_verified_load_roundtrip(tmp_path):
    path = str(tmp_path / "doc.json")
    doc = {"version": 4, "state": {"a": [1, 2, 3]}}
    doc["checksum"] = resil.doc_checksum(doc)
    resil.write_json_atomic(path, doc)
    assert resil.load_json_verified(path) == doc
    assert not os.path.exists(path + ".tmp")


def test_verified_load_rejects_truncation_and_tampering(tmp_path):
    path = str(tmp_path / "doc.json")
    doc = {"version": 4, "state": {"a": 1}}
    doc["checksum"] = resil.doc_checksum(doc)
    resil.write_json_atomic(path, doc)
    blob = open(path).read()
    # truncation (the kill-mid-write shape)
    with open(path, "w") as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(ValueError, match="truncated|corrupt"):
        resil.load_json_verified(path)
    # valid JSON, tampered content
    doc["state"]["a"] = 2
    with open(path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match="checksum"):
        resil.load_json_verified(path)


def test_checksum_ignores_key_order():
    a = {"x": 1, "y": [1, 2]}
    b = {"y": [1, 2], "x": 1}
    assert resil.doc_checksum(a) == resil.doc_checksum(b)


# --- orchestrator integration ------------------------------------------------

def _tiny_plan(**kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    defaults = dict(structures=["regfile"], batch_size=64,
                    target_halfwidth=0.2, confidence=0.95,
                    max_trials=128, min_trials=64)
    defaults.update(kw)
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        **defaults)
    # canaries/audit off: these tests target the resilience ladder, and
    # the integrity layer's per-campaign canary/audit compiles would only
    # slow the failure-path smoke (tests/test_integrity.py owns that
    # coverage; the free tally invariants stay on)
    plan.integrity.canary_trials = 0
    plan.integrity.audit_rate = 0.0
    return plan


def _final_results(orch):
    from shrewd_tpu.sim.exit_event import ExitEvent

    events = list(orch.events())
    return events, dict(events[-1][1]) if (
        events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE) else None


def _wedge_device_tier(monkeypatch, fail=lambda calls: True):
    """Patch the ladder builder so the device tier raises BackendError
    whenever ``fail(call_number)`` is true, falling back to the REAL
    dispatch labeled as the cpu tier — the injected-wedge harness."""
    real_builder = resil.dispatcher_for_campaign
    calls = [0]

    def patched(campaign, cfg=None, watchdog=None, chaos=None):
        real_fn = resil._device_tier(campaign)

        def wedgy(keys, stratified):
            calls[0] += 1
            if fail(calls[0]):
                raise BackendError("injected wedge")
            return real_fn(keys, stratified)

        cfg = cfg if cfg is not None else ResilienceConfig()
        return ResilientDispatcher(
            [(TIER_DEVICE, wedgy), (TIER_CPU, real_fn)], cfg,
            watchdog=watchdog, chaos=chaos)

    monkeypatch.setattr(resil, "dispatcher_for_campaign", patched)
    return real_builder


def test_injected_wedge_degrades_and_tallies_bit_identical(monkeypatch):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    # healthy reference run
    _, clean = _final_results(Orchestrator(_tiny_plan()))
    assert clean is not None

    # every device dispatch wedges → every batch degrades one tier
    _wedge_device_tier(monkeypatch)
    plan = _tiny_plan()
    plan.resilience.max_retries = 0
    plan.resilience.backoff_base = 0.0
    plan.resilience.escalation_threshold = 0.25
    orch = Orchestrator(plan)
    events, results = _final_results(orch)
    assert results is not None
    kinds = [e for e, _ in events]
    assert ExitEvent.BACKEND_DEGRADED in kinds
    assert ExitEvent.ESCALATION_EXCEEDED in kinds    # action=warn continues
    # bit-identity: same frozen keys on the fallback tier → same tallies
    for k in clean:
        np.testing.assert_array_equal(clean[k].tallies, results[k].tallies)
    # every trial accounted to the cpu tier
    assert orch.budget.rate() == pytest.approx(1.0)
    assert orch.budget.counts[TIER_CPU] == orch.budget.total
    st = orch.state[("w0", "regfile")]
    assert int(st.tier_trials[TIER_CPU]) == st.trials


def test_transient_wedge_retries_on_device_tier(monkeypatch):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    # only the first dispatch fails → retry keeps everything on-device
    _wedge_device_tier(monkeypatch, fail=lambda n: n == 1)
    plan = _tiny_plan()
    plan.resilience.max_retries = 2
    plan.resilience.backoff_base = 0.0
    orch = Orchestrator(plan)
    _, results = _final_results(orch)
    assert results is not None
    assert orch.budget.escalated == 0
    assert orch.budget.counts[TIER_DEVICE] == orch.budget.total


def test_escalation_budget_abort_leaves_resumable_checkpoint(
        monkeypatch, tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    _wedge_device_tier(monkeypatch)
    plan = _tiny_plan()
    plan.resilience.max_retries = 0
    plan.resilience.backoff_base = 0.0
    plan.resilience.escalation_threshold = 0.01
    plan.resilience.escalation_action = "abort"
    orch = Orchestrator(plan, outdir=str(tmp_path))
    events = list(orch.events())
    kinds = [e for e, _ in events]
    assert orch.aborted
    assert ExitEvent.ESCALATION_EXCEEDED in kinds
    assert ExitEvent.CAMPAIGN_COMPLETE not in kinds   # never claims success
    # the abort checkpoint is resumable and carries the tier ledger
    ckpt = os.path.join(str(tmp_path), "campaign_ckpt")
    orch2 = Orchestrator.resume(ckpt)
    assert orch2.budget.escalated > 0
    st = orch2.state[("w0", "regfile")]
    assert st.trials > 0 and int(st.tier_trials.sum()) == st.trials


def test_escalation_abort_resume_rearms_not_relitigates(
        monkeypatch, tmp_path):
    """Resuming a budget-aborted run must not re-abort on frozen history:
    while the backend is still wedged (rate not improving) it re-aborts,
    but once the backend heals the restored rate only falls and the run
    completes — the 'resumable' promise of escalation_action=abort."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    # 3 cap-limited batches: abort #1 leaves work for the wedged resume,
    # which leaves a real device batch for the healed resume to run
    knobs = dict(target_halfwidth=0.001, max_trials=192)
    _, clean = _final_results(Orchestrator(_tiny_plan(**knobs)))

    real_builder = _wedge_device_tier(monkeypatch)
    plan = _tiny_plan(**knobs)
    plan.resilience.max_retries = 0
    plan.resilience.backoff_base = 0.0
    plan.resilience.escalation_threshold = 0.01
    plan.resilience.escalation_action = "abort"
    orch = Orchestrator(plan, outdir=str(tmp_path))
    list(orch.events())
    assert orch.aborted
    ckpt = os.path.join(str(tmp_path), "campaign_ckpt")

    # still wedged: escalation keeps pace with history → re-abort
    orch2 = Orchestrator.resume(ckpt, outdir=str(tmp_path))
    kinds2 = [e for e, _ in orch2.events()]
    assert orch2.aborted
    assert ExitEvent.ESCALATION_EXCEEDED in kinds2

    # healed: restored rate is the baseline, device-only batches only
    # lower it → the gate stays quiet and the campaign completes
    monkeypatch.setattr(resil, "dispatcher_for_campaign", real_builder)
    orch3 = Orchestrator.resume(ckpt, outdir=str(tmp_path))
    events3 = list(orch3.events())
    kinds3 = [e for e, _ in events3]
    assert not orch3.aborted
    assert ExitEvent.CAMPAIGN_COMPLETE in kinds3
    assert ExitEvent.ESCALATION_EXCEEDED not in kinds3
    results = dict(events3[-1][1])
    for k in clean:
        np.testing.assert_array_equal(clean[k].tallies, results[k].tallies)


def test_resume_from_truncated_checkpoint_uses_previous_valid(tmp_path):
    """Kill-mid-checkpoint: the torn campaign.json is detected (checksum)
    and resume falls back to campaign.prev.json; the finished campaign is
    bit-identical to an uninterrupted run (skipped batches re-run from
    their PRNG coordinates)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    # force 3 batches (uncapped halfwidth would converge after one, and a
    # single checkpoint never rotates a .prev to fall back on)
    knobs = dict(target_halfwidth=0.001, max_trials=192)
    _, clean = _final_results(Orchestrator(_tiny_plan(**knobs)))

    plan = _tiny_plan(checkpoint_every=1, **knobs)
    orch = Orchestrator(plan, outdir=str(tmp_path))
    ckpts = 0
    ckpt_dir = None
    for ev, payload in orch.events():
        if ev is ExitEvent.CHECKPOINT:
            ckpts += 1
            ckpt_dir = payload
            if ckpts == 2:      # both campaign.json and .prev.json exist
                break
    assert ckpt_dir is not None
    latest = os.path.join(ckpt_dir, "campaign.json")
    prev = os.path.join(ckpt_dir, "campaign.prev.json")
    assert os.path.exists(latest) and os.path.exists(prev)
    # tear the latest checkpoint mid-write
    blob = open(latest).read()
    with open(latest, "w") as f:
        f.write(blob[:len(blob) // 3])

    orch2 = Orchestrator.resume(ckpt_dir)
    # fell back one checkpoint: some progress restored, not all lost
    assert any(st.trials > 0 for st in orch2.state.values())
    _, resumed = _final_results(orch2)
    assert resumed is not None
    for k in clean:
        np.testing.assert_array_equal(clean[k].tallies, resumed[k].tallies)
        assert clean[k].trials == resumed[k].trials


def test_resume_with_no_valid_checkpoint_raises(tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    ckpt = orch.checkpoint()
    for name in ("campaign.json", "campaign.prev.json"):
        path = os.path.join(ckpt, name)
        if os.path.exists(path):
            with open(path, "w") as f:
                f.write("{ torn")
    with pytest.raises(ValueError, match="no valid campaign checkpoint"):
        Orchestrator.resume(ckpt)


def test_checkpoint_format_and_v3_upgrade(tmp_path):
    from shrewd_tpu.campaign.orchestrator import (CKPT_VERSION, Orchestrator,
                                                  upgrade_checkpoint)

    orch = Orchestrator(_tiny_plan(), outdir=str(tmp_path))
    list(orch.events())
    ckpt = orch.checkpoint()
    doc = resil.load_json_verified(os.path.join(ckpt, "campaign.json"))
    assert doc["version"] == CKPT_VERSION == 5
    assert doc["checksum"] == resil.doc_checksum(doc)
    assert "integrity" in doc                     # v5: monitor state rides
    for per_s in doc["state"].values():
        for st_doc in per_s.values():
            assert len(st_doc["tier_trials"]) == len(TIERS)

    # a v3-era document (no tier provenance, no integrity state) upgrades
    # to zeroed ledgers — old trials must NOT be attributed to the device
    # tier, and pre-v5 history must read as unaudited
    for per_s in doc["state"].values():
        for st_doc in per_s.values():
            del st_doc["tier_trials"]
    del doc["integrity"]
    doc["version"] = 3
    upgrade_checkpoint(doc)
    assert doc["version"] == 5
    assert doc["integrity"] is None
    for per_s in doc["state"].values():
        for st_doc in per_s.values():
            assert st_doc["tier_trials"] == [0] * len(TIERS)


def test_stats_report_tier_vector_and_escalation(monkeypatch, tmp_path):
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    _wedge_device_tier(monkeypatch)
    plan = _tiny_plan()
    plan.resilience.max_retries = 0
    plan.resilience.backoff_base = 0.0
    orch = Orchestrator(plan, outdir=str(tmp_path))
    _, results = _final_results(orch)
    assert results is not None
    orch.write_outputs()
    text = (tmp_path / "stats.txt").read_text()
    assert "tier_trials" in text
    assert "escalation_rate" in text


# --- real-ladder construction ------------------------------------------------

def test_dispatcher_for_campaign_cpu_mesh_skips_cpu_tier():
    """On a cpu mesh the ladder is device(+oracle) — re-dispatching to the
    same platform is pointless; the oracle tier joins when the native
    golden kernel covers the structure."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    t = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                working_set_words=32, seed=7))
    camp = ShardedCampaign(TrialKernel(t, O3Config()), make_mesh(),
                           "regfile")
    d = resil.dispatcher_for_campaign(camp)
    tiers = [t for t, _ in d.tiers]
    assert tiers[0] == TIER_DEVICE
    assert TIER_CPU not in tiers
    assert resil.oracle_available(camp) == (TIER_ORACLE in tiers)


def test_oracle_tier_bit_identical_to_device():
    """The acceptance-criterion core, on the REAL ladder: the host-oracle
    tier classifies the same frozen keys to the same tally as the device
    dispatch (the CheckerCPU-parity contract, tests/test_native_diff.py)."""
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate
    from shrewd_tpu.utils import prng

    t = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                working_set_words=32, seed=7))
    camp = ShardedCampaign(TrialKernel(t, O3Config()), make_mesh(),
                           "regfile")
    if not resil.oracle_available(camp):
        pytest.skip("native golden kernel not available")
    keys = prng.trial_keys(prng.campaign_key(0), 64)
    dev = np.asarray(camp.tally_batch(keys))

    def wedged(k, stratified):
        raise BackendError("injected wedge")

    d = ResilientDispatcher(
        [(TIER_DEVICE, wedged),
         (TIER_ORACLE, resil._oracle_tier(camp))],
        _fast_cfg(max_retries=0))
    res = d.tally_batch(keys)
    assert res.tier == TIER_ORACLE
    np.testing.assert_array_equal(res.tally, dev)


def _mini_campaign(stratify=False):
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel
    from shrewd_tpu.parallel.campaign import ShardedCampaign
    from shrewd_tpu.parallel.mesh import make_mesh
    from shrewd_tpu.trace.synth import WorkloadConfig, generate

    t = generate(WorkloadConfig(n=96, nphys=32, mem_words=64,
                                working_set_words=32, seed=7))
    return ShardedCampaign(TrialKernel(t, O3Config()), make_mesh(),
                           "regfile", stratify=stratify)


def test_device_tier_wraps_crashing_backend_into_ladder(monkeypatch):
    """A backend that CRASHES (device lost / runtime aborted) — not just
    wedges — must engage the ladder too: generic device-tier exceptions
    become BackendError and degrade."""
    from shrewd_tpu.utils import prng

    camp = _mini_campaign()
    if not resil.oracle_available(camp):
        pytest.skip("native golden kernel not available")
    keys = prng.trial_keys(prng.campaign_key(0), 64)
    want = np.asarray(camp.tally_batch(keys))
    monkeypatch.setattr(
        camp, "tally_batch",
        lambda k: (_ for _ in ()).throw(RuntimeError("device lost")))
    d = resil.dispatcher_for_campaign(camp, _fast_cfg(max_retries=0))
    res = d.tally_batch(keys)
    assert res.tier == TIER_ORACLE
    np.testing.assert_array_equal(res.tally, want)


def test_orchestrator_campaign_shares_watchdog():
    """The per-step deadline lives INSIDE the campaign (around only the
    pure jitted step — no host counter mutation can come from an orphaned
    late dispatch), and the dispatcher then must not stack a second
    deadline around the same call."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    orch = Orchestrator(_tiny_plan())
    camp = orch.campaign(0, "regfile")
    assert camp.watchdog is orch.watchdog
    assert orch.dispatcher(0, "regfile").device_deadline is False


def test_run_until_ci_with_dispatcher_degrades_bit_identical():
    """The standalone driver loop (parallel.campaign.run_until_ci) carries
    the same ladder contract: flaky device tier → fallback on the same
    frozen keys → bit-identical tallies, per-tier counts in the result."""
    from shrewd_tpu.parallel.campaign import run_until_ci

    camp = _mini_campaign()
    knobs = dict(seed=3, simpoint_id=0, structure_id=0, batch_size=64,
                 target_halfwidth=1e-9, max_trials=128, min_trials=64)
    plain = run_until_ci(camp, **knobs)

    real_fn = resil._device_tier(camp)
    calls = [0]

    def flaky(keys, stratified):
        calls[0] += 1
        if calls[0] == 1:
            raise BackendError("injected wedge")
        return real_fn(keys, stratified)

    d = ResilientDispatcher([(TIER_DEVICE, flaky), (TIER_CPU, real_fn)],
                            _fast_cfg(max_retries=0))
    res = run_until_ci(camp, dispatcher=d, **knobs)
    np.testing.assert_array_equal(res.tallies, plain.tallies)
    assert res.tier_trials is not None
    assert int(res.tier_trials.sum()) == res.trials
    assert res.tier_trials[TIER_CPU] > 0          # first batch degraded
    assert 0.0 < res.escalation_rate <= 1.0


# --- standalone probe tool ---------------------------------------------------

def test_backend_probe_cpu_healthy():
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "backend_probe.py"),
         "--platform", "cpu", "--timeout", "120"],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-500:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True
    assert verdict["platform"] == "cpu"
