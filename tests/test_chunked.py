"""Chunked hierarchical replay (ops/chunked.py).

The contract is exact outcome parity with the dense full-window kernel —
chunking is an execution strategy, not an approximation — plus the
boundary/carry machinery working across chunk counts, padding, and
batch-overflow waves."""

import jax
import numpy as np
import pytest

from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.chunked import ChunkedCampaign
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def mk_kernel(n=384, seed=11, **cfg):
    t = generate(WorkloadConfig(n=n, nphys=32, mem_words=64,
                                working_set_words=32, seed=seed))
    return TrialKernel(t, O3Config(**cfg))


def dense_outcomes(kernel, keys, structure):
    return np.asarray(kernel.outcomes_from_keys(keys, structure))


@pytest.mark.parametrize("structure", ["regfile", "fu", "rob", "iq", "lsq"])
def test_outcomes_match_dense_kernel(structure):
    kernel = mk_kernel()
    keys = prng.trial_keys(prng.campaign_key(21), 96)
    dense = dense_outcomes(kernel, keys, structure)
    ch = ChunkedCampaign(kernel, chunk=128)     # 3 chunks
    np.testing.assert_array_equal(
        ch.outcomes_from_keys(keys, structure), dense, err_msg=structure)


def test_golden_boundaries_end_at_golden_final():
    kernel = mk_kernel()
    ch = ChunkedCampaign(kernel, chunk=100)     # padding: 384 = 3*100+84
    np.testing.assert_array_equal(ch.gb_reg[ch.C],
                                  np.asarray(kernel.golden.reg))
    np.testing.assert_array_equal(ch.gb_mem[ch.C],
                                  np.asarray(kernel.golden.mem))


def test_padding_chunk_parity():
    # chunk length that does NOT divide n: NOP padding must not perturb
    # outcomes (NOP writes nothing, accesses nothing)
    kernel = mk_kernel(n=300)
    keys = prng.trial_keys(prng.campaign_key(5), 64)
    dense = dense_outcomes(kernel, keys, "regfile")
    ch = ChunkedCampaign(kernel, chunk=77)
    np.testing.assert_array_equal(
        ch.outcomes_from_keys(keys, "regfile"), dense)


def test_small_batch_forces_waves_and_carry_overflow():
    # B=8 with 96 trials over 2 chunks: many waves per chunk; survivors
    # can exceed one batch — exercises the carry-slice path
    kernel = mk_kernel()
    keys = prng.trial_keys(prng.campaign_key(9), 96)
    dense = dense_outcomes(kernel, keys, "regfile")
    ch = ChunkedCampaign(kernel, chunk=192, max_batch=8)
    np.testing.assert_array_equal(
        ch.outcomes_from_keys(keys, "regfile"), dense)


def test_single_chunk_degenerates_to_dense():
    kernel = mk_kernel(n=128)
    keys = prng.trial_keys(prng.campaign_key(3), 48)
    ch = ChunkedCampaign(kernel, chunk=4096)    # C == 1
    assert ch.C == 1
    np.testing.assert_array_equal(
        ch.outcomes_from_keys(keys, "fu"),
        dense_outcomes(kernel, keys, "fu"))


def test_tally_matches_outcomes():
    kernel = mk_kernel()
    keys = prng.trial_keys(prng.campaign_key(7), 64)
    ch = ChunkedCampaign(kernel, chunk=128)
    out = ch.outcomes_from_keys(keys, "regfile")
    tally = ch.run_keys(keys, "regfile")
    assert tally.sum() == 64
    for k in range(C.N_OUTCOMES):
        assert tally[k] == int((out == k).sum())


def test_shadow_detection_survives_chunking():
    kernel = mk_kernel(shadow_coverage=[1.0, 1.0, 0.0, 0.0, 0.0, 1.0, 1.0])
    keys = prng.trial_keys(prng.campaign_key(13), 96)
    dense = dense_outcomes(kernel, keys, "fu")
    ch = ChunkedCampaign(kernel, chunk=96)
    got = ch.outcomes_from_keys(keys, "fu")
    np.testing.assert_array_equal(got, dense)
    assert (got == C.OUTCOME_DETECTED).any()


def test_latch_structure_parity_with_padding():
    """Latch faults with a chunk length that does NOT divide n: latch
    entry coordinates can land out-of-window (sentinel entries < 0 or in
    [n, n+n_latches)), where the padded chunk stream used to replay them
    onto NOP padding and misclassify — they must resolve MASKED, matching
    the dense kernel by construction."""
    kernel = mk_kernel(n=300)
    keys = prng.trial_keys(prng.campaign_key(21), 96)
    dense = dense_outcomes(kernel, keys, "latch")
    ch = ChunkedCampaign(kernel, chunk=77)      # 300 = 3*77 + 69
    np.testing.assert_array_equal(
        ch.outcomes_from_keys(keys, "latch"), dense)
    # the out-of-window resolver actually fired on this sample and those
    # trials are all masked (never replayed onto padding)
    assert ch.last_stats["oow_masked"] > 0


@pytest.mark.slow
def test_lifted_window_parity():
    """Real lifted window (sort.c) with the VA-space memmap: chunked
    outcomes equal dense outcomes on every structure."""
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools("workloads/sort.c")
    trace, meta = hd.capture_and_lift(paths)
    kernel = TrialKernel(trace, O3Config(),
                         memmap=hd.memmap_from_meta(meta))
    keys = prng.trial_keys(prng.campaign_key(31), 64)
    ch = ChunkedCampaign(kernel, chunk=1024)
    for structure in ("regfile", "fu", "lsq"):
        np.testing.assert_array_equal(
            ch.outcomes_from_keys(keys, structure),
            dense_outcomes(kernel, keys, structure), err_msg=structure)


def test_carry_horizon_is_conservative_and_bounded():
    """carry_horizon classifies long-divergent trials SDC without full
    replay: outcomes may differ from exact ONLY as masked→SDC (the
    conservative direction), and on this window they do not differ at
    all (divergent state never re-converges past the overwrite
    horizon)."""
    kernel = mk_kernel(n=512, seed=17)
    keys = prng.trial_keys(prng.campaign_key(23), 128)
    exact = ChunkedCampaign(kernel, chunk=64)
    oe = exact.outcomes_from_keys(keys, "regfile")
    fast = ChunkedCampaign(kernel, chunk=64, carry_horizon=1)
    of = fast.outcomes_from_keys(keys, "regfile")
    diff = oe != of
    # a horizon cut can only relabel a long-carried trial: would-be
    # masked (late reconvergence) or would-be DUE (trap further down
    # the window) become SDC; detected/frozen classes are untouched and
    # the vulnerable set (SDC+DUE) never shrinks
    assert np.isin(oe[diff], [C.OUTCOME_MASKED, C.OUTCOME_DUE]).all()
    assert (of[diff] == C.OUTCOME_SDC).all()
    vuln = lambda o: ((o == C.OUTCOME_SDC) | (o == C.OUTCOME_DUE)).sum()
    assert vuln(of) >= vuln(oe)
    # the fast path genuinely cut work
    assert fast.last_stats["horizon_sdc"] >= int(diff.sum())
    assert fast.last_stats["horizon_sdc"] > 0

