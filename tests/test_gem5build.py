"""Tests for the scons-less reference-build harness (gem5build/).

The mini-m4 is the riskiest piece (hand-written macro processor feeding
libelf's generated C), so its classic-m4 semantics are pinned here:
expansion during argument collection, recursion via shift($@), quoting,
dnl, and the define-inside-define idiom libelf uses.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "gem5build"))

from mini_m4 import M4, m4_expand  # noqa: E402

REF = "/root/reference"

pytestmark = pytest.mark.quick


def expand(text, defines=None):
    m4 = M4(defines=defines)
    m4.process(text)
    return m4.result()


class TestMiniM4:
    def test_define_and_expand(self):
        assert expand("define(`A', `hello')A world") == "hello world"

    def test_quoting_suppresses_expansion(self):
        assert expand("define(`A', `x')`A' A") == "A x"

    def test_nested_quotes_strip_one_level(self):
        assert expand("``A''") == "`A'"

    def test_args_substitute(self):
        assert expand("define(`F', `[$1|$2]')F(a, b)") == "[a|b]"

    def test_arg_count_and_at(self):
        assert expand("define(`F', `$#')F(a,b,c)") == "3"
        assert expand("define(`F', `$@')F(a,b)") == "a,b"

    def test_dnl_eats_line(self):
        assert expand("a dnl comment here\nb") == "a b"

    def test_comment_passthrough_no_expansion(self):
        assert expand("define(`A', `x')# A stays\nA") == "# A stays\nx"

    def test_expansion_during_arg_collection(self):
        # the libelf list idiom: a macro expanding to `a',`b' must split
        # the outer call's arguments
        text = ("define(`LIST', ``a', `b', `c'')"
                "define(`COUNT', `$#')"
                "COUNT(LIST)")
        assert expand(text) == "3"

    def test_shift_recursion(self):
        text = ("define(`JOIN', `ifelse($#, 1, `$1', `$1-JOIN(shift($@))')')"
                "JOIN(x, y, z)")
        assert expand(text) == "x-y-z"

    def test_define_inside_define(self):
        # NOCVT(TYPE) -> define(NOCVT_TYPE, 1) (libelf_convert.m4)
        text = ("define(`MARK', `define(`SAW_'$1, 1)')"
                "MARK(`FOO')"
                "ifdef(`SAW_FOO', `yes', `no')")
        assert expand(text) == "yes"

    def test_pushdef_popdef(self):
        text = ("define(`V', `one')pushdef(`V', `two')V popdef(`V')V")
        assert expand(text) == "two one"

    def test_divert_discards(self):
        assert expand("keep divert(-1)gone divert(0)back") == "keep back"

    def test_ifelse_chain(self):
        t = "define(`F', `ifelse($1, a, `A', $1, b, `B', `other')')"
        assert expand(t + "F(a)") == "A"
        assert expand(t + "F(b)") == "B"
        assert expand(t + "F(z)") == "other"

    def test_builtin_bare_word_passthrough(self):
        # words like "include" in C prose must not fire the builtin
        assert expand("do not include this") == "do not include this"

    @pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
    def test_libelf_msize_generates_full_table(self):
        out = m4_expand(os.path.join(REF, "ext/libelf/libelf_msize.m4"),
                        defines={"SRCDIR": os.path.join(REF, "ext/libelf")})
        # every fixed-size ELF type must land one initializer row
        for t in ("ADDR", "EHDR", "SYM", "RELA", "PHDR", "SHDR"):
            assert f"[ELF_T_{t}]" in out
        assert "ELF_TYPE_LIST" not in out

    @pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
    def test_libelf_convert_generates_functions(self):
        out = m4_expand(os.path.join(REF, "ext/libelf/libelf_convert.m4"),
                        defines={"SRCDIR": os.path.join(REF, "ext/libelf")})
        assert out.count("_libelf_cvt_") > 100  # defs + table refs
        for fn in ("_libelf_cvt_EHDR64_tom", "_libelf_cvt_SYM32_tof"):
            assert fn in out


@pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
class TestConf:
    def test_x86_se_config(self):
        from conf import make_conf

        conf = make_conf()
        assert conf["USE_X86_ISA"] is True
        assert conf["RUBY"] is False
        assert conf["USE_ARM_ISA"] is False
        assert conf["USE_KVM"] is False
        # every symbol the SConscripts consult must exist
        for key in ("USE_SYSTEMC", "BUILD_GPU", "HAVE_PROTOBUF",
                    "BUILD_TLM", "KVM_ISA", "USE_EFENCE"):
            assert key in conf


class TestGoldenCampaignPatch:
    """The m5.cpt GPR patcher, against a synthetic checkpoint in the
    reference's serialization shape (regs.<class> flattened byte arrays,
    src/cpu/thread_context.cc:194-216)."""

    CPT = (
        "[Globals]\n"
        "curTick=1000\n"
        "\n"
        "[system.cpu.xc.0]\n"
        "regs.integer=" + " ".join(
            str((r * 17 + b) % 256) for r in range(18) for b in range(8))
        + "\n"
        "regs.floating_point=0 0 0 0\n"
        "_pc=4198400\n"
        "\n"
        "[system.mem_ctrl]\n"
        "range_size=536870912\n"
    )

    def _mod(self):
        import golden_campaign as gc
        return gc

    def test_find_intregs(self):
        gc = self._mod()
        (start, end), vals = gc.find_intregs(self.CPT)
        assert len(vals) == 18 * 8
        assert self.CPT[start:end].startswith("regs.integer=")
        assert vals[0] == "0" and vals[8] == "17"  # (r*17+b) % 256 fill

    def test_patch_flips_exactly_one_bit(self, tmp_path):
        gc = self._mod()
        src = tmp_path / "ckpt"
        src.mkdir()
        (src / "m5.cpt").write_text(self.CPT)
        dst = tmp_path / "patched"
        gc.prepare_patch_dir(str(src), str(dst))
        for reg, bit in ((0, 0), (7, 33), (15, 63)):
            gc.patch_cpt(self.CPT, str(dst), reg, bit)
            text = (dst / "m5.cpt").read_text()
            (_, vals0) = gc.find_intregs(self.CPT)[0], \
                gc.find_intregs(self.CPT)[1]
            (_, vals1) = gc.find_intregs(text)[0], gc.find_intregs(text)[1]
            diffs = [i for i, (a, b) in enumerate(zip(vals0, vals1))
                     if a != b]
            assert diffs == [reg * 8 + bit // 8]
            delta = int(vals0[diffs[0]]) ^ int(vals1[diffs[0]])
            assert delta == 1 << (bit % 8)
            # everything outside the key line is untouched
            assert text.split("regs.integer=")[0] == \
                self.CPT.split("regs.integer=")[0]
            assert text.split("\nregs.floating_point=")[1] == \
                self.CPT.split("\nregs.floating_point=")[1]

    def test_last_section_checkpoint(self):
        gc = self._mod()
        cpt = ("[system.cpu.xc.0]\n"
               "regs.integer=" + " ".join(["5"] * 128) + "\n")
        (_s, _e), vals = gc.find_intregs(cpt)
        assert len(vals) == 128
