"""64-bit pair-lane lift (ingest/lift64.py): carry/borrow µop algebra,
full-width self-validation on a real capture, and the hi-lane fault
semantics the 32-bit projection could not express.

Reference role: the 64-bit PhysRegFile banks
(/root/reference/src/cpu/o3/regfile.hh:65-99) as *device-side* fault
targets — round 3 covered bits [32,64) only through the host emulator."""

import numpy as np
import pytest

from shrewd_tpu.ingest.lift64 import HI, Lifter64, hi
from shrewd_tpu.isa import semantics


def _lifter():
    from shrewd_tpu.ingest.lift import NativeTrace

    steps = np.zeros((2, 17), dtype=np.uint64)
    return Lifter64(NativeTrace(0, 0, steps, [], 0), {})


def _set(lf, r, v):
    lf.reg[r] = v & 0xFFFFFFFF
    lf.reg[hi(r)] = (v >> 32) & 0xFFFFFFFF


def _get(lf, r):
    return int(lf.reg[r]) | (int(lf.reg[hi(r)]) << 32)


M64 = 0xFFFFFFFFFFFFFFFF


class TestPairAlgebra:
    """The golden sim executes every emitted µop immediately, so checking
    lf.reg after a helper checks the exact sequence the kernel replays."""

    @pytest.mark.parametrize("a,b", [
        (1, 2), (0xFFFFFFFF, 1), (0xFFFFFFFF_FFFFFFFF, 1),
        (0x12345678_9ABCDEF0, 0x0FEDCBA9_87654321),
        (0x80000000_00000000, 0x80000000_00000000), (0, 0),
    ])
    def test_add64_carry(self, a, b):
        lf = _lifter()
        _set(lf, 1, a)
        _set(lf, 2, b)
        lf._add64(3, 1, 2)
        assert _get(lf, 3) == (a + b) & M64

    @pytest.mark.parametrize("a,b", [
        (2, 1), (0, 1), (1, 0xFFFFFFFF), (0x1_00000000, 1),
        (0x12345678_9ABCDEF0, 0xFEDCBA98_76543210),
    ])
    def test_sub64_borrow(self, a, b):
        lf = _lifter()
        _set(lf, 1, a)
        _set(lf, 2, b)
        lf._sub64(3, 1, 2)
        assert _get(lf, 3) == (a - b) & M64

    def test_add64_aliasing_dst(self):
        lf = _lifter()
        _set(lf, 1, 0xFFFFFFFF)
        _set(lf, 2, 3)
        lf._add64(1, 1, 2)
        assert _get(lf, 1) == 0x1_00000002

    @pytest.mark.parametrize("c", [0, 1, 5, 31, 32, 33, 63])
    def test_shl64(self, c):
        v = 0x92345678_9ABCDEF1
        lf = _lifter()
        _set(lf, 1, v)
        lf._shl64_imm(2, 1, c)
        assert _get(lf, 2) == (v << c) & M64

    @pytest.mark.parametrize("c", [0, 1, 5, 31, 32, 33, 63])
    @pytest.mark.parametrize("arith", [False, True])
    def test_shr64(self, c, arith):
        v = 0x92345678_9ABCDEF1                  # negative as signed
        lf = _lifter()
        _set(lf, 1, v)
        lf._shr64_imm(2, 1, c, arith=arith)
        want = ((v - (1 << 64) if arith else v) >> c) & M64
        assert _get(lf, 2) == want

    @pytest.mark.parametrize("a,b", [
        (1, 2), (2, 1), (5, 5), (0xFFFFFFFF_FFFFFFFF, 0),
        (0x8000000000000000, 0x7FFFFFFFFFFFFFFF),
        (0x1_00000005, 0x2_00000001),
    ])
    @pytest.mark.parametrize("signed", [False, True])
    def test_ltu64(self, a, b, signed):
        def s64(x):
            return x - (1 << 64) if x >> 63 else x

        lf = _lifter()
        _set(lf, 1, a)
        _set(lf, 2, b)
        lf._ltu64(3, 1, hi(1), 2, hi(2), signed=signed)
        want = (s64(a) < s64(b)) if signed else (a < b)
        assert int(lf.reg[3]) == int(want)

    def test_const64_and_mov64(self):
        lf = _lifter()
        lf._const64(0xDEADBEEF_CAFEF00D, 5)
        assert _get(lf, 5) == 0xDEADBEEF_CAFEF00D
        lf._mov64(6, 5)
        assert _get(lf, 6) == 0xDEADBEEF_CAFEF00D


@pytest.fixture(scope="module")
def lifted64(sort_capture64):
    from shrewd_tpu.ingest.lift64 import lift64

    trace_bin, wl = sort_capture64
    return lift64(str(trace_bin), str(wl))


@pytest.fixture(scope="module")
def sort_capture64(tmp_path_factory):
    from shrewd_tpu.ingest import hostdiff as hd

    paths = hd.build_tools()
    bd = tmp_path_factory.mktemp("l64")
    trace_bin = bd / "sort64.bin"
    import subprocess

    subprocess.run([str(paths.tracer), str(trace_bin), f"{paths.begin:x}",
                    f"{paths.end:x}", "2000000", str(paths.workload)],
                   check=True, capture_output=True, text=True)
    return trace_bin, paths.workload


def test_full_width_lift_rate(lifted64):
    trace, meta = lifted64
    assert meta["width"] == 64 and trace.nphys == 64
    assert meta["stats"]["lift_rate"] > 0.99, \
        meta["stats"]["opaque_mnemonics"]
    assert meta["stats"]["branches_dropped"] == 0


def test_golden_matches_full_64bit_capture(lifted64):
    """Scalar golden replay of the pair-lane trace reproduces the FULL
    captured 64-bit register file — the correctness authority the 32-bit
    lift could only assert for the low halves."""
    trace, meta = lifted64
    reg, mem = trace.init_reg.copy(), trace.init_mem.copy()
    semantics.scalar_replay(trace, reg, mem)
    exp = np.asarray(meta["final_reg_expect"], np.uint64)
    got = reg[:16].astype(np.uint64) | (reg[HI:HI + 16].astype(np.uint64)
                                        << 32)
    np.testing.assert_array_equal(got, exp)


def test_hi_pointer_fault_traps_on_device(lifted64):
    """Flipping a hi-lane bit of a live pointer register must reach the
    memory system: the hi-guard poisons the effective address and the VA
    crash model traps — the silicon outcome (any hi-bit pointer
    corruption segfaults).  The 32-bit projection silently ignored these
    coordinates."""
    import jax
    import jax.numpy as jnp

    from shrewd_tpu.ingest.hostdiff import memmap_from_meta
    from shrewd_tpu.models.o3 import Fault, KIND_REGFILE, O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    trace, meta = lifted64
    k = TrialKernel(trace, O3Config(enable_shrewd=False),
                    memmap=memmap_from_meta(meta))
    assert not bool(k.golden.trapped)
    # rsp (reg 4) is live at every step; flip bit 45 (hi lane bit 13)
    f = Fault(kind=jnp.int32(KIND_REGFILE), cycle=jnp.int32(0),
              entry=jnp.int32(4 + HI), bit=jnp.int32(13),
              shadow_u=jnp.float32(1.0))
    res = jax.jit(k._replay_one)(f)
    assert bool(res.trapped)
