"""SLICC transition-table validation harness tests (tools/mesi_slicc_check).

The heavy all-scenario sweep is the tool's job (MESI_SLICC_VALIDATE_r05);
these tests pin the extraction machinery and one representative closure so
regressions in the parser or the model surface in CI without the full run.
Reference-source-dependent pieces skip when /root/reference is absent.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from mesi_slicc_check import (DEFAULT_SM_DIR, STABLE_L1, STABLE_L2,  # noqa: E402
                              closure, l1_to_fw, l2_to_fw, parse_sm,
                              run_model, scenarios)

SM = Path(DEFAULT_SM_DIR)
needs_ref = pytest.mark.skipif(not SM.exists(),
                               reason="reference protocol sources absent")


@needs_ref
def test_parse_extracts_full_l1_table():
    t = parse_sm(SM / "MESI_Two_Level-L1cache.sm")
    # brace-list expansion: {NP,I} × {Load,...} rows all present
    assert t[("NP", "Load")] == "IS" and t[("I", "Load")] == "IS"
    assert t[("E", "Store")] == "M" and t[("M", "Store")] == "M"
    assert t[("S", "Inv")] == "I"
    # 2-arg transitions keep their state (z_stall / stay)
    assert t[("S", "Load")] == "S"
    assert len(t) > 150


@needs_ref
def test_closure_walks_transients_to_stable():
    t = parse_sm(SM / "MESI_Two_Level-L1cache.sm")
    end, path = closure(t, "I", "Store", ["Data_all_Acks"], STABLE_L1)
    assert end == "M" and path == ["I", "IM", "M"]
    end, path = closure(t, "M", "L1_Replacement", ["WB_Ack"], STABLE_L1)
    assert end == "I" and path == ["M", "M_I", "I"]
    # unknown event on the path fails loudly, not silently
    with pytest.raises(KeyError):
        closure(t, "I", "Bogus_Event", [], STABLE_L1)


@needs_ref
def test_one_scenario_end_to_end():
    """store_invalidates_owner: the dirtiest cross-core path (M owner
    forced to writeback + invalidate) agrees between the SLICC closure
    and both framework implementations."""
    l1 = parse_sm(SM / "MESI_Two_Level-L1cache.sm")
    l2 = parse_sm(SM / "MESI_Two_Level-L2cache.sm")
    name, stream, legs = next(s for s in scenarios()
                              if s[0] == "store_invalidates_owner")
    l1_state, dir_states = run_model(stream)
    for key, (ctrl, start, trig, comp) in legs.items():
        table, stable = (l1, STABLE_L1) if ctrl == "L1" else (l2, STABLE_L2)
        end, _ = closure(table, start, trig, comp, stable)
        if key[0] == "l1":
            assert l1_state(key[1], key[2]) == l1_to_fw(end), key
        else:
            assert dir_states[key[1]] == l2_to_fw(end), key
