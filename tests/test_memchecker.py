"""Memory-ordering checker (utils/memchecker.py; reference
src/mem/mem_checker.hh readable-set semantics)."""

import numpy as np
import pytest

from shrewd_tpu.models.mesi import MesiConfig, scalar_mesi, torture_stream
from shrewd_tpu.utils import memchecker as MC
from shrewd_tpu.trace.synth import WorkloadConfig, generate


def _trace(n=256, seed=4):
    return generate(WorkloadConfig(n=n, nphys=64, mem_words=256,
                                   working_set_words=64, seed=seed))


class TestSingleStream:
    def test_golden_replay_is_clean(self):
        from shrewd_tpu.isa.semantics import scalar_replay

        tr = _trace()
        reg = np.asarray(tr.init_reg, np.uint32).copy()
        mem = np.asarray(tr.init_mem, np.uint32).copy()
        observed = []
        from shrewd_tpu.isa import uops as U
        for i, ldv in _walk_loads(tr):
            observed.append(ldv)
        r = MC.check_trace(tr, observed_loads=np.asarray(observed,
                                                         np.uint32))
        assert r.n_violations == 0
        assert r.n_loads > 0

    def test_corrupted_load_detected(self):
        tr = _trace()
        observed = np.asarray([v for _, v in _walk_loads(tr)], np.uint32)
        observed = observed.copy()
        observed[len(observed) // 2] ^= 0x4
        r = MC.check_trace(tr, observed_loads=observed)
        assert r.n_violations >= 1
        assert r.first_violation >= 0
        assert "expected" in r.detail

    def test_device_golden_record_is_clean(self):
        """The device replay's golden record passes the checker — the
        framework self-check this module exists for."""
        from shrewd_tpu.models.o3 import O3Config
        from shrewd_tpu.ops.trial import TrialKernel

        tr = _trace(n=128, seed=6)
        kern = TrialKernel(tr, O3Config())
        r = MC.check_trace(tr, golden_record=kern.golden_rec)
        assert r.n_violations == 0, r.detail


def _walk_loads(tr):
    """Independent helper: yields (µop, value) per load via scalar_replay's
    contract (separate from expected_load_values' own walk)."""
    from shrewd_tpu.isa import uops as U
    from shrewd_tpu.isa.semantics import scalar_replay

    reg = np.asarray(tr.init_reg, np.uint32).copy()
    mem = np.asarray(tr.init_mem, np.uint32).copy()
    rec = []
    scalar_replay(tr, reg, mem, record_mem=rec)
    # re-walk to capture values: simplest is a second pass recording loads
    reg = np.asarray(tr.init_reg, np.uint32).copy()
    mem = np.asarray(tr.init_mem, np.uint32).copy()
    out = []
    from shrewd_tpu.isa.semantics import alu
    for i in range(tr.n):
        op = int(tr.opcode[i])
        a, b = int(reg[tr.src1[i]]), int(reg[tr.src2[i]])
        res = alu(op, a, b, int(tr.imm[i]))
        if op == U.LOAD:
            v = int(mem[res >> 2])
            out.append((i, v))
            reg[tr.dst[i]] = v
        elif op == U.STORE:
            mem[res >> 2] = b
        elif U.writes_dest(np.int64(op)):
            reg[tr.dst[i]] = res
    return out


class TestTransactionWindows:
    def test_simple_read_after_write(self):
        mc = MC.MemChecker(np.zeros(4, np.uint32))
        s = mc.start_write(0, 1, 0xAB)
        mc.complete_write(s, 1, 1)
        r = mc.start_read(2, 1)
        assert mc.complete_read(r, 3, 1, 0xAB)
        assert not mc.violations

    def test_stale_read_flagged(self):
        mc = MC.MemChecker(np.zeros(4, np.uint32))
        s = mc.start_write(0, 1, 0xAB)
        mc.complete_write(s, 1, 1)
        r = mc.start_read(5, 1)
        assert not mc.complete_read(r, 6, 1, 0x0)   # init value now stale
        assert mc.violations
        with pytest.raises(MC.MemoryViolation):
            mc.assert_clean()

    def test_overlapping_write_makes_both_values_legal(self):
        mc = MC.MemChecker(np.zeros(4, np.uint32))
        s1 = mc.start_write(0, 2, 0x11)
        mc.complete_write(s1, 1, 2)
        s2 = mc.start_write(2, 2, 0x22)        # overlaps the read below
        r = mc.start_read(3, 2)
        ok_either = mc.complete_read(r, 4, 2, 0x22)
        assert ok_either                        # in-flight write readable
        mc.complete_write(s2, 10, 2)
        r2 = mc.start_read(11, 2)
        assert mc.complete_read(r2, 12, 2, 0x22)
        r3 = mc.start_read(13, 2)
        assert not mc.complete_read(r3, 14, 2, 0x11)  # now stale

    def test_initial_value_readable_before_any_write(self):
        mc = MC.MemChecker(np.array([7, 8, 9], np.uint32))
        r = mc.start_read(0, 2)
        assert mc.complete_read(r, 1, 2, 9)

    def test_unknown_serial_raises(self):
        mc = MC.MemChecker()
        with pytest.raises(KeyError):
            mc.complete_write(99, 1, 0)


class TestMesiIntegration:
    def test_mesi_golden_loads_serializable(self):
        cfg = MesiConfig()
        tr = torture_stream(cfg, 128, mem_words=64, seed=2)
        init = np.arange(64, dtype=np.uint32)
        loads, _mem = scalar_mesi(tr, cfg, init)
        assert MC.check_mesi_trace(tr, cfg, init, loads) == 0

    def test_mesi_corrupted_load_caught(self):
        cfg = MesiConfig()
        tr = torture_stream(cfg, 128, mem_words=64, seed=2)
        init = np.arange(64, dtype=np.uint32)
        loads, _ = scalar_mesi(tr, cfg, init)
        loads = np.asarray(loads, np.uint32).copy()
        if loads.size == 0:
            pytest.skip("no loads in stream")
        loads[0] ^= 0x100
        assert MC.check_mesi_trace(tr, cfg, init, loads) >= 1
