"""Multi-tenant campaign service (shrewd_tpu/service/): scheduler,
submission queue, fleet semantics.

The contract under test is the ISSUE acceptance criterion: a 2+ tenant
fleet on one mesh produces per-tenant tallies BIT-IDENTICAL to each
tenant's solo serial run — co-scheduling changes wall-clock, never
results — including under injected chaos (wedge / corrupt tally /
kill_worker rescoped to the afflicted tenant only) and across a
mid-fleet drain → resume.  Scheduling itself must be deterministic
(weighted fair-share stride + strict priority consume only admission
order, trial counts and weights), tenants must stop independently
(per-tenant Wilson rule), and cross-tenant compile dedupe through the
content-keyed executable cache must be observable: the second tenant on
a shared window compiles ZERO new steps.
"""

import json
import os

import numpy as np
import pytest

from shrewd_tpu.parallel import exec_cache
from shrewd_tpu.service import (CampaignScheduler, SubmissionQueue,
                                TenantKilled, TenantSpec)


# --- plan / solo-run fixtures ----------------------------------------------

def _plan(seed=3, n_batches=6, batch_size=32, mode="hybrid",
          stratify=False, sync_every=1, chaos=None, wd=0.0,
          ckpt_every=0, **kw):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.trace.synth import WorkloadConfig

    defaults = dict(structures=["regfile"], batch_size=batch_size,
                    target_halfwidth=0.2,
                    max_trials=batch_size * n_batches,
                    min_trials=batch_size * n_batches,
                    stratify=stratify, checkpoint_every=ckpt_every)
    defaults.update(kw)
    plan = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        seed=seed, **defaults)
    plan.machine.replay_kernel = mode
    plan.integrity.canary_trials = 0
    plan.integrity.audit_rate = 0.0
    plan.resilience.backoff_base = 0.0
    if wd:
        plan.resilience.dispatch_timeout = wd
    plan.pipeline.sync_every = sync_every
    if chaos:
        plan.chaos.spec = json.dumps(chaos)
    return plan


def _solo_tallies(plan):
    """One run-to-completion serial campaign → {(sp, structure): tallies}
    (the reference point every fleet assertion compares against)."""
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(plan)
    events = list(orch.events())
    assert events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE
    return {k: np.asarray(v.tallies, dtype=np.int64)
            for k, v in dict(events[-1][1]).items()}


def _assert_tenant_matches(sched, name, solo):
    fleet = sched.tenant_tallies(name)
    assert fleet.keys() == solo.keys()
    for k, t in solo.items():
        np.testing.assert_array_equal(fleet[k], t)


# --- specs / queue (jax-free units) -----------------------------------------

def test_tenant_spec_roundtrip_and_validation():
    spec = TenantSpec(name="t0", plan={"seed": 1}, priority=2, weight=0.5,
                      quota_batches=7)
    back = TenantSpec.from_dict(spec.to_dict())
    assert (back.name, back.priority, back.weight, back.quota_batches) \
        == ("t0", 2, 0.5, 7)
    with pytest.raises(ValueError):
        TenantSpec(name="", plan={})
    with pytest.raises(ValueError):
        TenantSpec(name="t", plan={}, weight=0.0)
    with pytest.raises(ValueError):
        TenantSpec(name="t", plan={}, quota_batches=-1)


def test_submission_queue_spool(tmp_path):
    q = SubmissionQueue(str(tmp_path / "spool"))
    t1 = q.submit(TenantSpec(name="a", plan={"seed": 1}))
    t2 = q.submit(TenantSpec(name="b", plan={"seed": 2}))
    assert q.pending() == [t1, t2]
    # tickets are sequence-ordered and collision-free for equal names
    t3 = q.submit(TenantSpec(name="a", plan={"seed": 3}))
    assert t3 != t1 and q.pending() == [t1, t2, t3]
    claimed = q.claim()
    assert [t for t, _ in claimed] == [t1, t2, t3]
    assert q.pending() == []
    # a second claim sees nothing (tickets moved to claimed/)
    assert q.claim() == []
    q.mark_done(t1, {"tenant": "a", "status": "complete"})
    assert q.done(t1)["status"] == "complete"
    assert q.done(t2) is None
    # a torn/in-flight submission is skipped, never claimed half-written
    bad = tmp_path / "spool" / "pending" / "000099_torn.json"
    bad.write_text("{\"name\": \"torn")
    assert q.claim() == []
    assert bad.exists()


# --- deterministic scheduling policies --------------------------------------

def test_weighted_fair_share_stride_ordering():
    # weights 1 vs 3: stride scheduling serves b three batches for every
    # one of a's, deterministically (virtual time = trials/weight, ties
    # break on admission order) — drain after 8 ticks and read the log
    def drain_at_8(s):
        if s.ticks == 8:
            s.request_drain()

    sched = CampaignScheduler(on_tick=drain_at_8)
    sched.admit(TenantSpec(name="a", plan=_plan(3, n_batches=12).to_dict(),
                           weight=1.0))
    sched.admit(TenantSpec(name="b", plan=_plan(5, n_batches=12).to_dict(),
                           weight=3.0))
    rc = sched.run()
    assert rc == 4 and sched.preempted
    assert sched.schedule_log == ["a", "b", "b", "b",
                                  "a", "b", "b", "b"]
    assert sched.tenants["b"].trials == 3 * sched.tenants["a"].trials


def test_strict_priority_runs_high_class_first():
    sched = CampaignScheduler(policy="priority")
    sched.admit(TenantSpec(name="lo", plan=_plan(3, n_batches=3).to_dict(),
                           priority=0))
    sched.admit(TenantSpec(name="hi", plan=_plan(5, n_batches=3).to_dict(),
                           priority=1))
    assert sched.run() == 0
    hi_ticks = sched.tenants["hi"].ticks
    # every one of hi's quanta (including its terminal tick) precedes
    # lo's first — strict classes, not shares
    assert sched.schedule_log[:hi_ticks] == ["hi"] * hi_ticks
    assert set(sched.schedule_log[hi_ticks:]) == {"lo"}


def test_depth_budget_rebalances_across_tenants():
    sched = CampaignScheduler(depth_budget=2)
    sched.admit(TenantSpec(name="a", plan=_plan(3, n_batches=2,
                                                sync_every=2).to_dict()))
    sched.admit(TenantSpec(name="b", plan=_plan(5, n_batches=2,
                                                sync_every=2).to_dict()))
    both = sched._candidates()
    assert [t.orch.pcfg.depth for t in both] == [1, 1]   # 2 // 2 tenants
    sched.tenants["a"].status = "complete"
    sched._rebalance()
    assert sched.tenants["b"].orch.pcfg.depth == 2       # whole budget


# --- bit-identity vs solo (the acceptance criterion) ------------------------

@pytest.mark.parametrize("mode,stratify", [
    ("dense", False), ("hybrid", False), ("hybrid", True)])
def test_fleet_bit_identical_to_solo(mode, stratify):
    # solo arm: the exact serial loop; fleet arm: pipelined (sync 2) and
    # interleaved with a second tenant — neither may perturb the tallies
    solo = _solo_tallies(_plan(3, mode=mode, stratify=stratify))
    other = _solo_tallies(_plan(11, n_batches=4))
    sched = CampaignScheduler()
    sched.admit(TenantSpec(name="t", plan=_plan(
        3, mode=mode, stratify=stratify, sync_every=2).to_dict()))
    sched.admit(TenantSpec(name="other", plan=_plan(
        11, n_batches=4).to_dict()))
    assert sched.run() == 0
    _assert_tenant_matches(sched, "t", solo)
    _assert_tenant_matches(sched, "other", other)


def test_per_tenant_stopping_is_independent():
    # "loose" converges by the Wilson rule well before its cap; "capped"
    # has an unreachable halfwidth target and runs to MAX_TRIALS —
    # per-tenant stopping state must not bleed across tenants
    loose = _plan(3, n_batches=12, target_halfwidth=0.45,
                  min_trials=32, max_trials=32 * 12)
    capped = _plan(5, n_batches=4, target_halfwidth=0.001,
                   min_trials=32, max_trials=32 * 4)
    sched = CampaignScheduler()
    sched.admit(TenantSpec(name="loose", plan=loose.to_dict()))
    sched.admit(TenantSpec(name="capped", plan=capped.to_dict()))
    assert sched.run() == 0
    rl = sched.tenants["loose"].results["w0/regfile"]
    rc_ = sched.tenants["capped"].results["w0/regfile"]
    assert rl["converged"] and rl["trials"] < 32 * 12
    assert not rc_["converged"] and rc_["trials"] == 32 * 4
    np.testing.assert_array_equal(
        sched.tenant_tallies("loose")["w0", "regfile"],
        _solo_tallies(loose)["w0", "regfile"])


def test_tenant_quota_drains_to_resumable_checkpoint(tmp_path):
    sched = CampaignScheduler(outdir=str(tmp_path))
    sched.admit(TenantSpec(name="q", plan=_plan(3, n_batches=8).to_dict(),
                           quota_batches=3))
    assert sched.run() == 0
    t = sched.tenants["q"]
    assert t.status == "quota" and t.batches == 3
    # the tenant checkpointed into its namespace, resumable
    assert os.path.exists(os.path.join(
        str(tmp_path), "tenants", "q", "campaign_ckpt", "campaign.json"))


# --- chaos isolation --------------------------------------------------------

def test_chaos_quarantines_only_the_afflicted_tenant():
    solo = _solo_tallies(_plan(3))
    clean_solo = _solo_tallies(_plan(7))
    sched = CampaignScheduler()
    sched.admit(TenantSpec(name="afflicted", plan=_plan(3, chaos={
        "faults": [{"kind": "wedge", "at_batch": 1},
                   {"kind": "corrupt_tally", "at_batch": 3, "delta": 2}],
    }, wd=5.0).to_dict()))
    sched.admit(TenantSpec(name="clean", plan=_plan(7).to_dict()))
    assert sched.run() == 0
    a = sched.tenants["afflicted"]
    c = sched.tenants["clean"]
    assert a.orch.chaos.injected == {"wedge": 1, "corrupt_tally": 1}
    assert a.orch.chaos.survived == a.orch.chaos.injected
    # the corruption quarantined and recovered INSIDE the afflicted
    # tenant; the clean tenant's monitor never saw a problem
    assert a.orch.monitor.quarantined == 1
    assert a.orch.monitor.recovered == 1
    assert c.orch.chaos is None and c.orch.monitor.quarantined == 0
    _assert_tenant_matches(sched, "afflicted", solo)
    _assert_tenant_matches(sched, "clean", clean_solo)


def test_kill_worker_rescopes_to_tenant_and_recovers(tmp_path):
    # in a fleet the chaos "worker" is the tenant's driver: the kill
    # tears down only the victim's orchestrator; the scheduler rebuilds
    # it from its namespaced checkpoint and the fleet completes with
    # both tenants bit-identical to their solo runs
    solo = _solo_tallies(_plan(3, ckpt_every=1))
    by_solo = _solo_tallies(_plan(5, n_batches=4))
    sched = CampaignScheduler(outdir=str(tmp_path))
    sched.admit(TenantSpec(name="victim", plan=_plan(3, chaos={
        "faults": [{"kind": "kill_worker", "at_batch": 2}],
    }, ckpt_every=1).to_dict()))
    sched.admit(TenantSpec(name="bystander",
                           plan=_plan(5, n_batches=4).to_dict()))
    assert sched.run() == 0
    v = sched.tenants["victim"]
    assert v.kills == 1 and v.status == "complete"
    assert v.orch.chaos.injected == {"kill_worker": 1}
    assert v.orch.chaos.survived == {"kill_worker": 1}
    assert sched.tenants["bystander"].kills == 0
    _assert_tenant_matches(sched, "victim", solo)
    _assert_tenant_matches(sched, "bystander", by_solo)


def test_bad_tenant_quarantines_in_isolation(tmp_path):
    # a plan that cannot elaborate (missing trace file) is THAT
    # tenant's failure: it burns its retry budget (tick-counted
    # backoff), lands in durable "quarantined" with the exception
    # ledger as evidence, its spool ticket resolved, and every other
    # tenant still served — a resident scheduler must never die on one
    # bad submission
    from shrewd_tpu.campaign.plan import CampaignPlan, TraceFileSpec

    q = SubmissionQueue(str(tmp_path / "spool"))
    bad = CampaignPlan(simpoints=[TraceFileSpec(
        name="w0", path=str(tmp_path / "missing.npz"))],
        structures=["regfile"], batch_size=32, max_trials=64,
        min_trials=64)
    ticket = q.submit(TenantSpec(name="bad", plan=bad.to_dict()))
    good_solo = _solo_tallies(_plan(3, n_batches=3))
    sched = CampaignScheduler(queue=q, retry_budget=1, backoff_ticks=1)
    sched.admit(TenantSpec(name="good", plan=_plan(3,
                                                   n_batches=3).to_dict()))
    assert sched.run() == 0
    t = sched.tenants["bad"]
    assert t.status == "quarantined"
    assert t.failures == 2                    # initial try + 1 retry
    assert len(t.errors) == 2 and "error" in t.results
    assert q.done(ticket)["status"] == "quarantined"
    _assert_tenant_matches(sched, "good", good_solo)


def test_pending_kill_survives_drain_and_fires_on_resume(tmp_path):
    # the drain flag preempts at the next batch boundary BEFORE any
    # compute, so a scheduled kill cannot fire during the drain itself;
    # it must survive the drain → resume round-trip (the chaos engine
    # rebuilds from the plan spec) and still quarantine only its tenant
    solo = _solo_tallies(_plan(3, ckpt_every=1))

    def drain_at_1(s):
        if s.ticks == 1:
            s.request_drain()

    sched = CampaignScheduler(outdir=str(tmp_path), on_tick=drain_at_1)
    sched.admit(TenantSpec(name="victim", plan=_plan(3, chaos={
        "faults": [{"kind": "kill_worker", "at_batch": 2}],
    }, ckpt_every=1).to_dict()))
    assert sched.run() == 4
    v = sched.tenants["victim"]
    assert v.kills == 0 and v.status == "preempted"   # not reached yet
    resumed = CampaignScheduler.resume(str(tmp_path))
    assert resumed.run() == 0
    rv = resumed.tenants["victim"]
    assert rv.kills == 1 and rv.status == "complete"
    _assert_tenant_matches(resumed, "victim", solo)


def test_depth_ceiling_survives_clamped_checkpoint(tmp_path):
    # _rebalance clamps pcfg.depth in place and the clamp rides the
    # tenant checkpoint; the budget ceiling must come from the SPEC, or
    # a drained/killed tenant's depth would ratchet down monotonically
    # across every resume
    plan = _plan(3, sync_every=2)
    plan.pipeline.depth = 2

    def drain_at_2(s):
        if s.ticks == 2:
            s.request_drain()

    sched = CampaignScheduler(outdir=str(tmp_path), depth_budget=1,
                              on_tick=drain_at_2)
    sched.admit(TenantSpec(name="t", plan=plan.to_dict()))
    sched._candidates()
    assert sched.tenants["t"].orch.pcfg.depth == 1     # clamped by budget
    assert sched.run() == 4
    resumed = CampaignScheduler.resume(str(tmp_path), depth_budget=4)
    resumed._candidates()
    t = resumed.tenants["t"]
    assert t._plan_depth == 2                # ceiling from the spec...
    assert t.orch.pcfg.depth == 2            # ...restored under budget 4


def test_tenant_killed_raises_out_of_driver():
    # the unit seam: ChaosEngine.kill_action is replaceable (default
    # os._exit), and the scheduler's rescoped action raises TenantKilled
    from shrewd_tpu.chaos import ChaosEngine

    eng = ChaosEngine({"faults": [{"kind": "kill_worker", "at_batch": 0,
                                   "rc": 99}]})
    fired = []
    eng.kill_action = lambda rc: fired.append(rc) or (_ for _ in ()).throw(
        TenantKilled("t", rc))
    eng.begin_batch(0, "w0", "regfile")
    with pytest.raises(TenantKilled):
        eng.maybe_kill()
    assert fired == [99]


# --- drain / resume ---------------------------------------------------------

def test_fleet_drain_and_resume_bit_identical(tmp_path):
    solo_a = _solo_tallies(_plan(3))
    solo_b = _solo_tallies(_plan(5))

    def drain_at_3(s):
        if s.ticks == 3:
            s.request_drain()

    sched = CampaignScheduler(outdir=str(tmp_path), on_tick=drain_at_3)
    sched.admit(TenantSpec(name="a", plan=_plan(3).to_dict()))
    sched.admit(TenantSpec(name="b", plan=_plan(5).to_dict()))
    assert sched.run() == 4 and sched.preempted
    assert sched._by_status() == {"preempted": 2}
    # every admitted tenant checkpointed into its namespace + the fleet
    # persisted its own resumable state
    for name in ("a", "b"):
        assert os.path.exists(os.path.join(
            str(tmp_path), "tenants", name, "campaign_ckpt",
            "campaign.json"))
    assert os.path.exists(os.path.join(str(tmp_path), "fleet_ckpt",
                                       "fleet.json"))
    resumed = CampaignScheduler.resume(str(tmp_path))
    assert resumed.run() == 0
    assert resumed._by_status() == {"complete": 2}
    _assert_tenant_matches(resumed, "a", solo_a)
    _assert_tenant_matches(resumed, "b", solo_b)


# --- cross-tenant compile dedupe (the co-scheduling win) --------------------

def test_second_tenant_on_shared_window_compiles_zero_new_steps():
    # warm the cache with a solo run over the window (kept alive: cache
    # entries are weakly owner-guarded by their kernels) ...
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    warm = Orchestrator(_plan(3))
    list(warm.events())
    cache = exec_cache.cache()
    before = cache.compiled
    hits_before = {d: s["hits"] for d, s in cache.per_key_stats().items()}
    # ... then a 2-tenant fleet over the SAME window content (different
    # campaign seeds — keys are data, the executables are shared): zero
    # new compiles, pure hits on the window's step keys
    sched = CampaignScheduler()
    sched.admit(TenantSpec(name="x", plan=_plan(3).to_dict()))
    sched.admit(TenantSpec(name="y", plan=_plan(23).to_dict()))
    assert sched.run() == 0
    assert cache.compiled == before
    grew = [d for d, s in cache.per_key_stats().items()
            if s["hits"] > hits_before.get(d, 0)]
    assert grew, "shared-window fleet produced no per-key cache hits"
    assert all(s["misses"] >= 1 for s in cache.per_key_stats().values())


# --- durable queue: submit while the fleet runs -----------------------------

def test_submit_while_fleet_runs_is_admitted_and_served(tmp_path):
    q = SubmissionQueue(str(tmp_path / "spool"))
    late_solo = _solo_tallies(_plan(13, n_batches=3))
    state = {"submitted": None}

    def submit_late(s):
        if s.ticks == 2 and state["submitted"] is None:
            state["submitted"] = q.submit(TenantSpec(
                name="late", plan=_plan(13, n_batches=3).to_dict()))

    sched = CampaignScheduler(queue=q, on_tick=submit_late)
    sched.admit(TenantSpec(name="early", plan=_plan(3).to_dict()))
    assert sched.run() == 0
    assert sched._by_status() == {"complete": 2}
    t = sched.tenants["late"]
    assert t.ticket == state["submitted"] and t.queue_latency_s >= 0.0
    assert q.done(state["submitted"])["status"] == "complete"
    _assert_tenant_matches(sched, "late", late_solo)


# --- fleet observability ----------------------------------------------------

def test_fleet_stats_and_outputs(tmp_path):
    sched = CampaignScheduler(outdir=str(tmp_path))
    sched.admit(TenantSpec(name="a", plan=_plan(3, n_batches=2).to_dict()))
    sched.admit(TenantSpec(name="b", plan=_plan(5, n_batches=2).to_dict(),
                           weight=2.0))
    assert sched.run() == 0
    with open(os.path.join(str(tmp_path), "fleet_stats.json")) as f:
        doc = json.load(f)
    fleet = doc["fleet"]
    assert fleet["tenants_admitted"] == 2
    assert fleet["tenants_by_status"] == {"complete": 2}
    assert set(fleet["tenant_trials"]) == {"a", "b"}
    assert 0.0 < fleet["fairness_index"] <= 1.0
    assert 0.0 <= fleet["cache_hit_rate"] <= 1.0
    # per-tenant artifacts landed in each namespace
    for name in ("a", "b"):
        assert os.path.exists(os.path.join(
            str(tmp_path), "tenants", name, "stats.json"))


def test_graftlint_gl101_covers_service():
    # the CI lint gate's GL101 (bare-jit-must-route-through-exec_cache)
    # scope is extended over the service subsystem — regression-pin it
    from shrewd_tpu.analysis.config import load_config

    cfg = load_config(os.path.join(os.path.dirname(__file__), ".."))
    for f in ("shrewd_tpu/service/scheduler.py",
              "shrewd_tpu/service/queue.py",
              "shrewd_tpu/service/journal.py"):
        assert f in cfg.jit_modules
        assert f in cfg.checkpoint_modules
        assert f in cfg.deterministic_modules


# --- quota revocation (the sanctioned early-stop seam) ----------------------
#
# The scheduler-side contract the scenario-matrix Pareto loop builds on
# (shrewd_tpu/scenario/), tested here INDEPENDENT of scenario/: a
# supervising controller may withdraw a tenant's remaining service at
# any time; the decision is journaled before any state change, a
# running tenant drains to the terminal status "pruned" with its
# partial results first-class, a queued tenant prunes WITHOUT paying a
# plan elaboration, and the pruned status is excluded from fair share
# like quarantine — but is never an error.

def test_revoke_queued_tenant_prunes_without_elaboration(tmp_path):
    # the victim's plan CANNOT elaborate (missing trace file): pruning
    # it must not cost a plan build, so it lands in "pruned" with zero
    # failures — never in the quarantine path
    from shrewd_tpu.campaign.plan import CampaignPlan, TraceFileSpec

    q = SubmissionQueue(str(tmp_path / "spool"))
    bad = CampaignPlan(simpoints=[TraceFileSpec(
        name="w0", path=str(tmp_path / "missing.npz"))],
        structures=["regfile"], batch_size=32, max_trials=64,
        min_trials=64)
    ticket = q.submit(TenantSpec(name="victim", plan=bad.to_dict()))
    good_solo = _solo_tallies(_plan(3, n_batches=2))
    sched = CampaignScheduler(outdir=str(tmp_path / "out"), queue=q)
    sched.admit(TenantSpec(name="good",
                           plan=_plan(3, n_batches=2).to_dict()))
    sched._poll_queue()
    assert sched.revoke_quota("victim", "operator: superseded")
    assert sched.run() == 0
    t = sched.tenants["victim"]
    assert t.status == "pruned" and t.revoked == "operator: superseded"
    assert t.failures == 0 and t.trials == 0       # never elaborated
    done = q.done(ticket)
    assert done["status"] == "pruned"
    assert done["reason"] == "operator: superseded"
    _assert_tenant_matches(sched, "good", good_solo)


def test_revoke_running_tenant_drains_to_pruned_with_partial_results():
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.obs import metrics as obs_metrics

    calls = []

    def revoke_mid_run(sched):
        t = sched.tenants["victim"]
        if not calls and 0 < t.trials < 32 * 8:
            calls.append(sched.revoke_quota("victim", "test: dominated"))
            # idempotent: the second call on an already-revoked tenant
            # declines (callers may re-decide every tick)
            calls.append(sched.revoke_quota("victim", "again"))

    sched = CampaignScheduler(on_tick=revoke_mid_run)
    sched.admit(TenantSpec(name="victim",
                           plan=_plan(3, n_batches=8).to_dict()))
    sched.admit(TenantSpec(name="bystander",
                           plan=_plan(5, n_batches=2).to_dict()))
    assert sched.run() == 0
    assert calls == [True, False]
    t = sched.tenants["victim"]
    assert t.status == "pruned" and t.rc == Orchestrator.RC_PREEMPTED
    assert t.revoked == "test: dominated"
    # partial service, with the partial tallies summarized first-class
    assert 0 < t.trials < 32 * 8
    row = t.results["w0/regfile"]
    assert row["trials"] == t.trials and not row["converged"]
    assert sched.tenants["bystander"].status == "complete"
    # pruned is terminal: no further revoke, and the metrics surface
    # counts it separately from quarantine
    assert not sched.revoke_quota("victim", "too late")
    snap = obs_metrics.snapshot(sched)
    assert snap["fleet"]["pruned"] == 1
    assert snap["fleet"]["quarantined"] == 0
    assert sched.stats.fleet.pruned.fn() == 1


def test_revoke_unknown_tenant_raises():
    sched = CampaignScheduler()
    with pytest.raises(KeyError):
        sched.revoke_quota("nobody")


def test_revoke_decision_replays_after_hard_kill(tmp_path):
    # the revoke record is journaled BEFORE any state change: a hard
    # kill between the decision and the drain replays it on recovery,
    # and the re-queued tenant prunes without ever elaborating again
    from shrewd_tpu.service import FleetKilled

    state = {}

    def revoke_then_die(sched):
        t = sched.tenants["victim"]
        if t.trials >= 32 and not t.revoked:
            assert sched.revoke_quota("victim", "test: dominated")
            state["revoked_at"] = t.trials
            raise FleetKilled(137)      # dead before the drain tick

    sched = CampaignScheduler(outdir=str(tmp_path),
                              on_tick=revoke_then_die)
    sched.admit(TenantSpec(name="victim",
                           plan=_plan(3, n_batches=8).to_dict()))
    with pytest.raises(FleetKilled):
        sched.run()
    assert sched.tenants["victim"].status == "running"   # drain never ran

    rec = CampaignScheduler.recover(str(tmp_path))
    t = rec.tenants["victim"]
    assert t.revoked == "test: dominated"       # the WAL replayed it
    assert t.status == "queued"                 # resumable → re-queued
    assert rec.run() == 0
    t = rec.tenants["victim"]
    assert t.status == "pruned" and t.failures == 0
    assert t.trials == state["revoked_at"]      # decision-time service


def test_revoke_racing_completion_still_finalizes_pruned():
    # the revocation decision is authoritative over a cooperative
    # ending: a tenant revoked after its final batch (but before the
    # completion tick) still lands "pruned" — the journaled decision
    # and the terminal status may never disagree (the Pareto artifact's
    # decision list is keyed off both)
    from shrewd_tpu.campaign.orchestrator import Orchestrator

    def revoke_at_cap(sched):
        t = sched.tenants["victim"]
        if t.status == "running" and not t.revoked and t.trials >= 32 * 2:
            assert sched.revoke_quota("victim", "test: raced")

    sched = CampaignScheduler(on_tick=revoke_at_cap)
    sched.admit(TenantSpec(name="victim",
                           plan=_plan(3, n_batches=2).to_dict()))
    assert sched.run() == 0
    t = sched.tenants["victim"]
    assert t.rc == Orchestrator.RC_COMPLETE     # the campaign DID finish
    assert t.status == "pruned"                 # ...but the decision wins
    assert t.revoked == "test: raced"
    assert t.results["w0/regfile"]["trials"] == 64
