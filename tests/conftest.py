"""Test harness: force an 8-device virtual CPU platform.

The multi-chip analog of the reference's dist-gem5-on-localhost / NULL-build
testing posture (SURVEY §4): all sharding tests run on
``--xla_force_host_platform_device_count=8`` without TPU hardware.

IMPORTANT: this image's sitecustomize imports jax at interpreter startup with
``JAX_PLATFORMS=axon`` (the TPU tunnel), so jax's config default is already
baked by the time conftest runs — mutating ``os.environ`` here is NOT enough.
``jax.config.update("jax_platforms", ...)`` is authoritative post-import, and
XLA_FLAGS must be set before the first CPU backend *initialization* (lazy),
which no code has triggered yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Bound accumulated XLA-CPU compile state: a full-suite run (~300
    tests, hundreds of jit compilations on the 8-device host platform)
    was observed to segfault inside ``backend_compile_and_load`` late in
    the session (reproducibly at the same test in full-suite order, never
    in any subset).  Dropping dead executables between modules keeps the
    backend's live compilation state small; module-scoped fixtures die at
    the same boundary, so almost nothing live gets recompiled."""
    yield
    jax.clear_caches()
