"""Test harness: force an 8-device virtual CPU platform.

The multi-chip analog of the reference's dist-gem5-on-localhost / NULL-build
testing posture (SURVEY §4): all sharding tests run on
``--xla_force_host_platform_device_count=8`` without TPU hardware.

IMPORTANT: this image's sitecustomize imports jax at interpreter startup with
``JAX_PLATFORMS=axon`` (the TPU tunnel), so jax's config default is already
baked by the time conftest runs — mutating ``os.environ`` here is NOT enough.
``jax.config.update("jax_platforms", ...)`` is authoritative post-import, and
XLA_FLAGS must be set before the first CPU backend *initialization* (lazy),
which no code has triggered yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Child interpreters (hostdiff tools, dist_launch, multihost tests) re-run
# sitecustomize from PYTHONPATH; if that includes the axon TPU-tunnel site
# and the relay is wedged, every child hangs at first device query.  Tests
# are CPU-only by contract — scrub the tunnel site from what children see.
_pp = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
       if p and "axon_site" not in p]
if _pp:
    os.environ["PYTHONPATH"] = os.pathsep.join(_pp)
else:
    os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"


import pytest  # noqa: E402

# --- quick/slow tiering (reference TESTING.md quick/long analog) ---------
#
# `pytest -m quick` is the <2-minute smoke tier for CI-on-every-push; the
# full suite takes ~30 min on the 8-device virtual CPU mesh.  Because
# almost every test pays a ~4 s XLA compile, the quick tier is a curated
# WHITELIST (one representative per subsystem) rather than an exclusion
# list — anything unlisted is slow, so the runtime bound holds as tests
# are added.  SLOW_TESTS wins over a whole-module listing.

QUICK_MODULES = {
    # sub-second unit modules: host utilities, stats engine, m5.cpt
    # ingest, trace format, the dedicated smoke module
    "test_utils", "test_stats", "test_ingest", "test_trace",
    "test_quick_smoke", "test_bench",
    # backend resilience: mostly sub-second unit tests (watchdog, backoff,
    # re-probe, budget, ladder, checkpoint IO) plus a handful of ~4-13 s
    # injected-wedge / torn-checkpoint campaign integrations — the
    # failure-path smoke belongs in the on-every-push tier by design
    "test_resilience",
    # result integrity: invariant/ledger units plus the canary/audit/
    # quarantine campaign integrations and the v1→v5 upgrader chain —
    # same rationale as test_resilience (the corruption-path smoke must
    # run on every push)
    "test_integrity",
    # chaos harness + elastic layer: DSL/lease/heartbeat units plus the
    # injected-fault campaign integrations (each fault class survived
    # bit-identically) — the whole-resilience-stack smoke belongs in the
    # on-every-push tier like its two predecessors; the multi-process
    # kill/recover case stays slow-tier (tests/test_multihost.py)
    "test_chaos",
    # pipelined engine + executable cache: cache/watchdog units plus the
    # serial-vs-pipelined bit-identity integrations (ragged intervals,
    # chaos mid-interval, mid-grid checkpoint resume) — the perf-path
    # correctness smoke runs on every push like the layers it rides on
    "test_pipeline",
    # graftlint static analysis: AST-rule fixtures are sub-second; the
    # jaxpr-auditor certifications are trace-only (no XLA compile), and
    # the strict-admission integration rides the shared tiny-kernel
    # compiles — the lint gate's own correctness belongs in the tier
    # that runs the gate
    "test_graftlint",
    # multi-tenant fleet: queue/policy units plus the fleet-vs-solo
    # bit-identity integrations (chaos mid-fleet, drain/resume, shared-
    # window compile dedupe) — all fleets ride the same tiny-kernel
    # compiles through the shared executable cache, so the module is
    # compile-dominated once like its predecessors
    "test_fleet",
    # fleet survivability: journal/lock/spool units plus the hard-kill →
    # recover bit-identity integrations (kill_fleet at tick/journal
    # ordinals, torn journal tail, poison-tenant quarantine, livelock
    # watchdog) — the whole module reuses test_fleet's tiny-kernel
    # compiles through the shared executable cache (~12 s total), and
    # the crash-recovery smoke belongs in the on-every-push tier for
    # the same reason the chaos/integrity smokes do
    "test_fleet_survive",
    # device-resident run-until-CI: stopping-mirror parity sweeps are
    # sub-second; the fused-vs-host-loop bit-identity integrations reuse
    # the test_pipeline tiny-kernel geometry through the shared executable
    # cache, and the convergence-correctness smoke (the north-star loop
    # itself) belongs in the on-every-push tier like the layers under it
    "test_until_ci",
    # observability: tracer/exporter/metrics units are sub-second; the
    # trace-determinism and tracing-on/off bit-identity integrations
    # reuse the shared tiny-kernel compiles, and the observability-
    # never-perturbs-the-run contract guards every other pin in this
    # tier — it belongs on every push
    "test_obs",
    # scenario-matrix campaigns: expansion/Pareto-algebra units are
    # sub-second; the matrix-vs-solo, kill-recover and prune-replay
    # integrations ride the same tiny-kernel compiles through the
    # shared executable cache (zero-new-compiles is itself one of the
    # pins), and the closed-loop correctness smoke belongs on every
    # push like the fleet layers it drives
    "test_scenario",
    # federated fleet-of-fleets: chaos-DSL/supervisor/ETA units are
    # sub-second; the failover/partition/migration bit-identity
    # integrations and the bounded gateway-WAL crash sweep reuse the
    # fleet tier's tiny-kernel compiles through the shared executable
    # cache (~23 s total), and the survive-pod-death smoke belongs on
    # every push for the same reason the fleet-survive smoke does
    "test_federation",
    # streaming ingest: store/axes/spool/chaos-vocab units are
    # sub-second; the pipeline integrations (dedup O(1) + byte-identity,
    # torn-doc re-lift, single-flight, quarantine verdicts, kill-during-
    # lift resume) are tracer-dominated (~30 s, no jax), and the
    # binary-path-vs-plan-path bit-identity e2e + bounded ingest-surface
    # crash sweep (~105 s) are the acceptance pins for the binary-in
    # submission path — the crash-safety smoke belongs on every push
    # like the fleet/federation smokes it extends
    "test_ingest_pipeline",
}
QUICK_TESTS = {
    # one representative per subsystem (≈4-10 s each, compile-dominated)
    "test_null_fault_is_masked",           # dense replay semantics
    # live-profile fitting + exec-cache routing of the DesignSpace sweep
    # (the protect.py surfaces the scenario Pareto loop depends on)
    "test_from_tally_records_halfwidth_and_bounds",
    "test_from_tally_conservative_takes_upper_vulnerable_bounds",
    "test_design_space_evaluate_routes_through_exec_cache",
    "test_regfile_fault_consumed_is_sdc",  # inject→propagate→classify
    "test_unmapped_va_traps",              # VA crash model (MemMap)
    "test_fp_fault_propagates_to_sdc",     # FP µop lanes
    "test_lift_rate_is_high",              # capture → x86 lift
    "test_mulhu_bit_exact_across_backends",  # MULHU parity
    "test_latch_structure_parity_with_padding",  # chunked replay + oow fix
    # SimPoint-scale fast chunked path (tests/test_chunked_fast.py): one
    # representative each for fast-vs-exact bit-identity under forced
    # fallbacks, the content-addressed window store round-trip, and the
    # chunked route composing with quarantine recovery — the full
    # structure × engine sweep stays slow-tier
    "test_fast_fallback_lanes_still_bit_identical",
    "test_store_roundtrip_byte_identical",
    "test_chunked_quarantine_recovers_bit_identical",
}
QUICK_CLASSES = {
    "TestSuffixStems", "TestSimdSubset",   # emulator units, no capture
    "TestPairAlgebra",                     # 64-bit carry/borrow µop algebra
}
SLOW_TESTS = {
    "test_strmix_emu64_runs_to_exit",      # whole-program emu, ~30 s
    "test_probe_self_exits_never_hangs",   # cold jax import, ≤75 s bound
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        base = item.name.split("[", 1)[0]
        cls = item.cls.__name__ if item.cls else ""
        quick = (mod in QUICK_MODULES or base in QUICK_TESTS
                 or cls in QUICK_CLASSES) and base not in SLOW_TESTS
        item.add_marker(pytest.mark.quick if quick else pytest.mark.slow)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Bound accumulated XLA-CPU compile state: a full-suite run (~300
    tests, hundreds of jit compilations on the 8-device host platform)
    was observed to segfault inside ``backend_compile_and_load`` late in
    the session (reproducibly at the same test in full-suite order, never
    in any subset).  Dropping dead executables between modules keeps the
    backend's live compilation state small; module-scoped fixtures die at
    the same boundary, so almost nothing live gets recompiled."""
    yield
    jax.clear_caches()
