"""Test harness: force an 8-device virtual CPU platform.

The multi-chip analog of the reference's dist-gem5-on-localhost / NULL-build
testing posture (SURVEY §4): all sharding tests run on
``--xla_force_host_platform_device_count=8`` without TPU hardware.  Must run
before the first jax import anywhere in the test process.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
