"""Test harness: force an 8-device virtual CPU platform.

The multi-chip analog of the reference's dist-gem5-on-localhost / NULL-build
testing posture (SURVEY §4): all sharding tests run on
``--xla_force_host_platform_device_count=8`` without TPU hardware.

IMPORTANT: this image's sitecustomize imports jax at interpreter startup with
``JAX_PLATFORMS=axon`` (the TPU tunnel), so jax's config default is already
baked by the time conftest runs — mutating ``os.environ`` here is NOT enough.
``jax.config.update("jax_platforms", ...)`` is authoritative post-import, and
XLA_FLAGS must be set before the first CPU backend *initialization* (lazy),
which no code has triggered yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"
