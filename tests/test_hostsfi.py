"""Host-silicon differential AVF: the framework's classification vs the
real host CPU perturbed through ptrace (tools/hostsfi.cc).

The CI-scale version of the DIFF_AVF_r03.json campaign (VERDICT r2
next-round #2): same pipeline, fewer trials.  The reference analog is the
golden-stdout classification of a full campaign run
(/root/reference/tests/gem5/verifier.py:158 MatchStdout over
x86_spec/x86-spec-cpu2017.py:403-436).
"""

import json
import shutil
import subprocess

import numpy as np
import pytest

from shrewd_tpu.ingest import hostdiff as hd

needs_toolchain = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("objdump") is None,
    reason="host toolchain required")


def _ptrace_works() -> bool:
    try:
        paths = hd.build_tools()
        proc = subprocess.run([str(paths.workload)], capture_output=True,
                              timeout=10)
        return proc.returncode == 0
    except Exception:
        return False


pytestmark = [needs_toolchain,
              pytest.mark.skipif(not _ptrace_works(),
                                 reason="workload build/run failed")]


@pytest.fixture(scope="module")
def lifted():
    paths = hd.build_tools()
    trace, meta = hd.capture_and_lift_to_output(paths)
    return paths, trace, meta


def test_extended_lift_invariants(lifted):
    paths, trace, meta = lifted
    assert meta["output_syscalls"] >= 1
    assert len(meta["output_words"]) >= 1
    assert 0 < meta["window_macro_ops"] < meta["macro_ops"]
    assert meta["stats"]["lift_rate"] >= 0.95
    # every output event cuts inside the µop stream
    for ev in meta["output_events"]:
        assert 0 < ev["cut_uop"] <= len(trace.opcode)
        assert ev["macro"] >= meta["window_macro_ops"]


def test_golden_replay_clean(lifted):
    """The fault-free replay of the extended window must not diverge or
    trap — round 3's first regression was exactly a diverging golden
    (un-lifted indirect call dropping its return-address push)."""
    import jax

    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    _, trace, meta = lifted
    k = TrialKernel(trace, O3Config(enable_shrewd=False))
    g = k.golden
    assert not bool(g.diverged)
    assert not bool(g.trapped)


def test_hostdiff_agreement_ci():
    """≥100 paired trials: device classification within CI-loose gates of
    the host oracle (the 5k-trial campaign tightens these to ±0.02/0.97)."""
    rep = hd.run_diff(n_trials=120, seed=7)
    assert rep["trials"] == 120
    assert rep["agreement_vulnerable"] >= 0.90, rep
    assert rep["avf_abs_err"] <= 0.10, rep
    # the replay must never hide a host-visible error class entirely
    conf = np.asarray(rep["confusion_host_x_device"])
    host_vuln_dev_masked = conf[1, 0] + conf[2, 0]
    assert host_vuln_dev_masked <= 0.05 * rep["trials"], rep


def test_diff_avf_artifact_schema(tmp_path):
    """The committed DIFF_AVF artifact (when present) parses and meets the
    r3 gates — guards against stale or hand-edited artifacts."""
    art = hd.REPO / "DIFF_AVF_r03.json"
    if not art.exists():
        pytest.skip("artifact not yet generated")
    rep = json.loads(art.read_text())
    assert rep["trials"] >= 5000
    assert rep["avf_abs_err"] <= 0.02
    assert rep["agreement_vulnerable"] >= 0.97


def test_capture_window_matches_lift():
    """capture_window_macro_ops (the emu64 fast path) must agree with the
    full lift's macro-op count (review r3: emu64 paid a whole lift pass
    for this one integer)."""
    paths = hd.build_tools()
    w = hd.capture_window_macro_ops(paths)
    _tr, meta = hd.capture_and_lift(paths)
    assert w == meta["macro_ops"] > 0


@pytest.mark.quick
def test_demoted_exposed_escalation_rule():
    """_demoted_exposed semantics on a synthetic window: a fault in a
    register a LATER demoted instruction reads escalates, unless a pure
    (non-RMW) replayed write to the faulted phys lane kills it first."""
    import numpy as np

    from shrewd_tpu.ingest.hostdiff import _demoted_exposed
    from shrewd_tpu.isa import uops as U
    from shrewd_tpu.trace.format import Trace

    # 4 macro steps, 2 µops each: step2 writes r3 (pure LUI), others NOPs
    op = np.full(8, U.NOP, np.int32)
    dst = np.zeros(8, np.int32)
    op[4] = U.LUI                      # step 2, first µop: r3 = const
    dst[4] = 3
    op[6] = U.ADDI                     # step 3: r5 += 1 (RMW of r5)
    dst[6] = 5
    src1 = np.zeros(8, np.int32)
    src1[6] = 5
    tr = Trace(opcode=op, dst=dst, src1=src1,
               src2=np.zeros(8, np.int32), imm=np.zeros(8, np.uint32),
               taken=np.zeros(8, np.int32),
               init_reg=np.zeros(16, np.uint32),
               init_mem=np.zeros(8, np.uint32))
    meta = {"uop_start": [0, 2, 4, 6],
            "demoted_reads": [(3, [3, 5])],    # step 3 demotes, reads r3+r5
            "width": 32}
    coords = np.array([
        [0, 3, 1],    # fault r3 @0: killed by step2's pure LUI → clean
        [3, 3, 1],    # fault r3 @3 (same step as demoted read) → exposed
        [0, 5, 1],    # fault r5 @0: step3's ADDI is RMW → still exposed
        [0, 1, 1],    # fault r1: never read by a demotion → clean
    ], dtype=np.int64)
    got = _demoted_exposed(tr, meta, coords)
    assert got.tolist() == [False, True, True, False]
