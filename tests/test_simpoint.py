"""SimPoint BBV profiling + representative-window selection
(ingest/simpoint.py) — the reference's simpoint probe methodology
(/root/reference/src/cpu/simple/probes/simpoint.hh:82) rebuilt over
captured/emulated pc streams."""

import shutil

import numpy as np
import pytest

from shrewd_tpu.ingest.simpoint import (bbv_profile, choose_simpoints,
                                        simpoint_windows)

needs_toolchain = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("objdump") is None,
    reason="host toolchain required")


def _loop_stream(bodies):
    """Synthesize a pc stream: each phase executes its own loop body."""
    pcs = []
    for base, body_len, n in bodies:
        for _ in range(n):
            pcs.extend(range(base, base + body_len * 4, 4))
    return np.asarray(pcs, dtype=np.uint64)


def test_bbv_separates_phases():
    # two phases with disjoint code → BBVs cluster into two groups
    pcs = _loop_stream([(0x1000, 8, 200), (0x9000, 8, 200)])
    prof = bbv_profile(pcs, interval=160)
    sps = choose_simpoints(prof, k=2, seed=1)
    n_iv = prof.bbvs.shape[0]
    # intervals from phase 1 and phase 2 must land in different clusters
    labels = sps.labels
    phase1 = labels[: n_iv // 2 - 1]
    phase2 = labels[n_iv // 2 + 1:]
    assert len(set(phase1.tolist())) == 1
    assert len(set(phase2.tolist())) == 1
    assert phase1[0] != phase2[0]
    assert np.isclose(sps.weights.sum(), 1.0)


def test_block_heads_key_on_control_flow():
    # a taken backward branch starts a new block at the loop head
    pcs = np.asarray(list(range(0x100, 0x120, 4)) * 3, dtype=np.uint64)
    prof = bbv_profile(pcs, interval=len(pcs))
    assert 0x100 in prof.block_heads.tolist()
    assert prof.bbvs.shape[0] == 1
    assert prof.bbvs.sum() == len(pcs)


def test_deterministic_under_seed():
    pcs = _loop_stream([(0x1000, 6, 100), (0x5000, 10, 80), (0x9000, 4, 90)])
    prof = bbv_profile(pcs, interval=120)
    a = choose_simpoints(prof, k=3, seed=7)
    b = choose_simpoints(prof, k=3, seed=7)
    assert np.array_equal(a.intervals, b.intervals)
    assert np.array_equal(a.weights, b.weights)


@needs_toolchain
def test_simpoint_windows_lift_and_replay():
    """End-to-end on sort.c: pick 3 representative windows, lift each from
    emulated state, and verify the golden replay is clean — the
    restore-then-rewarm path with no checkpoint file in the loop."""
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.ops.trial import TrialKernel

    paths = hd.build_tools()
    windows, sps, prof = simpoint_windows(paths, interval=1500, k=3)
    assert len(windows) >= 2
    assert np.isclose(sps.weights.sum(), 1.0)
    for trace, meta in windows:
        assert meta["stats"]["lift_rate"] >= 0.9
        k = TrialKernel(trace, O3Config(enable_shrewd=False))
        assert not bool(k.golden.diverged)
        assert not bool(k.golden.trapped)
        assert 0.0 < meta["simpoint_weight"] <= 1.0


def test_phase_homogeneous_stream_does_not_crash():
    """All-identical BBVs made k-means++ pass an all-zero probability
    vector to rng.choice (review r3); now the init stops early and the
    single phase yields one full-weight cluster."""
    import numpy as np

    from shrewd_tpu.ingest.simpoint import BBVProfile, choose_simpoints

    n_iv = 20
    bbvs = np.tile(np.ones(8), (n_iv, 1))
    heads = np.arange(8, dtype=np.uint64)
    sp = choose_simpoints(
        BBVProfile(bbvs=bbvs, block_heads=heads, interval=160), k=3)
    assert len(sp.intervals) >= 1
    assert (sp.weights > 0).all()
    assert abs(sp.weights.sum() - 1.0) < 1e-9


def test_empty_clusters_are_dropped():
    """Zero-weight representatives must not survive (they cost an
    emulate+lift pass and contribute nothing to the weighted AVF)."""
    import numpy as np

    from shrewd_tpu.ingest.simpoint import BBVProfile, choose_simpoints

    # two distinct phases, k=3 → at most 2 non-empty clusters
    a = np.zeros((6, 8)); a[:, 0] = 100
    b = np.zeros((6, 8)); b[:, 7] = 100
    sp = choose_simpoints(BBVProfile(
        bbvs=np.concatenate([a, b]),
        block_heads=np.arange(8, dtype=np.uint64), interval=64),
        k=3, seed=1)
    assert (sp.weights > 0).all()
    assert len(sp.intervals) <= 2
    assert (sp.labels >= 0).all()
