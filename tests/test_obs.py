"""Observability subsystem (shrewd_tpu/obs/): tracer, exporters, flight
recorder, fleet metrics — and the contracts that make it safe to leave
on everywhere:

- the DISABLED tracer is a no-op constant (the default every hot path
  pays for);
- two identical runs emit byte-identical event streams after timestamp
  normalization (event identity is campaign coordinates, never wall
  clock or object identity) — including a chaos-quarantined run
  replayed;
- tracing on vs. off is bit-identical in every tally (observability
  never perturbs what it observes), for dense/hybrid/stratified and a
  2-tenant fleet;
- a quarantined run leaves a flight-recorder dump from which the
  failing batch's dispatch → integrity-verdict → quarantine →
  ladder-recovery timeline is reconstructable;
- the resident scheduler publishes an atomic metrics snapshot
  (metrics.json + Prometheus text) each tick.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from shrewd_tpu.obs import clock as obs_clock
from shrewd_tpu.obs import export as obs_export
from shrewd_tpu.obs import metrics as obs_metrics
from shrewd_tpu.obs import trace as obs_trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_clean_slate():
    """Tracing is process-global: every test starts and ends with the
    no-op constant and the real clocks."""
    obs_trace.disable()
    obs_clock.reset()
    yield
    obs_trace.disable()
    obs_clock.reset()


# --- tracer units -----------------------------------------------------------

def test_null_tracer_is_noop_constant():
    t = obs_trace.tracer()
    assert t is obs_trace.NULL_TRACER and not t.enabled
    # every method is a no-op; the context managers are ONE shared object
    t.emit("x", cat="y", b0=1)
    t.counter("d", 3)
    assert t.span("a") is t.span("b") is t.scope(k=1)
    with t.span("a"):
        pass
    assert t.snapshot() == [] and t.emitted == 0 and t.dropped == 0
    t.maybe_flight_dump("nothing")      # no path, no write, no raise


def test_tracer_ring_counters_and_disable_returns_window():
    live = obs_trace.enable(ring=4, timestamps=False)
    assert obs_trace.tracer() is live and live.enabled
    for i in range(6):
        live.emit("ev", cat="c", i=i)
    assert live.emitted == 6 and live.dropped == 2
    window = live.snapshot()
    assert [ev["seq"] for ev in window] == [2, 3, 4, 5]
    assert all(ev["ts"] is None for ev in window)
    assert live.by_name == {"ev": 6}
    prev = obs_trace.disable()
    assert prev is live and obs_trace.tracer() is obs_trace.NULL_TRACER
    # the returned tracer still holds its window for post-hoc export
    assert len(prev.snapshot()) == 4


def test_scope_merges_and_span_pairs():
    live = obs_trace.enable(timestamps=False)
    with live.scope(tenant="t0"):
        with live.span("interval", cat="dispatch", b0=3):
            live.counter("depth", 2, cat="dispatch")
    evs = live.snapshot()
    assert [e["ph"] for e in evs] == ["B", "C", "E"]
    assert all(e["args"]["tenant"] == "t0" for e in evs)
    assert evs[0]["args"]["b0"] == 3 and evs[2]["args"]["b0"] == 3
    assert evs[1]["args"]["value"] == 2
    # scope restored: later events carry no tenant
    live.emit("after")
    assert "tenant" not in live.snapshot()[-1]["args"]


def test_fake_clock_installs_and_resets():
    ticks = iter(range(100))
    obs_clock.install(mono=lambda: float(next(ticks)), wall=lambda: 1e9)
    live = obs_trace.enable()
    live.emit("a")
    live.emit("b")
    ts = [e["ts"] for e in live.snapshot()]
    assert ts == [0.0, 1.0] and obs_clock.now() == 1e9
    obs_clock.reset()
    assert obs_clock.now() > 1e9 - 1   # real epoch again


# --- exporters --------------------------------------------------------------

def test_normalize_strips_only_timestamps():
    evs = [{"seq": 0, "name": "a", "cat": "c", "ph": "i",
            "args": {"b0": 1}, "ts": 12.5}]
    norm = obs_export.normalize(evs)
    assert norm == [{"seq": 0, "name": "a", "cat": "c", "ph": "i",
                     "args": {"b0": 1}}]
    # canonical bytes are insensitive to timestamps and key order
    evs2 = [{"ts": 99.0, "args": {"b0": 1}, "ph": "i", "cat": "c",
             "name": "a", "seq": 0}]
    assert (obs_export.canonical_bytes(evs)
            == obs_export.canonical_bytes(evs2))


def test_to_trace_event_lanes_and_phases():
    live = obs_trace.enable(timestamps=False)
    with live.scope(tenant="t0"):
        with live.span("interval", cat="dispatch", sp="w0",
                       structure="regfile", b0=0):
            pass
    live.emit("quarantine", cat="integrity", sp="w0", structure="regfile")
    live.counter("depth", 1, cat="dispatch")
    doc = obs_export.to_trace_event(live.snapshot())
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta if m["name"] == "process_name"} \
        == {"t0", "campaign"}
    b = next(e for e in evs if e["ph"] == "b")
    e = next(e for e in evs if e["ph"] == "e")
    assert b["id"] == e["id"]           # async pair by semantic identity
    c = next(e for e in evs if e["ph"] == "C")
    assert c["args"] == {"depth": 1}
    inst = next(e for e in evs if e["ph"] == "i")
    assert inst["name"] == "quarantine"
    # clock-free events render on the deterministic seq axis
    assert b["ts"] == 0.0


def test_summarize_and_render_text():
    live = obs_trace.enable(timestamps=False)
    with live.scope(tenant="t1"):
        with live.span("tick", cat="fleet"):
            live.emit("quarantine", cat="integrity", sp="w0",
                      structure="rob")
    s = obs_export.summarize(live.snapshot())
    assert s["events"] == 3 and s["by_name"]["quarantine"] == 1
    assert s["tenants"] == ["t1"] and s["unclosed_spans"] == 0
    assert "w0/rob" in s["lanes"]
    txt = obs_export.render_text(live.snapshot())
    assert "quarantine" in txt and "tenant=t1" in txt


# --- campaign-level contracts -----------------------------------------------

def _tiny_plan(seed=0, mode="hybrid", stratify=False, n_batches=3,
               sync_every=1, chaos=None):
    from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
    from shrewd_tpu.models.o3 import O3Config
    from shrewd_tpu.trace.synth import WorkloadConfig

    p = CampaignPlan(
        simpoints=[WorkloadSpec(
            name="w0", workload=WorkloadConfig(n=96, nphys=32, mem_words=64,
                                               working_set_words=32,
                                               seed=7))],
        structures=["regfile"], batch_size=32, target_halfwidth=0.5,
        max_trials=32 * n_batches, min_trials=32 * n_batches, seed=seed,
        machine=O3Config(replay_kernel=mode), stratify=stratify)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    p.pipeline.sync_every = sync_every
    return p


def _run(plan, chaos=None, outdir=None):
    from shrewd_tpu.campaign.orchestrator import Orchestrator
    from shrewd_tpu.chaos import ChaosEngine
    from shrewd_tpu.sim.exit_event import ExitEvent

    orch = Orchestrator(plan, outdir=outdir)
    if chaos is not None:
        orch.attach_chaos(ChaosEngine(chaos))
    events = list(orch.events())
    results = (dict(events[-1][1])
               if events[-1][0] is ExitEvent.CAMPAIGN_COMPLETE else None)
    return orch, results


CORRUPT = {"faults": [{"kind": "corrupt_tally", "at_batch": 1,
                       "delta": 1}]}


def test_two_identical_runs_emit_byte_identical_streams():
    """Event identity is campaign coordinates: same plan, same process,
    warm cache → byte-identical streams after timestamp normalization."""
    _run(_tiny_plan())                       # warm (compiles traced out)
    streams = []
    for _ in range(2):
        live = obs_trace.enable()
        _run(_tiny_plan())
        obs_trace.disable()
        streams.append(obs_export.canonical_bytes(live.snapshot()))
        assert live.emitted > 0
    assert streams[0] == streams[1]


def test_chaos_quarantined_run_replays_byte_identical():
    """The quarantine→ladder-recovery path is deterministic too: a
    corrupt-tally run and its replay produce the same stream, and the
    stream tells the whole story in order."""
    _run(_tiny_plan(), chaos=CORRUPT)        # warm incl. recovery tiers
    streams, names = [], None
    for _ in range(2):
        live = obs_trace.enable()
        _run(_tiny_plan(), chaos=CORRUPT)
        obs_trace.disable()
        streams.append(obs_export.canonical_bytes(live.snapshot()))
        names = [e["name"] for e in live.snapshot()]
    assert streams[0] == streams[1]
    # dispatch → verdict(bad) → quarantine → verdict(ok) → recovery
    i_inj = names.index("chaos_inject")
    i_q = names.index("quarantine")
    i_rec = names.index("quarantine_recovered")
    assert i_inj < i_q < i_rec
    assert "batch_believed" in names[i_rec:]


@pytest.mark.parametrize("mode,stratify", [
    ("hybrid", False), ("dense", False), ("hybrid", True)])
def test_tracing_on_vs_off_is_bit_identical(mode, stratify):
    _, off = _run(_tiny_plan(mode=mode, stratify=stratify))
    obs_trace.enable()
    _, on = _run(_tiny_plan(mode=mode, stratify=stratify))
    obs_trace.disable()
    for k in off:
        np.testing.assert_array_equal(off[k].tallies, on[k].tallies)
        assert off[k].trials == on[k].trials


def test_pipelined_run_records_interval_spans():
    """sync_every > 1 emits paired in-flight interval spans plus the
    dispatch-depth counter — the async timeline the exporter draws."""
    _run(_tiny_plan(n_batches=8, sync_every=4))   # warm interval step
    live = obs_trace.enable()
    _run(_tiny_plan(n_batches=8, sync_every=4))
    obs_trace.disable()
    evs = live.snapshot()
    b = [e for e in evs if e["name"] == "interval_inflight"
         and e["ph"] == "B"]
    e = [e for e in evs if e["name"] == "interval_inflight"
         and e["ph"] == "E"]
    assert b and len(b) == len(e)
    assert any(e["name"] == "dispatch_depth" and e["ph"] == "C"
               for e in evs)
    s = obs_export.summarize(evs)
    assert s["unclosed_spans"] == 0


def test_flight_recorder_dump_reconstructs_quarantine(tmp_path):
    """The acceptance artifact: a chaos-quarantined run with an outdir
    leaves flightrec.json; the failing batch's dispatch →
    integrity-verdict → quarantine → ladder-recovery timeline is
    reconstructable from that one file, and write_outputs exports the
    Perfetto trace alongside."""
    obs_trace.enable()
    orch, results = _run(_tiny_plan(), chaos=CORRUPT,
                         outdir=str(tmp_path))
    orch.write_outputs()
    obs_trace.disable()
    assert results is not None
    rec_path = tmp_path / "flightrec.json"
    assert rec_path.exists()
    with open(rec_path) as f:
        rec = json.load(f)
    assert rec["reason"] == "quarantine_evidence"
    evs = rec["events"]
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    bad = by_name["invariant_verdict"][0]["args"]
    q = by_name["quarantine"][0]["args"]
    rcv = by_name["quarantine_recovered"][0]["args"]
    # the timeline names the SAME failing batch at every step
    assert not by_name["invariant_verdict"][1]["args"]["ok"]
    assert q["batch_id"] == rcv["batch_id"] \
        == by_name["invariant_verdict"][1]["args"]["batch_id"] == 1
    assert q["sp"] == "w0" and q["structure"] == "regfile"
    assert not q["fatal"] and rcv["attempts"] >= 2
    # Perfetto export loads and carries the same story
    with open(tmp_path / "trace.json") as f:
        doc = json.load(f)
    assert any(r["name"] == "quarantine" for r in doc["traceEvents"])
    # stats bridge: the obs group counted what the tracer did
    from shrewd_tpu import stats as statsmod

    obs_stats = statsmod.to_dict(orch.stats)["obs"]
    assert obs_stats["tracing"] == 0          # disabled again by now
    assert (tmp_path / "stats.json").exists()


def test_flight_dump_is_noop_without_tracing_or_outdir(tmp_path):
    assert obs_trace.flight_dump(str(tmp_path), "x") is None
    obs_trace.enable()
    assert obs_trace.flight_dump(None, "x") is None
    path = obs_trace.flight_dump(str(tmp_path), "why", batch_id=4)
    obs_trace.disable()
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "why" and doc["coords"] == {"batch_id": 4}


# --- fleet: per-tenant lanes + live metrics ---------------------------------

def test_traced_fleet_bit_identical_with_metrics(tmp_path):
    from shrewd_tpu.service import CampaignScheduler, TenantSpec

    solos = {}
    warm = []
    for seed in (0, 9):
        orch, results = _run(_tiny_plan(seed=seed, n_batches=2))
        warm.append(orch)    # keep kernels alive (owner-guarded cache)
        solos[seed] = {k: v.tallies for k, v in results.items()}
    obs_trace.enable()
    sched = CampaignScheduler(outdir=str(tmp_path))
    sched.admit(TenantSpec(name="t0", plan=_tiny_plan(
        seed=0, n_batches=2).to_dict()))
    sched.admit(TenantSpec(name="t9", plan=_tiny_plan(
        seed=9, n_batches=2).to_dict()))
    assert sched.run() == 0
    live = obs_trace.disable()
    for name, seed in (("t0", 0), ("t9", 9)):
        got = sched.tenant_tallies(name)
        for k, t in solos[seed].items():
            np.testing.assert_array_equal(got[k], np.asarray(t))
    # per-tenant lanes: scheduler + nested seam events carry the tenant
    evs = live.snapshot()
    tenants = {e["args"].get("tenant") for e in evs} - {None}
    assert tenants == {"t0", "t9"}
    for name in ("tenant_admit", "tenant_tick", "tenant_done",
                 "journal_append"):
        assert any(e["name"] == name for e in evs), name
    nested = [e for e in evs if e["name"] == "batch_believed"]
    assert nested and all("tenant" in e["args"] for e in nested)
    # live metrics: atomic snapshot + Prometheus text published per tick
    snap = obs_metrics.read(str(tmp_path))
    assert snap["tick"] == snap["fleet"]["ticks"] > 0
    for name in ("t0", "t9"):
        row = snap["tenants"][name]
        assert row["status"] == "complete" and row["trials"] == 64
        assert "halfwidth" in row and "w0/regfile" in row["halfwidth"]
    assert 0.0 < snap["fleet"]["fairness_index"] <= 1.0
    with open(tmp_path / "metrics.prom") as f:
        prom = f.read()
    assert 'shrewd_fleet_tenant_trials{tenant="t0"} 64' in prom
    assert "shrewd_fleet_fairness_index" in prom
    # fleet-level Perfetto export rides write_outputs
    with open(tmp_path / "trace.json") as f:
        doc = json.load(f)
    lanes = {m["args"]["name"] for m in doc["traceEvents"]
             if m.get("ph") == "M" and m["name"] == "process_name"}
    assert {"t0", "t9"} <= lanes


def test_prometheus_text_renders_a_snapshot():
    snap = {"tick": 3,
            "fleet": {"ticks": 3, "fairness_index": 0.98,
                      "cache_hit_rate": 0.75, "journal_depth": 4,
                      "recoveries": 1, "quarantined": 0},
            "tenants": {"a": {"trials": 64, "trials_per_s": 10.0,
                              "ticks": 2, "vtime": 64.0,
                              "queue_latency_s": 0.5, "failures": 0,
                              "halfwidth": {"w0/regfile": 0.21}}}}
    text = obs_metrics.prometheus_text(snap)
    assert "# TYPE shrewd_fleet_ticks gauge" in text
    assert "shrewd_fleet_recoveries 1" in text
    assert ('shrewd_fleet_tenant_halfwidth{lane="w0/regfile",'
            'tenant="a"} 0.21') in text
    # exposition grouping: with 2+ tenants every family's samples are
    # CONTIGUOUS under one HELP/TYPE (promtool rejects interleaving)
    snap["tenants"]["b"] = {"trials": 32, "trials_per_s": 5.0,
                            "ticks": 1, "vtime": 32.0,
                            "queue_latency_s": 0.1, "failures": 1}
    lines = obs_metrics.prometheus_text(snap).splitlines()
    trials = [i for i, ln in enumerate(lines)
              if ln.startswith("shrewd_fleet_tenant_trials{")]
    assert trials == list(range(trials[0], trials[0] + 2))
    assert sum(1 for ln in lines
               if ln == "# TYPE shrewd_fleet_tenant_trials gauge") == 1
    # label values are exposition-escaped: a hostile tenant name cannot
    # inject lines or break the scrape
    snap["tenants"] = {'a"b\n': {"trials": 1}}
    text = obs_metrics.prometheus_text(snap)
    assert 'tenant="a\\"b\\n"' in text and '\nb\n' not in text


# --- tools/obs.py -----------------------------------------------------------

def test_obs_cli_summarize_and_timeline(tmp_path):
    obs_trace.enable()
    orch, _ = _run(_tiny_plan(), chaos=CORRUPT, outdir=str(tmp_path))
    orch.write_outputs()
    obs_trace.disable()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    tool = os.path.join(REPO_ROOT, "tools", "obs.py")
    r = subprocess.run(
        [sys.executable, tool, "--summarize",
         str(tmp_path / "flightrec.json")],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["reason"] == "quarantine_evidence"
    assert summary["by_name"]["quarantine"] == 1
    # the Perfetto document loads through the same CLI
    r = subprocess.run(
        [sys.executable, tool, "--summarize", str(tmp_path / "trace.json")],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0 and json.loads(r.stdout)["events"] > 0
    r = subprocess.run(
        [sys.executable, tool, "--timeline",
         str(tmp_path / "flightrec.json")],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0 and "quarantine" in r.stdout
