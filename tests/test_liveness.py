"""Unit tests for the post-window liveness analysis (ingest/liveness.py).

classify_access is pure (Inst + captured regs → read/write sets), so the
rule table is testable without ptrace; the end-to-end test needs the build
toolchain and exercises a real post-window capture of sort.c.
"""

import shutil

import numpy as np
import pytest

from shrewd_tpu.ingest.lift import Inst, Operand
from shrewd_tpu.ingest import liveness as lv
from shrewd_tpu.ingest.liveness import (RAX, RCX, RDX, RSP, RBP, RSI, RDI,
                                        R12, classify_access)


def _regs(over=None):
    r = np.zeros(18, dtype=np.uint64)
    r[RSP] = 0x7FFF0000
    r[RBP] = 0x7FFF0100
    r[RSI] = 0x500000
    r[RDI] = 0x600000
    r[RCX] = 4
    for k, v in (over or {}).items():
        r[k] = v
    return r


def _inst(mnem, *ops):
    return Inst(0x1000, 4, mnem, list(ops), None)


def reg_op(idx, width=64):
    return Operand("reg", reg=idx, width=width)


def mem_op(base=-1, disp=0, index=-1, scale=1):
    return Operand("mem", base=base, index=index, scale=scale, disp=disp)


def test_mov_load_reads_mem_writes_reg():
    acc = classify_access(_inst("mov", mem_op(base=RSI, disp=8),
                                reg_op(RAX)), _regs())
    assert RSI in acc.reg_reads and RAX in acc.reg_writes
    assert acc.mem_reads == ((0x500008, 8),) and not acc.mem_writes


def test_mov_store_writes_mem():
    acc = classify_access(_inst("mov", reg_op(RAX), mem_op(base=RDI)),
                          _regs())
    assert RAX in acc.reg_reads and acc.mem_writes == ((0x600000, 8),)


def test_partial_reg_write_counts_as_read():
    # writes to %al merge with the old rax value
    acc = classify_access(_inst("mov", mem_op(base=RSI), reg_op(RAX, 8)),
                          _regs())
    assert RAX in acc.reg_reads and RAX in acc.reg_writes


def test_lea_does_not_touch_memory():
    acc = classify_access(_inst("lea", mem_op(base=RSI, disp=0x30),
                                reg_op(RDI)), _regs())
    assert not acc.mem_reads and not acc.mem_writes
    assert RSI in acc.reg_reads and RDI in acc.reg_writes


def test_push_pop_ret():
    acc = classify_access(_inst("push", reg_op(R12)), _regs())
    assert R12 in acc.reg_reads and acc.mem_writes == ((0x7FFF0000 - 8, 8),)
    acc = classify_access(_inst("pop", reg_op(R12)), _regs())
    assert acc.mem_reads == ((0x7FFF0000, 8),) and R12 in acc.reg_writes
    acc = classify_access(_inst("ret"), _regs())
    assert acc.mem_reads == ((0x7FFF0000, 8),)


def test_rmw_reads_and_writes_dst():
    acc = classify_access(_inst("add", reg_op(RCX), reg_op(RAX)), _regs())
    assert RCX in acc.reg_reads and RAX in acc.reg_reads
    assert RAX in acc.reg_writes


def test_cmp_reads_only():
    acc = classify_access(_inst("cmp", reg_op(RCX), reg_op(RAX)), _regs())
    assert not acc.reg_writes and not acc.mem_writes


def test_write_syscall_reads_buffer_and_stops_on_exit():
    acc = classify_access(_inst("syscall"),
                          _regs({RAX: 1, RDX: 9}))
    assert (0x500000, 9) in acc.mem_reads
    assert not acc.stop
    acc = classify_access(_inst("syscall"), _regs({RAX: 231}))
    assert acc.stop


def test_rep_movs_conservative_ranges():
    # both ranges are marked LIVE (reads) — with unknown element size a
    # mis-sized DEAD marking could hide a host-visible SDC
    acc = classify_access(_inst("rep", reg_op(-2)), _regs())
    assert any(a == 0x500000 for a, _ in acc.mem_reads)
    assert any(a == 0x600000 for a, _ in acc.mem_reads)
    assert not acc.mem_writes
    # rcx = 0: no access at all
    acc = classify_access(_inst("rep", reg_op(-2)), _regs({RCX: 0}))
    assert not acc.mem_reads and not acc.mem_writes


def test_subword_store_marks_word_live_not_dead():
    # movb writes one byte: the containing word keeps 3 live bytes, so a
    # DEAD marking would hide SDC there — analyze must mark it LIVE
    nt = lv.NativeTrace(0, 0, np.stack([
        _regs({16: 0x1000}), _regs({16: 0x1004})]), [])
    insts = {0x1000: _inst("movb", Operand("imm", imm=7),
                           mem_op(base=RDI)),
             0x1004: _inst("syscall")}
    res = lv.analyze(nt._replace(
        steps=np.stack([_regs({16: 0x1000}),
                        _regs({RAX: 231, 16: 0x1004})])), insts)
    assert 0x600000 in res.mem_live32


def test_unknown_mnemonic_is_conservative():
    acc = classify_access(_inst("fxsave64", mem_op(base=RDI)), _regs())
    assert acc.unknown
    assert acc.mem_reads and acc.mem_writes      # both directions assumed


@pytest.mark.skipif(shutil.which("gcc") is None or
                    shutil.which("objdump") is None,
                    reason="host toolchain required")
def test_sort_post_window_liveness_end_to_end():
    from shrewd_tpu.ingest import hostdiff as hd
    from shrewd_tpu.ingest.liveness import post_window_liveness

    paths = hd.build_tools()
    trace, meta = hd.capture_and_lift(paths)
    res = post_window_liveness(paths, meta["clusters"])
    assert not res.truncated                 # exit reached
    assert res.reg_live[RSP]                 # stack pointer always read
    # data[] is read by the post-window checksum loop → live words exist
    mask = res.mem_word_mask(meta["clusters"], trace.mem_words)
    assert mask.sum() >= 48                  # the 48-int array at minimum
