"""FP µop datapath (FADD/FSUB/FMUL/FDIV) across every backend.

The FP contract (isa/uops.py): IEEE round-to-nearest with FTZ on inputs
and outputs plus canonical quiet NaN, so the XLA dense kernel, the taint
kernel, the C++ golden oracle, and the scalar python semantics produce
identical BITS — making FP fault trials classifiable bit-exactly, the way
the reference's shadow-FU detection chiefly targets FP units
(/root/reference/src/cpu/FuncUnitConfig.py, fu_pool.cc:177-294).
"""

import jax
import jax.numpy as jnp
import numpy as np

from shrewd_tpu import native
from shrewd_tpu.isa import semantics, uops as U
from shrewd_tpu.models.o3 import Fault, KIND_FU, KIND_REGFILE, O3Config
from shrewd_tpu.ops import classify as C
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng


def _fp_trace(n=160, seed=11):
    return generate(WorkloadConfig(
        n=n, nphys=32, mem_words=64, working_set_words=32, seed=seed,
        frac_alu=0.3, frac_mul=0.05, frac_load=0.1, frac_store=0.1,
        frac_branch=0.05, frac_fp=0.35))


def test_fp_trace_contains_fp_ops():
    t = _fp_trace()
    assert int(U.is_fp(t.opcode).sum()) > 20


def test_scalar_semantics_bits():
    f = np.float32
    bits = lambda x: int(np.float32(x).view(np.uint32))
    # exact IEEE results
    assert semantics.alu(U.FADD, bits(f(1.5)), bits(f(2.25)), 0) \
        == bits(f(3.75))
    assert semantics.alu(U.FMUL, bits(f(3.0)), bits(f(-2.0)), 0) \
        == bits(f(-6.0))
    # x/0 = inf (no trap, unlike integer DIV)
    assert semantics.alu(U.FDIV, bits(f(1.0)), 0, 0) == bits(np.inf)
    # 0/0 = canonical quiet NaN
    assert semantics.alu(U.FDIV, 0, 0, 0) == 0x7FC00000
    # subnormal result flushes to signed zero
    tiny = bits(f(2.0 ** -130))      # already subnormal input → flushed
    assert semantics.alu(U.FADD, tiny, 0, 0) == 0
    # NaN payloads canonicalize
    assert semantics.alu(U.FADD, 0x7F800001, bits(f(1.0)), 0) == 0x7FC00000


def test_dense_matches_scalar_golden():
    """Fault-free dense replay == scalar semantics on an FP-heavy trace."""
    t = _fp_trace()
    k = TrialKernel(t, O3Config(enable_shrewd=False))
    reg = t.init_reg.copy()
    mem = t.init_mem.copy()
    semantics.scalar_replay(t, reg, mem)
    assert np.array_equal(np.asarray(k.golden.reg), reg)
    assert np.array_equal(np.asarray(k.golden.mem), mem)


def test_native_golden_matches_device_on_fp():
    """C++ golden oracle vs device kernel, FP trace, sampled faults."""
    t = _fp_trace()
    k = TrialKernel(t, O3Config(shadow_coverage=[0.4] * U.N_OPCLASSES))
    keys = prng.trial_keys(prng.campaign_key(4), 256)
    faults = k.sampler("regfile").sample_batch(keys)
    fk, fc, fe, fb, fs = (np.asarray(x) for x in faults)
    cov = np.asarray(k.shadow_cov)
    base = native.golden_trials(t, fk, fc, fe, fb, fs, cov)
    dev = np.asarray(k.run_batch(faults))
    assert np.array_equal(base, dev)


def test_taint_hybrid_matches_dense_on_fp():
    t = _fp_trace()
    k = TrialKernel(t, O3Config())
    keys = prng.trial_keys(prng.campaign_key(6), 128)
    faults = k.sample_batch(keys, "regfile")
    hybrid = k.run_batch_hybrid(faults, may_latch=False)
    dense = np.asarray(k.run_batch(faults))
    assert np.array_equal(hybrid, dense)


def test_fp_fault_propagates_to_sdc():
    """A flipped mantissa bit feeding an FMUL chain must reach SDC."""
    from shrewd_tpu.trace.format import Trace

    bits = lambda x: np.uint32(np.float32(x).view(np.uint32))
    init_reg = np.zeros(32, dtype=np.uint32)
    init_reg[1] = bits(1.5)
    init_reg[2] = bits(2.0)
    t = Trace(opcode=np.array([U.FMUL, U.FADD], np.int32),
              dst=np.array([3, 4], np.int32),
              src1=np.array([1, 3], np.int32),
              src2=np.array([2, 3], np.int32),
              imm=np.zeros(2, np.uint32), taken=np.zeros(2, np.int32),
              init_reg=init_reg, init_mem=np.zeros(64, np.uint32))
    k = TrialKernel(t, O3Config(enable_shrewd=False))
    f = Fault(kind=jnp.int32(KIND_REGFILE), cycle=jnp.int32(0),
              entry=jnp.int32(1), bit=jnp.int32(20),
              shadow_u=jnp.float32(1.0))
    r = jax.jit(k._replay_one)(f)
    assert int(C.classify(r, k.golden)) == C.OUTCOME_SDC


def test_fp_shadow_fu_detects():
    """A FU fault on an FP µop is caught by the FP shadow units when
    coverage is full — the FP half of the SHREWD detection story."""
    t = _fp_trace(n=64, seed=3)
    k = TrialKernel(t, O3Config(shadow_coverage=[1.0] * U.N_OPCLASSES))
    fp_idx = int(np.nonzero(U.is_fp(t.opcode))[0][0])
    f = Fault(kind=jnp.int32(KIND_FU), cycle=jnp.int32(fp_idx),
              entry=jnp.int32(fp_idx), bit=jnp.int32(3),
              shadow_u=jnp.float32(0.0))
    r = jax.jit(k._replay_one)(f)
    assert bool(r.detected)


def test_fp_opclasses_cover_reference_fu_classes():
    """The FU pool models the reference's FP unit classes with shadow
    eligibility (FuncUnitConfig.py FP_ALU / FP_MultDiv)."""
    from shrewd_tpu.models.fupool import FUPoolConfig

    cfg = FUPoolConfig()
    caps = {c for d in cfg.descs() for c in d.capabilities}
    assert U.OC_FP_ALU in caps and U.OC_FP_MULT in caps
    assert U.OC_FP_ALU in cfg.shadow_eligible
    assert U.OC_FP_MULT in cfg.shadow_eligible
    # FP_ALU can approximately check FP multiplies as a shadow
    assert U.OC_FP_MULT in cfg.fp_alu.approx_capabilities
