// Native runtime for shrewd_tpu: C ABI shared by the golden kernel and the
// trace engine.  This is the framework's C++ tier — the counterpart of the
// reference's C++ simulation core (gem5's src/sim + src/cpu), reduced to the
// roles the TPU design keeps on the host: the serial golden oracle
// (CheckerCPU pattern, reference src/cpu/checker/cpu.hh) and the workload
// engine (traffic-generator pattern, reference cpu/testers/traffic_gen).
//
// Semantics here MUST stay bit-identical to shrewd_tpu/isa/semantics.py and
// shrewd_tpu/ops/replay.py; the differential tests in
// tests/test_native_diff.py enforce it.
#ifndef SHREWD_NATIVE_H
#define SHREWD_NATIVE_H

#include <cstdint>

extern "C" {

// --- µop opcodes (mirror shrewd_tpu/isa/uops.py) ---
enum Opcode : int32_t {
  OP_NOP = 0, OP_ADD, OP_SUB, OP_AND, OP_OR, OP_XOR, OP_SLL, OP_SRL, OP_SRA,
  OP_ADDI, OP_ANDI, OP_ORI, OP_XORI, OP_LUI, OP_MUL, OP_SLT, OP_SLTU,
  OP_DIV, OP_REM, OP_DIVU, OP_REMU,
  OP_LOAD, OP_STORE, OP_BEQ, OP_BNE, OP_BLT, OP_BGE,
  OP_FADD, OP_FSUB, OP_FMUL, OP_FDIV,
  OP_MULHU,   // high32(a*b) unsigned (divide-by-constant idiom)
  N_OPCODES
};

enum OpClass : int32_t {
  OC_INT_ALU = 0, OC_INT_MULT, OC_MEM_READ, OC_MEM_WRITE, OC_NONE,
  OC_FP_ALU, OC_FP_MULT,
  N_OPCLASSES
};

// --- fault kinds (mirror shrewd_tpu/models/o3.py) ---
enum FaultKind : int32_t {
  KIND_NONE = 0, KIND_REGFILE, KIND_FU, KIND_ROB_DST, KIND_IQ_SRC1,
  KIND_IQ_SRC2, KIND_LSQ_ADDR, KIND_LSQ_DATA, KIND_LATCH_OP, KIND_LATCH_IMM
};

// --- outcomes (mirror shrewd_tpu/ops/classify.py) ---
enum Outcome : int32_t {
  OUTCOME_MASKED = 0, OUTCOME_SDC, OUTCOME_DUE, OUTCOME_DETECTED
};

struct TraceView {       // SoA borrow of a trace window (not owned)
  const int32_t* opcode;
  const int32_t* dst;
  const int32_t* src1;
  const int32_t* src2;
  const uint32_t* imm;
  const int32_t* taken;
  int32_t n;
  int32_t nphys;      // power of two
  int32_t mem_words;  // power of two
};

struct FaultView {       // SoA borrow of a fault batch
  const int32_t* kind;
  const int32_t* cycle;
  const int32_t* entry;
  const int32_t* bit;
  const float* shadow_u;
  int32_t n_trials;
};

// Run the fault-free replay; writes final_reg[nphys], final_mem[mem_words].
void shrewd_golden_replay(const TraceView* tr, const uint32_t* init_reg,
                          const uint32_t* init_mem, uint32_t* final_reg,
                          uint32_t* final_mem);

// Run a batch of serial trials; writes outcomes[n_trials].
// coverage: float[tr->n] per-µop shadow detection probability (FU-pool
// availability folded in by the host, shrewd_tpu/models/fupool.py).
// Returns the number of trials run.
int32_t shrewd_golden_trials(const TraceView* tr, const uint32_t* init_reg,
                             const uint32_t* init_mem, const FaultView* faults,
                             const float* coverage, int32_t compare_regs,
                             int32_t* outcomes);

// Synthetic workload engine: fills caller-allocated SoA arrays (sizes per
// TraceView) and the initial machine state, executing as it generates.
// Returns 0 on success, nonzero on bad parameters.
struct WorkloadParams {
  uint64_t seed;
  int32_t n;
  int32_t nphys;
  int32_t mem_words;
  int32_t working_set_words;
  float frac_alu, frac_mul, frac_load, frac_store, frac_branch;
  float locality;
  float reuse_geo_p;
};

int32_t shrewd_generate_trace(const WorkloadParams* p, int32_t* opcode,
                              int32_t* dst, int32_t* src1, int32_t* src2,
                              uint32_t* imm, int32_t* taken,
                              uint32_t* init_reg, uint32_t* init_mem);

}  // extern "C"

// --- shared µop semantics (single definition for golden kernel + engine) ---
// Must stay bit-identical to shrewd_tpu/isa/semantics.py and ops/replay.py;
// tests/test_native_diff.py enforces the contract.

inline uint32_t shrewd_alu(int32_t op, uint32_t a, uint32_t b, uint32_t imm) {
  const uint32_t sh = b & 31u;
  switch (op) {
    case OP_NOP:  return 0;
    case OP_ADD:  return a + b;
    case OP_SUB:  return a - b;
    case OP_AND:  return a & b;
    case OP_OR:   return a | b;
    case OP_XOR:  return a ^ b;
    case OP_SLL:  return a << sh;
    case OP_SRL:  return a >> sh;
    case OP_SRA:  return static_cast<uint32_t>(static_cast<int32_t>(a) >> sh);
    case OP_ADDI: return a + imm;
    case OP_ANDI: return a & imm;
    case OP_ORI:  return a | imm;
    case OP_XORI: return a ^ imm;
    case OP_LUI:  return imm;
    case OP_MUL:  return a * b;
    case OP_MULHU:
      return static_cast<uint32_t>(
          (static_cast<uint64_t>(a) * static_cast<uint64_t>(b)) >> 32);
    case OP_SLT:  return static_cast<int32_t>(a) < static_cast<int32_t>(b);
    case OP_SLTU: return a < b;
    // x86 #DE cases (b==0, INT_MIN/-1) return 0 here; the replay's trap
    // path classifies them DUE — matches ops/replay.py _div4 exactly
    case OP_DIV: {
      if (b == 0 || (a == 0x80000000u && b == 0xFFFFFFFFu)) return 0;
      return static_cast<uint32_t>(static_cast<int32_t>(a)
                                   / static_cast<int32_t>(b));
    }
    case OP_REM: {
      if (b == 0 || (a == 0x80000000u && b == 0xFFFFFFFFu)) return 0;
      return static_cast<uint32_t>(static_cast<int32_t>(a)
                                   % static_cast<int32_t>(b));
    }
    case OP_DIVU: return b ? a / b : 0;
    case OP_REMU: return b ? a % b : 0;
    case OP_LOAD: case OP_STORE: return a + imm;  // effective address
    case OP_BEQ:  return a == b;
    case OP_BNE:  return a != b;
    case OP_BLT:  return static_cast<int32_t>(a) < static_cast<int32_t>(b);
    case OP_BGE:  return static_cast<int32_t>(a) >= static_cast<int32_t>(b);
    case OP_FADD: case OP_FSUB: case OP_FMUL: case OP_FDIV: {
      // uops.py FP contract: IEEE RN, FTZ on inputs/outputs, canonical qNaN
      auto flush = [](uint32_t v) -> uint32_t {
        const uint32_t mag = v & 0x7FFFFFFFu;
        return (mag > 0 && mag < 0x00800000u) ? (v & 0x80000000u) : v;
      };
      float af, bf;
      uint32_t fa = flush(a), fb = flush(b);
      __builtin_memcpy(&af, &fa, 4);
      __builtin_memcpy(&bf, &fb, 4);
      float r = op == OP_FADD ? af + bf
              : op == OP_FSUB ? af - bf
              : op == OP_FMUL ? af * bf : af / bf;
      if (r != r) return 0x7FC00000u;        // canonical quiet NaN
      uint32_t bits;
      __builtin_memcpy(&bits, &r, 4);
      return flush(bits);
    }
    default:      return 0;
  }
}

inline int32_t shrewd_opclass(int32_t op) {
  switch (op) {
    case OP_NOP:   return OC_NONE;
    case OP_MUL: case OP_MULHU:
    case OP_DIV: case OP_REM: case OP_DIVU: case OP_REMU:
      return OC_INT_MULT;  // the reference's IntMultDiv unit
    case OP_FADD: case OP_FSUB: return OC_FP_ALU;
    case OP_FMUL: case OP_FDIV: return OC_FP_MULT;
    case OP_LOAD:  return OC_MEM_READ;
    case OP_STORE: return OC_MEM_WRITE;
    default:       return OC_INT_ALU;
  }
}

#endif  // SHREWD_NATIVE_H
