// Native synthetic-workload engine — the data-loader tier of the runtime.
// Plays the role of the reference's traffic/workload generators
// (cpu/testers/traffic_gen/base.hh:67) at native speed for large windows;
// the Python generator (shrewd_tpu/trace/synth.py) stays as the slow
// reference.  The two produce *different* streams (different RNGs) — both are
// valid workloads; replay semantics, not workload bits, are the contract.
//
// Executes as it generates (same scalar semantics as golden.cc) so branch
// outcomes and memory addressing stay consistent.
#include "shrewd.h"

#include <cmath>
#include <cstring>
#include <vector>

namespace {

// splitmix64: tiny, seedable, good-enough stream for workload shaping.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9E3779B97F4A7C15ull) {}
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // uniform integer in [0, n)
  int64_t below(int64_t n) { return (int64_t)(uniform() * n); }
  uint32_t u32() { return (uint32_t)next(); }
  int geometric(double p) {  // support {1, 2, ...}
    double u = uniform();
    if (u >= 1.0) u = 0.999999999;
    int g = (int)std::ceil(std::log1p(-u) / std::log1p(-p));
    return g < 1 ? 1 : g;
  }
};

constexpr auto alu32 = shrewd_alu;

const int32_t kAluOps[] = {OP_ADD, OP_SUB, OP_AND, OP_OR, OP_XOR, OP_SLL,
                           OP_SRL, OP_SRA, OP_ADDI, OP_ANDI, OP_ORI, OP_XORI,
                           OP_LUI, OP_SLT, OP_SLTU};
const int32_t kBranchOps[] = {OP_BEQ, OP_BNE, OP_BLT, OP_BGE};

}  // namespace

extern "C" {

int32_t shrewd_generate_trace(const WorkloadParams* p, int32_t* opcode,
                              int32_t* dst, int32_t* src1, int32_t* src2,
                              uint32_t* imm, int32_t* taken,
                              uint32_t* init_reg, uint32_t* init_mem) {
  if (p->n <= 0 || p->nphys <= 0 || (p->nphys & (p->nphys - 1)) ||
      p->mem_words <= 0 || (p->mem_words & (p->mem_words - 1)))
    return 1;
  const double fsum = p->frac_alu + p->frac_mul + p->frac_load +
                      p->frac_store + p->frac_branch;
  if (fsum > 1.0 + 1e-9) return 2;
  const int32_t ws = p->working_set_words < p->mem_words ? p->working_set_words
                                                         : p->mem_words;
  if (ws <= 0) return 3;

  Rng rng(p->seed);
  std::vector<uint32_t> reg(p->nphys), mem(p->mem_words);
  for (auto& r : reg) r = rng.u32();
  for (auto& m : mem) m = rng.u32();
  std::memcpy(init_reg, reg.data(), p->nphys * 4);
  std::memcpy(init_mem, mem.data(), p->mem_words * 4);

  std::vector<int32_t> recent;
  recent.reserve(128);
  auto pick_src = [&]() -> int32_t {
    if (!recent.empty() && rng.uniform() < p->locality) {
      int d = rng.geometric(p->reuse_geo_p);
      if (d > (int)recent.size()) d = (int)recent.size();
      return recent[recent.size() - d];
    }
    return (int32_t)rng.below(p->nphys);
  };

  for (int32_t i = 0; i < p->n; ++i) {
    const double u = rng.uniform();
    const double t_alu = p->frac_alu;
    const double t_mul = t_alu + p->frac_mul;
    const double t_load = t_mul + p->frac_load;
    const double t_store = t_load + p->frac_store;
    const double t_branch = t_store + p->frac_branch;
    int32_t op, d = 0, s1 = 0, s2 = 0;
    uint32_t im = 0;
    if (u < t_alu) {
      op = kAluOps[rng.below(15)];
      s1 = pick_src(); s2 = pick_src();
      d = (int32_t)rng.below(p->nphys);
      im = (uint32_t)rng.below(1 << 16);
    } else if (u < t_mul) {
      op = OP_MUL;
      s1 = pick_src(); s2 = pick_src();
      d = (int32_t)rng.below(p->nphys);
    } else if (u < t_store) {
      op = (u < t_load) ? OP_LOAD : OP_STORE;
      s1 = pick_src(); s2 = pick_src();
      d = (int32_t)rng.below(p->nphys);
      const uint32_t word = (uint32_t)rng.below(ws);
      im = word * 4u - reg[s1];  // effective address lands on `word`
    } else if (u < t_branch) {
      op = kBranchOps[rng.below(4)];
      s1 = pick_src(); s2 = pick_src();
    } else {
      op = OP_NOP;
    }

    opcode[i] = op; dst[i] = d; src1[i] = s1; src2[i] = s2; imm[i] = im;
    taken[i] = 0;

    // execute
    const uint32_t a = reg[s1], b = reg[s2];
    const uint32_t res = alu32(op, a, b, im);
    if (op == OP_LOAD) {
      reg[d] = mem[res >> 2];
      recent.push_back(d);
    } else if (op == OP_STORE) {
      mem[res >> 2] = b;
    } else if (op >= OP_BEQ && op <= OP_BGE) {
      taken[i] = (int32_t)res;
    } else if ((op >= OP_ADD && op <= OP_REMU)) {
      reg[d] = res;
      recent.push_back(d);
    }
    if (recent.size() > 64) recent.erase(recent.begin(), recent.end() - 64);
  }
  return 0;
}

}  // extern "C"
