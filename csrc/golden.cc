// Serial golden trial kernel — the C++ oracle the batched TPU path is
// differentially tested against, and the serial-baseline denominator for the
// bench (the role gem5's serial C++ campaign plays in BASELINE configs[0]).
//
// Step phases and fault application mirror shrewd_tpu/ops/replay.py exactly:
//   1. storage-fault landing  2. operand read (IQ index faults)
//   3. execute (FU faults, shadow detection)  4. memory (LSQ faults, traps)
//   5. branch resolution  6. writeback (ROB dest faults)
#include "shrewd.h"

#include <cstring>
#include <vector>

namespace {

constexpr auto alu = shrewd_alu;

struct TrialResult {
  bool detected = false;
  bool trapped = false;
  bool diverged = false;
};

// One replay; reg/mem are the trial's state (modified in place).
// `coverage` is the per-µop shadow detection probability (length tr.n) —
// FU-pool availability already folded in by the host (models/fupool.py).
TrialResult replay(const TraceView& tr, uint32_t* reg, uint32_t* mem,
                   int32_t kind, int32_t fcycle, int32_t fentry, int32_t fbit,
                   float shadow_u, const float* coverage) {
  TrialResult r;
  const int32_t idx_mask = tr.nphys - 1;
  const uint32_t bitmask = 1u << (fbit & 31);
  const int32_t index_mask = (int32_t)(1u << (fbit & 31));

  for (int32_t i = 0; i < tr.n; ++i) {
    // 1. storage-fault landing
    if (kind == KIND_REGFILE && i == fcycle) reg[fentry] ^= bitmask;

    int32_t op = tr.opcode[i];
    const bool at_uop = (i == fentry);

    // 2. operand read — latch-field faults first (MinorCPU model): a
    // flipped opcode may leave the legal range (illegal µop → DUE), a
    // flipped immediate just propagates through execute.
    uint32_t imm = tr.imm[i];
    if (kind == KIND_LATCH_OP && at_uop) {
      op ^= index_mask;
      if (op >= N_OPCODES || op < 0) {
        r.trapped = true;
        return r;
      }
    }
    if (kind == KIND_LATCH_IMM && at_uop) imm ^= bitmask;
    int32_t s1 = tr.src1[i];
    int32_t s2 = tr.src2[i];
    if (kind == KIND_IQ_SRC1 && at_uop) s1 = (s1 ^ index_mask) & idx_mask;
    if (kind == KIND_IQ_SRC2 && at_uop) s2 = (s2 ^ index_mask) & idx_mask;
    const uint32_t a = reg[s1];
    const uint32_t b = reg[s2];

    // 3. execute
    uint32_t eff = alu(op, a, b, imm);
    if (kind == KIND_FU && at_uop) {
      eff ^= bitmask;
      if (shadow_u < coverage[i]) {  // shadow FU re-executes
        r.detected = true;
        return r;  // fault contained before any commit
      }
    }

    const bool is_ld = (op == OP_LOAD);
    const bool is_st = (op == OP_STORE);
    const bool is_br = (op >= OP_BEQ && op <= OP_BGE);

    // x86 #DE: div-by-zero / INT_MIN÷-1 traps (DUE) — ops/replay.py div_trap
    if (op >= OP_DIV && op <= OP_REMU) {
      const bool bad_s = (b == 0) || (a == 0x80000000u && b == 0xFFFFFFFFu);
      const bool bad_u = (b == 0);
      if (((op == OP_DIV || op == OP_REM) && bad_s) ||
          ((op == OP_DIVU || op == OP_REMU) && bad_u)) {
        r.trapped = true;
        return r;
      }
    }

    // 4. memory access with LSQ faults
    if (is_ld || is_st) {
      uint32_t addr = eff;
      if (kind == KIND_LSQ_ADDR && at_uop) addr ^= bitmask;
      const bool valid = ((addr & 3u) == 0) && ((addr >> 2) < (uint32_t)tr.mem_words);
      if (!valid) {
        r.trapped = true;
        return r;
      }
      const int32_t slot = (int32_t)(addr >> 2) & (tr.mem_words - 1);
      if (is_ld) {
        eff = mem[slot];
      } else {
        uint32_t data = b;
        if (kind == KIND_LSQ_DATA && at_uop) data ^= bitmask;
        mem[slot] = data;
      }
    }

    // 5. branch resolution — effective control flow vs the golden outcome;
    // covers opcode latch flips that turn a branch into a non-branch and
    // vice versa (taken is 0 for non-branches)
    const bool taken_eff = is_br && (eff != 0);
    if (taken_eff != (tr.taken[i] != 0)) {
      r.diverged = true;
      return r;
    }
    if (is_br) continue;

    // 6. writeback with ROB dest-index fault
    const bool writes = (op >= OP_ADD && op <= OP_REMU) || is_ld ||
                        (op >= OP_FADD && op <= OP_FDIV) || op == OP_MULHU;
    if (writes) {
      int32_t d = tr.dst[i];
      if (kind == KIND_ROB_DST && at_uop) d = (d ^ index_mask) & idx_mask;
      reg[d] = eff;
    }
  }
  return r;
}

}  // namespace

extern "C" {

void shrewd_golden_replay(const TraceView* tr, const uint32_t* init_reg,
                          const uint32_t* init_mem, uint32_t* final_reg,
                          uint32_t* final_mem) {
  std::memcpy(final_reg, init_reg, tr->nphys * sizeof(uint32_t));
  std::memcpy(final_mem, init_mem, tr->mem_words * sizeof(uint32_t));
  const std::vector<float> cov(tr->n, 0.0f);
  replay(*tr, final_reg, final_mem, KIND_NONE, 0, 0, 0, 1.0f, cov.data());
}

int32_t shrewd_golden_trials(const TraceView* tr, const uint32_t* init_reg,
                             const uint32_t* init_mem, const FaultView* faults,
                             const float* coverage, int32_t compare_regs,
                             int32_t* outcomes) {
  const size_t nr = tr->nphys, nm = tr->mem_words;
  std::vector<uint32_t> gold_reg(nr), gold_mem(nm);
  shrewd_golden_replay(tr, init_reg, init_mem, gold_reg.data(), gold_mem.data());

  std::vector<uint32_t> reg(nr), mem(nm);
  for (int32_t t = 0; t < faults->n_trials; ++t) {
    std::memcpy(reg.data(), init_reg, nr * sizeof(uint32_t));
    std::memcpy(mem.data(), init_mem, nm * sizeof(uint32_t));
    const TrialResult r =
        replay(*tr, reg.data(), mem.data(), faults->kind[t], faults->cycle[t],
               faults->entry[t], faults->bit[t], faults->shadow_u[t], coverage);
    int32_t out;
    if (r.detected) {
      out = OUTCOME_DETECTED;
    } else if (r.trapped) {
      out = OUTCOME_DUE;
    } else {
      bool diff = r.diverged ||
                  std::memcmp(mem.data(), gold_mem.data(), nm * 4) != 0;
      if (!diff && compare_regs)
        diff = std::memcmp(reg.data(), gold_reg.data(), nr * 4) != 0;
      out = diff ? OUTCOME_SDC : OUTCOME_MASKED;
    }
    outcomes[t] = out;
  }
  return faults->n_trials;
}

}  // extern "C"
