/* x86 string-op workload (lifter-hardening tier).
 *
 * Explicit rep movsq/movsl/stosq/stosl/stosb via inline asm — the erms
 * memcpy/memset loops glibc emits, pinned here so the lifter's string-op
 * handlers (ingest/lift.py _lift_movs/_lift_stos, pair-lane variants in
 * ingest/lift64.py) are exercised deterministically regardless of which
 * path the host libc picks.  Contract as sort.c: kernel_begin/kernel_end
 * markers, one write(2) checksum at the end.
 */

#include <unistd.h>

#define N 64

static unsigned long src64[N];
static unsigned long dst64[N];
static unsigned int src32[N];
static unsigned int dst32[N];
static unsigned char bytes[96];

static unsigned int rng_state = 0x5EEDF00Du;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

static void rep_movsq(void *dst, const void *srcp, unsigned long n) {
    __asm__ volatile("rep movsq"
                     : "+D"(dst), "+S"(srcp), "+c"(n) :: "memory");
}

static void rep_movsl(void *dst, const void *srcp, unsigned long n) {
    __asm__ volatile("rep movsl"
                     : "+D"(dst), "+S"(srcp), "+c"(n) :: "memory");
}

static void rep_stosq(void *dst, unsigned long v, unsigned long n) {
    __asm__ volatile("rep stosq" : "+D"(dst), "+c"(n) : "a"(v) : "memory");
}

static void rep_stosl(void *dst, unsigned int v, unsigned long n) {
    __asm__ volatile("rep stosl" : "+D"(dst), "+c"(n) : "a"(v) : "memory");
}

static void rep_stosb(void *dst, unsigned char v, unsigned long n) {
    __asm__ volatile("rep stosb" : "+D"(dst), "+c"(n) : "a"(v) : "memory");
}

static void emit_checksum(unsigned int sum) {
    char buf[16];
    int i;
    for (i = 0; i < 8; i++) {
        unsigned int nib = (sum >> (28 - 4 * i)) & 0xF;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    unsigned int i, sum = 0;
    for (i = 0; i < N; i++) {
        src64[i] = ((unsigned long)xorshift() << 32) | xorshift();
        src32[i] = xorshift();
    }

    kernel_begin();
    rep_movsq(dst64, src64, N);                /* qword copy */
    rep_movsl(dst32, src32, N);                /* dword copy */
    rep_stosq(src64, 0x0123456789abcdefUL, N / 2);  /* qword fill */
    rep_stosl(src32, 0xCAFEBABEu, N / 2);      /* dword fill */
    rep_stosb(bytes, 0x5A, sizeof(bytes));     /* byte fill (erms) */
    for (i = 0; i < N; i++) {
        sum = sum * 31u + (unsigned int)dst64[i]
            + (unsigned int)(dst64[i] >> 32) + dst32[i]
            + (unsigned int)src64[i] + src32[i];
    }
    for (i = 0; i < sizeof(bytes); i++)
        sum = sum * 31u + bytes[i];
    kernel_end();

    emit_checksum(sum);
    return 0;
}
