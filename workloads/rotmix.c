/* Rotate + exchange torture: exercises the rol/ror and xchg lifts
 * (ingest/lift.py) on 32-bit registers and memory operands.  Same
 * marker contract as the other workloads (kernel_begin/kernel_end). */
/* Output via one write(2) with a hand-rolled hex formatter, like the
 * other workloads: no libc in the measured window or the output path
 * (the 64-bit emulator replays these programs end-to-end). */
#include <stdint.h>
#include <unistd.h>

#define N 96

static uint32_t buf[N];

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void) { __asm__ volatile(""); }

__attribute__((noinline)) static uint32_t rotmix(void) {
    uint32_t h = 0x9E3779B9u;
    for (int i = 0; i < N; i++) {
        uint32_t v = buf[i];
        __asm__("roll $7, %0" : "+r"(v));
        h ^= v;
        __asm__("rorl %%cl, %0" : "+r"(h) : "c"(i & 31));
        __asm__("xchgl %0, %1" : "+r"(h), "+r"(v));
        h += v;
        if (i & 1)
            __asm__("xchgl %0, %1" : "+r"(h), "+m"(buf[i]));
    }
    return h;
}

int main(void) {
    uint32_t s = 12345;
    for (int i = 0; i < N; i++) {
        s = s * 1103515245u + 12345u;
        buf[i] = s;
    }
    kernel_begin();
    uint32_t h = rotmix();
    kernel_end();
    char buf[10];
    for (int i = 0; i < 8; i++) {
        unsigned d = (h >> (28 - 4 * i)) & 0xF;
        buf[i] = d < 10 ? '0' + d : 'a' + (d - 10);
    }
    buf[8] = '\n';
    write(1, buf, 9);
    return 0;
}
