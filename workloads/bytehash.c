/* Byte / partial-register workload (lifter-hardening tier).
 *
 * Exercises byte loads/stores (movb/movzbl/movsbl), partial-register
 * arithmetic, byte compares (cmpb) and a strcmp-style early-exit loop —
 * the sub-word datapath VERDICT r2 flagged as unmeasured.  Contract as
 * sort.c: markers, one write(2) checksum, no libc in the window.
 */

#include <unistd.h>

#define N 192

static unsigned char a[N];
static unsigned char b[N];
static signed char sdelta[N];
static unsigned int tallies[8];
static volatile int sink;

static unsigned int rng_state = 0xBEEFCAFEu;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static int bytecmp(const unsigned char *p,
                                             const unsigned char *q, int n) {
    /* strcmp-shaped: byte loads + cmpb + early exit */
    for (int i = 0; i < n; i++) {
        if (p[i] != q[i])
            return (int)p[i] - (int)q[i];
    }
    return 0;
}

__attribute__((noinline)) static void byte_kernel(void) {
    /* byte RMW mix with signed/unsigned extension */
    for (int i = 0; i < N; i++) {
        unsigned char x = a[i];
        x = (unsigned char)(x ^ (b[i] >> 3));
        x = (unsigned char)(x + (unsigned char)i);
        a[i] = x;
        sdelta[i] = (signed char)(a[i] - b[i]);
        tallies[x & 7]++;
    }
    /* chunked compares drive data-dependent control flow */
    for (int c = 0; c + 16 <= N; c += 16) {
        int d = bytecmp(a + c, b + c, 16);
        if (d < 0)
            tallies[0] += 3;
        else if (d > 0)
            tallies[1] += 5;
        else
            tallies[2] += 7;
    }
    /* signed byte reduction (movsbl) */
    int s = 0;
    for (int i = 0; i < N; i++)
        s += sdelta[i];
    tallies[3] ^= (unsigned int)s;
}

static void emit_checksum(void) {
    unsigned int h = 2166136261u;
    for (int i = 0; i < N; i++)
        h = (h ^ a[i]) * 16777619u;
    for (int i = 0; i < 8; i++)
        h = (h ^ tallies[i]) * 16777619u;
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    for (int i = 0; i < N; i++) {
        a[i] = (unsigned char)(xorshift() & 0xff);
        b[i] = (unsigned char)((xorshift() >> 8) & 0xff);
    }
    kernel_begin();
    byte_kernel();
    kernel_end();
    emit_checksum();
    sink = a[0];
    return 0;
}
