/* Scalar-SSE floating-point workload (VERDICT r3 #6: FP/vector state as
 * a lifted injection target; reference FP OpClasses,
 * src/cpu/FuncUnitConfig.py, FP shadow FUs being the fork's raison
 * d'etre).
 *
 * Single-precision only — the replay ISA's FADD/FSUB/FMUL/FDIV lanes are
 * f32 with FTZ + canonical-NaN semantics (isa/uops.py FP contract), and
 * scalar SSE keeps every value in an xmm low lane the tracer can capture.
 * A polynomial-evaluation / dot-product / iterative-refinement mix keeps
 * add/sub/mul/div and float compares all hot.  Output: the float
 * accumulator's BIT PATTERN as an integer checksum (bit-exact, no printf
 * rounding), same marker/build conventions as sort.c.
 */

#include <unistd.h>

#define N 96

static float xs[N], ys[N];
static volatile int sink;

static unsigned int rng_state = 0x1234567u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static float fp_kernel(void) {
    float acc = 0.0f;
    float p, d;
    int i, j;
    /* dot product with a polynomial twist */
    for (i = 0; i < N; i++) {
        p = xs[i] * ys[i];
        acc = acc + p;
        /* Horner polynomial on xs[i] */
        p = 1.5f;
        for (j = 0; j < 4; j++)
            p = p * xs[i] + 0.25f;
        acc = acc + p;
    }
    /* iterative refinement of a reciprocal (division + compare loop) */
    d = acc;
    if (d < 1.0f)
        d = d + 2.0f;
    for (i = 0; i < 24; i++) {
        float q = 100.0f / d;
        if (q > d)
            acc = acc + 0.125f;
        else
            acc = acc - 0.0625f;
        d = d + q;
    }
    /* running min/max via compares */
    p = xs[0];
    for (i = 1; i < N; i++) {
        if (xs[i] > p)
            p = xs[i];
        if (ys[i] < acc && ys[i] > 0.0f)
            acc = acc + ys[i];
    }
    return acc + p;
}

static char out_line[32];

static int fmt(unsigned int v, char *p) {
    char tmp[16];
    int n = 0, i;
    if (!v) tmp[n++] = '0';
    while (v) { tmp[n++] = (char)('0' + v % 10u); v /= 10u; }
    for (i = 0; i < n; i++) p[i] = tmp[n - 1 - i];
    return n;
}

int main(void) {
    int i, pos = 0;
    union { float f; unsigned int u; } r;
    for (i = 0; i < N; i++) {
        xs[i] = (float)(int)(xorshift() & 255u) / 64.0f - 1.0f;
        ys[i] = (float)(int)(xorshift() & 511u) / 128.0f - 2.0f;
    }
    kernel_begin();
    r.f = fp_kernel();
    kernel_end();
    sink = (int)r.u;
    pos += fmt(r.u, out_line + pos);
    out_line[pos++] = '\n';
    if (write(1, out_line, (unsigned long)pos) != pos) return 2;
    return 0;
}
