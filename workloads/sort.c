/* Deterministic integer bubblesort workload for the native-trace pipeline.
 *
 * The framework's analog of the reference's self-checking guest programs
 * (tests/gem5/cpu_tests ref workloads: Bubblesort, FloatMM): a small,
 * deterministic, stack-light computational kernel whose output is a single
 * checksum line, so the host-SFI harness (tools/hostsfi.cc) can classify a
 * perturbed run by exit status + output alone.
 *
 * Design constraints (see shrewd_tpu/ingest/lift.py):
 *  - int32 data only (the lifter's datapath is the 32-bit projection);
 *  - no libc calls inside the measured kernel (pure compute between the
 *    markers), output via one write(2) at the end;
 *  - `kernel_begin`/`kernel_end` are global symbols the tracer uses to
 *    delimit the measured window (the SimPoint analog);
 *  - static, -no-pie build so static decode (objdump) matches runtime PCs.
 */

#include <unistd.h>

#define N 48

static int data[N];
static volatile int sink;

/* xorshift32 — deterministic fill, no libc rand */
static unsigned int rng_state = 0x2545F491u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

/* Markers: the tracer single-steps from the first PC == kernel_begin's
 * address to PC == kernel_end's address.  noinline + asm barrier keep the
 * symbols real at -O1. */
__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static void sort_kernel(void) {
    for (int i = 0; i < N - 1; i++) {
        for (int j = 0; j < N - 1 - i; j++) {
            if (data[j] > data[j + 1]) {
                int t = data[j];
                data[j] = data[j + 1];
                data[j + 1] = t;
            }
        }
    }
}

static void emit_checksum(void) {
    /* order-sensitive checksum of the sorted array */
    unsigned int h = 2166136261u;
    for (int i = 0; i < N; i++) {
        h = (h ^ (unsigned int)data[i]) * 16777619u;
    }
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

/* exported hooks: the rdtsc cycle-count harness re-runs exactly the
 * traced kernel (workloads/rdtsc_harness.c, tools/timing_validate.py) */
__attribute__((noinline)) void workload_init(void) {
    rng_state = 0x2545F491u;
    for (int i = 0; i < N; i++) {
        data[i] = (int)(xorshift() & 0xffff) - 0x8000;
    }
}

__attribute__((noinline)) void kernel_payload(void) {
    sort_kernel();
}

int main(void) {
    workload_init();
    kernel_begin();
    kernel_payload();
    kernel_end();
    emit_checksum();
    sink = data[0];
    return 0;
}
