/* memcpy/memset-style loop workload (lifter-hardening tier).
 *
 * Word-granular copy, fill, and reverse loops — the streaming-store
 * pattern of memcpy/strcpy rewritten over int32 (open-coded, no libc, so
 * the window stays in lifted territory rather than rep-string microcode).
 * Contract as sort.c: markers, one write(2) checksum.
 */

#include <unistd.h>

#define N 256

static unsigned int src[N];
static unsigned int dst[N];
static unsigned int scratch[N];
static volatile int sink;

static unsigned int rng_state = 0x0DDBA11u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static void mem_kernel(void) {
    /* copy forward */
    for (int i = 0; i < N; i++)
        dst[i] = src[i];
    /* fill a strided pattern */
    for (int i = 0; i < N; i += 4)
        scratch[i] = 0xA5A5A5A5u ^ (unsigned int)i;
    /* reverse copy with rotate-by-word mixing */
    for (int i = 0; i < N; i++) {
        unsigned int v = dst[N - 1 - i];
        scratch[i] = (scratch[i] + v) ^ (v >> 7);
    }
    /* overlapped shift-down (memmove-shaped) */
    for (int i = 0; i + 8 < N; i++)
        dst[i] = dst[i + 8] + scratch[i];
}

static void emit_checksum(void) {
    unsigned int h = 2166136261u;
    for (int i = 0; i < N; i++)
        h = (h ^ dst[i]) * 16777619u;
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    for (int i = 0; i < N; i++)
        src[i] = xorshift();
    kernel_begin();
    mem_kernel();
    kernel_end();
    emit_checksum();
    sink = (int)dst[0];
    return 0;
}
