/* lzss at SimPoint scale: ~9M macro-ops in the marker window (~10M+
 * lifted µops) — the ≥10M-µop chunked-replay scaling target
 * (reference bar: 30B-instruction SimPoint regions,
 * x86_spec/x86-spec-cpu2017.py:403-436).  Same code as lzss.c, input
 * scaled 4.75x. */
#define IN_N 98304
#include "lzss.c"
