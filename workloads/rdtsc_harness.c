/* Cycle-count harness: run a marker workload's kernel under rdtsc and
 * print the median cycle count — the EXTERNAL timing truth the scoreboard
 * model is sanity-anchored against (tools/timing_validate.py).  The host
 * x86 core is itself a wide out-of-order machine, i.e. exactly the class
 * of pipeline the reference's O3 model and our scoreboard approximate.
 *
 * Build: gcc -O1 -static -fno-pie -no-pie -DWORKLOAD='"sort.c"' \
 *            rdtsc_harness.c -o harness
 * The workload's main() is renamed away; we call its kernel directly.
 */

#include <stdint.h>
#include <unistd.h>

#define main workload_main          /* keep the workload's main out */
#include WORKLOAD
#undef main

static inline uint64_t rdtsc_begin(void) {
    uint32_t lo, hi;
    __asm__ volatile("cpuid\n\trdtsc" : "=a"(lo), "=d"(hi)
                     :: "rbx", "rcx");
    return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtsc_end(void) {
    uint32_t lo, hi;
    __asm__ volatile("rdtscp" : "=a"(lo), "=d"(hi) :: "rcx");
    return ((uint64_t)hi << 32) | lo;
}

static int out(char *p, uint64_t v) {
    char tmp[24];
    int n = 0, i;
    if (!v) tmp[n++] = '0';
    while (v) { tmp[n++] = (char)('0' + v % 10u); v /= 10u; }
    for (i = 0; i < n; i++) p[i] = tmp[n - 1 - i];
    p[n] = '\n';
    return n + 1;
}

int main(void) {
    enum { REPS = 21 };
    uint64_t cyc[REPS];
    char line[32];
    int i, j;
    /* one warm run populates caches/predictors the way the traced run
     * (which the scoreboard models) executed */
    workload_init();
    kernel_payload();
    for (i = 0; i < REPS; i++) {
        workload_init();
        uint64_t a = rdtsc_begin();
        kernel_payload();
        cyc[i] = rdtsc_end() - a;
    }
    /* insertion-sort, print median */
    for (i = 1; i < REPS; i++)
        for (j = i; j > 0 && cyc[j] < cyc[j - 1]; j--) {
            uint64_t t = cyc[j]; cyc[j] = cyc[j - 1]; cyc[j - 1] = t;
        }
    if (write(1, line, (unsigned long)out(line, cyc[REPS / 2]))
            < 0)
        return 2;
    return 0;
}
