/* lzss.c at a reduced input size: a ~500k-µop whole-program window that
 * keeps host-diff (ptrace campaign + emulator escalation) affordable
 * while still two orders of magnitude past the toy kernels. */
#define IN_N 2048
#include "lzss.c"
