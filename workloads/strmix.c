/* libc string-primitive torture: strlen/memcpy/memset on variable-length
 * buffers — the calls resolve (via glibc's startup IFUNCs) to the SSE2/
 * AVX2/erms variants, exercising the 64-bit emulator's SIMD + rep-string
 * subset (ingest/emu.py).  Marker + write(2) contract as usual. */
#include <stdint.h>
#include <string.h>
#include <unistd.h>

#define N 192

static char a[N + 1], b[N + 1];

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void) { __asm__ volatile(""); }

__attribute__((noinline)) static uint32_t strmix(void) {
    uint32_t h = 0x811C9DC5u;
    uint32_t s = 424242;
    for (int r = 0; r < 6; r++) {
        s = s * 1103515245u + 12345u;
        size_t n = 17 + (s % (N - 18));
        memset(a, 'a' + (r % 7), n);
        a[n] = 0;
        h = (h ^ (uint32_t)strlen(a)) * 16777619u;
        memcpy(b, a, n + 1);
        h = (h ^ (uint32_t)strlen(b)) * 16777619u;
        b[n / 2] = 0;
        h = (h ^ (uint32_t)strlen(b)) * 16777619u;
    }
    return h;
}

int main(void) {
    kernel_begin();
    uint32_t h = strmix();
    kernel_end();
    char buf[10];
    for (int i = 0; i < 8; i++) {
        unsigned d = (h >> (28 - 4 * i)) & 0xF;
        buf[i] = d < 10 ? '0' + d : 'a' + (d - 10);
    }
    buf[8] = '\n';
    write(1, buf, 9);
    return 0;
}
