/* Integer division / remainder workload (lifter-hardening tier).
 *
 * Exercises idiv/div (32-bit quotient+remainder through edx:eax), cdq
 * sign-extension, and division-fed control flow — the macro-ops VERDICT r2
 * called out as unmeasured lifter territory.  Same contract as sort.c:
 * kernel_begin/kernel_end markers, one write(2) checksum at the end,
 * int32 data, no libc inside the window.
 */

#include <unistd.h>

#define N 96

static int num[N];
static int den[N];
static unsigned int acc[N];
static volatile int sink;

static unsigned int rng_state = 0x12345678u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static void div_kernel(void) {
    for (int i = 0; i < N; i++) {
        int q = num[i] / den[i];               /* idiv */
        int r = num[i] % den[i];
        unsigned int uq = (unsigned int)num[i] / (unsigned int)(den[i] | 1);
        acc[i] = (unsigned int)(q * 31 + r) ^ (uq << 3);
        if (q > r) {
            acc[i] += (unsigned int)(q - r) % 97u;   /* div-fed branch */
        }
    }
    /* second pass: accumulating remainder chain */
    unsigned int h = 0x9e3779b9u;
    for (int i = 0; i < N; i++) {
        h = (h + acc[i]) % 0x7fffffffu;
        acc[i] = h;
    }
}

static void emit_checksum(void) {
    unsigned int h = 2166136261u;
    for (int i = 0; i < N; i++) {
        h = (h ^ acc[i]) * 16777619u;
    }
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    for (int i = 0; i < N; i++) {
        num[i] = (int)(xorshift() & 0xffffff) - 0x800000;
        den[i] = (int)(xorshift() & 0xfff) + 1;     /* nonzero */
        if (xorshift() & 1) den[i] = -den[i];
    }
    kernel_begin();
    div_kernel();
    kernel_end();
    emit_checksum();
    sink = (int)acc[0];
    return 0;
}
