/* Pointer-chasing workload (lifter-hardening tier).
 *
 * A shuffled singly-linked ring walked with data-dependent loads (the
 * classic latency microbenchmark shape), plus an index-indirection pass —
 * address formation from loaded values is the pattern that stresses the
 * lifter's EA handling and the replay's load-value taint routing.
 * Contract as sort.c: markers, one write(2) checksum, int32 data.
 */

#include <unistd.h>

#define N 128

static int next_idx[N];          /* ring successor per slot */
static unsigned int payload[N];
static unsigned int order[N];
static volatile int sink;

static unsigned int rng_state = 0xC0FFEE11u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static void chase_kernel(void) {
    /* walk the ring 3*N hops, mixing payloads along the way */
    unsigned int h = 0x811c9dc5u;
    int p = 0;
    for (int hop = 0; hop < 3 * N; hop++) {
        h = (h ^ payload[p]) * 16777619u;
        payload[p] = h;
        p = next_idx[p];
    }
    /* index indirection: order[] permutes reads of payload[] */
    for (int i = 0; i < N; i++) {
        unsigned int j = order[i] & (N - 1);
        payload[i] ^= payload[j] >> 5;
    }
    sink = p;
}

static void emit_checksum(void) {
    unsigned int h = 2166136261u;
    for (int i = 0; i < N; i++)
        h = (h ^ payload[i]) * 16777619u;
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    /* Sattolo shuffle → one N-cycle, so the chase visits every slot */
    for (int i = 0; i < N; i++)
        next_idx[i] = i;
    for (int i = N - 1; i > 0; i--) {
        int j = (int)(xorshift() % (unsigned int)i);
        int t = next_idx[i];
        next_idx[i] = next_idx[j];
        next_idx[j] = t;
    }
    for (int i = 0; i < N; i++) {
        payload[i] = xorshift();
        order[i] = xorshift();
    }
    kernel_begin();
    chase_kernel();
    kernel_end();
    emit_checksum();
    sink ^= (int)payload[0];
    return 0;
}
