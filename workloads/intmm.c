/* Deterministic integer matrix-multiply workload (the FloatMM analog of the
 * reference's tests/gem5/cpu_tests, on the int32 datapath).  Same contract
 * as sort.c: kernel_begin/kernel_end markers delimit the measured window,
 * one checksum line on stdout classifies the run. */

#include <unistd.h>

#define M 12

static int a[M][M], b[M][M], c[M][M];
static volatile int sink;

static unsigned int rng_state = 0x9E3779B9u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

__attribute__((noinline)) static void mm_kernel(void) {
    for (int i = 0; i < M; i++) {
        for (int j = 0; j < M; j++) {
            int acc = 0;
            for (int k = 0; k < M; k++) {
                acc += a[i][k] * b[k][j];
            }
            c[i][j] = acc;
        }
    }
}

static void emit_checksum(void) {
    unsigned int h = 2166136261u;
    for (int i = 0; i < M; i++) {
        for (int j = 0; j < M; j++) {
            h = (h ^ (unsigned int)c[i][j]) * 16777619u;
        }
    }
    char buf[16];
    for (int i = 7; i >= 0; i--) {
        unsigned int nib = h & 0xfu;
        buf[i] = (char)(nib < 10 ? '0' + nib : 'a' + nib - 10);
        h >>= 4;
    }
    buf[8] = '\n';
    write(1, buf, 9);
}

int main(void) {
    for (int i = 0; i < M; i++) {
        for (int j = 0; j < M; j++) {
            a[i][j] = (int)(xorshift() & 0xff) - 0x80;
            b[i][j] = (int)(xorshift() & 0xff) - 0x80;
        }
    }
    kernel_begin();
    mm_kernel();
    kernel_end();
    emit_checksum();
    sink = c[0][0];
    return 0;
}
