/* LZSS-style compression loop — the large-window workload (VERDICT r3 #4:
 * the SPEC-scale analog; reference north star measures 30B-instruction
 * SimPoint regions, x86_spec/x86-spec-cpu2017.py:404).
 *
 * A hash-chain match searcher compresses a deterministic, partly
 * repetitive buffer: pointer-and-byte-heavy code (greedy match loops,
 * hash table probes, window copies) whose measured window runs to
 * hundreds of thousands of macro-ops — two orders of magnitude past the
 * toy kernels — while keeping the lifter's constraints (int32 data,
 * no libc inside the markers, one write(2) checksum at the end).
 *
 * Same marker/build conventions as sort.c.
 */

#include <unistd.h>

#ifndef IN_N
#define IN_N   20480          /* input bytes (override: -DIN_N=...) */
#endif
#define OUT_N  (IN_N + IN_N / 8 + 64)
#define HASH_BITS 12
#define HASH_N (1 << HASH_BITS)
#define MAX_MATCH 34
#define MIN_MATCH 3
#define WINDOW 4096

static unsigned char in_buf[IN_N];
static unsigned char out_buf[OUT_N];
static int head[HASH_N];
static int prev[IN_N];
static volatile int sink;

static unsigned int rng_state = 0x9E3779B9u;
static unsigned int xorshift(void) {
    unsigned int x = rng_state;
    x ^= x << 13;
    x ^= x >> 17;
    x ^= x << 5;
    rng_state = x;
    return x;
}

__attribute__((noinline)) void kernel_begin(void) { __asm__ volatile(""); }
__attribute__((noinline)) void kernel_end(void)   { __asm__ volatile(""); }

static unsigned int hash3(const unsigned char *p) {
    unsigned int h = (unsigned int)p[0] | ((unsigned int)p[1] << 8)
                   | ((unsigned int)p[2] << 16);
    h *= 0x9E3779B1u;
    return h >> (32 - HASH_BITS);
}

__attribute__((noinline)) static int compress(void) {
    int op = 0;                 /* output cursor */
    int ip = 0;
    int i;
    for (i = 0; i < HASH_N; i++) head[i] = -1;
    while (ip + MIN_MATCH < IN_N && op + 5 < OUT_N) {
        unsigned int h = hash3(&in_buf[ip]);
        int cand = head[h];
        int best_len = 0, best_dist = 0, chain = 8;
        while (cand >= 0 && chain-- > 0 && ip - cand <= WINDOW) {
            int len = 0;
            int lim = IN_N - ip;
            if (lim > MAX_MATCH) lim = MAX_MATCH;
            while (len < lim && in_buf[cand + len] == in_buf[ip + len])
                len++;
            if (len > best_len) { best_len = len; best_dist = ip - cand; }
            cand = prev[cand];
        }
        head[h] = ip;
        prev[ip] = (head[h] >= 0) ? head[h] : -1;
        /* maintain the chain properly: prev points at the previous
         * occupant of this bucket (recorded before overwrite above) */
        if (best_len >= MIN_MATCH) {
            out_buf[op++] = (unsigned char)(0x80 | (best_len - MIN_MATCH));
            out_buf[op++] = (unsigned char)(best_dist & 0xFF);
            out_buf[op++] = (unsigned char)(best_dist >> 8);
            /* index the skipped positions so later matches can find them */
            {
                int stop = ip + best_len;
                ip++;
                while (ip < stop && ip + MIN_MATCH < IN_N) {
                    unsigned int h2 = hash3(&in_buf[ip]);
                    prev[ip] = head[h2];
                    head[h2] = ip;
                    ip++;
                }
                ip = stop;
            }
        } else {
            out_buf[op++] = in_buf[ip] & 0x7F;
            ip++;
        }
    }
    while (ip < IN_N && op < OUT_N) out_buf[op++] = in_buf[ip++] & 0x7F;
    return op;
}

static char out_line[64];

static int fmt(unsigned int v, char *p) {
    char tmp[16];
    int n = 0, i;
    if (!v) tmp[n++] = '0';
    while (v) { tmp[n++] = (char)('0' + v % 10u); v /= 10u; }
    for (i = 0; i < n; i++) p[i] = tmp[n - 1 - i];
    return n;
}

int main(void) {
    int i, olen, pos = 0;
    unsigned int csum = 2166136261u;
    /* fill: repetitive runs interleaved with noise so matches exist */
    for (i = 0; i < IN_N; i++) {
        if ((i >> 6) & 1)
            in_buf[i] = (unsigned char)(i & 31);          /* repetitive */
        else
            in_buf[i] = (unsigned char)(xorshift() & 63); /* semi-noise */
    }
    kernel_begin();
    olen = compress();
    for (i = 0; i < olen; i++)
        csum = (csum ^ out_buf[i]) * 16777619u;
    kernel_end();
    sink = (int)csum;
    pos += fmt(csum, out_line + pos);
    out_line[pos++] = ' ';
    pos += fmt((unsigned int)olen, out_line + pos);
    out_line[pos++] = '\n';
    if (write(1, out_line, (unsigned long)pos) != pos) return 2;
    return 0;
}
