#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command VERBATIM, so local runs and CI
# cannot drift.  Exits with pytest's status; prints DOTS_PASSED for the
# driver's pass-count comparison.
#
# Usage: scripts/ci_tier1.sh   (from anywhere — the script resolves the
# repo root from its own path, so CI and local invocations cannot diverge
# on the working directory)

cd "$(dirname "$0")/.." || exit 1

# graftlint gate (FATAL): static determinism & replay-safety
# certification (shrewd_tpu/analysis/, tools/graftlint.py).  AST passes
# over the package (exec-cache jit routing, no wall clock in
# deterministic regions, atomic checkpoint writes, PRNG hygiene, and
# the GL2xx crash-safety family: journal-before-mutate dominance,
# journal-kind exhaustiveness, fsync-before-rename, best-effort
# guards) plus the jaxpr/HLO audit of the standard campaign
# executables (frozen-key RNG lineage, no host callbacks, ONE
# device->host transfer per sync interval, donation consistency) —
# recorded as LINT_r11.json + SARIF annotations.  --audit-waivers
# additionally fails on STALE waivers, so the reasoned-waiver ledger
# cannot rot.  A NEW violation fails the build; findings are waived
# in-source with "# graftlint: allow-<rule> -- <reason>" (re-run with
# --baseline LINT_r11.json to gate only on regressions).
timeout -k 10 420 env JAX_PLATFORMS=cpu python tools/graftlint.py --strict --audit-waivers --json LINT_r11.json --sarif LINT_r11.sarif \
  || { echo "FATAL: graftlint gate failed (static determinism/replay-safety violations)"; exit 1; }

# crashcheck gate (FATAL): exhaustive crash-point model checking of the
# fleet WAL (shrewd_tpu/analysis/crashcheck.py).  A 3-tenant fleet runs
# under the instrumented VFS shim, every durability boundary (journal
# append / compaction / atomic rename) is snapshotted, and recover() is
# re-executed from EVERY boundary plus a torn-tail variant of every
# append — final tallies must be bit-identical to the undisturbed run
# at every single crash point, with journal seqs never regressing.
# This replaces single-kill-point sampling with full coverage of the
# crash surface — recorded as CRASH_r11.json.
timeout -k 10 420 env JAX_PLATFORMS=cpu python tools/graftlint.py --no-jaxpr --crashcheck --crash-json CRASH_r11.json \
  || { echo "FATAL: crashcheck gate failed (a crash point did not recover bit-identically)"; exit 1; }

# Non-fatal backend-probe smoke: catches probe drift (import breakage,
# verdict-format changes) in tier-1 without ever affecting the pass/fail
# status — the probe is the first thing operators reach for when a
# backend misbehaves, so it must not rot silently.
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/backend_probe.py --platform cpu --timeout 120 \
  || echo "WARNING: backend_probe smoke failed (non-fatal)"

# Non-fatal chaos smoke: a single-process campaign with two injected
# faults (a permanent device-tier failure and a corrupt batch tally) must
# finish with a tally bit-identical to the undisturbed run — the fastest
# end-to-end proof that the ladder and the integrity quarantine still
# compose (shrewd_tpu/chaos.py).  Never affects the pass/fail status.
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'CHAOS_SMOKE' \
  || echo "WARNING: chaos smoke failed (non-fatal)"
import numpy as np
from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.chaos import ChaosEngine
from shrewd_tpu.trace.synth import WorkloadConfig

def plan():
    p = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=64, nphys=32, mem_words=64, working_set_words=32, seed=3))],
        structures=["regfile"], batch_size=32, target_halfwidth=0.5,
        max_trials=64, min_trials=64)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    return p

clean = dict(list(Orchestrator(plan()).events())[-1][1])
orch = Orchestrator(plan())
orch.attach_chaos(ChaosEngine({"faults": [
    {"kind": "backend_error", "at_batch": 0, "tier": "device",
     "permanent": True},
    {"kind": "corrupt_tally", "at_batch": 1, "delta": 1},
]}))
res = dict(list(orch.events())[-1][1])
for k in clean:
    np.testing.assert_array_equal(clean[k].tallies, res[k].tallies)
assert orch.chaos.injected == {"backend_error": 1, "corrupt_tally": 1}, \
    orch.chaos.injected
assert orch.chaos.survived == orch.chaos.injected, orch.chaos.survived
print(f"chaos smoke: injected {orch.chaos.injected} -> survived, "
      "tally bit-identical")
CHAOS_SMOKE

# Non-fatal fleet smoke: a 2-tenant multi-tenant fleet on one mesh
# (shrewd_tpu/service/), both tenants over the SAME window — each
# tenant's tally must be bit-identical to its solo serial run, and the
# second tenant must compile ZERO new steps (cross-tenant dedupe through
# the content-keyed executable cache).  Never affects pass/fail status.
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'FLEET_SMOKE' \
  || echo "WARNING: fleet smoke failed (non-fatal)"
import numpy as np
from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.parallel import exec_cache
from shrewd_tpu.service import CampaignScheduler, TenantSpec
from shrewd_tpu.trace.synth import WorkloadConfig

def plan(seed):
    p = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=64, nphys=32, mem_words=64, working_set_words=32, seed=3))],
        structures=["regfile"], batch_size=32, target_halfwidth=0.5,
        max_trials=128, min_trials=128, seed=seed)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    return p

solos = {}
warm = []
for seed in (0, 9):
    orch = Orchestrator(plan(seed))
    warm.append(orch)       # keep kernels alive: cache entries are owner-guarded
    solos[seed] = {k: v.tallies for k, v in dict(list(orch.events())[-1][1]).items()}
before = exec_cache.cache().compiled
sched = CampaignScheduler()
sched.admit(TenantSpec(name="t0", plan=plan(0).to_dict()))
sched.admit(TenantSpec(name="t9", plan=plan(9).to_dict()))
assert sched.run() == 0, "fleet did not complete"
for name, seed in (("t0", 0), ("t9", 9)):
    got = sched.tenant_tallies(name)
    for k, t in solos[seed].items():
        np.testing.assert_array_equal(got[k], np.asarray(t))
compiled = exec_cache.cache().compiled - before
assert compiled == 0, f"shared-window fleet compiled {compiled} new steps"
print(f"fleet smoke: 2 tenants bit-identical to solo, 0 new compiles "
      f"(fairness {sched.fairness_index():.3f})")
FLEET_SMOKE

# Non-fatal fleet-survivability smoke: a 3-tenant fleet served through
# the real CLI dies a HARD death (kill_fleet chaos -> os._exit 137 at a
# deterministic tick: no drain, no snapshot — the write-ahead journal
# and per-tenant checkpoints are the only survivors), then
# `fleet.py --recover` replays snapshot+journal (reaping the dead
# server's stale pid lock on the way) and every tenant must finish with
# tallies bit-identical to its solo serial run.  Never affects the
# pass/fail status.
timeout -k 10 420 env JAX_PLATFORMS=cpu python - <<'SURVIVE_SMOKE' \
  || echo "WARNING: fleet survive smoke failed (non-fatal)"
import json, os, subprocess, sys, tempfile
import numpy as np
from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.trace.synth import WorkloadConfig

def plan(seed):
    p = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=64, nphys=32, mem_words=64, working_set_words=32, seed=3))],
        structures=["regfile"], batch_size=32, target_halfwidth=0.5,
        max_trials=128, min_trials=128, seed=seed)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    return p

seeds = (0, 9, 17)
solos = {}
warm = []
for seed in seeds:
    orch = Orchestrator(plan(seed))
    warm.append(orch)     # keep kernels alive: cache entries are owner-guarded
    solos[seed] = {k: np.asarray(v.tallies)
                   for k, v in dict(list(orch.events())[-1][1]).items()}
td = tempfile.mkdtemp(prefix="fleet_survive_")
outdir = os.path.join(td, "out")
paths = []
for i, seed in enumerate(seeds):
    pth = os.path.join(td, f"p{i}.json")
    with open(pth, "w") as f:
        json.dump(plan(seed).to_dict(), f)
    paths.append(pth)
cpath = os.path.join(td, "chaos.json")
with open(cpath, "w") as f:
    json.dump({"faults": [{"kind": "kill_fleet", "at_tick": 5}]}, f)
env = dict(os.environ, JAX_PLATFORMS="cpu")
r = subprocess.run([sys.executable, "tools/fleet.py", "--plans", *paths,
                    "--outdir", outdir, "--chaos-plan", cpath], env=env)
assert r.returncode == 137, f"expected hard-kill rc 137, got {r.returncode}"
r = subprocess.run([sys.executable, "tools/fleet.py", "--recover", outdir],
                   env=env)
assert r.returncode == 0, f"recover rc {r.returncode}"
with open(os.path.join(outdir, "fleet_ckpt", "fleet.json")) as f:
    snap = json.load(f)
assert snap["recoveries"] == 1, snap
by_name = {d["spec"]["name"]: d for d in snap["tenants"]}
for i, seed in enumerate(seeds):
    doc = by_name[f"t{i}_p{i}"]
    assert doc["status"] == "complete", (doc["spec"]["name"], doc["status"])
    for k, t in solos[seed].items():
        got = np.asarray(doc["results"][f"{k[0]}/{k[1]}"]["tallies"])
        np.testing.assert_array_equal(got, t)
print("fleet survive smoke: hard kill at tick 5 -> --recover -> "
      "3 tenants complete, tallies bit-identical to solo")
SURVIVE_SMOKE

# Non-fatal obs smoke: a small campaign run through the REAL CLI with
# --trace and an injected corrupt-tally quarantine must leave (1) a
# Perfetto trace.json that loads and has events, and (2) a flight-
# recorder dump whose window contains the quarantine span — the
# dispatch → integrity-verdict → quarantine → ladder-recovery timeline
# reconstructable from one artifact (shrewd_tpu/obs/).  Event counts
# land in OBS_r09.json.  Never affects the pass/fail status.
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'OBS_SMOKE' \
  || echo "WARNING: obs smoke failed (non-fatal)"
import json, os, subprocess, sys, tempfile
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.obs import export as obs_export
from shrewd_tpu.trace.synth import WorkloadConfig

td = tempfile.mkdtemp(prefix="obs_smoke_")
p = CampaignPlan(
    simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
        n=64, nphys=32, mem_words=64, working_set_words=32, seed=3))],
    structures=["regfile"], batch_size=32, target_halfwidth=0.5,
    max_trials=96, min_trials=96)
p.integrity.canary_trials = 0
p.integrity.audit_rate = 0.0
p.resilience.backoff_base = 0.0
ppath = os.path.join(td, "plan.json")
with open(ppath, "w") as f:
    json.dump(p.to_dict(), f)
cpath = os.path.join(td, "chaos.json")
with open(cpath, "w") as f:
    json.dump({"faults": [
        {"kind": "corrupt_tally", "at_batch": 1, "delta": 1}]}, f)
outdir = os.path.join(td, "out")
env = dict(os.environ, JAX_PLATFORMS="cpu")
r = subprocess.run([sys.executable, "-m", "shrewd_tpu", "run", ppath,
                    "--outdir", outdir, "--trace", "--chaos-plan", cpath],
                   env=env)
assert r.returncode == 0, f"traced run rc {r.returncode}"
with open(os.path.join(outdir, "trace.json")) as f:
    doc = json.load(f)
assert doc["traceEvents"], "Perfetto export is empty"
with open(os.path.join(outdir, "flightrec.json")) as f:
    rec = json.load(f)
names = [ev["name"] for ev in rec["events"]]
for want in ("invariant_verdict", "quarantine", "quarantine_recovered",
             "batch_believed"):
    assert want in names, f"flight recorder missing {want}: {names}"
summary = obs_export.summarize(rec["events"])
with open("OBS_r09.json", "w") as f:
    json.dump({"reason": rec["reason"],
               "trace_events": len(doc["traceEvents"]),
               "flight_events": summary["events"],
               "by_name": summary["by_name"]}, f, indent=1)
    f.write("\n")
print(f"obs smoke: quarantine timeline in flightrec.json "
      f"({summary['events']} events), trace.json loads "
      f"({len(doc['traceEvents'])} trace events) -> OBS_r09.json")
OBS_SMOKE

# Non-fatal scenario-matrix smoke: a 2x2 mini-matrix (O3 regfile +
# MESI directory x parity/dmr schemes) served through the closed
# Pareto loop (shrewd_tpu/scenario/) — the cross-product expands
# deterministically, every cell runs through the resident fleet, the
# de-weighted dmr cells are pruned once their parity mates converge
# and dominate (journaled revoke_quota), and the PARETO artifact's
# front + decisions land in SCENARIO_r10.json.  Never affects the
# pass/fail status.
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'SCENARIO_SMOKE' \
  || echo "WARNING: scenario smoke failed (non-fatal)"
import json, os, tempfile
from shrewd_tpu.parallel import exec_cache
from shrewd_tpu.scenario import ScenarioMatrix, ScenarioRunner, pareto

matrix = ScenarioMatrix(
    tag="r10", seed=3,
    workloads=[{"name": "wl", "simpoints": [{
        "type": "WorkloadSpec", "name": "w0",
        "workload": {"n": 96, "nphys": 32, "mem_words": 64,
                     "working_set_words": 32, "seed": 7}}]}],
    targets=["regfile", "mesi:state"],
    schemes=[{"name": "parity", "detect": 1.0, "area": 1.03},
             {"name": "dmr", "detect": 1.0, "area": 2.0,
              "weight": 0.2}],
    base={"batch_size": 32, "max_trials": 192, "min_trials": 192,
          "target_halfwidth": 0.2, "coherence_accesses": 64,
          "coherence_mem_words": 64,
          "integrity": {"canary_trials": 0, "audit_rate": 0.0},
          "resilience": {"backoff_base": 0.0}})
outdir = os.path.join(tempfile.mkdtemp(prefix="scenario_smoke_"), "out")
before = exec_cache.cache().stats()
runner = ScenarioRunner(matrix, outdir, pareto_every=1)
assert runner.serve() == 0, "matrix fleet did not complete"
after = exec_cache.cache().stats()
sched = runner.sched
statuses = {n: t.status for n, t in sched.tenants.items()}
assert len(statuses) == 4, statuses
doc = json.load(open(pareto.artifact_path(outdir, "r10")))
decisions = doc["decisions"]
assert decisions, "no Pareto prune fired on the dominated dmr cells"
for d in decisions:
    assert sched.tenants[d["cell"]].status == "pruned", statuses
assert doc["search"], "no converged system group searched"
with open("SCENARIO_r10.json", "w") as f:
    json.dump({"cells": statuses,
               "decisions": decisions,
               "fronts": {g: [[p["area"], p["sdc_rate"]]
                              for p in r["pareto"]]
                          for g, r in doc["search"].items()},
               "pruned_trials_saved": {
                   d["cell"]: 192 - sched.tenants[d["cell"]].trials
                   for d in decisions},
               "cache": {"compiled": after["compiled"]
                         - before["compiled"],
                         "reused": after["reused"] - before["reused"]}},
              f, indent=1)
    f.write("\n")
print(f"scenario smoke: 2x2 matrix -> {len(decisions)} cells pruned "
      f"by the closed loop, PARETO front emitted -> SCENARIO_r10.json")
SCENARIO_SMOKE

# Non-fatal federation smoke: a NORTHSTAR-mini tenant matrix served
# across a 3-pod fleet-of-fleets (shrewd_tpu/federation/) under a chaos
# schedule that HARD-kills one pod mid-campaign (kill_pod at a
# deterministic tick: dirty WAL, stale heartbeat, no drain) and
# partitions another (heartbeat suppression without death).  The
# supervisor's lease expiry must fail the stranded tenants over to
# survivors from their namespaced checkpoints, the healed pod's stale
# placements must be fenced, and the AGGREGATE tallies must be
# bit-identical to solo serial runs with every tenant counted exactly
# once.  The gateway WAL is then crash-swept at every durability
# boundary (run_gateway_crashcheck).  Results -> FED_r12.json.  Never
# affects the pass/fail status.
timeout -k 10 560 env JAX_PLATFORMS=cpu python - <<'FED_SMOKE' \
  || echo "WARNING: federation smoke failed (non-fatal)"
import json, os, tempfile
import numpy as np
from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.chaos import ChaosEngine
from shrewd_tpu.federation import Federation
from shrewd_tpu.obs import metrics as obs_metrics
from shrewd_tpu.service import TenantSpec
from shrewd_tpu.trace.synth import WorkloadConfig

def plan(seed):
    p = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=96, nphys=32, mem_words=64, working_set_words=32, seed=7))],
        structures=["regfile", "rob"], batch_size=32,
        target_halfwidth=0.2, max_trials=192, min_trials=192, seed=seed)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    return p

seeds = (3, 5, 7)
solos, warm = {}, []
for seed in seeds:
    orch = Orchestrator(plan(seed))
    warm.append(orch)   # keep kernels alive: cache entries are owner-guarded
    solos[seed] = {k: np.asarray(v.tallies)
                   for k, v in dict(list(orch.events())[-1][1]).items()}
root = os.path.join(tempfile.mkdtemp(prefix="fed_smoke_"), "fed")
chaos = ChaosEngine({"faults": [
    {"kind": "kill_pod", "pod": "pod0", "at_tick": 4},
    {"kind": "partition_pod", "pod": "pod1", "at_round": 3, "rounds": 3}]})
fed = Federation(root, pod_names=("pod0", "pod1", "pod2"), chaos=chaos,
                 expiry_rounds=2)
admissions = {}
for seed in seeds:
    doc = fed.submit(TenantSpec(name=f"t{seed}", plan=plan(seed).to_dict(),
                                slo_s=900.0))
    admissions[f"t{seed}"] = doc
assert fed.serve() == 0, "federation did not converge"
assert chaos.injected == {"kill_pod": 1, "partition_pod": 1}, chaos.injected
assert fed.gateway.dead_pods == {"pod0"}, fed.gateway.dead_pods
for seed in seeds:
    got = fed.tenant_tallies(f"t{seed}")
    for k, t in solos[seed].items():
        np.testing.assert_array_equal(got[k], t)
# per-pod serving rates off the published metrics (the aggregate
# observability the near-linear claim is judged against on real pods)
rates = {}
for name, pod in fed.pods.items():
    try:
        snap = obs_metrics.read(pod.outdir)
        rates[name] = sum((r.get("trials_per_s") or 0)
                          for r in snap.get("tenants", {}).values())
    except (OSError, ValueError):
        rates[name] = None
# gateway-WAL crash sweep: full coverage, every boundary + torn appends
sweep = crashcheck.run_gateway_crashcheck(
    os.path.join(tempfile.mkdtemp(prefix="fed_sweep_"), "w"))
assert sweep["ok"], sweep["failures"][:3]
with open("FED_r12.json", "w") as f:
    json.dump({
        "tenants": {n: {"pod": e.pod, "epoch": e.epoch,
                        "status": e.status,
                        "path": [h["pod"] for h in e.history],
                        "deadline_s": admissions[n]["deadline_s"],
                        "slo_ok": admissions[n]["slo_ok"]}
                    for n, e in sorted(fed.gateway.entries.items())},
        "chaos": chaos.to_dict(),
        "counters": fed.counters(),
        "pod_trials_per_s": rates,
        "aggregate_trials_per_s": sum(r for r in rates.values() if r),
        "bit_identical_vs_solo": True,
        "gateway_crashcheck": {k: sweep[k] for k in (
            "points", "checks", "torn_checks", "boundaries_by_event",
            "ok")},
    }, f, indent=1)
    f.write("\n")
print(f"federation smoke: 3 tenants x 3 pods, kill_pod+partition_pod -> "
      f"{fed.failovers} failovers, {fed.fenced} fenced, aggregate "
      f"bit-identical; gateway WAL swept at {sweep['points']} boundaries "
      f"({sweep['checks']} recoveries) -> FED_r12.json")
FED_SMOKE

# Sharded-campaign gate (FATAL): ONE campaign (a NORTHSTAR structure,
# 576 frozen-key trials) striped as shards: 3 across a 5-pod
# federation under the merge-targeted chaos pair — kill_shard HARD-
# kills the pod hosting one stripe mid-campaign and
# partition_during_merge suppresses another pod's beats exactly while
# a gateway fold is in flight (at_fold keys on the journaled fold
# ordinal).  Both stripes must fail over, the healed pod must be
# fenced, and the gateway's order-fixed merge fold must produce
# tallies bit-identical to the solo run at >= 2.5x the solo busy time
# of the hottest pod.  The gateway WAL of a SHARDED run is then
# crash-swept at every durability boundary including each
# shard_split / shard_fold / shard_converged append (+ torn variants)
# with 0 divergent recoveries.  Results -> FED_SHARD_r13.json +
# CRASH_r13.json.  FATAL: this is the PR-16 acceptance pin.
timeout -k 10 560 env JAX_PLATFORMS=cpu python - <<'FED_SHARD_GATE' \
  || { echo "FATAL: sharded-federation gate failed (merge fold diverged, chaos unsurvived, speedup < 2.5x, or a merge-ledger crash point did not recover bit-identically)"; exit 1; }
import json, os, tempfile
import numpy as np
from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.campaign.orchestrator import Orchestrator
from shrewd_tpu.campaign.plan import CampaignPlan, WorkloadSpec
from shrewd_tpu.chaos import ChaosEngine
from shrewd_tpu.federation import Federation
from shrewd_tpu.service import TenantSpec
from shrewd_tpu.trace.synth import WorkloadConfig

def plan():
    p = CampaignPlan(
        simpoints=[WorkloadSpec(name="w0", workload=WorkloadConfig(
            n=96, nphys=32, mem_words=64, working_set_words=32, seed=7))],
        structures=["regfile"], batch_size=32,
        target_halfwidth=0.2, max_trials=576, min_trials=576, seed=3)
    p.integrity.canary_trials = 0
    p.integrity.audit_rate = 0.0
    p.resilience.backoff_base = 0.0
    return p

# warm the content-keyed exec cache so both runs measure pure serving
# (keep the orchestrator alive: cache entries are owner-guarded), and
# take its tallies as the ground-truth solo trajectory
warm = Orchestrator(plan())
warm_solo = {k: np.asarray(v.tallies)
             for k, v in dict(list(warm.events())[-1][1]).items()}
root = tempfile.mkdtemp(prefix="fed_shard_")
# solo baseline: the same campaign unsharded on a one-pod federation —
# same pod machinery, so busy_s is the like-for-like denominator
solo_fed = Federation(os.path.join(root, "solo"), pod_names=("solo0",))
solo_fed.submit(TenantSpec(name="camp", plan=plan().to_dict()))
assert solo_fed.serve() == 0
solo = solo_fed.tenant_tallies("camp")
for k, t in warm_solo.items():
    np.testing.assert_array_equal(solo[k], t)
solo_busy = solo_fed.pods["solo0"].busy_s

chaos = ChaosEngine({"faults": [
    {"kind": "kill_shard", "shard": "camp+shard1", "at_round": 3},
    {"kind": "partition_during_merge", "pod": "pod2", "at_fold": 2,
     "rounds": 3}]})
fed = Federation(os.path.join(root, "fed"),
                 pod_names=tuple(f"pod{i}" for i in range(5)),
                 chaos=chaos, expiry_rounds=2)
doc = fed.submit(TenantSpec(name="camp", plan=plan().to_dict(), shards=3))
assert fed.serve() == 0, "sharded federation did not converge"
assert chaos.injected == {"kill_shard": 1,
                          "partition_during_merge": 1}, chaos.injected
assert chaos.survived == {"kill_shard": 1,
                          "partition_during_merge": 1}, chaos.survived
e = fed.gateway.entries["camp"]
assert e.result["status"] == "complete" and e.result["converged"]
got = fed.tenant_tallies("camp")
assert got.keys() == solo.keys()
for k, t in solo.items():
    np.testing.assert_array_equal(got[k], np.asarray(t))
busy = fed.counters()["busy_s"]
hot = max(busy.values())
speedup = solo_busy / hot
assert speedup >= 2.5, (
    f"sharded speedup {speedup:.2f}x < 2.5x "
    f"(solo {solo_busy:.2f}s, hottest shard pod {hot:.2f}s)")

# merge-ledger crash sweep: a sharded run recovered from every gateway
# durability boundary, 0 divergent recoveries required
sweep = crashcheck.run_gateway_crashcheck(
    os.path.join(root, "sweep"),
    plans=crashcheck.small_fleet_plans(seeds=(3,), n_batches=4),
    pod_names=("pod0", "pod1"), shards={"t0": 2})
assert sweep["ok"], sweep["failures"][:3]
for kind in ("shard_split", "shard_fold", "shard_converged"):
    assert sweep["boundaries_by_kind"].get(kind, 0) >= 1, \
        f"sweep never crossed a {kind} boundary"
with open("CRASH_r13.json", "w") as f:
    json.dump(sweep, f, indent=1)
    f.write("\n")
with open("FED_SHARD_r13.json", "w") as f:
    json.dump({
        "plan": {"structure": "regfile", "trials": 576,
                 "batch_size": 32, "shards": 3, "pods": 5},
        "admission": {"shards": doc["shards"],
                      "eta_trials": doc["eta_trials"],
                      "deadline_s": doc["deadline_s"]},
        "chaos": chaos.to_dict(),
        "counters": fed.counters(),
        "merged": {"status": e.result["status"],
                   "converged": e.result["converged"],
                   "trials": e.result["trials"],
                   "folds": e.result["folds"],
                   "shards": e.result["shards"]},
        "solo_busy_s": round(solo_busy, 4),
        "hottest_pod_busy_s": round(hot, 4),
        "speedup_busy": round(speedup, 3),
        "bit_identical_vs_solo": True,
        "sharded_gateway_crashcheck": {k: sweep[k] for k in (
            "points", "checks", "torn_checks",
            "boundaries_by_kind", "ok")},
    }, f, indent=1)
    f.write("\n")
print(f"sharded-federation gate: 3 shards x 5 pods, kill_shard + "
      f"partition_during_merge -> {fed.failovers} failovers, "
      f"{fed.fenced} fenced, {e.result['folds']} folds, merged "
      f"bit-identical at {speedup:.2f}x; merge-ledger sweep "
      f"{sweep['points']} boundaries ({sweep['checks']} recoveries, "
      f"0 divergent) -> FED_SHARD_r13.json + CRASH_r13.json")
FED_SHARD_GATE

# Streaming-ingest gate (FATAL): binary in, Pareto out.  A raw
# workload ELF (workloads/sort.c built by the ingest toolchain) is
# POSTed over the HTTP front as a binary-carrying TenantSpec; the
# federation claims it from the spool, runs the journaled ingest
# pipeline (capture -> lift -> liveness -> simpoint -> window) into
# the federation's digest-keyed artifact store, and serves the
# campaign to completion.  The tallies must be BIT-IDENTICAL to the
# same store windows submitted as a pre-lifted plan, and a
# resubmission of the same (binary, axes) over the same store must
# warm-start with 0 lifts / 0 captures.  The federation is then
# crash-swept across the ENTIRE ingest/store durability surface —
# every ingest-WAL append and artifact-store rename, plus torn-WAL-
# tail and payload-rot variants — with 0 divergent recoveries.
# Results -> INGEST_r14.json.  FATAL: this is the PR-17 acceptance
# pin.  Skipped (non-fatally) when the host toolchain is absent.
if command -v gcc >/dev/null && command -v objdump >/dev/null; then
timeout -k 10 560 env JAX_PLATFORMS=cpu python - <<'INGEST_GATE' \
  || { echo "FATAL: streaming-ingest gate failed (binary path diverged from plan path, resubmission re-lifted, or an ingest/store crash point did not recover bit-identically)"; exit 1; }
import base64, json, os, tempfile, urllib.request
import numpy as np
from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.federation import Federation, GatewayHTTPFront
from shrewd_tpu.ingest import ArtifactStore, IngestPipeline, data_digest
from shrewd_tpu.ingest import hostdiff
from shrewd_tpu.service import TenantSpec

AXES = {"interval": 1500, "k": 2, "max_steps": 20000}
PLAN = {"structures": ["regfile"], "batch_size": 16, "max_trials": 32,
        "min_trials": 32, "target_halfwidth": 0.5, "seed": 3}

data = open(hostdiff.build_tools("workloads/sort.c").workload, "rb").read()
digest = data_digest(data)
bin_kw = {"binary_b64": base64.b64encode(data).decode(),
          "binary_digest": digest, "ingest": AXES}
root = tempfile.mkdtemp(prefix="ingest_gate_")

def lifts(fed):
    pods = [p.sched for p in fed.pods.values() if p.sched is not None]
    return (sum(s.ingest_captures for s in pods),
            sum(s.ingest_lifts for s in pods))

# binary in, over the wire: POST /submit -> spool -> ingest -> campaign
front = GatewayHTTPFront(os.path.join(root, "gateway"), port=0).start()
try:
    spec = TenantSpec(name="bin0", plan=PLAN, **bin_kw)
    req = urllib.request.Request(
        f"http://127.0.0.1:{front.port}/submit",
        data=json.dumps(spec.to_dict()).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.load(r)["tenant"] == "bin0"
finally:
    front.stop()
fed = Federation(root, pod_names=("pod0", "pod1"))
assert fed.serve() == 0, "binary-in federation did not converge"
bt = fed.tenant_tallies("bin0")
cold_captures, cold_lifts = lifts(fed)
assert cold_captures == 1 and cold_lifts >= 2, (cold_captures, cold_lifts)

# the pre-lifted plan path over the SAME store windows
store = ArtifactStore(os.path.join(root, "store"))
probe = IngestPipeline(os.path.join(root, "probe"), store, digest,
                       axes=AXES)
probe.run()
assert (probe.captures, probe.lifts) == (0, 0), "probe was not warm"
fed2 = Federation(os.path.join(root, "planfed"), pod_names=("pod0",))
fed2.submit(TenantSpec(name="plan0", plan=probe.resolved_plan(PLAN)))
assert fed2.serve() == 0
pt = fed2.tenant_tallies("plan0")
assert bt.keys() == pt.keys() and len(bt) > 0
for k in bt:
    np.testing.assert_array_equal(np.asarray(bt[k]), np.asarray(pt[k]))

# resubmission of the same (binary, axes) against the same store:
# a pure O(1) warm start — zero captures, zero lifts, same tallies
fed3 = Federation(os.path.join(root, "refed"), pod_names=("pod0",),
                  store_dir=os.path.join(root, "store"))
fed3.submit(TenantSpec(name="bin1", plan=PLAN, **bin_kw))
assert fed3.serve() == 0
assert lifts(fed3) == (0, 0), f"resubmission re-ingested: {lifts(fed3)}"
rt = fed3.tenant_tallies("bin1")
for k in bt:
    np.testing.assert_array_equal(np.asarray(bt[k]), np.asarray(rt[k]))

# the full ingest/store durability surface, exhaustively: every
# ingest-WAL append + artifact-store rename (+ torn/rot variants)
sweep = crashcheck.run_gateway_crashcheck(
    os.path.join(root, "sweep"),
    plans={"b0": dict(PLAN, batch_size=8, max_trials=8, min_trials=8)},
    binaries={"b0": bin_kw},
    point_filter=lambda pt: (pt.kind or "").startswith(("ingest",
                                                        "store")))
assert sweep["ok"], sweep["failures"][:3]
bk = sweep["boundaries_by_kind"]
assert bk.get("ingest_stage", 0) >= 5 and bk.get("ingest_done", 0) >= 1
assert bk.get("store_payload", 0) >= 4, bk
with open("INGEST_r14.json", "w") as f:
    json.dump({
        "binary": {"workload": "workloads/sort.c", "sha256": digest,
                   "bytes": len(data)},
        "axes": AXES,
        "cold": {"captures": cold_captures, "lifts": cold_lifts,
                 "windows": len(bt) // len(PLAN["structures"])},
        "bit_identical_vs_plan_path": True,
        "resubmit": {"captures": 0, "lifts": 0,
                     "bit_identical": True},
        "ingest_crashcheck": {k: sweep[k] for k in (
            "points", "points_selected", "points_checked", "checks",
            "torn_checks", "boundaries_by_kind", "ok")},
    }, f, indent=1)
    f.write("\n")
print(f"streaming-ingest gate: sort.c ({len(data)} bytes) over HTTP -> "
      f"{cold_captures} capture / {cold_lifts} lifts / "
      f"{len(bt)} cells, bit-identical to the plan path; resubmit "
      f"warm-started at 0 lifts; ingest/store sweep "
      f"{sweep['points_checked']} boundaries ({sweep['checks']} "
      f"recoveries, 0 divergent) -> INGEST_r14.json")
INGEST_GATE
else
  echo "WARNING: streaming-ingest gate skipped (no host toolchain)"
fi

# Elastic-pool Pareto gate (FATAL): the NORTHSTAR matrix through the
# federation, autoscaled and chaos-proven.  A 100-cell scenario matrix
# (5 targets x 4 protection schemes x 5 thermal envelopes) runs twice:
# once through the solo resident scheduler, once through a federated
# pod pool that starts at its 3-pod static floor, autoscales out under
# the matrix's admission pressure (journaled pool_scale_up records),
# and contracts back to the floor through the retire-via-migration
# path (pool_retire_begin fences the pod, the drain rides the ordinary
# migration machinery, pool_retire_done completes) — while pod-level
# chaos HARD-kills one freshly scaled pod the moment the driver first
# steps it (kill_new_pod @ scale 1) and another mid-retire-drain
# (kill_during_retire @ scale 5; both addressed by the journaled scale
# ordinal, never a clock).  The Pareto front must be BIT-IDENTICAL to
# the solo run's (scheme-mates share frozen PRNG keys on measurement
# coordinates; prune timing may differ across pool schedules, the
# front cannot).  The gateway WAL of an autoscaled run is then
# crash-swept from every pool scale-event boundary (every
# pool_scale_up / pool_retire_begin / pool_retire_done append + torn
# variants), recovery re-executed WITHOUT an autoscaler attached, with
# 0 divergent recoveries.  Results -> PARETO_FED_r15.json.  FATAL:
# this is the PR-18 acceptance pin.
timeout -k 10 560 env JAX_PLATFORMS=cpu python - <<'PARETO_FED_GATE' \
  || { echo "FATAL: elastic-pool Pareto gate failed (front diverged from solo, pool chaos unsurvived, pool did not return to its floor, or a pool-boundary crash point did not recover bit-identically)"; exit 1; }
import json, os, tempfile
from shrewd_tpu.analysis import crashcheck
from shrewd_tpu.chaos import ChaosEngine
from shrewd_tpu.federation import Autoscaler
from shrewd_tpu.scenario import (FederatedScenarioRunner, ScenarioMatrix,
                                 ScenarioRunner, pareto)

def matrix():
    return ScenarioMatrix(
        tag="r15", seed=3,
        workloads=[{"name": "wl", "simpoints": [{
            "type": "WorkloadSpec", "name": "w0",
            "workload": {"n": 96, "nphys": 32, "mem_words": 64,
                         "working_set_words": 32, "seed": 7}}]}],
        targets=["regfile", "rob", "iq", "lsq", "fu"],
        schemes=[{"name": "none"},
                 {"name": "parity", "detect": 1.0, "area": 1.03},
                 {"name": "ecc", "detect": 0.5, "correct": 0.5,
                  "area": 1.12},
                 {"name": "dmr", "detect": 1.0, "area": 2.0,
                  "weight": 0.2}],
        thermal=[{"name": "t60", "temperature_c": 60.0},
                 {"name": "t71", "temperature_c": 71.0},
                 {"name": "t85", "temperature_c": 85.0},
                 {"name": "t95", "temperature_c": 95.0},
                 {"name": "t105", "temperature_c": 105.0}],
        base={"batch_size": 16, "max_trials": 32, "min_trials": 32,
              "target_halfwidth": 0.5,
              "integrity": {"canary_trials": 0, "audit_rate": 0.0},
              "resilience": {"backoff_base": 0.0}})

cells = matrix().expand()
assert len(cells) >= 100, f"matrix shrank to {len(cells)} cells"
root = tempfile.mkdtemp(prefix="pareto_fed_")

# the single-scheduler reference front
solo = ScenarioRunner(matrix(), os.path.join(root, "solo"),
                      pareto_every=4)
assert solo.serve() == 0, "solo matrix did not complete"
sdoc = json.load(open(pareto.artifact_path(
    os.path.join(root, "solo"), "r15")))

# the same matrix through the autoscaled, chaos-ridden pod pool
chaos = ChaosEngine({"faults": [
    {"kind": "kill_new_pod", "at_scale": [1]},
    {"kind": "kill_during_retire", "at_scale": [5]},
]})
auto = Autoscaler(min_pods=3, max_pods=6, up_trials=256.0,
                  down_trials=64.0, cooldown_rounds=1)
runner = FederatedScenarioRunner(matrix(), os.path.join(root, "fed"),
                                 pod_names=("pod0", "pod1", "pod2"),
                                 pareto_every=4, autoscale=auto,
                                 chaos=chaos, expiry_rounds=2)
assert runner.serve() == 0, "federated matrix did not complete"
fed, gw = runner.fed, runner.fed.gateway
assert chaos.injected == {"kill_new_pod": 1,
                          "kill_during_retire": 1}, chaos.injected
assert chaos.survived == chaos.injected, chaos.survived
assert fed.scale_ups >= 1 and fed.retired == fed.scale_ups
assert sorted(gw.pods) == ["pod0", "pod1", "pod2"], "pool not at floor"
assert not gw.retiring and not gw.scaled_pods
for pod, rec in gw.retires.items():
    assert rec["done_round"] is not None, (pod, rec)
fdoc = json.load(open(pareto.artifact_path(
    os.path.join(root, "fed"), "r15")))

# front equality: converged rows only; the per-group "cells" key is
# PROVENANCE (which scheme-mate supplied the profile may differ across
# schedules) — everything the front decides on must be bit-identical
def front(doc):
    return {g: {k: v for k, v in r.items() if k != "cells"}
            for g, r in doc["search"].items()}
assert front(fdoc) == front(sdoc), "federated front diverged from solo"
assert fdoc["search"], "empty design search"

# the pool-boundary crash sweep: every scale-event WAL append, plain +
# torn, recovered without an autoscaler — 0 divergent recoveries
pool_kinds = ("pool_scale_up", "pool_retire_begin", "pool_retire_done")
sweep = crashcheck.run_gateway_crashcheck(
    os.path.join(root, "sweep"),
    crashcheck.small_fleet_plans(seeds=(3, 5), n_batches=2),
    pod_names=("pod0",),
    autoscale=lambda: Autoscaler(min_pods=1, max_pods=2,
                                 up_trials=64.0, down_trials=16.0,
                                 cooldown_rounds=1),
    point_filter=lambda pt: pt.kind in pool_kinds)
assert sweep["ok"], sweep["failures"][:3]
for kind in pool_kinds:
    assert sweep["boundaries_by_kind"].get(kind, 0) >= 1, \
        f"sweep never crossed a {kind} boundary"

with open("PARETO_FED_r15.json", "w") as f:
    json.dump({
        "matrix": {"tag": "r15", "cells": len(cells),
                   "targets": 5, "schemes": 4, "thermal": 5},
        "pool": {"floor": 3, "max": 6,
                 "scale_ups": fed.scale_ups, "retired": fed.retired,
                 "scale_seq": gw.scale_seq,
                 "retires": gw.retires},
        "chaos": chaos.to_dict(),
        "front_bit_identical_vs_solo": True,
        "fronts": {g: [[p["area"], p["sdc_rate"]]
                       for p in r["pareto"]]
                   for g, r in fdoc["search"].items()},
        "decisions": {"solo": len(sdoc["decisions"]),
                      "federated": len(fdoc["decisions"])},
        "pool_crashcheck": {k: sweep[k] for k in (
            "points", "points_selected", "points_checked", "checks",
            "torn_checks", "boundaries_by_kind", "autoscaled", "ok")},
    }, f, indent=1)
    f.write("\n")
print(f"elastic-pool Pareto gate: {len(cells)} cells, pool 3 -> "
      f"{3 + fed.scale_ups} -> 3 under kill_new_pod + "
      f"kill_during_retire, front bit-identical to solo "
      f"({len(fdoc['search'])} groups); pool sweep "
      f"{sweep['points_checked']} boundaries ({sweep['checks']} "
      f"recoveries, 0 divergent) -> PARETO_FED_r15.json")
PARETO_FED_GATE

# Non-fatal bench smoke: bench.py --quick includes the serial-vs-
# pipelined campaign-loop microbenchmark (now surfacing the PerfStats
# overlap ledger — host/device-wait/device-step seconds, depth HWM),
# the until-CI convergence microbenchmark, AND the obs-overhead stage
# (disabled-tracer ≈zero-overhead pin + tracing-on/off bit-identity,
# asserted fatally) — recorded as BENCH_r09.json alongside the earlier
# BENCH_r0X trajectory files.  Never affects the pass/fail status.
timeout -k 10 560 env JAX_PLATFORMS=cpu python bench.py --quick > BENCH_r09.json \
  || echo "WARNING: bench smoke failed (non-fatal)"

# Non-fatal chunked-replay smoke: a small window split across 2 chunks
# through the SimPoint-scale fast path (ops/chunked.py) — fast-engine
# outcomes asserted bit-identical to the exact-chunked reference, and
# the content-addressed window store's warm start asserted to
# re-preprocess NOTHING (builds delta 0, mmap'd load, zero re-lifts).
# Records CHUNKED_SMOKE_r16.json.  Never affects the pass/fail status.
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'CHUNKED_SMOKE' \
  || echo "WARNING: chunked smoke failed (non-fatal)"
import json, tempfile, time
import numpy as np
from shrewd_tpu.ingest.store import ArtifactStore
from shrewd_tpu.models.o3 import O3Config
from shrewd_tpu.ops import window as W
from shrewd_tpu.ops.chunked import ChunkedCampaign, preprocess_window
from shrewd_tpu.ops.trial import TrialKernel
from shrewd_tpu.trace.synth import WorkloadConfig, generate
from shrewd_tpu.utils import prng

t = generate(WorkloadConfig(n=512, nphys=32, mem_words=64,
                            working_set_words=32, seed=16))
kernel = TrialKernel(t, O3Config())
store = ArtifactStore(tempfile.mkdtemp(prefix="chunked_smoke_"))

W.clear_registry()
win = preprocess_window(kernel, 256, store=store)   # 2 chunks
assert win.C == 2 and win.source == "built", (win.C, win.source)

# warm start: a second campaign over the stored window re-lifts and
# re-preprocesses nothing
W.clear_registry()
builds0 = W.STATS["builds"]
win2 = preprocess_window(kernel, 256, store=store)
assert win2.source == "store" and W.STATS["builds"] == builds0, \
    (win2.source, W.STATS["builds"] - builds0)

keys = prng.trial_keys(prng.campaign_key(16), 64)
exact = ChunkedCampaign(kernel, chunk=256, engine="exact", window=win2)
fast = ChunkedCampaign(kernel, chunk=256, engine="taint", window=win2)
t0 = time.monotonic()
of = np.asarray(fast.outcomes_from_keys(keys, "regfile"))
dt = time.monotonic() - t0
oe = np.asarray(exact.outcomes_from_keys(keys, "regfile"))
assert np.array_equal(of, oe), "fast-vs-exact bit-identity violated"

doc = {"metric": "chunked_smoke", "n_uops": 512, "chunks": 2,
       "engines": ["taint", "exact"], "bit_identical": True,
       "warm_start": {"builds_delta": 0, "source": "store",
                      "relifts": 0},
       "fast_trials_per_sec": round(64 / dt, 2),
       "tally": np.bincount(of, minlength=4).tolist(),
       "resolution": {k: int(v) for k, v in fast.last_stats.items()
                      if isinstance(v, (int, np.integer))}}
with open("CHUNKED_SMOKE_r16.json", "w") as f:
    json.dump(doc, f, indent=1); f.write("\n")
print(f"chunked smoke: 2-chunk fast path bit-identical to exact, "
      f"warm start re-preprocessed nothing -> CHUNKED_SMOKE_r16.json")
CHUNKED_SMOKE

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
