#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command VERBATIM, so local runs and CI
# cannot drift.  Exits with pytest's status; prints DOTS_PASSED for the
# driver's pass-count comparison.
#
# Usage: scripts/ci_tier1.sh   (from anywhere — the script resolves the
# repo root from its own path, so CI and local invocations cannot diverge
# on the working directory)

cd "$(dirname "$0")/.." || exit 1

# Non-fatal backend-probe smoke: catches probe drift (import breakage,
# verdict-format changes) in tier-1 without ever affecting the pass/fail
# status — the probe is the first thing operators reach for when a
# backend misbehaves, so it must not rot silently.
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/backend_probe.py --platform cpu --timeout 120 \
  || echo "WARNING: backend_probe smoke failed (non-fatal)"

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
